"""Per-config BASELINE benchmarks (BASELINE.json configs[0..4]).

One config per process (HBM is not reclaimed promptly across builds on
the tunneled chip — see bench.py); measurement hygiene is shared with
bench.py (multi-window best-of, agreement retry).

Usage:
    python bench_configs.py resnet50_o1            # one leg, real chip
    python bench_configs.py gpt2_tp8_full_step     # CPU full-size step
    python bench_configs.py all                    # drives each leg in
                                                   # a fresh subprocess,
                                                   # writes BENCH_CONFIGS.json

Legs (reference workloads per BASELINE.json):
  resnet50_o1        ResNet-50, amp O1 + FusedSGD           (configs[0])
  resnet50_syncbn    + DDP shard_map step + SyncBatchNorm   (configs[1..2])
  bert_o1            BERT-Large, amp O1 interceptor + FusedAdam, +
                     grad-sync bytes-on-wire model and the measured
                     bert_o1_ddp int8-allreduce A/B child (ROADMAP 2b)
  bert_o1_zero       ZeRO-2 A/B child (ISSUE 11): replicated vs
                     sharded optimizer state at O2 — hbm_peak +
                     state-bytes drop, grown-batch samples/sec, and
                     the _zero_bytes_on_wire wire/residency model
  gpt2_1p3b          GPT-2 1.3B-family single-chip proxy    (configs[3])
                     (BENCH_GPT_VARIANT: base/noselect/fused_cast —
                     the round-5 optimizer-overlap experiment)
  gpt2_tp8_full_step full 1.3B TP=8+SP step EXECUTED, CPU   (configs[3])
  gpt2_3d_full_step  full 1.3B tp2×pp2×dp2 1F1B step, CPU   (configs[3])
  mistral7b_tp8_full_step  full 7.24B GQA step EXECUTED, CPU mesh
  llama_1b           1.03B GQA+SwiGLU recipe + GQA/MLP A/B rows
  decode             llama_1b generate(): prefill + decode tokens/s,
                     bytes/token roofline, blocked-vs-einsum A/B
  serving_decode     continuous-batching engine tokens/s at fixed
                     occupancy vs single-stream generate() baseline
  prefix_spec_serving  CoW prefix sharing A/B at equal HBM (tokens/s,
                     TTFT, pool capacity shared vs unshared) + the
                     prompt-lookup speculative-decoding tokens/step
  quantized_kv_serving  int8 KV pages at equal HBM: 2x slots in the
                     same bytes (capacity >= 1.9x asserted), tokens/s
                     + TTFT A/B vs the unquantized paged pool
  resilience_overhead  ResilientLoop + async rolling checkpoints vs
                     the bare train loop (target <2% at ckpt-every-100)
  fleet_serving      multi-replica FleetRouter tokens/s + TTFT p50/p99
                     per chip at fixed SLO, 1 vs 3 replicas, plus a
                     kill-at-midpoint resilience row
  vit_huge_lamb      ViT-H/14, amp O2 + FusedLAMB           (configs[4])
  long_context       8k/16k/32k/32k-windowed ladder, phase-sum bounds
  group_norm         GN+SiLU fwd+bwd achieved GB/s
"""

from __future__ import annotations

import functools
import json
import os
import subprocess
import sys

import bench

# ISSUE-15: the analytic cost models below grew up bench-local (each
# beside the leg that measured it); they are now LIBRARY code — the
# apex_tpu.plan planner scores layouts with the same arithmetic — so
# the single implementation lives in apex_tpu/plan/costs.py and the
# bench imports it back under the historical names.  Zero drift is
# regression-gated: tests/test_plan.py::TestCostModelDedup
# byte-compares these functions' outputs (and the recorded bench
# rows' model blocks) against the lifted implementations.  Importing
# apex_tpu does not initialize a jax backend — the orchestrator
# parent still never holds the chip; only the per-leg children do.
from apex_tpu.plan.costs import (           # noqa: E402
    ddp_bytes_on_wire as _ddp_bytes_on_wire,
    resnet_traffic_model as _resnet_traffic_model,
    serving_traffic_model as _serving_traffic_model,
    zero_bytes_on_wire as _zero_bytes_on_wire,
)


def _emit(d):
    print(json.dumps(d))


def _run_child(leg, env_overrides=None, timeout=1800):
    """Run one leg in a fresh subprocess and parse its last JSON line.

    The single implementation behind every orchestrator (llama_1b /
    decode / long_context rows and _run_all): fresh process per
    measurement because HBM is not reclaimed promptly across builds on
    the tunneled chip.  Returns a result dict; timeouts and non-zero
    exits become ``{"error": ...}`` rows so sibling measurements are
    never lost."""
    env = dict(os.environ)
    for k, v in (env_overrides or {}).items():
        if v is None:
            env.pop(k, None)            # None = remove from child env
        else:
            env[k] = v
    try:
        proc = subprocess.run(
            [sys.executable, __file__, leg], env=env,
            capture_output=True, text=True, timeout=timeout)
    except subprocess.TimeoutExpired:
        return {"error": f"timeout after {timeout}s"}
    lines = [l for l in proc.stdout.splitlines() if l.startswith("{")]
    if proc.returncode != 0 or not lines:
        return {"error": (proc.stderr or proc.stdout or "?")[-2000:]}
    return json.loads(lines[-1])


def _measure(state, step, batch, samples_per_step, extra=None,
             measured_tflops=None, phase_bounds=None):
    n_steps = int(os.environ.get("BENCH_STEPS", "20"))
    k_windows = max(1, int(os.environ.get("BENCH_WINDOWS", "3")))
    # AOT-compile: the executable doubles as the memory/cost analysis
    # source (fills hbm_peak on backends without memory_stats, and the
    # roofline self-check fields)
    compiled = bench._aot_compile(step, state, *batch)
    timed = compiled if compiled is not None else step
    dt, dts, loss, finite, _ = bench._measure_step(
        state, timed, batch, n_steps, k_windows)
    out = {
        "value": round(samples_per_step / dt, 3),
        "unit": "samples/sec/chip",
        "step_ms": round(dt * 1e3, 2),
        "window_ms": [round(d * 1e3, 2) for d in dts],
        "loss_finite": finite,
    }
    out.update(bench._memory_fields(compiled))
    out.update(bench._roofline_fields(compiled, dt,
                                      measured_tflops=measured_tflops,
                                      phase_bounds=phase_bounds))
    out.update(extra or {})
    return out


# ----------------------------------------------------------------- ResNet-50

# (lifted to apex_tpu/plan/costs.py — imported back above as _resnet_traffic_model)


def _build_resnet(opt_level, sync_bn):
    """ResNet-50 train state (examples/imagenet/main_amp.py workload).

    BENCH_RESNET_FUSED_BN=1 routes BN through the fused kernels
    (ops/batch_norm.py); BENCH_RESNET_STEM=s2d swaps in the MLPerf
    space-to-depth stem — the ISSUE-3 A/B levers.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from apex_tpu import amp
    from apex_tpu.models.resnet import ResNet, ResNetConfig
    from apex_tpu.optim import fused_sgd

    # b=128 measured fastest (round-3 sweep: 64 -> 2184, 128 -> 2461,
    # 256 -> 2363 samples/s) — bigger batches amortize the BN stat
    # passes until activations blow the ~10 GB working set
    b = int(os.environ.get("BENCH_BATCH", "128"))
    size = int(os.environ.get("BENCH_IMAGE", "224"))
    cfg = ResNetConfig(
        num_classes=1000,
        bn_axis_names=("data",) if sync_bn else None,
        dtype=jnp.bfloat16 if opt_level in ("O1", "O2", "O3")
        else jnp.float32,
        fused_bn=os.environ.get("BENCH_RESNET_FUSED_BN") == "1",
        stem=os.environ.get("BENCH_RESNET_STEM", "conv"))
    model = ResNet(cfg)

    rng = np.random.default_rng(0)
    images = jnp.asarray(rng.normal(size=(b, size, size, 3)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 1000, size=(b,)))

    variables = model.init(jax.random.PRNGKey(0), images[:2], train=True)
    params, batch_stats = variables["params"], variables["batch_stats"]

    def apply_fn(p, x, bs):
        return model.apply({"params": p, "batch_stats": bs}, x,
                           train=True, mutable=["batch_stats"])

    state = amp.initialize(
        apply_fn, params,
        fused_sgd(0.1, momentum=0.9, weight_decay=1e-4),
        opt_level=opt_level)
    return model, state, batch_stats, (images, labels), b


# the fused-BN × s2d-stem A/B grid (ISSUE 3): each row runs in a fresh
# child process (HBM is not reclaimed promptly across builds)
_RESNET_VARIANTS = {
    # both keys always explicit (None = remove from the child env) so
    # an ambient BENCH_RESNET_* can't leak into the wrong row
    "base": {"BENCH_RESNET_FUSED_BN": None, "BENCH_RESNET_STEM": None},
    "fused_bn": {"BENCH_RESNET_FUSED_BN": "1",
                 "BENCH_RESNET_STEM": None},
    "s2d": {"BENCH_RESNET_FUSED_BN": None,
            "BENCH_RESNET_STEM": "s2d"},
    "fused_bn_s2d": {"BENCH_RESNET_FUSED_BN": "1",
                     "BENCH_RESNET_STEM": "s2d"},
}


def _resnet_ab(leg, variants):
    """Orchestrate the fused/s2d A/B rows for a resnet leg; the main
    row is the fully-fused config (the production recommendation), and
    ``ab`` quantifies each lever against base on the shared bn_real
    bound."""
    rows = {}
    for name in variants:
        rows[name] = _run_child(
            leg, dict(_RESNET_VARIANTS[name],
                      BENCH_RESNET_VARIANT="1"), timeout=2700)
    main = dict(rows.get("fused_bn_s2d") or {})
    ab = {}
    base = rows.get("base") or {}
    for name in variants:
        row = rows.get(name) or {}
        if name != "base" and row.get("value") and base.get("value"):
            ab[f"{name}_vs_base_speedup"] = round(
                row["value"] / base["value"], 3)
        if row.get("roofline_frac") is not None:
            ab[f"{name}_frac_of_bn_real"] = row["roofline_frac"]
    _emit({
        "metric": main.get("metric", leg) + "_ab",
        "value": main.get("value"),
        "unit": "samples/sec/chip (fused_bn + s2d stem)",
        "rows": rows,
        "ab": ab,
    })


def bench_resnet50_o1():
    import jax
    import jax.numpy as jnp

    if not os.environ.get("BENCH_RESNET_VARIANT"):
        _resnet_ab("resnet50_o1",
                   ("base", "fused_bn", "s2d", "fused_bn_s2d"))
        return

    _, state, batch_stats, (images, labels), b = _build_resnet("O1", False)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(carry, x, y):
        state, bs = carry

        def loss_fn(p):
            logits, mut = state.apply_fn(p, x, bs)
            onehot = jax.nn.one_hot(y, 1000)
            loss = -jnp.mean(jnp.sum(
                jax.nn.log_softmax(logits.astype(jnp.float32)) * onehot,
                axis=-1))
            return state.scale_loss(loss), (loss, mut["batch_stats"])

        grads, (loss, new_bs) = jax.grad(
            loss_fn, has_aux=True)(state.compute_params())
        new_state, finite = state.apply_gradients(grads=grads)
        return (new_state, new_bs), loss, finite

    out = _measure((state, batch_stats), step, (images, labels), b,
                   {"batch": b})
    _resnet_rescore(out, b)
    out["metric"] = "resnet50_imagenet_O1_fusedsgd_samples_per_sec_per_chip"
    _emit(out)


def _resnet_rescore(out, b):
    """Re-score roofline_frac against the analytic traffic model (see
    :func:`_resnet_traffic_model`); the XLA cost-model frac stays as a
    diagnostic.  Guarantees frac ≤ 1 up to clock noise and makes the
    near-ceiling resnet captures certify something real.  The frac is
    ALWAYS vs ``bn_real`` (so fused/unfused A/B rows share one bound);
    fused rows additionally record their kernels' own mandated bytes
    (``bn_fused_kernel``)."""
    import jax

    fused = os.environ.get("BENCH_RESNET_FUSED_BN") == "1"
    out["fused_bn"] = fused
    out["stem"] = os.environ.get("BENCH_RESNET_STEM", "conv")
    if jax.default_backend() != "tpu":
        return          # rooflines are chip certifications; CPU runs
    tm = _resnet_traffic_model(
        b, int(os.environ.get("BENCH_IMAGE", "224")), fused_bn=fused)
    dt = out["step_ms"] / 1e3
    t_hbm_real = tm["bn_real"] / (bench._PEAK_HBM_GBS * 1e9)
    t_mxu = out.get("mxu_bound_frac", 0.0) * dt
    out["roofline_frac_costmodel"] = out.get("roofline_frac")
    out["roofline_frac"] = round(max(t_mxu, t_hbm_real) / dt, 3)
    out["roofline_bound"] = ("analytic_traffic_bn_real"
                             if t_hbm_real >= t_mxu else "mxu")
    out["analytic_traffic_bytes"] = tm
    out["traffic_model_note"] = (
        "frac scored vs the architecture's analytic bn_real traffic "
        "bound (conv act passes + unfusable BN stat passes + "
        "param/optimizer state); the XLA cost-model frac "
        "(roofline_frac_costmodel) overcounts fusion-internal bytes "
        "and is diagnostic only")


def bench_resnet50_syncbn():
    """The DDP + SyncBatchNorm leg: the full shard_map data-parallel
    step (explicit grad all-reduce, cross-replica BN stats) on the
    ``data`` mesh axis — world size = however many chips the process
    has (1 on the tunneled chip; the multi-device path is exercised on
    the 8-device CPU mesh in tests/test_parallel.py)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from apex_tpu.core import mesh as mesh_lib
    from apex_tpu.parallel import all_reduce_mean_grads

    if not os.environ.get("BENCH_RESNET_VARIANT"):
        # 2-row A/B (base vs fully fused): the per-lever split is the
        # o1 leg's job; this leg certifies the psum'd fused-stats path
        _resnet_ab("resnet50_syncbn", ("base", "fused_bn_s2d"))
        return

    mesh = mesh_lib.initialize_mesh(data_parallel_size=-1)
    _, state, batch_stats, (images, labels), b = _build_resnet("O1", True)

    def shard_step(carry, x, y):
        state, bs = carry

        def loss_fn(p):
            logits, mut = state.apply_fn(p, x, bs)
            onehot = jax.nn.one_hot(y, 1000)
            loss = -jnp.mean(jnp.sum(
                jax.nn.log_softmax(logits.astype(jnp.float32)) * onehot,
                axis=-1))
            return state.scale_loss(loss), (loss, mut["batch_stats"])

        grads, (loss, new_bs) = jax.grad(
            loss_fn, has_aux=True)(state.compute_params())
        grads = all_reduce_mean_grads(grads)   # explicit DDP all-reduce
        new_state, finite = state.apply_gradients(grads=grads)
        return (new_state, new_bs), loss, finite

    sharded = jax.shard_map(
        shard_step, mesh=mesh,
        in_specs=((P(), P()), P("data"), P("data")),
        out_specs=((P(), P()), P(), P()),
        check_vma=False)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(carry, x, y):
        return sharded(carry, x, y)

    world = mesh.shape["data"]
    with mesh:
        # per-chip throughput: the global batch is sharded over `world`
        out = _measure((state, batch_stats), step, (images, labels),
                       b / world, {"batch": b, "world": world})
    # per-chip traffic: each chip streams the activations of its own
    # b/world shard (param/optimizer traffic is batch-independent)
    _resnet_rescore(out, b // world)
    out["metric"] = ("resnet50_ddp_syncbn_O1_fusedsgd_"
                     "samples_per_sec_per_chip")
    _emit(out)


# ----------------------------------------------------------------- GPT-2

def _gpt_cfg(num_layers, scan):
    import jax.numpy as jnp

    from apex_tpu.models import GPTConfig

    return GPTConfig.gpt2_1p3b(
        num_layers=num_layers, dtype=jnp.bfloat16, remat=True,
        scan_layers=scan)


def bench_gpt2_1p3b():
    """Single-chip proxy: the 1.3B architecture at BENCH_GPT_LAYERS of
    its 24 layers (full state for 24 layers needs ~13 GB of optimizer
    state alone — more than the tunneled chip's usable HBM).  The
    reported number is the *proxy's* measured throughput, not an
    extrapolation; the full-size model is EXECUTED on the 8-device mesh
    by the ``gpt2_tp8_full_step`` / ``gpt2_3d_full_step`` legs.

    BENCH_GPT_VARIANT (round-4 verdict item 4 — the optimizer-overlap
    experiment; results + mechanism in BASELINE.md round-5 section):
      base       the production step (apply_gradients).
      noselect   per-leaf Adam applied UNconditionally (no DLS
                 step-skip select): removes the only data dependency
                 that could serialize the update behind the global
                 finite-flag, and removes the select's 3-pass master
                 traffic — an UPPER BOUND on what any finite-flag
                 restructuring could buy.
      fused_cast the state carries the bf16 compute copy; each update
                 emits (new master, new copy) in one fusion, so the
                 forward never re-reads the 5.3 GB fp32 masters — a
                 pure traffic-elimination lever (O2 semantics intact:
                 the copy equals cast_to_compute(master) bit-exactly,
                 and on overflow both are rolled back).
    The optimizer-only probe (t_opt_alone) is measured in every
    variant: step_ms vs fwd_bwd_ms + t_opt_alone quantifies how much
    of the optimizer's HBM streaming XLA actually hides under the
    backward (TPU executes one op at a time — overlap can only come
    from fusion, not concurrent kernels)."""
    import jax
    import jax.numpy as jnp

    from apex_tpu import amp
    from apex_tpu.core.loss_scale import all_finite
    from apex_tpu.models import GPTModel, gpt_loss_fn
    from apex_tpu.optim import fused_adam
    from apex_tpu.utils.tree import tree_select

    variant = os.environ.get("BENCH_GPT_VARIANT", "base")
    layers = int(os.environ.get("BENCH_GPT_LAYERS", "12"))
    # b=8 measured +10.7% over round-3's b=4 (29.4 vs 26.5 samples/s
    # at full settings, round 4): the ~21 GB/step of per-param state
    # (optimizer/master) traffic amortizes over twice the samples,
    # exactly as the BASELINE.md balanced-roofline analysis of this
    # leg predicts — and it still fits the chip
    b = int(os.environ.get("BENCH_BATCH", "8"))
    s = int(os.environ.get("BENCH_SEQ", "1024"))
    cfg = _gpt_cfg(layers, scan=False)
    model = GPTModel(cfg)

    ids = jax.random.randint(
        jax.random.PRNGKey(0), (b, s + 1), 0, cfg.vocab_size, jnp.int32)
    inputs, labels = ids[:, :-1], ids[:, 1:]
    tx = fused_adam(1e-4, moment_dtype=jnp.bfloat16)

    def make_state():
        params = model.init(jax.random.PRNGKey(0), inputs[:2])
        return amp.initialize(
            model.apply, params, tx, opt_level="O2",
            half_dtype=jnp.bfloat16)

    state = make_state()

    def loss_of(state, cp, inputs, labels):
        logits = state.apply_fn(cp, inputs)
        loss = gpt_loss_fn(logits.astype(jnp.float32), labels)
        return state.scale_loss(loss), loss

    import optax as _optax

    # each variant defines grad_of (how the step differentiates) and
    # apply_opt (its post-grad optimizer sequence); step AND both
    # probes are assembled from the SAME two functions, so the probes
    # time exactly the computation the step runs (no probe drift)
    if variant in ("base", "noselect"):
        def grad_of(carry, inputs, labels):
            state = carry

            def loss_fn(p):
                return loss_of(state, state.policy.cast_to_compute(p),
                               inputs, labels)

            return jax.grad(loss_fn, has_aux=True)(state.params)

        if variant == "base":
            def apply_opt(carry, grads):
                return carry.apply_gradients(grads=grads)
        else:
            def apply_opt(state, grads):
                ls = state.loss_scaler
                grads = jax.tree.map(
                    lambda g, p: g.astype(p.dtype), grads,
                    state.params)
                grads = ls.unscale(state.loss_scale_state, grads)
                finite = all_finite(grads)
                updates, new_opt = state.tx.update(
                    grads, state.opt_state, state.params)
                new_params = _optax.apply_updates(state.params,
                                                  updates)
                new_state = state.replace(
                    step=state.step + 1, params=new_params,
                    opt_state=new_opt,
                    loss_scale_state=ls.adjust(
                        state.loss_scale_state, finite))
                return new_state, finite
        carry = state
    elif variant == "fused_cast":
        # the copy casts EVERY leaf to bf16 (unlike cast_to_compute,
        # which keeps norm params fp32 and would alias those buffers
        # between master and copy — an illegal double-donation): this
        # is a traffic experiment, and the ~0.1% of params that are
        # norms don't move the numbers either way
        def to_copy(p):
            return jax.tree.map(
                lambda x: x.astype(jnp.bfloat16), p)

        def grad_of(carry, inputs, labels):
            state, copy = carry
            return jax.grad(
                lambda cp: loss_of(state, cp, inputs, labels),
                has_aux=True)(copy)

        def apply_opt(carry, grads):
            state, copy = carry
            # O2 grads arrive in bf16 (w.r.t. the compute copy) —
            # upcast+unscale exactly as apply_gradients does
            ls = state.loss_scaler
            grads = jax.tree.map(
                lambda g, p: g.astype(p.dtype), grads, state.params)
            grads = ls.unscale(state.loss_scale_state, grads)
            finite = all_finite(grads)
            updates, new_opt = state.tx.update(
                grads, state.opt_state, state.params)
            new_params = _optax.apply_updates(state.params, updates)
            # the next step's bf16 copy comes out of the same fusion
            # that writes the new master — one master read total
            new_copy = to_copy(new_params)
            new_params = tree_select(finite, new_params, state.params)
            new_copy = tree_select(finite, new_copy, copy)
            new_opt = tree_select(finite, new_opt, state.opt_state)
            new_state = state.replace(
                step=state.step + 1, params=new_params,
                opt_state=new_opt,
                loss_scale_state=ls.adjust(state.loss_scale_state,
                                           finite))
            return (new_state, new_copy), finite
    else:
        raise ValueError(f"unknown BENCH_GPT_VARIANT {variant!r}")

    def make_carry(st):
        return (st, to_copy(st.params)) if variant == "fused_cast" \
            else st

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(carry, inputs, labels):
        grads, loss = grad_of(carry, inputs, labels)
        new_carry, finite = apply_opt(carry, grads)
        return new_carry, loss, finite

    # optimizer-only probe: the un-overlapped cost of THIS variant's
    # post-grad sequence.  Grads ride in as real arguments in the
    # dtype grad_of produces, the probe returns the FULL new carry
    # (all moment/master writes must materialize — returning scalars
    # would let XLA shrink the streaming to per-leaf slices), and
    # carry+grads are donated and threaded through the window loop so
    # the probe never holds two full states (the grads input rides
    # back out as an aliased passthrough).  The probed carry is
    # consumed; a fresh state is built for the probes/step after.
    import time as _time

    gdtype = (jnp.bfloat16 if variant == "fused_cast"
              else jnp.float32)
    gprobe = jax.tree.map(
        lambda p: jnp.full(p.shape, 1e-4, gdtype), state.params)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def opt_only(carry, grads):
        new_carry, _finite = apply_opt(carry, grads)
        return new_carry, grads

    n_steps = int(os.environ.get("BENCH_STEPS", "20"))
    k_windows = max(1, int(os.environ.get("BENCH_WINDOWS", "3")))
    n_probe = max(n_steps // 2, 5)
    box = [make_carry(state), gprobe]
    del state, gprobe
    box[:] = opt_only(*box)                    # warm + compile
    bench._sync(box[0])

    def opt_window():
        c, g = box
        t0 = _time.perf_counter()
        for _ in range(n_probe):
            c, g = opt_only(c, g)
        bench._sync(c)
        box[:] = [c, g]
        return (_time.perf_counter() - t0) / n_probe

    t_opt, _ = bench._time_windows(opt_window, k_windows)
    del box

    carry = make_carry(make_state())

    @jax.jit
    def fwd_bwd(carry, inputs, labels):
        grads, loss = grad_of(carry, inputs, labels)
        return bench._probe_reduce(grads, loss)

    t_fb = bench._measure_fn(
        fwd_bwd, carry, (inputs, labels), n_probe, k_windows)

    out = _measure(carry, step, (inputs, labels), b,
                   {"batch": b, "seq": s, "num_layers": layers,
                    "variant": variant,
                    "tokens_per_sec": None})
    out["tokens_per_sec"] = round(out["value"] * s, 1)
    out["fwd_bwd_ms"] = round(t_fb * 1e3, 2)
    out["opt_alone_ms"] = round(t_opt * 1e3, 2)
    out["overlap_hidden_ms"] = round(
        max(t_fb + t_opt - out["step_ms"] / 1e3, 0.0) * 1e3, 2)
    out["metric"] = (f"gpt2_1p3b_proxy{layers}L_O2_fusedadam_"
                     "samples_per_sec_per_chip")
    if variant != "base":
        out["metric"] += f"_{variant}"
    _emit(out)


def bench_gpt2_tp8_full_step():
    """EXECUTE (not just compile) one full O2+FusedAdam+DLS train step
    of the whole 24-layer 1.316B-param GPT-2 under TP=8 + sequence
    parallelism (BASELINE.json configs[3] topology) on the 8-device
    virtual CPU mesh, asserting a finite loss.  The wall time is
    host-CPU execution time (1 core, 8 virtual devices) — a
    works-at-scale proof, NOT a throughput claim; per-device memory is
    XLA's analysis of the sharded program.  Run with JAX_PLATFORMS=cpu
    XLA_FLAGS=--xla_force_host_platform_device_count=8."""
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np
    import flax.linen as nn
    from jax.sharding import NamedSharding, PartitionSpec as P

    from apex_tpu import amp
    from apex_tpu.core import mesh as mesh_lib
    from apex_tpu.models import GPTModel, gpt_loss_fn
    from apex_tpu.optim import fused_adam

    # sequential dispatch (CPU-only flag, must be set BEFORE the first
    # backend query initializes the client): see the cross-program
    # rendezvous note in bench_gpt2_3d_full_step
    jax.config.update("jax_cpu_enable_async_dispatch", False)
    mesh = mesh_lib.initialize_mesh(tensor_model_parallel_size=8)
    cfg = _gpt_cfg(24, scan=True)
    cfg = __import__("dataclasses").replace(cfg, sequence_parallel=True)
    model = GPTModel(cfg)
    # batch sized for single-core CPU execution (~20 TFLOP/step); the
    # model is the full 1.3B — only the token count is small
    b = int(os.environ.get("BENCH_BATCH", "2"))
    s = int(os.environ.get("BENCH_SEQ", "1024"))
    ids0 = jnp.zeros((b, s), jnp.int32)
    tx = fused_adam(1e-4)

    def create_state():
        params = model.init(jax.random.PRNGKey(0), ids0)
        return amp.initialize(model.apply, params, tx,
                              opt_level="O2", half_dtype=jnp.bfloat16)

    state_shape = jax.eval_shape(create_state)
    specs = nn.get_partition_spec(state_shape)
    shardings = jax.tree.map(
        lambda sp: NamedSharding(mesh, sp), specs,
        is_leaf=lambda x: isinstance(x, P))
    data_sharding = NamedSharding(mesh, P("data"))

    def train_step(state, inputs, labels):
        def loss_fn(p):
            cp = state.policy.cast_to_compute(p)
            logits = state.apply_fn(cp, inputs)
            loss = gpt_loss_fn(logits.astype(jnp.float32), labels)
            return state.scale_loss(loss), loss

        grads, loss = jax.grad(loss_fn, has_aux=True)(state.params)
        new_state, finite = state.apply_gradients(grads=grads)
        return new_state, loss, finite

    n_params = sum(
        x.size for x in jax.tree.leaves(state_shape.params)
        if hasattr(x, "size"))
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size, size=(b, s + 1))
    ln_v = float(np.log(cfg.vocab_size))
    with jax.set_mesh(mesh):
        jitted = jax.jit(
            train_step,
            in_shardings=(shardings, data_sharding, data_sharding),
            donate_argnums=(0,))
        compiled = jitted.lower(
            state_shape,
            jax.ShapeDtypeStruct((b, s), jnp.int32),
            jax.ShapeDtypeStruct((b, s), jnp.int32)).compile()
        mem = compiled.memory_analysis()
        state = jax.jit(create_state, out_shardings=shardings)()
        inputs = jax.device_put(
            jnp.asarray(tokens[:, :-1], jnp.int32), data_sharding)
        labels = jax.device_put(
            jnp.asarray(tokens[:, 1:], jnp.int32), data_sharding)
        t0 = time.perf_counter()
        state, loss, finite = compiled(state, inputs, labels)
        loss = float(loss)
        dt = time.perf_counter() - t0
    assert np.isfinite(loss), f"non-finite loss {loss}"
    # init-loss plausibility (round-3 verdict item 4): a correctly
    # wired fresh model scores ≈ uniform over the vocab
    assert 0.8 * ln_v <= loss <= 1.6 * ln_v, (
        f"init loss {loss} implausible vs ln(V)={ln_v:.3f}")
    _emit({
        "metric": "gpt2_1p3b_tp8_sp_train_step_executed",
        "value": 1,
        "unit": "ok",
        "executed": True,
        "loss": round(loss, 4),
        "loss_over_ln_vocab": round(loss / ln_v, 3),
        "loss_plausibility_checked": "0.8 <= loss/ln(V) <= 1.6",
        "grads_finite": bool(finite),
        "batch": b, "seq": s,
        "host_cpu_step_seconds": round(dt, 1),
        "num_params": int(n_params),
        "mesh": dict(mesh.shape),
        "per_device_argument_bytes": getattr(mem, "argument_size_in_bytes",
                                             None),
        "per_device_temp_bytes": getattr(mem, "temp_size_in_bytes", None),
        "per_device_output_bytes": getattr(mem, "output_size_in_bytes",
                                           None),
    })


def bench_gpt2_3d_full_step():
    """EXECUTE one full-model train step of the 24-layer 1.3B GPT-2
    composed TP=2 × PP=2 × DP=2 *through the 1F1B schedule*: stages
    from ``build_model`` (12 layers each, TP/SP inside), embedding +
    learned positions + untied head closed over the pipelined region
    via ``loss_params``/``return_input_cotangents``, O2 master weights
    + FusedAdam + dynamic loss scaling on the whole pytree.  Finite
    loss asserted; wall time is host-CPU execution (works-at-scale
    proof, not throughput).  Embed/head are replicated here (their
    GSPMD vocab sharding is exercised by the TP=8 leg); compute dtype
    is f32 on CPU (XLA:CPU crashes on bf16 all-reduce inside
    partial-manual shard_map) and bf16 on TPU."""
    import dataclasses
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from apex_tpu import amp
    from apex_tpu.core import mesh as mesh_lib
    from apex_tpu.models import TransformerConfig, ParallelTransformerLayer
    from apex_tpu.optim import fused_adam
    from apex_tpu.transformer.pipeline_parallel import (
        build_model,
        forward_backward_pipelining_without_interleaving,
    )

    # async dispatch lets two programs' collectives interleave in
    # different per-device orders — a cross-program rendezvous deadlock
    # on the in-process CPU communicator (observed: a resharding
    # all-to-all racing the step's all-reduces).  CPU-only flag; must
    # be set BEFORE the first backend query initializes the client.
    jax.config.update("jax_cpu_enable_async_dispatch", False)
    mesh = mesh_lib.initialize_mesh(
        tensor_model_parallel_size=2,
        pipeline_model_parallel_size=2,
        data_parallel_size=2)
    gcfg = _gpt_cfg(24, scan=False)
    # s=256 keeps the peak inside the 125 GB host (the model is the
    # full 1.3B either way; only the token count is small)
    s = int(os.environ.get("BENCH_SEQ", "256"))
    m, mb = 2, 2
    cfg = TransformerConfig(
        vocab_size=gcfg.vocab_size, hidden_size=gcfg.hidden_size,
        num_layers=1, num_heads=gcfg.num_heads, max_seq_len=s,
        sequence_parallel=True, causal=True,
        dtype=jnp.float32 if jax.default_backend() == "cpu"
        else jnp.bfloat16)
    layer = ParallelTransformerLayer(cfg)
    x0 = jnp.zeros((mb, s, cfg.hidden_size), jnp.float32)
    stage_fn, stages, stage_spec = build_model(
        layer, num_layers=24, pipeline_model_parallel_size=2,
        rng=jax.random.PRNGKey(0), sample_input=x0,
        # one layer's residuals at a time when the 1F1B backward unit
        # recomputes its 12-layer stage — without this the per-tick vjp
        # holds all 12 layers' residuals (~24 GB across the 8 virtual
        # devices) and the leg OOMs the 125 GB host
        layer_remat=True)

    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size, size=(m * mb, s + 1))
    half = (jnp.float32 if jax.default_backend() == "cpu"
            else jnp.bfloat16)

    with jax.set_mesh(mesh):
        embed = jnp.asarray(
            rng.normal(size=(cfg.vocab_size, cfg.hidden_size)) * 0.02,
            jnp.float32)
        pos = jnp.asarray(
            rng.normal(size=(s, cfg.hidden_size)) * 0.02, jnp.float32)
        head = jnp.asarray(
            rng.normal(size=(cfg.hidden_size, cfg.vocab_size)) * 0.02,
            jnp.float32)
        # final pre-head LayerNorm, exactly as GPTModel applies after
        # the layer stack — round 3 omitted it from this hand-rolled
        # closure model, which is why the leg's init loss read 22.6
        # (≈ 2x ln(V)): 24 unnormalized residual additions grow the
        # stream's scale, inflating the logit variance.  Its params
        # ride loss_params so their grads close over the pipeline.
        fln_scale = jnp.ones((cfg.hidden_size,), jnp.float32)
        fln_bias = jnp.zeros((cfg.hidden_size,), jnp.float32)
        params = {"embed": embed, "pos": pos, "stages": stages,
                  "head": head, "fln_scale": fln_scale,
                  "fln_bias": fln_bias}
        n_params = sum(x.size for x in jax.tree.leaves(params))
        # bf16 moments (as the gpt2_1p3b proxy leg): XLA:CPU does not
        # honor buffer donation, so the step materializes a second
        # optimizer state — fp32 moments put the peak past 125 GB
        state = amp.initialize(
            None, params,
            fused_adam(1e-4, moment_dtype=jnp.bfloat16),
            opt_level="O2", half_dtype=half)

        # placement: stages sharded per build_model's spec; embed/head
        # masters+moments ZeRO-sharded over (data, tensor) — on 8
        # virtual CPU devices a replicated 412 MB f32 leaf materializes
        # 8 host copies, and with masters+2 moments+grads that alone
        # OOMs the 125 GB host
        emb_spec = {"embed": P(("data", "tensor"), None), "pos": P(),
                    "head": P(None, ("data", "tensor")),
                    "fln_scale": P(), "fln_bias": P()}

        # storage spec: additionally ZeRO-shard the per-stage axis over
        # `data` (distributed_fused_adam semantics) — XLA:CPU does not
        # honor donation, so the step materializes a second state and
        # the un-data-sharded x2 replication would put the peak past
        # the 125 GB host
        stage_storage = jax.tree.map(
            lambda sp: P(sp[0], "data", *sp[2:]), stage_spec,
            is_leaf=lambda v: isinstance(v, P))

        def place(tree):
            out = dict(tree)
            out["stages"] = jax.tree.map(
                lambda sp, l: jax.device_put(
                    l, NamedSharding(mesh, sp)),
                stage_storage, tree["stages"],
                is_leaf=lambda v: isinstance(v, P))
            for k, sp in emb_spec.items():
                out[k] = jax.device_put(
                    tree[k], NamedSharding(mesh, sp))
            return out

        opt = state.opt_state
        state = state.replace(
            params=place(state.params),
            opt_state=opt._replace(
                exp_avg=place(opt.exp_avg),
                exp_avg_sq=place(opt.exp_avg_sq)))
        # free the pre-placement unsharded copies (~20 GB of zombies:
        # build_model's stacked stages, amp.initialize's master copy
        # and moment inits all stay alive through these references)
        del stages, params, opt, embed, pos, head
        import gc

        gc.collect()
        # token ids/labels replicated: with them data-sharded, GSPMD
        # emits all-to-alls (in-tick label indexing, embedding
        # scatter-add) and XLA:CPU's in-process AllToAll thunk
        # deadlocks under the concurrent thunk executor — every fatal
        # trace of this leg died in InProcessCommunicator::AllToAll.
        # The data-sharded input path is exercised by the dryrun
        # dp×tp×sp×pp leg and tests/test_parallel.py; on TPU this leg
        # would run with P("data") inputs unchanged.
        inputs = jax.device_put(
            jnp.asarray(tokens[:, :-1], jnp.int32),
            NamedSharding(mesh, P()))
        labels = jax.device_put(
            jnp.asarray(tokens[:, 1:], jnp.int32),
            NamedSharding(mesh, P()))

        def train_step(state, inputs, labels):
            cp = state.policy.cast_to_compute(state.params)
            lab_mb = labels.reshape(m, mb, s)

            def loss_fn(lp, y, i):
                hd, g, be = lp
                # final LN (as GPTModel's post-stack norm), fp32
                yf = y.astype(jnp.float32)
                mu = jnp.mean(yf, axis=-1, keepdims=True)
                var = jnp.var(yf, axis=-1, keepdims=True)
                yn = (yf - mu) * jax.lax.rsqrt(var + 1e-5) * g + be
                logits = (yn.astype(y.dtype) @ hd).astype(jnp.float32)
                lab = jax.lax.dynamic_index_in_dim(
                    lab_mb, jnp.clip(i, 0, m - 1), axis=0,
                    keepdims=False)
                logp = jax.nn.log_softmax(logits)
                nll = -jnp.take_along_axis(
                    logp, lab[..., None], axis=-1)[..., 0]
                return state.scale_loss(jnp.mean(nll))

            h = (jnp.take(cp["embed"], inputs, axis=0)
                 + cp["pos"][None]).astype(cfg.dtype)
            # distribute_inputs=False: M=2 needs no feed ring, and the
            # cyclic reshard's all-to-all is the one collective the
            # XLA:CPU in-process communicator deadlocks on
            sloss, sgrads, aux = \
                forward_backward_pipelining_without_interleaving(
                    stage_fn, loss_fn, cp["stages"], h, mesh=mesh,
                    num_microbatches=m,
                    loss_params=(cp["head"], cp["fln_scale"],
                                 cp["fln_bias"]),
                    return_input_cotangents=True,
                    distribute_inputs=False)
            cts = aux["input_cotangents"].astype(jnp.float32)
            cts = cts.reshape(m * mb, s, cfg.hidden_size)
            d_embed = jnp.zeros_like(cp["embed"]).at[inputs].add(cts)
            d_head, d_flns, d_flnb = aux["loss_params_grads"]
            grads = {"embed": d_embed, "pos": cts.sum(0),
                     "stages": sgrads, "head": d_head,
                     "fln_scale": d_flns, "fln_bias": d_flnb}
            new_state, finite = state.apply_gradients(grads=grads)
            loss = state.loss_scaler.unscale(
                state.loss_scale_state, sloss)
            return new_state, loss, finite

        step = jax.jit(train_step, donate_argnums=(0,))
        t0 = time.perf_counter()
        state, loss, finite = step(state, inputs, labels)
        loss = float(loss)
        dt = time.perf_counter() - t0
    assert np.isfinite(loss), f"non-finite loss {loss}"
    # init-loss plausibility (round-3 verdict item 4): with the final
    # LN restored this leg must agree with the TP=8 leg's ≈ ln(V)
    ln_v = float(np.log(cfg.vocab_size))
    assert 0.8 * ln_v <= loss <= 1.6 * ln_v, (
        f"init loss {loss} implausible vs ln(V)={ln_v:.3f}")
    _emit({
        "metric": "gpt2_1p3b_tp2pp2dp2_1f1b_train_step_executed",
        "value": 1,
        "unit": "ok",
        "executed": True,
        "loss": round(loss, 4),
        "loss_over_ln_vocab": round(loss / ln_v, 3),
        "loss_plausibility_checked": "0.8 <= loss/ln(V) <= 1.6",
        "grads_finite": bool(finite),
        "microbatches": m, "microbatch_size": mb, "seq": s,
        "host_cpu_step_seconds": round(dt, 1),
        "num_params": int(n_params),
        "mesh": dict(mesh.shape),
        "inputs_replicated_on_cpu": True,
    })


def bench_mistral7b_tp8_full_step():
    """EXECUTE one full O2+FusedAdam+DLS train step of the 7.24B
    ``mistral_7b`` preset — GQA (8 kv heads over TP=8 → exactly one kv
    head per shard, the divisibility edge), SwiGLU gated MLP, RMSNorm,
    untied head — under TP=8 + sequence parallelism on the 8-device
    virtual CPU mesh, asserting a finite, ln(V)-plausible init loss
    (round-4 verdict item 3: promote the 7B presets + GQA sharding
    from config-file claims to executed capability).

    CPU-host memory shape: XLA:CPU does not honor buffer donation for
    SHARDED computations (re-probed this round: an 8 GB donated
    mesh-sharded array peaks at 17 GB; single-device peaks at 8.6 GB),
    so a one-jit state→state step would materialize the 7B O2 state
    twice (2 × 58 GB) plus transients — past the 125 GB host.  The leg
    therefore runs the step in two phases with IDENTICAL math:
    (1) one sharded jit computing scaled-loss grads w.r.t. the fp32
    masters, (2) the optimizer/DLS sequence of
    ``MixedPrecisionTrainState.apply_gradients`` applied leaf-wise
    (upcast → unscale → finite-AND → FusedAdam update → select →
    scale-adjust), bounding live temps to one stacked leaf.  Per-leaf
    unscaled finiteness equals after-unscale finiteness (x/scale with
    scale ≥ 1 preserves inf/nan and finiteness).  On a real TPU mesh
    the same step runs as ONE jit with donation — this split is a
    host-RAM accommodation, not a framework limitation."""
    import functools as ft
    import resource
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np
    import flax.linen as nn
    from jax.sharding import NamedSharding, PartitionSpec as P

    from apex_tpu import amp
    from apex_tpu.core import mesh as mesh_lib
    from apex_tpu.models import LlamaConfig, LlamaModel, gpt_loss_fn
    from apex_tpu.optim import fused_adam

    jax.config.update("jax_cpu_enable_async_dispatch", False)
    mesh = mesh_lib.initialize_mesh(tensor_model_parallel_size=8)
    b = int(os.environ.get("BENCH_BATCH", "1"))
    s = int(os.environ.get("BENCH_SEQ", "512"))
    cfg = LlamaConfig.mistral_7b(
        max_seq_len=s, dtype=jnp.bfloat16, remat=True,
        scan_layers=True, sequence_parallel=True,
        # full 32 layers by default; override only for smoke tests
        num_layers=int(os.environ.get("BENCH_7B_LAYERS", "32")))
    model = LlamaModel(cfg)
    ids0 = jnp.zeros((b, s), jnp.int32)
    # bf16 moments as the gpt2 legs: fp32 moments alone are 58 GB
    tx = fused_adam(1e-4, moment_dtype=jnp.bfloat16)

    def create_state():
        params = model.init(jax.random.PRNGKey(0), ids0)
        return amp.initialize(model.apply, params, tx,
                              opt_level="O2", half_dtype=jnp.bfloat16)

    state_shape = jax.eval_shape(create_state)
    specs = nn.get_partition_spec(state_shape)
    shardings = jax.tree.map(
        lambda sp: NamedSharding(mesh, sp), specs,
        is_leaf=lambda x: isinstance(x, P))
    data_sharding = NamedSharding(mesh, P("data"))
    n_params = sum(
        x.size for x in jax.tree.leaves(state_shape.params)
        if hasattr(x, "size"))

    def grad_step(state, inputs, labels):
        def loss_fn(p):
            cp = state.policy.cast_to_compute(p)
            logits = state.apply_fn(cp, inputs)
            loss = gpt_loss_fn(logits, labels)
            return state.scale_loss(loss), loss

        grads, loss = jax.grad(loss_fn, has_aux=True)(state.params)
        return grads, loss

    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size, size=(b, s + 1))
    ln_v = float(np.log(cfg.vocab_size))
    with jax.set_mesh(mesh):
        jitted = jax.jit(
            grad_step,
            in_shardings=(shardings, data_sharding, data_sharding),
            out_shardings=(shardings.params, None))
        compiled = jitted.lower(
            state_shape,
            jax.ShapeDtypeStruct((b, s), jnp.int32),
            jax.ShapeDtypeStruct((b, s), jnp.int32)).compile()
        mem = compiled.memory_analysis()
        state = jax.jit(create_state, out_shardings=shardings)()
        inputs = jax.device_put(
            jnp.asarray(tokens[:, :-1], jnp.int32), data_sharding)
        labels = jax.device_put(
            jnp.asarray(tokens[:, 1:], jnp.int32), data_sharding)

        t0 = time.perf_counter()
        grads, sloss = compiled(state, inputs, labels)
        sloss = float(sloss)        # sync: grads materialized
        t_grads = time.perf_counter() - t0

        # phase 2: apply_gradients leaf-wise (identical sequence) ----
        ls, ls_state = state.loss_scaler, state.loss_scale_state
        scale = ls_state.loss_scale

        @jax.jit
        def leaf_finite(g, scale):
            return jnp.isfinite(g.astype(jnp.float32) / scale).all()

        finite = jnp.asarray(True)
        for g in jax.tree.leaves(grads):
            finite = finite & leaf_finite(g, scale)

        @jax.jit
        def leaf_update(p, m, v, g, count, scale, finite):
            g = g.astype(p.dtype) / scale          # upcast → unscale
            upd, new = tx.update(
                {"x": g},
                type(state.opt_state)(
                    count=count, exp_avg={"x": m}, exp_avg_sq={"x": v}),
                {"x": p})
            new_p = p + upd["x"]
            sel = lambda a, b: jnp.where(finite, a, b)
            return (sel(new_p, p), sel(new.exp_avg["x"], m),
                    sel(new.exp_avg_sq["x"], v), new.count)

        params = state.params
        opt = state.opt_state
        flat_p, treedef = jax.tree.flatten(params)
        flat_m = treedef.flatten_up_to(opt.exp_avg)
        flat_v = treedef.flatten_up_to(opt.exp_avg_sq)
        flat_g = treedef.flatten_up_to(grads)
        del grads, params
        new_count = opt.count
        for i in range(len(flat_p)):
            flat_p[i], flat_m[i], flat_v[i], new_count = leaf_update(
                flat_p[i], flat_m[i], flat_v[i], flat_g[i],
                opt.count, scale, finite)
            flat_g[i] = None                       # free as we go
        new_params = jax.tree.unflatten(treedef, flat_p)
        new_opt = type(opt)(
            count=jnp.where(finite, new_count, opt.count),
            exp_avg=jax.tree.unflatten(treedef, flat_m),
            exp_avg_sq=jax.tree.unflatten(treedef, flat_v))
        new_ls_state = ls.adjust(ls_state, finite)
        state = state.replace(
            step=state.step + 1, params=new_params, opt_state=new_opt,
            loss_scale_state=new_ls_state)
        jax.block_until_ready(state.params)
        dt = time.perf_counter() - t0
        loss = float(ls.unscale(ls_state, sloss))
        finite = bool(finite)

    assert np.isfinite(loss), f"non-finite loss {loss}"
    assert 0.8 * ln_v <= loss <= 1.6 * ln_v, (
        f"init loss {loss} implausible vs ln(V)={ln_v:.3f}")
    peak_rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    _emit({
        "metric": "mistral_7b_tp8_sp_train_step_executed",
        "value": 1,
        "unit": "ok",
        "executed": True,
        "loss": round(loss, 4),
        "loss_over_ln_vocab": round(loss / ln_v, 3),
        "loss_plausibility_checked": "0.8 <= loss/ln(V) <= 1.6",
        "grads_finite": finite,
        "batch": b, "seq": s,
        "host_cpu_step_seconds": round(dt, 1),
        "host_cpu_grad_seconds": round(t_grads, 1),
        "num_params": int(n_params),
        "kv_heads_per_shard": cfg.kv_heads // mesh.shape["tensor"],
        "mesh": dict(mesh.shape),
        "host_peak_rss_bytes": int(peak_rss),
        "two_phase_cpu_note": (
            "grad jit + leaf-wise optimizer (XLA:CPU ignores donation "
            "for sharded buffers; one-jit form exceeds host RAM at 7B "
            "O2 x2 state — TPU runs the one-jit form)"),
        "per_device_argument_bytes": getattr(
            mem, "argument_size_in_bytes", None),
        "per_device_temp_bytes": getattr(mem, "temp_size_in_bytes",
                                         None),
        "per_device_output_bytes": getattr(
            mem, "output_size_in_bytes", None),
    })


def bench_moe_mixtral():
    """Measured MoE throughput leg (ISSUE-3 satellite / round-5
    verdict Missing #2: MoE was dryrun-correct and parity-tested but
    had no on-chip row).  A Mixtral-geometry proxy — the 8x7b recipe
    (hidden 4096, 8 SwiGLU experts, top-2 token-choice routing, GQA,
    sliding window) at BENCH_MOE_LAYERS of its 32 layers, the same
    full-geometry-proxy convention as ``gpt2_1p3b`` — trained one real
    O2+FusedAdam+DLS step per measurement under the standard
    best-of-window/agreement hygiene.  The router trains through
    ``moe_aux_loss`` exactly as production would.

    ``moe_capacity_factor`` defaults to the *training* value 1.25
    (token drop is routine when training from scratch; the drop-free
    parity default cf=4 makes the dispatch masks quadratic in S and is
    an import-parity concern, not a throughput recipe) — override with
    BENCH_MOE_CF.  BENCH_MOE_PRESET=tiny swaps in LlamaConfig.tiny
    with the same expert structure for CPU smoke tests."""
    import jax
    import jax.numpy as jnp

    from apex_tpu import amp
    from apex_tpu.models import (
        LlamaConfig,
        LlamaModel,
        gpt_loss_fn,
        moe_aux_loss,
    )
    from apex_tpu.optim import fused_adam

    preset = os.environ.get("BENCH_MOE_PRESET", "mixtral")
    b = int(os.environ.get("BENCH_BATCH", "1"))
    s = int(os.environ.get("BENCH_SEQ", "1024"))
    cf = float(os.environ.get("BENCH_MOE_CF", "1.25"))
    if preset == "tiny":
        cfg = LlamaConfig.tiny(
            max_seq_len=s, num_moe_experts=4, moe_top_k=2,
            moe_capacity_factor=cf, scan_layers=False)
    else:
        cfg = LlamaConfig.mixtral_8x7b(
            max_seq_len=s, dtype=jnp.bfloat16, remat=True,
            scan_layers=False, moe_capacity_factor=cf,
            # 2 of 32 layers fits the chip beside the O2 state; the
            # per-layer geometry (the thing measured) is full-size
            num_layers=int(os.environ.get("BENCH_MOE_LAYERS", "2")))
    model = LlamaModel(cfg)

    ids = jax.random.randint(
        jax.random.PRNGKey(0), (b, s + 1), 0, cfg.vocab_size, jnp.int32)
    inputs, labels = ids[:, :-1], ids[:, 1:]
    params = model.init(jax.random.PRNGKey(0), inputs[:1, :8])
    n_params = sum(x.size for x in jax.tree.leaves(params))
    state = amp.initialize(
        model.apply, params,
        fused_adam(1e-4, moment_dtype=jnp.bfloat16),
        opt_level="O2", half_dtype=jnp.bfloat16)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(state, inputs, labels):
        def loss_fn(p):
            cp = state.policy.cast_to_compute(p)
            logits, mut = state.apply_fn(cp, inputs,
                                         mutable=["losses"])
            loss = gpt_loss_fn(logits, labels) + moe_aux_loss(mut)
            return state.scale_loss(loss), loss

        grads, loss = jax.grad(loss_fn, has_aux=True)(state.params)
        new_state, finite = state.apply_gradients(grads=grads)
        return new_state, loss, finite

    out = _measure(state, step, (inputs, labels), b,
                   {"batch": b, "seq": s,
                    "num_layers": cfg.num_layers,
                    "num_experts": cfg.num_moe_experts,
                    "moe_top_k": cfg.moe_top_k,
                    "moe_capacity_factor": cf,
                    "num_params": int(n_params)})
    out["tokens_per_sec"] = round(out["value"] * s, 1)
    out["metric"] = (f"moe_mixtral_proxy{cfg.num_layers}L_O2_fusedadam"
                     "_samples_per_sec_per_chip")
    _emit(out)


# ----------------------------------------------------------------- BERT O1

# (lifted to apex_tpu/plan/costs.py — imported back above as _ddp_bytes_on_wire)


def bench_bert_o1():
    """BERT-Large under O1 — per-op cast interceptor (amp/o1.py clone
    mechanism + amp/lists.py tables) + FusedAdam — so O1 has a measured
    number like O2 (round-1 verdict item 5).  The model is built with
    ``dtype=None`` (modules promote with their fp32 params) and every
    MXU op is routed to bf16 by the interceptor, the reference's O1
    semantics (fp32 masters, per-op half compute).

    ISSUE-8 satellite (ROADMAP 2b): the emission now carries the
    ``_ddp_bytes_on_wire`` model for this model's grad sync (int8
    all-reduce ≈ 4× fewer ICI bytes than fp32), and the leg
    orchestrates a measured ``bert_o1_ddp`` child — an 8-way
    virtual-CPU-mesh DDP A/B of ``allreduce_dtype`` None vs ``"int8"``
    on a layer-shrunk proxy (BENCH_BERT_DDP=0 skips it; on-chip, run
    the child leg directly on the real mesh)."""
    from apex_tpu.utils import numcheck
    from apex_tpu.utils.metrics import counters as _counters

    # ISSUE-10 satellite: the leg rides the runtime numerics sanitizer
    # in observe mode — the emission carries the grad underflow-to-zero
    # fraction and the loss-scale growth/backoff event counts, so the
    # loss-trajectory band tests can correlate precision events with
    # divergence.  One scalar reduction + async callback per step;
    # BENCH_NUMCHECK=0 opts out for on-chip wall-clock purity.
    observe_numerics = os.environ.get("BENCH_NUMCHECK", "1") != "0"
    if observe_numerics:
        numcheck.reset()
        numcheck.instrument(strict=False)
    events_before = _counters.snapshot()
    try:
        out = _bench_bert_o1_measured(observe_numerics, events_before)
    finally:
        # the wrappers are process-wide: never leak them into later
        # legs run in this process if the measurement raises
        if observe_numerics:
            numcheck.uninstrument()
    if os.environ.get("BENCH_BERT_DDP", "1") != "0":
        # measured companion: 8-way virtual-CPU-mesh DDP A/B of
        # allreduce_dtype None vs "int8" on a layer-shrunk proxy
        out["ddp_int8_ab"] = _run_child("bert_o1_ddp", {
            "JAX_PLATFORMS": "cpu",
            "PALLAS_AXON_POOL_IPS": None,
            "XLA_FLAGS": (os.environ.get("XLA_FLAGS", "")
                          + " --xla_force_host_platform_device"
                            "_count=8").strip(),
        }, timeout=1500)
    if os.environ.get("BENCH_BERT_ZERO", "1") != "0":
        # ISSUE-11 companion: replicated-vs-ZeRO-2 optimizer-state A/B
        # on the same virtual mesh (hbm_peak drop, grown-batch row)
        out["zero_ab"] = _run_child("bert_o1_zero", {
            "JAX_PLATFORMS": "cpu",
            "PALLAS_AXON_POOL_IPS": None,
            "XLA_FLAGS": (os.environ.get("XLA_FLAGS", "")
                          + " --xla_force_host_platform_device"
                            "_count=8").strip(),
        }, timeout=1500)
    _emit(out)


def _bench_bert_o1_measured(observe_numerics, events_before):
    """The measured body of :func:`bench_bert_o1` (split out so the
    numcheck instrumentation wraps it in one try/finally)."""
    import jax
    import jax.numpy as jnp

    from apex_tpu import amp
    from apex_tpu.amp import o1
    from apex_tpu.models import BertConfig, BertModel, bert_mlm_loss_fn
    from apex_tpu.optim import fused_adam
    from apex_tpu.utils import numcheck
    from apex_tpu.utils.metrics import counters as _counters

    b = int(os.environ.get("BENCH_BATCH", "16"))
    cfg = BertConfig.bert_large(remat=True, dtype=None, scan_layers=False)
    model = BertModel(cfg)
    s = int(os.environ.get("BENCH_SEQ", str(min(cfg.max_seq_len, 512))))
    p = min(max(8, int(0.15 * s / 8 + 0.5) * 8), s)

    rng = jax.random.PRNGKey(0)
    ids = jax.random.randint(rng, (b, s), 0, cfg.vocab_size)
    positions = jnp.argsort(jax.random.uniform(rng, (b, s)), axis=-1)[:, :p]
    mlm_labels = jnp.take_along_axis(ids, positions, axis=1)

    def apply_fn(params, ids, **kw):
        with o1.o1_intercept(jnp.bfloat16):
            return model.apply(params, ids, **kw)

    params = model.init(jax.random.PRNGKey(0), ids[:2])
    state = amp.initialize(apply_fn, params, fused_adam(1e-4),
                           opt_level="O1")

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(state, ids, positions, mlm_labels):
        def loss_fn(p):
            logits, _ = state.apply_fn(
                p, ids, mlm_positions=positions, deterministic=True)
            loss = bert_mlm_loss_fn(logits.astype(jnp.float32), mlm_labels)
            return state.scale_loss(loss), loss

        grads, loss = jax.grad(
            loss_fn, has_aux=True)(state.compute_params())
        new_state, finite = state.apply_gradients(grads=grads)
        return new_state, loss, finite

    n_params = sum(x.size for x in jax.tree.leaves(params))
    replicas = int(os.environ.get("BENCH_DDP_REPLICAS", "8"))
    out = _measure(state, step, (ids, positions, mlm_labels), b,
                   {"batch": b, "seq": s})
    out["metric"] = "bert_large_O1_fusedadam_samples_per_sec_per_chip"
    # ISSUE-8 / ROADMAP 2b: what the grad sync of THIS model costs on
    # the wire per step, fp32 vs bf16 vs the ddp.py int8 path
    out["ddp_bytes_on_wire"] = _ddp_bytes_on_wire(n_params, replicas)
    # ISSUE-10: precision-event telemetry beside the throughput number
    if observe_numerics:
        jax.effects_barrier()
        stats = numcheck.summary()
        after = _counters.snapshot()
        out["numcheck"] = {
            "grad_underflow_frac": round(
                stats["grad_underflow_frac"], 6),
            "nonfinite_grad_steps": stats["nonfinite_grad_steps"],
            "loss_scale_growth": (
                after.get("amp.loss_scale.growth", 0)
                - events_before.get("amp.loss_scale.growth", 0)),
            "loss_scale_backoff": (
                after.get("amp.loss_scale.backoff", 0)
                - events_before.get("amp.loss_scale.backoff", 0)),
        }
    return out


def bench_bert_o1_ddp():
    """Measured ROADMAP-2b row: the BERT O1 recipe under 8-way DDP
    (``shard_map`` + ``all_reduce_mean_grads``), A/B'ing the exact
    fp32 grad all-reduce against the EQuARX-style int8 one
    (``parallel/ddp.py``).  Virtual-CPU-mesh proxy by default (the
    layer count shrinks via BENCH_BERT_DDP_LAYERS — protocol and
    LOSS-AGREEMENT are the artifact; on real ICI the int8 row's win
    tracks the 4× wire-byte reduction in ``_ddp_bytes_on_wire``,
    while CPU "wire" is memcpy so the wall ratio here only prices the
    quantize/dequant arithmetic).  Emits samples/sec + final-loss
    agreement + the bytes model for the measured size.

    Env: BENCH_BERT_DDP_LAYERS (2), BENCH_BATCH (16 global),
    BENCH_SEQ (128), BENCH_DDP_STEPS (8)."""
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from apex_tpu import amp
    from apex_tpu import parallel as apx_parallel
    from apex_tpu.amp import o1
    from apex_tpu.models import BertConfig, BertModel, bert_mlm_loss_fn
    from apex_tpu.optim import fused_adam

    n_dev = jax.device_count()
    if n_dev < 2:
        _emit({"metric": "bert_o1_ddp", "value": None,
               "skipped": f"needs >= 2 devices, have {n_dev}"})
        return
    layers = int(os.environ.get("BENCH_BERT_DDP_LAYERS", "2"))
    b = int(os.environ.get("BENCH_BATCH", "16"))
    b -= b % n_dev                     # divisible global batch
    b = max(b, n_dev)
    cfg = BertConfig.bert_large(remat=True, dtype=None,
                                scan_layers=False, num_layers=layers)
    model = BertModel(cfg)
    s = int(os.environ.get("BENCH_SEQ", str(min(cfg.max_seq_len, 128))))
    p = min(max(8, int(0.15 * s / 8 + 0.5) * 8), s)
    steps = int(os.environ.get("BENCH_DDP_STEPS", "8"))

    rng = jax.random.PRNGKey(0)
    ids = jax.random.randint(rng, (b, s), 0, cfg.vocab_size)
    positions = jnp.argsort(jax.random.uniform(rng, (b, s)),
                            axis=-1)[:, :p]
    mlm_labels = jnp.take_along_axis(ids, positions, axis=1)

    def apply_fn(params, ids, **kw):
        with o1.o1_intercept(jnp.bfloat16):
            return model.apply(params, ids, **kw)

    init = model.init(jax.random.PRNGKey(0), ids[:2])
    n_params = sum(x.size for x in jax.tree.leaves(init))
    # raw mesh, NOT registered with core.mesh: the step is fully
    # manual inside shard_map, so maybe_constrain stays a no-op
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:n_dev]),
                             ("data",))

    def run(allreduce_dtype):
        # private param copy: the donated step consumes the state's
        # buffers, and both A/B runs must start from the same init
        state = amp.initialize(apply_fn,
                               jax.tree.map(jnp.copy, init),
                               fused_adam(1e-4), opt_level="O1")

        def dp_step(state, ids, positions, mlm_labels):
            def loss_fn(p):
                logits, _ = state.apply_fn(
                    p, ids, mlm_positions=positions,
                    deterministic=True)
                loss = bert_mlm_loss_fn(
                    logits.astype(jnp.float32), mlm_labels)
                return state.scale_loss(loss), loss

            grads, loss = jax.grad(
                loss_fn, has_aux=True)(state.compute_params())
            grads = apx_parallel.all_reduce_mean_grads(
                grads, "data", allreduce_dtype=allreduce_dtype)
            new_state, finite = state.apply_gradients(grads=grads)
            return new_state, jax.lax.pmean(loss, "data"), finite

        step = jax.jit(jax.shard_map(
            dp_step, mesh=mesh,
            in_specs=(P(), P("data"), P("data"), P("data")),
            out_specs=(P(), P(), P()), check_vma=False),
            donate_argnums=(0,))
        state, loss, _ = step(state, ids, positions, mlm_labels)
        bench._sync(loss)              # compile + warm
        t0 = time.perf_counter()
        for _ in range(steps):
            state, loss, finite = step(state, ids, positions,
                                       mlm_labels)
        bench._sync(loss)
        dt = (time.perf_counter() - t0) / steps
        return {
            "allreduce_dtype": str(allreduce_dtype or "fp32"),
            "samples_per_sec": round(b / dt, 2),
            "step_ms": round(dt * 1e3, 2),
            "final_loss": round(float(loss), 5),
            "loss_finite": bool(finite),
        }

    exact = run(None)
    int8 = run("int8")
    _emit({
        "metric": "bert_o1_ddp_int8_allreduce_samples_per_sec",
        "value": int8["samples_per_sec"],
        "unit": "samples/sec (CPU-mesh proxy)",
        "replicas": n_dev, "global_batch": b, "seq": s,
        "num_layers": layers, "num_params": int(n_params),
        "rows": {"fp32_allreduce": exact, "int8_allreduce": int8},
        "sps_vs_fp32_allreduce": round(
            int8["samples_per_sec"]
            / max(exact["samples_per_sec"], 1e-9), 3),
        "final_loss_delta": round(
            abs(int8["final_loss"] - exact["final_loss"]), 5),
        "ddp_bytes_on_wire": _ddp_bytes_on_wire(n_params, n_dev),
        "note": ("measured ROADMAP-2b row: wire bytes drop 4x (model "
                 "above; genuine int8 all_to_all/all_gather traffic), "
                 "loss trajectory agreement is gated by "
                 "test_loss_trajectory's exact-vs-int8 band test; the "
                 "CPU wall ratio prices quantize arithmetic, not ICI "
                 "— the on-chip win follows the bytes model"),
    })


# (lifted to apex_tpu/plan/costs.py — imported back above as _zero_bytes_on_wire)


def bench_bert_o1_zero():
    """Measured ISSUE-11 row: the BERT recipe under 8-way DP at O2,
    A/B'ing replicated optimizer state against ZeRO-2
    (``parallel.distributed_optim``: reduce-scatter grads →
    shard-local FusedAdam on fp32 master shards → bf16 param
    all-gather).  Three rows:

    - ``dp`` — the baseline: fp32 masters + both moments replicated,
      fp32 grad all-reduce.
    - ``zero2`` — same global batch: the hbm_peak / state-bytes drop
      at unchanged math (final-loss agreement emitted; the band gate
      is ``test_loss_trajectory``'s DP-vs-ZeRO-2 leg).
    - ``zero2_grown`` — the reclaimed-capacity-becomes-throughput
      play: the per-chip batch grown until the ZeRO step's modeled
      HBM fills the DP baseline's budget, samples/sec at the larger
      batch.  (CPU-mesh proxy: the HBM numbers are XLA
      memory-analysis bytes of the compiled step — exact and
      deterministic; the wall ratio prices CPU compute, not HBM
      bandwidth — on chip the larger batch's win follows the
      roofline as usual.)

    Env: BENCH_BERT_ZERO_LAYERS (2), BENCH_BATCH (16 global),
    BENCH_SEQ (128), BENCH_ZERO_STEPS (8), BENCH_ZERO_GROWN_BATCH
    (0 = derive from the reclaimed bytes)."""
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from apex_tpu import amp
    from apex_tpu import parallel as apx_parallel
    from apex_tpu.models import BertConfig, BertModel, bert_mlm_loss_fn
    from apex_tpu.optim import fused_adam
    from apex_tpu.parallel import ZeroConfig, zero_state_specs

    n_dev = jax.device_count()
    if n_dev < 2:
        _emit({"metric": "bert_o1_zero", "value": None,
               "skipped": f"needs >= 2 devices, have {n_dev}"})
        return
    layers = int(os.environ.get("BENCH_BERT_ZERO_LAYERS", "2"))
    b = int(os.environ.get("BENCH_BATCH", "16"))
    b -= b % n_dev
    b = max(b, n_dev)
    cfg = BertConfig.bert_large(remat=True, dtype=None,
                                scan_layers=False, num_layers=layers)
    model = BertModel(cfg)
    s = int(os.environ.get("BENCH_SEQ", str(min(cfg.max_seq_len, 128))))
    p = min(max(8, int(0.15 * s / 8 + 0.5) * 8), s)
    steps = int(os.environ.get("BENCH_ZERO_STEPS", "8"))

    def batch_of(nb):
        rng = jax.random.PRNGKey(0)
        ids = jax.random.randint(rng, (nb, s), 0, cfg.vocab_size)
        positions = jnp.argsort(jax.random.uniform(rng, (nb, s)),
                                axis=-1)[:, :p]
        return ids, positions, jnp.take_along_axis(ids, positions,
                                                   axis=1)

    init = model.init(jax.random.PRNGKey(0), batch_of(2)[0])
    n_params = sum(x.size for x in jax.tree.leaves(init))
    tx = fused_adam(1e-4)
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:n_dev]),
                             ("data",))

    def loss_grads(state, ids, positions, mlm_labels):
        def loss_fn(pr):
            cp = state.policy.cast_to_compute(pr)
            logits, _ = state.apply_fn(
                cp, ids, mlm_positions=positions, deterministic=True)
            loss = bert_mlm_loss_fn(logits.astype(jnp.float32),
                                    mlm_labels)
            return state.scale_loss(loss), loss

        return jax.grad(loss_fn, has_aux=True)(state.params)

    def measure(step, state, batch, nb, extra):
        compiled = bench._aot_compile(step, state, *batch)
        timed = compiled if compiled is not None else step
        state, loss, finite = timed(state, *batch)
        bench._sync(loss)                  # compile + warm
        t0 = time.perf_counter()
        for _ in range(steps):
            state, loss, finite = timed(state, *batch)
        bench._sync(loss)
        dt = (time.perf_counter() - t0) / steps
        mem = {}
        if compiled is not None:
            try:
                ana = compiled.memory_analysis()
                mem = {
                    "argument": getattr(ana, "argument_size_in_bytes",
                                        None),
                    "output": getattr(ana, "output_size_in_bytes",
                                      None),
                    "temp": getattr(ana, "temp_size_in_bytes", None),
                }
            except Exception:
                mem = {}
        row = {
            "global_batch": nb,
            "samples_per_sec": round(nb / dt, 2),
            "step_ms": round(dt * 1e3, 2),
            "final_loss": round(float(loss), 5),
            "loss_finite": bool(finite),
            "hbm_analysis_bytes": mem,
            "hbm_peak_bytes": bench._analysis_estimate(mem) if mem
            else None,
        }
        row.update(extra)
        return row

    def run_dp(nb):
        state = amp.initialize(model.apply,
                               jax.tree.map(jnp.copy, init), tx,
                               opt_level="O2",
                               half_dtype=jnp.bfloat16)

        def dp_step(state, ids, positions, mlm_labels):
            grads, loss = loss_grads(state, ids, positions, mlm_labels)
            grads = apx_parallel.all_reduce_mean_grads(grads, "data")
            new_state, finite = state.apply_gradients(grads=grads)
            return new_state, jax.lax.pmean(loss, "data"), finite

        step = jax.jit(jax.shard_map(
            dp_step, mesh=mesh,
            in_specs=(P(), P("data"), P("data"), P("data")),
            out_specs=(P(), P(), P()), check_vma=False),
            donate_argnums=(0,))
        # replicated resident state: fp32 masters + both moments on
        # every chip
        state_bytes = sum(l.size * l.dtype.itemsize
                          for l in jax.tree.leaves(state.opt_state)) \
            + sum(l.size * l.dtype.itemsize
                  for l in jax.tree.leaves(state.params))
        return measure(step, state, batch_of(nb), nb,
                       {"layout": "replicated",
                        "state_bytes_per_chip": int(state_bytes)})

    def run_zero(nb):
        state = amp.initialize(model.apply,
                               jax.tree.map(jnp.copy, init), tx,
                               opt_level="O2", half_dtype=jnp.bfloat16,
                               zero=ZeroConfig(axis="data", stage=2,
                                               axis_size=n_dev))
        specs = zero_state_specs(state)

        def z_step(state, ids, positions, mlm_labels):
            grads, loss = loss_grads(state, ids, positions, mlm_labels)
            new_state, finite = state.apply_gradients(grads=grads)
            return new_state, jax.lax.pmean(loss, "data"), finite

        step = jax.jit(jax.shard_map(
            z_step, mesh=mesh,
            in_specs=(specs, P("data"), P("data"), P("data")),
            out_specs=(specs, P(), P()), check_vma=False),
            donate_argnums=(0,))
        # sharded resident state: 1/n of masters+moments + the bf16
        # param replica
        state_bytes = sum(
            -(-l.size // n_dev) * l.dtype.itemsize
            for l in jax.tree.leaves(state.opt_state)) \
            + sum(l.size * l.dtype.itemsize
                  for l in jax.tree.leaves(state.params))
        return measure(step, state, batch_of(nb), nb,
                       {"layout": "zero2_sharded",
                        "state_bytes_per_chip": int(state_bytes)})

    dp = run_dp(b)
    zero = run_zero(b)

    # grow the per-chip batch into the reclaimed HBM: activation bytes
    # scale ~linearly with batch (temp dominates), so the headroom in
    # samples is reclaimed / (temp / batch)
    grown = int(os.environ.get("BENCH_ZERO_GROWN_BATCH", "0"))
    reclaimed = (dp["hbm_peak_bytes"] or 0) - (zero["hbm_peak_bytes"]
                                               or 0)
    if not grown:
        temp = (zero["hbm_analysis_bytes"] or {}).get("temp") or 0
        per_sample = max(temp // max(b, 1), 1)
        grown = b + max(int(reclaimed // per_sample), 0)
        grown = min(grown, 4 * b)
        grown -= grown % n_dev
        grown = max(grown, b)
    zero_grown = run_zero(grown)
    fits = (zero_grown["hbm_peak_bytes"] or 0) <= \
        (dp["hbm_peak_bytes"] or 0)

    _emit({
        "metric": "bert_o2_zero2_samples_per_sec",
        "value": zero_grown["samples_per_sec"],
        "unit": "samples/sec (CPU-mesh proxy)",
        "replicas": n_dev, "seq": s, "num_layers": layers,
        "num_params": int(n_params),
        "rows": {"dp": dp, "zero2": zero, "zero2_grown": zero_grown},
        "hbm_peak_drop_bytes": int(reclaimed),
        "hbm_peak_drop_frac": round(
            reclaimed / dp["hbm_peak_bytes"], 3)
        if dp["hbm_peak_bytes"] else None,
        "state_bytes_saved_per_chip": (
            dp["state_bytes_per_chip"] - zero["state_bytes_per_chip"]),
        "grown_batch": grown,
        "grown_batch_fits_dp_hbm_budget": bool(fits),
        "sps_grown_vs_dp": round(
            zero_grown["samples_per_sec"]
            / max(dp["samples_per_sec"], 1e-9), 3),
        "final_loss_delta_equal_batch": round(
            abs(zero["final_loss"] - dp["final_loss"]), 5),
        "zero_bytes_on_wire": _zero_bytes_on_wire(n_params, n_dev),
        "note": ("ISSUE-11 row: optimizer bytes MOVE (sharded "
                 "residency, exact placed-array accounting above) and "
                 "the hbm numbers are XLA memory-analysis bytes of "
                 "the compiled steps; trajectory agreement is gated "
                 "by test_loss_trajectory's DP-vs-ZeRO-2 band leg; "
                 "the CPU wall ratio prices compute, not HBM — "
                 "on-chip the grown batch converts the reclaimed "
                 "capacity per the roofline"),
    })


# ----------------------------------------------------------------- llama 1B

def _llama_1b_cfg(variant):
    """1.03B-param Llama recipe (d=128 heads — full MXU lanes):
    hidden 2048 × 20 layers, GQA 16q/4kv, SwiGLU ffn 5632, RoPE,
    RMSNorm, untied head, no linear biases.

    Variants isolate the recipe's two levers (round-4 verdict item 1):
    ``mha``  — kv heads = q heads (16), everything else equal: what
               GQA buys (in training: qkv-proj params/flops + kv
               bandwidth; the cache win shows in the decode bench).
    ``gelu`` — ungated GELU MLP at ffn 8448 = iso-PARAM with the
               gated 3-matrix SwiGLU (2·2048·8448 = 3·2048·5632):
               what the SwiGLU structure costs at equal capacity.
    """
    import jax.numpy as jnp

    from apex_tpu.models import LlamaConfig

    kw = dict(
        # full 20 layers by default; override for smoke tests
        num_layers=int(os.environ.get("BENCH_LLAMA_LAYERS", "20")),
        max_seq_len=int(os.environ.get("BENCH_SEQ", "1024")),
        dtype=jnp.bfloat16, remat=True, scan_layers=False)
    if variant == "mha":
        kw["num_kv_heads"] = 16
    elif variant == "gelu":
        kw.update(gated_mlp=False, activation="gelu",
                  ffn_hidden_size=8448)
    return LlamaConfig.llama_1b(**kw)


def _llama_1b_single():
    import jax
    import jax.numpy as jnp

    from apex_tpu import amp
    from apex_tpu.models import LlamaModel, gpt_loss_fn
    from apex_tpu.optim import fused_adam

    var = os.environ["BENCH_LLAMA_VARIANT"]
    cfg = _llama_1b_cfg(var)
    model = LlamaModel(cfg)
    # b=8 OOMs this chip with the probe set live (1.03B O2 state +
    # fwd/bwd probe residents); b=4 fits with margin
    b = int(os.environ.get("BENCH_BATCH", "4"))
    s = cfg.max_seq_len

    ids = jax.random.randint(
        jax.random.PRNGKey(0), (b, s + 1), 0, cfg.vocab_size, jnp.int32)
    inputs, labels = ids[:, :-1], ids[:, 1:]
    params = model.init(jax.random.PRNGKey(0), inputs[:2])
    n_params = sum(x.size for x in jax.tree.leaves(params))
    state = amp.initialize(
        model.apply, params,
        fused_adam(1e-4, moment_dtype=jnp.bfloat16),
        opt_level="O2", half_dtype=jnp.bfloat16)

    def loss_of(state, p, inputs, labels):
        cp = state.policy.cast_to_compute(p)
        logits = state.apply_fn(cp, inputs)
        # bf16 logits straight into the fused CE (upcasts per-element)
        loss = gpt_loss_fn(logits, labels)
        return state.scale_loss(loss), loss

    # BENCH_ACCUM > 1: gradient accumulation over microbatches of
    # b/accum (set BENCH_BATCH to the GLOBAL batch — e.g. the measured
    # negative in BASELINE.md is BENCH_BATCH=8 BENCH_ACCUM=2) — the
    # amortization lever the round-5 overlap experiment points at
    # (optimizer/master streaming can't overlap more, but it CAN run
    # once per accum fwd+bwds; the single-shot b is HBM-capped at 4)
    accum = int(os.environ.get("BENCH_ACCUM", "1"))
    if b % accum:
        raise ValueError(
            f"BENCH_BATCH ({b}) must be divisible by BENCH_ACCUM "
            f"({accum})")
    if accum > 1:
        @functools.partial(jax.jit, donate_argnums=(0,))
        def step(state, inputs, labels):
            mbs = jax.tree.map(
                lambda x: x.reshape(accum, x.shape[0] // accum,
                                    *x.shape[1:]), (inputs, labels))

            def body(acc, mb):
                g, l = jax.grad(
                    lambda p: loss_of(state, p, *mb),
                    has_aux=True)(state.params)
                acc_g, acc_l = acc
                return (jax.tree.map(
                    lambda a, gg: a + gg.astype(a.dtype), acc_g, g),
                    acc_l + l), None

            # bf16 accumulator: the fp32 one costs an extra 2 GB that
            # OOMs this chip; grads feed bf16 moments downstream anyway
            zero = (jax.tree.map(
                lambda p: jnp.zeros(p.shape, jnp.bfloat16),
                state.params), jnp.zeros((), jnp.float32))
            (gsum, lsum), _ = jax.lax.scan(body, zero, mbs)
            grads = jax.tree.map(lambda g: g / accum, gsum)
            new_state, finite = state.apply_gradients(grads=grads)
            return new_state, lsum / accum, finite
    else:
        @functools.partial(jax.jit, donate_argnums=(0,))
        def step(state, inputs, labels):
            grads, loss = jax.grad(
                lambda p: loss_of(state, p, inputs, labels),
                has_aux=True)(state.params)
            new_state, finite = state.apply_gradients(grads=grads)
            return new_state, loss, finite

    @jax.jit
    def fwd_only(state, inputs, labels):
        return loss_of(state, state.params, inputs, labels)[1]

    @jax.jit
    def fwd_bwd(state, inputs, labels):
        grads, loss = jax.grad(
            lambda p: loss_of(state, p, inputs, labels),
            has_aux=True)(state.params)
        return bench._probe_reduce(grads, loss)

    n_steps = int(os.environ.get("BENCH_STEPS", "20"))
    k_windows = max(1, int(os.environ.get("BENCH_WINDOWS", "3")))
    n_probe = max(n_steps // 2, 5)
    extra = {"batch": b, "seq": s, "variant": var, "accum": accum,
             "num_params": int(n_params)}
    if accum == 1:
        # probes run the whole global batch in one fwd/bwd — only
        # meaningful (and HBM-feasible) without accumulation
        t_fwd = bench._measure_fn(fwd_only, state, (inputs, labels),
                                  n_probe, k_windows)
        t_fb = bench._measure_fn(fwd_bwd, state, (inputs, labels),
                                 n_probe, k_windows)
        extra["fwd_ms"] = round(t_fwd * 1e3, 2)
        extra["bwd_ms"] = round(max(t_fb - t_fwd, 0.0) * 1e3, 2)
    out = _measure(state, step, (inputs, labels), b, extra)
    if accum == 1:
        out["opt_ms"] = round(
            max(out["step_ms"] / 1e3 - t_fb, 0.0) * 1e3, 2)
    out["tokens_per_sec"] = round(out["value"] * s, 1)
    out["metric"] = f"llama_1b_{var}_O2_fusedadam_samples_per_sec_per_chip"
    _emit(out)


def bench_llama_1b():
    """The Llama recipe on the scoreboard (round-4 verdict item 1):
    1.03B GQA+SwiGLU+RMSNorm+RoPE, O2+FusedAdam, measured on-chip with
    fwd/bwd/opt split and roofline self-check, plus the two A/B rows
    (GQA vs MHA; SwiGLU vs iso-param GELU).  One fresh process per
    variant (HBM not reclaimed promptly across builds)."""
    if os.environ.get("BENCH_LLAMA_VARIANT"):
        _llama_1b_single()
        return
    rows = {}
    for var in ("gqa", "mha", "gelu"):
        rows[var] = _run_child(
            "llama_1b", {"BENCH_LLAMA_VARIANT": var}, timeout=2400)
    main = dict(rows.get("gqa") or {})
    ab = {}
    if rows.get("mha", {}).get("value") and main.get("value"):
        ab["gqa_vs_mha_speedup"] = round(
            main["value"] / rows["mha"]["value"], 3)
    if rows.get("gelu", {}).get("value") and main.get("value"):
        ab["swiglu_vs_gelu_iso_param_speedup"] = round(
            main["value"] / rows["gelu"]["value"], 3)
    _emit({
        "metric": "llama_1b_pretrain_O2_fusedadam_samples_per_sec_per_chip",
        "value": main.get("value"),
        "unit": "samples/sec/chip",
        "rows": rows,
        "ab": ab,
    })


# ----------------------------------------------------------------- long ctx

def bench_long_context():
    """Long-context leg (beyond-reference: the reference's fmha caps at
    seqlen 512 buckets and apex has no context parallelism): full
    O2+FusedAdam train steps MEASURED at 8k, 16k and 32k tokens through
    the O(S) flash kernel — 16k/32k are past the point where the O(S²)
    composition stops compiling on this chip (the 8k row also records
    XLA's 32k attention temp-memory comparison as the capability
    proof).  Each sequence length runs in a fresh process (HBM is not
    reclaimed promptly across builds)."""
    if not os.environ.get("BENCH_LC_SINGLE"):
        # orchestrate: one fresh process per sequence length; do NOT
        # touch jax here — the child must be the only process holding
        # the chip
        rows = {}
        # the (32768, 4096) row is Mistral-style sliding-window: the
        # banded kernel grid pays only window/seq of full attention
        for s, w, m in ((8192, 0, "gpt"), (16384, 0, "gpt"),
                        (32768, 0, "gpt"), (32768, 4096, "gpt"),
                        # full-composition row (round-4 verdict weak
                        # #5): GQA×SWA×RoPE×RMSNorm×SwiGLU in ONE
                        # full train step at 32k
                        (32768, 4096, "llama")):
            key = (f"{s}w{w}" if w else str(s)) + (
                "_llama" if m == "llama" else "")
            rows[key] = _run_child(
                "long_context",
                {"BENCH_LC_SINGLE": "1", "BENCH_SEQ": str(s),
                 "BENCH_WINDOW": str(w), "BENCH_LC_MODEL": m},
                timeout=1500)
        out8 = dict(rows.get("8192") or {})
        out8.pop("metric", None)
        _emit({
            "metric": "gpt_long_context_O2_tokens_per_sec_per_chip",
            "value": out8.get("tokens_per_sec"),
            "unit": "tokens/sec/chip",
            "rows": rows,
        })
        return
    _long_context_single()


def _long_context_single():
    import jax
    import jax.numpy as jnp

    from apex_tpu import amp
    from apex_tpu.models import GPTConfig, GPTModel, gpt_loss_fn
    from apex_tpu.optim import fused_adam
    from apex_tpu.ops.attention import fused_attention, attention_reference

    b = int(os.environ.get("BENCH_BATCH", "1"))
    s = int(os.environ.get("BENCH_SEQ", "8192"))
    w = int(os.environ.get("BENCH_WINDOW", "0")) or None
    lc_model = os.environ.get("BENCH_LC_MODEL", "gpt")
    # shared bench settings; qkv_grouped off: no TP on a single chip
    # to profit from the grouped layout, and its strided-slice temps
    # (2x-padded at d=64) cost real HBM at 16k-32k tokens
    common = dict(max_seq_len=s, sliding_window=w, dtype=jnp.bfloat16,
                  remat=True, scan_layers=False, qkv_grouped=False)
    if lc_model == "llama":
        # the full-composition row: GQA (16q/4kv) × sliding window ×
        # RoPE × RMSNorm × SwiGLU at d=128, one real train step at
        # 32k — the llama_1b recipe geometry at 6 layers (12 OOMs:
        # the 32000-vocab CE at 32k tokens costs ~6 GB by itself;
        # composition, not depth, is what this row certifies)
        from apex_tpu.models import LlamaConfig

        cfg = LlamaConfig.llama_1b(num_layers=6, **common)
    else:
        cfg = GPTConfig(
            vocab_size=32768, hidden_size=1024, num_layers=12,
            num_heads=16, **common)
    model = GPTModel(cfg)
    ids = jax.random.randint(
        jax.random.PRNGKey(0), (b, s + 1), 0, cfg.vocab_size, jnp.int32)
    inputs, labels = ids[:, :-1], ids[:, 1:]
    params = model.init(jax.random.PRNGKey(0), inputs[:1])
    state = amp.initialize(
        model.apply, params, fused_adam(1e-4, moment_dtype=jnp.bfloat16),
        opt_level="O2", half_dtype=jnp.bfloat16)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(state, inputs, labels):
        def loss_fn(p):
            cp = state.policy.cast_to_compute(p)
            logits = state.apply_fn(cp, inputs)
            # bf16 logits straight into the fused CE (it upcasts
            # per-element internally): materializing f32 logits first
            # costs an extra 2·b·s·V·2-byte pass and doubles the
            # xentropy residual at 32k vocab
            loss = gpt_loss_fn(logits, labels)
            return state.scale_loss(loss), loss

        grads, loss = jax.grad(loss_fn, has_aux=True)(state.params)
        new_state, finite = state.apply_gradients(grads=grads)
        return new_state, loss, finite

    # Uniform phase-sum bound for the whole ladder (round-4 verdict
    # weak #2 — and a round-5 correction: XLA's cost model reports
    # flops=None for Pallas custom calls, so the round-4 "kernel-own
    # bound" 16k/32k rows were accidentally scoring the bound on the
    # NON-attention remainder only).  The flash kernels' work is
    # accounted analytically — tools/attn_bench.py's useful-flop
    # units: one tile-matmul = 2·b·h·visible_pairs·d; per step the
    # kernels run 9 units (fwd 2 + dq 3 + dkv 4) — at the family's
    # MEASURED achievable rate (93 TFLOP/s full-causal, 70 windowed;
    # the d=64 contraction padding caps it below chip peak).  NOT 11:
    # although remat=True nominally re-runs the forward in the
    # backward, the layers remat with prevent_cse=False and the
    # measured step times REFUTE an executed re-run — counting 11
    # units puts the 16k/32k bounds at 1.00-1.06 of the measured
    # clock, i.e. attention alone would need longer than the whole
    # step minus its XLA work; the only consistent reading is that
    # XLA CSEs the recomputed fwd kernel against the original.
    ww = min(w or s, s)
    pairs = (ww - 1) * ww / 2 + (s - ww + 1) * ww
    unit = 2 * b * cfg.num_heads * pairs * cfg.head_dim
    attn_flops = 9 * unit * cfg.num_layers
    if cfg.head_dim == 128:
        # d=128 GQA rates measured at this exact geometry
        # (tools/attn_bench.py h=16 hk=4 d=128: windowed 162.4,
        # full-causal (h32/kv8) 152.5 fwd+bwd useful TFLOP/s)
        attn_rate = (162.0 if w else 152.0) * 1e12
    else:
        attn_rate = (70.0 if w else 93.0) * 1e12
    # kernel I/O visible to XLA (deducted from its bytes-accessed so
    # the phase-sum bound never counts this traffic twice), per layer
    # per step, GQA-aware: q-head-sized bf16 passes — q reads ×3
    # calls, o write, do reads ×2, dq write = 7; kv-head-sized — k,v
    # reads ×3 calls = 6; dk/dv — direct bf16 kv-head writes under
    # MHA, but with rep>1 the dkv kernel writes PER-Q-HEAD fp32
    # partials that XLA then group-sums (write+read f32 ×2 tensors)
    # before the kv-head-sized bf16 result
    io_h = b * s * cfg.num_heads * cfg.head_dim * 2
    io_hk = b * s * cfg.kv_heads * cfg.head_dim * 2
    io_h_f32 = 2 * io_h
    dkv_io = (2 * io_hk if cfg.kv_heads == cfg.num_heads
              else 2 * 2 * io_h_f32 + 2 * io_hk)
    lse_io = b * s * cfg.num_heads * 4
    attn_xla_bytes = cfg.num_layers * (
        7 * io_h + 6 * io_hk + dkv_io + 5 * lse_io)
    out = _measure(
        state, step, (inputs, labels), b,
        {"batch": b, "seq": s, "window": w},
        phase_bounds=[{"name": "flash_attention_fwd_bwd",
                       "seconds": attn_flops / attn_rate,
                       "flops": attn_flops,
                       "xla_bytes": attn_xla_bytes}])
    out["tokens_per_sec"] = round(out["value"] * s, 1)

    if s == 8192:
        # 32k capability proof: compile one attention fwd+bwd both ways
        # and compare XLA's per-device temp memory (no execution)
        s32, h, d = 32768, 8, 64
        q = jax.ShapeDtypeStruct((1, s32, h, d), jnp.bfloat16)

        def attn_loss(impl):
            def f(qq, kk, vv):
                o = (fused_attention(qq, kk, vv, causal=True,
                                     implementation="pallas")
                     if impl == "pallas" else
                     attention_reference(qq, kk, vv, causal=True))
                return jnp.sum(o.astype(jnp.float32) ** 2)
            return jax.jit(jax.grad(f, argnums=(0, 1, 2)))

        mems = {}
        for impl in ("pallas", "xla"):
            try:
                stats = attn_loss(impl).lower(q, q, q).compile(
                ).memory_analysis()
                mems[impl] = int(stats.temp_size_in_bytes)
            except Exception as e:                 # composition may not
                mems[impl] = f"uncompilable: {type(e).__name__}"  # fit
        out["attn_32k_temp_bytes"] = mems
    tag = (f"{s//1024}k" + (f"_swa{w//1024}k" if w else "")
           + ("_llama_gqa" if lc_model == "llama" else ""))
    out["metric"] = f"gpt_long_context_{tag}_O2_samples_per_sec_per_chip"
    _emit(out)


# ---------------------------------------------------------------- serving

# (lifted to apex_tpu/plan/costs.py — imported back above as _serving_traffic_model)


def bench_serving_decode():
    """Continuous-batching engine scoreboard (ISSUE 2): steady-state
    tokens/sec of ``apex_tpu.serving`` at FIXED slot occupancy on the
    llama_1b GQA recipe, against the single-stream ``generate()``
    baseline.  Decode is HBM-bound — every step streams all params
    regardless of batch — so ``slots`` co-resident tenants amortize the
    same param read ``slots`` ways; the ratio row quantifies how much
    of that consolidation the slotted engine (vmapped b=1 decode +
    per-slot cursors) actually delivers vs. the lockstep batch loop.

    Env: BENCH_SERVE_SLOTS (8), BENCH_SERVE_PROMPT (128),
    BENCH_DECODE_MAXLEN (2048), BENCH_SERVE_TOKENS (64),
    BENCH_LLAMA_LAYERS (20 — shrink for CPU smoke)."""
    import dataclasses
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from apex_tpu.models import LlamaModel, generate
    from apex_tpu.serving import Engine

    slots = int(os.environ.get("BENCH_SERVE_SLOTS", "8"))
    S = int(os.environ.get("BENCH_DECODE_MAXLEN", "2048"))
    P = int(os.environ.get("BENCH_SERVE_PROMPT", "128"))
    N = int(os.environ.get("BENCH_SERVE_TOKENS", "64"))
    k_windows = max(1, int(os.environ.get("BENCH_WINDOWS", "3")))
    cfg = dataclasses.replace(_llama_1b_cfg("gqa"), max_seq_len=S)
    model = LlamaModel(cfg)

    rng = np.random.default_rng(0)
    prompts = rng.integers(0, cfg.vocab_size,
                           size=(slots, P)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.asarray(prompts[:1, :8]))
    # inference: bf16 params (the O2 compute copy; no masters needed)
    params = {"params": jax.tree.map(
        lambda x: x.astype(jnp.bfloat16)
        if jnp.issubdtype(x.dtype, jnp.floating) else x,
        params["params"])}
    n_params = sum(x.size for x in jax.tree.leaves(params))

    # steps the measurement needs per tenant: 1 warm + the windows —
    # budgets and cache room must outlast them so occupancy stays
    # pinned at 1.0 (no mid-window eviction/refill)
    total_steps = 1 + k_windows * N
    room = S - P - 1
    if total_steps > room:
        N = max(1, (room - 1) // k_windows)
        total_steps = 1 + k_windows * N
    engine = Engine(model, params, max_slots=slots,
                    prompt_buckets=(P,))
    engine.warmup()
    for slot in range(slots):
        engine.admit(slot, prompts[slot],
                     max_new_tokens=total_steps + 1)
    engine.step()                              # warm the full pool
    ovh = bench._call_overhead()

    def serve_window():
        t0 = time.perf_counter()
        for _ in range(N):
            engine.step()          # step() syncs (host token routing)
        return (time.perf_counter() - t0 - ovh) / N

    t_step, step_w = bench._time_windows(serve_window, k_windows)
    for slot in range(slots):
        engine.release(slot)
    serving_tps = slots / t_step

    # single-stream baseline: generate() at b=1, same prompt length
    ids1 = jnp.asarray(prompts[:1])
    out = generate(model, params, ids1, max_new_tokens=N)   # compile
    bench._sync(out)

    def gen_window():
        t0 = time.perf_counter()
        out = generate(model, params, ids1, max_new_tokens=N)
        bench._sync(out)
        return (time.perf_counter() - t0 - ovh) / N

    t_gen, gen_w = bench._time_windows(gen_window, k_windows)
    single_tps = 1.0 / t_gen

    _emit({
        "metric": f"serving_decode_s{slots}_S{S}_tokens_per_sec",
        "value": round(serving_tps, 1),
        "unit": "tokens/sec/chip",
        "slots": slots, "max_seq_len": S, "prompt": P,
        "tokens_per_window": N,
        "occupancy": 1.0,
        "num_params": int(n_params),
        "step_ms": round(t_step * 1e3, 3),
        "step_window_ms": [round(d * 1e3, 2) for d in step_w],
        "single_stream_generate_tokens_per_sec": round(single_tps, 1),
        "single_stream_ms_per_token": round(t_gen * 1e3, 3),
        "single_stream_window_ms": [round(d * 1e3, 2) for d in gen_w],
        "consolidation_speedup": round(serving_tps / single_tps, 2),
        "trace_counts": engine.trace_counts,
        "note": ("serving step() includes the per-step host sync "
                 "(token routing); generate() loops on-device — the "
                 "speedup is net of that overhead"),
    })

    # -------- paged A/B + occupancy sweep (ISSUE 5 acceptance) --------
    # equal HBM budget = the dense slab just measured (slots × S
    # tokens of K/V per layer).  The A/B row (mult=1) answers "same
    # slot count, paged layout: how much does the per-step gather
    # cost?" (target: tokens/s per slot within 10% of dense); the
    # sweep rows hold 2× and 4× the slot count in the SAME budget —
    # possible only because live tokens/slot ≈ prompt + generated
    # « max_seq_len, exactly the overcommit the dense slab forbids.
    from apex_tpu.serving import PagedEngine

    del engine                      # free the dense slab first
    pool_tokens = slots * S
    block = int(os.environ.get("BENCH_PAGED_BLOCK", "16"))
    # +2 decode headroom beyond the measurement, capped so
    # prompt + budget never exceeds max_seq_len when the room cap
    # already pinned total_steps at its edge
    paged_budget = min(total_steps + 2, S - P)
    live = P + paged_budget
    kv_bytes = 2 if cfg.dtype == jnp.bfloat16 else 4
    paged_base_tps = None
    live_pages = -(-live // block)
    total_pages = -(-pool_tokens // block)
    for mult in (1, 2, 4):
        pslots = slots * mult
        if pslots * live_pages > total_pages:
            # capacity counted in PAGES (per-slot ceil rounding —
            # token arithmetic under-counts near the edge and would
            # let mid-window preemption silently shrink the
            # measurement): record the bound instead
            _emit({
                "metric": (f"serving_decode_paged_x{mult}_"
                           f"s{pslots}_S{S}_tokens_per_sec"),
                "value": None,
                "skipped": (f"{pslots} slots × {live_pages} live "
                            f"pages exceed the {total_pages}-page "
                            f"pool"),
            })
            continue
        pengine = PagedEngine(model, params, max_slots=pslots,
                              block_size=block,
                              pool_tokens=pool_tokens,
                              prefill_chunk=min(P, 128))
        pengine.warmup()
        pprompts = rng.integers(0, cfg.vocab_size,
                                size=(pslots, P)).astype(np.int32)
        for slot in range(pslots):
            pengine.admit(slot, pprompts[slot],
                          max_new_tokens=paged_budget)
        # chunked prefill to completion, then one warm decode step
        while any(t is not None and t.fed < P
                  for t in pengine._tenants):
            pengine.step()
        pengine.step()
        occupancy_blocks = pengine.blocks_in_use / pengine.blocks_total

        def paged_window():
            t0 = time.perf_counter()
            for _ in range(N):
                pengine.step()
            return (time.perf_counter() - t0 - ovh) / N

        t_paged, paged_w = bench._time_windows(paged_window, k_windows)
        paged_tps = pslots / t_paged
        per_slot = paged_tps / pslots
        if mult == 1:
            paged_base_tps = paged_tps
        tm = _serving_traffic_model(
            num_layers=cfg.num_layers, kv_heads=cfg.kv_heads,
            head_dim=cfg.head_dim, max_seq_len=S, live_tokens=live,
            slots=pslots, block_size=pengine.block_size,
            dtype_bytes=kv_bytes)
        row = {
            "metric": (f"serving_decode_paged_x{mult}_s{pslots}_S{S}"
                       f"_tokens_per_sec"),
            "value": round(paged_tps, 1),
            "unit": "tokens/sec/chip",
            "slots": pslots, "max_seq_len": S, "prompt": P,
            "block_size": pengine.block_size,
            "pool_tokens": pool_tokens,
            "hbm_budget": f"= dense slab at {slots} slots",
            "occupancy_blocks": round(occupancy_blocks, 3),
            "step_ms": round(t_paged * 1e3, 3),
            "step_window_ms": [round(d * 1e3, 2) for d in paged_w],
            "tokens_per_sec_per_slot": round(per_slot, 2),
            "dense_tokens_per_sec_per_slot":
                round(serving_tps / slots, 2),
            "per_slot_vs_dense":
                round(per_slot / (serving_tps / slots), 3),
            "analytic_kv_traffic": tm,
            "trace_counts": pengine.trace_counts,
        }
        if mult > 1 and paged_base_tps is not None:
            row["tps_vs_paged_x1"] = round(
                paged_tps / paged_base_tps, 2)
        for slot in range(pslots):
            pengine.release(slot)
        _emit(row)
        del pengine


def bench_prefix_spec_serving():
    """Prefix-sharing + speculative-decoding scoreboard (ISSUE 7).

    Two rows on the paged datapath, tiny-GPT proxy (CPU smoke — the
    protocol and the RATIOS are the artifact, like ``fleet_serving``):

    - **shared-system-prompt A/B at EQUAL HBM**: every request carries
      the same system prompt + a small unique tail; the same pool is
      served with ``share_prefixes`` off vs on.  Off, each tenant
      charges the pool its full prompt, the token-budget gate admits
      only a couple at a time, and the rest queue; on, the prefix's
      pages are mapped refcounted so the SAME pool admits the whole
      wave — reclaimed capacity converts into admitted occupancy and
      therefore tokens/s (reported with TTFT p50/p99, which also
      collapses: shared admissions skip the prefix prefill compute).
      ``pool capacity in tokens`` is reported shared vs unshared from
      the analytic traffic model + the measured ``blocks_saved`` peak.
    - **speculative decoding on a prompt-lookup-friendly workload**:
      repetitive prompts, drafted with the n-gram prompt-lookup
      drafter at K = ``BENCH_PSS_SPEC_K``.  The honest accelerator
      metric is **decode tokens per STEP** (= 1 + accepted drafts per
      verify step): a TPU decode step is HBM-bound on the param/KV
      stream, so at K ≪ seq the verify step costs ≈ one decode step
      and tokens/s scales with tokens/step; the CPU proxy's wall
      tokens/s is also reported but is compute-bound (verify width
      costs linearly) and NOT the acceptance number.

    Env: BENCH_PSS_SYS (192), BENCH_PSS_USER (12), BENCH_PSS_TOKENS
    (32), BENCH_PSS_SLOTS (6), BENCH_PSS_SPEC_K (4),
    BENCH_PSS_BLOCK (16)."""
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from apex_tpu.models import GPTConfig, GPTModel
    from apex_tpu.serving import (
        InferenceServer,
        PagedEngine,
        Request,
        Scheduler,
    )

    SYS = int(os.environ.get("BENCH_PSS_SYS", "192"))
    U = int(os.environ.get("BENCH_PSS_USER", "12"))
    N = int(os.environ.get("BENCH_PSS_TOKENS", "32"))
    slots = int(os.environ.get("BENCH_PSS_SLOTS", "6"))
    K = int(os.environ.get("BENCH_PSS_SPEC_K", "4"))
    block = int(os.environ.get("BENCH_PSS_BLOCK", "16"))

    cfg = GPTConfig.tiny(position_embedding="learned",
                         scan_layers=True)
    if SYS + U + N + 2 > cfg.max_seq_len:
        raise ValueError("BENCH_PSS_SYS+USER+TOKENS exceeds the "
                         f"proxy's max_seq_len ({cfg.max_seq_len})")
    model = GPTModel(cfg)
    params = {"params": model.init(
        jax.random.PRNGKey(0),
        jnp.zeros((1, 4), jnp.int32))["params"]}
    rng = np.random.default_rng(0)
    sys_prompt = rng.integers(0, cfg.vocab_size,
                              size=(SYS,)).astype(np.int32)
    prompts = [np.concatenate([sys_prompt, rng.integers(
        0, cfg.vocab_size, size=(U,)).astype(np.int32)])
        for _ in range(slots)]

    # -------- A: shared-system-prompt wave at EQUAL HBM --------------
    # the pool holds ONE copy of the system prefix + every tenant's
    # private tail (+decode headroom) — unshared, the same pool fits
    # only ~pool/(SYS+U+N) tenants and the rest queue behind the
    # token-budget admission gate
    pool_tokens = SYS + slots * (U + N + 2 * block) + 2 * block

    def run_wave(share):
        server = InferenceServer(
            model, params, max_slots=slots, kv_cache="paged",
            block_size=block, pool_tokens=pool_tokens,
            prefill_chunk=32, share_prefixes=share)
        peak_saved = 0
        with server:
            t0 = time.perf_counter()
            handles = [server.submit(p, max_new_tokens=N, seed=i)
                       for i, p in enumerate(prompts)]
            while not all(h.done for h in handles):
                peak_saved = max(peak_saved,
                                 server.engine.blocks_saved)
                time.sleep(0.005)
            tokens = sum(len(h.result(timeout=600)) for h in handles)
            wall = time.perf_counter() - t0
            lat = server.latency_summary()
            assert server.engine.blocks_in_use == 0
        return {
            "share_prefixes": share,
            "tokens_per_sec": round(tokens / wall, 1),
            "wall_s": round(wall, 3),
            "ttft_p50_ms": round(lat.get("ttft_p50_s", 0.0) * 1e3, 1),
            "ttft_p99_ms": round(lat.get("ttft_p99_s", 0.0) * 1e3, 1),
            "peak_blocks_saved": int(peak_saved),
            "cow_forks": int(server.engine.cow_forks),
        }

    unshared = run_wave(False)
    shared = run_wave(True)
    tm = _serving_traffic_model(
        num_layers=cfg.num_layers, kv_heads=cfg.kv_heads,
        head_dim=cfg.head_dim, max_seq_len=cfg.max_seq_len,
        live_tokens=SYS + U + N, slots=slots, block_size=block,
        dtype_bytes=4, shared_prefix_tokens=SYS)
    _emit({
        "metric": "prefix_spec_serving_shared_tokens_per_sec",
        "value": shared["tokens_per_sec"],
        "unit": "tokens/sec (CPU-proxy smoke)",
        "system_prompt": SYS, "user_tail": U, "budget": N,
        "slots": slots, "block_size": block,
        "pool_tokens": pool_tokens,
        "hbm_budget": "equal pool both rows",
        "rows": {"unshared": unshared, "shared": shared},
        "tps_vs_unshared": round(
            shared["tokens_per_sec"]
            / max(unshared["tokens_per_sec"], 1e-9), 2),
        "pool_capacity_tokens_unshared":
            tm["paged_live_pool_tokens_unshared"],
        "pool_capacity_tokens_shared":
            tm["paged_live_pool_tokens_shared"],
        "analytic_kv_traffic": tm,
        "note": ("equal-HBM A/B: sharing admits the whole wave where "
                 "the unshared pool serializes it behind the token "
                 "gate — tokens/s tracks admitted occupancy; TTFT "
                 "also collapses because shared admissions skip the "
                 "prefix prefill"),
    })

    # -------- B: speculative decoding, lookup-friendly workload ------
    # prompt lookup pays when generation CONTINUES spans of the
    # context (summarization, code edits, few-shot) — an ability a
    # RANDOM init does not have.  Briefly train the proxy on cyclic
    # sequences so it (like any real LM) continues repetitions, then
    # serve prompts of 1.5 periods: the drafter finds the continuation
    # one period back and the trained model actually emits it.
    from apex_tpu.models import gpt_loss_fn

    train_steps = int(os.environ.get("BENCH_PSS_TRAIN_STEPS", "200"))
    period = 24
    cyc = rng.permutation(min(cfg.vocab_size, 256))[:period] \
        .astype(np.int32)
    tparams = model.init(jax.random.PRNGKey(0),
                         jnp.zeros((1, 4), jnp.int32))["params"]

    def cyc_batch(bs, L):
        phases = rng.integers(0, period, size=bs)
        idx = (phases[:, None] + np.arange(L + 1)) % period
        return jnp.asarray(cyc[idx])

    @jax.jit
    def sgd_step(p, ids, lr):
        def loss_fn(p):
            logits = model.apply({"params": p}, ids[:, :-1],
                                 deterministic=True)
            return gpt_loss_fn(logits, ids[:, 1:])
        loss, grads = jax.value_and_grad(loss_fn)(p)
        return jax.tree.map(lambda a, g: a - lr * g, p, grads), loss

    loss = None
    for i in range(train_steps):
        tparams, loss = sgd_step(
            tparams, cyc_batch(8, 48),
            jnp.float32(0.5 if i < train_steps // 2 else 0.2))
    trained = {"params": tparams}
    spec_prompts = [np.asarray(
        cyc[(ph + np.arange(period + period // 2)) % period],
        np.int32) for ph in range(slots)]

    def run_spec(k):
        engine = PagedEngine(model, trained, max_slots=slots,
                             block_size=block, prefill_chunk=32,
                             spec_tokens=k, spec_ngram=2)
        engine.warmup()
        sched = Scheduler(engine)
        reqs = [sched.submit(Request(prompt=p, max_new_tokens=N,
                                     seed=i))
                for i, p in enumerate(spec_prompts)]
        while any(t is not None and t.fed < t.prompt.size
                  for t in engine._tenants):
            sched.run_step()          # prefill outside the window
        t0 = time.perf_counter()
        steps, row_steps, tokens = 0, 0, 0
        while sched.has_work():
            events = sched.run_step()
            steps += 1
            # one row-step per DISTINCT emitting row: an undrafted
            # run scores exactly 1.0 token per row-step, a drafted
            # one 1 + accepted-per-verify — batch-size-independent
            row_steps += len({id(ev.request) for ev in events})
            tokens += len(events)
        wall = time.perf_counter() - t0
        assert tokens == sum(len(r.tokens) for r in reqs)
        assert engine.blocks_in_use == 0
        return {
            "spec_tokens": k,
            "decode_tokens_per_sec": round(tokens / wall, 1),
            "decode_steps": steps,
            "tokens_per_row_step": round(tokens / max(row_steps, 1),
                                         3),
            "accept_rate": round(engine.spec_accept_rate, 3),
            "proposed": int(engine.spec_proposed),
            "accepted": int(engine.spec_accepted),
        }

    base = run_spec(0)
    spec = run_spec(K)
    _emit({
        "metric": f"prefix_spec_serving_spec_k{K}_tokens_per_row_step",
        "value": spec["tokens_per_row_step"],
        "unit": "decode tokens/row-step (HBM-bound tokens/s proxy)",
        "slots": slots, "budget": N, "spec_ngram": 2,
        "proxy_train_steps": train_steps,
        "proxy_train_loss": round(float(loss), 4),
        "rows": {"undrafted": base, "drafted": spec},
        "tokens_per_row_step_vs_undrafted": round(
            spec["tokens_per_row_step"]
            / max(base["tokens_per_row_step"], 1e-9), 2),
        "wall_tps_vs_undrafted_cpu": round(
            spec["decode_tokens_per_sec"]
            / max(base["decode_tokens_per_sec"], 1e-9), 2),
        "note": ("tokens/row-step is the accelerator metric: a TPU "
                 "decode step is HBM-bound on the param/KV stream, so "
                 "a K-token verify costs ≈ one width-1 step and "
                 "tokens/s scales with tokens/row-step at the "
                 "measured accept rate; the CPU proxy's wall ratio is "
                 "compute-bound (verify width is linear cost there) "
                 "and reported only for honesty"),
    })


def bench_quantized_kv_serving():
    """Quantized KV pages scoreboard (ISSUE 8): equal-HBM A/B of the
    unquantized paged pool vs an ``kv_dtype="int8"`` pool holding 2×
    the slots in the SAME byte budget, tiny-GPT proxy (CPU smoke — the
    protocol and the RATIOS are the artifact, like
    ``prefix_spec_serving``).

    Protocol: a wave of ``2 × quantized slots`` independent requests
    hits both servers.  The unquantized pool fits only
    ``pool_bytes / (fp32 K+V bytes/token)`` tokens, the token-budget
    admission gate serializes the wave behind it; the int8 pool's same
    bytes hold ~3.9× the tokens (scales included — fp32 compute proxy;
    2× from bf16), so 2× the slots admit concurrently and tokens/s
    tracks admitted occupancy exactly as the ISSUE-5 occupancy sweep
    measured (2× slots → 2.25× tokens/s at equal HBM on-chip; the CPU
    wall ratio reported here is compute-bound and understates it).
    The smoke ASSERTS the capacity side — ≥1.9× pool tokens at equal
    HBM from the extended traffic model AND from the engines' actual
    pool sizes — and reports tokens/s + TTFT p50/p99 for both rows.

    Env: BENCH_QKV_SLOTS (3), BENCH_QKV_PROMPT (24), BENCH_QKV_TOKENS
    (16), BENCH_QKV_BLOCK (8)."""
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from apex_tpu.models import GPTConfig, GPTModel
    from apex_tpu.ops.paged_attention import kv_store_bytes_per_token
    from apex_tpu.serving import InferenceServer

    slots = int(os.environ.get("BENCH_QKV_SLOTS", "3"))
    P = int(os.environ.get("BENCH_QKV_PROMPT", "24"))
    N = int(os.environ.get("BENCH_QKV_TOKENS", "16"))
    block = int(os.environ.get("BENCH_QKV_BLOCK", "8"))

    cfg = GPTConfig.tiny(position_embedding="learned",
                         scan_layers=True)
    if P + N + 2 > cfg.max_seq_len:
        raise ValueError("BENCH_QKV_PROMPT+TOKENS exceeds the proxy's "
                         f"max_seq_len ({cfg.max_seq_len})")
    model = GPTModel(cfg)
    params = {"params": model.init(
        jax.random.PRNGKey(0),
        jnp.zeros((1, 4), jnp.int32))["params"]}
    rng = np.random.default_rng(0)

    # the shared byte budget: an unquantized pool that fits the base
    # slot count's working set (prompt + budget + page slack)
    per_tenant = P + N + 2 * block
    pool_base = slots * per_tenant
    # K+V bytes per token per (kv_head, layer) — the common factor
    # cancels in the ratio; the shared formula is the one
    # PagedEngine's equal-HBM default admits with
    unq_tok = kv_store_bytes_per_token(cfg.head_dim, block,
                                       dtype=cfg.dtype)
    q_tok = kv_store_bytes_per_token(cfg.head_dim, block, "int8")
    pool_quant = int(pool_base * unq_tok / q_tok)
    q_slots = 2 * slots
    wave = 2 * q_slots
    prompts = [rng.integers(0, cfg.vocab_size, size=(P,))
               .astype(np.int32) for _ in range(wave)]

    def run_wave(kv_dtype, max_slots, pool_tokens):
        server = InferenceServer(
            model, params, max_slots=max_slots, kv_cache="paged",
            block_size=block, pool_tokens=pool_tokens,
            prefill_chunk=8, kv_dtype=kv_dtype)
        with server:
            t0 = time.perf_counter()
            handles = [server.submit(p, max_new_tokens=N, seed=i)
                       for i, p in enumerate(prompts)]
            tokens = sum(len(h.result(timeout=600)) for h in handles)
            wall = time.perf_counter() - t0
            lat = server.latency_summary()
            assert server.engine.blocks_in_use == 0
            pool = server.engine.pool_tokens
        return {
            "kv_dtype": kv_dtype or "none",
            "slots": max_slots,
            "pool_tokens": pool,
            "tokens_per_sec": round(tokens / wall, 1),
            "wall_s": round(wall, 3),
            "ttft_p50_ms": round(lat.get("ttft_p50_s", 0.0) * 1e3, 1),
            "ttft_p99_ms": round(lat.get("ttft_p99_s", 0.0) * 1e3, 1),
        }

    base = run_wave(None, slots, pool_base)
    quant = run_wave("int8", q_slots, pool_quant)
    capacity_mult = quant["pool_tokens"] / base["pool_tokens"]
    assert capacity_mult >= 1.9, (
        f"equal-HBM int8 pool holds only {capacity_mult:.2f}x the "
        "tokens (acceptance: >= 1.9x, scales included)")
    tm = _serving_traffic_model(
        num_layers=cfg.num_layers, kv_heads=cfg.kv_heads,
        head_dim=cfg.head_dim, max_seq_len=cfg.max_seq_len,
        live_tokens=P + N, slots=q_slots, block_size=block,
        dtype_bytes=comp_bytes, kv_dtype="int8")
    assert tm["quantized_capacity_multiplier"] >= 1.9
    _emit({
        "metric": "quantized_kv_serving_int8_tokens_per_sec",
        "value": quant["tokens_per_sec"],
        "unit": "tokens/sec (CPU-proxy smoke)",
        "prompt": P, "budget": N, "block_size": block,
        "hbm_budget": f"= unquantized pool at {slots} slots "
                      f"({base['pool_tokens']} tokens)",
        "rows": {"unquantized": base, "int8_2x_slots": quant},
        "pool_capacity_multiplier_at_equal_hbm":
            round(capacity_mult, 2),
        "tps_vs_unquantized": round(
            quant["tokens_per_sec"]
            / max(base["tokens_per_sec"], 1e-9), 2),
        "analytic_kv_traffic": tm,
        "note": ("equal-HBM A/B: the int8 pool admits 2x the slots in "
                 "the same bytes; on-chip the occupancy-sweep protocol "
                 "(serving_decode: 2x slots -> 2.25x tokens/s) "
                 "converts that into >= 1.5x sustained tokens/s — the "
                 "CPU wall ratio here is compute-bound (dequant is "
                 "arithmetic, not bandwidth, on CPU) and reported for "
                 "honesty; the asserted artifact is the capacity side, "
                 "scales included"),
    })


# ----------------------------------------------------------------- decode

def _decode_single():
    """One (batch, max_seq_len, attn-impl) decode measurement: prefill
    tokens/s + steady-state per-token decode latency on the llama_1b
    GQA model, with a bytes/token roofline (decode is the canonical
    HBM-bound workload: every token reads all params + the KV cache)."""
    import dataclasses
    import time

    import jax
    import jax.numpy as jnp

    from apex_tpu.models import LlamaModel, init_cache

    b = int(os.environ["BENCH_DECODE_BATCH"])
    S = int(os.environ["BENCH_DECODE_MAXLEN"])
    P = int(os.environ.get("BENCH_DECODE_PROMPT", "1024"))
    N = int(os.environ.get("BENCH_DECODE_TOKENS", "64"))
    # host-side read, plumbed through config (part of the compile
    # signature) — the model no longer reads this env var at trace time
    attn = os.environ.get("APEX_TPU_DECODE_ATTN", "auto")
    cfg = dataclasses.replace(_llama_1b_cfg("gqa"), max_seq_len=S,
                              decode_attn=attn)
    model = LlamaModel(cfg)

    ids = jax.random.randint(
        jax.random.PRNGKey(0), (b, P), 0, cfg.vocab_size, jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids[:1, :8])
    # inference: bf16 params (the O2 compute copy; no masters needed)
    params = {"params": jax.tree.map(
        lambda x: x.astype(jnp.bfloat16)
        if jnp.issubdtype(x.dtype, jnp.floating) else x,
        params["params"])}
    n_params = sum(x.size for x in jax.tree.leaves(params))
    cache = init_cache(model, b)

    def apply(params, cache, ids):
        logits, upd = model.apply(
            {**params, "cache": cache}, ids, deterministic=True,
            decode=True, mutable=["cache"])
        nxt = jnp.argmax(logits[:, -1].astype(jnp.float32),
                         axis=-1).astype(jnp.int32)
        return nxt, upd["cache"]

    prefill = jax.jit(apply)

    @jax.jit
    def decode_n(params, cache, tok):
        def step(carry, _):
            cache, tok = carry
            nxt, cache = apply(params, cache, tok[:, None])
            return (cache, nxt), None

        (cache, tok), _ = jax.lax.scan(step, (cache, tok), None,
                                       length=N)
        return tok

    tok, filled = prefill(params, cache, ids)          # warm + fill
    bench._sync(tok)
    dec_c = bench._aot_compile(decode_n, params, filled, tok)
    dec = dec_c if dec_c is not None else decode_n
    bench._sync(dec(params, filled, tok))
    ovh = bench._call_overhead()
    k_windows = max(1, int(os.environ.get("BENCH_WINDOWS", "3")))

    reps = 5

    def prefill_window():
        t0 = time.perf_counter()
        for _ in range(reps):
            nxt, _f = prefill(params, cache, ids)
        bench._sync(nxt)
        return (time.perf_counter() - t0 - ovh) / reps

    t_pre, pre_w = bench._time_windows(prefill_window, k_windows)

    def decode_window():
        t0 = time.perf_counter()
        for _ in range(2):
            out = dec(params, filled, tok)
        bench._sync(out)
        return (time.perf_counter() - t0 - ovh) / 2

    t_dec, dec_w = bench._time_windows(decode_window, k_windows)
    t_tok = t_dec / N

    # bytes/token roofline: params once + KV (k and v) per layer, bf16.
    # 'full' = the whole (b, S, hk, d) cache (what the one-shot einsum
    # reads); 'live' = the filled prefix P..P+N only (what the blocked
    # skip bounds reads to).
    kvb = cfg.num_layers * b * cfg.kv_heads * cfg.head_dim * 2 * 2
    bytes_full = 2 * n_params + kvb * S
    bytes_live = 2 * n_params + kvb * (P + N // 2)
    out = {
        "batch": b, "max_seq_len": S, "prompt": P,
        "decode_attn": cfg.decode_attn,
        "num_params": int(n_params),
        "prefill_tokens_per_sec": round(b * P / t_pre, 1),
        "prefill_ms": round(t_pre * 1e3, 2),
        "prefill_window_ms": [round(d * 1e3, 2) for d in pre_w],
        "decode_tokens_per_sec": round(b / t_tok, 1),
        "decode_ms_per_token": round(t_tok * 1e3, 3),
        "decode_window_ms": [round(d * 1e3, 2) for d in dec_w],
        "bytes_per_token_model": {
            "params": 2 * n_params, "kv_full_cache": kvb * S,
            "kv_live": kvb * (P + N // 2)},
        "achieved_gbs_vs_full_read": round(
            bytes_full / t_tok / 1e9, 1),
        "achieved_gbs_vs_live_read": round(
            bytes_live / t_tok / 1e9, 1),
        "frac_of_peak_hbm_live": round(
            bytes_live / t_tok / 1e9 / bench._PEAK_HBM_GBS, 3),
    }
    if dec_c is not None:
        try:
            ca = dec_c.cost_analysis() or {}
            byts = float(ca.get("bytes accessed", 0.0))
            if byts:
                out["cost_bytes_per_token"] = round(byts / N, 1)
        except Exception:
            pass
    out["metric"] = f"llama1b_decode_b{b}_S{S}"
    _emit(out)


def bench_decode():
    """Generation scoreboard (round-4 verdict item 2a): prefill +
    steady-state decode throughput of the llama_1b recipe at
    b ∈ {1, 8, 32}, plus the full-vs-live cache-read A/B (the dense
    einsum reads all max_seq_len slots every token; the blocked form
    skips dead blocks) at 2k and 8k cache sizes."""
    if os.environ.get("BENCH_DECODE_BATCH"):
        _decode_single()
        return
    runs = [
        ("b1_S2048", {"BENCH_DECODE_BATCH": "1",
                      "BENCH_DECODE_MAXLEN": "2048"}),
        ("b8_S2048", {"BENCH_DECODE_BATCH": "8",
                      "BENCH_DECODE_MAXLEN": "2048"}),
        ("b32_S2048", {"BENCH_DECODE_BATCH": "32",
                       "BENCH_DECODE_MAXLEN": "2048"}),
        ("b8_S2048_einsum", {"BENCH_DECODE_BATCH": "8",
                             "BENCH_DECODE_MAXLEN": "2048",
                             "APEX_TPU_DECODE_ATTN": "einsum"}),
        ("b8_S8192", {"BENCH_DECODE_BATCH": "8",
                      "BENCH_DECODE_MAXLEN": "8192"}),
        ("b8_S8192_einsum", {"BENCH_DECODE_BATCH": "8",
                             "BENCH_DECODE_MAXLEN": "8192",
                             "APEX_TPU_DECODE_ATTN": "einsum"}),
    ]
    rows = {}
    for key, env_kw in runs:
        rows[key] = _run_child("decode", env_kw, timeout=1500)
    head = rows.get("b8_S2048") or {}
    _emit({
        "metric": "llama1b_decode_tokens_per_sec",
        "value": head.get("decode_tokens_per_sec"),
        "unit": "tokens/sec (b=8, S=2048)",
        "rows": rows,
    })


# ------------------------------------------------------- decode epilogue

def bench_decode_epilogue():
    """Fused decode-step epilogue A/B (ISSUE 14): the decode
    executable with the HISTORICAL sampling tail — full-vocab sort,
    softmax, cumsum, masking passes and the categorical draw as
    separate XLA ops over ``(slots, vocab)`` — against the fused
    one-pass epilogue (``ops.fused_sampling``), reporting XLA
    cost-analysis bytes and wall tokens/s.

    Bytes protocol: every arm that XLA can compile on this backend is
    MEASURED via ``Compiled.cost_analysis()`` (the
    ``test_paged_attention`` protocol).  On TPU that includes the
    fused step, whose pallas call declares its true one-pass traffic
    through ``pl.CostEstimate`` —
    ``fused_sampling.sampling_cost_bytes``, the logits read once.  On
    the CPU smoke the Mosaic kernel cannot compile, so the fused
    step's bytes are COMPOSED from measured parts: (measured unfused
    step − measured unfused tail) + the kernel's declared cost — i.e.
    exactly the rollup a TPU cost analysis performs — and the
    interpret-mode kernel's measured bytes ride alongside as a
    cross-check (they OVERSTATE the kernel: interpret materializes
    every VMEM pass as a buffer).  ``fused_bytes_source`` names which
    path produced the headline number.  The ≥10% acceptance drop on
    the decode executable is asserted here, on the CPU smoke.

    Wall rows are host wall (noisy on CPU — the kernel itself isn't
    in play off-chip; documented, not asserted), EXCEPT the
    sort-short-circuit row: the satellite fix gates the reference's
    sort + cumsum tail behind a runtime ``lax.cond`` on any row
    enabling top-k/top-p, so an ALL-GREEDY step measurably skips the
    sort even on CPU — ``greedy_shortcircuit_speedup`` is that
    measured ratio (the pre-PR tail paid the sort anyway).

    Env: BENCH_EPILOGUE_SLOTS (16), BENCH_EPILOGUE_VOCAB (16384),
    BENCH_EPILOGUE_WIDTH (4 — the spec-step ``1+K`` row),
    BENCH_EPILOGUE_LAYERS (2)."""
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from apex_tpu.models import GPTConfig, GPTModel
    from apex_tpu.models.generate import apply_decode, init_cache
    from apex_tpu.ops.fused_sampling import (
        fused_sample,
        fused_sample_reference,
        sampling_cost_bytes,
    )

    slots = int(os.environ.get("BENCH_EPILOGUE_SLOTS", "16"))
    V = int(os.environ.get("BENCH_EPILOGUE_VOCAB", "16384"))
    W = int(os.environ.get("BENCH_EPILOGUE_WIDTH", "4"))
    L = int(os.environ.get("BENCH_EPILOGUE_LAYERS", "2"))
    k_windows = max(1, int(os.environ.get("BENCH_WINDOWS", "3")))
    on_tpu = jax.default_backend() == "tpu"

    cfg = GPTConfig.tiny(vocab_size=V, num_layers=L,
                         position_embedding="learned",
                         scan_layers=True)
    model = GPTModel(cfg)
    rng = np.random.default_rng(0)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4), jnp.int32))
    variables = {"params": params["params"]}
    cache = init_cache(model, slots)
    tok = jnp.asarray(rng.integers(1, V, (slots,)), jnp.int32)
    keys = jax.vmap(jax.random.PRNGKey)(
        jnp.arange(slots, dtype=jnp.uint32))
    mixed = dict(
        temperature=jnp.asarray(
            rng.choice([0.0, 0.7, 1.0], slots), jnp.float32),
        top_k=jnp.asarray(rng.choice([0, 8, 40], slots), jnp.int32),
        top_p=jnp.asarray(rng.choice([0.0, 0.9], slots), jnp.float32))
    greedy = dict(temperature=jnp.zeros((slots,), jnp.float32),
                  top_k=jnp.zeros((slots,), jnp.int32),
                  top_p=jnp.zeros((slots,), jnp.float32))

    def legacy_tail(logits, keys, temperature, top_k, top_p):
        # the pre-fusion sample_dynamic body — the executable tail
        # every decode step used to pay, sort and all, regardless of
        # which filters the admitted rows enabled
        logits = logits.astype(jnp.float32)
        g = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        scaled = logits / jnp.maximum(temperature, 1e-6)[:, None]
        k = jnp.where(top_k > 0, top_k, V)
        ordered = jnp.sort(scaled, axis=-1)
        kth = jnp.take_along_axis(ordered, (V - k)[:, None], axis=-1)
        scaled = jnp.where(scaled < kth, -1e30, scaled)
        p_on = (top_p > 0.0) & (top_p < 1.0)
        desc = jnp.where(ordered[:, ::-1] < kth, -1e30,
                         ordered[:, ::-1])
        probs = jax.nn.softmax(desc, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        keep = cum - probs < jnp.where(p_on, top_p, 1.0)[:, None]
        thresh = jnp.min(jnp.where(keep, desc, jnp.inf), axis=-1,
                         keepdims=True)
        scaled = jnp.where(p_on[:, None] & (scaled < thresh), -1e30,
                           scaled)
        s = jax.vmap(jax.random.categorical)(keys, scaled)
        return jnp.where(temperature > 0.0, s.astype(jnp.int32), g)

    fused_impl = "pallas" if on_tpu else "pallas_interpret"

    def fused_tail(logits, keys, temperature, top_k, top_p):
        return fused_sample(logits, keys, temperature, top_k, top_p,
                            implementation=fused_impl)

    def interp_tail(logits, keys, temperature, top_k, top_p):
        # the interpret-mode cross-check row is ALWAYS interpret —
        # on TPU fused_tail compiles the Mosaic kernel, which would
        # otherwise masquerade as the interpret overstatement
        return fused_sample(logits, keys, temperature, top_k, top_p,
                            implementation="pallas_interpret")

    def ref_tail(logits, keys, temperature, top_k, top_p):
        return fused_sample_reference(logits, keys, temperature,
                                      top_k, top_p, V)

    def step_with(tail):
        def step(variables, cache, tok, keys, temperature, top_k,
                 top_p):
            logits, cache = apply_decode(model, variables, cache,
                                         tok[:, None])
            nxt = tail(logits[:, -1], keys, temperature, top_k, top_p)
            return cache, nxt
        return step

    def bytes_of(fn, *args, **kw):
        ca = jax.jit(fn).lower(*args, **kw).compile().cost_analysis()
        if isinstance(ca, list):
            ca = ca[0]
        return float((ca or {}).get("bytes accessed", 0.0))

    logits0 = jnp.asarray(rng.normal(size=(slots, V)) * 2, jnp.float32)
    t_un = bytes_of(legacy_tail, logits0, keys, **mixed)
    t_ref = bytes_of(ref_tail, logits0, keys, **mixed)
    t_model = float(sampling_cost_bytes(slots, V, jnp.float32))
    t_interp = bytes_of(interp_tail, logits0, keys, **mixed)
    s_un = bytes_of(step_with(legacy_tail), variables, cache, tok,
                    keys, **mixed)
    if on_tpu:
        s_fused = bytes_of(step_with(fused_tail), variables, cache,
                           tok, keys, **mixed)
        src = "measured"
    else:
        # the TPU rollup, composed from measured parts + the kernel's
        # declared CostEstimate (see docstring)
        s_fused = (s_un - t_un) + t_model
        src = "declared-model"
    drop = 1.0 - s_fused / s_un

    # spec-step row: W positions per row — the old executable looped W
    # sorted tails, the fused op takes the width axis in ONE call
    logits_w = jnp.asarray(rng.normal(size=(slots, W, V)),
                           jnp.float32)
    keys_w = jnp.stack([keys] * W, axis=1)

    def legacy_spec_tail(logits, keys, temperature, top_k, top_p):
        return jnp.stack(
            [legacy_tail(logits[:, j], keys[:, j], temperature,
                         top_k, top_p) for j in range(W)], axis=1)

    ts_un = bytes_of(legacy_spec_tail, logits_w, keys_w, **mixed)
    ts_model = float(sampling_cost_bytes(slots * W, V, jnp.float32))

    # wall: steady decode steps, each arm (fused arm on CPU == the
    # reference tail the engine actually dispatches to off-chip)
    ovh = bench._call_overhead()

    def wall(tail, sampling):
        fn = jax.jit(step_with(tail))
        c = jax.tree.map(jnp.copy, cache)
        c, out = fn(variables, c, tok, keys, **sampling)   # compile
        bench._sync(out)

        def window():
            nonlocal c
            t0 = time.perf_counter()
            for _ in range(8):
                c, out = fn(variables, c, tok, keys, **sampling)
            bench._sync(out)
            return (time.perf_counter() - t0 - ovh) / 8

        t, _w = bench._time_windows(window, k_windows)
        return t

    wall_tail = fused_tail if on_tpu else ref_tail
    t_leg_mix = wall(legacy_tail, mixed)
    t_new_mix = wall(wall_tail, mixed)
    t_leg_gre = wall(legacy_tail, greedy)
    t_new_gre = wall(wall_tail, greedy)

    out = {
        "metric": "decode_epilogue_bytes_drop",
        "value": round(drop, 4),
        "unit": f"fraction of decode-executable cost-analysis bytes "
                f"(slots={slots}, V={V})",
        "fused_bytes_source": src,
        "epilogue_bytes": {
            "unfused_sort_tail": t_un,
            "reference_cond_tail": t_ref,
            "fused_kernel_declared": t_model,
            "fused_kernel_interpret_measured": t_interp,
            "spec_width_unfused": ts_un,
            "spec_width_fused_declared": ts_model,
            "spec_width": W,
        },
        "step_bytes": {"unfused": s_un, "fused": s_fused},
        "wall_ms_per_step": {
            "legacy_mixed": round(t_leg_mix * 1e3, 3),
            "fused_arm_mixed": round(t_new_mix * 1e3, 3),
            "legacy_all_greedy": round(t_leg_gre * 1e3, 3),
            "fused_arm_all_greedy": round(t_new_gre * 1e3, 3),
        },
        "greedy_shortcircuit_speedup": round(t_leg_gre / t_new_gre,
                                             3),
        "tokens_per_sec_mixed": round(slots / t_new_mix, 1),
        "wall_note": ("CPU wall is noisy and the Mosaic kernel is "
                      "not in play off-chip; the short-circuit row "
                      "is the one wall claim the CPU smoke makes"),
    }
    # the acceptance bar: >= 10% cost-analysis bytes off the decode
    # executable from the fused epilogue
    assert drop >= 0.10, (
        f"fused epilogue bytes drop {drop:.3f} < 0.10 on the decode "
        f"executable (unfused {s_un}, fused {s_fused}, {src})")
    # and the tail itself must shrink however it is measured: even the
    # interpret-mode OVERSTATEMENT of the kernel must beat the sort
    # tail it replaces
    assert t_interp < t_un, (t_interp, t_un)
    _emit(out)


# ----------------------------------------------------------------- ViT-Huge

def bench_vit_huge_lamb():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from apex_tpu import amp
    from apex_tpu.models import ViTConfig, ViTModel
    from apex_tpu.optim import fused_lamb

    b = int(os.environ.get("BENCH_BATCH", "32"))
    cfg = ViTConfig.vit_huge(dtype=jnp.bfloat16, remat=True,
                             scan_layers=False)
    model = ViTModel(cfg)

    rng = np.random.default_rng(0)
    images = jnp.asarray(rng.normal(size=(b, 224, 224, 3)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, cfg.num_classes, size=(b,)))
    params = model.init(jax.random.PRNGKey(0), images[:2])
    state = amp.initialize(
        model.apply, params, fused_lamb(1e-3),
        opt_level="O2", half_dtype=jnp.bfloat16)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(state, x, y):
        def loss_fn(p):
            cp = state.policy.cast_to_compute(p)
            logits = state.apply_fn(cp, x)
            onehot = jax.nn.one_hot(y, cfg.num_classes)
            loss = -jnp.mean(jnp.sum(
                jax.nn.log_softmax(logits.astype(jnp.float32)) * onehot,
                axis=-1))
            return state.scale_loss(loss), loss

        grads, loss = jax.grad(loss_fn, has_aux=True)(state.params)
        new_state, finite = state.apply_gradients(grads=grads)
        return new_state, loss, finite

    out = _measure(state, step, (images, labels), b, {"batch": b})
    out["metric"] = "vit_huge_O2_fusedlamb_samples_per_sec_per_chip"
    _emit(out)


# ----------------------------------------------------------------- groupnorm

def bench_group_norm():
    """GroupNorm+SiLU scoreboard (round-2 verdict weak #6): fwd+bwd
    GN(32 groups)+SiLU over a diffusion-typical activation, achieved
    HBM GB/s vs the chip's peak, measured with the DEFAULT
    implementation — the round-3 Pallas kernels on TPU (the round-2
    XLA composition measured 70 GB/s ≈ 9% of peak here, which refuted
    the original no-kernel rationale; the kernel A/B lives in
    BASELINE.md).  Set APEX_TPU_OPS_IMPL=xla to re-measure the
    composition."""
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from apex_tpu.ops.group_norm import group_norm

    b, hw, c, groups = 8, 64, 512, 32
    x = jnp.asarray(
        np.random.default_rng(0).normal(size=(b, hw, hw, c)),
        jnp.bfloat16)
    w = jnp.ones((c,), jnp.float32)
    bias = jnp.zeros((c,), jnp.float32)

    # ≥1000 in-jit iterations: the tunneled chip's FIXED ~100 ms
    # call+sync overhead poisoned every round-3 GN number at the old
    # 50 steps (÷50 → +2 ms/step on a ~0.3 ms op — the scoreboard's
    # 2.5 ms/step was ~80% overhead); the measured trivial-call
    # overhead is also subtracted per window now
    n_steps = int(os.environ.get("BENCH_STEPS", "0")) or 1000

    # the timed body is EXACTLY the counted passes (round-3 verdict
    # weak #1 — the old harness added ~4 uncounted passes): fwd (read
    # x, write y) + vjp (read dy, read x, write dx), with y and dx
    # both live in the carry and dy independent of x so XLA can
    # neither dead-code the forward nor alias dy into the x read
    dy0 = jnp.asarray(
        np.random.default_rng(1).normal(size=(b, hw, hw, c)),
        jnp.bfloat16)

    @jax.jit
    def many(x, dy, w, bias):
        def body(carry, _):
            xx, dd = carry
            y, pull = jax.vjp(
                lambda q: group_norm(q, groups, w, bias, act="silu"),
                xx)
            (dx,) = pull(dd)
            return (dx.astype(xx.dtype), y.astype(dd.dtype)), None

        carry, _ = jax.lax.scan(body, (x, dy), None, length=n_steps)
        return carry

    out = many(x, dy0, w, bias)
    bench._sync(out)
    assert bool(jnp.isfinite(out[0][0, 0, 0]).all()), "diverged"
    ovh = bench._call_overhead()

    def window():
        t0 = time.perf_counter()
        out = many(x, dy0, w, bias)
        bench._sync(out)
        return (time.perf_counter() - t0 - ovh) / n_steps

    dt, dts = bench._time_windows(
        window, max(1, int(os.environ.get("BENCH_WINDOWS", "3"))))
    # HBM traffic of what is timed: read x, write y (fwd); read dy,
    # read x, write dx (bwd) — 5 × numel × 2 bytes in bf16 (stat
    # reductions are negligible).  NB the KERNEL's own traffic is
    # higher (two-phase sweeps re-read x/dy once each: 8 passes); this
    # metric stays the end-to-end lower-bound form for comparability.
    numel = b * hw * hw * c
    min_bytes = 5 * numel * 2
    gbs = min_bytes / dt / 1e9
    _emit({
        "metric": "group_norm_silu_fwd_bwd_achieved_gbs",
        "value": round(gbs, 1),
        "unit": "GB/s (lower-bound traffic / time)",
        "shape": [b, hw, hw, c], "groups": groups,
        "step_us": round(dt * 1e6, 1),
        "window_us": [round(d * 1e6, 1) for d in dts],
        "frac_of_peak_hbm": round(gbs / bench._PEAK_HBM_GBS, 3),
        "impl_note": (
            "default impl = XLA composition (measured 2.3x faster "
            "than the Pallas kernels once the fixed call overhead is "
            "subtracted — BASELINE.md round-4 GN section); "
            "APEX_TPU_OPS_IMPL=pallas re-measures the kernels"),
    })


# ------------------------------------------------------------- resilience

def bench_resilience_overhead():
    """Steady-state cost of the resilience wrapper (ISSUE 4): the SAME
    jitted train step driven by the bare python loop vs
    ``ResilientLoop`` with async rolling hash-manifest checkpoints
    every ``BENCH_RESIL_CKPT_EVERY`` steps.  Target: <2% step-time
    overhead at checkpoint-every-100 — per step the wrapper adds two
    no-plan fault-injection checks, a ``time.monotonic`` pair and a
    preemption-flag read; the checkpoint's device_get+hash+write rides
    a background thread and amortizes across the interval.  The step
    count is sized so the run ends ON a checkpoint boundary (the final
    blocking save is skipped as already-saved, keeping the measurement
    steady-state).

    Env: BENCH_RESIL_STEPS (300), BENCH_RESIL_CKPT_EVERY (100)."""
    import shutil
    import tempfile
    import time

    import jax
    import jax.numpy as jnp

    from apex_tpu import amp
    from apex_tpu.models import gpt_loss_fn
    from apex_tpu.optim import fused_adam
    from apex_tpu.resilience import ResilientCheckpointer, ResilientLoop
    from apex_tpu.transformer.testing import standalone_gpt

    steps = int(os.environ.get("BENCH_RESIL_STEPS", "300"))
    every = int(os.environ.get("BENCH_RESIL_CKPT_EVERY", "100"))
    steps = max(every, steps - steps % every)   # end ON a ckpt boundary
    b, s = 8, 32
    model, init_params = standalone_gpt(seed=0, max_seq_len=s)
    vocab = model.cfg.vocab_size
    ids = jax.random.randint(jax.random.PRNGKey(7), (4, b, s + 1), 0,
                             vocab, jnp.int32)

    def make_state():
        # fresh buffers per run: the donated step would otherwise
        # delete the shared init_params out from under the next run
        fresh = jax.tree.map(jnp.array, init_params)
        return amp.initialize(
            model.apply, {"params": fresh}, fused_adam(3e-4),
            opt_level="O2", half_dtype=jnp.bfloat16)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(state, chunk):
        inputs, labels = chunk[:, :-1], chunk[:, 1:]

        def loss_fn(p):
            cp = state.policy.cast_to_compute(p)
            logits = state.apply_fn(cp, inputs)
            loss = gpt_loss_fn(logits.astype(jnp.float32), labels)
            return state.scale_loss(loss), loss

        grads, loss = jax.grad(loss_fn, has_aux=True)(state.params)
        new_state, _finite = state.apply_gradients(grads=grads)
        return new_state, loss

    def data_fn(i):
        return ids[i % 4]

    # shared warmup: one compile serves both loops (same jit object)
    warm, _ = step(make_state(), ids[0])
    jax.block_until_ready(warm.params)
    del warm

    def bare():
        state = make_state()
        t0 = time.perf_counter()
        for i in range(steps):
            state, loss = step(state, data_fn(i))
        jax.block_until_ready(loss)
        return (time.perf_counter() - t0) / steps

    def loop_step(st, batch):
        st, loss = step(st, batch)
        return st, {"loss": loss}

    def resilient():
        ckpt_dir = tempfile.mkdtemp(prefix="apex_tpu_resil_bench_")
        loop = ResilientLoop(
            loop_step,
            checkpointer=ResilientCheckpointer(ckpt_dir, keep=2),
            checkpoint_every=every, async_checkpoints=True)
        try:
            t0 = time.perf_counter()
            carry, report = loop.run(make_state(), data_fn, steps)
            jax.block_until_ready(carry.params)
            dt = (time.perf_counter() - t0) / steps
        finally:
            shutil.rmtree(ckpt_dir, ignore_errors=True)
        return dt, report

    k_windows = max(1, int(os.environ.get("BENCH_WINDOWS", "3")))
    bare_dt = min(bare() for _ in range(k_windows))
    pairs = [resilient() for _ in range(k_windows)]
    resil_dt = min(dt for dt, _ in pairs)
    report = pairs[0][1]
    overhead = resil_dt / bare_dt - 1.0
    n_ckpts = max(1, report.checkpoints_saved)
    _emit({
        "metric": f"resilience_overhead_ckpt{every}_pct",
        "value": round(100.0 * overhead, 2),
        "unit": "percent step-time overhead (ResilientLoop + async "
                "rolling checkpoints vs bare loop)",
        "bare_step_ms": round(bare_dt * 1e3, 3),
        "resilient_step_ms": round(resil_dt * 1e3, 3),
        "ms_per_checkpoint": round(
            (resil_dt - bare_dt) * steps * 1e3 / n_ckpts, 1),
        "steps": steps,
        "checkpoint_every": every,
        "checkpoints_written": report.checkpoints_saved,
        "target_pct": 2.0,
        "meets_target": bool(overhead < 0.02),
        "note": ("same jitted step both rows, shared compile, best of "
                 f"{k_windows} runs each; run ends on a checkpoint "
                 "boundary so the final blocking save is amortized "
                 "out (steady state, not save latency).  On the CPU "
                 "backend this is an UPPER bound: the async snapshot "
                 "copy and the background hash/serialize thread share "
                 "the step's own cores, whereas on TPU the step runs "
                 "on-device and only the (μs-scale) on-device copy "
                 "lands in the step's critical path — "
                 "ms_per_checkpoint / (checkpoint_every × step_ms) "
                 "models other intervals"),
    })


def bench_fleet_serving():
    """Multi-replica serving fleet scoreboard (ISSUE 6): tokens/s and
    TTFT p50/p99 *per chip* at a fixed SLO, 1 vs 3 replicas, plus a
    kill-at-midpoint resilience row — reporting protocol per the
    Gemma-on-TPU serving paper (PAPERS.md, arxiv 2605.25645):
    throughput numbers are only comparable at a fixed latency SLO, so
    every row carries the SLO and whether it held.  One replica = one
    chip's worth of serving in this model, so per-chip tokens/s should
    be ~flat 1 → 3 replicas (the router adds routing, not compute),
    and the kill row quantifies what a replica death costs: migrated
    tenants resume on survivors with zero lost requests while
    fleet-wide throughput degrades to the surviving capacity.

    Env: BENCH_FLEET_REPLICAS (3), BENCH_FLEET_REQUESTS (18),
    BENCH_FLEET_PROMPT (8), BENCH_FLEET_TOKENS (16),
    BENCH_FLEET_SLOTS (2), BENCH_FLEET_TTFT_SLO_MS (5000).
    CPU smoke uses the tiny-GPT proxy; the protocol (not the absolute
    numbers) is the artifact."""
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from apex_tpu.models import GPTConfig, GPTModel
    from apex_tpu.serving import FleetRouter, InferenceServer

    replicas = int(os.environ.get("BENCH_FLEET_REPLICAS", "3"))
    requests = int(os.environ.get("BENCH_FLEET_REQUESTS", "18"))
    P = int(os.environ.get("BENCH_FLEET_PROMPT", "8"))
    N = int(os.environ.get("BENCH_FLEET_TOKENS", "16"))
    slots = int(os.environ.get("BENCH_FLEET_SLOTS", "2"))
    slo_ms = float(os.environ.get("BENCH_FLEET_TTFT_SLO_MS", "5000"))

    cfg = GPTConfig.tiny(position_embedding="learned",
                         scan_layers=True)
    model = GPTModel(cfg)
    params = {"params": model.init(
        jax.random.PRNGKey(0),
        jnp.zeros((1, 4), jnp.int32))["params"]}
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, cfg.vocab_size, size=(P,)).astype(
        np.int32) for _ in range(requests)]

    def factory():
        return InferenceServer(
            model, params, max_slots=slots, kv_cache="paged",
            block_size=8, prefill_chunk=4,
            pool_tokens=slots * cfg.max_seq_len)

    def run_fleet(n_replicas, *, kill_mid=False):
        router = FleetRouter(factory, replicas=n_replicas,
                             probe_interval=0.05)
        with router:
            t0 = time.perf_counter()
            handles = [router.submit(p, max_new_tokens=N, seed=i)
                       for i, p in enumerate(prompts)]
            if kill_mid:
                # midpoint: half the total token work done, then a
                # SIGKILL-equivalent death of the busiest replica
                target = requests * N // 2
                while router.stats()["tokens_total"] < target:
                    time.sleep(0.005)
                live = [r for r in router._replicas
                        if r is not None and not r.dead]
                victim = max(live,
                             key=lambda r: len(r.active)).index
                router.kill_replica(victim)
            tokens = sum(len(h.result(timeout=600)) for h in handles)
            wall = time.perf_counter() - t0
            lat = router.latency_summary()
            stats = router.stats()
        ttft_p99_ms = lat.get("ttft_p99_s", 0.0) * 1e3
        return {
            "replicas": n_replicas,
            "tokens_per_sec": round(tokens / wall, 1),
            "tokens_per_sec_per_chip": round(
                tokens / wall / n_replicas, 1),
            "ttft_p50_ms": round(lat.get("ttft_p50_s", 0.0) * 1e3, 1),
            "ttft_p99_ms": round(ttft_p99_ms, 1),
            "ttft_slo_ms": slo_ms,
            "slo_met": bool(ttft_p99_ms <= slo_ms),
            "completed": stats["completed"],
            "failed": stats["failed"],
            "migrated": stats["migrated"],
            "wall_s": round(wall, 3),
        }

    rows = {
        "x1": run_fleet(1),
        f"x{replicas}": run_fleet(replicas),
        f"x{replicas}_kill_midpoint": run_fleet(replicas,
                                                kill_mid=True),
    }
    kill_row = rows[f"x{replicas}_kill_midpoint"]
    _emit({
        "metric": f"fleet_serving_x{replicas}_tokens_per_sec_per_chip",
        "value": rows[f"x{replicas}"]["tokens_per_sec_per_chip"],
        "unit": "tokens/sec/chip at fixed TTFT SLO",
        "requests": requests, "prompt": P, "budget": N,
        "slots_per_replica": slots,
        "rows": rows,
        "zero_loss_under_kill": bool(
            kill_row["completed"] + kill_row["failed"] == requests
            and kill_row["failed"] == 0),
        "note": ("Gemma-paper protocol: tokens/s and TTFT p50/p99 per "
                 "chip reported AT the SLO; the kill row shows "
                 "migrated in-flight tenants resuming on survivors "
                 "with zero lost requests (CPU smoke on the tiny-GPT "
                 "proxy — protocol, not absolute throughput, is the "
                 "artifact)"),
    })


def bench_tp_serving():
    """Tensor-parallel paged serving A/B (ISSUE 13): at EQUAL chip
    count C, (a) C replicas × 1 chip behind a FleetRouter vs (b) ONE
    replica × C chips (``InferenceServer(tp=C)`` — pool sharded on
    kv_heads, matmuls over the GSPMD TP layers), reporting tokens/s
    and TTFT p50/p99 *per chip* per the Gemma-paper protocol, with
    the per-step ICI collective column of ``_serving_traffic_model``
    populated for the TP row.  The M×1 fleet wins pure throughput
    (zero ICI, C independent steps in flight) — the TP row's value is
    CAPACITY: it serves a model C× too big for one chip, and the
    A/B + traffic model quantify exactly what that costs per chip.

    Env: BENCH_TP_CHIPS (2), BENCH_TP_REQUESTS (10),
    BENCH_TP_PROMPT (8), BENCH_TP_TOKENS (16), BENCH_TP_SLOTS (2).
    CPU smoke uses the tiny-GPT proxy over the virtual-device mesh;
    the protocol (not the absolute numbers) is the artifact."""
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from apex_tpu.models import GPTConfig, GPTModel
    from apex_tpu.serving import FleetRouter, InferenceServer

    chips = int(os.environ.get("BENCH_TP_CHIPS", "2"))
    if len(jax.devices()) < chips:
        raise RuntimeError(
            f"tp_serving needs {chips} devices, found "
            f"{len(jax.devices())} — on CPU run with "
            f"XLA_FLAGS=--xla_force_host_platform_device_count=8 "
            f"(the _run_all driver sets it)")
    requests = int(os.environ.get("BENCH_TP_REQUESTS", "10"))
    P = int(os.environ.get("BENCH_TP_PROMPT", "8"))
    N = int(os.environ.get("BENCH_TP_TOKENS", "16"))
    slots = int(os.environ.get("BENCH_TP_SLOTS", "2"))

    cfg = GPTConfig.tiny(position_embedding="learned",
                         scan_layers=True)
    model = GPTModel(cfg)
    params = {"params": model.init(
        jax.random.PRNGKey(0),
        jnp.zeros((1, 4), jnp.int32))["params"]}
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, cfg.vocab_size, size=(P,)).astype(
        np.int32) for _ in range(requests)]

    def summarize(tokens, wall, lat, n_chips, extra):
        ttft_p99 = lat.get("ttft_p99_s", 0.0) * 1e3
        return {
            "chips": n_chips,
            "tokens_per_sec": round(tokens / wall, 1),
            "tokens_per_sec_per_chip": round(
                tokens / wall / n_chips, 1),
            "ttft_p50_ms": round(lat.get("ttft_p50_s", 0.0) * 1e3, 1),
            "ttft_p99_ms": round(ttft_p99, 1),
            "wall_s": round(wall, 3),
            **extra,
        }

    def run_fleet():
        # C replicas × 1 chip: the pre-ISSUE-13 scaling axis.  Each
        # replica's weights are COMMITTED to its own device so the
        # jitted steps actually run there (uncommitted params would
        # pile every replica onto device 0 and the per-chip division
        # below would be fiction)
        import itertools

        devices = jax.devices()
        idx = itertools.count()

        def factory():
            dev = devices[next(idx) % len(devices)]
            return InferenceServer(
                model, jax.device_put(params, dev), max_slots=slots,
                kv_cache="paged", block_size=8, prefill_chunk=4)

        router = FleetRouter(factory, replicas=chips,
                             probe_interval=0.05)
        with router:
            t0 = time.perf_counter()
            handles = [router.submit(p, max_new_tokens=N, seed=i)
                       for i, p in enumerate(prompts)]
            tokens = sum(len(h.result(timeout=600)) for h in handles)
            wall = time.perf_counter() - t0
            lat = router.latency_summary()
            merged = router.health()
        return summarize(tokens, wall, lat, chips, {
            "layout": f"{chips}x1 (replicas x chips)",
            "chips_total": merged["chips_total"],
        })

    def run_tp():
        # 1 replica × C chips: one engine spans the mesh
        server = InferenceServer(
            model, params, max_slots=slots, kv_cache="paged",
            block_size=8, prefill_chunk=4, tp=chips)
        with server:
            t0 = time.perf_counter()
            handles = [server.submit(p, max_new_tokens=N, seed=i)
                       for i, p in enumerate(prompts)]
            tokens = sum(len(h.result(timeout=600)) for h in handles)
            wall = time.perf_counter() - t0
            lat = server.latency_summary()
            health = server.health()
        return summarize(tokens, wall, lat, chips, {
            "layout": f"1x{chips} (replicas x chips)",
            "chips_per_replica": health["chips_per_replica"],
            "mesh_shape": str(health.get("mesh_shape")),
        })

    tm = _serving_traffic_model(
        num_layers=cfg.num_layers, kv_heads=cfg.kv_heads,
        head_dim=cfg.head_dim, max_seq_len=cfg.max_seq_len,
        live_tokens=P + N, slots=slots, block_size=8,
        dtype_bytes=jnp.dtype(cfg.dtype).itemsize,
        tp=chips, hidden_size=cfg.hidden_size)
    rows = {
        f"{chips}x1_fleet": run_fleet(),
        f"1x{chips}_tp": run_tp(),
    }
    _emit({
        "metric": f"tp_serving_1x{chips}_tokens_per_sec_per_chip",
        "value": rows[f"1x{chips}_tp"]["tokens_per_sec_per_chip"],
        "unit": "tokens/sec/chip at equal chip count",
        "requests": requests, "prompt": P, "budget": N,
        "slots_per_replica": slots,
        "rows": rows,
        "traffic_model": tm,
        "note": ("ISSUE-13 A/B at equal chip count: the M×1 fleet is "
                 "the throughput ceiling (zero ICI), the 1×M TP row "
                 "buys model CAPACITY (one replica spans the mesh; "
                 "kv-head-sharded pool reads "
                 f"{tm['paged_kv_read_bytes_per_step_per_chip']} "
                 "B/step/chip vs "
                 f"{tm['paged_kv_read_bytes_per_step']} single-chip) "
                 "at the modeled ICI cost of "
                 f"{tm['ici_bytes_per_step_per_chip']} B/step/chip "
                 "(CPU smoke on the tiny-GPT proxy — protocol, not "
                 "absolute throughput, is the artifact)"),
    })


# ----------------------------------------------------------------- driver

# ----------------------------------------------------- pipeline 1F1B


def bench_pipeline_train():
    """Measured ISSUE-20 row: dp baseline vs dp × pipe at EQUAL chips.

    Two arms over the same global batch and the same stacked
    residual-MLP layer stack (the pipeline test suite's workload):

    - ``dp`` — pure data parallelism over all chips, replicated
      params/optimizer: the layout the planner falls back FROM when
      per-chip residency busts the HBM budget.
    - ``dp_pipe`` — ``parallel.pipeline`` end-to-end: ``stage_split``
      over ``pipe=BENCH_PIPE_PP``, stage-local ZeRO-2 over the
      remaining ``data`` axis, 1F1B via ``wrap_pipeline_step``.

    Emits samples/sec/chip for both arms, the XLA memory-analysis
    per-chip (= per-stage × dp-shard) HBM plus exact placed-array
    state bytes, and measured-vs-modeled bubble: the pipe arm runs at
    two microbatch counts (m, 2m) so the per-microbatch time
    ``τ = (t(2m) − t(m)) / m`` factors out the fixed overhead;
    ``measured_bubble = (t(m) − m·τ) / (m·τ)`` is pinned against the
    schedule's ``(p−1)/m`` and the ``plan.costs.pipeline_costs``
    block the planner scores with.  On the CPU mesh τ prices compute,
    not the overlapped ppermute wire, so the comparison is
    report-only unless ``BENCH_PIPE_BUBBLE_BAND`` is set (> 0:
    ``|measured − modeled|`` must land inside the band).

    Env: BENCH_PIPE_PP (2), BENCH_PIPE_LAYERS (8), BENCH_PIPE_HIDDEN
    (64), BENCH_PIPE_MB (8), BENCH_PIPE_MICROBATCHES (8),
    BENCH_PIPE_STEPS (8), BENCH_PIPE_BUBBLE_BAND (0 = report-only).
    """
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh
    from jax.sharding import PartitionSpec as P

    from apex_tpu import amp
    from apex_tpu.optim import fused_adam
    from apex_tpu.parallel import ZeroConfig
    from apex_tpu.parallel import pipeline as pl
    from apex_tpu.plan.costs import pipeline_costs

    n_dev = jax.device_count()
    pp = int(os.environ.get("BENCH_PIPE_PP", "2"))
    if n_dev < 2 or pp < 2 or n_dev % pp:
        _emit({"metric": "pipeline_train", "value": None,
               "skipped": (f"needs device_count % pp == 0 with "
                           f"pp >= 2, have {n_dev} devices, pp={pp}")})
        return
    dp = n_dev // pp
    layers = int(os.environ.get("BENCH_PIPE_LAYERS", "8"))
    layers = max(pp, layers - layers % pp)      # stage-balance gate
    hid = int(os.environ.get("BENCH_PIPE_HIDDEN", "64"))
    mb = int(os.environ.get("BENCH_PIPE_MB", "8"))
    m = int(os.environ.get("BENCH_PIPE_MICROBATCHES", "8"))
    m = max(pp, m - m % pp)                     # m >= p, DP-divisible
    steps = int(os.environ.get("BENCH_PIPE_STEPS", "8"))
    lr = 1e-2

    r = np.random.default_rng(0)
    params = {"stages": (
        jnp.asarray(r.normal(size=(layers, hid, hid)) * 0.3,
                    jnp.float32),
        jnp.asarray(r.normal(size=(layers, hid)) * 0.1, jnp.float32),
        jnp.asarray(r.normal(size=(layers, hid, hid)) * 0.3,
                    jnp.float32),
    )}
    n_params = sum(x.size for x in jax.tree.leaves(params))

    def layer(x, args):
        w1, b1, w2 = args
        h = jnp.tanh(x @ w1 + b1)
        return x + h @ w2, None

    def stage_fn(stage_params, x):
        x, _ = jax.lax.scan(layer, x, stage_params)
        return x

    def batch_of(mm):
        rb = np.random.default_rng(1)
        x = jnp.asarray(rb.normal(size=(dp * mm, mb, hid)),
                        jnp.float32)
        y = jnp.asarray(rb.normal(size=(dp * mm, mb, hid)),
                        jnp.float32)
        return x, y

    def placed_bytes_per_chip(tree):
        total = 0
        for leaf in jax.tree.leaves(tree):
            try:
                shp = leaf.sharding.shard_shape(leaf.shape)
            except Exception:
                shp = leaf.shape
            total += int(np.prod(shp, dtype=np.int64)) \
                * leaf.dtype.itemsize
        return int(total)

    def timed_loop(step, state, batch):
        state, loss = step(state, *batch)       # compile + warm
        bench._sync(loss)
        t0 = time.perf_counter()
        for _ in range(steps):
            state, loss = step(state, *batch)
        bench._sync(loss)
        return (time.perf_counter() - t0) / steps, float(loss)

    samples = dp * m * mb                       # global samples/step

    def run_dp():
        mesh = Mesh(np.array(jax.devices()[:n_dev]), ("data",))
        state = amp.initialize(None, jax.tree.map(jnp.copy, params),
                               fused_adam(lr), opt_level="O0")

        def dp_step(state, x, y):
            def loss_fn(p):
                out, _ = jax.lax.scan(layer, x, p["stages"])
                return jnp.mean((out - y) ** 2)

            loss, grads = jax.value_and_grad(loss_fn)(state.params)
            grads = jax.tree.map(
                lambda g: jax.lax.pmean(g, "data"), grads)
            new_state, _ = state.apply_gradients(grads=grads)
            return new_state, jax.lax.pmean(loss, "data")

        step = jax.jit(jax.shard_map(
            dp_step, mesh=mesh,
            in_specs=(P(), P("data"), P("data")),
            out_specs=(P(), P()), check_vma=False),
            donate_argnums=(0,))
        x, y = batch_of(m)                      # same global samples
        flat = (x.reshape(-1, hid), y.reshape(-1, hid))
        compiled = bench._aot_compile(step, state, *flat)
        state_bytes = placed_bytes_per_chip(
            (state.params, state.opt_state))
        dt, loss = timed_loop(step, state, flat)
        row = {"layout": f"dp={n_dev}",
               "samples_per_sec_per_chip": round(
                   samples / dt / n_dev, 2),
               "step_ms": round(dt * 1e3, 2),
               "final_loss": round(loss, 5),
               "state_bytes_per_chip": state_bytes}
        row.update(bench._memory_fields(compiled))
        return row

    def run_pipe(mm, want_mem):
        mesh = Mesh(np.array(jax.devices()[:n_dev]).reshape(dp, pp),
                    ("data", "pipe"))
        staged = {"stages": pl.stage_split(params["stages"], pp)}
        state = amp.initialize(
            None, jax.tree.map(jnp.copy, staged), fused_adam(lr),
            opt_level="O0",
            zero=ZeroConfig(axis="data", axis_size=dp, stage=2))
        state = pl.stage_local_zero(state, num_stages=pp)
        state = jax.device_put(
            state, pl.pipeline_state_shardings(state, mesh=mesh))

        def body(state, mbs, labels):
            def loss_fn(out, i):
                yl = jax.lax.dynamic_index_in_dim(labels, i, 0,
                                                  keepdims=False)
                return jnp.mean((out - yl) ** 2)

            loss, grads = pl.run_1f1b(stage_fn, loss_fn,
                                      state.params["stages"], mbs)
            grads = pl.sync_grad_overflow({"stages": grads})
            new_state, _ = state.apply_gradients(grads=grads)
            return new_state, jax.lax.pmean(loss, "data")

        step = pl.wrap_pipeline_step(
            body, state=state, mesh=mesh,
            batch_specs=(P("data"), P("data")))
        batch = batch_of(mm)
        row = {}
        if want_mem:
            compiled = bench._aot_compile(step, state, *batch)
            row.update(bench._memory_fields(compiled))
            row["state_bytes_per_chip"] = placed_bytes_per_chip(
                (state.params, state.opt_state))
        dt, loss = timed_loop(step, state, batch)
        row.update({"layout": f"dp={dp} x pipe={pp} zero2",
                    "microbatches": mm,
                    "samples_per_sec_per_chip": round(
                        dp * mm * mb / dt / n_dev, 2),
                    "step_ms": round(dt * 1e3, 2),
                    "final_loss": round(loss, 5)})
        return row

    dp_row = run_dp()
    pipe_row = run_pipe(m, want_mem=True)
    pipe_2m = run_pipe(2 * m, want_mem=False)

    # two-m extraction: t(m) = m·τ + overhead, so τ falls out of the
    # difference and the bubble is the overhead in units of work time
    t1 = pipe_row["step_ms"]
    t2 = pipe_2m["step_ms"]
    tau = (t2 - t1) / m
    measured_bubble = (round((t1 - m * tau) / (m * tau), 4)
                       if tau > 0 else None)
    modeled = pipeline_costs(pp, m, microbatch_tokens=mb,
                             hidden_size=hid, dtype_bytes=4)
    band = float(os.environ.get("BENCH_PIPE_BUBBLE_BAND", "0"))
    within = (abs(measured_bubble - modeled["bubble_fraction"]) <= band
              if band > 0 and measured_bubble is not None else None)

    _emit({
        "metric": "pipeline_train_samples_per_sec_per_chip",
        "value": pipe_row["samples_per_sec_per_chip"],
        "unit": "samples/sec/chip (CPU-mesh proxy)",
        "devices": n_dev, "dp": dp, "pipe": pp,
        "num_layers": layers, "hidden": hid,
        "num_params": int(n_params),
        "global_samples_per_step": samples,
        "rows": {"dp": dp_row, "dp_pipe": pipe_row,
                 "dp_pipe_2m": pipe_2m},
        "measured_bubble_fraction": measured_bubble,
        "modeled": modeled,
        "bubble_band": band or None,
        "bubble_within_band": within,
        "sps_pipe_vs_dp": round(
            pipe_row["samples_per_sec_per_chip"]
            / max(dp_row["samples_per_sec_per_chip"], 1e-9), 3),
        "state_bytes_pipe_vs_dp": round(
            pipe_row["state_bytes_per_chip"]
            / max(dp_row["state_bytes_per_chip"], 1), 3),
        "note": ("ISSUE-20 row: equal chips, equal global batch; the "
                 "pipe arm's per-chip state is the stage-local "
                 "ZeRO-2 residency (exact placed-array accounting) "
                 "and its hbm fields are XLA memory-analysis bytes "
                 "of the compiled 1F1B step; trajectory agreement is "
                 "gated by test_loss_trajectory's dp-vs-dp×pipe band "
                 "leg; on CPU the wall ratio prices compute, not the "
                 "overlapped ppermute wire — on chip the bubble "
                 "comparison is the contract (set "
                 "BENCH_PIPE_BUBBLE_BAND to gate it)"),
    })


LEGS = {
    "resnet50_o1": bench_resnet50_o1,
    "resnet50_syncbn": bench_resnet50_syncbn,
    "bert_o1": bench_bert_o1,
    "bert_o1_ddp": bench_bert_o1_ddp,
    "bert_o1_zero": bench_bert_o1_zero,
    "gpt2_1p3b": bench_gpt2_1p3b,
    "gpt2_tp8_full_step": bench_gpt2_tp8_full_step,
    "gpt2_3d_full_step": bench_gpt2_3d_full_step,
    "mistral7b_tp8_full_step": bench_mistral7b_tp8_full_step,
    "moe_mixtral": bench_moe_mixtral,
    "llama_1b": bench_llama_1b,
    "decode": bench_decode,
    "serving_decode": bench_serving_decode,
    "decode_epilogue": bench_decode_epilogue,
    "prefix_spec_serving": bench_prefix_spec_serving,
    "quantized_kv_serving": bench_quantized_kv_serving,
    "resilience_overhead": bench_resilience_overhead,
    "fleet_serving": bench_fleet_serving,
    "tp_serving": bench_tp_serving,
    "pipeline_train": bench_pipeline_train,
    "vit_huge_lamb": bench_vit_huge_lamb,
    "long_context": bench_long_context,
    "group_norm": bench_group_norm,
}

# legs that must run on the virtual CPU mesh, not the real chip
_CPU_LEGS = {"gpt2_tp8_full_step", "gpt2_3d_full_step",
             "mistral7b_tp8_full_step", "pipeline_train"}


# per-leg timeouts: orchestrator legs must outlast the sum of their
# own children's budgets (a parent timeout would discard every
# already-measured child row)
_LEG_TIMEOUT = {"decode": 10000, "llama_1b": 8000,
                "long_context": 6600,
                # A/B orchestrators: 4 (o1) / 2 (syncbn) child rows
                "resnet50_o1": 11000, "resnet50_syncbn": 5600}


def _run_all():
    results = {}
    for name in LEGS:
        env = {}
        if name in _CPU_LEGS:
            env = {"JAX_PLATFORMS": "cpu",
                   "PALLAS_AXON_POOL_IPS": None,
                   "XLA_FLAGS": (os.environ.get("XLA_FLAGS", "")
                                 + " --xla_force_host_platform_device"
                                   "_count=8").strip()}
        elif name == "tp_serving":
            # needs a multi-chip mesh: the host-platform device-count
            # flag makes the CPU smoke multi-device and is inert on a
            # real TPU child (which brings its own chips)
            env = {"XLA_FLAGS": (os.environ.get("XLA_FLAGS", "")
                                 + " --xla_force_host_platform_device"
                                   "_count=8").strip()}
        print(f"== {name}", file=sys.stderr)
        results[name] = _run_child(
            name, env, timeout=_LEG_TIMEOUT.get(name, 5400))
        if "error" in results[name]:
            print(f"  FAILED: {results[name]['error'][-300:]}",
                  file=sys.stderr)
        else:
            print(f"  {json.dumps(results[name])[:400]}",
                  file=sys.stderr)
    with open("BENCH_CONFIGS.json", "w") as f:
        json.dump(results, f, indent=2)
    print(json.dumps({"legs": {k: v.get("value") for k, v in
                               results.items()}}))


def main():
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    if which == "all":
        _run_all()
    else:
        LEGS[which]()


if __name__ == "__main__":
    main()
