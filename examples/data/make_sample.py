"""Regenerate the checked-in sample shard (deterministic).

``sample_imagenet.npz``: 16 class-separable 32x32x3 uint8 images
(4 classes; each class a distinct low-frequency pattern + noise,
quantized) + int64 labels — a few-KB stand-in for one real-dataset
shard, so the examples' ``--data`` loader branches run in CI and can
be demoed offline:

    python examples/imagenet/main_amp.py \
        --data examples/data/sample_imagenet.npz --arch resnet18 \
        --batch-size 16 --image-size 32 --steps 5
    python examples/dcgan/main_amp.py \
        --data examples/data/sample_imagenet.npz --steps 5

Usage: python examples/data/make_sample.py
"""

import os

import numpy as np


def main():
    rng = np.random.default_rng(1234)
    n, size, classes = 16, 32, 4
    labels = rng.integers(0, classes, size=(n,))
    protos = rng.normal(size=(classes, 8, 8, 3))
    pats = np.repeat(np.repeat(protos[labels], size // 8, 1),
                     size // 8, 2)
    imgs = pats + 0.3 * rng.normal(size=(n, size, size, 3))
    imgs = (imgs - imgs.min()) / (imgs.max() - imgs.min())
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "sample_imagenet.npz")
    np.savez_compressed(out,
                        images=(imgs * 255).astype(np.uint8),
                        labels=labels.astype(np.int64))
    print(out, os.path.getsize(out), "bytes")


if __name__ == "__main__":
    main()
