"""Import a torch Llama/Mistral checkpoint and generate with the KV cache.

The migration story end to end: build a HF model (here randomly
initialized — swap in ``from_pretrained`` when you have weights), map
its state dict onto the TPU-native :class:`LlamaModel`, and sample with
the jitted KV-cache decode loop.  With ``--window`` the model uses
sliding-window attention (Mistral-style): training/prefill run the
banded flash grid and the decode cache is a window-sized ring buffer.

Run (CPU works):
    python examples/llama_generate.py [--window 8] [--temperature 0.8]
                                      [--prefill-chunk 4]

``--prefill-chunk`` demonstrates chunked prefill (the long-prompt
path: prompts above 8k tokens chunk automatically so a 32k-token
prompt compiles; forcing a small chunk here shows the output is
identical either way).
"""

from __future__ import annotations

import argparse

import jax
import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--window", type=int, default=None,
                    help="sliding-window size (Mistral-style)")
    ap.add_argument("--temperature", type=float, default=0.0)
    ap.add_argument("--max-new-tokens", type=int, default=24)
    ap.add_argument("--prefill-chunk", type=int, default=None,
                    help="prefill the prompt in chunks of this many "
                         "tokens (None = auto: single call below 8k)")
    args = ap.parse_args()

    import torch
    from transformers import LlamaConfig as HFLlamaConfig
    from transformers import LlamaForCausalLM

    from apex_tpu.models import (
        LlamaConfig,
        LlamaModel,
        generate,
        load_torch_llama,
    )

    # a tiny GQA llama; replace with LlamaForCausalLM.from_pretrained
    torch.manual_seed(0)
    hf = LlamaForCausalLM(HFLlamaConfig(
        vocab_size=256, hidden_size=128, intermediate_size=256,
        num_hidden_layers=4, num_attention_heads=8,
        num_key_value_heads=4, max_position_embeddings=128,
        tie_word_embeddings=False)).eval()

    cfg = LlamaConfig(
        vocab_size=256, hidden_size=128, ffn_hidden_size=256,
        num_layers=4, num_heads=8, num_kv_heads=4, max_seq_len=128,
        sliding_window=args.window)
    model = LlamaModel(cfg)

    prompt = np.random.default_rng(0).integers(0, 256, size=(2, 8))
    params = model.init(jax.random.PRNGKey(0),
                        np.asarray(prompt, np.int32))
    params = load_torch_llama(params, hf.state_dict(),
                              num_heads=cfg.num_heads,
                              num_kv_heads=cfg.num_kv_heads)

    out = generate(
        model, params, prompt, max_new_tokens=args.max_new_tokens,
        temperature=args.temperature,
        prefill_chunk=args.prefill_chunk,
        rng=jax.random.PRNGKey(1) if args.temperature > 0 else None)
    for row in np.asarray(out):
        print("prompt:", row[:8].tolist())
        print("  cont:", row[8:].tolist())

    if args.temperature == 0.0 and args.window is None:
        # greedy + full attention: cross-check against torch generate
        with torch.no_grad():
            want = hf.generate(
                torch.from_numpy(prompt), do_sample=False,
                max_new_tokens=args.max_new_tokens,
                pad_token_id=0).numpy()
        assert np.array_equal(np.asarray(out), want), "torch mismatch"
        print("greedy output token-identical to torch generate")


if __name__ == "__main__":
    main()
