"""Tensor + sequence-parallel GPT training on a mesh.

The ``apex.transformer`` workflow (BASELINE.json configs[3], GPT-2-TP)
rebuilt TPU-native: one jit, weights sharded over the ``tensor`` axis by
their ``nn.with_partitioning`` specs, batch over ``data``, sequence
parallelism as activation sharding — XLA inserts the same collectives
the reference's mappings hand-code (SURVEY.md §3.4).

``--pp N`` adds pipeline parallelism: the transformer body is stacked
into stages with ``build_model`` (reference:
``pipeline_parallel/utils.py``) and pipelined with microbatches over the
``pipe`` axis; embedding/head run outside the pipelined region, as in
Megatron's stage-embedding special-casing.

Runs anywhere:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python examples/transformer_tp.py --tp 2 --dp 4 --steps 5
  ... python examples/transformer_tp.py --tp 2 --pp 2 --dp 2 --steps 5
"""

from __future__ import annotations

import argparse
import functools
import time

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu import amp, initialize_mesh
from apex_tpu.models import GPTConfig, GPTModel, gpt_loss_fn
from apex_tpu.optim import fused_adam
from apex_tpu.transformer import broadcast_data


def run_pipelined(args):  # graftlint: hot-step
    """tp×pp×dp: transformer body pipelined via build_model stages."""
    import numpy as np

    from apex_tpu.core.mesh import PIPE_AXIS
    from apex_tpu.models import TransformerConfig, ParallelTransformerLayer
    from apex_tpu.transformer.pipeline_parallel import (
        build_model, spmd_pipeline)

    mesh = initialize_mesh(tensor_model_parallel_size=args.tp,
                           pipeline_model_parallel_size=args.pp,
                           data_parallel_size=args.dp)
    m = 2
    if args.batch_size % m or args.batch_size < m:
        raise SystemExit(
            f"--batch-size {args.batch_size} must be a positive "
            f"multiple of the microbatch count ({m}) under --pp")
    seq, mb = args.seq_len, args.batch_size // m
    cfg = TransformerConfig(
        vocab_size=1024, hidden_size=256, num_layers=1, num_heads=2,
        max_seq_len=seq, sequence_parallel=(args.tp > 1), causal=True,
        dtype=jnp.bfloat16)
    layer = ParallelTransformerLayer(cfg)
    x0 = jnp.zeros((mb, seq, cfg.hidden_size), jnp.float32)
    stage_fn, stages, stage_spec = build_model(
        layer, num_layers=args.pp * 2, pipeline_model_parallel_size=args.pp,
        rng=jax.random.PRNGKey(0), sample_input=x0)

    def pipe_forward(p, ids):
        h = jnp.take(p["embed"], ids, axis=0)
        mbs = h.reshape(m, mb, seq, cfg.hidden_size)

        @jax.shard_map(mesh=mesh, in_specs=(P(PIPE_AXIS), P()),
                       out_specs=P(), axis_names={PIPE_AXIS})
        def run(stages_local, mbs_local):
            return spmd_pipeline(stage_fn, stages_local, mbs_local)

        outs = run(p["stages"], mbs).reshape(m * mb, seq, cfg.hidden_size)
        return outs @ p["head"]

    with jax.set_mesh(mesh):
        embed = jax.random.normal(
            jax.random.PRNGKey(1), (cfg.vocab_size, cfg.hidden_size)) * 0.02
        head = jax.random.normal(
            jax.random.PRNGKey(2), (cfg.hidden_size, cfg.vocab_size)) * 0.02
        params = {"embed": embed, "stages": stages, "head": head}
        half = jnp.bfloat16 if jax.default_backend() == "tpu" else jnp.float32
        state = amp.initialize(pipe_forward, params, fused_adam(1e-3),
                               opt_level=args.opt_level, half_dtype=half)
        # stage leaves pipe(+tensor)-sharded per build_model's spec
        new_params = dict(state.params)
        new_params["stages"] = jax.tree.map(
            lambda sp, l: jax.device_put(l, NamedSharding(mesh, sp)),
            stage_spec, state.params["stages"],
            is_leaf=lambda v: isinstance(v, P))
        state = state.replace(params=new_params)

        rng = np.random.default_rng(0)
        ids = jnp.asarray(rng.integers(
            0, cfg.vocab_size, size=(m * mb, seq + 1)), jnp.int32)
        inputs = jax.device_put(ids[:, :-1], NamedSharding(mesh, P("data")))
        labels = jax.device_put(ids[:, 1:], NamedSharding(mesh, P("data")))

        # the old state is dead once the new one returns — donate it so
        # params/opt-state don't hold two copies of HBM across the step
        # (inputs/labels are reused every step and must NOT be donated)
        @functools.partial(jax.jit, donate_argnums=(0,))
        def train_step(state, inputs, labels):
            def loss_fn(p_):
                logits = pipe_forward(state.policy.cast_to_compute(p_),
                                      inputs)
                loss = gpt_loss_fn(logits.astype(jnp.float32), labels)
                return state.scale_loss(loss), loss

            grads, loss = jax.grad(loss_fn, has_aux=True)(state.params)
            new_state, _ = state.apply_gradients(grads=grads)
            return new_state, loss

        for step in range(args.steps):
            t0 = time.perf_counter()
            state, loss = train_step(state, inputs, labels)
            # stop the clock on device completion, not on the loss
            # readback — float(loss) inside the timed region bills the
            # d2h transfer to the step and stalls the next dispatch
            jax.block_until_ready(loss)
            dt = time.perf_counter() - t0
            # graftlint: unsharded(loss fetched for logging only, after the timed region closes)
            print(f"step {step:3d}  loss {float(loss):.4f}  "
                  f"({dt * 1e3:,.0f} ms)")


def main():  # graftlint: hot-step
    p = argparse.ArgumentParser()
    p.add_argument("--tp", type=int, default=2)
    p.add_argument("--pp", type=int, default=1)
    p.add_argument("--dp", type=int, default=-1)
    p.add_argument("--steps", type=int, default=5)
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--seq-len", type=int, default=128)
    p.add_argument("--opt-level", default="O2")
    args = p.parse_args()

    if args.pp > 1:
        run_pipelined(args)
        return

    mesh = initialize_mesh(tensor_model_parallel_size=args.tp,
                           data_parallel_size=args.dp)
    cfg = GPTConfig.tiny(sequence_parallel=True,
                         max_seq_len=args.seq_len,
                         dtype=jnp.bfloat16)
    model = GPTModel(cfg)

    with mesh:
        tokens = jnp.zeros((args.batch_size, args.seq_len), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), tokens)["params"]
        state = amp.initialize(
            lambda p_, ids: model.apply({"params": p_}, ids),
            params, fused_adam(1e-3), opt_level=args.opt_level,
            half_dtype=jnp.bfloat16)

        key = jax.random.PRNGKey(1)
        ids = jax.random.randint(
            key, (args.batch_size, args.seq_len + 1), 0, cfg.vocab_size,
            jnp.int32)
        batch = broadcast_data(
            ["inputs", "labels"],
            {"inputs": ids[:, :-1], "labels": ids[:, 1:]}, jnp.int32)

        # donate the threaded state (batch tensors are reused per step)
        @functools.partial(jax.jit, donate_argnums=(0,))
        def train_step(state, inputs, labels):
            def loss_fn(p_):
                logits = state.apply_fn(p_, inputs)
                loss = gpt_loss_fn(logits, labels)
                return state.scale_loss(loss), loss
            grads, loss = jax.grad(loss_fn, has_aux=True)(
                state.compute_params())
            new_state, finite = state.apply_gradients(grads=grads)
            return new_state, loss

        for step in range(args.steps):
            t0 = time.perf_counter()
            state, loss = train_step(state, batch["inputs"],
                                     batch["labels"])
            # the tok/s figure must time the device work alone: block
            # for completion, then read the loss off the clock
            jax.block_until_ready(loss)
            dt = time.perf_counter() - t0
            tok_s = args.batch_size * args.seq_len / dt
            # graftlint: unsharded(loss fetched for logging only, after the timed region closes)
            print(f"step {step:3d}  loss {float(loss):.4f}  "
                  f"tok/s {tok_s:,.0f}")


if __name__ == "__main__":
    main()
