"""Tensor + sequence-parallel GPT training on a mesh.

The ``apex.transformer`` workflow (BASELINE.json configs[3], GPT-2-TP)
rebuilt TPU-native: one jit, weights sharded over the ``tensor`` axis by
their ``nn.with_partitioning`` specs, batch over ``data``, sequence
parallelism as activation sharding — XLA inserts the same collectives
the reference's mappings hand-code (SURVEY.md §3.4).

Runs anywhere:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python examples/transformer_tp.py --tp 2 --dp 4 --steps 5
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu import amp, initialize_mesh
from apex_tpu.models import GPTConfig, GPTModel, gpt_loss_fn
from apex_tpu.optim import fused_adam
from apex_tpu.transformer import broadcast_data


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--tp", type=int, default=2)
    p.add_argument("--dp", type=int, default=-1)
    p.add_argument("--steps", type=int, default=5)
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--seq-len", type=int, default=128)
    p.add_argument("--opt-level", default="O2")
    args = p.parse_args()

    mesh = initialize_mesh(tensor_model_parallel_size=args.tp,
                           data_parallel_size=args.dp)
    cfg = GPTConfig.tiny(sequence_parallel=True,
                         max_seq_len=args.seq_len,
                         dtype=jnp.bfloat16)
    model = GPTModel(cfg)

    with mesh:
        tokens = jnp.zeros((args.batch_size, args.seq_len), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), tokens)["params"]
        state = amp.initialize(
            lambda p_, ids: model.apply({"params": p_}, ids),
            params, fused_adam(1e-3), opt_level=args.opt_level,
            half_dtype=jnp.bfloat16)

        key = jax.random.PRNGKey(1)
        ids = jax.random.randint(
            key, (args.batch_size, args.seq_len + 1), 0, cfg.vocab_size,
            jnp.int32)
        batch = broadcast_data(
            ["inputs", "labels"],
            {"inputs": ids[:, :-1], "labels": ids[:, 1:]}, jnp.int32)

        @jax.jit
        def train_step(state, inputs, labels):
            def loss_fn(p_):
                logits = state.apply_fn(p_, inputs)
                loss = gpt_loss_fn(logits, labels)
                return state.scale_loss(loss), loss
            grads, loss = jax.grad(loss_fn, has_aux=True)(
                state.compute_params())
            new_state, finite = state.apply_gradients(grads=grads)
            return new_state, loss

        for step in range(args.steps):
            t0 = time.perf_counter()
            state, loss = train_step(state, batch["inputs"],
                                     batch["labels"])
            loss = float(loss)
            dt = time.perf_counter() - t0
            tok_s = args.batch_size * args.seq_len / dt
            print(f"step {step:3d}  loss {loss:.4f}  tok/s {tok_s:,.0f}")


if __name__ == "__main__":
    main()
