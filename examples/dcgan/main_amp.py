"""DCGAN with mixed precision — two models, two optimizers, one scaler
regime.

Mirror of the reference's ``examples/dcgan/main_amp.py``, whose point is
amp with *multiple* models/optimizers/losses (``amp.initialize`` taking
lists).  Functionally here: two independent ``MixedPrecisionTrainState``s
(G and D), each with its own dynamic loss scale, trained adversarially
on synthetic data.

  python examples/dcgan/main_amp.py --steps 10
"""

from __future__ import annotations

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import flax.linen as nn

from apex_tpu import amp
from apex_tpu.optim import fused_adam


class Generator(nn.Module):
    feat: int = 32

    @nn.compact
    def __call__(self, z):
        x = nn.Dense(4 * 4 * self.feat * 4)(z)
        x = x.reshape(z.shape[0], 4, 4, self.feat * 4)
        for mult in (2, 1):
            x = nn.ConvTranspose(self.feat * mult, (4, 4), (2, 2),
                                 padding="SAME")(x)
            x = nn.relu(nn.GroupNorm(num_groups=8)(x))
        x = nn.ConvTranspose(3, (4, 4), (2, 2), padding="SAME")(x)
        return jnp.tanh(x)


class Discriminator(nn.Module):
    feat: int = 32

    @nn.compact
    def __call__(self, x):
        for mult in (1, 2, 4):
            x = nn.Conv(self.feat * mult, (4, 4), (2, 2),
                        padding="SAME")(x)
            x = nn.leaky_relu(x, 0.2)
        return nn.Dense(1)(x.reshape(x.shape[0], -1))


def bce_logits(logits, target):
    return jnp.mean(
        jnp.maximum(logits, 0) - logits * target
        + jnp.log1p(jnp.exp(-jnp.abs(logits))))


def main():  # graftlint: hot-step
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--zdim", type=int, default=64)
    p.add_argument("--opt-level", default="O1")
    p.add_argument("--data", default=None, metavar="FILE.npz",
                   help="npz with an `images` array (NHWC, 32x32, "
                        "uint8 or float) as the real distribution; "
                        "default: synthetic noise images")
    args = p.parse_args()

    gen, disc = Generator(), Discriminator()
    key = jax.random.PRNGKey(0)
    z0 = jnp.zeros((2, args.zdim))
    g_params = gen.init(key, z0)["params"]
    d_params = disc.init(key, jnp.zeros((2, 32, 32, 3)))["params"]

    g_state = amp.initialize(
        lambda p_, z: gen.apply({"params": p_}, z), g_params,
        fused_adam(2e-4, b1=0.5), opt_level=args.opt_level)
    d_state = amp.initialize(
        lambda p_, x: disc.apply({"params": p_}, x), d_params,
        fused_adam(2e-4, b1=0.5), opt_level=args.opt_level)

    rng = np.random.default_rng(0)
    if args.data:
        raw = np.load(args.data)["images"]
        if raw.shape[1:] != (32, 32, 3):
            raise ValueError(
                f"dcgan expects (N, 32, 32, 3) images, got {raw.shape}")
        if raw.shape[0] < args.batch_size:
            # D must see as many reals as fakes per step
            print(f"# shard has {raw.shape[0]} images < batch-size "
                  f"{args.batch_size}; clamping batch size")
            args.batch_size = raw.shape[0]
        raw = raw[: args.batch_size]
        if raw.dtype == np.uint8:
            raw = raw.astype(np.float32) / 255.0
        # map into the generator's tanh range
        real = jnp.asarray(raw * 2.0 - 1.0, jnp.float32)
    else:
        real = jnp.asarray(
            rng.normal(size=(args.batch_size, 32, 32, 3)), jnp.float32)

    @jax.jit
    def step(g_state, d_state, z):
        fake = g_state.apply_fn(g_state.compute_params(), z)

        def d_loss_fn(dp):
            d_real = d_state.apply_fn(dp, real)
            d_fake = d_state.apply_fn(dp, jax.lax.stop_gradient(fake))
            loss = bce_logits(d_real, 1.0) + bce_logits(d_fake, 0.0)
            return d_state.scale_loss(loss), loss
        d_grads, d_loss = jax.grad(d_loss_fn, has_aux=True)(
            d_state.compute_params())
        d_state, _ = d_state.apply_gradients(grads=d_grads)

        def g_loss_fn(gp):
            fake = g_state.apply_fn(gp, z)
            loss = bce_logits(d_state.apply_fn(
                d_state.compute_params(), fake), 1.0)
            return g_state.scale_loss(loss), loss
        g_grads, g_loss = jax.grad(g_loss_fn, has_aux=True)(
            g_state.compute_params())
        g_state, _ = g_state.apply_gradients(grads=g_grads)
        return g_state, d_state, g_loss, d_loss

    for i in range(args.steps):
        z = jax.random.normal(jax.random.PRNGKey(i),
                              (args.batch_size, args.zdim))
        g_state, d_state, g_loss, d_loss = step(g_state, d_state, z)
        # graftlint: unsharded(demo logging — both losses ride one fetch instead of two)
        g_loss, d_loss = jax.device_get((g_loss, d_loss))
        print(f"step {i:3d}  G {float(g_loss):.4f}  D {float(d_loss):.4f}")


if __name__ == "__main__":
    main()
