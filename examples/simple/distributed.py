"""Minimal data-parallel training — the reference's
``examples/simple/distributed/distributed_data_parallel.py``.

The reference launches one process per GPU and wraps the model in
``apex.parallel.DistributedDataParallel``; gradients all-reduce during
backward.  TPU-native: one process, a ``Mesh`` over all devices, batch
sharded on the ``data`` axis — jit inserts the gradient ``psum``.

  python examples/simple/distributed.py
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
import flax.linen as nn
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu import amp, initialize_mesh
from apex_tpu.optim import fused_sgd


class Net(nn.Module):
    @nn.compact
    def __call__(self, x):
        x = nn.relu(nn.Dense(128)(x))
        return nn.Dense(1)(x)


def main():
    # multi-host: pick up MASTER_ADDR/RANK/WORLD_SIZE (the reference
    # launcher's env contract) if set; single-host no-op
    from apex_tpu.parallel import init_distributed
    init_distributed()
    mesh = initialize_mesh(data_parallel_size=-1)
    ndev = len(jax.devices())
    print(f"mesh: {ndev} device(s) on the 'data' axis")

    net = Net()
    params = net.init(jax.random.PRNGKey(0), jnp.zeros((1, 16)))["params"]
    state = amp.initialize(
        lambda p, x: net.apply({"params": p}, x), params,
        fused_sgd(0.05), opt_level="O0")

    rng = np.random.default_rng(0)
    X = jnp.asarray(rng.normal(size=(64 * ndev, 16)), jnp.float32)
    Y = jnp.sum(X[:, :4], axis=1, keepdims=True)
    sharding = NamedSharding(mesh, P("data"))
    X, Y = jax.device_put(X, sharding), jax.device_put(Y, sharding)

    # donate the threaded state; X/Y are reused across the whole loop
    @functools.partial(jax.jit, donate_argnums=(0,))
    def train_step(state, x, y):
        def loss_fn(p):
            return jnp.mean((state.apply_fn(p, x) - y) ** 2)
        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        new_state, _ = state.apply_gradients(grads=grads)
        return new_state, loss

    with mesh:
        for step in range(50):
            state, loss = train_step(state, X, Y)
            if step % 10 == 0:
                print(f"step {step:3d}  loss {float(loss):.5f}")
    print(f"final loss {float(loss):.5f}")


if __name__ == "__main__":
    main()
