"""Minimal data-parallel training — the reference's
``examples/simple/distributed/distributed_data_parallel.py``.

The reference launches one process per GPU and wraps the model in
``apex.parallel.DistributedDataParallel``; gradients all-reduce during
backward.  TPU-native: one process, a ``Mesh`` over all devices, batch
sharded on the ``data`` axis — jit inserts the gradient ``psum``.

The loop runs under ``apex_tpu.resilience.ResilientLoop`` — with
``--ckpt-dir`` it survives kill -TERM (final checkpoint + clean exit)
and auto-resumes on relaunch; without, the wrapper is a near-free
pass-through (the ``resilience_overhead`` bench leg quantifies it).

  python examples/simple/distributed.py [--ckpt-dir /tmp/ddp_ckpts]
"""

from __future__ import annotations

import argparse
import functools

import jax
import jax.numpy as jnp
import numpy as np
import flax.linen as nn
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu import amp, initialize_mesh
from apex_tpu.optim import fused_sgd
from apex_tpu.resilience import ResilientCheckpointer, ResilientLoop


class Net(nn.Module):
    @nn.compact
    def __call__(self, x):
        x = nn.relu(nn.Dense(128)(x))
        return nn.Dense(1)(x)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ckpt-dir", default=None,
                    help="rolling checkpoints + auto-resume here")
    ap.add_argument("--steps", type=int, default=50)
    args = ap.parse_args()
    # multi-host: pick up MASTER_ADDR/RANK/WORLD_SIZE (the reference
    # launcher's env contract) if set; single-host no-op
    from apex_tpu.parallel import init_distributed
    init_distributed()
    mesh = initialize_mesh(data_parallel_size=-1)
    ndev = len(jax.devices())
    print(f"mesh: {ndev} device(s) on the 'data' axis")

    net = Net()
    params = net.init(jax.random.PRNGKey(0), jnp.zeros((1, 16)))["params"]
    state = amp.initialize(
        lambda p, x: net.apply({"params": p}, x), params,
        fused_sgd(0.05), opt_level="O0")

    rng = np.random.default_rng(0)
    X = jnp.asarray(rng.normal(size=(64 * ndev, 16)), jnp.float32)
    Y = jnp.sum(X[:, :4], axis=1, keepdims=True)
    sharding = NamedSharding(mesh, P("data"))
    X, Y = jax.device_put(X, sharding), jax.device_put(Y, sharding)
    # committed-replicated carry so a checkpoint-restored state (which
    # lands on its target's placement) matches the fresh-run placement
    state = jax.device_put(state, NamedSharding(mesh, P()))

    # donate the threaded state; X/Y are reused across the whole loop
    @functools.partial(jax.jit, donate_argnums=(0,))
    def train_step(state, x, y):
        def loss_fn(p):
            # loss reduction anchored in fp32 (the convention every
            # model loss here follows): under a half-dtype net the
            # MSE mean would otherwise accumulate in bf16
            pred = state.apply_fn(p, x).astype(jnp.float32)
            return jnp.mean((pred - y) ** 2)
        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        new_state, _ = state.apply_gradients(grads=grads)
        return new_state, loss

    def loop_step(state, batch):
        state, loss = train_step(state, *batch)
        return state, {"loss": loss}

    def show(step, row):
        if step % 10 == 0 or step == args.steps:
            print(f"step {step:3d}  loss {row['loss']:.5f}")

    from apex_tpu.utils import MetricsWriter
    loop = ResilientLoop(
        loop_step,
        checkpointer=(ResilientCheckpointer(args.ckpt_dir, keep=2)
                      if args.ckpt_dir else None),
        checkpoint_every=20,
        scalars_of=lambda aux: {"loss": aux["loss"]},
        metrics=MetricsWriter(sink=show))
    with mesh:
        state, report = loop.run(state, lambda s: (X, Y), args.steps)
    print(f"steps_run {report.steps_run}  "
          f"resumed_from {report.resumed_from}  "
          f"preempted {report.preempted}")


if __name__ == "__main__":
    main()
