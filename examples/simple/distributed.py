"""Minimal data-parallel training — the reference's
``examples/simple/distributed/distributed_data_parallel.py``.

The reference launches one process per GPU and wraps the model in
``apex.parallel.DistributedDataParallel``; gradients all-reduce during
backward.  TPU-native: one process, a ``Mesh`` over all devices, batch
sharded on the ``data`` axis — jit inserts the gradient ``psum``.

``--zero {0,1,2}`` (ISSUE 11) swaps the replicated optimizer for the
ZeRO-sharded one (``apex_tpu.parallel.distributed_optim``): fp32
masters and Adam/SGD moments shard over the ``data`` axis instead of
being hand-replicated on every device, gradients reduce-scatter
(stage 2; stage 1 all-reduces then slices), and the updated params
all-gather in the compute dtype.  ``--zero-int8`` additionally puts
the grad sync on the int8 quantized wire.  The state placement comes
from ``zero_shardings`` — which is also the checkpoint-restore
target, so ``--ckpt-dir`` resume lands the shards exactly where a
fresh run puts them.

The loop runs under ``apex_tpu.resilience.ResilientLoop`` — with
``--ckpt-dir`` it survives kill -TERM (final checkpoint + clean exit)
and auto-resumes on relaunch; without, the wrapper is a near-free
pass-through (the ``resilience_overhead`` bench leg quantifies it).

``--plan auto`` (ISSUE 15) stops hand-picking the layout entirely:
the ZeRO stage and wire dtype come from ``apex_tpu.plan()`` over a
parameter-count profile of the net (data-parallel only — the planner
knows nothing about an arbitrary flax module's insides).  An explicit
``--zero`` still wins.

``--plan auto --layers N`` (ISSUE 20) swaps the net for a stacked
residual-MLP ``N`` layers deep so the planner can also enumerate
**pipeline** degrees; ``--hbm-gb`` sets the per-chip feasibility
budget.  Tighten it until every dp/ZeRO layout busts and the winner
is a ``dp × pipe`` layout, which this path adopts end-to-end:
``stage_split`` by the planned degree → stage-local ZeRO → the
plan's own ``state_shardings`` placement →
``parallel.pipeline.wrap_pipeline_step`` running 1F1B over the
planned mesh with ``plan.microbatches`` microbatches per step.

  python examples/simple/distributed.py [--zero 2] [--ckpt-dir /tmp/d]
  python examples/simple/distributed.py --plan auto
  python examples/simple/distributed.py --plan auto --layers 8 \\
      --hbm-gb 0.001   # tiny budget: only pipelined layouts fit
"""

from __future__ import annotations

import argparse
import functools

import jax
import jax.numpy as jnp
import numpy as np
import flax.linen as nn
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu import amp, initialize_mesh
from apex_tpu.optim import fused_sgd
from apex_tpu.parallel import ZeroConfig, zero_shardings, zero_state_specs
from apex_tpu.resilience import ResilientCheckpointer, ResilientLoop


class Net(nn.Module):
    @nn.compact
    def __call__(self, x):
        x = nn.relu(nn.Dense(128)(x))
        return nn.Dense(1)(x)


def _drive(args, state, train_step, data, mesh):
    """The shared resilient training loop: both the DP/ZeRO path and
    the planned-pipeline path end here."""
    def loop_step(state, batch):
        state, loss = train_step(state, *batch)
        return state, {"loss": loss}

    def show(step, row):
        if step % 10 == 0 or step == args.steps:
            print(f"step {step:3d}  loss {row['loss']:.5f}")

    from apex_tpu.utils import MetricsWriter
    loop = ResilientLoop(
        loop_step,
        checkpointer=(ResilientCheckpointer(args.ckpt_dir, keep=2)
                      if args.ckpt_dir else None),
        checkpoint_every=20,
        scalars_of=lambda aux: {"loss": aux["loss"]},
        metrics=MetricsWriter(sink=show))
    with mesh:
        state, report = loop.run(state, lambda s: data, args.steps)
    print(f"steps_run {report.steps_run}  "
          f"resumed_from {report.resumed_from}  "
          f"preempted {report.preempted}")


def _run_planned_stack(args, ndev):
    """``--plan auto --layers N``: let the planner pick dp × pipe ×
    ZeRO for a stacked residual-MLP, then adopt whatever it emits —
    the same recipe works for a pure-dp winner (``pipe == 1``
    degenerates cleanly) and a pipelined one."""
    import dataclasses

    import apex_tpu
    from apex_tpu.parallel import pipeline as pl
    from apex_tpu.plan import DEFAULT_HW

    hid = 64
    r = np.random.default_rng(0)
    stacked = (
        jnp.asarray(r.normal(size=(args.layers, hid, hid)) * 0.3,
                    jnp.float32),
        jnp.asarray(r.normal(size=(args.layers, hid)) * 0.1,
                    jnp.float32),
        jnp.asarray(r.normal(size=(args.layers, hid, hid)) * 0.3,
                    jnp.float32),
    )
    n_params = sum(x.size for x in jax.tree.leaves(stacked))
    hw = (dataclasses.replace(DEFAULT_HW,
                              hbm_bytes=args.hbm_gb * 2**30)
          if args.hbm_gb else None)
    planned = apex_tpu.plan(
        apex_tpu.plan.generic_profile(n_params, dtype_bytes=4,
                                      num_layers=args.layers),
        devices=ndev, objective="train", hw=hw,
        microbatches=args.microbatches)
    lay = planned.layout
    print(f"plan: auto -> {lay.describe()} "
          f"({planned.score['value']:.0f} samples/s/chip modeled, "
          f"{len(planned.alternatives)} alternatives scored)")
    pipe, m = max(lay.pipe, 1), max(planned.microbatches, 1)
    if pipe > 1:
        print(f"pipeline: {pipe} stages (layers "
              f"{planned.stage_assignment}), {m} microbatches/step, "
              f"modeled bubble "
              f"{planned.score.get('bubble_fraction', 0.0):.3f}")
    else:
        print("planned layout is not pipelined — tighten --hbm-gb "
              "to make the dp/ZeRO layouts infeasible")

    # adopt: stage partition -> (stage-local) ZeRO -> planned placement
    staged = {"stages": pl.stage_split(stacked, pipe)}
    state = amp.initialize(None, staged,
                           fused_sgd(0.05, momentum=0.9),
                           opt_level="O0", zero=planned.zero)
    if planned.zero is not None:
        state = pl.stage_local_zero(state, num_stages=pipe)
    state = jax.device_put(state, planned.state_shardings(state))

    def layer_apply(x, wb):
        w1, b1, w2 = wb
        h = jnp.tanh(x @ w1 + b1)
        return x + h @ w2, None

    def stage_fn(stage_params, x):
        x, _ = jax.lax.scan(layer_apply, x, stage_params)
        return x

    def body(state, x, y):
        def loss_fn(out, i):
            yl = jax.lax.dynamic_index_in_dim(y, i, 0, keepdims=False)
            # loss reduction anchored in fp32, like every loss here
            d = (out - yl).astype(jnp.float32)
            return jnp.mean(d * d)

        loss, grads = pl.run_1f1b(stage_fn, loss_fn,
                                  state.params["stages"], x)
        grads = pl.sync_grad_overflow({"stages": grads})
        if planned.zero is None:
            # no ZeRO reduce-scatter to sync the replicas — mean the
            # grads over data here
            grads = jax.tree.map(
                lambda g: jax.lax.pmean(g, "data"), grads)
        new_state, _ = state.apply_gradients(grads=grads)
        return new_state, jax.lax.pmean(loss, "data")

    train_step = pl.wrap_pipeline_step(
        body, state=state, mesh=planned.mesh,
        batch_specs=(planned.data_spec, planned.data_spec))

    mb = 8
    A = jnp.asarray(r.normal(size=(hid, hid)) * 0.5, jnp.float32)
    X = jnp.asarray(r.normal(size=(lay.dp * m, mb, hid)), jnp.float32)
    Y = jnp.tanh(X @ A)
    sharding = NamedSharding(planned.mesh, planned.data_spec)
    X, Y = jax.device_put(X, sharding), jax.device_put(Y, sharding)
    _drive(args, state, train_step, (X, Y), planned.mesh)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ckpt-dir", default=None,
                    help="rolling checkpoints + auto-resume here")
    ap.add_argument("--steps", type=int, default=50)
    ap.add_argument("--zero", type=int, default=None,
                    choices=(0, 1, 2),
                    help="ZeRO stage: 0 = replicated optimizer state, "
                         "1 = sharded state + all-reduce grads, "
                         "2 = sharded state + reduce-scatter grads "
                         "(unset + --plan auto = planner's choice)")
    ap.add_argument("--zero-int8", action="store_true",
                    help="int8 quantized wire for the ZeRO grad sync")
    ap.add_argument("--plan", choices=("auto",), default=None,
                    help="auto = route the ZeRO/wire layout choice "
                         "through apex_tpu.plan() (explicit --zero "
                         "still wins)")
    ap.add_argument("--layers", type=int, default=0,
                    help="with --plan auto: use a stacked residual-MLP "
                         "this many layers deep so the planner can "
                         "also enumerate pipeline degrees (ISSUE 20)")
    ap.add_argument("--hbm-gb", type=float, default=None,
                    help="per-chip HBM feasibility budget in GB for "
                         "the planner (tiny fractions are fine for "
                         "the CPU demo — tighten until only pipelined "
                         "layouts fit)")
    ap.add_argument("--microbatches", type=int, default=8,
                    help="1F1B microbatches per step for planned "
                         "pipeline layouts")
    args = ap.parse_args()
    if args.zero_int8 and not args.zero:
        ap.error("--zero-int8 needs --zero 1 or 2 (the int8 wire is "
                 "the ZeRO grad sync's dtype)")
    # multi-host: pick up MASTER_ADDR/RANK/WORLD_SIZE (the reference
    # launcher's env contract) if set; single-host no-op
    from apex_tpu.parallel import init_distributed
    init_distributed()
    ndev = len(jax.devices())
    if args.plan == "auto" and args.layers:
        _run_planned_stack(args, ndev)
        return
    mesh = initialize_mesh(data_parallel_size=-1)
    print(f"mesh: {ndev} device(s) on the 'data' axis")

    net = Net()
    params = net.init(jax.random.PRNGKey(0), jnp.zeros((1, 16)))["params"]
    zero = None
    if args.plan == "auto" and args.zero is None:
        # route the layout choice through the planner (ISSUE 15): a
        # parameter-count profile is all an arbitrary flax net can
        # offer, so the decision space is dp × ZeRO stage × wire — the
        # emitted ZeroConfig is committed exactly like a hand-set one
        import apex_tpu

        n_params = sum(x.size for x in jax.tree.leaves(params))
        planned = apex_tpu.plan(
            apex_tpu.plan.generic_profile(n_params), devices=ndev,
            objective="train")
        zero = planned.zero
        print(f"plan: auto -> {planned.layout.describe()} "
              f"({planned.score['value']:.0f} samples/s/chip modeled, "
              f"{len(planned.alternatives)} alternatives scored)")
    elif args.zero:
        zero = ZeroConfig(
            axis="data", stage=args.zero,
            reduce_dtype="int8" if args.zero_int8 else None,
            axis_size=ndev)
    state = amp.initialize(
        lambda p, x: net.apply({"params": p}, x), params,
        fused_sgd(0.05, momentum=0.9), opt_level="O0", zero=zero)

    rng = np.random.default_rng(0)
    X = jnp.asarray(rng.normal(size=(64 * ndev, 16)), jnp.float32)
    Y = jnp.sum(X[:, :4], axis=1, keepdims=True)
    sharding = NamedSharding(mesh, P("data"))
    X, Y = jax.device_put(X, sharding), jax.device_put(Y, sharding)

    if zero is not None:
        # sharded masters + optimizer state, replicated params — the
        # committed placement doubles as the checkpoint-restore target
        state = jax.device_put(state, zero_shardings(state, mesh=mesh))
        shard_bytes = sum(
            int(np.prod(l.sharding.shard_shape(l.shape))) * l.dtype.itemsize
            for l in jax.tree.leaves(state.opt_state))
        wire = ("int8" if zero.reduce_dtype == "int8"
                else "fp32" if zero.reduce_dtype is None
                else str(jnp.dtype(zero.reduce_dtype)))
        print(f"zero: stage {zero.stage} over {ndev}-way 'data' axis, "
              f"reduce_dtype={wire}, "
              f"optimizer-state shard {shard_bytes} B/device "
              f"(~1/{ndev} of replicated)")
        specs = zero_state_specs(state)

        # the step runs fully-manual inside shard_map: per-replica
        # grads go straight to apply_gradients, which owns the ZeRO
        # reduce-scatter / shard-local update / param all-gather
        def zero_step(state, x, y):
            def loss_fn(p):
                pred = state.apply_fn(p, x).astype(jnp.float32)
                return jnp.mean((pred - y) ** 2)
            loss, grads = jax.value_and_grad(loss_fn)(state.params)
            new_state, _ = state.apply_gradients(grads=grads)
            return new_state, jax.lax.pmean(loss, "data")

        train_step = jax.jit(jax.shard_map(
            zero_step, mesh=mesh,
            in_specs=(specs, P("data"), P("data")),
            out_specs=(specs, P()), check_vma=False),
            donate_argnums=(0,))
    else:
        # committed-replicated carry so a checkpoint-restored state
        # (which lands on its target's placement) matches the
        # fresh-run placement
        state = jax.device_put(state, NamedSharding(mesh, P()))

        # donate the threaded state; X/Y are reused across the loop
        @functools.partial(jax.jit, donate_argnums=(0,))
        def train_step(state, x, y):
            def loss_fn(p):
                # loss reduction anchored in fp32 (the convention every
                # model loss here follows): under a half-dtype net the
                # MSE mean would otherwise accumulate in bf16
                pred = state.apply_fn(p, x).astype(jnp.float32)
                return jnp.mean((pred - y) ** 2)
            loss, grads = jax.value_and_grad(loss_fn)(state.params)
            new_state, _ = state.apply_gradients(grads=grads)
            return new_state, loss

    _drive(args, state, train_step, (X, Y), mesh)


if __name__ == "__main__":
    main()
