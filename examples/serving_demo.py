"""Continuous-batching inference server, end to end on a tiny GPT.

Starts an :class:`apex_tpu.serving.InferenceServer` over a randomly
initialized tiny GPT, submits a handful of requests with mixed prompt
lengths, budgets and sampling configs, streams each request's tokens as
they decode, and prints the server's throughput/occupancy metrics.

The interesting property on display: every request shape/config mix
runs through ONE compiled decode step (per-slot sampling params are
device arrays, prompts are bucketed) — the engine's retrace guards
would raise if anything recompiled mid-traffic.

With ``--replicas N`` (N > 1) the same traffic goes through a
:class:`apex_tpu.serving.FleetRouter` front door instead: N paged
replica servers, least-loaded health-gated routing by the
blocks-occupancy gauge, and per-replica metrics aggregated into one
fleet view (docs/fleet.md).

With ``--kv-dtype int8`` (or ``fp8`` where the jax build has
``float8_e4m3fn``) the server runs the PAGED datapath with a quantized
KV pool: 1-byte pages + per-page amax scales, ~2–4× the token capacity
at equal HBM admitted as occupancy (docs/serving.md).

With ``--tp M`` (M > 1) each replica spans M chips (tensor-parallel
paged serving, docs/serving.md): the KV pool shards on kv_heads, the
matmuls ride the GSPMD TP layers, and everything above — sharing,
drafting, quantized pages, the fleet router — is unchanged.  Composes
with ``--replicas N`` into an N×M fleet, each replica on its own
device slice.

With ``--plan auto`` (ISSUE 15) the replicas×tp split itself stops
being hand-set: ``apex_tpu.plan(cfg, devices, objective="serve")``
enumerates every equal-chip-count split through the GQA divisibility
gate, scores them on the unified traffic model (per-chip tokens/s,
the Gemma-paper unit), and the demo serves the winner.  Explicit
``--tp`` / ``--replicas`` flags still win; ``--chips`` bounds the
device budget the planner may spend (default: all attached).

Run (CPU works; --tp needs
XLA_FLAGS=--xla_force_host_platform_device_count=8 on CPU):
    python examples/serving_demo.py [--max-slots 2] [--requests 5]
    python examples/serving_demo.py --replicas 3 --requests 8
    python examples/serving_demo.py --kv-dtype int8 --requests 5
    python examples/serving_demo.py --tp 2 --replicas 2 --requests 6
    python examples/serving_demo.py --plan auto --chips 2
"""

from __future__ import annotations

import argparse

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--max-slots", type=int, default=2)
    ap.add_argument("--requests", type=int, default=5)
    ap.add_argument("--replicas", type=int, default=None,
                    help="N > 1 serves through a FleetRouter over N "
                         "paged replica servers (unset + --plan auto "
                         "= planner's choice; defaults to 1)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--kv-dtype", default=None,
                    choices=("int8", "fp8"),
                    help="quantize the paged KV pool (1-byte pages + "
                         "per-page amax scales; implies the paged "
                         "datapath on the single-server run)")
    ap.add_argument("--tp", type=int, default=None,
                    help="chips per replica (M > 1 = tensor-parallel "
                         "paged serving: the KV pool shards on "
                         "kv_heads, one replica spans M chips; "
                         "implies the paged datapath and composes "
                         "with --replicas into an NxM fleet; unset + "
                         "--plan auto = planner's choice; defaults "
                         "to 1)")
    ap.add_argument("--plan", choices=("auto",), default=None,
                    help="auto = route the replicas x tp split "
                         "through apex_tpu.plan(cfg, objective="
                         "'serve'); an explicit --tp/--replicas PINS "
                         "that axis and the planner picks among the "
                         "scored splits consistent with it")
    ap.add_argument("--chips", type=int, default=0,
                    help="with --plan auto: the chip budget the "
                         "planner may spend (0 = all attached "
                         "devices)")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from apex_tpu.models import GPTConfig, GPTModel
    from apex_tpu.serving import FleetRouter, InferenceServer, tp_mesh
    from apex_tpu.utils import MetricsWriter

    cfg = GPTConfig.tiny(position_embedding="learned",
                         scan_layers=True)
    model = GPTModel(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4), jnp.int32))
    params = {"params": params["params"]}

    rng = np.random.default_rng(args.seed)
    metrics = MetricsWriter(sink=lambda step, row: print(
        f"metrics step={step} " + " ".join(
            f"{k}={v:.3g}" for k, v in sorted(row.items()))))

    # mixed traffic: lengths spanning three buckets, greedy and
    # sampled tenants side by side in the same compiled step
    configs = [
        {"length": 3, "max_new_tokens": 6, "temperature": 0.0},
        {"length": 7, "max_new_tokens": 4, "temperature": 0.8,
         "top_k": 20},
        {"length": 12, "max_new_tokens": 5, "temperature": 1.2,
         "top_k": 5},
        {"length": 2, "max_new_tokens": 7, "temperature": 0.0},
        {"length": 9, "max_new_tokens": 3, "temperature": 0.5},
    ]
    configs = [configs[i % len(configs)] for i in range(args.requests)]

    def submit_and_stream(front):
        handles = []
        for i, c in enumerate(configs):
            prompt = rng.integers(0, cfg.vocab_size,
                                  size=(c["length"],))
            h = front.submit(
                prompt,
                max_new_tokens=c["max_new_tokens"],
                temperature=c["temperature"],
                top_k=c.get("top_k"),
                seed=i)
            handles.append((i, prompt, h))
        for i, prompt, h in handles:
            toks = list(h.stream(timeout=600))
            print(f"req {i} prompt={prompt.tolist()} -> {toks}")
        return handles

    if args.tp is not None and args.tp < 1:
        raise SystemExit(f"--tp must be >= 1, got {args.tp}")
    devices = jax.devices()
    if (args.tp or 1) > len(devices):
        raise SystemExit(
            f"--tp {args.tp} needs {args.tp} devices, found "
            f"{len(devices)} (on CPU run with XLA_FLAGS="
            f"--xla_force_host_platform_device_count=8)")

    block_size = 8                 # the demo's paged-pool page size
    if args.plan == "auto" and (args.tp is None
                                or args.replicas is None):
        # ISSUE 15: enumerate the replicas×tp splits over the chip
        # budget, score per-chip tokens/s on the unified traffic
        # model, and serve the winner.  An explicit flag PINS its
        # axis: the choice is then made among the planner's own
        # scored splits consistent with the pin — never a grafted
        # split no score ever evaluated.
        import apex_tpu

        chips = args.chips or len(devices)
        if chips < 1 or chips > len(devices):
            raise SystemExit(
                f"--chips {args.chips} must be between 1 and the "
                f"{len(devices)} attached device(s)")
        planned = apex_tpu.plan(cfg, devices=devices[:chips],
                                objective="serve",
                                slots=args.max_slots)
        cands = [planned.score] + planned.alternatives
        if args.tp is not None:
            cands = [s for s in cands
                     if s["layout"].tp == args.tp]
        if args.replicas is not None:
            cands = [s for s in cands
                     if s["layout"].dp == args.replicas]
        if not cands:
            raise SystemExit(
                f"--plan auto: no feasible {chips}-chip split "
                f"matches the pinned flags (tp={args.tp}, "
                f"replicas={args.replicas}) — scored splits: "
                + ", ".join(s["layout"].describe()
                            for s in [planned.score]
                            + planned.alternatives))
        best = cands[0]           # already sorted best-first
        print(f"plan: auto -> {best['layout'].describe()} "
              f"({best['value']:.0f} tokens/s/chip modeled, "
              f"{len(planned.alternatives)} alternatives scored)")
        args.tp = best["layout"].tp
        args.replicas = best["layout"].dp
        tuned = best.get("autotune") or {}
        if tuned.get("autotuned") and args.kv_dtype in (
                None, tuned["kv_dtype"]):
            # serve the pool the score (and the feasibility gate) was
            # computed with — dropping the tuned (block_size,
            # kv_dtype) would launch an engine up to ~2-4x the
            # modeled pool bytes on the very split those bytes
            # approved.  An explicit --kv-dtype that DISAGREES with
            # the tuned storage dtype wins whole: block sizes are
            # swept per storage dtype (the engine's own key
            # discipline), so the tuned block must not be mixed with
            # a different pool width.
            block_size = tuned["block_size"]
            if args.kv_dtype is None:
                args.kv_dtype = tuned["kv_dtype"]
    args.tp = args.tp or 1
    args.replicas = args.replicas or 1

    if args.replicas > 1:
        import itertools

        replica_idx = itertools.count()

        def factory():
            mesh = None
            if args.tp > 1:
                # each replica gets its own tp-wide device slice
                # (wrapping when the fleet overcommits the host —
                # fine on CPU smoke, a real pod sizes N*M to fit)
                off = next(replica_idx) * args.tp
                mesh = tp_mesh(args.tp, [
                    devices[(off + j) % len(devices)]
                    for j in range(args.tp)])
            return InferenceServer(
                model, params, max_slots=args.max_slots,
                kv_cache="paged", block_size=block_size,
                prefill_chunk=4,
                pool_tokens=args.max_slots * cfg.max_seq_len,
                kv_dtype=args.kv_dtype, mesh=mesh,
                metrics_interval=4)

        router = FleetRouter(factory, replicas=args.replicas,
                             probe_interval=0.1, metrics=metrics,
                             metrics_interval=1)
        with router:
            handles = submit_and_stream(router)
            stats = router.stats()
            health = router.health()
            print(f"fleet: replicas={args.replicas} "
                  f"ready={health['replicas_ready']} "
                  f"chips_per_replica={health['chips_per_replica']} "
                  f"chips_total={health['chips_total']} "
                  f"migrated={stats['migrated']}")
        print(f"done: {len(handles)} requests, "
              f"{stats['tokens_total']} tokens across "
              f"{args.replicas} replicas x "
              f"{health['chips_per_replica']} chips")
        return

    if args.kv_dtype is not None or args.tp > 1:
        # quantized pools and tensor-parallel replicas live in the
        # paged datapath (a dense server rejects both loudly)
        server = InferenceServer(
            model, params, max_slots=args.max_slots,
            kv_cache="paged", block_size=block_size, prefill_chunk=4,
            kv_dtype=args.kv_dtype, tp=args.tp if args.tp > 1 else 0,
            metrics=metrics, metrics_interval=4)
    else:
        server = InferenceServer(
            model, params, max_slots=args.max_slots,
            prompt_buckets=(4, 8, 16), metrics=metrics,
            metrics_interval=4)
    with server:
        handles = submit_and_stream(server)
        if args.kv_dtype is not None:
            h = server.health()
            print(f"kv: dtype={h['kv_dtype']} bits={h['kv_bits']} "
                  f"pool_tokens={server.engine.pool_tokens}")
        if args.tp > 1:
            h = server.health()
            print(f"tp: chips_per_replica={h['chips_per_replica']} "
                  f"mesh_shape={h['mesh_shape']}")
    print(f"done: {len(handles)} requests, "
          f"{server.tokens_emitted} tokens in {server.steps} steps")


if __name__ == "__main__":
    main()
