"""ImageNet training with mixed precision + data parallelism.

Mirror of the reference's ``examples/imagenet/main_amp.py`` (ResNet-50,
amp O1/O2, FusedSGD, apex DDP / SyncBatchNorm) rebuilt TPU-native:
``PrecisionPolicy`` instead of monkey-patched amp, GSPMD data
parallelism (grads ``psum`` over the mesh) instead of bucketed NCCL
allreduce, SyncBatchNorm via cross-replica Welford ``psum``.

Runs on any JAX backend; uses synthetic data by default (the reference
needs an ImageNet folder — pass ``--data`` for a real ``.npy`` pair).

  python examples/imagenet/main_amp.py --opt-level O2 --steps 20 \
      --batch-size 64 --image-size 64
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu import amp, initialize_mesh
from apex_tpu.models.resnet import ResNet, ResNetConfig
from apex_tpu.optim import fused_sgd


def parse_args():
    p = argparse.ArgumentParser()
    p.add_argument("--opt-level", default="O2",
                   choices=["O0", "O1", "O2", "O3"])
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--image-size", type=int, default=64)
    p.add_argument("--num-classes", type=int, default=100)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--momentum", type=float, default=0.9)
    p.add_argument("--weight-decay", type=float, default=1e-4)
    p.add_argument("--sync-bn", action="store_true",
                   help="SyncBatchNorm over the data axis")
    p.add_argument("--arch", default="resnet50",
                   choices=["resnet18", "resnet50"])
    return p.parse_args()


def main():
    args = parse_args()
    mesh = initialize_mesh(data_parallel_size=-1)  # all devices → DP

    stages = (3, 4, 6, 3) if args.arch == "resnet50" else (2, 2, 2, 2)
    cfg = ResNetConfig(
        stage_sizes=stages, num_classes=args.num_classes,
        bn_axis_names=("data",) if args.sync_bn else None,
        dtype=jnp.bfloat16 if args.opt_level in ("O1", "O2", "O3")
        else jnp.float32)
    model = ResNet(cfg)

    rng = np.random.default_rng(0)
    shape = (args.batch_size, args.image_size, args.image_size, 3)
    images = jnp.asarray(rng.normal(size=shape), jnp.float32)
    labels = jnp.asarray(
        rng.integers(0, args.num_classes, size=(args.batch_size,)))

    variables = model.init(jax.random.PRNGKey(0), images[:2], train=True)
    params, batch_stats = variables["params"], variables["batch_stats"]

    def apply_fn(p, x, bs):
        return model.apply({"params": p, "batch_stats": bs}, x,
                           train=True, mutable=["batch_stats"])

    state = amp.initialize(
        apply_fn, params,
        fused_sgd(args.lr, momentum=args.momentum,
                  weight_decay=args.weight_decay),
        opt_level=args.opt_level)

    batch_sharding = NamedSharding(mesh, P("data"))
    images = jax.device_put(images, batch_sharding)
    labels = jax.device_put(labels, batch_sharding)

    @jax.jit
    def train_step(state, batch_stats, x, y):
        def loss_fn(p):
            logits, mut = state.apply_fn(p, x, batch_stats)
            onehot = jax.nn.one_hot(y, args.num_classes)
            loss = -jnp.mean(jnp.sum(
                jax.nn.log_softmax(logits) * onehot, axis=-1))
            return state.scale_loss(loss), (loss, mut["batch_stats"])
        grads, (loss, new_bs) = jax.grad(
            loss_fn, has_aux=True)(state.compute_params())
        new_state, finite = state.apply_gradients(grads=grads)
        return new_state, new_bs, loss, finite

    with mesh:
        for step in range(args.steps):
            t0 = time.perf_counter()
            state, batch_stats, loss, finite = train_step(
                state, batch_stats, images, labels)
            loss = float(loss)
            dt = time.perf_counter() - t0
            print(f"step {step:4d}  loss {loss:.4f}  "
                  f"finite {bool(finite)}  "
                  f"imgs/s {args.batch_size / dt:9.1f}")


if __name__ == "__main__":
    main()
