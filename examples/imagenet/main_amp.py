"""ImageNet training with mixed precision + data parallelism.

Mirror of the reference's ``examples/imagenet/main_amp.py`` (ResNet-50,
amp O1/O2, FusedSGD, apex DDP / SyncBatchNorm) rebuilt TPU-native:
``PrecisionPolicy`` instead of monkey-patched amp, GSPMD data
parallelism (grads ``psum`` over the mesh) instead of bucketed NCCL
allreduce, SyncBatchNorm via cross-replica Welford ``psum``.

Runs on any JAX backend.  Data: ``--data file.npz`` (arrays
``images`` NHWC float and ``labels`` int) trains on real data;
``--synthetic-learnable`` generates class-conditional synthetic images
so convergence is demonstrable without a dataset (loss falls, accuracy
rises — printed per step); the default is random synthetic throughput
mode, as in the reference's no-dataset dry runs.

O1 here is the real per-op interceptor (``amp.o1.o1_intercept`` over a
dtype-None model — conv/dense run bf16, BN/softmax fp32), not a whole-
model cast; O2/O3 cast the model via the precision policy.

  python examples/imagenet/main_amp.py --opt-level O1 --steps 30 \
      --batch-size 64 --image-size 64 --synthetic-learnable
"""

from __future__ import annotations

import argparse
import functools
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu import amp, initialize_mesh
from apex_tpu.models.resnet import ResNet, ResNetConfig
from apex_tpu.optim import fused_sgd


def parse_args():
    p = argparse.ArgumentParser()
    p.add_argument("--opt-level", default="O2",
                   choices=["O0", "O1", "O2", "O3"])
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--image-size", type=int, default=64)
    p.add_argument("--num-classes", type=int, default=100)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--momentum", type=float, default=0.9)
    p.add_argument("--weight-decay", type=float, default=1e-4)
    p.add_argument("--sync-bn", action="store_true",
                   help="SyncBatchNorm over the data axis")
    p.add_argument("--fused-bn", action="store_true",
                   help="fused BN(+add+ReLU) kernels "
                        "(apex_tpu.ops.batch_norm; docs/perf_resnet.md)")
    p.add_argument("--stem", default="conv", choices=["conv", "s2d"],
                   help="'s2d' = MLPerf space-to-depth stem (needs an "
                        "even image size)")
    p.add_argument("--arch", default="resnet50",
                   choices=["resnet18", "resnet50"])
    p.add_argument("--data", default=None, metavar="FILE.npz",
                   help="npz with arrays images (NHWC) + labels (int)")
    p.add_argument("--synthetic-learnable", action="store_true",
                   help="class-conditional synthetic data so training "
                        "demonstrably converges (prints accuracy)")
    p.add_argument("--ckpt-dir", default=None,
                   help="run under apex_tpu.resilience.ResilientLoop: "
                        "rolling hash-verified checkpoints here, "
                        "auto-resume, SIGTERM → final checkpoint + "
                        "clean exit, NaN rewind (docs/resilience.md)")
    p.add_argument("--ckpt-every", type=int, default=50,
                   help="checkpoint cadence (steps) for --ckpt-dir")
    return p.parse_args()


def main():  # graftlint: hot-step
    args = parse_args()
    mesh = initialize_mesh(data_parallel_size=-1)  # all devices → DP

    if args.data:
        # the model head must match the dataset: peek at the labels
        # before building the config
        args.num_classes = int(np.load(args.data)["labels"].max()) + 1
    stages = (3, 4, 6, 3) if args.arch == "resnet50" else (2, 2, 2, 2)
    # O1: model stays dtype-None (modules promote with fp32 params) and
    # the per-op interceptor routes convs/dense to bf16, norms/losses
    # to fp32 — the reference's O1, not a whole-model cast
    dtype = (None if args.opt_level == "O1"
             else jnp.bfloat16 if args.opt_level in ("O2", "O3")
             else jnp.float32)
    cfg = ResNetConfig(
        stage_sizes=stages, num_classes=args.num_classes,
        bn_axis_names=("data",) if args.sync_bn else None,
        dtype=dtype, fused_bn=args.fused_bn, stem=args.stem)
    model = ResNet(cfg)

    rng = np.random.default_rng(0)
    shape = (args.batch_size, args.image_size, args.image_size, 3)
    if args.data:
        blob = np.load(args.data)
        raw = blob["images"][: args.batch_size]
        if raw.dtype == np.uint8:      # shards ship uint8 pixels
            raw = raw.astype(np.float32) / 255.0
        images = jnp.asarray(raw, jnp.float32)
        labels = jnp.asarray(blob["labels"][: args.batch_size])
    elif args.synthetic_learnable:
        # class-conditional means: each class is a distinct low-freq
        # pattern + noise, so a working train step must separate them
        labels_np = rng.integers(0, args.num_classes,
                                 size=(args.batch_size,))
        protos = rng.normal(size=(args.num_classes, 8, 8, 3))
        pats = np.repeat(np.repeat(
            protos[labels_np], args.image_size // 8, 1),
            args.image_size // 8, 2)
        images = jnp.asarray(
            pats + 0.5 * rng.normal(size=shape), jnp.float32)
        labels = jnp.asarray(labels_np)
    else:
        images = jnp.asarray(rng.normal(size=shape), jnp.float32)
        labels = jnp.asarray(
            rng.integers(0, args.num_classes, size=(args.batch_size,)))

    variables = model.init(jax.random.PRNGKey(0), images[:2], train=True)
    params, batch_stats = variables["params"], variables["batch_stats"]

    def apply_fn(p, x, bs):
        if args.opt_level == "O1":
            from apex_tpu.amp import o1
            with o1.o1_intercept(jnp.bfloat16):
                return model.apply({"params": p, "batch_stats": bs}, x,
                                   train=True, mutable=["batch_stats"])
        return model.apply({"params": p, "batch_stats": bs}, x,
                           train=True, mutable=["batch_stats"])

    state = amp.initialize(
        apply_fn, params,
        fused_sgd(args.lr, momentum=args.momentum,
                  weight_decay=args.weight_decay),
        opt_level=args.opt_level)

    batch_sharding = NamedSharding(mesh, P("data"))
    images = jax.device_put(images, batch_sharding)
    labels = jax.device_put(labels, batch_sharding)
    # commit the carry replicated over the mesh: a fresh (uncommitted)
    # state composes with the sharded batch implicitly, but a state
    # RESTORED from a checkpoint comes back committed to its target's
    # placement — so the target must already be the placement the step
    # expects (docs/resilience.md, "restore places like the target")
    replicated = NamedSharding(mesh, P())
    state = jax.device_put(state, replicated)
    batch_stats = jax.device_put(batch_stats, replicated)

    # state and batch_stats are replaced every step — donate both so the
    # old copies' HBM is reused (x/y are the same arrays each step and
    # must stay undonated)
    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def train_step(state, batch_stats, x, y):
        def loss_fn(p):
            logits, mut = state.apply_fn(p, x, batch_stats)
            logits = logits.astype(jnp.float32)
            onehot = jax.nn.one_hot(y, args.num_classes)
            loss = -jnp.mean(jnp.sum(
                jax.nn.log_softmax(logits) * onehot, axis=-1))
            acc = jnp.mean(jnp.argmax(logits, -1) == y)
            return state.scale_loss(loss), (loss, acc,
                                            mut["batch_stats"])
        grads, (loss, acc, new_bs) = jax.grad(
            loss_fn, has_aux=True)(state.compute_params())
        new_state, finite = state.apply_gradients(grads=grads)
        return new_state, new_bs, loss, acc, finite

    with mesh:
        if args.ckpt_dir:
            # preemption-safe path: the reference's kill-and-come-back
            # workflow (save model+optimizer+amp together, restore,
            # keep training), with the dying part handled too
            from apex_tpu.resilience import (
                ResilientCheckpointer, ResilientLoop)

            def loop_step(carry, batch):
                st, bs = carry
                st, bs, loss, acc, finite = train_step(st, bs, *batch)
                return (st, bs), {"loss": loss, "acc": acc,
                                  "finite": finite}

            loop = ResilientLoop(
                loop_step,
                checkpointer=ResilientCheckpointer(args.ckpt_dir,
                                                   keep=3),
                checkpoint_every=args.ckpt_every,
                finite_of=lambda aux: aux["finite"])
            (state, batch_stats), report = loop.run(
                (state, batch_stats),
                lambda step: (images, labels), args.steps)
            print(f"resilient loop: resumed_from={report.resumed_from} "
                  f"steps_run={report.steps_run} "
                  f"preempted={report.preempted} "
                  f"rewinds={report.rewinds} "
                  f"checkpoints={report.checkpoints_saved}")
            return

        for step in range(args.steps):
            t0 = time.perf_counter()
            state, batch_stats, loss, acc, finite = train_step(
                state, batch_stats, images, labels)
            # time the device work alone — reading the metrics inside
            # the window bills three d2h transfers to imgs/s
            jax.block_until_ready(loss)
            dt = time.perf_counter() - t0
            # graftlint: unsharded(metrics fetched once for logging, off the clock — one transfer, not three)
            loss, acc, finite = jax.device_get((loss, acc, finite))
            print(f"step {step:4d}  loss {float(loss):.4f}  "
                  f"acc {float(acc):.3f}  finite {bool(finite)}  "
                  f"imgs/s {args.batch_size / dt:9.1f}")


if __name__ == "__main__":
    main()
