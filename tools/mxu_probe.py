"""MXU dot_general-form probe — measures, on the real chip, the rate of
every matmul orientation the flash-attention kernels could use at
head_dim=64, to ground the d=64 redesign in hardware facts rather than
folklore.

Context (VERDICT round 3, missing #1): the long-context legs run at
11-20% of roofline because d=64 half-fills the MXU.  The 128-deep
systolic array gives a hard 50% utilization cap to any matmul whose
CONTRACTION dim is 64 (each output element is a 64-term dot product —
half the array depth is idle by construction, and block-diagonal
head-packing just moves the waste into multiply-by-zero).  But the
OUTPUT-dim waste (N=64 in P@V, dS@K, Pᵀ@dO, dSᵀ@Q) is removable by
computing the transposed output (N becomes bq/bk, M=64): whether that
pays depends on how Mosaic lowers non-NN dot_general forms, which this
probe measures.

Forms probed (all bf16 operands, f32 accumulation, 512-tiles):
  nn_full   (512,512)@(512,512)             reference full-rate
  nn_qk     (512,64)@(64,512)    K=64       current QKᵀ   (cap: 50%)
  nn_pv     (512,512)@(512,64)   N=64       current P@V   (cap: 50%)
  tn_pv     dg((512,64),(512,512),c0/c0)    proposed accᵀ += Vᵀ@Pᵀ form
  tn_dq     same shape class                proposed dqᵀ  += Kᵀ@dSᵀ
  nt_dv     dg((512,64),(512,512),c0/c1)    proposed dvᵀ  += dOᵀ@P
  nn_T      (512,64)ᵀ-free: k@qᵀ M=512,K=64 transposed-score form
  xpose     (512,64) -> (64,512) transpose  per-step relayout cost

Usage: python tools/mxu_probe.py   (on the chip; idle machine)
"""

from __future__ import annotations

import functools
import json
import sys
import time

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

S, D = 512, 64

# the tunneled chip carries ~100 ms of FIXED call+sync overhead per
# jitted call (measured: a trivial program + device_get = 96-100 ms),
# so each form runs enough grid steps to put ~0.5 s of real work on
# the clock, and the measured trivial-call overhead is subtracted
_G_BY_FORM = {  # steps sized for ~0.5s assuming ~100 TFLOP/s
    "nn_full": 1 << 18, "nn_qk": 1 << 20, "nn_pv": 1 << 20,
    "tn": 1 << 20, "nt": 1 << 20, "nn_T": 1 << 20, "xpose": 1 << 20,
}


def _kernel(a_ref, b_ref, o_ref, acc_ref, *, form, n_steps):
    g = pl.program_id(0)

    @pl.when(g == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    a = a_ref[:]
    b = b_ref[:]
    f32 = jnp.float32
    if form == "nn_full":          # (S,S)@(S,S)
        r = jax.lax.dot_general(a, b, (((1,), (0,)), ((), ())),
                                preferred_element_type=f32)
    elif form == "nn_qk":          # (S,D)@(D,S): K=64
        r = jax.lax.dot_general(a, b, (((1,), (0,)), ((), ())),
                                preferred_element_type=f32)
    elif form == "nn_pv":          # (S,S)@(S,D): N=64
        r = jax.lax.dot_general(a, b, (((1,), (0,)), ((), ())),
                                preferred_element_type=f32)
    elif form == "tn":             # dg((S,D),(S,S), c0/c0) -> (D,S)
        r = jax.lax.dot_general(a, b, (((0,), (0,)), ((), ())),
                                preferred_element_type=f32)
    elif form == "nt":             # dg((S,D),(S,S), c0/c1) -> (D,S)
        r = jax.lax.dot_general(a, b, (((0,), (1,)), ((), ())),
                                preferred_element_type=f32)
    elif form == "nn_T":           # (S,D)@(D,S) M=S,K=64 (k@qT)
        r = jax.lax.dot_general(a, b, (((1,), (0,)), ((), ())),
                                preferred_element_type=f32)
    elif form == "xpose":          # relayout cost probe
        r = jnp.transpose(a).astype(f32)        # (S,D) -> (D,S)
    else:
        raise ValueError(form)
    acc_ref[:] += r

    @pl.when(g == n_steps - 1)
    def _():
        o_ref[:] = acc_ref[:]


_SHAPES = {
    # form: (a_shape, b_shape, out_shape, useful_flops_per_step)
    "nn_full": ((S, S), (S, S), (S, S), 2 * S * S * S),
    "nn_qk": ((S, D), (D, S), (S, S), 2 * S * S * D),
    "nn_pv": ((S, S), (S, D), (S, D), 2 * S * S * D),
    "tn": ((S, D), (S, S), (D, S), 2 * S * S * D),
    "nt": ((S, D), (S, S), (D, S), 2 * S * S * D),
    "nn_T": ((S, D), (D, S), (S, S), 2 * S * S * D),
    "xpose": ((S, D), (D, S), (D, S), 0),
}


def _overhead():
    """Fixed per-call+sync cost of the tunneled backend (subtracted)."""
    triv = jax.jit(lambda x: x + 1)
    x = jnp.float32(0)
    jax.device_get(triv(x))
    dts = []
    for _ in range(3):
        t0 = time.perf_counter()
        jax.device_get(triv(x))
        dts.append(time.perf_counter() - t0)
    return min(dts)


def probe(form, overhead):
    a_shape, b_shape, out_shape, flops = _SHAPES[form]
    g_steps = _G_BY_FORM[form]
    a = jax.random.normal(jax.random.PRNGKey(0), a_shape, jnp.bfloat16)
    b = jax.random.normal(jax.random.PRNGKey(1), b_shape, jnp.bfloat16)
    fn = pl.pallas_call(
        functools.partial(_kernel, form=form, n_steps=g_steps),
        grid=(g_steps,),
        in_specs=[
            pl.BlockSpec(a_shape, lambda g: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(b_shape, lambda g: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(out_shape, lambda g: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(out_shape, jnp.float32),
        scratch_shapes=[pltpu.VMEM(out_shape, jnp.float32)],
    )
    jfn = jax.jit(fn)
    out = jfn(a, b)
    jax.device_get(out.ravel()[0])              # full sync (axon)
    dts = []
    for _ in range(3):
        t0 = time.perf_counter()
        out = jfn(a, b)
        jax.device_get(out.ravel()[0])
        dts.append(time.perf_counter() - t0)
    dt = (min(dts) - overhead) / g_steps
    return {
        "form": form,
        "ns_per_step": round(dt * 1e9, 1),
        "tflops": round(flops / dt / 1e12, 2) if flops else None,
        "windows_ms_total": [round(d * 1e3) for d in dts],
    }


def main():
    forms = sys.argv[1:] or list(_SHAPES)
    overhead = _overhead()
    print(json.dumps({"call_overhead_ms": round(overhead * 1e3, 1)}))
    for f in forms:
        print(json.dumps(probe(f, overhead)))


if __name__ == "__main__":
    main()
