"""graftlint core: AST framework, trace-path inference, taint engine.

The analyzer is a single parse per file feeding a set of registered
rules (``tools/graftlint/rules.py``).  Everything JAX-specific that
rules share lives here:

- :class:`ModuleContext` — parsed tree + parent links + suppression
  map for one file;
- **trace-path inference** (:func:`ModuleContext.traced_functions`) —
  which function bodies execute *at trace time*: jit-family decorators
  (``jax.jit``/``pjit``/``vmap``/``grad``/``checkpoint``/...),
  ``__call__``/``@nn.compact`` methods of ``nn.Module`` subclasses,
  functions passed by name to jit-family call sites or
  ``lax.scan``/``cond``/``while_loop``, plus the transitive closure
  over same-file bare-name calls and lexical nesting.  ``# graftlint:
  traced`` on a ``def`` line force-marks it; ``# graftlint:
  not-traced`` opts out.
- a **taint engine** (:func:`taint_function`, :func:`expr_tainted`) —
  a one-pass, forward, no-kill dataflow marking names derived from a
  traced function's array arguments.  Static metadata accessors
  (``.shape``/``.ndim``/``.dtype``/``len()``/...) sanitize, so
  ``b, s, _ = x.shape`` stays untainted while ``y = x.sum()`` taints.

Suppression syntax (checked per finding line):

- trailing ``# graftlint: disable=<rule>[,<rule>...]`` suppresses on
  that line;
- a standalone ``# graftlint: disable=...`` comment line suppresses
  the line directly below it;
- ``# graftlint: disable-file=<rule>[,...]`` anywhere suppresses the
  rule for the whole file (``all`` works in both forms).
"""

from __future__ import annotations

import argparse
import ast
import dataclasses
import io
import json
import os
import sys
import time
import tokenize
from typing import Callable, Dict, Iterable, Iterator, List, Optional, Set

__all__ = [
    "Finding", "Rule", "ProgramRule", "ModuleContext", "Program",
    "register", "register_program", "all_rules", "all_program_rules",
    "load_context", "lint_source", "lint_path", "lint_paths",
    "expr_tainted", "taint_function", "closure_taint", "dotted_name",
    "main", "run_stats",
]


# --------------------------------------------------------------- findings

@dataclasses.dataclass(frozen=True)
class Finding:
    """One lint hit, pointing at a source line."""

    rule: str
    path: str
    line: int
    col: int
    message: str

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: "
                f"[{self.rule}] {self.message}")

    def to_json(self) -> dict:
        """Machine-readable record (the CI job turns these into inline
        PR annotations): file / line / col / rule / message."""
        return {"file": self.path, "line": self.line, "col": self.col,
                "rule": self.rule, "message": self.message}


class Rule:
    """A named check over a :class:`ModuleContext`.

    Subclasses set ``name`` (the suppression key) and ``summary`` and
    implement :meth:`check` yielding findings (suppressions are applied
    by the runner, not the rule).
    """

    name: str = ""
    summary: str = ""

    def check(self, ctx: "ModuleContext") -> Iterator[Finding]:
        raise NotImplementedError

    def finding(self, ctx: "ModuleContext", node: ast.AST,
                message: str) -> Finding:
        return Finding(self.name, ctx.path, getattr(node, "lineno", 1),
                       getattr(node, "col_offset", 0) + 1, message)


class ProgramRule:
    """A named check over a whole :class:`Program` (module set).

    Per-file rules see one :class:`ModuleContext`; program rules see
    them all — the concurrency pass (``concurrency.py``) is
    interprocedural across ``apex_tpu/serving``, ``resilience`` and
    ``utils/metrics`` and cannot work file-at-a-time.  Suppressions
    still apply per finding line in the finding's own file.
    """

    name: str = ""
    summary: str = ""
    #: rules sharing one expensive analysis name it here: the runner
    #: times :meth:`prepare` once under this row in ``--timings``, so
    #: the cost is not charged to whichever rule happens to run first
    shared_pass: str = ""

    def prepare(self, program: "Program") -> None:
        """Run/memoize any shared analysis on ``program`` (timed under
        :attr:`shared_pass`); default no-op."""

    def check_program(self, program: "Program") -> Iterator[Finding]:
        raise NotImplementedError


class Program:
    """The parsed module set one lint run covers."""

    def __init__(self, contexts: List["ModuleContext"]):
        self.contexts = list(contexts)
        self.by_path = {ctx.path: ctx for ctx in self.contexts}


_REGISTRY: Dict[str, Rule] = {}
_PROGRAM_REGISTRY: Dict[str, ProgramRule] = {}


def register(rule_cls: type) -> type:
    """Class decorator adding a rule to the global registry."""
    rule = rule_cls()
    if not rule.name:
        raise ValueError(f"{rule_cls.__name__} has no name")
    if rule.name in _REGISTRY or rule.name in _PROGRAM_REGISTRY:
        raise ValueError(f"duplicate rule name {rule.name!r}")
    _REGISTRY[rule.name] = rule
    return rule_cls


def register_program(rule_cls: type) -> type:
    """Class decorator adding a whole-program rule to the registry."""
    rule = rule_cls()
    if not rule.name:
        raise ValueError(f"{rule_cls.__name__} has no name")
    if rule.name in _REGISTRY or rule.name in _PROGRAM_REGISTRY:
        raise ValueError(f"duplicate rule name {rule.name!r}")
    _PROGRAM_REGISTRY[rule.name] = rule
    return rule_cls


def _load_rule_modules() -> None:
    # rules self-register on import; import lazily to avoid a cycle
    from tools.graftlint import concurrency as _conc  # noqa: F401
    from tools.graftlint import precision as _prec  # noqa: F401
    from tools.graftlint import rules as _rules  # noqa: F401
    from tools.graftlint import sharding as _shard  # noqa: F401


def all_rules() -> Dict[str, Rule]:
    _load_rule_modules()
    return dict(_REGISTRY)


def all_program_rules() -> Dict[str, ProgramRule]:
    _load_rule_modules()
    return dict(_PROGRAM_REGISTRY)


# ----------------------------------------------------------- suppressions

_DISABLE = "graftlint: disable="
_DISABLE_FILE = "graftlint: disable-file="
_MARK_TRACED = "graftlint: traced"
_MARK_NOT_TRACED = "graftlint: not-traced"


def _parse_rule_list(text: str) -> Set[str]:
    """Comma-separated rule names; each stops at whitespace so trailing
    commentary (``disable=env-read-in-trace — host-only value``) does
    not silently break the suppression."""
    rules: Set[str] = set()
    for segment in text.split(","):
        words = segment.strip().split()
        if words:
            rules.add(words[0])
    return rules


class _Suppressions:
    def __init__(self) -> None:
        self.by_line: Dict[int, Set[str]] = {}
        self.file_wide: Set[str] = set()
        self.traced_marks: Set[int] = set()
        self.not_traced_marks: Set[int] = set()
        #: raw text of every `graftlint:` comment, by line — the
        #: concurrency pass parses its annotation marks out of these
        self.graftlint_comments: Dict[int, str] = {}
        #: lines whose graftlint comment is standalone (whole-line):
        #: only those may annotate the line below them
        self.standalone_comment_lines: Set[int] = set()

    @classmethod
    def scan(cls, source: str) -> "_Suppressions":
        sup = cls()
        try:
            tokens = tokenize.generate_tokens(
                io.StringIO(source).readline)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                text = tok.string.lstrip("#").strip()
                line = tok.start[0]
                if "graftlint:" in text:
                    sup.graftlint_comments[line] = text
                    if tok.line.strip().startswith("#"):
                        sup.standalone_comment_lines.add(line)
                standalone = tok.line.strip().startswith("#")
                if text.startswith(_DISABLE_FILE):
                    sup.file_wide |= _parse_rule_list(
                        text[len(_DISABLE_FILE):])
                elif text.startswith(_DISABLE):
                    rules = _parse_rule_list(text[len(_DISABLE):])
                    target = line + 1 if standalone else line
                    sup.by_line.setdefault(target, set()).update(rules)
                elif text.startswith(_MARK_NOT_TRACED):
                    sup.not_traced_marks.add(line)
                elif text.startswith(_MARK_TRACED):
                    sup.traced_marks.add(line)
        except tokenize.TokenError:
            pass
        return sup

    def is_suppressed(self, rule: str, line: int) -> bool:
        if rule in self.file_wide or "all" in self.file_wide:
            return True
        rules = self.by_line.get(line, ())
        return rule in rules or "all" in rules


# ------------------------------------------------------------ AST helpers

def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def last_attr(node: ast.AST) -> Optional[str]:
    """Final component of a dotted name (``jit`` for ``jax.jit``)."""
    d = dotted_name(node)
    return d.rsplit(".", 1)[-1] if d else None


# transforms whose operand executes at trace time
_JIT_LIKE = {"jit", "pjit", "pmap", "vmap", "grad", "value_and_grad",
             "checkpoint", "remat", "shard_map", "custom_vjp",
             "custom_jvp", "named_call", "xmap"}
# control-flow combinators → positional indices of their traced
# callables (None = every argument from the first index onward, for
# switch's variadic branch list).  Predicates/operands at other
# positions (cond's args[0], fori_loop's bounds) are NOT callables and
# must not mark same-named defs traced.
_CALLABLE_TAKER_ARGS = {
    "scan": (0,), "map": (0,), "associative_scan": (0,),
    "while_loop": (0, 1),          # cond_fun, body_fun
    "cond": (1, 2),                # pred, true_fun, false_fun
    "fori_loop": (2,),             # lower, upper, body_fun
    "switch": None,                # index, *branches
    "custom_root": (0, 2, 3),      # f, initial_guess, solve, tangent_solve
    "custom_linear_solve": (0, 2, 3),  # matvec, b, solve, transpose_solve
}

_FuncNode = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _decorator_marks_traced(dec: ast.AST) -> bool:
    if isinstance(dec, ast.Call):
        # @functools.partial(jax.jit, ...) / @jax.jit(...)-style factory
        if last_attr(dec.func) == "partial" and dec.args:
            return _decorator_marks_traced(dec.args[0])
        return _decorator_marks_traced(dec.func)
    la = last_attr(dec)
    return la in _JIT_LIKE or la == "compact"


def _is_module_class(cls: ast.ClassDef) -> bool:
    """``class X(nn.Module)`` / ``(flax.linen.Module)`` / ``(Module)``."""
    for base in cls.bases:
        if last_attr(base) == "Module":
            return True
    return False


class ModuleContext:
    """Everything the rules need about one parsed file."""

    def __init__(self, path: str, source: str, tree: ast.Module):
        self.path = path
        self.source = source
        self.tree = tree
        self.lines = source.splitlines()
        self.suppressions = _Suppressions.scan(source)
        self.parents: Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent
        # a standalone disable above a decorator targets the decorator
        # line, but def-anchored findings (jit-missing-donate) point at
        # the def — extend decorator-line suppressions to the def line
        for node in ast.walk(tree):
            decorators = getattr(node, "decorator_list", None)
            if not decorators:
                continue
            for dec in decorators:
                rules = self.suppressions.by_line.get(dec.lineno)
                if rules:
                    self.suppressions.by_line.setdefault(
                        node.lineno, set()).update(rules)
        self._traced: Optional[Set[ast.AST]] = None
        self._entries: Set[ast.AST] = set()

    # -- navigation ---------------------------------------------------

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self.parents.get(node)

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, _FuncNode):
                return cur
            cur = self.parents.get(cur)
        return None

    def functions(self) -> Iterator[ast.AST]:
        for node in ast.walk(self.tree):
            if isinstance(node, _FuncNode):
                yield node

    def func_name(self, fn: ast.AST) -> str:
        return getattr(fn, "name", "<lambda>")

    # -- trace-path inference -----------------------------------------

    def traced_functions(self) -> Set[ast.AST]:
        if self._traced is None:
            self._traced = self._infer_traced()
        return self._traced

    def traced_entries(self) -> Set[ast.AST]:
        """Trace-path *entry points*: functions whose parameters are
        the traced operands themselves (jit-family decorated,
        ``nn.Module.__call__``/``@nn.compact`` methods, callables
        passed to jit/scan/cond call sites, ``# graftlint: traced``
        marks).  Transitively-traced same-file helpers are excluded —
        their parameters are often static config threaded by the
        entry, so taint-based rules seed only here."""
        self.traced_functions()
        return self._entries

    def is_traced(self, node: ast.AST) -> bool:
        """Is ``node`` lexically inside a trace-time function body?"""
        fn = node if isinstance(node, _FuncNode) \
            else self.enclosing_function(node)
        while fn is not None:
            if fn in self.traced_functions():
                return True
            fn = self.enclosing_function(fn)
        return False

    def defines_trace_paths(self) -> bool:
        return bool(self.traced_functions())

    def owns(self, entry: ast.AST, node: ast.AST) -> bool:
        """Does ``entry``'s walk cover ``node``?

        A node belongs to its nearest enclosing traced *entry*: nested
        non-entry defs (the jit'd train_step's inner ``loss_fn``
        closure, scan bodies) are part of the enclosing entry's trace
        and share its taint, while nested defs that are entries in
        their own right are covered by their own iteration.  Lambda
        entries are transparent (rules skip lambdas as iteration
        roots, so their bodies must stay with the enclosing entry)."""
        entries = self.traced_entries()
        cur = self.enclosing_function(node)
        while cur is not None:
            if cur is entry:
                return True
            if cur in entries and not isinstance(cur, ast.Lambda):
                return False
            cur = self.enclosing_function(cur)
        return False

    def nested_in_entry(self, fn: ast.AST) -> bool:
        """Is ``fn`` lexically nested inside a (non-lambda) traced
        entry?  Such functions are covered by the entry's walk."""
        entries = self.traced_entries()
        cur = self.enclosing_function(fn)
        while cur is not None:
            if cur in entries and not isinstance(cur, ast.Lambda):
                return True
            cur = self.enclosing_function(cur)
        return False

    def _infer_traced(self) -> Set[ast.AST]:
        traced: Set[ast.AST] = set()
        opted_out: Set[ast.AST] = set()
        # name -> defs (over-approximate: any scope in the file)
        by_name: Dict[str, List[ast.AST]] = {}
        for fn in self.functions():
            if isinstance(fn, ast.Lambda):
                continue
            by_name.setdefault(fn.name, []).append(fn)

        def mark_name(name: Optional[str]) -> None:
            if name:
                for fn in by_name.get(name, ()):
                    traced.add(fn)

        for node in ast.walk(self.tree):
            # explicit comment marks on the def line
            if isinstance(node, _FuncNode):
                line = getattr(node, "lineno", -1)
                if line in self.suppressions.not_traced_marks:
                    opted_out.add(node)
                elif line in self.suppressions.traced_marks:
                    traced.add(node)
            # jit-family decorators; nn.compact methods
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if any(_decorator_marks_traced(d)
                       for d in node.decorator_list):
                    traced.add(node)
            # __call__ of nn.Module subclasses
            if isinstance(node, ast.ClassDef) and _is_module_class(node):
                for item in node.body:
                    if (isinstance(item, (ast.FunctionDef,
                                          ast.AsyncFunctionDef))
                            and item.name == "__call__"):
                        traced.add(item)
            # call sites: jit(f) / lax.scan(f, ...) / checkpoint(f)
            if isinstance(node, ast.Call):
                la = last_attr(node.func)
                callable_args = ()
                if la in _JIT_LIKE:
                    callable_args = node.args[:1]
                elif la in _CALLABLE_TAKER_ARGS:
                    positions = _CALLABLE_TAKER_ARGS[la]
                    if positions is None:    # switch: index, *branches
                        callable_args = node.args[1:]
                    else:
                        callable_args = [node.args[i] for i in positions
                                         if i < len(node.args)]
                for arg in callable_args:
                    if isinstance(arg, ast.Name):
                        mark_name(arg.id)
                    elif isinstance(arg, ast.Lambda):
                        traced.add(arg)

        self._entries = set(traced) - opted_out

        # transitive closure: lexical nesting + same-file bare-name
        # calls + self.method() calls within Module classes
        changed = True
        while changed:
            changed = False
            for fn in list(traced):
                if fn in opted_out:
                    continue
                for node in ast.walk(fn):
                    if isinstance(node, _FuncNode) and node is not fn \
                            and node not in traced:
                        traced.add(node)
                        changed = True
                    if isinstance(node, ast.Call):
                        callee = None
                        if isinstance(node.func, ast.Name):
                            callee = node.func.id
                        elif (isinstance(node.func, ast.Attribute)
                              and isinstance(node.func.value, ast.Name)
                              and node.func.value.id == "self"):
                            callee = node.func.attr
                        if callee:
                            for cand in by_name.get(callee, ()):
                                if cand not in traced:
                                    traced.add(cand)
                                    changed = True
        return traced - opted_out


# ------------------------------------------------------------ taint engine

#: attribute accesses yielding static (trace-safe) python values
SANITIZING_ATTRS = {"shape", "ndim", "dtype", "size", "aval",
                    "sharding", "itemsize", "device", "weak_type"}
#: calls whose result is static regardless of argument taint
SANITIZING_CALLS = {"len", "isinstance", "hasattr", "type", "callable",
                    "repr", "id"}
#: annotations marking a parameter static (config, not data)
_STATIC_ANNOTATIONS = {"bool", "int", "float", "str", "bytes"}


def _annotation_static(ann: Optional[ast.AST]) -> bool:
    if ann is None:
        return False
    # bool / Optional[int] / typing.Optional[str] ...
    names = {n.id for n in ast.walk(ann) if isinstance(n, ast.Name)}
    names |= {n.attr for n in ast.walk(ann)
              if isinstance(n, ast.Attribute)}
    if not names:
        return False
    # FooConfig-typed params are hashable static config, not arrays
    # (the TransformerConfig/GPTConfig convention): branching on their
    # fields specializes the trace, which is the point of config
    if any(n.endswith("Config") for n in names):
        return True
    return names <= (_STATIC_ANNOTATIONS | {"Optional", "Union", "None"})


def expr_tainted(expr: Optional[ast.AST], tainted: Set[str]) -> bool:
    """Does ``expr`` (possibly) derive from a tainted name?"""
    if expr is None:
        return False
    if isinstance(expr, ast.Name):
        return expr.id in tainted
    if isinstance(expr, ast.Attribute):
        if expr.attr in SANITIZING_ATTRS:
            return False
        return expr_tainted(expr.value, tainted)
    if isinstance(expr, ast.Call):
        if isinstance(expr.func, ast.Name) \
                and expr.func.id in SANITIZING_CALLS:
            return False
        if expr_tainted(expr.func, tainted):
            return True
        return any(expr_tainted(a, tainted) for a in expr.args) or \
            any(expr_tainted(k.value, tainted) for k in expr.keywords)
    if isinstance(expr, ast.Subscript):
        return expr_tainted(expr.value, tainted) \
            or expr_tainted(expr.slice, tainted)
    if isinstance(expr, (ast.BinOp, ast.UnaryOp, ast.BoolOp,
                         ast.Compare, ast.IfExp, ast.Tuple, ast.List,
                         ast.Set, ast.Dict, ast.Starred, ast.JoinedStr,
                         ast.FormattedValue, ast.Slice, ast.NamedExpr,
                         ast.Await)):
        return any(expr_tainted(c, tainted)
                   for c in ast.iter_child_nodes(expr)
                   if isinstance(c, ast.expr))
    return False


def _seed_params(fn: ast.AST) -> Set[str]:
    """Parameters of a traced function treated as traced arrays.

    Excluded: ``self``/``cls``, params with static-typed annotations
    (``bool``/``int``/``str``/...), and params whose default is a
    python literal (``deterministic=True``, ``block=1024`` — config
    knobs, not arrays).  ``=None`` defaults stay traced (optional
    arrays)."""
    args = fn.args
    seeds: Set[str] = set()
    ordered = list(args.posonlyargs) + list(args.args)
    defaults = list(args.defaults)
    # align defaults with the tail of the positional list
    pad = [None] * (len(ordered) - len(defaults))
    for arg, default in zip(ordered, pad + defaults):
        seeds.add(arg.arg)
        if arg.arg in ("self", "cls"):
            seeds.discard(arg.arg)
        elif _annotation_static(arg.annotation):
            seeds.discard(arg.arg)
        elif default is not None and isinstance(default, ast.Constant) \
                and default.value is not None:
            seeds.discard(arg.arg)
    for arg, default in zip(args.kwonlyargs, args.kw_defaults):
        if _annotation_static(arg.annotation):
            continue
        if default is not None and isinstance(default, ast.Constant) \
                and default.value is not None:
            continue
        seeds.add(arg.arg)
    if args.vararg:
        seeds.add(args.vararg.arg)
    if args.kwarg:
        seeds.add(args.kwarg.arg)
    return seeds


def _assign_targets(target: ast.AST) -> Iterator[str]:
    if isinstance(target, ast.Name):
        yield target.id
    elif isinstance(target, (ast.Tuple, ast.List)):
        for elt in target.elts:
            yield from _assign_targets(elt)
    elif isinstance(target, ast.Starred):
        yield from _assign_targets(target.value)


def closure_taint(ctx: "ModuleContext", fn: ast.AST) -> Set[str]:
    """Taint for ``fn`` including closure capture: a traced entry that
    is lexically nested in other traced code (``jax.grad(loss_fn)``
    inside a jit'd train_step) sees the enclosing function's arrays
    through its closure, so their taint is unioned in."""
    tainted = taint_function(fn)
    cur = ctx.enclosing_function(fn)
    while cur is not None:
        if cur in ctx.traced_functions() \
                and not isinstance(cur, ast.Lambda):
            tainted |= taint_function(cur)
        cur = ctx.enclosing_function(cur)
    return tainted


def taint_function(fn: ast.AST) -> Set[str]:
    """Names tainted anywhere in ``fn`` (one forward pass, no kill).

    Nested defs/lambdas are part of the same trace: their bodies see
    the enclosing arrays through closure capture, and their own
    parameters are traced operands (``loss_fn(p)``, scan bodies), so
    both are seeded into one shared taint set.  Over-approximates (a
    rebind to a static value does not clear taint, and scopes share
    one namespace) — acceptable for a linter that supports
    suppression."""
    tainted = _seed_params(fn)
    for node in ast.walk(fn):
        if isinstance(node, _FuncNode) and node is not fn:
            tainted |= _seed_params(node)
    body = fn.body if isinstance(fn.body, list) else [fn.body]

    def visit(stmts: Iterable[ast.stmt]) -> None:
        for stmt in stmts:
            if isinstance(stmt, ast.Assign):
                if expr_tainted(stmt.value, tainted):
                    for t in stmt.targets:
                        tainted.update(_assign_targets(t))
            elif isinstance(stmt, ast.AnnAssign) and stmt.value:
                if expr_tainted(stmt.value, tainted):
                    tainted.update(_assign_targets(stmt.target))
            elif isinstance(stmt, ast.AugAssign):
                if expr_tainted(stmt.value, tainted):
                    tainted.update(_assign_targets(stmt.target))
            elif isinstance(stmt, ast.For):
                if expr_tainted(stmt.iter, tainted):
                    tainted.update(_assign_targets(stmt.target))
            elif isinstance(stmt, ast.With):
                for item in stmt.items:
                    if item.optional_vars is not None and \
                            expr_tainted(item.context_expr, tainted):
                        tainted.update(
                            _assign_targets(item.optional_vars))
            # walrus assignments anywhere in the statement's exprs
            for node in ast.walk(stmt):
                if isinstance(node, ast.NamedExpr) and \
                        expr_tainted(node.value, tainted):
                    tainted.update(_assign_targets(node.target))
            # recurse into compound bodies AND nested defs (closures
            # share the trace, so their assignments propagate taint)
            for field in ("body", "orelse", "finalbody", "handlers"):
                sub = getattr(stmt, field, None)
                if not sub or not isinstance(sub, list):
                    continue
                if field == "handlers":
                    for h in sub:
                        visit(h.body)
                else:
                    visit(sub)

    # two passes approximate a fixpoint for use-before-def in loops
    visit(body)
    visit(body)
    return tainted


# ---------------------------------------------------------------- running

#: stats of the most recent lint run (the --timings summary and the
#: budget assertion in tests/test_graftlint.py read these)
run_stats: Dict[str, object] = {
    "files": 0, "parse_s": 0.0, "parse_count": 0, "cache_hits": 0,
    "rules_s": {}, "total_s": 0.0,
}

#: parsed-context cache: path -> ((mtime_ns, size), ModuleContext).
#: One parse feeds every per-file rule AND the whole-program pass —
#: and repeated runs in one process (tests, editors) re-lint a file
#: for free until it changes on disk.
_context_cache: Dict[str, "tuple"] = {}


def _build_context(source: str, path: str):
    """Parse ``source`` into a ModuleContext, or a parse-error Finding."""
    t0 = time.perf_counter()
    run_stats["parse_count"] = int(run_stats["parse_count"]) + 1
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as exc:
        run_stats["parse_s"] = float(run_stats["parse_s"]) \
            + (time.perf_counter() - t0)
        return None, Finding("parse-error", path, exc.lineno or 1,
                             (exc.offset or 0) + 1,
                             f"syntax error: {exc.msg}")
    ctx = ModuleContext(path, source, tree)
    run_stats["parse_s"] = float(run_stats["parse_s"]) \
        + (time.perf_counter() - t0)
    return ctx, None


def load_context(path: str):
    """Cached parse of ``path`` → (ModuleContext | None, parse Finding
    | None).  The cache key is (mtime_ns, size), so an edited file
    reparses and an unchanged one is free."""
    try:
        st = os.stat(path)
        sig = (st.st_mtime_ns, st.st_size)
    except OSError:
        sig = None
    if sig is not None:
        hit = _context_cache.get(path)
        if hit is not None and hit[0] == sig:
            run_stats["cache_hits"] = int(run_stats["cache_hits"]) + 1
            return hit[1], hit[2]
    with open(path, encoding="utf-8") as f:
        source = f.read()
    ctx, err = _build_context(source, path)
    if sig is not None:
        _context_cache[path] = (sig, ctx, err)
    return ctx, err


def _selected(select: Optional[Iterable[str]]):
    rules = all_rules()
    program_rules = all_program_rules()
    names = set(select) if select else set(rules) | set(program_rules)
    unknown = names - set(rules) - set(program_rules)
    if unknown:
        raise ValueError(f"unknown rule(s): {sorted(unknown)}")
    return ({n: rules[n] for n in names if n in rules},
            {n: program_rules[n] for n in names if n in program_rules})


def _timed(name: str, fn) -> List[Finding]:
    t0 = time.perf_counter()
    out = list(fn())
    per_rule = run_stats["rules_s"]
    per_rule[name] = per_rule.get(name, 0.0) \
        + (time.perf_counter() - t0)
    return out


def _run_rules(contexts, parse_errors,
               select: Optional[Iterable[str]]) -> List[Finding]:
    file_rules, program_rules = _selected(select)
    findings: List[Finding] = list(parse_errors)
    for ctx in contexts:
        for name in sorted(file_rules):
            for f in _timed(name, lambda n=name, c=ctx:
                            file_rules[n].check(c)):
                if not ctx.suppressions.is_suppressed(f.rule, f.line):
                    findings.append(f)
    if program_rules and contexts:
        program = Program(contexts)
        prepared: Set[str] = set()
        for name in sorted(program_rules):
            shared = program_rules[name].shared_pass
            if shared and shared not in prepared:
                prepared.add(shared)
                _timed(shared, lambda n=name: (
                    program_rules[n].prepare(program), ())[1])
            for f in _timed(name, lambda n=name:
                            program_rules[n].check_program(program)):
                ctx = program.by_path.get(f.path)
                if ctx is not None and \
                        ctx.suppressions.is_suppressed(f.rule, f.line):
                    continue
                findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def _reset_stats() -> None:
    run_stats.update(files=0, parse_s=0.0, parse_count=0,
                     cache_hits=0, rules_s={}, total_s=0.0)


def lint_source(source: str, path: str = "<string>",
                select: Optional[Iterable[str]] = None) -> List[Finding]:
    """Lint python ``source``; returns unsuppressed findings.  The
    single module is also treated as a whole program, so the
    concurrency rules run on it (fixture-friendly)."""
    _reset_stats()          # run_stats describes THIS run only
    ctx, err = _build_context(source, path)
    if ctx is None:
        all_rules()          # still validate `select` names
        all_program_rules()
        if select:
            _selected(select)
        return [err]
    return _run_rules([ctx], [], select)


def lint_path(path: str,
              select: Optional[Iterable[str]] = None) -> List[Finding]:
    _reset_stats()          # run_stats describes THIS run only
    ctx, err = load_context(path)
    if ctx is None:
        return [err] if err is not None else []
    return _run_rules([ctx], [], select)


_SKIP_DIRS = {"__pycache__", "build", "dist", ".git", ".eggs",
              "node_modules"}


def iter_python_files(paths: Iterable[str]) -> Iterator[str]:
    for path in paths:
        if os.path.isfile(path):
            if path.endswith(".py"):
                yield path
        elif os.path.isdir(path):
            for root, dirs, files in os.walk(path):
                dirs[:] = sorted(d for d in dirs
                                 if d not in _SKIP_DIRS
                                 and not d.startswith("."))
                for name in sorted(files):
                    if name.endswith(".py"):
                        yield os.path.join(root, name)
        else:
            raise FileNotFoundError(path)


def lint_paths(paths: Iterable[str],
               select: Optional[Iterable[str]] = None) -> List[Finding]:
    """Lint files/trees: per-file rules on each module, then the
    whole-program rules over the full module set (one parse per file
    feeds both — see :func:`load_context`)."""
    _reset_stats()
    t0 = time.perf_counter()
    contexts = []
    parse_errors: List[Finding] = []
    n_files = 0
    for path in iter_python_files(paths):
        n_files += 1
        ctx, err = load_context(path)
        if ctx is not None:
            contexts.append(ctx)
        elif err is not None:
            parse_errors.append(err)
    findings = _run_rules(contexts, parse_errors, select)
    run_stats["files"] = n_files
    run_stats["total_s"] = time.perf_counter() - t0
    return findings


def _timing_summary(detail: bool = False) -> str:
    per_rule = dict(run_stats["rules_s"])
    rules_s = sum(per_rule.values())
    line = (f"timing: {run_stats['total_s']:.2f}s total "
            f"(parse {run_stats['parse_s']:.2f}s over "
            f"{run_stats['parse_count']} parse(s), "
            f"{run_stats['cache_hits']} cache hit(s); "
            f"rules {rules_s:.2f}s)")
    if detail and per_rule:
        rows = sorted(per_rule.items(), key=lambda kv: -kv[1])
        line += "".join(f"\n  {name:28s} {secs * 1e3:8.1f} ms"
                        for name, secs in rows)
    elif per_rule:
        slowest = max(per_rule.items(), key=lambda kv: kv[1])
        line += f"; slowest rule {slowest[0]} {slowest[1]:.2f}s"
    return line


#: default on-disk twin of the in-memory AST cache: the (path,
#: mtime_ns, size) signature of every clean file from the last
#: ``--changed-only`` run, persisted so LOCAL iteration skips the
#: full-tree walk.  Full-tree (no flag) remains the CI gate.
STATE_FILE = ".graftlint_state.json"


def _load_state(state_path: str) -> Dict[str, List[int]]:
    try:
        with open(state_path, encoding="utf-8") as f:
            data = json.load(f)
        files = data.get("files", {})
        return {str(k): list(v) for k, v in files.items()}
    except (OSError, ValueError, AttributeError):
        return {}


def _save_state(state_path: str, files: Dict[str, List[int]]) -> None:
    try:
        with open(state_path, "w", encoding="utf-8") as f:
            json.dump({"files": files}, f)
    except OSError:
        pass                      # read-only checkout: stay best-effort


def _changed_files(paths: Iterable[str], state_path: str
                   ) -> "tuple[List[str], Dict[str, List[int]]]":
    """Files under ``paths`` whose (mtime_ns, size) signature differs
    from the persisted record, plus the fresh signature map."""
    prev = _load_state(state_path)
    sigs: Dict[str, List[int]] = {}
    changed: List[str] = []
    for path in iter_python_files(paths):
        key = os.path.abspath(path)
        try:
            st = os.stat(path)
        except OSError:
            continue
        sigs[key] = [st.st_mtime_ns, st.st_size]
        if prev.get(key) != sigs[key]:
            changed.append(path)
    return changed, sigs


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="graftlint",
        description="JAX trace-hygiene + concurrency + precision + "
                    "sharding static analyzer (see docs/graftlint.md)")
    parser.add_argument("paths", nargs="*", default=["apex_tpu"],
                        help="files or directories to lint")
    parser.add_argument("--format", choices=("text", "json"),
                        default="text")
    parser.add_argument("--select", action="append", default=None,
                        metavar="RULE",
                        help="run only these rules (repeatable)")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule registry and exit")
    parser.add_argument("--timings", action="store_true",
                        help="print the per-rule timing table")
    parser.add_argument("--changed-only", action="store_true",
                        help="lint only files whose (path, mtime, "
                             "size) signature changed since the last "
                             "--changed-only run (local iteration; "
                             "whole-program rules see only the "
                             "changed subset — CI runs the full tree)")
    parser.add_argument("--state-file", default=None, metavar="PATH",
                        help=f"--changed-only signature record "
                             f"(default ./{STATE_FILE})")
    args = parser.parse_args(argv)

    if args.list_rules:
        for name, rule in sorted(all_rules().items()):
            print(f"{name:26s} {rule.summary}")
        for name, rule in sorted(all_program_rules().items()):
            print(f"{name:26s} [program] {rule.summary}")
        return 0

    state_path = args.state_file or os.path.join(os.getcwd(),
                                                 STATE_FILE)
    try:
        targets: List[str] = args.paths
        sigs: Dict[str, List[int]] = {}
        if args.changed_only:
            targets, sigs = _changed_files(args.paths, state_path)
            if not targets:
                print("graftlint: 0 changed file(s), clean")
                _save_state(state_path, sigs)
                return 0
        findings = lint_paths(targets, args.select)
    except (FileNotFoundError, ValueError) as exc:
        print(f"graftlint: error: {exc}", file=sys.stderr)
        return 2

    if args.changed_only:
        # record only files that linted CLEAN: a file with findings
        # must re-lint next run even if untouched on disk
        dirty = {os.path.abspath(f.path) for f in findings}
        _save_state(state_path,
                    {k: v for k, v in sigs.items() if k not in dirty})

    if args.format == "json":
        print(json.dumps([f.to_json() for f in findings], indent=2))
    else:
        for f in findings:
            print(f.render())
        status = (f"{len(findings)} finding(s)" if findings
                  else "clean")
        print(f"graftlint: {run_stats['files']} file(s), {status}")
        print(f"graftlint: {_timing_summary(detail=args.timings)}")
    return 1 if findings else 0
