"""graftlint sharding pass — whole-program SPMD/collective analysis.

The trace-hygiene, concurrency and precision passes leave one
discipline unchecked: *placement*.  PR 12/14 shipped hand-audited GSPMD
annotations (``zero_shardings``, ``paged_pool_shardings``, the planner's
emitted specs) whose silent failure mode is a correct-but-fully-
replicated — or per-step host-syncing — program, and every open ROADMAP
item (pipeline over a ``pipe`` axis, multi-host fleet) multiplies the
mesh/collective surface.  This pass makes the placement contract
machine-checked:

1. **Axis-binding inference** — mesh constructions (``Mesh(devs,
   axis_names)``, ``jax.make_mesh``), mesh *factories* (any function
   whose body builds a mesh with resolvable axes — ``initialize_mesh``,
   ``tp_mesh`` — transitively through ``return factory(...)``),
   ``shard_map(mesh=, in_specs=, out_specs=)`` call sites and
   decorators, and ``pmap(axis_name=)``.  Axis names resolve through
   module-level string constants program-wide (``TENSOR_AXIS =
   "tensor"`` in ``core/mesh.py`` resolves at every import site), and
   bindings flow interprocedurally through same-file bare-name /
   ``self.m()`` calls and lexical nesting, exactly like the trace-path
   closure in ``core.py``.

2. **Five rules** on top of that state (catalog in
   ``docs/graftlint.md``): ``unbound-axis-name``,
   ``spec-mesh-mismatch``, ``unreplicated-out-spec``,
   ``host-sync-in-step`` and ``donation-after-use``.

Annotation convention (the concurrency/precision twins of which are
``unguarded(<why>)`` / ``lowprec(<why>)``):

- ``# graftlint: hot-step`` on a ``def`` line marks a *host-side* step
  entry point (an engine decode step, a train-loop step, a bench leg):
  code that runs once per token/step and must not force device→host
  syncs beyond its declared output read.  Rule 4 checks only marked
  functions, so the blast radius is exactly the annotated step set.
- ``# graftlint: unsharded(<why>)`` on a finding line (or a standalone
  comment directly above it) is a justified, deliberate exception to
  any sharding rule — the why is mandatory; an empty ``unsharded()`` is
  itself flagged, matching the guarded-by/lowprec convention.

The runtime twin is :mod:`apex_tpu.utils.shardcheck`, which records the
*actual* output shardings of the compiled step executables against the
declared spec trees under the chaos soaks (``APEX_TPU_SHARDCHECK=
strict``).
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, FrozenSet, Iterator, List, Optional, Set, Tuple

from tools.graftlint.core import (
    Finding,
    ModuleContext,
    ProgramRule,
    dotted_name,
    last_attr,
    register_program,
)

__all__ = ["analyze_program"]

# ----------------------------------------------------------------- marks

_MARK_RE = re.compile(
    r"graftlint:\s*(?:(hot-step)\b|(unsharded)\(([^)]*)\))")

#: collective primitives -> positional index of their axis-name operand
_COLLECTIVES = {
    "psum": 1, "pmean": 1, "pmax": 1, "pmin": 1, "psum_scatter": 1,
    "all_gather": 1, "all_to_all": 1, "ppermute": 1, "pshuffle": 1,
    "collective_permute": 1,
    "axis_index": 0, "axis_size": 0,
}
#: collectives that REDUCE across shards (clear rule-3 divergence)
_REDUCING = {"psum", "pmean", "pmax", "pmin", "psum_scatter",
             "all_gather", "all_to_all"}
#: collectives that PERMUTE across shards: they bind an axis name
#: (rule S1 checks it, via _COLLECTIVES above) but they are NOT
#: reductions — every shard still holds a DIFFERENT (neighbor's)
#: value afterward, so they must not sanitize per-shard divergence
#: in the rule-S3 lattice (the 1F1B pipeline moves activations with
#: exactly this op; a misclassification would blind S3 inside every
#: pipeline body)
_PERMUTING = {"ppermute", "pshuffle", "collective_permute"}

_FuncDef = (ast.FunctionDef, ast.AsyncFunctionDef)
_FuncNode = _FuncDef + (ast.Lambda,)


def _marks_for_line(ctx: ModuleContext, line: int) -> List[Tuple[str, str]]:
    """Sharding marks on ``line`` — trailing, or on a *standalone*
    comment directly above (same contract as the other passes)."""
    sup = ctx.suppressions
    text = sup.graftlint_comments.get(line, "")
    if line - 1 in sup.standalone_comment_lines:
        text += " " + sup.graftlint_comments.get(line - 1, "")
    out: List[Tuple[str, str]] = []
    for m in _MARK_RE.finditer(text):
        if m.group(1):
            out.append(("hot-step", ""))
        else:
            out.append(("unsharded", (m.group(3) or "").strip()))
    return out


def _key(node: ast.AST) -> Optional[str]:
    """``x`` / ``self.x`` → a trackable dotted key, else None."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name):
        return f"{node.value.id}.{node.attr}"
    return None


# ----------------------------------------------- program-wide constants

class _Consts:
    """Module-level string / string-tuple constants, program-wide.

    Axis names in this repo are module constants (``TENSOR_AXIS =
    "tensor"``, ``AXIS_ORDER = (DATA_AXIS, ...)`` in ``core/mesh.py``)
    imported by simple name everywhere — so one flat name→value map
    over every module resolves them at any use site."""

    def __init__(self, contexts: List[ModuleContext]):
        self.strings: Dict[str, str] = {}
        self.tuples: Dict[str, Tuple[str, ...]] = {}
        pending: List[Tuple[str, ast.AST]] = []
        for ctx in contexts:
            for node in ctx.tree.body:
                if isinstance(node, ast.Assign) \
                        and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name):
                    name, value = node.targets[0].id, node.value
                elif isinstance(node, ast.AnnAssign) \
                        and node.value is not None \
                        and isinstance(node.target, ast.Name):
                    # AXIS_ORDER: Tuple[str, ...] = (...) — annotated
                    name, value = node.target.id, node.value
                else:
                    continue
                if isinstance(value, ast.Constant) \
                        and isinstance(value.value, str):
                    self.strings.setdefault(name, value.value)
                elif isinstance(value, (ast.Tuple, ast.List)):
                    pending.append((name, value))
        for name, value in pending:         # second pass: tuples of names
            elems = self.axis_strings(value)
            if elems:
                self.tuples.setdefault(name, tuple(elems))

    def axis_strings(self, node: Optional[ast.AST]
                     ) -> Optional[List[str]]:
        """Resolve ``node`` to a list of axis-name strings, or None if
        it is not statically resolvable (a parameter, a call, ...)."""
        if node is None:
            return None
        if isinstance(node, ast.Constant):
            if isinstance(node.value, str):
                return [node.value]
            if node.value is None:
                return []
            return None
        if isinstance(node, ast.Name):
            if node.id in self.strings:
                return [self.strings[node.id]]
            if node.id in self.tuples:
                return list(self.tuples[node.id])
            return None
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            out: List[str] = []
            for elt in node.elts:
                sub = self.axis_strings(elt)
                if sub is None:
                    return None
                out.extend(sub)
            return out
        return None


# -------------------------------------------------------- mesh resolution

def _mesh_ctor_axes(call: ast.Call, consts: _Consts
                    ) -> Optional[List[str]]:
    """Axes of a ``Mesh(devs, axis_names)`` / ``make_mesh(shape,
    axis_names)`` construction, when literal/constant-resolvable."""
    la = last_attr(call.func)
    if la not in ("Mesh", "make_mesh", "AbstractMesh"):
        return None
    node = None
    for kw in call.keywords:
        if kw.arg == "axis_names":
            node = kw.value
    if node is None and len(call.args) >= 2:
        node = call.args[1]
    return consts.axis_strings(node)


class _MeshResolver:
    """Resolve a mesh *expression* at a call site to its axis names.

    Handles: a direct ``Mesh(...)`` construction; a name assigned one
    in the enclosing function or at module level; a call to a known
    mesh factory (a function whose body constructs a mesh — found
    program-wide, with one propagation round for ``return
    other_factory(...)``); ``self.mesh`` through the owning class's
    ``__init__`` assignment.  Unresolvable → None (checks skip)."""

    def __init__(self, contexts: List[ModuleContext], consts: _Consts):
        self.consts = consts
        self.factories: Dict[str, FrozenSet[str]] = {}
        self._fn_defs: List[Tuple[ModuleContext, ast.AST]] = []
        for ctx in contexts:
            for fn in ctx.functions():
                if isinstance(fn, ast.Lambda):
                    continue
                self._fn_defs.append((ctx, fn))
                axes: Set[str] = set()
                for node in ast.walk(fn):
                    if isinstance(node, ast.Call):
                        got = _mesh_ctor_axes(node, consts)
                        if got:
                            axes.update(got)
                if axes:
                    self.factories.setdefault(fn.name, frozenset(axes))
        # one propagation round: `def tp_mesh(): return initialize_mesh(..)`
        for ctx, fn in self._fn_defs:
            if fn.name in self.factories:
                continue
            for node in ast.walk(fn):
                if isinstance(node, ast.Return) \
                        and isinstance(node.value, ast.Call):
                    callee = last_attr(node.value.func)
                    if callee in self.factories:
                        self.factories[fn.name] = self.factories[callee]

    def resolve(self, ctx: ModuleContext, expr: Optional[ast.AST],
                site: ast.AST) -> Optional[FrozenSet[str]]:
        if expr is None:
            return None
        if isinstance(expr, ast.Call):
            axes = _mesh_ctor_axes(expr, self.consts)
            if axes:
                return frozenset(axes)
            callee = last_attr(expr.func)
            return self.factories.get(callee) if callee else None
        if isinstance(expr, ast.Name):
            return self._resolve_name(ctx, expr.id, site)
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self":
            return self._resolve_self_attr(ctx, expr.attr, site)
        return None

    def _assigned_value(self, scope: ast.AST, name: str
                        ) -> Optional[ast.AST]:
        found = None
        for node in ast.walk(scope):
            if isinstance(node, _FuncNode) and node is not scope:
                continue
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id == name:
                        found = node.value
        return found

    def _resolve_name(self, ctx: ModuleContext, name: str,
                      site: ast.AST) -> Optional[FrozenSet[str]]:
        fn = ctx.enclosing_function(site)
        while fn is not None:
            value = self._assigned_value(fn, name)
            if value is not None:
                return self.resolve(ctx, value, site)
            fn = ctx.enclosing_function(fn)
        for node in ctx.tree.body:
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id == name:
                        return self.resolve(ctx, node.value, site)
        return None

    def _resolve_self_attr(self, ctx: ModuleContext, attr: str,
                           site: ast.AST) -> Optional[FrozenSet[str]]:
        cur = ctx.parent(site)
        while cur is not None and not isinstance(cur, ast.ClassDef):
            cur = ctx.parent(cur)
        if cur is None:
            return None
        for node in ast.walk(cur):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call):
                for t in node.targets:
                    if isinstance(t, ast.Attribute) and t.attr == attr \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id == "self":
                        return self.resolve(ctx, node.value, site)
        return None


# -------------------------------------------------------- shard_map sites

@dataclasses.dataclass
class _ShardMapSite:
    ctx: ModuleContext
    call: ast.Call
    wrapped: Optional[ast.AST]           # resolved function def, if any
    mesh_axes: Optional[FrozenSet[str]]  # None = unresolvable
    manual_axes: Optional[FrozenSet[str]]    # axis_names= subset, if given
    in_specs: Optional[ast.AST]
    out_specs: Optional[ast.AST]

    @property
    def bound_axes(self) -> Optional[FrozenSet[str]]:
        """Axes manual (collective-visible) inside the wrapped body."""
        if self.manual_axes is not None:
            return self.manual_axes
        return self.mesh_axes


def _call_kw(call: ast.Call, name: str) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _is_partial_of(call: ast.Call, attr: str) -> bool:
    return (last_attr(call.func) == "partial" and call.args
            and last_attr(call.args[0]) == attr)


def _shard_map_sites(ctx: ModuleContext, resolver: _MeshResolver,
                     consts: _Consts) -> List[_ShardMapSite]:
    sites = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        is_direct = last_attr(node.func) == "shard_map"
        is_partial = _is_partial_of(node, "shard_map")
        if not (is_direct or is_partial):
            continue
        # the wrapped callable: arg 0 (direct), the decorated def
        # (decorator form), or the operand of the partial's later call
        wrapped: Optional[ast.AST] = None
        pos = list(node.args[1:]) if is_direct else []
        cand = node.args[0] if (is_direct and node.args) else None
        if is_partial:
            cand = None
        parent = ctx.parent(node)
        if isinstance(parent, _FuncDef) \
                and node in parent.decorator_list:
            wrapped = parent                 # @shard_map(...) decorator
        elif isinstance(cand, ast.Lambda):
            wrapped = cand
        elif isinstance(cand, ast.Name):
            for fn in ctx.functions():
                if getattr(fn, "name", None) == cand.id:
                    wrapped = fn
                    break
        elif cand is None and isinstance(parent, ast.Call) \
                and parent.func is node and parent.args \
                and isinstance(parent.args[0], ast.Name):
            # partial(shard_map, ...)(f) — rare; resolve f
            for fn in ctx.functions():
                if getattr(fn, "name", None) == parent.args[0].id:
                    wrapped = fn
                    break
        mesh_expr = _call_kw(node, "mesh")
        if mesh_expr is None and is_direct and pos:
            mesh_expr = pos[0]
            pos = pos[1:]
        in_specs = _call_kw(node, "in_specs")
        if in_specs is None and is_direct and pos:
            in_specs = pos[0]
            pos = pos[1:]
        out_specs = _call_kw(node, "out_specs")
        if out_specs is None and is_direct and pos:
            out_specs = pos[0]
        manual = _call_kw(node, "axis_names")
        manual_axes = None
        if manual is not None:
            got = consts.axis_strings(manual)
            if got is not None:
                manual_axes = frozenset(got)
        sites.append(_ShardMapSite(
            ctx, node, wrapped,
            resolver.resolve(ctx, mesh_expr, node),
            manual_axes, in_specs, out_specs))
    return sites


# --------------------------------------------------------- the analysis

@dataclasses.dataclass
class _Binding:
    """Axis-binding state of one function body."""
    axes: Set[str] = dataclasses.field(default_factory=set)
    has_binder: bool = False
    unknown: bool = False        # reached by a binder we cannot resolve

    def merge(self, other: "_Binding") -> bool:
        before = (len(self.axes), self.has_binder, self.unknown)
        self.axes |= other.axes
        self.has_binder |= other.has_binder
        self.unknown |= other.unknown
        return before != (len(self.axes), self.has_binder, self.unknown)


class _Analysis:
    """One whole-program sharding analysis over a module set."""

    def __init__(self, contexts: List[ModuleContext]):
        self.contexts = list(contexts)
        self.consts = _Consts(self.contexts)
        self.resolver = _MeshResolver(self.contexts, self.consts)
        self.findings: List[Finding] = []
        self.sites: Dict[str, List[_ShardMapSite]] = {}
        #: every axis any mesh/pmap/spec in the program declares — the
        #: fallback set for collectives in unwrapped library functions
        self.declared_axes: Set[str] = set()

    # ---------------------------------------------------------- helpers
    def _finding(self, rule: str, ctx: ModuleContext, node: ast.AST,
                 message: str) -> None:
        f = Finding(rule, ctx.path, getattr(node, "lineno", 1),
                    getattr(node, "col_offset", 0) + 1, message)
        if f not in self.findings:
            self.findings.append(f)

    def _spec_axes_in(self, node: Optional[ast.AST]
                      ) -> Iterator[Tuple[ast.Call, List[str]]]:
        """Every ``P(...)``/``PartitionSpec(...)`` call under ``node``
        with its constant-resolvable axis names."""
        if node is None:
            return
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            la = last_attr(sub.func)
            if la not in ("P", "PartitionSpec"):
                continue
            axes: List[str] = []
            for arg in sub.args:
                got = self.consts.axis_strings(arg)
                if got:
                    axes.extend(got)
            yield sub, axes

    # -------------------------------------------------------------- run
    def run(self) -> List[Finding]:
        for ctx in self.contexts:
            self.sites[ctx.path] = _shard_map_sites(
                ctx, self.resolver, self.consts)
        self._collect_declared_axes()
        bindings = self._infer_bindings()
        for ctx in self.contexts:
            self._check_unbound_axes(ctx, bindings)
            self._check_shard_map_sites(ctx)
            self._check_hot_steps(ctx)
            self._check_donation(ctx)
        return self._apply_marks()

    # ------------------------------------------------- declared axis set
    def _collect_declared_axes(self) -> None:
        for axes in self.resolver.factories.values():
            self.declared_axes |= axes
        for ctx in self.contexts:
            for node in ast.walk(ctx.tree):
                if not isinstance(node, ast.Call):
                    continue
                got = _mesh_ctor_axes(node, self.consts)
                if got:
                    self.declared_axes.update(got)
                la = last_attr(node.func)
                if la == "pmap" or _is_partial_of(node, "pmap"):
                    axis = _call_kw(node, "axis_name")
                    got = self.consts.axis_strings(axis)
                    if got:
                        self.declared_axes.update(got)
            for site in self.sites[ctx.path]:
                if site.mesh_axes:
                    self.declared_axes |= site.mesh_axes
                if site.manual_axes:
                    self.declared_axes |= site.manual_axes
                for spec_expr in (site.in_specs, site.out_specs):
                    for _call, axes in self._spec_axes_in(spec_expr):
                        self.declared_axes.update(axes)

    # ------------------------------------------- rule 1: axis bindings
    def _infer_bindings(self) -> Dict[int, _Binding]:
        bindings: Dict[int, _Binding] = {}

        def bind(fn: Optional[ast.AST],
                 axes: Optional[FrozenSet[str]]) -> None:
            if fn is None:
                return
            b = bindings.setdefault(id(fn), _Binding())
            b.has_binder = True
            if axes is None:
                b.unknown = True
            else:
                b.axes |= axes

        for ctx in self.contexts:
            for site in self.sites[ctx.path]:
                bind(site.wrapped, site.bound_axes)
            for node in ast.walk(ctx.tree):
                if not isinstance(node, ast.Call):
                    continue
                la = last_attr(node.func)
                if la == "pmap" or _is_partial_of(node, "pmap"):
                    axis = _call_kw(node, "axis_name")
                    got = self.consts.axis_strings(axis)
                    axes = frozenset(got) if got is not None else None
                    # pmap(fn, ...) call / @partial(pmap, ...) decorator
                    target: Optional[ast.AST] = None
                    parent = ctx.parent(node)
                    if isinstance(parent, _FuncDef) \
                            and node in parent.decorator_list:
                        target = parent
                    elif la == "pmap" and node.args:
                        cand = node.args[0]
                        if isinstance(cand, ast.Lambda):
                            target = cand
                        elif isinstance(cand, ast.Name):
                            for fn in ctx.functions():
                                if getattr(fn, "name", None) == cand.id:
                                    target = fn
                                    break
                    bind(target, axes)

        # interprocedural fixpoint: lexical nesting + same-file
        # bare-name / self.method calls flow the caller's binding in
        changed = True
        while changed:
            changed = False
            for ctx in self.contexts:
                by_name: Dict[str, List[ast.AST]] = {}
                for fn in ctx.functions():
                    if not isinstance(fn, ast.Lambda):
                        by_name.setdefault(fn.name, []).append(fn)
                for fn in ctx.functions():
                    src = bindings.get(id(fn))
                    if src is None or not src.has_binder:
                        continue
                    for node in ast.walk(fn):
                        if isinstance(node, _FuncNode) and node is not fn:
                            dst = bindings.setdefault(id(node),
                                                      _Binding())
                            if dst.merge(src):
                                changed = True
                        if isinstance(node, ast.Call):
                            callee = None
                            if isinstance(node.func, ast.Name):
                                callee = node.func.id
                            elif (isinstance(node.func, ast.Attribute)
                                  and isinstance(node.func.value,
                                                 ast.Name)
                                  and node.func.value.id == "self"):
                                callee = node.func.attr
                            for cand in by_name.get(callee or "", ()):
                                if cand is fn:
                                    continue
                                dst = bindings.setdefault(id(cand),
                                                          _Binding())
                                if dst.merge(src):
                                    changed = True
        return bindings

    def _collective_axis_args(self, call: ast.Call
                              ) -> Optional[List[str]]:
        la = last_attr(call.func)
        pos = _COLLECTIVES.get(la or "")
        if pos is None:
            return None
        axis = _call_kw(call, "axis_name")
        if axis is None and len(call.args) > pos:
            axis = call.args[pos]
        if axis is None:
            return None
        return self.consts.axis_strings(axis)

    def _check_unbound_axes(self, ctx: ModuleContext,
                            bindings: Dict[int, _Binding]) -> None:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            axes = self._collective_axis_args(node)
            if not axes:
                continue
            fn = ctx.enclosing_function(node)
            state = _Binding()
            cur = fn
            while cur is not None:
                b = bindings.get(id(cur))
                if b is not None:
                    state.merge(b)
                cur = ctx.enclosing_function(cur)
            if state.unknown:
                continue
            la = last_attr(node.func)
            for axis in axes:
                if state.has_binder and axis not in state.axes:
                    self._finding(
                        "unbound-axis-name", ctx, node,
                        f"`{la}` names axis '{axis}' but the enclosing "
                        f"shard_map/pmap binds only "
                        f"{sorted(state.axes) or '[]'} — a typo'd axis "
                        f"fails only at trace time (or silently no-ops "
                        f"on a 1-sized axis)")
                elif not state.has_binder \
                        and axis not in self.declared_axes:
                    self._finding(
                        "unbound-axis-name", ctx, node,
                        f"`{la}` names axis '{axis}' but no mesh, "
                        f"shard_map or pmap anywhere in the program "
                        f"declares that axis (declared: "
                        f"{sorted(self.declared_axes) or '[]'}) — "
                        f"likely a typo'd axis name")

    # ----------------------------------- rules 2+3: shard_map contracts
    def _check_shard_map_sites(self, ctx: ModuleContext) -> None:
        for site in self.sites[ctx.path]:
            self._check_spec_mesh(site)
            self._check_out_spec_replication(site)

    def _check_spec_mesh(self, site: _ShardMapSite) -> None:
        mesh_axes = site.mesh_axes
        if mesh_axes is not None:
            for spec_expr in (site.in_specs, site.out_specs):
                for call, axes in self._spec_axes_in(spec_expr):
                    for axis in axes:
                        if axis not in mesh_axes:
                            self._finding(
                                "spec-mesh-mismatch", site.ctx, call,
                                f"P(...) names axis '{axis}' which the "
                                f"mesh in scope does not have (mesh "
                                f"axes: {sorted(mesh_axes)}) — this "
                                f"spec cannot commit and the value "
                                f"falls back to replication")
        # arity: literal in_specs tuple vs the wrapped fn's signature
        fn = site.wrapped
        if fn is None or isinstance(fn, ast.Lambda) \
                or not isinstance(site.in_specs, (ast.Tuple, ast.List)):
            return
        args = fn.args
        if args.vararg is not None or args.kwarg is not None:
            return
        params = [a.arg for a in
                  list(args.posonlyargs) + list(args.args)
                  if a.arg not in ("self", "cls")]
        total = len(params)
        required = total - len(args.defaults)
        n = len(site.in_specs.elts)
        if n < required or n > total:
            self._finding(
                "spec-mesh-mismatch", site.ctx, site.in_specs,
                f"in_specs has {n} entr{'y' if n == 1 else 'ies'} but "
                f"`{site.ctx.func_name(fn)}` takes "
                f"{total if total == required else f'{required}..{total}'}"
                f" positional argument(s) — the zip misaligns specs "
                f"and operands")

    def _sharded_param_names(self, site: _ShardMapSite) -> Set[str]:
        """Wrapped-fn params whose in_spec is (or may be) sharded."""
        fn = site.wrapped
        if fn is None:
            return set()
        args = fn.args
        params = [a.arg for a in
                  list(args.posonlyargs) + list(args.args)
                  if a.arg not in ("self", "cls")]
        if not isinstance(site.in_specs, (ast.Tuple, ast.List)):
            # unknown spec shape: assume every param may be sharded
            return set(params)
        sharded: Set[str] = set()
        for param, elt in zip(params, site.in_specs.elts):
            if self._spec_is_replicated(elt):
                continue
            sharded.add(param)
        return sharded

    def _spec_is_replicated(self, elt: ast.AST) -> bool:
        """True only for a *provably* replicated spec element: ``P()``
        / ``P(None, ...)`` with no axis names."""
        if isinstance(elt, ast.Call) \
                and last_attr(elt.func) in ("P", "PartitionSpec"):
            return all(isinstance(a, ast.Constant) and a.value is None
                       for a in elt.args)
        if isinstance(elt, ast.Constant) and elt.value is None:
            return True
        return False

    def _check_out_spec_replication(self, site: _ShardMapSite) -> None:
        fn = site.wrapped
        if fn is None or isinstance(fn, ast.Lambda) \
                or site.out_specs is None:
            return
        sharded = self._sharded_param_names(site)
        if not sharded:
            return
        tainted = self._shard_taint(fn, sharded)
        returns = [n for n in ast.walk(fn) if isinstance(n, ast.Return)
                   and n.value is not None]
        if not returns:
            return

        def element_checks(out_elt: ast.AST, ret_expr: ast.AST) -> None:
            if not self._spec_is_replicated(out_elt):
                return
            if self._contains_reduction(ret_expr):
                return
            if self._divergent_expr(ret_expr, tainted):
                self._finding(
                    "unreplicated-out-spec", site.ctx, out_elt,
                    f"out_spec claims replication (P()) but "
                    f"`{site.ctx.func_name(fn)}` returns a value "
                    f"derived from sharded inputs with no "
                    f"psum/all_gather on the return path — each shard "
                    f"returns a DIFFERENT value; jax's "
                    f"check_vma/check_rep rejects this at trace time "
                    f"(see docs/graftlint.md)")

        for ret in returns:
            out = site.out_specs
            if isinstance(out, (ast.Tuple, ast.List)) \
                    and isinstance(ret.value, (ast.Tuple, ast.List)) \
                    and len(out.elts) == len(ret.value.elts):
                for out_elt, ret_elt in zip(out.elts, ret.value.elts):
                    element_checks(out_elt, ret_elt)
            else:
                element_checks(out, ret.value)

    def _contains_reduction(self, expr: ast.AST) -> bool:
        for node in ast.walk(expr):
            if isinstance(node, ast.Call) \
                    and last_attr(node.func) in _REDUCING:
                return True
        return False

    def _divergent_expr(self, expr: Optional[ast.AST],
                        tainted: Set[str]) -> bool:
        """Does ``expr`` carry shard-divergent data derived from
        ``tainted`` names?  Reducing collectives sanitize (a psum'd
        value is shard-uniform again), and so does any call we cannot
        see into (``pipeline_fn(...)``, a helper from another module —
        it may reduce internally; flagging through it would make every
        composed pipeline a false positive).  Element-wise jnp/lax/np
        math and method calls (``x.sum()`` is a LOCAL reduce — still
        per-shard) propagate."""
        if expr is None or not isinstance(expr, ast.AST):
            return False
        if isinstance(expr, ast.Name):
            return expr.id in tainted
        if isinstance(expr, ast.Call):
            la = last_attr(expr.func)
            if la in _REDUCING:
                return False
            d = dotted_name(expr.func) or ""
            root = d.split(".", 1)[0]
            operands = (list(expr.args)
                        + [k.value for k in expr.keywords])
            if la in _PERMUTING:
                # a permute moves shard-divergent data between shards
                # — the output is exactly as divergent as the input,
                # whichever spelling (bare `ppermute(...)` included:
                # without this branch it would fall through to the
                # unknown-callee sanitizer below)
                return any(self._divergent_expr(a, tainted)
                           for a in operands)
            if root in ("jnp", "lax", "np", "jax", "numpy"):
                return any(self._divergent_expr(a, tainted)
                           for a in operands)
            if isinstance(expr.func, ast.Attribute):
                # x.sum() / x.reshape(...) — a method of the operand
                return self._divergent_expr(expr.func.value, tainted) \
                    or any(self._divergent_expr(a, tainted)
                           for a in operands)
            return False          # unknown callee: may reduce inside
        return any(self._divergent_expr(c, tainted)
                   for c in ast.iter_child_nodes(expr)
                   if isinstance(c, ast.AST))

    def _shard_taint(self, fn: ast.AST, seeds: Set[str]) -> Set[str]:
        """Names derived (visibly) from sharded params."""
        tainted = set(seeds)
        for _ in range(2):        # two passes ≈ fixpoint, like core
            for node in ast.walk(fn):
                targets: List[ast.AST] = []
                value: Optional[ast.AST] = None
                if isinstance(node, ast.Assign):
                    targets, value = node.targets, node.value
                elif isinstance(node, ast.AugAssign):
                    targets, value = [node.target], node.value
                elif isinstance(node, ast.NamedExpr):
                    targets, value = [node.target], node.value
                if value is not None \
                        and self._divergent_expr(value, tainted):
                    for t in targets:
                        for name in self._target_names(t):
                            tainted.add(name)
        return tainted

    @staticmethod
    def _target_names(target: ast.AST) -> Iterator[str]:
        if isinstance(target, ast.Name):
            yield target.id
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                yield from _Analysis._target_names(elt)
        elif isinstance(target, ast.Starred):
            yield from _Analysis._target_names(target.value)

    # -------------------------------------- rule 4: host-sync-in-step
    def _jit_map(self, ctx: ModuleContext
                 ) -> Dict[str, Tuple[int, ...]]:
        """``name``/``self.attr`` → donated positions for every
        assignment of a jit/retrace_guard-wrapped callable (donation
        tuple empty when none declared).  Shared by rules 4 and 5."""
        out: Dict[str, Tuple[int, ...]] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call):
                donate = self._donated_positions(node.value)
                if donate is None:
                    continue
                for t in node.targets:
                    key = _key(t)
                    if key:
                        out[key] = donate
            elif isinstance(node, _FuncDef):
                # @jax.jit / @partial(jax.jit, donate_argnums=...) defs
                for dec in node.decorator_list:
                    if isinstance(dec, ast.Call):
                        donate = self._donated_positions(dec)
                        if donate is not None:
                            out[node.name] = donate
                    elif last_attr(dec) in ("jit", "pjit"):
                        out.setdefault(node.name, ())
        return out

    def _donated_positions(self, call: ast.Call
                           ) -> Optional[Tuple[int, ...]]:
        """() for a jit-family call without donation; (i, ...) with;
        None when the call is not jit-like at all."""
        la = last_attr(call.func)
        is_jit = la in ("jit", "pjit", "retrace_guard") \
            or _is_partial_of(call, "jit") or _is_partial_of(call, "pjit")
        if not is_jit:
            return None
        donate = _call_kw(call, "donate_argnums")
        if donate is None:
            return ()
        if isinstance(donate, ast.Constant) \
                and isinstance(donate.value, int):
            return (donate.value,)
        if isinstance(donate, (ast.Tuple, ast.List)):
            out = []
            for elt in donate.elts:
                if isinstance(elt, ast.Constant) \
                        and isinstance(elt.value, int):
                    out.append(elt.value)
                else:
                    return ()
            return tuple(out)
        return ()

    def _check_hot_steps(self, ctx: ModuleContext) -> None:
        jit_map = self._jit_map(ctx)
        for fn in ctx.functions():
            if isinstance(fn, ast.Lambda):
                continue
            if not any(m == "hot-step" for m, _ in
                       _marks_for_line(ctx, fn.lineno)):
                continue
            self._check_hot_step_body(ctx, fn, jit_map)

    def _check_hot_step_body(self, ctx: ModuleContext, fn: ast.AST,
                             jit_map: Dict[str, Tuple[int, ...]]
                             ) -> None:
        # device-derived values: results of calls to jit-wrapped
        # callables (incl. self._step attrs) and jnp/jax ops, flowed
        # forward through assignments
        tainted: Set[str] = set()

        def sync_kind(node: ast.Call) -> Optional[str]:
            d = dotted_name(node.func) or ""
            la = last_attr(node.func)
            if d in ("np.asarray", "numpy.asarray", "np.array",
                     "numpy.array"):
                return d
            if d in ("jax.device_get", "device_get"):
                return "jax.device_get"
            if isinstance(node.func, ast.Name) \
                    and node.func.id in ("float", "int", "bool"):
                return f"{node.func.id}()"
            if la == "item":
                return ".item()"
            if la == "callback" and "debug" in d:
                return d
            return None

        def device_expr(expr: ast.AST) -> bool:
            if isinstance(expr, ast.Call):
                if sync_kind(expr) is not None:
                    return False  # the sync materializes a host value
                key = _key(expr.func)
                if key is not None and key in jit_map:
                    return True
                d = dotted_name(expr.func)
                if d and (d.startswith("jnp.") or d.startswith("jax.")
                          or d.startswith("lax.")):
                    return True
            if isinstance(expr, (ast.Name, ast.Attribute)):
                key = _key(expr)
                return key in tainted
            return any(device_expr(c) for c in ast.iter_child_nodes(expr)
                       if isinstance(c, ast.AST))

        checked: Set[int] = set()

        def check_sync(call: ast.Call) -> None:
            if id(call) in checked:
                return
            checked.add(id(call))
            sync = sync_kind(call)
            if sync is None:
                return
            args = list(call.args) + [k.value for k in call.keywords]
            if last_attr(call.func) == "item":
                args.append(call.func.value)
            if not any(device_expr(a) for a in args):
                return
            self._finding(
                "host-sync-in-step", ctx, call,
                f"`{sync}` on a device value inside "
                f"`{ctx.func_name(fn)}` (# graftlint: hot-step) forces "
                f"a device→host sync every step — batch the read, keep "
                f"it on device, or justify it with `# graftlint: "
                f"unsharded(<why>)`")

        own = [n for n in ast.walk(fn)
               if ctx.enclosing_function(n) is fn
               or n is fn]
        # forward taint over the fn's own statements (nested defs are
        # traced callees, checked by host-sync-in-trace instead)
        for node in sorted(own, key=lambda n: (getattr(n, "lineno", 0),
                                               getattr(n, "col_offset",
                                                       0))):
            if isinstance(node, ast.Assign):
                # the RHS evaluates before the targets rebind: check
                # its syncs against the pre-assignment taint, THEN let
                # a host-valued RHS (e.g. a device_get) clear the
                # targets and a device RHS taint them
                for sub in ast.walk(node.value):
                    if isinstance(sub, ast.Call):
                        check_sync(sub)
                is_dev = device_expr(node.value)
                for t in node.targets:
                    keys = [k for k in [_key(t)] if k]
                    keys.extend(self._target_names(t))
                    for key in keys:
                        (tainted.add if is_dev
                         else tainted.discard)(key)
            elif isinstance(node, ast.Call):
                check_sync(node)

    # ------------------------------------ rule 5: donation-after-use
    def _check_donation(self, ctx: ModuleContext) -> None:
        jit_map = {k: v for k, v in self._jit_map(ctx).items() if v}
        for fn in ctx.functions():
            if isinstance(fn, ast.Lambda):
                continue
            own = [n for n in ast.walk(fn)
                   if ctx.enclosing_function(n) is fn]
            for node in own:
                if not isinstance(node, ast.Call):
                    continue
                donate: Optional[Tuple[int, ...]] = None
                key = _key(node.func)
                if key is not None and key in jit_map:
                    donate = jit_map[key]
                elif isinstance(node.func, ast.Call):
                    donate = self._donated_positions(node.func) or None
                if not donate:
                    continue
                self._check_donated_call(ctx, fn, node, donate, own)

    def _check_donated_call(self, ctx: ModuleContext, fn: ast.AST,
                            call: ast.Call, donate: Tuple[int, ...],
                            own: List[ast.AST]) -> None:
        line = getattr(call, "lineno", 0)
        # keys rebound by the very statement holding the call (the
        # `state = step(state, ...)` idiom) are fresh afterwards
        rebound: Set[str] = set()
        stmt = ctx.parent(call)
        while stmt is not None and not isinstance(stmt, ast.stmt):
            stmt = ctx.parent(stmt)
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                k = _key(t)
                if k:
                    rebound.add(k)
                rebound.update(self._target_names(t))
        # the call's own argument list can span lines — those reads
        # happen BEFORE the donation, never after it
        in_call = {id(n) for n in ast.walk(call)}
        for pos in donate:
            if pos >= len(call.args):
                continue
            key = _key(call.args[pos])
            if key is None or key in rebound:
                continue
            # first later touch wins: a Store clears, a Load flags
            events: List[Tuple[int, int, str, ast.AST]] = []
            for node in own:
                if id(node) in in_call or _key(node) != key:
                    continue
                nline = getattr(node, "lineno", 0)
                if nline <= line:
                    continue
                kind = "store" if isinstance(
                    getattr(node, "ctx", None),
                    (ast.Store, ast.Del)) else "load"
                events.append((nline, getattr(node, "col_offset", 0),
                               kind, node))
            for nline, _col, kind, node in sorted(
                    events, key=lambda e: (e[0], e[1])):
                if kind == "store":
                    break
                self._finding(
                    "donation-after-use", ctx, node,
                    f"`{key}` was donated (donate_argnums position "
                    f"{pos}) to the call on line {line} — its buffer "
                    f"is dead here; reading it returns garbage or "
                    f"raises on TPU.  Rebind it from the call's "
                    f"output or drop the donation")
                break

    # ------------------------------------------------- mark application
    def _apply_marks(self) -> List[Finding]:
        out: List[Finding] = []
        by_path = {ctx.path: ctx for ctx in self.contexts}
        for f in self.findings:
            ctx = by_path.get(f.path)
            if ctx is None:
                out.append(f)
                continue
            marks = [why for mark, why in _marks_for_line(ctx, f.line)
                     if mark == "unsharded"]
            if not marks:
                out.append(f)
            elif any(why for why in marks):
                continue                    # justified exception
            else:
                out.append(Finding(
                    f.rule, f.path, f.line, f.col,
                    f"marked unsharded() with no justification — the "
                    f"reason is the point of the annotation; say why "
                    f"this placement/sync is deliberate"))
        return out


def analyze_program(contexts: List[ModuleContext]) -> List[Finding]:
    """Run the sharding analysis; returns every finding (all five
    rules) unfiltered — the runner applies suppressions."""
    return _Analysis(list(contexts)).run()


# --------------------------------------------------------- program rules

class _ShardingRule(ProgramRule):
    """Shared driver: the analysis runs once per program (memoized on
    the Program object by :meth:`prepare`, timed under the
    ``sharding-pass`` row); each registered rule yields its slice."""

    shared_pass = "sharding-pass"

    def prepare(self, program) -> None:
        if getattr(program, "_sharding_findings", None) is None:
            program._sharding_findings = analyze_program(
                program.contexts)

    def check_program(self, program) -> Iterator[Finding]:
        self.prepare(program)
        for finding in program._sharding_findings:
            if finding.rule == self.name:
                yield finding


@register_program
class UnboundAxisName(_ShardingRule):
    """Rule S1 — a collective naming an axis nothing binds.

    ``psum``/``all_gather``/``all_to_all``/``ppermute``/``axis_index``
    (etc.) naming an axis the enclosing shard_map/pmap does not bind —
    or, for unwrapped library functions, an axis no mesh anywhere in
    the program declares.  The typo class that today fails only at
    trace time, or silently no-ops on a 1-sized axis.
    """

    name = "unbound-axis-name"
    summary = ("collective names an axis no enclosing shard_map/pmap "
               "binds (or no mesh in the program declares)")


@register_program
class SpecMeshMismatch(_ShardingRule):
    """Rule S2 — PartitionSpec axes absent from the mesh in scope, or
    in_specs arity misaligned with the wrapped function's signature.

    A ``P("tenosr")`` against a ``("data", "tensor")`` mesh cannot
    commit — the value silently falls back to replication; a spec
    tuple shorter/longer than the operand list zips wrong.
    """

    name = "spec-mesh-mismatch"
    summary = ("P(...) axis not in the mesh in scope, or "
               "in_specs/out_specs arity vs the wrapped signature")


@register_program
class UnreplicatedOutSpec(_ShardingRule):
    """Rule S3 — out_spec claims replication for a shard-divergent
    value.

    ``out_specs=P()`` asserts every shard returns the SAME value; a
    return derived from sharded inputs with no psum/all_gather on the
    path violates that — the shape ``check_vma`` (``check_rep`` on
    older jax, via ``jax_compat``) rejects at trace time.
    """

    name = "unreplicated-out-spec"
    summary = ("out_specs=P() on a value computed from sharded inputs "
               "with no reduction on the return path")


@register_program
class HostSyncInStep(_ShardingRule):
    """Rule S4 — device→host sync inside a ``hot-step`` function.

    ``np.asarray``/``float()``/``.item()``/``jax.device_get``/debug
    callbacks on device values inside a function marked ``# graftlint:
    hot-step`` (engine decode steps, train steps, bench legs) force a
    per-step sync; deliberate end-of-step reads carry ``# graftlint:
    unsharded(<why>)``.
    """

    name = "host-sync-in-step"
    summary = ("device->host sync on a device value inside a "
               "# graftlint: hot-step function")


@register_program
class DonationAfterUse(_ShardingRule):
    """Rule S5 — a donated buffer read after the donating call.

    An argument at a ``donate_argnums`` position is dead once the call
    returns: XLA may have aliased its buffer into the outputs.  A later
    read in the same scope (without rebinding from the call's result)
    returns garbage on TPU.
    """

    name = "donation-after-use"
    summary = ("buffer passed under donate_argnums read after the "
               "donating call in the same scope")
