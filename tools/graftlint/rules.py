"""graftlint rule set — JAX/TPU trace-hygiene checks.

Each rule targets a retrace / trace-time-capture hazard observed (or
nearly shipped) in this codebase; ``docs/graftlint.md`` documents them
with fix recipes.  Suppress a deliberate exception with
``# graftlint: disable=<rule>`` on (or directly above) the line.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional, Set

from tools.graftlint.core import (
    Finding, ModuleContext, Rule, register, dotted_name, last_attr,
    expr_tainted, closure_taint,
)

__all__ = []  # rules self-register; nothing to import by name


def _is_env_read(node: ast.AST) -> bool:
    """``os.environ[...]`` / ``os.environ.get(...)`` / ``os.getenv(...)``."""
    if isinstance(node, ast.Subscript):
        return dotted_name(node.value) in ("os.environ", "environ")
    if isinstance(node, ast.Call):
        d = dotted_name(node.func)
        if d in ("os.getenv", "getenv"):
            return True
        if d in ("os.environ.get", "environ.get"):
            return True
        # environ.get via attribute on the environ object
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in ("get", "__getitem__") \
                and dotted_name(node.func.value) in ("os.environ",
                                                     "environ"):
            return True
    return False


def _jit_call_sites(ctx: ModuleContext) -> Iterator[ast.Call]:
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call) \
                and last_attr(node.func) in ("jit", "pjit"):
            yield node


def _wrapped_def(ctx: ModuleContext,
                 call: ast.Call) -> Optional[ast.AST]:
    """The same-file ``def`` wrapped by a jit/pjit call, if resolvable."""
    if not call.args:
        return None
    target = call.args[0]
    if isinstance(target, ast.Lambda):
        return target
    if isinstance(target, ast.Name):
        for fn in ctx.functions():
            if getattr(fn, "name", None) == target.id:
                return fn
    return None


def _jitted_defs(ctx: ModuleContext):
    """(def, jit_call_or_decorator) for every jitted function whose
    definition is visible in this file."""
    seen = set()
    for fn in ctx.functions():
        for dec in getattr(fn, "decorator_list", ()):
            site = dec
            if isinstance(dec, ast.Call):
                # @partial(jax.jit, ...) — the partial call holds kwargs
                if last_attr(dec.func) == "partial" and dec.args \
                        and last_attr(dec.args[0]) in ("jit", "pjit"):
                    yield fn, dec
                    seen.add(fn)
                    break
                if last_attr(dec.func) in ("jit", "pjit"):
                    yield fn, dec
                    seen.add(fn)
                    break
            elif last_attr(dec) in ("jit", "pjit"):
                yield fn, dec
                seen.add(fn)
                break
    for call in _jit_call_sites(ctx):
        fn = _wrapped_def(ctx, call)
        if fn is not None and fn not in seen:
            seen.add(fn)
            yield fn, call


# ------------------------------------------------------------------ rule 1

@register
class EnvReadInTrace(Rule):
    """Rule 1 — ``os.environ`` read on a trace path.

    The value is captured into the jaxpr at *trace* time: flipping the
    variable later is a silent no-op (jit caches replay the old value),
    and two processes differing only by env silently compute different
    numerics (the ``APEX_TPU_DECODE_ATTN`` bug, ADVICE round 5).
    """

    name = "env-read-in-trace"
    summary = ("os.environ/getenv read inside traced code — the value "
               "is frozen into the compiled function")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        has_trace_paths = ctx.defines_trace_paths()
        for node in ast.walk(ctx.tree):
            if not _is_env_read(node):
                continue
            if ctx.is_traced(node):
                yield self.finding(
                    ctx, node,
                    "environment read inside a traced function: the "
                    "value is captured at trace time and frozen into "
                    "every cached executable — plumb it through config "
                    "or a static argument instead")
            elif has_trace_paths and ctx.enclosing_function(node) is None:
                # module-level reads in a module that defines trace
                # paths: import-time capture — legal but worth a look
                yield self.finding(
                    ctx, node,
                    "module-level environment read in a module that "
                    "defines traced code: the value is captured at "
                    "import time — make sure no trace path depends on "
                    "it changing")


# ------------------------------------------------------------------ rule 2

@register
class TracedBranch(Rule):
    """Rule 2 — python ``if``/``while`` on a traced value.

    Branching on data raises ``TracerBoolConversionError`` at best; at
    worst (weak types, ``shape[0]`` confusion) it silently bakes one
    branch into the program.  Use ``jnp.where``/``lax.cond``/
    ``lax.select`` instead.
    """

    name = "traced-branch"
    summary = ("python if/while on a value derived from traced "
               "arguments — use jnp.where / lax.cond")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for fn in ctx.traced_entries():
            if isinstance(fn, ast.Lambda):
                continue
            tainted = closure_taint(ctx, fn)
            for node in ast.walk(fn):
                if not isinstance(node, (ast.If, ast.While)):
                    continue
                # branches in nested non-entry defs (inner loss_fn
                # closures, scan bodies) belong to this entry's trace;
                # nested *entries* are covered by their own iteration
                if not ctx.owns(fn, node):
                    continue
                if self._cond_tainted(node.test, tainted):
                    yield self.finding(
                        ctx, node,
                        f"`{'if' if isinstance(node, ast.If) else 'while'}`"
                        " condition derives from a traced argument — "
                        "python control flow runs at trace time; use "
                        "jnp.where / lax.cond / lax.select")

    @staticmethod
    def _cond_tainted(test: ast.AST, tainted: set) -> bool:
        """Taint of a branch condition, ignoring the static idioms:
        ``x is (not) None``, isinstance/hasattr/callable checks."""
        if isinstance(test, ast.Compare) and all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops):
            return False
        if isinstance(test, ast.Call) and isinstance(test.func, ast.Name) \
                and test.func.id in ("isinstance", "hasattr", "callable"):
            return False
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            return TracedBranch._cond_tainted(test.operand, tainted)
        if isinstance(test, ast.BoolOp):
            return any(TracedBranch._cond_tainted(v, tainted)
                       for v in test.values)
        return expr_tainted(test, tainted)


# ------------------------------------------------------------------ rule 3

_UNHASHABLE = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp,
               ast.SetComp)


@register
class JitUnhashableDefault(Rule):
    """Rule 3a — jitted function with dict/list/set default args.

    Mutable defaults reach jit as traced operands with a fresh identity
    per call path, or blow up as unhashable static args — either way
    the executable cache can never hit reliably.
    """

    name = "jit-unhashable-default"
    summary = ("jitted function takes dict/list/set default arguments "
               "that defeat the executable cache")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for fn, site in _jitted_defs(ctx):
            args = fn.args
            defaults = list(args.defaults) + [
                d for d in args.kw_defaults if d is not None]
            for d in defaults:
                if isinstance(d, _UNHASHABLE) or (
                        isinstance(d, ast.Call)
                        and isinstance(d.func, ast.Name)
                        and d.func.id in ("dict", "list", "set")):
                    yield self.finding(
                        ctx, d,
                        f"jitted `{ctx.func_name(fn)}` has a mutable "
                        "container default — unhashable as a static "
                        "arg and identity-fresh as a traced one; pass "
                        "it explicitly or use a frozen/hashable value")


# ------------------------------------------------------------------ rule 3b

#: parameter names that mark a train-step-shaped signature whose input
#: buffers are conventionally dead after the call
_DONATABLE_PARAMS = {"state", "train_state", "opt_state", "cache",
                     "carry"}


@register
class JitMissingDonate(Rule):
    """Rule 3b — train-step-shaped jit without buffer donation.

    A step function that threads ``state``/``opt_state``/``cache``
    through itself holds both the old and new copy live across the
    call without ``donate_argnums`` — on TPU that is the difference
    between fitting and OOMing the model (and a guaranteed extra
    HBM copy per step).
    """

    name = "jit-missing-donate"
    summary = ("train-step-shaped jit (state/opt_state/cache params) "
               "without donate_argnums/donate_argnames")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for fn, site in _jitted_defs(ctx):
            if isinstance(fn, ast.Lambda):
                continue
            params = [a.arg for a in (list(fn.args.posonlyargs)
                                      + list(fn.args.args))]
            hits = [p for p in params if p in _DONATABLE_PARAMS]
            if not hits:
                continue
            if self._has_donate(site):
                continue
            yield self.finding(
                ctx, fn,
                f"jitted `{ctx.func_name(fn)}` threads "
                f"`{'`/`'.join(hits)}` without donate_argnums — the "
                "old buffers stay live across the call, doubling "
                "their HBM footprint; donate them (or suppress if "
                "the input really is reused afterwards)")

    @staticmethod
    def _has_donate(site: ast.AST) -> bool:
        if isinstance(site, ast.Call):
            return any(k.arg in ("donate_argnums", "donate_argnames")
                       for k in site.keywords)
        return False  # bare @jax.jit decorator has no kwargs


# ------------------------------------------------------------------ rule 4

@register
class LruCacheHazard(Rule):
    """Rule 4 — ``functools.lru_cache`` with a key that cannot work.

    Two flavors: mutable-container defaults (raise ``TypeError:
    unhashable`` on first call, or worse, force callers to pass
    tuples that alias) and env-dependent bodies (the cache key omits
    the env, so a cached entry silently outlives an env flip — the
    ``generate()``/``_compiled_run`` + ``APEX_TPU_DECODE_ATTN``
    interaction).
    """

    name = "lru-cache-hazard"
    summary = ("lru_cache keyed on unhashable defaults or caching an "
               "env-dependent result")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for fn in ctx.functions():
            if isinstance(fn, ast.Lambda):
                continue
            if not self._lru_decorated(fn):
                continue
            args = fn.args
            defaults = list(args.defaults) + [
                d for d in args.kw_defaults if d is not None]
            for d in defaults:
                if isinstance(d, _UNHASHABLE):
                    yield self.finding(
                        ctx, d,
                        f"lru_cache-wrapped `{fn.name}` has a mutable "
                        "container default — unhashable, so the cache "
                        "raises (or the caller aliases); use a tuple "
                        "or hashable config object")
            for node in ast.walk(fn):
                if _is_env_read(node):
                    yield self.finding(
                        ctx, node,
                        f"lru_cache-wrapped `{fn.name}` reads the "
                        "environment: the env is not part of the cache "
                        "key, so a cached entry survives an env flip — "
                        "hoist the read to the caller and pass it as "
                        "an argument")

    @staticmethod
    def _lru_decorated(fn: ast.AST) -> bool:
        for dec in getattr(fn, "decorator_list", ()):
            target = dec.func if isinstance(dec, ast.Call) else dec
            if last_attr(target) in ("lru_cache", "cache"):
                return True
        return False


# ------------------------------------------------------------------ rule 5

_WALLCLOCK = {"time.time", "time.perf_counter", "time.monotonic",
              "time.time_ns", "time.perf_counter_ns",
              "datetime.now", "datetime.utcnow",
              "datetime.datetime.now", "datetime.datetime.utcnow"}


@register
class TimeInTrace(Rule):
    """Rule 5 — wall-clock / host RNG inside traced code.

    ``time.time()`` or ``np.random`` in a traced body executes ONCE at
    trace time; every compiled replay reuses that constant — timings
    read as zero and "random" draws repeat forever.  Use
    ``jax.random`` with threaded keys; time around the jit boundary.
    """

    name = "time-in-trace"
    summary = ("time.*/datetime.now/np.random inside traced code runs "
               "once at trace time and is baked in")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if not ctx.is_traced(node):
                continue
            d = dotted_name(node.func)
            if d in _WALLCLOCK:
                yield self.finding(
                    ctx, node,
                    f"`{d}()` inside a traced function executes once "
                    "at trace time — the compiled function replays a "
                    "constant; measure outside the jit boundary")
            elif d and (d.startswith("np.random.")
                        or d.startswith("numpy.random.")):
                yield self.finding(
                    ctx, node,
                    f"`{d}()` inside a traced function draws once at "
                    "trace time — every replay reuses the same "
                    "values; use jax.random with an explicit key")


# ------------------------------------------------------------------ rule 6

_HOST_CONVERSIONS = {"float", "int", "bool", "complex"}


@register
class HostSyncInTrace(Rule):
    """Rule 6 — host conversion of a traced value.

    ``.item()`` / ``float(x)`` / ``int(x)`` on a tracer either raises
    (``ConcretizationTypeError``) or — under ``jax.debug``-style
    escapes — forces a device→host sync that serializes the pipeline.
    """

    name = "host-sync-in-trace"
    summary = (".item()/float()/int() on a traced value — "
               "concretization error or a hidden host sync")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for fn in ctx.traced_entries():
            if isinstance(fn, ast.Lambda):
                continue
            tainted = closure_taint(ctx, fn)
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                if not ctx.owns(fn, node):
                    continue
                # x.item() / jax.device_get(x) on tainted x
                if isinstance(node.func, ast.Attribute) \
                        and node.func.attr == "item" \
                        and expr_tainted(node.func.value, tainted):
                    yield self.finding(
                        ctx, node,
                        ".item() on a traced value — raises under jit; "
                        "keep the value on device or return it")
                    continue
                d = dotted_name(node.func)
                if d in ("jax.device_get", "device_get") and node.args \
                        and expr_tainted(node.args[0], tainted):
                    yield self.finding(
                        ctx, node,
                        "jax.device_get on a traced value inside a "
                        "traced function — host sync; return the "
                        "value instead")
                    continue
                if isinstance(node.func, ast.Name) \
                        and node.func.id in _HOST_CONVERSIONS \
                        and len(node.args) == 1 \
                        and expr_tainted(node.args[0], tainted):
                    yield self.finding(
                        ctx, node,
                        f"{node.func.id}() on a traced value — "
                        "ConcretizationTypeError under jit; use "
                        "jnp/astype forms that stay on device")


# ------------------------------------------------------------------ rule 7

@register
class PrintInTrace(Rule):
    """Rule 7 — ``print``/f-string formatting of traced values.

    ``print`` in a traced body fires once at trace time (then never
    again), and formatting a tracer prints ``Traced<...>`` garbage.
    ``jax.debug.print`` is the in-graph equivalent.
    """

    name = "print-in-trace"
    summary = ("print()/f-string on traced values — fires at trace "
               "time only; use jax.debug.print")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        entries = ctx.traced_entries()
        for fn in ctx.traced_functions():
            if isinstance(fn, ast.Lambda):
                continue
            is_entry = fn in entries
            if not is_entry and ctx.nested_in_entry(fn):
                continue    # covered by the enclosing entry's walk
            tainted = closure_taint(ctx, fn) if is_entry else set()
            for node in ast.walk(fn):
                if is_entry:
                    if not ctx.owns(fn, node):
                        continue
                elif ctx.enclosing_function(node) is not fn:
                    continue
                if isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Name) \
                        and node.func.id == "print":
                    traced_args = any(expr_tainted(a, tainted)
                                      for a in node.args)
                    msg = ("print() of a traced value — prints "
                           "`Traced<...>` once at trace time; use "
                           "jax.debug.print"
                           if traced_args else
                           "print() inside a traced function fires at "
                           "trace time only (never per step); use "
                           "jax.debug.print or log outside the jit")
                    yield self.finding(ctx, node, msg)
                elif isinstance(node, ast.JoinedStr) and is_entry:
                    # f-strings inside raise/assert are trace-time
                    # validation — idiomatic, not a formatting bug
                    if self._in_raise_or_assert(ctx, node):
                        continue
                    if any(expr_tainted(v.value, tainted)
                           for v in node.values
                           if isinstance(v, ast.FormattedValue)):
                        yield self.finding(
                            ctx, node,
                            "f-string formats a traced value — "
                            "stringifies the tracer at trace time; "
                            "use jax.debug.print formatting")

    @staticmethod
    def _in_raise_or_assert(ctx: ModuleContext, node: ast.AST) -> bool:
        cur = ctx.parent(node)
        while cur is not None and not isinstance(
                cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            if isinstance(cur, (ast.Raise, ast.Assert)):
                return True
            cur = ctx.parent(cur)
        return False


# ------------------------------------------------------------------ rule 8

_MUTATORS = {"append", "extend", "add", "update", "setdefault", "pop",
             "insert", "remove", "clear", "popitem", "discard",
             "appendleft"}


@register
class MutableGlobalInTrace(Rule):
    """Rule 8 — module-level mutable state mutated from traced code.

    The mutation happens once per *trace*, not once per call — counters
    under-count, registries grow per retrace, and the compiled function
    never sees the updated value.  Thread state functionally or keep it
    strictly host-side.
    """

    name = "mutable-global-in-trace"
    summary = ("module-level mutable state mutated inside traced code "
               "— mutations run per trace, not per call")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        module_mutables = self._module_mutables(ctx)
        for fn in ctx.traced_functions():
            declared_global: Set[str] = {
                name
                for node in ast.walk(fn)
                if isinstance(node, ast.Global)
                for name in node.names}
            for node in ast.walk(fn):
                if ctx.enclosing_function(node) is not fn:
                    continue
                # global X; X = ... rebinding
                if isinstance(node, (ast.Assign, ast.AugAssign)):
                    targets = (node.targets
                               if isinstance(node, ast.Assign)
                               else [node.target])
                    for t in targets:
                        if isinstance(t, ast.Name) \
                                and t.id in declared_global:
                            yield self.finding(
                                ctx, node,
                                f"rebinds global `{t.id}` inside a "
                                "traced function — runs once per "
                                "trace, not per call")
                        # X[...] = ... on a module-level container
                        elif isinstance(t, ast.Subscript) \
                                and isinstance(t.value, ast.Name) \
                                and t.value.id in module_mutables \
                                and not self._is_local(fn, t.value.id):
                            yield self.finding(
                                ctx, node,
                                f"writes into module-level container "
                                f"`{t.value.id}` inside a traced "
                                "function — mutation happens at trace "
                                "time only")
                # X.append(...) etc. on a module-level container
                if isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and node.func.attr in _MUTATORS \
                        and isinstance(node.func.value, ast.Name) \
                        and node.func.value.id in module_mutables \
                        and not self._is_local(fn, node.func.value.id):
                    yield self.finding(
                        ctx, node,
                        f"mutates module-level container "
                        f"`{node.func.value.id}` inside a traced "
                        "function — mutation happens at trace time "
                        "only; thread state functionally")

    @staticmethod
    def _module_mutables(ctx: ModuleContext) -> Set[str]:
        names: Set[str] = set()
        for stmt in ctx.tree.body:
            targets = []
            if isinstance(stmt, ast.Assign):
                targets, value = stmt.targets, stmt.value
            elif isinstance(stmt, ast.AnnAssign) and stmt.value:
                targets, value = [stmt.target], stmt.value
            else:
                continue
            mutable = isinstance(value, _UNHASHABLE) or (
                isinstance(value, ast.Call)
                and isinstance(value.func, ast.Name)
                and value.func.id in ("dict", "list", "set",
                                      "defaultdict", "deque"))
            if not mutable:
                continue
            for t in targets:
                if isinstance(t, ast.Name):
                    names.add(t.id)
        return names

    @staticmethod
    def _is_local(fn: ast.AST, name: str) -> bool:
        """Shadowed by a local binding (param or assignment)?"""
        args = fn.args
        params = {a.arg for a in (list(args.posonlyargs) + list(args.args)
                                  + list(args.kwonlyargs))}
        if args.vararg:
            params.add(args.vararg.arg)
        if args.kwarg:
            params.add(args.kwarg.arg)
        if name in params:
            return True
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id == name:
                        return True
        return False
