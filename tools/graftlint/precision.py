"""graftlint precision pass — whole-program mixed-precision dtype-flow.

Mixed precision is the library's headline capability (amp O1/O2/O3,
dynamic loss scaling, fp32 master weights), and its failure mode is the
worst kind: a bf16 softmax reduction, an optimizer update applied to
non-master params, or grad clipping computed on *scaled* grads does not
crash — it silently bends the loss curve.  Trace hygiene (``rules.py``)
and thread hygiene (``concurrency.py``) are machine-checked; this pass
closes the third gap with an interprocedural **dtype-flow analysis**:

1. **A dtype lattice** — every expression is inferred to one of
   ``fp32`` (float32/64), ``low`` (bfloat16/float16), ``quant``
   (int8/uint8/fp8 *codes* — values that are meaningless without their
   scale), ``storage`` (a Pallas ``*_ref`` load — follows the pool /
   input dtype, so possibly low), ``safe`` (ints/bools — exact
   accumulation), or ``unknown``.  Facts flow from ``astype(...)``
   casts, ``dtype=`` / ``preferred_element_type=`` kwargs, array
   constructors, dtype-typed defaults, and assignments.

2. **Function summaries** — every program function's return lattice
   (tuples element-wise) is computed once, program-wide, so
   ``aux = top_k_gating(...)[2]`` in one file knows the helper in
   another returns fp32.  ``jax.vmap(f)(...)`` / ``jit(f)(...)``
   resolve through to ``f``'s summary.

3. **Rules** (each with flagged+clean fixtures in
   ``tests/test_graftlint.py``): ``bf16-unsafe-reduction``,
   ``master-weight-violation``, ``unscaled-grad-use``,
   ``redundant-cast``, ``quant-code-arith`` — see the class docstrings
   and the catalog in ``docs/graftlint.md``.

Annotation convention (mirroring the concurrency pass's guarded-by
discipline; trailing, or on a standalone comment line directly above):

- ``# graftlint: precision(master-fp32)`` on a ``def``: the function
  consumes master weights — no call site may pass a value inferred
  low/quant, and the body must not downcast a parameter.
- ``# graftlint: reduce-fp32`` on a reduction line (or its ``def``):
  asserts the accumulation is fp32 *by construction* in a way the
  lattice cannot see (an upstream contract, a log2-domain online
  softmax with an f32 accumulator held elsewhere).
- ``# graftlint: lowprec(<why>)`` on a line (or ``def``): a justified
  deliberate low-precision / code-arithmetic exception.  The reason is
  mandatory — an empty ``lowprec()`` is itself flagged, exactly like
  an empty ``unguarded()``.

The runtime twin is :mod:`apex_tpu.utils.numcheck` (the lockcheck
mold): it hooks the amp cast boundaries, the optimizer step and the
loss-scale path and records per-site dtype histograms, non-finite
counts and the grad underflow-to-zero fraction, so the static
convention and the runtime verifier converge from both directions under
the strict chaos soaks.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple, Union

from tools.graftlint.core import (
    Finding,
    ModuleContext,
    ProgramRule,
    closure_taint,
    expr_tainted,
    last_attr,
    register_program,
)

__all__ = ["analyze_precision"]

# ---------------------------------------------------------------- lattice

FP32 = "fp32"
LOW = "low"
QUANT = "quant"
STORAGE = "storage"
SAFE = "safe"
UNKNOWN = "unknown"
NEUTRAL = "neutral"          # python scalars / dtype objects: join identity

Lat = str
LatOrTuple = Union[str, Tuple]

_FP32_NAMES = {"float32", "float64", "f32", "fp32", "double"}
_LOW_NAMES = {"bfloat16", "float16", "bf16", "fp16", "half"}
_QUANT_NAMES = {"int8", "uint8", "fp8", "float8_e4m3fn", "float8_e5m2",
                "float8_e4m3", "float8_e4m3b11fnuz", "float8_e5m2fnuz"}
_SAFE_NAMES = {"int16", "int32", "int64", "uint16", "uint32", "uint64",
               "bool", "bool_", "uint8_t"}


def _join(a: Lat, b: Lat) -> Lat:
    """Numpy-promotion-shaped join.  fp32 dominates (any float op with
    an fp32 operand promotes); ``safe`` ints are transparent;
    ``unknown`` is absorbing among the rest."""
    if a == NEUTRAL:
        return b
    if b == NEUTRAL:
        return a
    if a == b:
        return a
    if FP32 in (a, b):
        return FP32
    if UNKNOWN in (a, b):
        return UNKNOWN
    if SAFE in (a, b):                      # int op float -> the float
        return a if b == SAFE else b
    if {a, b} == {LOW, STORAGE}:
        return LOW                          # storage is at worst low
    return UNKNOWN                          # quant mixed with floats


def _collapse(lat: LatOrTuple) -> Lat:
    if isinstance(lat, tuple):
        out: Lat = NEUTRAL
        for el in lat:
            out = _join(out, _collapse(el))
        return out
    return lat


def _join_summaries(a: LatOrTuple, b: LatOrTuple) -> LatOrTuple:
    if isinstance(a, tuple) and isinstance(b, tuple) and len(a) == len(b):
        return tuple(_join_summaries(x, y) for x, y in zip(a, b))
    return _join(_collapse(a), _collapse(b))


# ------------------------------------------------------------------ marks

_MARK_RE = re.compile(
    r"graftlint:\s*(?:(precision)\(([^)]*)\)|(lowprec)\(([^)]*)\)"
    r"|(reduce-fp32))")


def _marks_for_line(ctx: ModuleContext, line: int) -> List[Tuple[str, str]]:
    """Precision marks on ``line`` — trailing, or on a *standalone*
    comment directly above (same attachment rule as the concurrency
    pass: a trailing comment on the previous code line never leaks)."""
    sup = ctx.suppressions
    text = sup.graftlint_comments.get(line, "")
    if line - 1 in sup.standalone_comment_lines:
        text += " " + sup.graftlint_comments.get(line - 1, "")
    out: List[Tuple[str, str]] = []
    for m in _MARK_RE.finditer(text):
        if m.group(1):
            out.append(("precision", m.group(2).strip()))
        elif m.group(3):
            out.append(("lowprec", m.group(4).strip()))
        elif m.group(5):
            out.append(("reduce-fp32", ""))
    return out


# ------------------------------------------------------- dtype resolution

def _dtype_name_lat(name: str) -> Optional[Lat]:
    low = name.lower()
    if low in _FP32_NAMES:
        return FP32
    if low in _LOW_NAMES:
        return LOW
    if low in _QUANT_NAMES:
        return QUANT
    if low in _SAFE_NAMES:
        return SAFE
    return None


def _dtype_from_expr(node: Optional[ast.AST],
                     dtype_env: Dict[str, Lat]) -> Optional[Lat]:
    """Lattice a dtype-denoting expression resolves to (``jnp.bfloat16``,
    ``"float32"``, a local bound to one), or None when unresolvable
    (``x.dtype``, an opaque variable)."""
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return _dtype_name_lat(node.value)
    if isinstance(node, ast.Name):
        hit = dtype_env.get(node.id)
        if hit is not None:
            return hit
        return _dtype_name_lat(node.id)
    if isinstance(node, ast.Attribute):
        if node.attr == "dtype":            # x.dtype: follows a value
            return None
        return _dtype_name_lat(node.attr)
    if isinstance(node, ast.Call):          # jnp.dtype(jnp.int8)
        la = _callee_name(node.func)
        if la == "dtype" and node.args:
            return _dtype_from_expr(node.args[0], dtype_env)
    return None


# ----------------------------------------------------------- op tables

#: reductions whose *accumulation* loses precision in a low dtype —
#: the rule-1 surface (max/min/argmax are exempt: no accumulation)
_MEAN_FAMILY = {"softmax", "log_softmax", "logsumexp", "logaddexp",
                "mean", "nanmean", "average", "var", "std", "nanvar",
                "nanstd"}
_SUM_FAMILY = {"sum", "nansum", "cumsum", "trace", "norm", "prod"}
_REDUCTIONS = _MEAN_FAMILY | _SUM_FAMILY

#: contractions: accumulator dtype set by preferred_element_type
_DOT_FAMILY = {"dot", "dot_general", "matmul", "einsum", "tensordot"}

#: dtype-preserving elementwise / structural ops the inference flows
#: through (collectives included: wire dtype == operand dtype)
_TRANSPARENT = {
    "where", "clip", "round", "abs", "absolute", "negative", "exp",
    "exp2", "expm1", "log", "log2", "log1p", "sqrt", "rsqrt", "square",
    "maximum", "minimum", "add", "subtract", "multiply", "divide",
    "true_divide", "power", "tanh", "sigmoid", "erf", "relu", "gelu",
    "silu", "swish", "softplus", "sort", "flip", "reshape", "ravel",
    "flatten", "pad", "transpose", "moveaxis", "swapaxes",
    "broadcast_to", "concatenate", "stack", "hstack", "vstack",
    "expand_dims", "squeeze", "take", "take_along_axis", "roll",
    "tile", "repeat", "split", "cumprod", "copy", "conj", "real",
    "stop_gradient", "dynamic_slice", "dynamic_update_slice", "select",
    "all_to_all", "all_gather", "psum", "pmean", "pmax", "pmin",
    "ppermute", "psum_scatter", "nan_to_num", "atleast_2d", "tril",
    "triu", "set", "at", "astype_like",
}

#: constructors whose default dtype is float32 under jax
_FP32_CTORS = {"zeros", "ones", "full", "empty", "eye", "linspace",
               "uniform", "normal", "randn"}
_LIKE_CTORS = {"zeros_like", "ones_like", "full_like", "empty_like"}

#: boolean / index producers
_SAFE_PRODUCERS = {"argmax", "argmin", "argsort", "isfinite", "isnan",
                   "isinf", "any", "all", "sign", "searchsorted",
                   "one_hot_int", "iota", "broadcasted_iota",
                   "program_id", "num_programs", "axis_index",
                   "categorical", "randint", "bernoulli"}

#: functions that consume *unscaled* grads: calling them on grads that
#: still carry the loss scale computes a scaled norm / clip threshold
_NORM_CONSUMERS = {"clip_grad_norm", "clip_by_global_norm",
                   "global_norm", "global_grad_clip_coef",
                   "tree_l2_norm", "per_tensor_l2_norms"}

#: jit-family wrappers resolved through to their operand's summary
_WRAPPERS = {"vmap", "pmap", "jit", "pjit", "shard_map", "remat",
             "checkpoint", "partial", "named_call"}

_REF_RE = re.compile(r"_refs?$|^refs$")

_FuncNode = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _callee_name(func: ast.AST) -> Optional[str]:
    """The method/function name a call dispatches to: ``astype`` for
    ``f(x).astype``, ``mean`` for ``jnp.mean`` — unlike
    :func:`last_attr` this survives calls inside the attribute chain
    (``state.apply_fn(p, x).astype(...)``)."""
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _walk_no_nested(node: ast.AST):
    """``ast.walk`` that does not descend into nested
    defs/lambdas — their assignments belong to their own scope, not
    the enclosing function's dtype environment."""
    stack = [node]
    while stack:
        cur = stack.pop()
        yield cur
        for child in ast.iter_child_nodes(cur):
            if isinstance(child, _FuncNode):
                continue
            stack.append(child)


def _is_kernel(fn: ast.AST) -> bool:
    """Pallas kernel heuristic: any parameter (incl. ``*refs``) named
    ``*_ref``/``refs`` — the ``pl.pallas_call`` body convention."""
    args = getattr(fn, "args", None)
    if args is None:
        return False
    names = [a.arg for a in list(args.posonlyargs) + list(args.args)
             + list(args.kwonlyargs)]
    if args.vararg is not None:
        names.append(args.vararg.arg)
    return any(_REF_RE.search(n) for n in names)


# ------------------------------------------------------------- inference

class _FnScope:
    """Dtype-flow facts for one function body."""

    def __init__(self, ctx: ModuleContext, fn: ast.AST,
                 summaries: Dict[str, LatOrTuple]):
        self.ctx = ctx
        self.fn = fn
        self.summaries = summaries
        self.env: Dict[str, Lat] = {}
        self.dtype_env: Dict[str, Lat] = {}
        self.kernel = _is_kernel(fn)
        self._seed_params()
        # two passes approximate a fixpoint (use-before-def in loops),
        # same recipe as the taint engine
        self._visit_body()
        self._visit_body()

    # ------------------------------------------------------------ seeds
    def _seed_params(self) -> None:
        args = self.fn.args
        ordered = list(args.posonlyargs) + list(args.args)
        defaults = list(args.defaults)
        pad: List[Optional[ast.AST]] = [None] * (len(ordered)
                                                 - len(defaults))
        for arg, default in zip(ordered, pad + defaults):
            d = _dtype_from_expr(default, {})
            if d is not None and not isinstance(default, ast.Constant):
                # dtype-object default (dtype=jnp.float32): the param
                # *denotes* a dtype, it is not an array of that dtype
                self.dtype_env[arg.arg] = d
        for arg, default in zip(args.kwonlyargs, args.kw_defaults):
            d = _dtype_from_expr(default, {})
            if d is not None and not isinstance(default, ast.Constant):
                self.dtype_env[arg.arg] = d

    # ------------------------------------------------------------- body
    def _visit_body(self) -> None:
        body = self.fn.body if isinstance(self.fn.body, list) \
            else [self.fn.body]
        self._visit_stmts(body)

    def _visit_stmts(self, stmts) -> None:
        for stmt in stmts:
            self._visit_stmt(stmt)

    def _assign(self, target: ast.AST, lat: LatOrTuple) -> None:
        if isinstance(target, ast.Name):
            d = None
            # `dt = jnp.float32` binds a dtype object, not an array
            if lat == NEUTRAL:
                d = None
            self.env[target.id] = _collapse(lat)
            del d
        elif isinstance(target, (ast.Tuple, ast.List)):
            elts = target.elts
            if isinstance(lat, tuple) and len(lat) == len(elts):
                for el, la in zip(elts, lat):
                    self._assign(el, la)
            else:
                for el in elts:
                    self._assign(el, _collapse(lat))
        elif isinstance(target, ast.Starred):
            self._assign(target.value, _collapse(lat))

    def _visit_stmt(self, stmt: ast.AST) -> None:
        if isinstance(stmt, _FuncNode):
            return                      # nested defs get their own scope
        if isinstance(stmt, ast.Assign):
            value = stmt.value
            d = _dtype_from_expr(value, self.dtype_env) \
                if not isinstance(value, ast.Constant) else None
            lat = self.lat_of(value)
            for t in stmt.targets:
                if d is not None and isinstance(t, ast.Name) \
                        and lat == NEUTRAL:
                    self.dtype_env[t.id] = d     # dt = jnp.float32
                else:
                    self._assign(t, lat)
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            self._assign(stmt.target, self.lat_of(stmt.value))
        elif isinstance(stmt, ast.AugAssign):
            lat = _join(self.lat_of(stmt.target), self.lat_of(stmt.value))
            self._assign(stmt.target, lat)
        elif isinstance(stmt, ast.For):
            self._assign(stmt.target, self.lat_of(stmt.iter))
        elif isinstance(stmt, ast.With):
            for item in stmt.items:
                if item.optional_vars is not None:
                    self._assign(item.optional_vars,
                                 self.lat_of(item.context_expr))
        for node in _walk_no_nested(stmt):
            if isinstance(node, ast.NamedExpr):
                self._assign(node.target, self.lat_of(node.value))
        for field in ("body", "orelse", "finalbody"):
            sub = getattr(stmt, field, None)
            if isinstance(sub, list):
                self._visit_stmts(sub)
        for handler in getattr(stmt, "handlers", ()):
            self._visit_stmts(handler.body)

    # ------------------------------------------------------------ lat_of
    def lat_of(self, node: Optional[ast.AST]) -> LatOrTuple:
        if node is None:
            return NEUTRAL
        if isinstance(node, ast.Constant):
            return NEUTRAL
        if isinstance(node, ast.Name):
            if node.id in self.dtype_env:
                return NEUTRAL              # a dtype object as a value
            return self.env.get(node.id, UNKNOWN)
        if isinstance(node, ast.Attribute):
            if _dtype_name_lat(node.attr) is not None:
                return NEUTRAL              # jnp.bfloat16 the *object*
            if node.attr in ("shape", "ndim", "dtype", "size", "T"):
                return NEUTRAL
            return UNKNOWN
        if isinstance(node, ast.Subscript):
            base = node.value
            if isinstance(base, ast.Name) and _REF_RE.search(base.id):
                return STORAGE              # Pallas ref load
            return _collapse(self.lat_of(base))
        if isinstance(node, ast.Call):
            return self._lat_call(node)
        if isinstance(node, ast.BinOp):
            return _join(_collapse(self.lat_of(node.left)),
                         _collapse(self.lat_of(node.right)))
        if isinstance(node, ast.UnaryOp):
            return _collapse(self.lat_of(node.operand))
        if isinstance(node, (ast.Compare, ast.BoolOp)):
            return SAFE
        if isinstance(node, ast.IfExp):
            return _join(_collapse(self.lat_of(node.body)),
                         _collapse(self.lat_of(node.orelse)))
        if isinstance(node, ast.Tuple):
            return tuple(self.lat_of(el) for el in node.elts)
        if isinstance(node, ast.List):
            out: Lat = NEUTRAL
            for el in node.elts:
                out = _join(out, _collapse(self.lat_of(el)))
            return out
        if isinstance(node, ast.Starred):
            return _collapse(self.lat_of(node.value))
        if isinstance(node, ast.NamedExpr):
            return _collapse(self.lat_of(node.value))
        if isinstance(node, ast.Lambda):
            return NEUTRAL
        return UNKNOWN

    def _kwarg(self, call: ast.Call, *names: str) -> Optional[ast.AST]:
        for kw in call.keywords:
            if kw.arg in names:
                return kw.value
        return None

    def dtype_kwarg_lat(self, call: ast.Call) -> Optional[Lat]:
        expr = self._kwarg(call, "dtype", "preferred_element_type")
        if expr is None:
            return None
        return _dtype_from_expr(expr, self.dtype_env)

    def _lat_call(self, call: ast.Call) -> LatOrTuple:
        la = _callee_name(call.func)
        explicit = self.dtype_kwarg_lat(call)
        if explicit is not None:
            return explicit
        if la == "astype":
            if call.args:
                d = _dtype_from_expr(call.args[0], self.dtype_env)
                if d is not None:
                    return d
            return UNKNOWN                  # cast to an opaque dtype
        if la in ("asarray", "array"):
            if len(call.args) >= 2:
                d = _dtype_from_expr(call.args[1], self.dtype_env)
                if d is not None:
                    return d
            return _collapse(self.lat_of(call.args[0])) \
                if call.args else UNKNOWN
        if la in _FP32_CTORS:
            return FP32                     # jax default float dtype
        if la in _LIKE_CTORS:
            return _collapse(self.lat_of(call.args[0])) \
                if call.args else UNKNOWN
        if la in _SAFE_PRODUCERS:
            return SAFE
        if la in _TRANSPARENT or la in _REDUCTIONS:
            out: Lat = NEUTRAL
            for arg in call.args:
                if isinstance(arg, ast.Constant):
                    continue
                out = _join(out, _collapse(self.lat_of(arg)))
            return out if out != NEUTRAL else UNKNOWN
        if la in _DOT_FAMILY:
            out = NEUTRAL
            for arg in call.args:
                if isinstance(arg, ast.Constant):
                    continue                # einsum's spec string
                out = _join(out, _collapse(self.lat_of(arg)))
            return out if out != NEUTRAL else UNKNOWN
        # jax.vmap(f)(...) / jit(f)(...): resolve through to f
        if isinstance(call.func, ast.Call):
            inner = call.func
            ila = _callee_name(inner.func)
            if ila in _WRAPPERS and inner.args:
                target = inner.args[0]
                if isinstance(target, ast.Name):
                    hit = self.summaries.get(target.id)
                    if hit is not None:
                        return hit
                if isinstance(target, ast.Lambda):
                    sub = _FnScope(self.ctx, target, self.summaries)
                    return sub.lat_of(target.body)
            return UNKNOWN
        if la is not None:
            hit = self.summaries.get(la)
            if hit is not None:
                return hit
        return UNKNOWN


def _fn_summary(ctx: ModuleContext, fn: ast.AST,
                summaries: Dict[str, LatOrTuple]) -> LatOrTuple:
    """Return lattice of ``fn`` (tuples element-wise), joined over
    every ``return`` statement in its own body (nested defs excluded)."""
    returns = [node for node in ast.walk(fn)
               if isinstance(node, ast.Return) and node.value is not None
               and ctx.enclosing_function(node) is fn]
    if not returns:
        return UNKNOWN          # procedure: skip the body inference
    scope = _FnScope(ctx, fn, summaries)
    out: Optional[LatOrTuple] = None
    for node in returns:
        lat = scope.lat_of(node.value)
        out = lat if out is None else _join_summaries(out, lat)
    return out if out is not None else UNKNOWN


# ------------------------------------------------------------ the analysis

class _Analysis:
    """One whole-program precision analysis over a module set."""

    def __init__(self, contexts: List[ModuleContext]):
        self.contexts = list(contexts)
        self.findings: List[Finding] = []
        # program-wide function table (bare name; first def wins the
        # name, later ones join into the summary)
        self.fns: List[Tuple[ModuleContext, ast.AST]] = []
        self.by_name: Dict[str, List[Tuple[ModuleContext, ast.AST]]] = {}
        for ctx in self.contexts:
            for fn in ctx.functions():
                if isinstance(fn, ast.Lambda):
                    continue
                self.fns.append((ctx, fn))
                self.by_name.setdefault(fn.name, []).append((ctx, fn))
        self.summaries: Dict[str, LatOrTuple] = {}
        # defs marked `# graftlint: precision(master-fp32)`
        self.master_fns: Dict[str, Tuple[ModuleContext, ast.AST]] = {}
        for ctx, fn in self.fns:
            for mark, arg in _marks_for_line(ctx, fn.lineno):
                if mark == "precision" and arg == "master-fp32":
                    self.master_fns[fn.name] = (ctx, fn)

    # ---------------------------------------------------------- running
    def run(self) -> List[Finding]:
        # two summary rounds: round 2 sees round 1's results, so a
        # helper calling a helper still resolves
        for _ in range(2):
            nxt: Dict[str, LatOrTuple] = {}
            for ctx, fn in self.fns:
                lat = _fn_summary(ctx, fn, self.summaries)
                prev = nxt.get(fn.name)
                nxt[fn.name] = lat if prev is None \
                    else _join_summaries(prev, lat)
            self.summaries = nxt
        for ctx in self.contexts:
            self._check_module(ctx)
        return self.findings

    def _finding(self, rule: str, ctx: ModuleContext, node: ast.AST,
                 message: str) -> None:
        f = Finding(rule, ctx.path, getattr(node, "lineno", 1),
                    getattr(node, "col_offset", 0) + 1, message)
        if f not in self.findings:
            self.findings.append(f)

    # ------------------------------------------------------- mark logic
    def _site_marks(self, ctx: ModuleContext, node: ast.AST,
                    fn: Optional[ast.AST]) -> List[Tuple[str, str]]:
        marks = list(_marks_for_line(ctx, getattr(node, "lineno", 0)))
        if fn is not None and not isinstance(fn, ast.Lambda):
            marks += _marks_for_line(ctx, fn.lineno)
        return marks

    def _excused(self, rule: str, ctx: ModuleContext, node: ast.AST,
                 fn: Optional[ast.AST]) -> bool:
        """True when a ``reduce-fp32`` / justified ``lowprec`` mark on
        the site (or its def) covers the would-be finding; an *empty*
        lowprec justification is itself flagged."""
        for mark, arg in self._site_marks(ctx, node, fn):
            if mark == "reduce-fp32" and rule == "bf16-unsafe-reduction":
                return True
            if mark == "lowprec":
                if not arg.strip():
                    self._finding(
                        rule, ctx, node,
                        "lowprec() with no justification — the reason "
                        "is the point of the annotation; say why the "
                        "low-precision exception is sound")
                    return True
                return True
        return False

    # ------------------------------------------------------ module walk
    def _check_module(self, ctx: ModuleContext) -> None:
        entries = ctx.traced_entries()
        for fn in ctx.functions():
            if isinstance(fn, ast.Lambda):
                continue
            scope = _FnScope(ctx, fn, self.summaries)
            tainted: Set[str] = set()
            weak_ok = fn in entries and not scope.kernel
            if weak_ok:
                tainted = closure_taint(ctx, fn)
            self._check_fn(ctx, fn, scope, tainted, weak_ok)
            self._check_unscaled_grads(ctx, fn, scope)

    def _own_nodes(self, ctx: ModuleContext, fn: ast.AST):
        """Walk ``fn``'s body excluding nested defs (those get their
        own scope and their own iteration)."""
        for node in ast.walk(fn):
            if isinstance(node, _FuncNode) and node is not fn:
                continue
            inner = ctx.enclosing_function(node)
            if inner is not fn:
                continue
            yield node

    def _check_fn(self, ctx: ModuleContext, fn: ast.AST,
                  scope: _FnScope, tainted: Set[str],
                  weak_ok: bool) -> None:
        for node in self._own_nodes(ctx, fn):
            if isinstance(node, ast.Call):
                self._check_reduction(ctx, fn, scope, node, tainted,
                                      weak_ok)
                self._check_redundant_cast(ctx, fn, scope, node)
                self._check_quant_call(ctx, fn, scope, node)
                self._check_master_call(ctx, fn, scope, node)
            elif isinstance(node, ast.BinOp):
                self._check_quant_binop(ctx, fn, scope, node)

    # -------------------------------------------------- rule 1: reduce
    def _check_reduction(self, ctx: ModuleContext, fn: ast.AST,
                         scope: _FnScope, call: ast.Call,
                         tainted: Set[str], weak_ok: bool) -> None:
        la = _callee_name(call.func)
        is_dot = la in _DOT_FAMILY
        if la not in _REDUCTIONS and not is_dot:
            return
        explicit = scope.dtype_kwarg_lat(call)
        if explicit == FP32:
            return                          # fp32 accumulator declared
        args = [a for a in call.args if not isinstance(a, ast.Constant)]
        if not args:
            return
        lat = NEUTRAL
        for a in args:
            lat = _join(lat, _collapse(scope.lat_of(a)))
        if lat == LOW and not is_dot:
            if self._excused("bf16-unsafe-reduction", ctx, call, fn):
                return
            self._finding(
                "bf16-unsafe-reduction", ctx, call,
                f"`{la}` accumulates in a low-precision dtype — the "
                f"operand is bf16/fp16, so the reduction's running sum "
                f"is too; cast the operand `.astype(jnp.float32)` (or "
                f"pass `dtype=jnp.float32`), or mark the line "
                f"`# graftlint: reduce-fp32` if an fp32 accumulator "
                f"exists by construction")
            return
        if scope.kernel and lat in (STORAGE, LOW):
            # Pallas body: the accumulator follows the pool/input dtype
            if is_dot and explicit is not None:
                return                      # non-fp32 but *deliberate*
            if self._excused("bf16-unsafe-reduction", ctx, call, fn):
                return
            what = ("contraction without `preferred_element_type="
                    "jnp.float32`" if is_dot else "reduction")
            self._finding(
                "bf16-unsafe-reduction", ctx, call,
                f"Pallas kernel {what} on a raw `*_ref` load: the "
                f"accumulator dtype follows the input, so a bf16/int8 "
                f"pool accumulates in bf16/int8 — upcast the load "
                f"`.astype(jnp.float32)` first"
                + ("" if is_dot else " (or pass `dtype=jnp.float32`)")
                + ", or mark `# graftlint: reduce-fp32`")
            return
        if weak_ok and not is_dot and la in _MEAN_FAMILY \
                and lat == UNKNOWN:
            if not any(expr_tainted(a, tainted) for a in args):
                return
            if self._excused("bf16-unsafe-reduction", ctx, call, fn):
                return
            self._finding(
                "bf16-unsafe-reduction", ctx, call,
                f"`{la}` in traced code on a value with no fp32 anchor "
                f"— under a half-precision policy this operand follows "
                f"the compute dtype and the reduction accumulates in "
                f"it; cast the operand `.astype(jnp.float32)`, or mark "
                f"`# graftlint: reduce-fp32` if it is fp32 by an "
                f"upstream contract")

    # --------------------------------------------- rule 2: master fp32
    def _check_master_call(self, ctx: ModuleContext, fn: ast.AST,
                           scope: _FnScope, call: ast.Call) -> None:
        la = _callee_name(call.func)
        if la in self.master_fns and la != getattr(fn, "name", None):
            for arg in call.args:
                lat = _collapse(scope.lat_of(arg))
                if lat in (LOW, QUANT):
                    if self._excused("master-weight-violation", ctx,
                                     call, fn):
                        return
                    self._finding(
                        "master-weight-violation", ctx, call,
                        f"`{la}` is marked `# graftlint: precision"
                        f"(master-fp32)` but this call passes a "
                        f"{lat}-precision value — under O2 the "
                        f"optimizer must consume fp32 master weights; "
                        f"update the masters and re-cast for the "
                        f"forward pass instead")
                    return
        # builtin shape: optax.apply_updates(params, updates) — the
        # canonical optimizer-apply; params must be the fp32 masters
        if la == "apply_updates" and call.args:
            lat = _collapse(scope.lat_of(call.args[0]))
            if lat in (LOW, QUANT):
                if self._excused("master-weight-violation", ctx, call,
                                 fn):
                    return
                self._finding(
                    "master-weight-violation", ctx, call,
                    f"optimizer update applied to {lat}-precision "
                    f"params — under O2 the update must land on the "
                    f"fp32 master copy (half-precision weight updates "
                    f"lose every increment smaller than ~2^-8 of the "
                    f"weight); apply to the masters, then "
                    f"`cast_to_compute` for the forward pass")
        # body contract: a master-fp32 def must not downcast a param
        if getattr(fn, "name", None) in self.master_fns \
                and la == "astype" and call.args:
            target = _dtype_from_expr(call.args[0], scope.dtype_env)
            obj = call.func.value if isinstance(call.func, ast.Attribute) \
                else None
            if target in (LOW, QUANT) and isinstance(obj, ast.Name):
                params = {a.arg for a in fn.args.args
                          + fn.args.posonlyargs + fn.args.kwonlyargs}
                if obj.id in params and not self._excused(
                        "master-weight-violation", ctx, call, fn):
                    self._finding(
                        "master-weight-violation", ctx, call,
                        f"`{obj.id}` is a parameter of a `precision"
                        f"(master-fp32)` function but is downcast to "
                        f"{target} here — masters stay fp32 through "
                        f"the update; cast only the forward-pass copy")

    # ------------------------------------------- rule 3: unscaled grads
    def _check_unscaled_grads(self, ctx: ModuleContext, fn: ast.AST,
                              scope: _FnScope) -> None:
        if isinstance(fn, ast.Lambda):
            return
        # only meaningful where a loss-scale multiply is in scope
        has_scaling = False
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                la = _callee_name(node.func)
                if la == "scale_loss" or (
                        la == "scale" and isinstance(node.func,
                                                     ast.Attribute)):
                    has_scaling = True
                    break
        if not has_scaling:
            return
        scaled: Set[str] = set()

        def names_in(expr: ast.AST) -> Set[str]:
            return {n.id for n in ast.walk(expr)
                    if isinstance(n, ast.Name)}

        def grad_targets(stmt: ast.Assign) -> List[str]:
            value = stmt.value
            if not (isinstance(value, ast.Call)
                    and isinstance(value.func, ast.Call)):
                return []
            inner = value.func
            ila = _callee_name(inner.func)
            if ila not in ("grad", "value_and_grad"):
                return []
            has_aux = any(kw.arg == "has_aux" for kw in inner.keywords)
            target = stmt.targets[0]
            if isinstance(target, ast.Name):
                return [target.id]
            if isinstance(target, (ast.Tuple, ast.List)):
                elts = [e.id for e in target.elts
                        if isinstance(e, ast.Name)]
                if len(elts) == 2:
                    if ila == "value_and_grad":
                        return [elts[1]]       # (value, grad)
                    if has_aux:
                        return [elts[0]]       # (grad, aux)
                return elts
            return []

        def scan(stmts) -> None:
            for stmt in stmts:
                if isinstance(stmt, _FuncNode):
                    continue
                # uses first: a consumer on this line sees the grads
                # as they were BEFORE any same-statement rebind
                for node in ast.walk(stmt):
                    if isinstance(node, _FuncNode):
                        continue
                    if isinstance(node, ast.Call):
                        la = _callee_name(node.func)
                        if la in _NORM_CONSUMERS and any(
                                names_in(a) & scaled
                                for a in node.args):
                            if not self._excused("unscaled-grad-use",
                                                 ctx, node, fn):
                                self._finding(
                                    "unscaled-grad-use", ctx, node,
                                    f"`{la}` consumes gradients that "
                                    f"still carry the loss scale — "
                                    f"the norm/clip threshold is "
                                    f"computed on scaled values, so "
                                    f"clipping strength silently "
                                    f"tracks the scale; unscale first "
                                    f"(`loss_scaler.unscale`) or clip "
                                    f"after `apply_gradients`")
                if isinstance(stmt, ast.Assign):
                    targets = grad_targets(stmt)
                    if targets:
                        scaled.update(targets)
                    else:
                        value = stmt.value
                        kills = isinstance(value, ast.Call) and \
                            _callee_name(value.func) in ("unscale",
                                                      "apply_gradients")
                        tnames = [t.id for t in stmt.targets
                                  if isinstance(t, ast.Name)]
                        if kills:
                            scaled.difference_update(tnames)
                            # g = ls.unscale(st, g): g is now clean
                        elif names_in(value) & scaled:
                            scaled.update(tnames)
                        else:
                            scaled.difference_update(tnames)
                for field in ("body", "orelse", "finalbody"):
                    sub = getattr(stmt, field, None)
                    if isinstance(sub, list):
                        scan(sub)
                for handler in getattr(stmt, "handlers", ()):
                    scan(handler.body)

        body = fn.body if isinstance(fn.body, list) else [fn.body]
        scan(body)

    # -------------------------------------------- rule 4: cast chains
    def _check_redundant_cast(self, ctx: ModuleContext, fn: ast.AST,
                              scope: _FnScope, call: ast.Call) -> None:
        if _callee_name(call.func) != "astype" or not call.args:
            return
        func = call.func
        if not isinstance(func, ast.Attribute):
            return
        inner = func.value
        if not (isinstance(inner, ast.Call)
                and _callee_name(inner.func) == "astype" and inner.args):
            return
        d_in = _dtype_from_expr(inner.args[0], scope.dtype_env)
        d_out = _dtype_from_expr(call.args[0], scope.dtype_env)
        if d_in is None or d_out is None:
            return
        if self._excused("redundant-cast", ctx, call, fn):
            return
        if d_in == d_out:
            why = "the inner cast already produced this dtype"
        else:
            why = ("the intermediate value is dead — on a hot path "
                   "this round-trips precision and materializes an "
                   "extra buffer")
        self._finding(
            "redundant-cast", ctx, call,
            f"chained `.astype(...).astype(...)`: {why}; cast once to "
            f"the final dtype (use `# graftlint: lowprec(<why>)` for a "
            f"deliberate quantize-dequantize round-trip)")

    # --------------------------------------------- rule 5: quant codes
    def _quant_flag(self, ctx: ModuleContext, fn: ast.AST,
                    node: ast.AST, how: str) -> None:
        if self._excused("quant-code-arith", ctx, node, fn):
            return
        self._finding(
            "quant-code-arith", ctx, node,
            f"arithmetic on int8/fp8 quantization codes ({how}) — "
            f"codes are meaningless without their scale and integer "
            f"ops saturate/overflow silently; dequantize first "
            f"(`.astype(jnp.float32)` / `.astype(jnp.int32)`, then "
            f"apply the scale), or mark a blessed dequant site "
            f"`# graftlint: lowprec(<why>)`")

    def _check_quant_binop(self, ctx: ModuleContext, fn: ast.AST,
                           scope: _FnScope, node: ast.BinOp) -> None:
        for side in (node.left, node.right):
            if _collapse(scope.lat_of(side)) == QUANT:
                self._quant_flag(ctx, fn, node,
                                 "a binary op on an un-dequantized "
                                 "operand")
                return

    def _check_quant_call(self, ctx: ModuleContext, fn: ast.AST,
                          scope: _FnScope, call: ast.Call) -> None:
        la = _callee_name(call.func)
        if la not in _REDUCTIONS and la not in _DOT_FAMILY \
                and la not in ("exp", "exp2", "sqrt", "log", "log2"):
            return
        for arg in call.args:
            if isinstance(arg, ast.Constant):
                continue
            if _collapse(scope.lat_of(arg)) == QUANT:
                self._quant_flag(ctx, fn, call,
                                 f"`{la}` over raw codes")
                return


def analyze_precision(contexts: List[ModuleContext]) -> List[Finding]:
    """Run the precision analysis; returns every finding (all five
    rules) unfiltered — the runner applies suppressions."""
    return _Analysis(list(contexts)).run()


# ------------------------------------------------------- program rules

class _PrecisionRule(ProgramRule):
    """Shared driver: the dtype-flow analysis runs once per program
    (memoized on the Program object by :meth:`prepare`, timed under the
    ``precision-pass`` row exactly like ``concurrency-pass``); each
    registered rule yields its slice."""

    shared_pass = "precision-pass"

    def prepare(self, program) -> None:
        if getattr(program, "_precision_findings", None) is None:
            program._precision_findings = analyze_precision(
                program.contexts)

    def check_program(self, program) -> Iterator[Finding]:
        self.prepare(program)
        for finding in program._precision_findings:
            if finding.rule == self.name:
                yield finding


@register_program
class Bf16UnsafeReduction(_PrecisionRule):
    """Rule P1 — reduction accumulated in a low-precision dtype.

    ``softmax``/``logsumexp``/``mean``/``var``/``norm``-family calls
    whose operand is inferred bf16/fp16 (or, in a Pallas kernel body,
    follows a raw ``*_ref`` load — including contractions without
    ``preferred_element_type=jnp.float32``), and mean-family reductions
    in traced code with no fp32 anchor anywhere on the operand's flow.
    Escapes: ``dtype=jnp.float32``, ``.astype(jnp.float32)`` upstream,
    ``# graftlint: reduce-fp32``, justified ``lowprec(<why>)``.
    """

    name = "bf16-unsafe-reduction"
    summary = ("softmax/mean/var/norm-family reduction accumulated in "
               "a low-precision dtype (incl. Pallas accumulators)")


@register_program
class MasterWeightViolation(_PrecisionRule):
    """Rule P2 — optimizer update touching non-fp32 master weights.

    A call of a ``# graftlint: precision(master-fp32)``-marked function
    passing a value inferred low/quant, ``optax.apply_updates`` on
    low-precision params, or a master-fp32 function body downcasting a
    parameter — the O2 discipline: updates land on fp32 masters, the
    half copy exists only for the forward pass.
    """

    name = "master-weight-violation"
    summary = ("optimizer update / weight decay applied to a non-fp32 "
               "leaf where the master-fp32 contract applies")


@register_program
class UnscaledGradUse(_PrecisionRule):
    """Rule P3 — gradients consumed between loss-scale and unscale.

    In a function whose loss is multiplied by a loss scale
    (``scale_loss`` / ``loss_scaler.scale``), the grads returned by
    ``jax.grad``/``value_and_grad`` carry that scale until an
    ``unscale`` (or ``apply_gradients``, which unscales internally);
    feeding them to ``clip_grad_norm``/``global_norm``-family helpers
    first computes clip thresholds that silently track the scale.
    """

    name = "unscaled-grad-use"
    summary = ("grad norm/clip computed on still-scaled gradients "
               "(between loss-scale multiply and unscale)")


@register_program
class RedundantCast(_PrecisionRule):
    """Rule P4 — ``.astype(A).astype(B)`` chains.

    The intermediate cast's result is dead: a hot-path perf smell, and
    when it narrows (fp32 → bf16 → fp32) a silent precision round-trip.
    A deliberate quantize-dequantize simulation is annotated
    ``# graftlint: lowprec(<why>)``.
    """

    name = "redundant-cast"
    summary = ("chained astype casts that round-trip precision / "
               "materialize a dead intermediate (perf smell)")


@register_program
class QuantCodeArith(_PrecisionRule):
    """Rule P5 — arithmetic on int8/fp8 quantization codes.

    Values cast to int8/uint8/fp8 are *codes* (KV pages, quantized
    AllReduce payloads): arithmetic on them outside a blessed dequant
    site saturates/overflows silently and ignores the scale.  Widening
    casts (``astype(int32)`` accumulate, ``astype(float32)`` dequant)
    sanitize; structural ops (reshape/pad/collectives) are fine.
    """

    name = "quant-code-arith"
    summary = ("arithmetic on int8/fp8 codes outside a blessed, "
               "annotated dequant site")
