"""graftlint — JAX trace-hygiene + concurrency static analyzer.

Catches the footgun class that silently erases fused-kernel wins:
trace-time environment capture, python branching on traced values,
cache-defeating jit signatures, wall-clock/RNG/print side effects
baked into traces, and mutable global state touched from traced code.

v2 adds whole-program **concurrency** rules over the threaded serving
stack (``tools/graftlint/concurrency.py``): instance fields reachable
from multiple thread entry points without a declared lock discipline,
``guarded-by(<lock>)`` annotations checked at every access,
``requires-lock`` caller contracts, and lock-order cycles (potential
deadlocks) across the interprocedural acquisition graph.

CLI::

    python -m tools.graftlint apex_tpu tools examples
    python -m tools.graftlint --list-rules
    python -m tools.graftlint --format json apex_tpu
    python -m tools.graftlint --timings apex_tpu

Exit status: 0 clean, 1 findings, 2 usage error.  Docs:
``docs/graftlint.md``.  The runtime counterparts (guards tests can
assert on) are :mod:`apex_tpu.utils.tracecheck` (retrace counter) and
:mod:`apex_tpu.utils.lockcheck` (acquisition-order recorder + strict
guarded-field verification).
"""

from tools.graftlint.core import (
    Finding, Program, ProgramRule, Rule, all_program_rules, all_rules,
    lint_paths, lint_path, lint_source, main, run_stats,
)

__all__ = ["Finding", "Program", "ProgramRule", "Rule",
           "all_program_rules", "all_rules", "lint_paths", "lint_path",
           "lint_source", "main", "run_stats"]
