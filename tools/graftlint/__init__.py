"""graftlint — JAX trace-hygiene static analyzer for this repo.

Catches the footgun class that silently erases fused-kernel wins:
trace-time environment capture, python branching on traced values,
cache-defeating jit signatures, wall-clock/RNG/print side effects
baked into traces, and mutable global state touched from traced code.

CLI::

    python -m tools.graftlint apex_tpu tools examples
    python -m tools.graftlint --list-rules
    python -m tools.graftlint --format json apex_tpu

Exit status: 0 clean, 1 findings, 2 usage error.  Docs:
``docs/graftlint.md``.  The runtime counterpart (a retrace counter
tests can assert on) is :mod:`apex_tpu.utils.tracecheck`.
"""

from tools.graftlint.core import (
    Finding, Rule, all_rules, lint_paths, lint_path, lint_source, main,
)

__all__ = ["Finding", "Rule", "all_rules", "lint_paths", "lint_path",
           "lint_source", "main"]
