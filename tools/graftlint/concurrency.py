"""graftlint concurrency pass — whole-program thread-hygiene analysis.

The trace-hygiene rules (``rules.py``) are per-file; the serving stack's
bugs are not.  ``InferenceServer`` worker threads, the ``FleetRouter``
supervisor, async checkpoint writers and cross-thread metrics pipelines
share instance fields across threads, and every recent review pass
caught a real race by hand (a cross-thread deque iteration, a
CircuitBreaker needing an RLock, an unlocked supervisor counter).  This
pass makes that review machine-checked:

1. **Thread-entry inference** — for every *concurrent class* (one that
   owns a ``threading.Lock``/``RLock``/``Condition``/``Event`` or
   starts a ``threading.Thread``), each ``Thread(target=self.m)`` /
   ``Thread(target=nested_def)`` roots its own thread group, and every
   public method roots the shared ``client`` group (callable from any
   client thread).  ``# graftlint: thread-entry(<group>)`` on a ``def``
   line declares a callback that runs on another thread (a fleet tap
   executed by a replica worker); ``# graftlint: single-threaded(<why>)``
   excludes a method that runs before/without concurrency (warmup).

2. **Interprocedural walk** — from each entry the pass walks
   ``self.m()`` calls, property reads, and one level of typed-field
   calls (``self.scheduler.run_step()`` resolves through the
   ``self.scheduler = Scheduler(...)`` assignment in ``__init__``,
   when the target class is itself concurrent), carrying the set of
   locks lexically held (``with self._lock:`` regions, with
   ``Condition(self._lock)`` aliasing resolved) across call edges.

3. **Shared-field discipline** — a field *mutated* from two groups, or
   mutated in one and *iterated* in another (the deque-``RuntimeError``
   shape), must carry an annotation on its ``__init__`` assignment:
   ``# graftlint: guarded-by(<lock>)`` (every access must then hold the
   lock — checked) or ``# graftlint: unguarded(<why>)`` (a deliberate,
   justified exception: single-writer publish, GIL-atomic ops,
   join-ordering).  Single-atomic reads (``len()``, subscript loads,
   membership, ``next()``) never count as hazardous touches; scalar
   fields written from exactly one group are the CPython-safe
   single-writer-publish idiom and pass unannotated.

4. **Lock discipline helpers** — ``# graftlint: requires-lock(<lock>)``
   on a ``def`` line asserts the caller holds the lock: the body is
   analyzed as holding it, and any call site that does not hold it is
   flagged.

5. **Lock-order cycles** — every ``with self.<lockB>:`` entered while
   ``<lockA>`` is held (lexically or through the call graph, across
   classes) adds edge A→B to a program-wide acquisition graph; a
   strongly-connected component (or a self-edge on a non-reentrant
   ``Lock``) is a potential deadlock and is reported with its witness
   sites.

The runtime twin is :mod:`apex_tpu.utils.lockcheck`, which wraps the
stack's locks and observes the *actual* acquisition order under the
chaos soaks.  ``docs/graftlint.md`` documents the rule catalog, the
annotation convention, and the resulting thread map.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, FrozenSet, Iterator, List, Optional, Set, Tuple

from tools.graftlint.core import (
    Finding,
    ModuleContext,
    ProgramRule,
    dotted_name,
    last_attr,
    register_program,
)

__all__ = ["analyze_program"]

# ---------------------------------------------------------------- marks

_MARK_RE = re.compile(
    r"graftlint:\s*"
    r"(guarded-by|unguarded|requires-lock|thread-entry|single-threaded)"
    r"\(([^)]*)\)")

#: lock-like constructors (the acquisition graph's node types)
_LOCK_CTORS = {"Lock": "lock", "RLock": "rlock", "Condition": "condition"}
#: internally-synchronized types: never themselves shared-field hazards
_SYNC_CTORS = {"Event", "Semaphore", "BoundedSemaphore", "Barrier",
               "Queue", "LifoQueue", "PriorityQueue", "SimpleQueue"}
#: container constructors/literals (iteration across threads can raise
#: or tear; mutation needs a discipline)
_CONTAINER_CTORS = {"dict", "list", "set", "deque", "defaultdict",
                    "OrderedDict", "Counter"}
#: container methods that mutate in place
_MUTATORS = {"append", "appendleft", "extend", "extendleft", "add",
             "update", "setdefault", "pop", "popleft", "popitem",
             "insert", "remove", "discard", "clear", "rotate"}
#: calls whose read of a container argument is a single atomic op
_ATOMIC_CALLS = {"len", "bool", "repr", "id", "next", "isinstance",
                 "hasattr", "type", "callable"}
#: calls that iterate their container argument
_ITERATING_CALLS = {"list", "tuple", "sorted", "set", "frozenset",
                    "sum", "min", "max", "any", "all", "iter",
                    "reversed", "enumerate", "zip", "map", "filter",
                    "dict"}
#: methods returning live iteration views — traversing one during a
#: concurrent mutation raises the same RuntimeError as iterating the
#: container directly (`.copy()` is excluded: C-level, GIL-atomic)
_ITER_VIEW_METHODS = {"values", "items", "keys"}

CLIENT = "client"

_FuncDef = (ast.FunctionDef, ast.AsyncFunctionDef)


def _self_attr(node: ast.AST) -> Optional[str]:
    """``X`` for ``self.X``, else None."""
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _is_threading_ctor(node: ast.AST, names: Dict[str, str]) -> Optional[str]:
    """Kind for ``threading.Lock()``-style calls (see ``names``)."""
    if not isinstance(node, ast.Call):
        return None
    la = last_attr(node.func)
    return names.get(la) if la else None


@dataclasses.dataclass
class _Access:
    group: str
    kind: str            # "write" | "iter" | "read"
    ctx: ModuleContext
    node: ast.AST
    held: FrozenSet[Tuple[str, str]]     # {(class, lock-attr), ...}


@dataclasses.dataclass
class _Field:
    name: str
    kind: str = "opaque"      # container | scalar | primitive | opaque
    init_ctx: Optional[ModuleContext] = None
    init_node: Optional[ast.AST] = None
    guard: Optional[str] = None
    unguarded_reason: Optional[str] = None
    accesses: List[_Access] = dataclasses.field(default_factory=list)

    def groups(self, kind: str) -> Set[str]:
        return {a.group for a in self.accesses if a.kind == kind}


class _ClassModel:
    """Static model of one (possibly concurrent) class."""

    def __init__(self, ctx: ModuleContext, node: ast.ClassDef):
        self.ctx = ctx
        self.node = node
        self.name = node.name
        self.methods: Dict[str, ast.AST] = {}
        self.properties: Set[str] = set()
        self.locks: Dict[str, str] = {}        # attr -> lock|rlock|condition
        self.alias: Dict[str, str] = {}        # condition attr -> lock attr
        self.field_class: Dict[str, str] = {}  # attr -> class name
        self.fields: Dict[str, _Field] = {}
        self.requires: Dict[str, Set[str]] = {}
        self.entry_marks: Dict[str, str] = {}        # method -> group
        self.single_threaded: Set[str] = set()
        self.starts_thread = False
        # (root function node, group, enclosing method or None)
        self.thread_roots: List[Tuple[ast.AST, str]] = []
        self._scan()

    # ------------------------------------------------------------ scan
    def _marks_for_line(self, line: int) -> List[Tuple[str, str]]:
        """Marks on ``line`` — trailing, or on a *standalone* comment
        directly above (for lines too long to carry the mark; a
        trailing comment on the previous code line never leaks down)."""
        sup = self.ctx.suppressions
        text = sup.graftlint_comments.get(line, "")
        if line - 1 in sup.standalone_comment_lines:
            text += " " + sup.graftlint_comments.get(line - 1, "")
        return _MARK_RE.findall(text)

    def _scan(self) -> None:
        for item in self.node.body:
            if isinstance(item, _FuncDef):
                self.methods[item.name] = item
                if any(last_attr(d) == "property"
                       for d in item.decorator_list):
                    self.properties.add(item.name)
                for mark, arg in self._marks_for_line(item.lineno):
                    arg = arg.strip()
                    if mark == "requires-lock":
                        self.requires.setdefault(item.name, set()).add(arg)
                    elif mark == "thread-entry":
                        self.entry_marks[item.name] = arg or item.name
                    elif mark == "single-threaded":
                        self.single_threaded.add(item.name)
        init = self.methods.get("__init__")
        if init is not None:
            self._scan_init(init)
        # thread creation anywhere in the class body
        for node in ast.walk(self.node):
            if isinstance(node, ast.Call) \
                    and last_attr(node.func) == "Thread":
                self.starts_thread = True
                target = next(
                    (k.value for k in node.keywords if k.arg == "target"),
                    None)
                if target is None and node.args:
                    target = node.args[0]
                if target is None:
                    continue
                attr = _self_attr(target)
                if attr and attr in self.methods:
                    fn = self.methods[attr]
                    group = self.entry_marks.get(attr, f"thread:{attr}")
                    self.thread_roots.append((fn, group))
                elif isinstance(target, ast.Name):
                    # nested def passed by name (async checkpoint /
                    # prefetch worker style)
                    enclosing = self.ctx.enclosing_function(node)
                    for cand in ast.walk(self.node):
                        if isinstance(cand, _FuncDef) \
                                and cand.name == target.id \
                                and cand is not enclosing \
                                and self.ctx.enclosing_function(cand) \
                                is enclosing:
                            self.thread_roots.append(
                                (cand, f"thread:{cand.name}"))

    def _scan_init(self, init: ast.AST) -> None:
        for node in ast.walk(init):
            targets: List[ast.AST] = []
            value: Optional[ast.AST] = None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            elif isinstance(node, ast.AnnAssign):
                targets, value = [node.target], None
            for target in targets:
                attr = _self_attr(target)
                if attr is None:
                    continue
                lock_kind = value is not None and _is_threading_ctor(
                    value, _LOCK_CTORS)
                if lock_kind:
                    self.locks[attr] = lock_kind
                    if lock_kind == "condition" and value.args:
                        inner = _self_attr(value.args[0])
                        if inner:
                            self.alias[attr] = inner
                    continue
                field = self.fields.setdefault(attr, _Field(attr))
                if field.init_node is None:
                    field.init_ctx = self.ctx
                    field.init_node = node
                    field.kind = self._classify(value)
                for mark, arg in self._marks_for_line(node.lineno):
                    if mark == "guarded-by":
                        field.guard = arg.strip()
                    elif mark == "unguarded":
                        field.unguarded_reason = arg.strip()
                # `self.x = self.y = ...` or conditional re-assigns:
                # keep the first classification
                if value is not None and isinstance(value, ast.Call):
                    callee = last_attr(value.func)
                    # resolvable field type (for cross-class walking)
                    if callee and callee[:1].isupper() \
                            and attr not in self.field_class:
                        self.field_class[attr] = callee

    @staticmethod
    def _classify(value: Optional[ast.AST]) -> str:
        if value is None:
            return "opaque"
        if isinstance(value, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                              ast.DictComp, ast.SetComp)):
            return "container"
        if isinstance(value, ast.Constant):
            return "scalar"
        if isinstance(value, ast.BinOp):
            # [None] * n / base + [x] — a container built by arithmetic
            for side in (value.left, value.right):
                if _ClassModel._classify(side) == "container":
                    return "container"
            return "scalar"
        if isinstance(value, (ast.UnaryOp, ast.Compare, ast.BoolOp)):
            return "scalar"
        if isinstance(value, ast.Call):
            la = last_attr(value.func)
            if la in _CONTAINER_CTORS:
                return "container"
            if la in _SYNC_CTORS:
                return "primitive"
            if la in ("int", "float", "bool", "str", "tuple", "max",
                      "min", "abs", "round"):
                return "scalar"
        return "opaque"

    # ------------------------------------------------------------ info
    @property
    def concurrent(self) -> bool:
        return bool(self.locks) or self.starts_thread or any(
            f.kind == "primitive" for f in self.fields.values())

    def canonical_lock(self, attr: str) -> Optional[str]:
        """Resolve condition aliases (``_cv`` wrapping ``_lock``)."""
        if attr in self.alias:
            return self.alias[attr]
        if attr in self.locks:
            return attr
        return None

    def client_roots(self) -> List[Tuple[ast.AST, str]]:
        thread_fns = {id(fn) for fn, _ in self.thread_roots}
        roots = []
        for name, fn in self.methods.items():
            if name == "__init__" or name in self.single_threaded:
                continue
            if id(fn) in thread_fns:
                continue
            if name in self.entry_marks:
                roots.append((fn, self.entry_marks[name]))
                continue
            public = not name.startswith("_") or name in (
                "__call__", "__enter__", "__exit__", "__iter__",
                "__next__")
            if public:
                roots.append((fn, CLIENT))
        return roots


# ------------------------------------------------------------ the walk

@dataclasses.dataclass(frozen=True)
class _LockEdge:
    held: Tuple[str, str]        # (class, lock attr)
    acquired: Tuple[str, str]
    ctx: ModuleContext
    node: ast.AST


class _Analysis:
    """One whole-program concurrency analysis over a module set."""

    MAX_DEPTH = 24

    def __init__(self, contexts: List[ModuleContext]):
        self.classes: Dict[str, _ClassModel] = {}
        for ctx in contexts:
            for node in ctx.tree.body:
                if isinstance(node, ast.ClassDef):
                    model = _ClassModel(ctx, node)
                    # first definition wins (names are unique in this
                    # tree; a collision would only widen the analysis)
                    self.classes.setdefault(model.name, model)
        self.edges: List[_LockEdge] = []
        self._edge_keys: Set[Tuple[Tuple[str, str], Tuple[str, str],
                                   str, int]] = set()
        self.findings: List[Finding] = []
        self._seen: Set[Tuple[str, int, str, FrozenSet]] = set()

    # -------------------------------------------------------------- run
    def run(self) -> List[Finding]:
        for model in self.classes.values():
            if not model.concurrent:
                continue
            for fn, group in model.thread_roots:
                self._visit(model, fn, group, frozenset(), 0)
            for fn, group in model.client_roots():
                self._visit(model, fn, group, frozenset(), 0)
        self._check_fields()
        self._check_cycles()
        return self.findings

    # ------------------------------------------------------------ visit
    def _visit(self, model: _ClassModel, fn: ast.AST, group: str,
               held: FrozenSet[Tuple[str, str]], depth: int) -> None:
        if depth > self.MAX_DEPTH:
            return
        name = getattr(fn, "name", "<lambda>")
        held = held | frozenset(
            (model.name, model.canonical_lock(req) or req)
            for req in model.requires.get(name, ()))
        key = (model.name, id(fn), group, held)
        if key in self._seen:
            return
        self._seen.add(key)
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        self._scan_stmts(model, body, group, held, depth)

    def _scan_stmts(self, model: _ClassModel, stmts, group: str,
                    held: FrozenSet, depth: int) -> None:
        for stmt in stmts:
            self._scan_node(model, stmt, group, held, depth)

    def _scan_node(self, model: _ClassModel, node: ast.AST, group: str,
                   held: FrozenSet, depth: int) -> None:
        if isinstance(node, (_FuncDef + (ast.Lambda,))):
            # nested defs run who-knows-where (callbacks); they are
            # analyzed only when rooted as thread targets
            return
        if isinstance(node, ast.With):
            # items acquire left-to-right: each later item is taken
            # while the earlier ones are held, so `with self._a,
            # self._b:` records the a->b edge like nested withs do
            inner = held
            for item in node.items:
                attr = _self_attr(item.context_expr)
                if attr is None:
                    self._scan_node(model, item.context_expr, group,
                                    inner, depth)
                    continue
                lock = model.canonical_lock(attr)
                if lock is None:
                    self._scan_node(model, item.context_expr, group,
                                    inner, depth)
                    continue
                acq = (model.name, lock)
                for h in inner:
                    if h == acq and model.locks.get(lock) != "lock":
                        continue        # re-entrant RLock/Condition
                    self._add_edge(h, acq, model.ctx, node)
                inner = inner | frozenset((acq,))
            self._scan_stmts(model, node.body, group, inner, depth)
            return
        if isinstance(node, ast.Try):
            self._scan_stmts(model, node.body, group, held, depth)
            for handler in node.handlers:
                self._scan_stmts(model, handler.body, group, held, depth)
            self._scan_stmts(model, node.orelse, group, held, depth)
            self._scan_stmts(model, node.finalbody, group, held, depth)
            return
        # expression-level handling first (so calls/accesses on this
        # statement are recorded with the current held set)
        self._scan_exprs(model, node, group, held, depth)
        for field in ("body", "orelse", "finalbody"):
            sub = getattr(node, field, None)
            if isinstance(sub, list):
                self._scan_stmts(model, sub, group, held, depth)

    def _scan_exprs(self, model: _ClassModel, stmt: ast.AST, group: str,
                    held: FrozenSet, depth: int) -> None:
        """Record accesses/calls in ``stmt``'s expressions (bodies of
        compound statements are handled by the caller)."""
        skip_fields = {"body", "orelse", "finalbody", "handlers"}
        stack = [child for name, child in ast.iter_fields(stmt)
                 if name not in skip_fields]
        flat: List[ast.AST] = []
        for child in stack:
            if isinstance(child, ast.AST):
                flat.append(child)
            elif isinstance(child, list):
                flat.extend(c for c in child if isinstance(c, ast.AST))
        for root in flat:
            for node in ast.walk(root):
                if isinstance(node, (_FuncDef + (ast.Lambda,))):
                    continue
                self._record(model, node, group, held, depth)

    # ---------------------------------------------------------- record
    def _record(self, model: _ClassModel, node: ast.AST, group: str,
                held: FrozenSet, depth: int) -> None:
        ctx = model.ctx
        parent = ctx.parent(node)
        attr = _self_attr(node)
        if attr is not None:
            if attr in model.locks:
                return
            # self.m() / self.prop — walk, don't record a field access
            if attr in model.methods:
                fn = model.methods[attr]
                is_call = isinstance(parent, ast.Call) \
                    and parent.func is node
                if is_call or attr in model.properties:
                    self._call(model, attr, group, held, depth, node)
                return
            kind = self._access_kind(ctx, node, parent)
            if kind is not None:
                field = model.fields.setdefault(attr, _Field(attr))
                field.accesses.append(
                    _Access(group, kind, ctx, node, held))
            return
        # self.f.m() / self.f.attr — one level through a typed field
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Attribute):
            base = _self_attr(node.value)
            if base is None:
                return
            target_cls = model.field_class.get(base)
            target = self.classes.get(target_cls) if target_cls else None
            if target is None or not target.concurrent:
                return
            sub = node.attr
            if sub in target.methods:
                is_call = isinstance(parent, ast.Call) \
                    and parent.func is node
                if is_call or sub in target.properties:
                    self._call(target, sub, group, held, depth, node)
                return
            kind = self._access_kind(ctx, node, parent)
            if kind is not None:
                field = target.fields.setdefault(sub, _Field(sub))
                field.accesses.append(
                    _Access(group, kind, ctx, node, held))

    def _call(self, model: _ClassModel, name: str, group: str,
              held: FrozenSet, depth: int, site: ast.AST) -> None:
        if name in model.single_threaded:
            return
        if name in model.entry_marks \
                and model.entry_marks[name] != group:
            # the method runs on its own declared thread; its accesses
            # are attributed by its own entry walk, not this caller's
            return
        required = model.requires.get(name, set())
        missing = [req for req in required
                   if (model.name, model.canonical_lock(req) or req)
                   not in held]
        if missing:
            self._finding(
                "requires-lock-violation", model.ctx, site,
                f"call of `{model.name}.{name}` requires holding "
                f"`{'`/`'.join(sorted(missing))}` "
                f"(# graftlint: requires-lock) but no caller on this "
                f"path acquires it")
        self._visit(model, model.methods[name], group, held, depth + 1)

    @staticmethod
    def _access_kind(ctx: ModuleContext, node: ast.AST,
                     parent: Optional[ast.AST]) -> Optional[str]:
        """Classify one ``self.X`` occurrence.

        Returns ``"write"`` (rebind, subscript store, in-place
        mutator), ``"iter"`` (whole-container traversal — the
        cross-thread ``RuntimeError`` / torn-read shape), ``"read"``
        (plain load), or ``"atomic"`` for single-atomic ops (``len``,
        subscript load, membership, ``next``).  Atomic ops are safe
        under the GIL and never count toward the *sharing hazard*, but
        they ARE recorded: a field *declared* ``guarded-by`` is
        checked at every access — the discipline the runtime sanitizer
        enforces too, so a graftlint-clean tree cannot fail the strict
        chaos soaks on a statically-sanctioned accessor."""
        if isinstance(getattr(node, "ctx", None), (ast.Store, ast.Del)):
            return "write"
        if isinstance(parent, ast.AugAssign) and parent.target is node:
            return "write"
        if isinstance(parent, ast.Subscript) and parent.value is node:
            if isinstance(parent.ctx, (ast.Store, ast.Del)):
                return "write"
            return "atomic"                  # atomic subscript load
        if isinstance(parent, ast.Attribute) and parent.value is node:
            grand = ctx.parent(parent)
            if isinstance(grand, ast.Call) and grand.func is parent:
                if parent.attr in _MUTATORS:
                    return "write"
                if parent.attr in _ITER_VIEW_METHODS:
                    return "iter"            # live view: traversal
                return "read"                # unknown method: plain read
            return "read"
        if isinstance(parent, ast.Call):
            fn_name = parent.func.id \
                if isinstance(parent.func, ast.Name) else None
            if parent.func is node:
                return "read"                # calling the field
            if fn_name in _ATOMIC_CALLS:
                return "atomic"
            if fn_name in _ITERATING_CALLS:
                return "iter"
            return "iter"        # unknown callee: conservative escape
        if isinstance(parent, (ast.For, ast.comprehension)) \
                and getattr(parent, "iter", None) is node:
            return "iter"
        if isinstance(parent, ast.Starred):
            return "iter"
        if isinstance(parent, ast.Compare) and node in parent.comparators \
                and any(isinstance(op, (ast.In, ast.NotIn))
                        for op in parent.ops):
            return "atomic"                  # atomic membership test
        return "read"

    # --------------------------------------------------------- findings
    def _finding(self, rule: str, ctx: ModuleContext, node: ast.AST,
                 message: str) -> None:
        f = Finding(rule, ctx.path, getattr(node, "lineno", 1),
                    getattr(node, "col_offset", 0) + 1, message)
        if f not in self.findings:
            self.findings.append(f)

    def _check_fields(self) -> None:
        for model in self.classes.values():
            if not model.concurrent:
                continue
            for field in model.fields.values():
                self._check_field(model, field)

    def _check_field(self, model: _ClassModel, field: _Field) -> None:
        if field.kind == "primitive":
            return
        anchor_ctx = field.init_ctx or model.ctx
        anchor = field.init_node or model.node
        if field.guard is not None:
            lock = model.canonical_lock(field.guard)
            if lock is None:
                self._finding(
                    "guarded-by-violation", anchor_ctx, anchor,
                    f"`{model.name}.{field.name}` declares guarded-by"
                    f"({field.guard}) but `{field.guard}` is not a "
                    f"lock attribute of {model.name}")
                return
            need = (model.name, lock)
            for access in field.accesses:
                if need not in access.held:
                    self._finding(
                        "guarded-by-violation", access.ctx, access.node,
                        f"`{model.name}.{field.name}` is declared "
                        f"guarded-by({field.guard}) but this "
                        f"{access.kind} (thread group `{access.group}`)"
                        f" does not hold it — wrap the access in "
                        f"`with self.{field.guard}:` or mark the "
                        f"method `# graftlint: requires-lock"
                        f"({field.guard})`")
            return
        if field.unguarded_reason is not None:
            if not field.unguarded_reason.strip():
                self._finding(
                    "unguarded-shared-field", anchor_ctx, anchor,
                    f"`{model.name}.{field.name}` is marked unguarded() "
                    f"with no justification — the reason is the point "
                    f"of the annotation; say why the race is benign")
            return
        write_groups = field.groups("write")
        iter_groups = field.groups("iter")
        # scalars written from one group and read elsewhere are the
        # CPython-safe single-writer publish idiom; the iteration
        # hazard (torn traversal, deque/dict RuntimeError) is a
        # container/opaque-object property
        shared = len(write_groups) >= 2 or (
            field.kind in ("container", "opaque")
            and write_groups and (iter_groups - write_groups))
        if not shared:
            return
        touches = sorted(write_groups | iter_groups)
        self._finding(
            "unguarded-shared-field", anchor_ctx, anchor,
            f"`{model.name}.{field.name}` ({field.kind}) is touched "
            f"from multiple thread groups ({', '.join(touches)}: "
            f"writes from {sorted(write_groups)}, iteration from "
            f"{sorted(iter_groups - write_groups) or '[]'}) with no "
            f"declared discipline — annotate its __init__ assignment "
            f"`# graftlint: guarded-by(<lock>)` (and hold the lock at "
            f"every access) or `# graftlint: unguarded(<why the race "
            f"is benign>)`")

    # ------------------------------------------------------------ edges
    def _add_edge(self, held: Tuple[str, str], acq: Tuple[str, str],
                  ctx: ModuleContext, node: ast.AST) -> None:
        key = (held, acq, ctx.path, getattr(node, "lineno", 0))
        if key in self._edge_keys:
            return
        self._edge_keys.add(key)
        self.edges.append(_LockEdge(held, acq, ctx, node))

    def _check_cycles(self) -> None:
        graph: Dict[Tuple[str, str], Set[Tuple[str, str]]] = {}
        witness: Dict[Tuple[Tuple[str, str], Tuple[str, str]],
                      _LockEdge] = {}
        for edge in self.edges:
            if edge.held == edge.acquired:
                # self-edge on a plain Lock: guaranteed self-deadlock
                self._finding(
                    "lock-order-cycle", edge.ctx, edge.node,
                    f"`{edge.held[0]}.{edge.held[1]}` is re-acquired "
                    f"while already held — a non-reentrant Lock "
                    f"deadlocks here; use an RLock or restructure")
                continue
            graph.setdefault(edge.held, set()).add(edge.acquired)
            witness.setdefault((edge.held, edge.acquired), edge)
        for scc in _find_cycles(graph):
            cycle = _cycle_in_scc(graph, scc)
            if cycle is None:       # pragma: no cover - SCC guarantees one
                continue
            pairs = list(zip(cycle, cycle[1:] + cycle[:1]))
            edges = [witness[p] for p in pairs if p in witness]
            if not edges:           # pragma: no cover - pairs are edges
                continue
            edges.sort(key=lambda e: (e.ctx.path,
                                      getattr(e.node, "lineno", 0)))
            names = " -> ".join(f"{c}.{a}" for c, a in cycle)
            sites = "; ".join(
                f"{e.held[0]}.{e.held[1]}->{e.acquired[0]}."
                f"{e.acquired[1]} at {e.ctx.path}:"
                f"{getattr(e.node, 'lineno', 0)}" for e in edges)
            self._finding(
                "lock-order-cycle", edges[0].ctx, edges[0].node,
                f"lock-order cycle {names} -> {cycle[0][0]}."
                f"{cycle[0][1]} — two threads taking these locks in "
                f"opposite orders deadlock; witnesses: {sites}")


def _cycle_in_scc(graph: Dict[Tuple[str, str], Set[Tuple[str, str]]],
                  scc: List[Tuple[str, str]]
                  ) -> Optional[List[Tuple[str, str]]]:
    """An actual elementary cycle through ``scc[0]`` built from
    witnessed edges only: BFS from each successor back to the start,
    restricted to the SCC.  Every adjacent pair of the returned list
    (wrapping) is a real edge of ``graph`` — the sorted node order of
    the SCC itself need not be (a 3-lock cycle oriented against the
    sort would otherwise be dropped as witness-less)."""
    scc_set = set(scc)
    start = scc[0]
    for succ in sorted(graph.get(start, ())):
        if succ not in scc_set:
            continue
        prev: Dict[Tuple[str, str], Tuple[str, str]] = {succ: start}
        queue = [succ]
        while queue and start not in prev:
            v = queue.pop(0)
            for w in sorted(graph.get(v, ())):
                if w in scc_set and w not in prev:
                    prev[w] = v
                    queue.append(w)
        if start not in prev:
            continue
        # prev[x] -> x is an edge; walk back from start to succ
        back = []
        v = prev[start]
        while v != start:
            back.append(v)
            v = prev[v]
        return [start] + back[::-1]     # start -> succ -> ... -> back
    return None


def _find_cycles(graph: Dict[Tuple[str, str], Set[Tuple[str, str]]]
                 ) -> List[List[Tuple[str, str]]]:
    """Elementary cycles via SCC decomposition (one report per SCC:
    the cycle along a back-path inside it — enough to name the locks
    and a witness, without enumerating every permutation)."""
    index: Dict[Tuple[str, str], int] = {}
    low: Dict[Tuple[str, str], int] = {}
    on_stack: Set[Tuple[str, str]] = set()
    stack: List[Tuple[str, str]] = []
    sccs: List[List[Tuple[str, str]]] = []
    counter = [0]

    def strongconnect(v):
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        for w in sorted(graph.get(v, ())):
            if w not in index:
                strongconnect(w)
                low[v] = min(low[v], low[w])
            elif w in on_stack:
                low[v] = min(low[v], index[w])
        if low[v] == index[v]:
            scc = []
            while True:
                w = stack.pop()
                on_stack.discard(w)
                scc.append(w)
                if w == v:
                    break
            if len(scc) > 1:
                sccs.append(sorted(scc))

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)
    return sccs


def analyze_program(contexts: List[ModuleContext]) -> List[Finding]:
    """Run the concurrency analysis; returns every finding (all three
    rules) unfiltered — the runner applies suppressions."""
    return _Analysis(list(contexts)).run()


# ------------------------------------------------------- program rules

class _ConcurrencyRule(ProgramRule):
    """Shared driver: the analysis runs once per program (memoized on
    the Program object by :meth:`prepare`, which the runner times
    under the ``concurrency-pass`` row — not whichever of the four
    rules happens to run first); each registered rule yields its
    slice."""

    shared_pass = "concurrency-pass"

    def prepare(self, program) -> None:
        if getattr(program, "_concurrency_findings", None) is None:
            program._concurrency_findings = analyze_program(
                program.contexts)

    def check_program(self, program) -> Iterator[Finding]:
        self.prepare(program)
        for finding in program._concurrency_findings:
            if finding.rule == self.name:
                yield finding


@register_program
class UnguardedSharedField(_ConcurrencyRule):
    """Rule C1 — multi-thread-reachable field with no lock discipline.

    A ``self.*`` field mutated from two thread groups — or mutated in
    one and iterated in another (the cross-thread deque
    ``RuntimeError`` shape) — with neither a ``guarded-by(<lock>)``
    nor a justified ``unguarded(<why>)`` annotation on its ``__init__``
    assignment.
    """

    name = "unguarded-shared-field"
    summary = ("instance field touched from multiple thread entry "
               "points without guarded-by/unguarded annotation")


@register_program
class GuardedByViolation(_ConcurrencyRule):
    """Rule C2 — access to a ``guarded-by`` field without its lock.

    The declared lock (condition aliases resolved) must be held —
    lexically or through a ``requires-lock``-marked caller — at every
    access of an annotated field.
    """

    name = "guarded-by-violation"
    summary = ("guarded-by(<lock>) field accessed on a path that does "
               "not hold the declared lock")


@register_program
class RequiresLockViolation(_ConcurrencyRule):
    """Rule C3 — ``requires-lock`` method called without the lock.

    ``# graftlint: requires-lock(<lock>)`` on a ``def`` asserts the
    caller holds the lock; a call reached on a path that does not is
    flagged at the call site.
    """

    name = "requires-lock-violation"
    summary = ("method marked requires-lock(<lock>) called on a path "
               "that does not hold the lock")


@register_program
class LockOrderCycle(_ConcurrencyRule):
    """Rule C4 — cyclic lock-acquisition order (potential deadlock).

    Built from the static nesting of ``with self.<lock>:`` regions and
    the calls made while they are held (across classes through typed
    fields).  Any cycle — including re-acquiring a non-reentrant
    ``Lock`` — is reported with its witness sites.
    """

    name = "lock-order-cycle"
    summary = ("cyclic with-lock nesting across the call graph — "
               "potential deadlock (witnesses listed)")
