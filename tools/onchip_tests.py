"""Run the kernel test subset on the REAL TPU chip and record the
result as a repo artifact (round-4 verdict weak #3: interpret-mode CI
cannot catch Mosaic-only miscompiles — e.g. the round-3 GroupNorm
sequential-grid assumption — so each round records one on-chip pass).

The subset is the Pallas-kernel golden suites (attention / layer norm /
ops / optim incl. the fp8-Adam kernel) — the tests whose CPU runs go
through interpret mode and therefore prove nothing about Mosaic
compilation.  Distributed/mesh suites stay CPU-only (one real chip).

Usage:  python tools/onchip_tests.py          # writes ONCHIP_r{N}.json
"""

from __future__ import annotations

import json
import os
import re
import subprocess
import sys
import time

SUBSET = [
    "tests/test_attention.py",
    "tests/test_batch_norm.py",    # fused BN(+add+ReLU) kernels (ISSUE 3)
    # paged-attention decode kernel (ISSUE 5): scalar-prefetch block
    # tables + the DMA-skip clamp are exactly what interpret mode
    # cannot prove — the gather path must run on the real chip.  The
    # quantized twin (ISSUE 8) adds the int8/fp8 page DMA + the (1,1)
    # per-page scale blocks through the same index maps — Mosaic must
    # compile the in-register dequant and the 1-byte tiles for real
    "tests/test_paged_attention.py",
    # prefix-shared CoW pages + speculative decoding (ISSUE 7): the
    # refcount/trie accounting and the drafted-step verify rollback
    # must hold against REAL pool pages — on chip a leaked or
    # double-freed page corrupts a co-tenant's KV instead of a numpy
    # shadow, and the spec_step executable must Mosaic-compile at its
    # 1+K width.  TestQuantizedKV (ISSUE 8) additionally pins the
    # quantize-on-write scatter + scale reset against real HBM pages
    "tests/test_paged_serving.py",
    # fused decode epilogue (ISSUE 14): the one-pass sampling kernel
    # must Mosaic-compile for real (radix descents, in-kernel threefry
    # replay, VMEM scratch) and its key-for-key chain identity to
    # sample_dynamic must hold on-chip where the COMPILED kernel — not
    # interpret mode — draws the tokens
    "tests/test_fused_sampling.py",
    "tests/test_layer_norm.py",
    "tests/test_ops.py",
    "tests/test_optim.py",
    # resilience layer (ISSUE 4): checkpoint atomicity/manifests and
    # the fault/rewind/preempt machinery against the REAL TPU runtime
    # — interpret-mode CPU proves nothing about on-chip donation,
    # device_get snapshots, or orbax sharded writes
    "tests/test_resilience.py",
    # serving fleet (ISSUE 6): the router/breaker unit tier plus the
    # chaos soaks (replica kill + drain) — on chip the kill path
    # abandons REAL device buffers and migration re-prefills on a
    # survivor's live pool, which CPU timing cannot exercise honestly
    "tests/test_fleet.py",
    # graftlint v2 runtime twin (ISSUE 9): the lock sanitizer's own
    # unit tier, and the chaos soaks below run the real stack under
    # strict instrumentation — on chip the worker/supervisor timing is
    # the honest interleaving the order recorder is meant to observe
    "tests/test_lockcheck.py",
    # graftlint v3 runtime twin (ISSUE 10): the numerics sanitizer's
    # unit tier — on chip the fp16 downcast-overflow and underflow
    # paths run against real MXU/VPU rounding, not the CPU emulation
    "tests/test_numcheck.py",
    # graftlint v4 runtime twin (ISSUE 16): the placement sanitizer's
    # unit tier — on chip the declared-vs-actual comparisons run
    # against REAL committed shardings (not the virtual CPU mesh) and
    # the transfer windows see real device->host DMA, not zero-copy
    "tests/test_shardcheck.py",
    # ZeRO-1/2 (ISSUE 11): the reduce-scatter/all-gather choreography,
    # the int8 wire leg and the sharded-checkpoint placement must run
    # against REAL ICI collectives and per-device HBM — the virtual
    # CPU mesh proves the math, not the placement or the wire
    "tests/test_zero.py",
    # tensor-parallel paged serving (ISSUE 13): the shard_map'ed paged
    # kernel (per-chip head slices, replicated block tables), the
    # sharded pool/scale placement fixed point behind the 5×1 retrace
    # budgets, and the TP↔single-chip token identity must hold against
    # REAL per-chip HBM pools and ICI all-reduces — the virtual CPU
    # mesh proves the math, not the placement or the wire
    "tests/test_tp_serving.py",
    # the planner (ISSUE 15): pure host-side arithmetic, but the
    # autotune-adoption seam reads the chip's REAL cache entries and
    # the emitted placements commit onto real devices — cheap to run,
    # catches a planner/engine key drift on the hardware that matters
    "tests/test_plan.py",
    # pipeline parallelism (ISSUE 20): the 1F1B schedule's ppermute
    # ring, the stage-local ZeRO placement and the single-trace budget
    # must hold against REAL ICI neighbor links and per-chip HBM — the
    # virtual CPU mesh proves the schedule math, not the wire or the
    # per-stage residency
    "tests/test_pipeline.py",
    "tests/test_chaos.py",
]


def main():
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["APEX_TPU_TEST_PLATFORM"] = os.environ.get(
        "APEX_TPU_TEST_PLATFORM", "axon")
    t0 = time.time()
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", *SUBSET, "-q"],
        cwd=root, env=env, capture_output=True, text=True,
        timeout=7200)
    dt = time.time() - t0
    result_line, m, failed = _parse_summary(proc.stdout or "")
    import jax

    out = {
        "artifact": "on-chip kernel test pass",
        "platform_env": env["APEX_TPU_TEST_PLATFORM"],
        "result_line": result_line,
        "passed": int(m.group(1)) if m else 0,
        "failed": int(failed.group(1)) if failed else 0,
        "returncode": proc.returncode,
        "wall_seconds": round(dt, 1),
        "jax": jax.__version__,
        "libtpu": _libtpu_version(),
        "date": time.strftime("%Y-%m-%d"),
        "subset": SUBSET,
    }
    name = os.environ.get("ONCHIP_ARTIFACT", "ONCHIP_r05.json")
    with open(os.path.join(root, name), "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps(out))
    if proc.returncode != 0:
        print(proc.stdout[-3000:], file=sys.stderr)
        sys.exit(1)


def _parse_summary(stdout: str):
    """Find pytest's ``N passed``/``N failed`` summary in the output tail.

    On green runs pytest -q prints the summary line above trailing
    warnings-summary / coverage chatter, so parsing only the very last
    line recorded 0/0 for successful passes.  Scan bottom-up (no line
    cap: a long tail must not push the summary out of reach; the
    count patterns cannot false-match ordinary test output) for the
    first line with a pass/fail/error count.
    """
    lines = stdout.strip().splitlines()
    for line in reversed(lines):
        m = re.search(r"(\d+) passed", line)
        failed = re.search(r"(\d+) (?:failed|error)", line)
        if m or failed:
            return line, m, failed
    return (lines[-1] if lines else ""), None, None


def _libtpu_version():
    try:
        import importlib.metadata as md

        return md.version("libtpu")
    except Exception:
        return None


if __name__ == "__main__":
    main()
