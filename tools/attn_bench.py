"""Isolated flash-attention kernel benchmark (real chip).

Measures fwd-only and fwd+bwd wall time and useful-TFLOP/s of
``apex_tpu.ops.attention.fused_attention`` at given (b, s, h, d) —
the harness behind BASELINE.md's long-context kernel-rate numbers.

Flop accounting (causal): each of the 9 tile matmuls (fwd: QKᵀ, PV;
dq: S-recompute, dP, dQ; dkv: S-recompute, dP, dV, dK) does
2·b·h·s²·d·0.5 flops; fwd-only = 2 matmuls.  Rates are *useful* flops
(recomputes counted, padding not) per second.

Handles the tunneled chip's ~100 ms fixed call+sync overhead by
iterating inside one jit (lax.scan) and subtracting the measured
trivial-call overhead.

Usage:
    python tools/attn_bench.py [s=32768] [d=64] [h=8] [hk=0] [b=1]
                               [iters=8] [window=0]
(``hk``: GQA kv heads, 0 = MHA; flops are counted per q-head, so GQA
rates are directly comparable with MHA rows.)
"""

from __future__ import annotations

import json
import sys
import time

import jax
import jax.numpy as jnp


def _overhead():
    triv = jax.jit(lambda x: x + 1)
    x = jnp.float32(0)
    jax.device_get(triv(x))
    dts = []
    for _ in range(3):
        t0 = time.perf_counter()
        jax.device_get(triv(x))
        dts.append(time.perf_counter() - t0)
    return min(dts)


def measure(fn, args, iters, overhead, windows=3):  # graftlint: hot-step
    @jax.jit
    def many(q, *rest):
        def body(c, _):
            # thread the carry into q so the call is NOT loop-invariant
            # (XLA hoists an invariant body out of the scan, measuring
            # nothing); scale keeps the perturbation numerically inert
            out = fn(q + c * jnp.bfloat16(1e-8), *rest)
            # fold a scalar from EVERY output leaf into the carry —
            # an unused leaf's entire producing kernel is DCE'd
            acc = jnp.bfloat16(0)
            for lf in jax.tree.leaves(out):
                acc = acc + lf.ravel()[0].astype(jnp.bfloat16)
            return acc, None

        c, _ = jax.lax.scan(body, jnp.bfloat16(0), None, length=iters)
        return c

    out = many(*args)
    jax.device_get(out)  # graftlint: unsharded(warmup barrier — compile before the timed windows)
    dts = []
    for _ in range(windows):
        t0 = time.perf_counter()
        # graftlint: unsharded(the fetch IS the measurement barrier; its cost is subtracted as `overhead`)
        jax.device_get(many(*args))
        dts.append(time.perf_counter() - t0)
    return (min(dts) - overhead) / iters


def main():
    kw = dict(s=32768, d=64, h=8, hk=0, b=1, iters=8, window=0)
    for a in sys.argv[1:]:
        k, v = a.split("=")
        kw[k] = int(v)
    s, d, h, b, iters = (kw[k] for k in ("s", "d", "h", "b", "iters"))
    window = kw["window"] or None
    hk = kw["hk"] or h                   # GQA: fewer kv heads

    from apex_tpu.ops.attention import fused_attention

    q = jax.random.normal(jax.random.PRNGKey(0), (b, s, h, d),
                          jnp.bfloat16)
    k = jax.random.normal(jax.random.PRNGKey(1), (b, s, hk, d),
                          jnp.bfloat16)
    v = jax.random.normal(jax.random.PRNGKey(2), (b, s, hk, d),
                          jnp.bfloat16)

    def fwd(q, k, v):
        return fused_attention(q, k, v, causal=True, window=window,
                               implementation="pallas")

    def fwd_bwd(q, k, v):
        def loss(q, k, v):
            o = fused_attention(q, k, v, causal=True, window=window,
                                implementation="pallas")
            return jnp.sum(o.astype(jnp.float32) ** 2)

        return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    overhead = _overhead()
    dt_f = measure(fwd, (q, k, v), iters, overhead)
    dt_fb = measure(fwd_bwd, (q, k, v), iters, overhead)
    # useful (visible) softmax positions: causal triangle, or the band
    # (window > s executes full attention — clamp so flops stay honest)
    w = min(window or s, s)
    pairs = (w - 1) * w / 2 + (s - w + 1) * w     # sum_q min(q+1, w)
    unit = 2 * b * h * pairs * d                  # one tile-matmul
    print(json.dumps({
        "b": b, "s": s, "h": h, "hk": hk, "d": d, "window": window,
        "call_overhead_ms": round(overhead * 1e3, 1),
        "fwd_ms": round(dt_f * 1e3, 2),
        "fwd_tflops": round(2 * unit / dt_f / 1e12, 2),
        "fwd_bwd_ms": round(dt_fb * 1e3, 2),
        "fwd_bwd_tflops": round(9 * unit / dt_fb / 1e12, 2),
    }))


if __name__ == "__main__":
    main()
