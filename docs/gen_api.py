"""Generate the API reference (docs/api/*.md) from docstrings.

The reference ships a sphinx tree (~2k lines of .rst over autodoc);
here the docstrings are the single source of truth and this script
renders them to markdown — run it after changing public APIs:

    python docs/gen_api.py

Each top-level subpackage becomes one page listing every public symbol
(``__all__`` when defined, else underscore-filtered module globals)
with its signature and full docstring.  A symbol without a docstring is
reported as an error so the "every public symbol documented" invariant
is enforced, not aspirational.
"""

from __future__ import annotations

import dataclasses
import importlib
import inspect
import pathlib
import sys

PAGES = {
    "amp": ["apex_tpu.amp", "apex_tpu.amp.frontend", "apex_tpu.amp.lists",
            "apex_tpu.amp.o1"],
    "core": ["apex_tpu.core.precision", "apex_tpu.core.loss_scale",
             "apex_tpu.core.train_state", "apex_tpu.core.mesh"],
    "ops": ["apex_tpu.ops.attention", "apex_tpu.ops.paged_attention",
            "apex_tpu.ops.fused_sampling",
            "apex_tpu.ops.multihead_attn",
            "apex_tpu.ops.layer_norm", "apex_tpu.ops.softmax",
            "apex_tpu.ops.rope", "apex_tpu.ops.mlp",
            "apex_tpu.ops.xentropy", "apex_tpu.ops.group_norm",
            "apex_tpu.ops.batch_norm", "apex_tpu.ops.autotune"],
    "optim": ["apex_tpu.optim.fused_adam", "apex_tpu.optim.fused_lamb",
              "apex_tpu.optim.fused_sgd", "apex_tpu.optim.fused_novograd",
              "apex_tpu.optim.fused_adagrad",
              "apex_tpu.optim.fused_mixed_precision_lamb",
              "apex_tpu.optim.larc", "apex_tpu.optim.clip",
              "apex_tpu.optim._multi_tensor"],
    "parallel": ["apex_tpu.parallel.ddp", "apex_tpu.parallel.sync_batchnorm",
                 "apex_tpu.parallel.ring_attention",
                 "apex_tpu.parallel.distributed_optim",
                 "apex_tpu.parallel.pipeline",
                 "apex_tpu.parallel.launch"],
    "plan": ["apex_tpu.plan", "apex_tpu.plan.costs",
             "apex_tpu.plan.enumerate", "apex_tpu.plan.score",
             "apex_tpu.plan.emit", "apex_tpu.plan.calibrate"],
    "transformer": ["apex_tpu.transformer.layers",
                    "apex_tpu.transformer.mappings",
                    "apex_tpu.transformer.cross_entropy",
                    "apex_tpu.transformer.random",
                    "apex_tpu.transformer.data",
                    "apex_tpu.transformer.moe",
                    "apex_tpu.transformer.microbatches",
                    "apex_tpu.transformer.parallel_state",
                    "apex_tpu.transformer.pipeline_parallel.schedules",
                    "apex_tpu.transformer.pipeline_parallel.build",
                    "apex_tpu.transformer.pipeline_parallel.p2p"],
    "contrib": ["apex_tpu.contrib", "apex_tpu.contrib.fmha",
                "apex_tpu.contrib.focal_loss",
                "apex_tpu.contrib.index_mul_2d",
                "apex_tpu.contrib.transducer", "apex_tpu.contrib.groupbn",
                "apex_tpu.contrib.conv_bias_relu",
                "apex_tpu.contrib.bottleneck",
                "apex_tpu.contrib.peer_memory",
                "apex_tpu.contrib.sparsity"],
    "models": ["apex_tpu.models.bert", "apex_tpu.models.gpt",
               "apex_tpu.models.vit", "apex_tpu.models.resnet",
               "apex_tpu.models.transformer",
               "apex_tpu.models.generate",
               "apex_tpu.models.torch_import"],
    "serving": ["apex_tpu.serving.api", "apex_tpu.serving.engine",
                "apex_tpu.serving.scheduler", "apex_tpu.serving.cache",
                "apex_tpu.serving.fleet"],
    "resilience": ["apex_tpu.resilience.faults",
                   "apex_tpu.resilience.checkpointing",
                   "apex_tpu.resilience.trainer"],
    "utils": ["apex_tpu.utils.checkpoint", "apex_tpu.utils.profiler",
              "apex_tpu.utils.debug", "apex_tpu.utils.metrics",
              "apex_tpu.utils.tree", "apex_tpu.utils.jax_compat",
              "apex_tpu.utils.lockcheck", "apex_tpu.utils.numcheck",
              "apex_tpu.utils.shardcheck"],
    "fp16_utils": ["apex_tpu.fp16_utils"],
    "data": ["apex_tpu.data"],
}


def _public_names(mod):
    if hasattr(mod, "__all__"):
        return list(mod.__all__)
    return [n for n, v in vars(mod).items()
            if not n.startswith("_") and getattr(v, "__module__", None)
            == mod.__name__]


def _signature(obj):
    import re

    try:
        sig = str(inspect.signature(obj))
    except (ValueError, TypeError):
        return ""
    # strip live object addresses (sentinel defaults etc.) so the
    # generated docs are deterministic across machines/runs
    return re.sub(r" at 0x[0-9a-f]+", "", sig)


def _render_symbol(name, obj, errors, qual):
    lines = []
    kind = ("class" if inspect.isclass(obj)
            else "function" if callable(obj) else "data")
    sig = _signature(obj) if kind != "data" else ""
    lines.append(f"### `{name}{sig}`\n")
    doc = inspect.getdoc(obj)
    if kind == "data" and type(obj).__module__ == "builtins" \
            and doc == inspect.getdoc(type(obj)):
        # a bare BUILTIN constant (str/int/tuple instance) "inherits"
        # its type's docstring through getdoc — boilerplate ("Create a
        # new string object..."), not documentation.  Project-class
        # singletons (e.g. metrics.counters) keep their class
        # docstring: for those the fallback IS the documentation.
        doc = None
    if not doc:
        if kind == "data":
            doc = f"*(module-level data: `{type(obj).__name__}`)*"
        else:
            errors.append(qual)
            doc = "**UNDOCUMENTED**"
    lines.append(doc + "\n")
    if inspect.isclass(obj):
        if dataclasses.is_dataclass(obj):
            fields = ", ".join(
                f"`{f.name}`" for f in dataclasses.fields(obj))
            if fields:
                lines.append(f"*Fields:* {fields}\n")
        for mname, m in sorted(vars(obj).items()):
            if mname.startswith("_") or not callable(m):
                continue
            mdoc = inspect.getdoc(m)
            if mdoc:
                first = mdoc.splitlines()[0]
                lines.append(
                    f"- **`.{mname}{_signature(m)}`** — {first}")
        lines.append("")
    return "\n".join(lines)


def main():
    out_dir = pathlib.Path(__file__).parent / "api"
    out_dir.mkdir(exist_ok=True)
    errors = []
    index = ["# API reference\n",
             "Generated from docstrings by `python docs/gen_api.py` — "
             "regenerate after public-API changes.\n"]
    for page, modules in PAGES.items():
        parts = [f"# `apex_tpu` API — {page}\n"]
        for modname in modules:
            mod = importlib.import_module(modname)
            parts.append(f"## module `{modname}`\n")
            mdoc = inspect.getdoc(mod)
            if mdoc:
                parts.append(mdoc + "\n")
            else:
                errors.append(modname)
            for name in _public_names(mod):
                obj = getattr(mod, name)
                parts.append(_render_symbol(
                    name, obj, errors, f"{modname}.{name}"))
        (out_dir / f"{page}.md").write_text("\n".join(parts))
        index.append(f"- [{page}]({page}.md)")
    (out_dir / "index.md").write_text("\n".join(index) + "\n")
    if errors:
        print("UNDOCUMENTED public symbols:", file=sys.stderr)
        for e in errors:
            print(f"  {e}", file=sys.stderr)
        sys.exit(1)
    n = sum(1 for _ in out_dir.glob("*.md"))
    print(f"wrote {n} pages to {out_dir}")


if __name__ == "__main__":
    main()
