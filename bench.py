"""Benchmark harness — north-star metric on real TPU hardware.

Emits ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Metric (BASELINE.json north star): BERT-Large pretraining train-step
throughput, samples/sec/chip, with the full apex-O2-equivalent stack —
precision policy O2 (bf16 compute, fp32 masters), FusedAdam, fused
(Pallas) layer norm + flash attention.  ``vs_baseline`` is the measured
speedup over the same model run at O0 (pure fp32, plain optax adam,
XLA-composition ops) — the reference's advertised amp+fusion gain,
measured rather than quoted (BASELINE.md: no number published in-repo).

Env knobs: BENCH_BATCH, BENCH_SEQ, BENCH_STEPS, BENCH_TINY=1 (smoke).
"""

from __future__ import annotations

import functools
import json
import os
import time


def _build(cfg_kw, opt_level, half_dtype, fused):
    import jax
    import jax.numpy as jnp
    import optax

    from apex_tpu import amp
    from apex_tpu.models import BertConfig, BertModel, bert_mlm_loss_fn
    from apex_tpu.optim import fused_adam

    # measured fastest on v5e (see PROGRESS notes): unrolled layers beat
    # nn.scan by ~26% (XLA schedules across layer boundaries), full
    # remat beats dots-saveable (HBM bandwidth > recompute FLOPs here)
    cfg_kw.setdefault("scan_layers", False)
    cfg = BertConfig.bert_large(**cfg_kw) if not int(
        os.environ.get("BENCH_TINY", "0")) else BertConfig.tiny(**cfg_kw)
    model = BertModel(cfg)
    tx = fused_adam(1e-4) if fused else optax.adam(1e-4)

    b = int(os.environ.get("BENCH_BATCH", "16"))
    s = int(os.environ.get("BENCH_SEQ", str(min(cfg.max_seq_len, 512))))
    # BERT pretraining gathers the ~15% masked positions before the
    # vocab projection (max_predictions_per_seq); P=80 ≈ 0.15*512
    # rounded to the nearest fp32 sublane multiple
    p = min(max(8, int(0.15 * s / 8 + 0.5) * 8), s)
    rng = jax.random.PRNGKey(0)
    ids = jax.random.randint(rng, (b, s), 0, cfg.vocab_size)
    positions = jax.numpy.argsort(
        jax.random.uniform(rng, (b, s)), axis=-1)[:, :p]
    mlm_labels = jax.numpy.take_along_axis(ids, positions, axis=1)

    params = model.init(jax.random.PRNGKey(0), ids[:2])
    state = amp.initialize(model.apply, params, tx, opt_level=opt_level,
                           half_dtype=half_dtype)

    # donate the state: in-place param/opt-state updates (~2% step time,
    # and frees a full copy of the fp32 masters + adam moments in HBM)
    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(state, ids, positions, mlm_labels):
        def loss_fn(p_):
            cp = state.policy.cast_to_compute(p_)
            logits, _ = state.apply_fn(
                cp, ids, mlm_positions=positions, deterministic=True)
            loss = bert_mlm_loss_fn(
                logits.astype(jnp.float32), mlm_labels)
            return state.scale_loss(loss), loss

        grads, loss = jax.grad(loss_fn, has_aux=True)(state.params)
        new_state, finite = state.apply_gradients(grads=grads)
        return new_state, loss, finite

    return state, step, (ids, positions, mlm_labels), b


def _sync(state):
    """Force full execution.  On the axon (tunneled-TPU) backend
    ``block_until_ready`` returns before execution finishes — only a
    host transfer truly syncs, so fetch one scalar off the final state
    (it depends transitively on every step)."""
    import jax

    leaf = jax.tree.leaves(state.params)[0]
    jax.device_get(leaf.ravel()[0])


def _measure(state, step, batch, n_steps, warmup=3):
    for _ in range(warmup):
        state, loss, finite = step(state, *batch)
    _sync(state)
    t0 = time.perf_counter()
    for _ in range(n_steps):
        state, loss, finite = step(state, *batch)
    _sync(state)
    dt = (time.perf_counter() - t0) / n_steps
    return dt, float(loss), bool(finite)


def main():
    import jax
    import jax.numpy as jnp

    cfg_kw = {"remat": True, "dtype": jnp.float32}
    n_steps = int(os.environ.get("BENCH_STEPS", "20"))

    # O2 + FusedAdam + fused kernels (the north-star stack)
    state, step, batch, b = _build(
        dict(cfg_kw, dtype=jnp.bfloat16), "O2", jnp.bfloat16, fused=True)
    dt_o2, loss, finite = _measure(state, step, batch, n_steps)
    del state, step

    # O0 fp32 + plain optax adam (the "eager" baseline).  Force true
    # fp32 matmuls: TPU's default precision would silently run bf16
    # passes, understating the O2 gain.
    with jax.default_matmul_precision("highest"):
        state, step, batch, _ = _build(cfg_kw, "O0", None, fused=False)
        dt_o0, _, _ = _measure(state, step, batch, max(n_steps // 2, 5))
    del state, step

    # the benchmark is unsharded: everything executes on one chip
    samples_sec_chip = b / dt_o2
    print(json.dumps({
        "metric": "bert_large_pretrain_O2_fusedadam_samples_per_sec_per_chip",
        "value": round(samples_sec_chip, 3),
        "unit": "samples/sec/chip",
        "vs_baseline": round(dt_o0 / dt_o2, 3),
    }))


if __name__ == "__main__":
    main()
