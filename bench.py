"""Benchmark harness — north-star metric on real TPU hardware.

Emits ONE JSON line (the last line of stdout):
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

Metric (BASELINE.json north star): BERT-Large pretraining train-step
throughput, samples/sec/chip, with the full apex-O2-equivalent stack —
precision policy O2 (bf16 compute, fp32 masters), FusedAdam, fused
(Pallas) layer norm + flash attention.  ``vs_baseline`` is the measured
speedup over the same model run at O0 (pure fp32, plain optax adam,
XLA-composition ops) — the reference's advertised amp+fusion gain,
measured rather than quoted (BASELINE.md: no number published in-repo).

Measurement hygiene (round-2 hardening; the round-1 driver capture was
poisoned ~24x by a transient in its single timing window):

* every phase is timed over ``k`` independent windows and scored by the
  *best* window — environmental transients (axon-tunnel contention) only
  ever slow a window down, never speed it up, so min is the unbiased
  estimator of the machine's real step time;
* if the windows disagree by >20% the phase re-measures with extra
  windows (contention detected);
* if the final ``vs_baseline`` still comes out < 1 the whole benchmark
  re-runs once — an O2-fused stack being slower than unfused fp32 is a
  measurement failure, not a plausible result;
* all windows are emitted in the JSON so the number can defend itself;
* the BASELINE.md-promised breakdown is emitted: fwd / bwd / optimizer
  step-time split (ms) and HBM peak bytes.

Env knobs: BENCH_BATCH, BENCH_SEQ, BENCH_STEPS (steps per window;
default 20), BENCH_WINDOWS (default 3), BENCH_FULL=1 (>=100-step
steady-state windows), BENCH_TINY=1 (smoke).
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time


def _build(cfg_kw, opt_level, half_dtype, fused):
    import jax
    import jax.numpy as jnp
    import optax

    from apex_tpu import amp
    from apex_tpu.models import BertConfig, BertModel, bert_mlm_loss_fn
    from apex_tpu.optim import fused_adam

    # measured fastest on v5e (see PROGRESS notes): unrolled layers beat
    # nn.scan by ~26% (XLA schedules across layer boundaries), full
    # remat beats dots-saveable (HBM bandwidth > recompute FLOPs here)
    cfg_kw.setdefault("scan_layers", False)
    cfg = BertConfig.bert_large(**cfg_kw) if not int(
        os.environ.get("BENCH_TINY", "0")) else BertConfig.tiny(**cfg_kw)
    model = BertModel(cfg)
    md = os.environ.get("BENCH_MOMENT_DTYPE", "fp32")
    if fused and md == "fp8":
        # beyond-reference fp8 block-scaled moment storage (A/B knob)
        tx = fused_adam(1e-4, moment_format="fp8_block_scaled")
    elif fused:
        tx = fused_adam(
            1e-4, moment_dtype={"bf16": jnp.bfloat16,
                                "fp32": jnp.float32}[md])
    else:
        tx = optax.adam(1e-4)

    b = int(os.environ.get("BENCH_BATCH", "16"))
    s = int(os.environ.get("BENCH_SEQ", str(min(cfg.max_seq_len, 512))))
    # BERT pretraining gathers the ~15% masked positions before the
    # vocab projection (max_predictions_per_seq); P=80 ≈ 0.15*512
    # rounded to the nearest fp32 sublane multiple
    p = min(max(8, int(0.15 * s / 8 + 0.5) * 8), s)
    rng = jax.random.PRNGKey(0)
    ids = jax.random.randint(rng, (b, s), 0, cfg.vocab_size)
    positions = jax.numpy.argsort(
        jax.random.uniform(rng, (b, s)), axis=-1)[:, :p]
    mlm_labels = jax.numpy.take_along_axis(ids, positions, axis=1)

    params = model.init(jax.random.PRNGKey(0), ids[:2])
    state = amp.initialize(model.apply, params, tx, opt_level=opt_level,
                           half_dtype=half_dtype)

    def loss_of(state, params, ids, positions, mlm_labels):
        cp = state.policy.cast_to_compute(params)
        logits, _ = state.apply_fn(
            cp, ids, mlm_positions=positions, deterministic=True)
        loss = bert_mlm_loss_fn(logits.astype(jnp.float32), mlm_labels)
        return state.scale_loss(loss), loss

    # donate the state: in-place param/opt-state updates (~2% step time,
    # and frees a full copy of the fp32 masters + adam moments in HBM)
    accum = int(os.environ.get("BENCH_ACCUM", "1"))
    if accum > 1:
        # gradient accumulation over microbatches (one optimizer step):
        # lets no-remat fit in HBM at small per-microbatch size —
        # trades the remat recompute FLOPs for saved activations
        @functools.partial(jax.jit, donate_argnums=(0,))
        def step(state, ids, positions, mlm_labels):
            mbs = jax.tree.map(
                lambda x: x.reshape(accum, x.shape[0] // accum,
                                    *x.shape[1:]),
                (ids, positions, mlm_labels))

            def body(acc, mb):
                g, l = jax.grad(
                    lambda p_: loss_of(state, p_, *mb),
                    has_aux=True)(state.params)
                acc_g, acc_l = acc
                return (jax.tree.map(jnp.add, acc_g, g),
                        acc_l + l), None

            zero = (jax.tree.map(
                lambda p: jnp.zeros(p.shape, jnp.float32), state.params),
                jnp.zeros((), jnp.float32))
            (gsum, lsum), _ = jax.lax.scan(body, zero, mbs)
            grads = jax.tree.map(lambda g: g / accum, gsum)
            new_state, finite = state.apply_gradients(grads=grads)
            return new_state, lsum / accum, finite
    else:
        @functools.partial(jax.jit, donate_argnums=(0,))
        def step(state, ids, positions, mlm_labels):
            grads, loss = jax.grad(
                lambda p_: loss_of(state, p_, ids, positions,
                                   mlm_labels),
                has_aux=True)(state.params)
            new_state, finite = state.apply_gradients(grads=grads)
            return new_state, loss, finite

    # breakdown probes: forward-only and forward+backward (no optimizer).
    # No donation — they leave the state alive for the full-step timing.
    @jax.jit
    def fwd_only(state, ids, positions, mlm_labels):
        return loss_of(state, state.params, ids, positions, mlm_labels)[1]

    @jax.jit
    def fwd_bwd(state, ids, positions, mlm_labels):
        grads, loss = jax.grad(
            lambda p_: loss_of(state, p_, ids, positions, mlm_labels),
            has_aux=True)(state.params)
        return _probe_reduce(grads, loss)

    return state, step, (fwd_only, fwd_bwd), (ids, positions, mlm_labels), b


def _probe_reduce(grads, loss):
    """Reduce a grad tree to one scalar so a fwd+bwd probe's output
    transfer is O(1) but still depends on every gradient leaf (an
    unused leaf's producing computation would be DCE'd)."""
    import jax

    acc = loss
    for g in jax.tree.leaves(grads):
        acc = acc + g.ravel()[0].astype(loss.dtype)
    return acc


def _sync(x):
    """Force full execution.  On the axon (tunneled-TPU) backend
    ``block_until_ready`` returns before execution finishes — only a
    host transfer truly syncs, so fetch one scalar that depends
    transitively on the whole computation."""
    import jax

    leaf = jax.tree.leaves(x)[0]
    jax.device_get(leaf.ravel()[0] if getattr(leaf, "ndim", 0) else leaf)


def _time_windows(run_window, k, max_extra=3, spread_tol=0.20):
    """Time ``k`` windows; add up to ``max_extra`` more while the
    windows disagree by more than ``spread_tol``.  Returns (best_dt,
    all_window_dts)."""
    dts = [run_window() for _ in range(k)]
    extra = 0

    def disagree():
        # the min must be *reproduced*: stop once the two fastest
        # windows agree (a single slow transient shouldn't force every
        # extra window to run)
        if len(dts) < 2:
            return False  # BENCH_WINDOWS=1: nothing to cross-check
        fast = sorted(dts)[:2]
        return (fast[1] / fast[0] - 1.0) > spread_tol

    while extra < max_extra and disagree():
        print(f"# bench: fastest windows disagree > {spread_tol:.0%}, "
              f"re-measuring (windows so far: "
              f"{[round(d*1e3,1) for d in dts]} ms)", file=sys.stderr)
        dts.append(run_window())
        extra += 1
    return min(dts), dts


def _measure_step(state, step, batch, n_steps, k_windows, warmup=3):
    """Multi-window timing of the donated full train step."""
    state_box = [state]

    def run_window():
        st = state_box[0]
        t0 = time.perf_counter()
        for _ in range(n_steps):
            st, loss, finite = step(st, *batch)
        _sync(st)
        dt = (time.perf_counter() - t0) / n_steps
        state_box[0] = st
        run_window.last = (loss, finite)
        return dt

    for _ in range(warmup):
        state_box[0], loss, finite = step(state_box[0], *batch)
    _sync(state_box[0])
    best, dts = _time_windows(run_window, k_windows)
    loss, finite = run_window.last
    return best, dts, float(loss), bool(finite), state_box[0]


def _measure_fn(fn, state, batch, n_steps, k_windows, warmup=2):
    """Multi-window timing of a non-donating probe (fwd / fwd+bwd)."""

    def run_window():
        t0 = time.perf_counter()
        for _ in range(n_steps):
            out = fn(state, *batch)
        _sync(out)
        return (time.perf_counter() - t0) / n_steps

    for _ in range(warmup):
        out = fn(state, *batch)
    _sync(out)
    best, _ = _time_windows(run_window, k_windows)
    return best


def _call_overhead():
    """The tunneled backend's FIXED per-call+sync cost (measured
    ~75-115 ms) — subtract from any window that doesn't amortize it
    over many seconds of work."""
    import jax
    import jax.numpy as jnp

    triv = jax.jit(lambda x: x + 1)
    x = jnp.float32(0)
    jax.device_get(triv(x))
    dts = []
    for _ in range(3):
        t0 = time.perf_counter()
        jax.device_get(triv(x))
        dts.append(time.perf_counter() - t0)
    return min(dts)


def _hbm_peak_bytes():
    import jax

    try:
        stats = jax.local_devices()[0].memory_stats() or {}
        return int(stats.get("peak_bytes_in_use", 0)) or None
    except Exception:
        return None


def _aot_compile(jitted, *args):
    """AOT-compile a jitted fn so the executable doubles as the
    measurement object (memory_analysis / cost_analysis) — the
    round-2 verdict's fix for every ``hbm_peak_bytes: null``: the axon
    backend has no ``memory_stats()``, but ``Compiled.memory_analysis``
    works everywhere.  Returns the compiled callable or None."""
    try:
        return jitted.lower(*args).compile()
    except Exception as e:
        print(f"# bench: AOT compile failed ({e}); falling back to jit",
              file=sys.stderr)
        return None


def _analysis_estimate(ana: dict) -> int:
    """Peak-bytes estimate from the analysis fields: arguments +
    outputs + temporaries (donation makes arg/output overlap, so this
    upper-bounds the true peak)."""
    return sum(ana.get(k) or 0 for k in ("argument", "output", "temp"))


def _memory_fields(compiled):
    """Per-device program memory from XLA's analysis.  The reported
    ``hbm_peak_bytes`` uses the runtime high-water mark when the
    backend exposes one, else :func:`_analysis_estimate`."""
    fields = {}
    runtime_peak = _hbm_peak_bytes()
    ma = None
    if compiled is not None:
        try:
            ma = compiled.memory_analysis()
        except Exception:
            ma = None
    if ma is not None:
        fields["hbm_analysis_bytes"] = {
            "argument": getattr(ma, "argument_size_in_bytes", None),
            "output": getattr(ma, "output_size_in_bytes", None),
            "temp": getattr(ma, "temp_size_in_bytes", None),
            "generated_code": getattr(
                ma, "generated_code_size_in_bytes", None),
        }
    if runtime_peak is not None:
        fields["hbm_peak_bytes"] = runtime_peak
        fields["hbm_peak_source"] = "memory_stats"
    elif ma is not None:
        fields["hbm_peak_bytes"] = _analysis_estimate(
            fields["hbm_analysis_bytes"])
        fields["hbm_peak_source"] = "memory_analysis_estimate"
    else:
        fields["hbm_peak_bytes"] = None
    return fields


# chip peaks for the roofline self-check (v5e-class defaults; override
# for other chips).  BASELINE.md derives both numbers.
_PEAK_TFLOPS = float(os.environ.get("BENCH_PEAK_TFLOPS", "197"))
_PEAK_HBM_GBS = float(os.environ.get("BENCH_PEAK_HBM_GBS", "819"))


def _roofline_fields(compiled, dt, measured_tflops=None,
                     phase_bounds=None):
    """Self-certifying scoreboard (round-2 verdict weak #1, flag rules
    re-grounded in round 4 so no flag fires by design on known-good
    captures): emit the capture's achieved TFLOP/s, its fraction of the
    program's own roofline bound, and flags that each mean exactly one
    thing:

    - ``impossible_above_peak``: the CLOCK beat the program's exact
      compute bound (cost-model flops at chip peak) — physically
      impossible, the measurement is wrong (the round-1 failure mode,
      a 24x-wrong clock, trips this immediately).  The HBM side is
      deliberately NOT part of this flag: XLA's ``bytes accessed``
      overcounts fusion-internal traffic by a measured 5-22%, so
      running nominally "above" the bandwidth bound is expected on
      well-fused programs — that state is reported as the
      informational ``hbm_bound_frac`` > 1 plus
      ``bytes_overcount_note`` instead of a flag readers must learn
      to ignore (round-3 verdict weak #3).
    - ``contention_suspect``: the step runs below 25% of the best
      AVAILABLE bound — chip peaks, or, when the caller passes
      ``measured_tflops`` (a measured achievable rate for this
      program's dominant kernel mix, e.g. the flash-attention rate
      from tools/attn_bench.py), that measured bound.  This keeps the
      flag meaningful for programs whose kernels legitimately cannot
      reach chip peak (d=64 attention: the contraction dim half-fills
      the MXU), instead of permanently firing on them (round-3 verdict
      weak #4).

    ``phase_bounds`` (round-5): a list of ``{"name", "seconds",
    "flops"}`` for work XLA's cost model CANNOT see — Pallas custom
    calls report ``flops: None`` (probed this round), so a program
    dominated by the flash kernel would otherwise score its bound on
    the non-attention remainder only (exactly what round 4's 16k/32k
    "kernel-own bound" rows did, making them accidentally loose).
    With phases, the bound is the SUM of the XLA-visible roofline and
    each phase's seconds (its analytic useful flops at its measured
    kernel rate — tools/attn_bench.py accounting), ``achieved_tflops``
    includes the phase flops, and each phase's ``xla_bytes`` (the
    kernel's argument/result I/O, which XLA's bytes-accessed already
    counts) is DEDUCTED from the XLA byte side so the same traffic is
    never in both terms — double-counting would inflate the bound and
    overstate ``roofline_frac``.

    ``roofline_frac`` ≈ 1 on an unflagged capture means the step runs
    at its program's bound (HBM for the BERT step).  Only computed on
    TPU backends.
    """
    import jax

    if compiled is None or jax.default_backend() != "tpu":
        return {}
    try:
        ca = compiled.cost_analysis() or {}
        # older runtimes returned a list of per-program dicts — sum
        # them (taking only [0] would silently undercount multi-program
        # executables)
        if isinstance(ca, (list, tuple)):
            flops = sum(float(c.get("flops", 0.0)) for c in ca)
            byts = sum(float(c.get("bytes accessed", 0.0)) for c in ca)
        else:
            flops = float(ca.get("flops", 0.0))
            byts = float(ca.get("bytes accessed", 0.0))
    except Exception:
        return {}
    if not flops or not dt:
        return {}
    phase_flops = sum(p["flops"] for p in phase_bounds or [])
    phase_s = sum(p["seconds"] for p in phase_bounds or [])
    # the kernels' argument/result bytes appear in XLA's "bytes
    # accessed" AND inside the phase's measured wall time — subtract
    # the analytic kernel I/O (phase "xla_bytes") from the XLA side so
    # the composed bound never counts the same traffic twice (which
    # would inflate the bound and overstate roofline_frac)
    phase_io = sum(p.get("xla_bytes", 0) for p in phase_bounds or [])
    byts_eff = max(byts - phase_io, 0.0)
    achieved = (flops + phase_flops) / dt / 1e12
    t_mxu = flops / (_PEAK_TFLOPS * 1e12)
    t_hbm = byts_eff / (_PEAK_HBM_GBS * 1e9)
    bound = max(t_mxu, t_hbm) + phase_s
    if measured_tflops:
        bound = max(bound, flops / (measured_tflops * 1e12))
    frac = bound / dt
    flags = []
    # 2% slack for cost-model rounding; flops counts are exact, so a
    # clock under the compute bound is a real measurement failure.
    # The HBM side tolerates the documented 5-22% bytes-accessed
    # double-count, but NOT more: beyond 25% over the bandwidth bound
    # the clock itself is suspect again (a half-speed clock on an
    # HBM-bound program must not pass with a reassuring note).
    if t_mxu / dt > 1.02 or t_hbm / dt > 1.25:
        flags.append("impossible_above_peak")
    if frac < 0.25:
        flags.append("contention_suspect")
    out = {
        "achieved_tflops": round(achieved, 2),
        "roofline_frac": round(frac, 3),
        "roofline_bound": ("phase_sum" if phase_bounds
                           else "measured_kernel" if measured_tflops and
                           flops / (measured_tflops * 1e12) >=
                           max(t_mxu, t_hbm)
                           else "hbm" if t_hbm >= t_mxu else "mxu"),
        "mxu_bound_frac": round(t_mxu / dt, 3),
        "hbm_bound_frac": round(t_hbm / dt, 3),
        "cost_flops": flops,
        "cost_bytes_accessed": byts,
        "peak_tflops_assumed": _PEAK_TFLOPS,
        "peak_hbm_gbs_assumed": _PEAK_HBM_GBS,
        "flags": flags,
    }
    if phase_bounds:
        out["phase_bounds"] = [
            {"name": p["name"], "seconds": round(p["seconds"], 5),
             "flops": p["flops"],
             "xla_bytes_deducted": p.get("xla_bytes", 0),
             "rate_tflops": round(p["flops"] / p["seconds"] / 1e12, 1)}
            for p in phase_bounds]
        out["cost_bytes_minus_kernel_io"] = byts_eff
        out["phase_note"] = (
            "bound = XLA-visible roofline (kernel I/O bytes deducted) "
            "+ sum of phase bounds; Pallas kernels report flops=None "
            "to cost_analysis, so their work is accounted analytically "
            "per phase")
    if measured_tflops:
        out["measured_bound_tflops"] = measured_tflops
    if 1.02 < t_hbm / dt <= 1.25:
        out["bytes_overcount_note"] = (
            "cost-model bytes-accessed exceeds the measured time x peak "
            "bandwidth by <=25% — consistent with the known 5-22% "
            "fusion-internal double-count (BASELINE.md)")
    return out


def _run_once(n_steps, k_windows, breakdown):
    import jax
    import jax.numpy as jnp

    cfg_kw = {"remat": True, "dtype": jnp.float32}

    # O2 + FusedAdam + fused kernels (the north-star stack)
    state, step, (fwd_only, fwd_bwd), batch, b = _build(
        dict(cfg_kw, dtype=jnp.bfloat16), "O2", jnp.bfloat16, fused=True)
    result = {}
    if breakdown:
        # probes first (they don't donate); smaller windows suffice
        n_probe = max(n_steps // 2, 5)
        t_fwd = _measure_fn(fwd_only, state, batch, n_probe, k_windows)
        t_fb = _measure_fn(fwd_bwd, state, batch, n_probe, k_windows)
        result["fwd_ms"] = round(t_fwd * 1e3, 2)
        result["bwd_ms"] = round(max(t_fb - t_fwd, 0.0) * 1e3, 2)
    # AOT-compile the step: the executable is both the timed callable
    # and the memory/cost analysis source
    compiled = _aot_compile(step, state, *batch)
    timed_step = compiled if compiled is not None else step
    dt_o2, o2_windows, loss, finite, state = _measure_step(
        state, timed_step, batch, n_steps, k_windows)
    if breakdown:
        result["opt_ms"] = round(max(dt_o2 - t_fb, 0.0) * 1e3, 2)
        result["step_ms"] = round(dt_o2 * 1e3, 2)
    result.update(_memory_fields(compiled))
    result.update(_roofline_fields(compiled, dt_o2))
    del state, step, compiled, timed_step, fwd_only, fwd_bwd

    # O0 fp32 + plain optax adam (the "eager" baseline).  Force true
    # fp32 matmuls: TPU's default precision would silently run bf16
    # passes, understating the O2 gain.
    with jax.default_matmul_precision("highest"):
        state, step, _, batch, _ = _build(cfg_kw, "O0", None, fused=False)
        dt_o0, o0_windows, _, _, state = _measure_step(
            state, step, batch, max(n_steps // 2, 5), k_windows)
    del state, step

    result.update({
        "value": round(b / dt_o2, 3),
        "vs_baseline": round(dt_o0 / dt_o2, 3),
        "o2_window_ms": [round(d * 1e3, 2) for d in o2_windows],
        "o0_window_ms": [round(d * 1e3, 2) for d in o0_windows],
        "loss_finite": finite,
    })
    return result


def main():
    n_steps = int(os.environ.get("BENCH_STEPS", "20"))
    if int(os.environ.get("BENCH_FULL", "0")):
        n_steps = max(n_steps, 100)
    k_windows = max(1, int(os.environ.get("BENCH_WINDOWS", "3")))
    breakdown = not int(os.environ.get("BENCH_TINY", "0"))

    result = _run_once(n_steps, k_windows, breakdown)
    retried = False
    if result["vs_baseline"] < 1.0:
        # an O2+fused stack slower than unfused fp32 is a measurement
        # failure (exactly how BENCH_r01 recorded a 24x-wrong number) —
        # re-run the whole benchmark once
        print(f"# bench: vs_baseline={result['vs_baseline']} < 1 is "
              "implausible; re-running the full measurement",
              file=sys.stderr)
        retried = True
        result = _run_once(n_steps, k_windows, breakdown)
        if result.get("hbm_peak_source") == "memory_stats":
            # peak_bytes_in_use is a process-lifetime high-water mark,
            # contaminated by the first run's fp32 stack; fall back to
            # the static per-program analysis estimate
            est = _analysis_estimate(
                result.get("hbm_analysis_bytes") or {})
            result["hbm_peak_bytes"] = est or None
            result["hbm_peak_source"] = (
                "memory_analysis_estimate" if est else None)

    out = {
        "metric": "bert_large_pretrain_O2_fusedadam_samples_per_sec_per_chip",
        "value": result.pop("value"),
        "unit": "samples/sec/chip",
        "vs_baseline": result.pop("vs_baseline"),
        "steps_per_window": n_steps,
        "retried": retried,
    }
    out.update(result)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
