"""Build shim: optional native extension on top of pyproject.toml.

Reference: apex's ``setup.py`` gates CUDA extensions behind feature
flags (``--cpp_ext --cuda_ext``, SURVEY.md §2.8).  Here the compute
kernels are Pallas (no native build); the one native piece is the
host-side ``_apex_C`` buffer packer, built by default and skipped
gracefully if no C toolchain exists (the package falls back to numpy —
``apex_tpu/native.py``).
"""

from setuptools import Extension, setup
from setuptools.command.build_ext import build_ext


class OptionalBuildExt(build_ext):
    """Never fail the install because the optional C ext didn't build."""

    def run(self):
        try:
            super().run()
        except Exception as exc:  # toolchain missing: pure-python install
            print(f"warning: skipping native _apex_C build: {exc}")

    def build_extension(self, ext):
        try:
            super().build_extension(ext)
        except Exception as exc:
            print(f"warning: skipping native {ext.name} build: {exc}")


setup(
    ext_modules=[
        Extension("_apex_C", sources=["csrc/apex_c.c"],
                  extra_compile_args=["-O3"]),
    ],
    cmdclass={"build_ext": OptionalBuildExt},
)
