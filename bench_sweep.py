"""Ad-hoc perf sweep for the north-star config (O2 path only).

Usage: BENCH_BATCH=32 BENCH_REMAT_POLICY=dots_with_no_batch_dims_saveable
       python bench_sweep.py
Fresh process per config (HBM is not reclaimed promptly across builds).
"""

import json
import os

import bench


def main():
    import jax.numpy as jnp

    cfg_kw = {
        "remat": os.environ.get("BENCH_REMAT", "1") == "1",
        "remat_policy": os.environ.get("BENCH_REMAT_POLICY",
                                       "nothing_saveable"),
        "dtype": jnp.bfloat16,
    }
    for knob in ("attention_block_q", "attention_block_k",
                 "remat_skip_every"):
        v = os.environ.get("BENCH_" + knob.upper())
        if v:
            cfg_kw[knob] = int(v)
    n_steps = int(os.environ.get("BENCH_STEPS", "20"))
    k_windows = max(1, int(os.environ.get("BENCH_WINDOWS", "2")))
    state, step, _probes, batch, b = bench._build(
        cfg_kw, "O2", jnp.bfloat16, fused=True)
    dt, dts, loss, finite, _ = bench._measure_step(
        state, step, batch, n_steps, k_windows)
    print(json.dumps({
        "batch": b,
        "remat_policy": cfg_kw["remat_policy"] if cfg_kw["remat"] else None,
        "step_ms": round(dt * 1e3, 2),
        "window_ms": [round(d * 1e3, 2) for d in dts],
        "samples_per_sec": round(b / dt, 2),
        "finite": finite,
    }))


if __name__ == "__main__":
    main()
