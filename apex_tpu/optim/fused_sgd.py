"""FusedSGD — momentum SGD as one jitted pytree update.

Reference: ``apex/optimizers/fused_sgd.py`` +
``csrc/multi_tensor_sgd_kernel.cu``.  Matches torch/apex SGD semantics:
``buf = momentum*buf + (1-dampening)*g`` (weight decay folded into ``g``
first), nesterov option, first-step momentum initialization to the
gradient.  The amp master-weight variants of the kernel are handled by
the train state (SURVEY.md §2.3).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Union

import jax
import jax.numpy as jnp
import optax

__all__ = ["fused_sgd", "FusedSgdState"]


class FusedSgdState(NamedTuple):
    count: jnp.ndarray
    momentum_buf: Any


def fused_sgd(
    learning_rate: Union[float, optax.Schedule] = 1e-3,
    momentum: float = 0.0,
    dampening: float = 0.0,
    weight_decay: float = 0.0,
    nesterov: bool = False,
) -> optax.GradientTransformation:
    """SGD(+momentum/nesterov/weight-decay) as one fused pytree update
    (reference ``apex.optimizers.FusedSGD`` /
    ``amp_C.multi_tensor_sgd``) — torch-parity momentum semantics."""
    if nesterov and (momentum <= 0 or dampening != 0):
        raise ValueError(
            "Nesterov momentum requires a momentum and zero dampening")

    def init(params):
        return FusedSgdState(
            count=jnp.zeros((), jnp.int32),
            momentum_buf=jax.tree.map(jnp.zeros_like, params),
        )

    # graftlint: precision(master-fp32)
    def update(grads, state, params=None):
        if params is None:
            raise ValueError("fused_sgd requires params")
        count = state.count + 1
        lr = learning_rate(count) if callable(learning_rate) else learning_rate
        first = state.count == 0

        def leaf(g, p, buf):
            gf = g.astype(jnp.float32)
            if weight_decay != 0.0:
                gf = gf + weight_decay * p.astype(jnp.float32)
            if momentum != 0.0:
                # torch semantics: first step buf = g (not damped)
                buf_new = jnp.where(
                    first, gf, momentum * buf.astype(jnp.float32)
                    + (1.0 - dampening) * gf)
                d = gf + momentum * buf_new if nesterov else buf_new
            else:
                buf_new = buf.astype(jnp.float32)
                d = gf
            # keep state dtype stable across steps (scan/donation safety)
            return (-lr * d).astype(p.dtype), buf_new.astype(buf.dtype)

        g_leaves, treedef = jax.tree.flatten(grads)
        p_leaves = treedef.flatten_up_to(params)
        b_leaves = treedef.flatten_up_to(state.momentum_buf)
        pairs = [leaf(g, p, b) for g, p, b
                 in zip(g_leaves, p_leaves, b_leaves)]
        updates = treedef.unflatten([t[0] for t in pairs])
        bufs = treedef.unflatten([t[1] for t in pairs])
        return updates, FusedSgdState(count=count, momentum_buf=bufs)

    return optax.GradientTransformation(init, update)
