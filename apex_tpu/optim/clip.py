"""Fused gradient clipping (reference: ``apex/contrib/clip_grad/`` —
multi-tensor ``clip_grad_norm_`` via ``amp_C.multi_tensor_l2norm`` +
``multi_tensor_scale``).

One jitted computation: fused global norm + fused scale.  Also provides
the optax-transformation form for chaining.
"""

from __future__ import annotations

from typing import Any, Tuple

import jax.numpy as jnp
import optax

from apex_tpu.utils.tree import global_grad_clip_coef, tree_scale

__all__ = ["clip_grad_norm", "clip_by_global_norm"]


def clip_grad_norm(grads: Any, max_norm: float,
                   *, eps: float = 1e-6) -> Tuple[Any, jnp.ndarray]:
    """Clip ``grads`` to global L2 norm ``max_norm``.

    Returns ``(clipped_grads, total_norm)`` — the reference's
    ``clip_grad_norm_`` returns the pre-clip total norm too.
    """
    coef, total_norm = global_grad_clip_coef(grads, max_norm, eps=eps)
    return tree_scale(grads, coef), total_norm


def clip_by_global_norm(max_norm: float) -> optax.GradientTransformation:
    """optax-style transformation form (chain before an optimizer)."""
    def init(params):
        del params
        return optax.ScaleState()

    def update(grads, state, params=None):
        del params
        clipped, _ = clip_grad_norm(grads, max_norm)
        return clipped, state

    return optax.GradientTransformation(init, update)
