"""apex_tpu.optim — fused optimizers as single-jit pytree updates.

TPU-native replacement for ``apex/optimizers/*`` + the ``amp_C``
multi-tensor CUDA kernels (``csrc/multi_tensor_*_kernel.cu``): each
optimizer's whole-parameter-list update compiles to one fused XLA
computation (SURVEY.md §2.2–2.3).  All are optax
``GradientTransformation``s and compose with ``optax.chain``.

Distributed ("ZeRO") variants — ``DistributedFusedAdam/LAMB`` upstream —
are the same transforms with optimizer state sharded over the ``fsdp``
mesh axis; see :mod:`apex_tpu.parallel.distributed_optim`.
"""

from apex_tpu.optim.fused_adam import fused_adam, FusedAdamState
from apex_tpu.optim.fused_lamb import fused_lamb, FusedLambState
from apex_tpu.optim.fused_sgd import fused_sgd, FusedSgdState
from apex_tpu.optim.fused_novograd import fused_novograd, FusedNovoGradState
from apex_tpu.optim.fused_adagrad import fused_adagrad, FusedAdagradState
from apex_tpu.optim.fused_mixed_precision_lamb import (
    fused_mixed_precision_lamb,
    FusedMixedPrecisionLambState,
)
from apex_tpu.optim.larc import larc
from apex_tpu.optim.clip import clip_grad_norm, clip_by_global_norm
from apex_tpu.optim._multi_tensor import (
    tree_l2_norm,
    per_tensor_l2_norms,
    tree_scale,
    tree_axpby,
    global_grad_clip_coef,
)

# Aliases matching the reference's class names for drop-in discovery.
FusedAdam = fused_adam
FusedLAMB = fused_lamb
FusedSGD = fused_sgd
FusedNovoGrad = fused_novograd
FusedAdagrad = fused_adagrad
LARC = larc
FusedMixedPrecisionLamb = fused_mixed_precision_lamb

__all__ = [
    "fused_adam", "FusedAdamState", "FusedAdam",
    "fused_lamb", "FusedLambState", "FusedLAMB",
    "fused_sgd", "FusedSgdState", "FusedSGD",
    "fused_novograd", "FusedNovoGradState", "FusedNovoGrad",
    "fused_adagrad", "FusedAdagradState", "FusedAdagrad",
    "larc", "LARC",
    "fused_mixed_precision_lamb", "FusedMixedPrecisionLambState",
    "FusedMixedPrecisionLamb",
    "clip_grad_norm", "clip_by_global_norm",
    "tree_l2_norm", "per_tensor_l2_norms", "tree_scale", "tree_axpby",
    "global_grad_clip_coef",
]
