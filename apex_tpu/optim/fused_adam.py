"""FusedAdam — Adam/AdamW as a single jitted pytree update.

Reference: ``apex/optimizers/fused_adam.py`` +
``csrc/multi_tensor_adam_kernel.cu``.  The reference fuses the Adam update
for all parameters into one CUDA kernel launch; here the optax-style
``update`` is one jit-compiled computation over the whole pytree — XLA
emits fused loops, which is the TPU equivalent (SURVEY.md §2.2).

Semantics parity:

- ``adam_w_mode=True`` (default, like the reference): decoupled weight
  decay (AdamW).  ``False``: L2-regularization added to the gradient.
- ``bias_correction`` on by default.
- ``capturable`` is trivially true — everything is in-graph; there is no
  CPU-side step counter to break CUDA graphs (the reference's
  ``capturable`` flag exists to fix exactly that).
- ``master_weights`` is handled one level up by
  :class:`~apex_tpu.core.train_state.MixedPrecisionTrainState`, matching
  the layer split in the reference (amp owns masters, FusedAdam consumes
  them).
- To freeze a subset of params, wrap with ``optax.masked`` (the JAX
  idiom for the reference's per-param-group machinery).

Beyond-reference: ``moment_format="fp8_block_scaled"`` stores both Adam
moments as float8_e4m3 with one fp32 scale per 256-element block
(compute stays fp32) — the algorithmic-traffic-reduction lever
BASELINE.md's roofline analysis identifies as the only remaining one
for the HBM-bound BERT step.  Raw e4m3 cannot hold second moments
(min normal ≈ 2⁻⁶ flushes the typical 1e-12..1e-4 range to zero), so
the block scale carries the magnitude and e4m3 carries ~2-decimal-digit
mantissa within the block — the FP8-optimizer-state recipe of 8-bit
Adam (block-wise quantization).  Storage: 1 byte + 4/256 per moment
element vs 4 (or 2 with ``moment_dtype=bf16``).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Union

import jax
import jax.numpy as jnp
import optax

__all__ = ["fused_adam", "FusedAdamState"]

_FP8 = jnp.float8_e4m3fn
_FP8_MAX = 448.0          # e4m3 finite max
_FP8_BLOCK = 256


def _fp8_zeros(p):
    n = max(1, p.size)
    npad = -(-n // _FP8_BLOCK) * _FP8_BLOCK
    return {"q": jnp.zeros((npad,), _FP8),
            "scale": jnp.zeros((npad // _FP8_BLOCK,), jnp.float32)}


def _fp8_dequant(st, n):
    q = st["q"].reshape(-1, _FP8_BLOCK).astype(jnp.float32)
    return (q * st["scale"][:, None]).reshape(-1)[:n]


def _fp8_quant(x_flat):
    n = x_flat.shape[0]
    npad = -(-max(1, n) // _FP8_BLOCK) * _FP8_BLOCK
    xb = jnp.pad(x_flat, (0, npad - n)).reshape(-1, _FP8_BLOCK)
    absmax = jnp.max(jnp.abs(xb), axis=1, keepdims=True)
    scale = jnp.maximum(absmax / _FP8_MAX, 1e-30)
    return {"q": (xb / scale).astype(_FP8).reshape(-1),
            "scale": scale[:, 0]}


class FusedAdamState(NamedTuple):
    count: jnp.ndarray  # shared step counter (i32 scalar), like apex's
    exp_avg: Any
    exp_avg_sq: Any


def _unzip3(treedef, triples):
    a = treedef.unflatten([t[0] for t in triples])
    b = treedef.unflatten([t[1] for t in triples])
    c = treedef.unflatten([t[2] for t in triples])
    return a, b, c


def fused_adam(
    learning_rate: Union[float, optax.Schedule] = 1e-3,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    adam_w_mode: bool = True,
    bias_correction: bool = True,
    moment_dtype: Optional[Any] = None,
    moment_format: str = "dense",
) -> optax.GradientTransformation:
    """Build the FusedAdam gradient transformation.

    ``moment_dtype`` optionally stores moments in a reduced dtype
    (reference stores fp32 moments; default None = match params).
    ``moment_format="fp8_block_scaled"`` stores both moments as
    float8_e4m3 + per-256-block fp32 scales with fp32 compute
    (beyond-reference; see module docstring) — ``moment_dtype`` is
    ignored in that case.  Single-chip / replicated-state prototype:
    the blocks run over the *flattened* leaf, so with GSPMD-sharded
    params the quantized state crosses shard boundaries and XLA
    gathers the full moment per leaf — keep ``"dense"`` (optionally
    with ``moment_dtype``) for sharded optimizer state.
    """
    if moment_format not in ("dense", "fp8_block_scaled"):
        raise ValueError(
            f"moment_format={moment_format!r} not in "
            f"('dense', 'fp8_block_scaled')")
    fp8 = moment_format == "fp8_block_scaled"

    def init(params):
        if fp8:
            zeros = _fp8_zeros
        else:
            zeros = lambda p: jnp.zeros_like(
                p, dtype=moment_dtype or jnp.asarray(p).dtype)
        return FusedAdamState(
            count=jnp.zeros((), jnp.int32),
            exp_avg=jax.tree.map(zeros, params),
            exp_avg_sq=jax.tree.map(zeros, params),
        )

    def update(grads, state, params=None):
        if params is None:
            raise ValueError("fused_adam requires params")
        count = state.count + 1
        lr = learning_rate(count) if callable(learning_rate) else learning_rate
        c = count.astype(jnp.float32)
        if bias_correction:
            bc1 = 1.0 - jnp.power(b1, c)
            bc2 = 1.0 - jnp.power(b2, c)
        else:
            bc1 = bc2 = jnp.asarray(1.0, jnp.float32)

        def leaf(g, p, m, v):
            if fp8:
                n = p.size
                m_f = _fp8_dequant(m, n)
                v_f = _fp8_dequant(v, n)
                gf = g.astype(jnp.float32).reshape(-1)
                pf = p.astype(jnp.float32).reshape(-1)
            else:
                m_f, v_f = m, v
                gf = g.astype(m.dtype)
                pf = p.astype(m.dtype)
            if not adam_w_mode and weight_decay != 0.0:
                gf = gf + weight_decay * pf
            m_new = b1 * m_f + (1.0 - b1) * gf
            v_new = b2 * v_f + (1.0 - b2) * jnp.square(gf)
            denom = jnp.sqrt(v_new / bc2) + eps
            step = m_new / (bc1 * denom)
            if adam_w_mode and weight_decay != 0.0:
                step = step + weight_decay * pf
            upd = -lr * step
            if fp8:
                return (upd.reshape(p.shape).astype(p.dtype),
                        _fp8_quant(m_new), _fp8_quant(v_new))
            return upd.astype(p.dtype), m_new, v_new

        g_leaves, treedef = jax.tree.flatten(grads)
        p_leaves = treedef.flatten_up_to(params)
        m_leaves = treedef.flatten_up_to(state.exp_avg)
        v_leaves = treedef.flatten_up_to(state.exp_avg_sq)
        triples = [leaf(g, p, m, v) for g, p, m, v
                   in zip(g_leaves, p_leaves, m_leaves, v_leaves)]
        updates, exp_avg, exp_avg_sq = _unzip3(treedef, triples)
        return updates, FusedAdamState(count=count, exp_avg=exp_avg,
                                       exp_avg_sq=exp_avg_sq)

    return optax.GradientTransformation(init, update)
