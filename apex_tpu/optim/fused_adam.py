"""FusedAdam — Adam/AdamW as a single jitted pytree update.

Reference: ``apex/optimizers/fused_adam.py`` +
``csrc/multi_tensor_adam_kernel.cu``.  The reference fuses the Adam update
for all parameters into one CUDA kernel launch; here the optax-style
``update`` is one jit-compiled computation over the whole pytree — XLA
emits fused loops, which is the TPU equivalent (SURVEY.md §2.2).

Semantics parity:

- ``adam_w_mode=True`` (default, like the reference): decoupled weight
  decay (AdamW).  ``False``: L2-regularization added to the gradient.
- ``bias_correction`` on by default.
- ``capturable`` is trivially true — everything is in-graph; there is no
  CPU-side step counter to break CUDA graphs (the reference's
  ``capturable`` flag exists to fix exactly that).
- ``master_weights`` is handled one level up by
  :class:`~apex_tpu.core.train_state.MixedPrecisionTrainState`, matching
  the layer split in the reference (amp owns masters, FusedAdam consumes
  them).
- To freeze a subset of params, wrap with ``optax.masked`` (the JAX
  idiom for the reference's per-param-group machinery).

Beyond-reference: ``moment_format="fp8_block_scaled"`` stores both Adam
moments as float8_e4m3 with one fp32 scale per 256-element block
(compute stays fp32) — the algorithmic-traffic-reduction lever
BASELINE.md's roofline analysis identifies as the only remaining one
for the HBM-bound BERT step.  Raw e4m3 cannot hold second moments
(min normal ≈ 2⁻⁶ flushes the typical 1e-12..1e-4 range to zero), so
the block scale carries the magnitude and e4m3 carries ~2-decimal-digit
mantissa within the block — the FP8-optimizer-state recipe of 8-bit
Adam (block-wise quantization).  Storage: 1 byte + 4/256 per moment
element vs 4 (or 2 with ``moment_dtype=bf16``).
"""

from __future__ import annotations

import functools
from typing import Any, NamedTuple, Optional, Union

import jax
import jax.numpy as jnp
import optax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops._dispatch import resolve_impl

__all__ = ["fused_adam", "FusedAdamState"]

_FP8 = jnp.float8_e4m3fn
_FP8_MAX = 448.0          # e4m3 finite max
_FP8_BLOCK = 256
# rows of 256 per grid step for the fused fp8 kernel (~1.5 MB of f32
# working tiles in VMEM); leaves below _FP8_KERNEL_MIN elements use
# the XLA path (and pad only to the quant block, not the row chunk)
_FP8_KERNEL_ROWS = 512
_FP8_KERNEL_MIN = _FP8_BLOCK * 64


def _fp8_pad(n):
    """Quantized-state length for ``n`` elements.  Kernel-path leaves
    (n >= _FP8_KERNEL_MIN) pad to a whole number of kernel row-chunks
    so the fused kernel's grid is exact (no ragged tail; waste
    ≤ 128 KiB of fp8 on leaves ≥ 16 Ki elements); smaller leaves stay
    on the XLA path and pad only to the 256-element quant block —
    chunk-padding them would turn a 1 Ki-element bias's moments into
    ~256 KiB of dead state."""
    n = max(1, n)
    if n < _FP8_KERNEL_MIN:
        return -(-n // _FP8_BLOCK) * _FP8_BLOCK
    chunk = _FP8_BLOCK * _FP8_KERNEL_ROWS
    return -(-n // chunk) * chunk


def _fp8_zeros(p):
    npad = _fp8_pad(p.size)
    return {"q": jnp.zeros((npad,), _FP8),
            "scale": jnp.zeros((npad // _FP8_BLOCK,), jnp.float32)}


def _fp8_dequant(st, n):
    q = st["q"].reshape(-1, _FP8_BLOCK).astype(jnp.float32)
    return (q * st["scale"][:, None]).reshape(-1)[:n]


def _fp8_quant(x_flat):
    n = x_flat.shape[0]
    npad = _fp8_pad(n)
    xb = jnp.pad(x_flat, (0, npad - n)).reshape(-1, _FP8_BLOCK)
    absmax = jnp.max(jnp.abs(xb), axis=1, keepdims=True)
    scale = jnp.maximum(absmax / _FP8_MAX, 1e-30)
    return {"q": (xb / scale).astype(_FP8).reshape(-1),
            "scale": scale[:, 0]}


# --------------------------------------------------------------------- #
# fused fp8-moment Adam kernel — ONE pass over grads/moments: dequant a
# moment block, update, requant, emit the param update.  This is the
# fix for BASELINE.md's round-3 measured negative: the XLA-composed
# quant/dequant materialized each moment as a full fp32 array between
# separate passes (165.6 GB accessed vs 99.5 dense), erasing the 1-byte
# storage win; in-kernel the fp32 moment exists only as a VMEM tile.
# Traffic per element with weight_decay=0: read g(4B) + m,v(1B each +
# scales) and write m,v(1B each) + upd(4B) ≈ 12 B vs 24 B for the dense
# fp32-moment update.  Measured caveat (BASELINE.md round-4 fp8
# section): this chip streams 1-byte blocks at ~1/9 of peak HBM
# bandwidth, so the 2x traffic model does NOT become a 2x time win —
# fp8 moments are a 4x state-MEMORY option (~8% step-time cost on the
# BERT step), not a throughput one.
# --------------------------------------------------------------------- #
def _fp8_adam_kernel(sc_ref, *refs, b1, b2, eps, wd, adamw, has_p, br):
    n = 0
    g_ref = refs[n]; n += 1
    p_ref = refs[n] if has_p else None
    n += 1 if has_p else 0
    mq_ref, ms_ref, vq_ref, vs_ref = refs[n:n + 4]
    upd_ref, mq2_ref, ms2_ref, vq2_ref, vs2_ref = refs[n + 4:]
    lr, bc1, bc2 = sc_ref[0], sc_ref[1], sc_ref[2]
    # scale arrays are WHOLE-resident in VMEM as (chunks, br) — tiny
    # (4 bytes per 1 KiB of moments) and lane-dense; per-step
    # (rows, 1) column-block DMAs measured ~0.75 µs each, ~35% of the
    # kernel's whole runtime at 4 per step.  The (br,)-row -> (br, 1)
    # column relayout here is VMEM-local and far cheaper.
    i = pl.program_id(0)
    ms = jnp.transpose(ms_ref[pl.ds(i, 1), :])     # (br, 1)
    vs = jnp.transpose(vs_ref[pl.ds(i, 1), :])
    g = g_ref[:].astype(jnp.float32)
    m = mq_ref[:].astype(jnp.float32) * ms
    v = vq_ref[:].astype(jnp.float32) * vs
    if has_p and not adamw:
        g = g + wd * p_ref[:].astype(jnp.float32)
    m2 = b1 * m + (1.0 - b1) * g
    v2 = b2 * v + (1.0 - b2) * (g * g)
    denom = jnp.sqrt(v2 / bc2) + eps
    step = m2 / (bc1 * denom)
    if has_p and adamw:
        step = step + wd * p_ref[:].astype(jnp.float32)
    upd_ref[:] = (-lr * step).astype(upd_ref.dtype)
    for x2, q_ref, s_ref in ((m2, mq2_ref, ms2_ref),
                             (v2, vq2_ref, vs2_ref)):
        absmax = jnp.max(jnp.abs(x2), axis=1, keepdims=True)
        sc = jnp.maximum(absmax / _FP8_MAX, 1e-30)
        q_ref[:] = (x2 / sc).astype(_FP8)
        s_ref[pl.ds(i, 1), :] = jnp.transpose(sc)


def _fp8_adam_leaf_pallas(g, p, m, v, lr_bc, b1, b2, eps, wd, adamw,
                          interpret):
    """Run the fused kernel over one flattened leaf.  Returns
    (update, m_state, v_state) with the same {"q","scale"} layout."""
    n = p.size
    rows = m["q"].shape[0] // _FP8_BLOCK
    npad = rows * _FP8_BLOCK

    def to_rows(x):
        flat = x.astype(jnp.float32).reshape(-1)
        if npad != n:                       # free reshape when aligned
            flat = jnp.pad(flat, (0, npad - n))
        return flat.reshape(rows, _FP8_BLOCK)

    has_p = wd != 0.0
    br = min(_FP8_KERNEL_ROWS, rows)
    assert rows % br == 0, (rows, br)      # _fp8_pad guarantees this
    chunks = rows // br
    args = [to_rows(g)]
    if has_p:
        args.append(to_rows(p))
    args += [m["q"].reshape(rows, _FP8_BLOCK),
             m["scale"].reshape(chunks, br),
             v["q"].reshape(rows, _FP8_BLOCK),
             v["scale"].reshape(chunks, br)]
    grid = (chunks,)
    row_spec = pl.BlockSpec((br, _FP8_BLOCK), lambda r: (r, 0),
                            memory_space=pltpu.VMEM)
    sc_spec = pl.BlockSpec(memory_space=pltpu.VMEM)  # whole-resident
    in_specs = [pl.BlockSpec(memory_space=pltpu.SMEM)]
    in_specs += [row_spec] * (2 if has_p else 1)
    in_specs += [row_spec, sc_spec, row_spec, sc_spec]
    kernel = functools.partial(
        _fp8_adam_kernel, b1=b1, b2=b2, eps=eps, wd=wd, adamw=adamw,
        has_p=has_p, br=br)
    upd2, mq2, ms2, vq2, vs2 = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[row_spec, row_spec, sc_spec, row_spec, sc_spec],
        out_shape=[
            jax.ShapeDtypeStruct((rows, _FP8_BLOCK), jnp.float32),
            jax.ShapeDtypeStruct((rows, _FP8_BLOCK), _FP8),
            jax.ShapeDtypeStruct((chunks, br), jnp.float32),
            jax.ShapeDtypeStruct((rows, _FP8_BLOCK), _FP8),
            jax.ShapeDtypeStruct((chunks, br), jnp.float32),
        ],
        interpret=interpret,
    )(lr_bc, *args)
    upd = upd2.reshape(-1)[:n].reshape(p.shape).astype(p.dtype)
    return (upd,
            {"q": mq2.reshape(-1), "scale": ms2.reshape(-1)},
            {"q": vq2.reshape(-1), "scale": vs2.reshape(-1)})


class FusedAdamState(NamedTuple):
    count: jnp.ndarray  # shared step counter (i32 scalar), like apex's
    exp_avg: Any
    exp_avg_sq: Any


def _unzip3(treedef, triples):
    a = treedef.unflatten([t[0] for t in triples])
    b = treedef.unflatten([t[1] for t in triples])
    c = treedef.unflatten([t[2] for t in triples])
    return a, b, c


def fused_adam(
    learning_rate: Union[float, optax.Schedule] = 1e-3,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    adam_w_mode: bool = True,
    bias_correction: bool = True,
    moment_dtype: Optional[Any] = None,
    moment_format: str = "dense",
) -> optax.GradientTransformation:
    """Build the FusedAdam gradient transformation.

    ``moment_dtype`` optionally stores moments in a reduced dtype
    (reference stores fp32 moments; default None = match params).
    ``moment_format="fp8_block_scaled"`` stores both moments as
    float8_e4m3 + per-256-block fp32 scales with fp32 compute
    (beyond-reference; see module docstring) — ``moment_dtype`` is
    ignored in that case.  Single-chip / replicated-state prototype:
    the blocks run over the *flattened* leaf, so with GSPMD-sharded
    params the quantized state crosses shard boundaries and XLA
    gathers the full moment per leaf — keep ``"dense"`` (optionally
    with ``moment_dtype``) for sharded optimizer state.
    """
    if moment_format not in ("dense", "fp8_block_scaled"):
        raise ValueError(
            f"moment_format={moment_format!r} not in "
            f"('dense', 'fp8_block_scaled')")
    fp8 = moment_format == "fp8_block_scaled"

    def init(params):
        if fp8:
            zeros = _fp8_zeros
        else:
            zeros = lambda p: jnp.zeros_like(
                p, dtype=moment_dtype or jnp.asarray(p).dtype)
        return FusedAdamState(
            count=jnp.zeros((), jnp.int32),
            exp_avg=jax.tree.map(zeros, params),
            exp_avg_sq=jax.tree.map(zeros, params),
        )

    # graftlint: precision(master-fp32)
    def update(grads, state, params=None):
        # under O2 `params` are the fp32 masters held by
        # MixedPrecisionTrainState — the update must never consume the
        # half forward-pass copy (the mark makes call sites checkable)
        if params is None:
            raise ValueError("fused_adam requires params")
        count = state.count + 1
        lr = learning_rate(count) if callable(learning_rate) else learning_rate
        c = count.astype(jnp.float32)
        if bias_correction:
            bc1 = 1.0 - jnp.power(b1, c)
            bc2 = 1.0 - jnp.power(b2, c)
        else:
            bc1 = bc2 = jnp.asarray(1.0, jnp.float32)

        impl = resolve_impl(None)

        def leaf(g, p, m, v):
            if fp8:
                n = p.size
                if impl != "xla" and n >= _FP8_KERNEL_MIN:
                    # fused Pallas path: dequant-update-requant in one
                    # pass over the moments (see _fp8_adam_kernel)
                    lr_bc = jnp.stack([
                        jnp.asarray(lr, jnp.float32),
                        bc1.astype(jnp.float32),
                        bc2.astype(jnp.float32)])
                    return _fp8_adam_leaf_pallas(
                        g, p, m, v, lr_bc, b1, b2, eps, weight_decay,
                        adam_w_mode, impl == "pallas_interpret")
                m_f = _fp8_dequant(m, n)
                v_f = _fp8_dequant(v, n)
                gf = g.astype(jnp.float32).reshape(-1)
                pf = p.astype(jnp.float32).reshape(-1)
            else:
                m_f, v_f = m, v
                gf = g.astype(m.dtype)
                pf = p.astype(m.dtype)
            if not adam_w_mode and weight_decay != 0.0:
                gf = gf + weight_decay * pf
            m_new = b1 * m_f + (1.0 - b1) * gf
            v_new = b2 * v_f + (1.0 - b2) * jnp.square(gf)
            denom = jnp.sqrt(v_new / bc2) + eps
            step = m_new / (bc1 * denom)
            if adam_w_mode and weight_decay != 0.0:
                step = step + weight_decay * pf
            upd = -lr * step
            if fp8:
                return (upd.reshape(p.shape).astype(p.dtype),
                        _fp8_quant(m_new), _fp8_quant(v_new))
            return upd.astype(p.dtype), m_new, v_new

        g_leaves, treedef = jax.tree.flatten(grads)
        p_leaves = treedef.flatten_up_to(params)
        m_leaves = treedef.flatten_up_to(state.exp_avg)
        v_leaves = treedef.flatten_up_to(state.exp_avg_sq)
        triples = [leaf(g, p, m, v) for g, p, m, v
                   in zip(g_leaves, p_leaves, m_leaves, v_leaves)]
        updates, exp_avg, exp_avg_sq = _unzip3(treedef, triples)
        return updates, FusedAdamState(count=count, exp_avg=exp_avg,
                                       exp_avg_sq=exp_avg_sq)

    return optax.GradientTransformation(init, update)
