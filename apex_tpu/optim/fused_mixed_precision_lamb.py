"""LAMB with fp32 master state for half-precision model params.

Reference: ``apex/optimizers/fused_mixed_precision_lamb.py`` — a LAMB
variant whose exp_avg/exp_avg_sq *and* a master copy of the params live
in fp32 while the model runs bf16/fp16; each step updates the masters
and writes the rounded copy back to the model params.

TPU design: an optax wrapper whose state carries the fp32 masters plus
the inner :func:`apex_tpu.optim.fused_lamb` state.  The emitted update
is ``cast(new_master) - param`` so that after ``optax.apply_updates``
the model params are exactly the rounded masters — the whole step is
one fused jit region over the pytree (amp_C parity, SURVEY.md §2.2).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax

from apex_tpu.optim.fused_lamb import fused_lamb

__all__ = ["fused_mixed_precision_lamb", "FusedMixedPrecisionLambState"]


class FusedMixedPrecisionLambState(NamedTuple):
    master_params: Any           # fp32 copies of the model params
    inner: Any                   # FusedLambState over the masters


def fused_mixed_precision_lamb(
    learning_rate: Any = 1e-3,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-6,
    weight_decay: float = 0.01,
    max_grad_norm: Optional[float] = 1.0,
    **lamb_kwargs: Any,
) -> optax.GradientTransformation:
    """LAMB over fp32 masters for half model params (drop-in optax tx)."""
    inner = fused_lamb(learning_rate, b1=b1, b2=b2, eps=eps,
                       weight_decay=weight_decay,
                       max_grad_norm=max_grad_norm, **lamb_kwargs)

    def _to_master(p):
        if jnp.issubdtype(jnp.asarray(p).dtype, jnp.floating):
            return jnp.asarray(p, jnp.float32)
        return p

    def init(params):
        masters = jax.tree.map(_to_master, params)
        return FusedMixedPrecisionLambState(masters, inner.init(masters))

    # graftlint: precision(master-fp32)
    def update(grads, state, params=None):
        if params is None:
            raise ValueError(
                "fused_mixed_precision_lamb requires params "
                "(the half-precision model params)")
        fgrads = jax.tree.map(_to_master, grads)
        updates, new_inner = inner.update(fgrads, state.inner,
                                          state.master_params)
        new_masters = optax.apply_updates(state.master_params, updates)
        # model param update = master - param, kept in fp32: apply_updates
        # adds in the promoted (fp32) dtype then casts to the param dtype,
        # so the applied params are exactly the rounded masters (a half-
        # precision difference would lose the low bits across binades).
        model_updates = jax.tree.map(
            lambda m, p: m - p.astype(jnp.float32), new_masters, params)
        return model_updates, FusedMixedPrecisionLambState(
            new_masters, new_inner)

    return optax.GradientTransformation(init, update)
