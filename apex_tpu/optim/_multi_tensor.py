"""Multi-tensor-apply semantics for optimizers — see
:mod:`apex_tpu.utils.tree` for the shared implementations.

The reference's ``apex/multi_tensor_apply/multi_tensor_apply.py`` +
``csrc/multi_tensor_*_kernel.cu`` launch ONE fused CUDA kernel over an
arbitrary list of tensors.  Under XLA the mechanism is unnecessary — a
jitted pytree function compiles to fused loops — but the semantics
("whole-parameter-list update in one compiled computation") are what
every optimizer in this package implements.
"""

from apex_tpu.utils.tree import (
    tree_l2_norm,
    per_tensor_l2_norms,
    tree_scale,
    tree_axpby,
    global_grad_clip_coef,
)

__all__ = [
    "tree_l2_norm",
    "per_tensor_l2_norms",
    "tree_scale",
    "tree_axpby",
    "global_grad_clip_coef",
]
