"""LARC — Layer-wise Adaptive Rate Clipping/scaling.

Reference: ``apex/parallel/LARC.py``.  The reference wraps any optimizer
and, per parameter, rescales the gradient by the "local lr"

    local_lr = trust_coefficient * ||p|| / (||g|| + wd * ||p|| + eps)

- ``clip=True`` (LARC): the effective lr is ``min(local_lr, lr)``,
  implemented by scaling the grad by ``min(local_lr/lr, 1)``.
- ``clip=False`` (LARS): the grad is scaled by ``local_lr`` directly.

Implemented as an optax-style gradient transformation to chain *before*
the base optimizer: ``optax.chain(larc(lr, ...), fused_sgd(lr, ...))``,
matching the reference's "wrap any optimizer" contract.  Weight decay is
only read for the local-lr formula (the base optimizer applies it),
exactly like the reference which pops and re-adds wd around the step.
"""

from __future__ import annotations

from typing import Union

import jax
import jax.numpy as jnp
import optax

__all__ = ["larc"]


def larc(
    learning_rate: Union[float, optax.Schedule],
    trust_coefficient: float = 0.02,
    clip: bool = True,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
) -> optax.GradientTransformation:
    """LARC — layer-wise adaptive rate clipping/scaling around any
    update (reference ``apex.parallel.LARC``): per-leaf trust ratio
    ``trust_coefficient * ||p|| / ||g||``, clipped at 1 in clip mode."""
    def init(params):
        return optax.ScaleState()

    def update(grads, state, params=None):
        if params is None:
            raise ValueError("larc requires params")
        lr = learning_rate if not callable(learning_rate) else None
        if lr is None:
            raise ValueError(
                "larc needs a concrete learning_rate float matching the "
                "base optimizer's (schedules: pass the same callable value "
                "per step via inject_hyperparams)")

        def leaf(g, p):
            gf = g.astype(jnp.float32)
            pf = p.astype(jnp.float32)
            p_norm = jnp.sqrt(jnp.sum(jnp.square(pf)))
            g_norm = jnp.sqrt(jnp.sum(jnp.square(gf)))
            local_lr = trust_coefficient * p_norm / (
                g_norm + weight_decay * p_norm + eps)
            # reference: only adapt when both norms are nonzero
            ok = (p_norm > 0) & (g_norm > 0)
            if clip:
                scale = jnp.where(ok, jnp.minimum(local_lr / lr, 1.0), 1.0)
            else:
                scale = jnp.where(ok, local_lr, 1.0)
            return (gf * scale).astype(g.dtype)

        return jax.tree.map(leaf, grads, params), state

    return optax.GradientTransformation(init, update)
