"""FusedAdagrad (reference: ``apex/optimizers/fused_adagrad.py`` +
``csrc/multi_tensor_adagrad_kernel.cu``):

    h += g^2 ;  p -= lr * g / (sqrt(h) + eps)

with L2 weight decay folded into the gradient ("adagrad_w_mode=False"
upstream behavior).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Union

import jax
import jax.numpy as jnp
import optax

__all__ = ["fused_adagrad", "FusedAdagradState"]


class FusedAdagradState(NamedTuple):
    count: jnp.ndarray
    sum_sq: Any


def fused_adagrad(
    learning_rate: Union[float, optax.Schedule] = 1e-2,
    eps: float = 1e-10,
    weight_decay: float = 0.0,
    initial_accumulator_value: float = 0.0,
) -> optax.GradientTransformation:
    """Adagrad as one fused pytree update (reference
    ``apex.optimizers.FusedAdagrad`` / ``amp_C.multi_tensor_adagrad``)."""
    def init(params):
        return FusedAdagradState(
            count=jnp.zeros((), jnp.int32),
            sum_sq=jax.tree.map(
                lambda p: jnp.full_like(p, initial_accumulator_value,
                                        dtype=jnp.float32), params),
        )

    # graftlint: precision(master-fp32)
    def update(grads, state, params=None):
        if params is None:
            raise ValueError("fused_adagrad requires params")
        count = state.count + 1
        lr = learning_rate(count) if callable(learning_rate) else learning_rate

        def leaf(g, p, h):
            gf = g.astype(jnp.float32)
            if weight_decay != 0.0:
                gf = gf + weight_decay * p.astype(jnp.float32)
            h_new = h + jnp.square(gf)
            return (-lr * gf / (jnp.sqrt(h_new) + eps)).astype(p.dtype), h_new

        g_leaves, treedef = jax.tree.flatten(grads)
        p_leaves = treedef.flatten_up_to(params)
        h_leaves = treedef.flatten_up_to(state.sum_sq)
        pairs = [leaf(g, p, h) for g, p, h
                 in zip(g_leaves, p_leaves, h_leaves)]
        updates = treedef.unflatten([t[0] for t in pairs])
        sums = treedef.unflatten([t[1] for t in pairs])
        return updates, FusedAdagradState(count=count, sum_sq=sums)

    return optax.GradientTransformation(init, update)
