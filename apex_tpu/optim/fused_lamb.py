"""FusedLAMB — layer-wise adaptive LAMB for large-batch training.

Reference: ``apex/optimizers/fused_lamb.py`` +
``csrc/multi_tensor_lamb_kernel.cu`` (and the two-stage
``lamb_stage_1/lamb_stage_2`` variants).  The reference computes:

1. global gradient norm over all params; clip by ``max_grad_norm``;
2. Adam-style moments with bias correction → per-param ``update``
   (+ decoupled weight decay term);
3. per-parameter trust ratio ``||p|| / ||update||`` (1.0 when either
   norm is zero), via ``multi_tensor_l2norm(per_tensor=True)``;
4. ``p -= lr * trust_ratio * update``.

Here stages 1–4 are one jitted pytree computation; the per-tensor norms
are XLA-fused reductions (SURVEY.md §2.2 "north-star" semantics).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Union

import jax
import jax.numpy as jnp
import optax

from apex_tpu.utils.tree import global_grad_clip_coef

__all__ = ["fused_lamb", "FusedLambState"]


class FusedLambState(NamedTuple):
    count: jnp.ndarray
    exp_avg: Any
    exp_avg_sq: Any


def fused_lamb(
    learning_rate: Union[float, optax.Schedule] = 1e-3,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-6,
    weight_decay: float = 0.01,
    bias_correction: bool = True,
    adam_w_mode: bool = True,
    max_grad_norm: Optional[float] = 1.0,
    trust_clip: bool = False,
    always_adapt: bool = False,
    shard_axis: Optional[str] = None,
) -> optax.GradientTransformation:
    """Build the FusedLAMB gradient transformation.

    ``max_grad_norm`` — global-norm clip applied to grads before the
    update (reference default 1.0).  ``trust_clip`` clamps the trust
    ratio at 1.  ``always_adapt=False`` (reference behavior): the trust
    ratio is only applied when ``weight_decay != 0`` for that group —
    here, globally.

    ``shard_axis`` — set when the update runs on ZeRO shards inside
    ``shard_map`` (:mod:`apex_tpu.parallel.distributed_optim`): the
    global-norm clip and the per-tensor trust-ratio norms ``psum``
    their squared sums over that mesh axis, so the shard-local update
    is exactly the full-tensor one (the reference
    ``distributed_fused_lamb``'s allreduced-L2 stage).
    """

    def init(params):
        zeros = lambda p: jnp.zeros_like(p)
        return FusedLambState(
            count=jnp.zeros((), jnp.int32),
            exp_avg=jax.tree.map(zeros, params),
            exp_avg_sq=jax.tree.map(zeros, params),
        )

    # graftlint: precision(master-fp32)
    def update(grads, state, params=None):
        if params is None:
            raise ValueError("fused_lamb requires params")
        count = state.count + 1
        lr = learning_rate(count) if callable(learning_rate) else learning_rate
        c = count.astype(jnp.float32)
        if bias_correction:
            bc1 = 1.0 - jnp.power(b1, c)
            bc2 = 1.0 - jnp.power(b2, c)
        else:
            bc1 = bc2 = jnp.asarray(1.0, jnp.float32)

        # stage 0: fused global-norm clip (multi_tensor_l2norm + scale;
        # with shard_axis the norm spans every ZeRO shard).
        coef, _ = global_grad_clip_coef(grads, max_grad_norm,
                                        axis=shard_axis)

        use_trust = always_adapt or weight_decay != 0.0

        def leaf_pre(g, p, m, v):
            gf = g.astype(jnp.float32) * coef
            pf = p.astype(jnp.float32)
            if not adam_w_mode and weight_decay != 0.0:
                gf = gf + weight_decay * pf
            m_new = b1 * m.astype(jnp.float32) + (1.0 - b1) * gf
            v_new = b2 * v.astype(jnp.float32) + (1.0 - b2) * jnp.square(gf)
            upd = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps)
            if adam_w_mode and weight_decay != 0.0:
                upd = upd + weight_decay * pf
            return pf, upd, m_new, v_new

        g_leaves, treedef = jax.tree.flatten(grads)
        p_leaves = treedef.flatten_up_to(params)
        m_leaves = treedef.flatten_up_to(state.exp_avg)
        v_leaves = treedef.flatten_up_to(state.exp_avg_sq)
        pre = [leaf_pre(g, p, m, v) for g, p, m, v
               in zip(g_leaves, p_leaves, m_leaves, v_leaves)]

        if use_trust and pre:
            # per-tensor trust-ratio norms, batched: every leaf's
            # w²/u² squared sum rides ONE stacked vector (and, under
            # shard_axis, ONE psum — the reference's single fused
            # allreduced-L2 stage, not 2 scalar collectives per leaf)
            sq = jnp.stack(
                [jnp.sum(jnp.square(pf)) for pf, _, _, _ in pre]
                + [jnp.sum(jnp.square(upd)) for _, upd, _, _ in pre])
            if shard_axis is not None:
                sq = jax.lax.psum(sq, shard_axis)
            norms = jnp.sqrt(sq)
            n_leaves = len(pre)

        triples = []
        for i, (pf, upd, m_new, v_new) in enumerate(pre):
            if use_trust:
                w_norm = norms[i]
                u_norm = norms[n_leaves + i]
                # reference: ratio = w/u when both > 0, else 1.0
                ratio = jnp.where(
                    (w_norm > 0) & (u_norm > 0), w_norm / u_norm, 1.0)
                if trust_clip:
                    ratio = jnp.minimum(ratio, 1.0)
            else:
                ratio = jnp.asarray(1.0, jnp.float32)
            p, m, v = p_leaves[i], m_leaves[i], v_leaves[i]
            triples.append(((-lr * ratio * upd).astype(p.dtype),
                            m_new.astype(m.dtype), v_new.astype(v.dtype)))
        updates = treedef.unflatten([t[0] for t in triples])
        exp_avg = treedef.unflatten([t[1] for t in triples])
        exp_avg_sq = treedef.unflatten([t[2] for t in triples])
        return updates, FusedLambState(count=count, exp_avg=exp_avg,
                                       exp_avg_sq=exp_avg_sq)

    return optax.GradientTransformation(init, update)
