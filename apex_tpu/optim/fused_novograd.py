"""FusedNovoGrad — NovoGrad with per-layer second moments.

Reference: ``apex/optimizers/fused_novograd.py`` +
``csrc/multi_tensor_novograd_kernel.cu``.  NovoGrad (Ginsburg et al.)
keeps ONE scalar second moment per layer (parameter tensor):

    v_t   = b2 * v_{t-1} + (1-b2) * ||g_t||^2         (scalar)
    m_t   = b1 * m_{t-1} + (g_t / (sqrt(v_t)+eps) + wd * p)
    p    -= lr * m_t

with ``v_0 = ||g_0||^2`` on the first step (reference's ``init_v``) and
optional gradient averaging (``grad_averaging`` scales the grad term by
``1-b1``).  ``norm_type=2`` only (the reference also ships inf-norm).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Union

import jax
import jax.numpy as jnp
import optax

__all__ = ["fused_novograd", "FusedNovoGradState"]


class FusedNovoGradState(NamedTuple):
    count: jnp.ndarray
    exp_avg: Any          # per-param first moment
    exp_avg_sq: Any       # per-LAYER scalar second moment


def fused_novograd(
    learning_rate: Union[float, optax.Schedule] = 1e-3,
    b1: float = 0.95,
    b2: float = 0.98,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    grad_averaging: bool = False,
    bias_correction: bool = False,
) -> optax.GradientTransformation:
    """NovoGrad — layer-wise second moment (one scalar per tensor),
    reference ``apex.optimizers.FusedNovoGrad`` incl. ``init_zero`` and
    decoupled weight-decay semantics."""
    def init(params):
        return FusedNovoGradState(
            count=jnp.zeros((), jnp.int32),
            exp_avg=jax.tree.map(jnp.zeros_like, params),
            exp_avg_sq=jax.tree.map(
                lambda p: jnp.zeros((), jnp.float32), params),
        )

    # graftlint: precision(master-fp32)
    def update(grads, state, params=None):
        if params is None:
            raise ValueError("fused_novograd requires params")
        count = state.count + 1
        lr = learning_rate(count) if callable(learning_rate) else learning_rate
        first = state.count == 0
        grad_coef = (1.0 - b1) if grad_averaging else 1.0
        c = count.astype(jnp.float32)
        if bias_correction:
            bc1 = 1.0 - jnp.power(b1, c)
            bc2 = 1.0 - jnp.power(b2, c)
        else:
            bc1 = bc2 = jnp.asarray(1.0, jnp.float32)

        def leaf(g, p, m, v):
            gf = g.astype(jnp.float32)
            pf = p.astype(jnp.float32)
            gnorm_sq = jnp.sum(jnp.square(gf))
            v_new = jnp.where(first, gnorm_sq,
                              b2 * v + (1.0 - b2) * gnorm_sq)
            denom = jnp.sqrt(v_new / bc2) + eps
            step_term = grad_coef * (gf / denom)
            if weight_decay != 0.0:
                step_term = step_term + grad_coef * weight_decay * pf
            m_new = b1 * m.astype(jnp.float32) + step_term
            return ((-lr * m_new / bc1).astype(p.dtype),
                    m_new.astype(m.dtype), v_new)

        g_leaves, treedef = jax.tree.flatten(grads)
        p_leaves = treedef.flatten_up_to(params)
        m_leaves = treedef.flatten_up_to(state.exp_avg)
        v_leaves = treedef.flatten_up_to(state.exp_avg_sq)
        triples = [leaf(g, p, m, v) for g, p, m, v
                   in zip(g_leaves, p_leaves, m_leaves, v_leaves)]
        updates = treedef.unflatten([t[0] for t in triples])
        exp_avg = treedef.unflatten([t[1] for t in triples])
        exp_avg_sq = treedef.unflatten([t[2] for t in triples])
        return updates, FusedNovoGradState(
            count=count, exp_avg=exp_avg, exp_avg_sq=exp_avg_sq)

    return optax.GradientTransformation(init, update)
