"""apex_tpu.serving — continuous-batching TPU inference engine.

Multi-tenant serving over the model zoo's ``decode=True`` KV-cache
path: a slotted cache pool with fixed ``max_slots × max_seq_len``
shapes (:mod:`~apex_tpu.serving.cache`), one jitted decode step with
per-slot device-array sampling params (:mod:`~apex_tpu.serving.engine`),
a bounded FIFO queue with slot-level admission/eviction at step
boundaries (:mod:`~apex_tpu.serving.scheduler`), and a threaded
submit/stream front-end (:mod:`~apex_tpu.serving.api`).  Greedy decode
through the engine is token-identical to
``apex_tpu.models.generate``; steady state is retrace-free and
*enforced* so by ``tracecheck.retrace_guard``.  See docs/serving.md.
"""

from apex_tpu.serving.api import (
    InferenceServer,
    RequestFailed,
    RequestHandle,
    ServerClosed,
)
from apex_tpu.serving.engine import DEFAULT_BUCKETS, Engine
from apex_tpu.serving.scheduler import (
    QueueFull,
    Request,
    Scheduler,
    StepEvent,
)

__all__ = [
    "InferenceServer",
    "RequestHandle",
    "RequestFailed",
    "ServerClosed",
    "Engine",
    "DEFAULT_BUCKETS",
    "Scheduler",
    "Request",
    "StepEvent",
    "QueueFull",
]
