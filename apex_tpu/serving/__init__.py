"""apex_tpu.serving — continuous-batching TPU inference engine.

Multi-tenant serving over the model zoo's ``decode=True`` KV-cache
path, in two cache layouts:

- **paged** (:class:`PagedEngine`, the hot path): a block-pool
  KV-cache sized in TOKENS with per-slot block tables
  (:mod:`~apex_tpu.serving.cache`), chunked prefill riding inside the
  fused mixed prefill+decode step, token-budget admission and
  block-exhaustion preemption — HBM footprint and per-step bytes
  scale with live tokens, not ``max_slots × max_seq_len``.  On top:
  refcounted **copy-on-write prefix sharing** (``share_prefixes=True``
  — a hot system prompt's KV pages are trie-matched at admission and
  mapped once per replica instead of once per tenant) and
  **speculative decoding** (``spec_tokens=K`` — host-side
  prompt-lookup drafts verified K-at-a-time in one mixed-step
  application, accepted-prefix + bonus token per step) and
  **tensor-parallel replicas** (``tp=M`` / ``mesh=`` — ONE replica
  spans M chips: weights ride the GSPMD TP layers, the pool shards on
  ``kv_heads`` via the shard_map path of
  :func:`~apex_tpu.ops.paged_attention.paged_attention`, block
  tables / trie / allocator stay replicated host logic — the first
  path that serves a model too big for one chip);
- **dense** (:class:`Engine`, the fallback): the fixed
  ``max_slots × max_seq_len`` slotted slab with bucket-padded prefill.

Plus a bounded FIFO queue with admission/eviction at step boundaries
(:mod:`~apex_tpu.serving.scheduler`), a threaded submit/stream
front-end with TTFT / step-latency / pool-occupancy telemetry
(:mod:`~apex_tpu.serving.api`), and a multi-replica fleet front door
(:mod:`~apex_tpu.serving.fleet`): least-loaded health-gated routing
across N replica servers with circuit breakers, graceful drain,
replica-kill tenant migration, and queue-depth/TTFT-driven scale
hooks.  Greedy decode through either engine is token-identical to
``apex_tpu.models.generate`` — including across a migration; steady
state is retrace-free and *enforced* so by
``tracecheck.retrace_guard``.  See docs/serving.md and docs/fleet.md.
"""

from apex_tpu.serving.api import (
    InferenceServer,
    ReplicaDraining,
    RequestFailed,
    RequestHandle,
    ServerClosed,
)
from apex_tpu.serving.fleet import (
    AutoscaleConfig,
    CircuitBreaker,
    FleetHandle,
    FleetRouter,
)
from apex_tpu.serving.engine import (
    DEFAULT_BUCKETS,
    Engine,
    PagedEngine,
    StepOutput,
    prompt_lookup_draft,
    tp_mesh,
)
from apex_tpu.serving.cache import (
    BlockAllocator,
    BlockExhausted,
    PrefixTrie,
    chain_digests,
)
from apex_tpu.serving.scheduler import (
    QueueFull,
    Request,
    Scheduler,
    StepEvent,
)

__all__ = [
    "InferenceServer",
    "RequestHandle",
    "RequestFailed",
    "ServerClosed",
    "ReplicaDraining",
    "FleetRouter",
    "FleetHandle",
    "CircuitBreaker",
    "AutoscaleConfig",
    "Engine",
    "PagedEngine",
    "StepOutput",
    "BlockAllocator",
    "BlockExhausted",
    "PrefixTrie",
    "chain_digests",
    "prompt_lookup_draft",
    "tp_mesh",
    "DEFAULT_BUCKETS",
    "Scheduler",
    "Request",
    "StepEvent",
    "QueueFull",
]
