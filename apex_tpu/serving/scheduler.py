"""Continuous batching: bounded FIFO queue + slot-level admission.

The scheduler owns the host-side view the device never needs: which
request occupies which slot, what has been emitted, and who is waiting.
At every step boundary it (1) refills free slots from the queue in FIFO
order — prompts quantized to the engine's length buckets so admission
replays compiled prefills — then (2) runs one engine decode step and
routes each produced token to its request, evicting tenants that
finished (eos or budget).  Requests never wait for each other's
completion: a 512-token generation and a 3-token one share the batch,
and the short one's slot is re-used the step after it finishes — the
continuous-batching property that fixed-batch ``generate()`` lacks.

Thread-safety: ``submit`` may be called from any thread (the queue has
its own lock); ``run_step`` must be called from the single thread that
owns the engine (``apex_tpu.serving.api.InferenceServer``'s worker).
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
import time
from collections import deque
from typing import Deque, List, Optional, Tuple

import numpy as np

from apex_tpu.resilience import faults
from apex_tpu.serving.engine import StepOutput
from apex_tpu.utils.metrics import counters

__all__ = ["Request", "Scheduler", "QueueFull", "StepEvent"]


class QueueFull(RuntimeError):
    """The bounded request queue is at capacity."""


@dataclasses.dataclass
class Request:
    """One generation request (host object).

    ``top_k=None``/``0`` disables truncation, ``top_p=None``/``1.0``
    disables the nucleus filter, ``eos_id=None`` disables eos
    stopping, ``seed`` derives the request's private sampling key
    (tokens are a function of the request, not of its co-tenants).
    ``deadline`` (seconds from acceptance, ``None`` = unbounded) is
    enforced by the serving loop: an expired request — queued or
    mid-decode — fails with an explicit terminal error rather than
    occupying a slot forever.

    ``retries`` / ``accepted_at`` are serving-loop bookkeeping: how
    many times this request has been requeued after a transient step
    fault, and when it entered the queue (the deadline epoch).
    """

    prompt: np.ndarray
    max_new_tokens: int
    temperature: float = 0.0
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    eos_id: Optional[int] = None
    seed: int = 0
    deadline: Optional[float] = None
    uid: int = -1                       # assigned by the scheduler
    tokens: List[int] = dataclasses.field(default_factory=list)
    retries: int = 0
    accepted_at: float = -1.0


@dataclasses.dataclass(frozen=True)
class StepEvent:
    """One token routed to one request at a step boundary."""

    request: Request
    token: int
    finished: bool


class Scheduler:
    """Bounded-queue continuous batcher over one
    :class:`~apex_tpu.serving.engine.Engine`."""

    def __init__(self, engine, *, queue_capacity: int = 64):
        if queue_capacity < 1:
            raise ValueError(
                f"queue_capacity must be >= 1, got {queue_capacity}")
        self.engine = engine
        self.queue_capacity = int(queue_capacity)
        self._queue: Deque[Request] = deque()  # graftlint: guarded-by(_lock)
        self._lock = threading.Lock()
        self._uid = itertools.count()
        # host shadow of slot occupancy — the device active mask is
        # never read back outside step().  Fixed-length: only the
        # serving worker assigns items (never resizes), so a monitor
        # thread's iteration (occupancy/has_work) reads each cell
        # atomically and cannot raise or tear
        # graftlint: unguarded(fixed-size list, item writes by the engine-owning worker only; iteration safe)
        self._slots: List[Optional[Request]] = [None] * engine.max_slots
        self._admit_failures: List[Tuple[Request, BaseException]] = []
        #: block-exhaustion preemptions requeued so far (paged engine)
        self.preempts = 0

    # ------------------------------------------------------------ intake
    def submit(self, request: Request) -> Request:
        """Enqueue (FIFO); raises :class:`QueueFull` at capacity and
        ``ValueError`` for requests the engine can never admit (the
        check runs HERE so a doomed request fails at submit time, not
        inside the serving loop)."""
        prompt = np.asarray(request.prompt, np.int32).reshape(-1)
        self.engine.validate_request(
            prompt.shape[0], request.max_new_tokens,
            request.temperature, request.top_k, request.top_p)
        request.prompt = prompt
        # originals, for fault-recovery requeues: a requeued request is
        # re-admitted with prompt = original ++ tokens-so-far and the
        # remaining budget, both derived from these
        request._prompt0 = prompt                    # type: ignore[attr-defined]
        request._budget0 = int(request.max_new_tokens)  # type: ignore[attr-defined]
        with self._lock:
            if len(self._queue) >= self.queue_capacity:
                raise QueueFull(
                    f"request queue at capacity "
                    f"({self.queue_capacity}); retry after a drain")
            request.uid = next(self._uid)
            request.accepted_at = time.monotonic()
            self._queue.append(request)
        return request

    def requeue(self, request: Request) -> None:
        """Put an already-ACCEPTED request back at the queue's front
        (fault-recovery path — see ``InferenceServer._serve``).

        The request continues where it left off: its next admission
        prefills ``original prompt ++ tokens emitted so far`` with the
        remaining budget, so clients keep their streamed prefix and the
        total token count is unchanged.  Validates the continuation
        (the longer prompt must still fit a bucket) — a ``ValueError``
        here means the request cannot be resumed and the caller must
        fail it terminally.  Bypasses the capacity check: accepted
        requests are never dropped for queue pressure.
        """
        prompt = np.asarray(request._prompt0, np.int32)  # type: ignore[attr-defined]
        if request.tokens:
            prompt = np.concatenate(
                [prompt, np.asarray(request.tokens, np.int32)])
        budget = int(request._budget0) - len(request.tokens)  # type: ignore[attr-defined]
        self.engine.validate_request(
            prompt.shape[0], budget, request.temperature,
            request.top_k, request.top_p)
        request.prompt = prompt
        request.max_new_tokens = budget
        with self._lock:
            self._queue.appendleft(request)

    def expire_queued(self, now: Optional[float] = None) -> List[Request]:
        """Remove and return queued requests whose deadline has passed
        (in-flight expiry is the serving loop's job — it owns the
        engine slots)."""
        now = time.monotonic() if now is None else now
        expired: List[Request] = []
        with self._lock:
            keep: Deque[Request] = deque()
            for req in self._queue:
                if req.deadline is not None \
                        and now - req.accepted_at > req.deadline:
                    expired.append(req)
                else:
                    keep.append(req)
            self._queue = keep
        return expired

    @property
    def queue_depth(self) -> int:
        with self._lock:
            return len(self._queue)

    @property
    def active_count(self) -> int:
        return sum(r is not None for r in self._slots)

    @property
    def occupancy(self) -> float:
        return self.active_count / self.engine.max_slots

    def has_work(self) -> bool:
        return self.active_count > 0 or self.queue_depth > 0

    # ------------------------------------------------------------- steps
    def _admit_from_queue(self) -> int:
        """Fill free slots FIFO; returns the number admitted.

        A TRANSIENT failure during one admission (a retryable
        :class:`~apex_tpu.resilience.faults.TransientError`, injected
        or real — the raiser's contract is that engine state is
        untouched) is isolated to that request: it is retried from the
        queue's front once, then recorded terminally on
        ``take_admit_failures`` — either way the other tenants keep
        decoding.  Any other exception propagates (fatal, as before).

        Admission is TOKEN-gated, not just slot-gated: the engine's
        ``can_admit`` must also clear the queue head (the paged engine
        requires free pages to cover prompt + decode headroom; the
        dense engine always says yes).  The check stays FIFO — a
        too-big head blocks the queue rather than being overtaken,
        so admission order cannot starve large requests.  Under a
        quantized pool (``kv_dtype="int8"``/``"fp8"``, ISSUE 8) the
        gate needs no extra logic: the engine sizes ``pool_tokens`` in
        QUANTIZED tokens (~2–4× more at equal HBM), so the same
        free-page arithmetic admits the reclaimed capacity as
        occupancy.
        """
        admitted = 0
        for slot, occupant in enumerate(self._slots):
            if occupant is not None:
                continue
            with self._lock:
                if not self._queue:
                    break
                head = self._queue[0]
                # shared-aware token gate: the engine discounts
                # trie-resident prefix pages, so a hot-prompt request
                # admits into capacity sharing reclaimed
                if not self.engine.can_admit(head.prompt.shape[0],
                                             head.max_new_tokens,
                                             prompt=head.prompt):
                    counters.inc("serving.admit_blocked")
                    break
                req = self._queue.popleft()
            try:
                faults.inject("serving.admit")
                self.engine.admit(
                    slot, req.prompt,
                    max_new_tokens=req.max_new_tokens,
                    temperature=req.temperature,
                    top_k=req.top_k or 0,
                    top_p=req.top_p,
                    eos_id=req.eos_id,
                    seed=req.seed)
            except faults.TransientError as exc:
                counters.inc("serving.admit_fault")
                if req.retries < 1:
                    req.retries += 1
                    with self._lock:
                        self._queue.appendleft(req)
                else:
                    self._admit_failures.append((req, exc))
                # don't spin on the same request within one boundary —
                # the retry happens at the next step
                break
            self._slots[slot] = req
            admitted += 1
        return admitted

    # graftlint: thread-entry(serving-worker)
    def take_admit_failures(self) -> List[Tuple[Request, BaseException]]:
        """Drain requests whose admission failed terminally (the
        serving loop routes these to their handles)."""
        failed, self._admit_failures = self._admit_failures, []
        return failed

    # graftlint: thread-entry(serving-worker)
    def evict(self, slot: int) -> Optional[Request]:
        """Release ``slot`` (zero the engine row) and return its
        tenant — deadline-expiry and fault-recovery path.  Call from
        the engine-owning thread only."""
        req = self._slots[slot]
        if req is None:
            return None
        self.engine.release(slot)
        self._slots[slot] = None
        return req

    # graftlint: thread-entry(serving-worker)
    def evict_all(self) -> List[Request]:
        """Evict every active tenant and return them in slot order —
        the graceful-drain path (``InferenceServer.begin_drain``).
        Engine rows are released through the same compiled ``release``
        as normal completion, so a paged pool gets all its pages back
        (``blocks_in_use`` returns to 0 once the queue is also
        cancelled).  Call from the engine-owning thread only."""
        evicted: List[Request] = []
        for slot in range(len(self._slots)):
            req = self.evict(slot)
            if req is not None:
                evicted.append(req)
        return evicted

    # graftlint: thread-entry(serving-worker)
    def run_step(self) -> List[StepEvent]:
        """One step boundary: admit → decode → route/evict.

        Returns the tokens produced this step (empty when idle).  Call
        from the engine-owning thread only.

        Paged engines return a :class:`~apex_tpu.serving.engine.
        StepOutput`: only ``emitted`` slots route a token (mid-prefill
        tenants compute but emit nothing), and ``preempted`` tenants —
        evicted by the engine for block exhaustion, pages already
        freed — are requeued at the FRONT to continue from their
        streamed prefix (the PR-4 fault-recovery machinery, but
        without spending the request's transient-fault retry budget:
        preemption is scheduling, not failure).
        """
        self._admit_from_queue()
        if self.active_count == 0:
            return []
        out = self.engine.step()
        if isinstance(out, StepOutput):
            tokens, finished, _emitted, preempted, counts = out
        else:
            tokens, finished = out
            counts, preempted = None, ()
        for slot in preempted:
            req = self._slots[slot]
            if req is None:
                continue
            self._slots[slot] = None    # engine already freed the slot
            self.preempts += 1
            counters.inc("serving.preempt")
            try:
                self.requeue(req)
            except ValueError as exc:   # unresumable continuation
                self._admit_failures.append((req, exc))
        events: List[StepEvent] = []
        for slot, req in enumerate(self._slots):
            if req is None:
                continue
            # a drafted (speculative) step can emit SEVERAL tokens for
            # one slot — route each in order, finishing on the last
            n_emit = 1 if counts is None else int(counts[slot])
            if n_emit == 0:
                continue
            row = tokens[slot]
            for j in range(n_emit):
                tok = int(row[j]) if np.ndim(row) else int(row)
                fin = bool(finished[slot]) and j == n_emit - 1
                req.tokens.append(tok)
                events.append(StepEvent(req, tok, fin))
                if fin:
                    self.engine.release(slot)
                    self._slots[slot] = None
        return events

    # graftlint: single-threaded(synchronous convenience for tests/batch scripts; no server thread runs beside it)
    def drain(self) -> List[StepEvent]:
        """Run steps until queue and slots are empty; returns every
        event in emission order (synchronous convenience for tests and
        batch scripts — the threaded server streams instead)."""
        events: List[StepEvent] = []
        while self.has_work():
            events.extend(self.run_step())
        return events

    def cancel_queued(self) -> List[Request]:
        """Drop every not-yet-admitted request (server shutdown path)."""
        with self._lock:
            dropped = list(self._queue)
            self._queue.clear()
        return dropped
