"""Continuous batching: bounded FIFO queue + slot-level admission.

The scheduler owns the host-side view the device never needs: which
request occupies which slot, what has been emitted, and who is waiting.
At every step boundary it (1) refills free slots from the queue in FIFO
order — prompts quantized to the engine's length buckets so admission
replays compiled prefills — then (2) runs one engine decode step and
routes each produced token to its request, evicting tenants that
finished (eos or budget).  Requests never wait for each other's
completion: a 512-token generation and a 3-token one share the batch,
and the short one's slot is re-used the step after it finishes — the
continuous-batching property that fixed-batch ``generate()`` lacks.

Thread-safety: ``submit`` may be called from any thread (the queue has
its own lock); ``run_step`` must be called from the single thread that
owns the engine (``apex_tpu.serving.api.InferenceServer``'s worker).
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
from collections import deque
from typing import Deque, List, Optional, Tuple

import numpy as np

__all__ = ["Request", "Scheduler", "QueueFull", "StepEvent"]


class QueueFull(RuntimeError):
    """The bounded request queue is at capacity."""


@dataclasses.dataclass
class Request:
    """One generation request (host object).

    ``top_k=None``/``0`` disables truncation, ``top_p=None``/``1.0``
    disables the nucleus filter, ``eos_id=None`` disables eos
    stopping, ``seed`` derives the request's private sampling key
    (tokens are a function of the request, not of its co-tenants).
    """

    prompt: np.ndarray
    max_new_tokens: int
    temperature: float = 0.0
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    eos_id: Optional[int] = None
    seed: int = 0
    uid: int = -1                       # assigned by the scheduler
    tokens: List[int] = dataclasses.field(default_factory=list)


@dataclasses.dataclass(frozen=True)
class StepEvent:
    """One token routed to one request at a step boundary."""

    request: Request
    token: int
    finished: bool


class Scheduler:
    """Bounded-queue continuous batcher over one
    :class:`~apex_tpu.serving.engine.Engine`."""

    def __init__(self, engine, *, queue_capacity: int = 64):
        if queue_capacity < 1:
            raise ValueError(
                f"queue_capacity must be >= 1, got {queue_capacity}")
        self.engine = engine
        self.queue_capacity = int(queue_capacity)
        self._queue: Deque[Request] = deque()
        self._lock = threading.Lock()
        self._uid = itertools.count()
        # host shadow of slot occupancy — the device active mask is
        # never read back outside step()
        self._slots: List[Optional[Request]] = [None] * engine.max_slots

    # ------------------------------------------------------------ intake
    def submit(self, request: Request) -> Request:
        """Enqueue (FIFO); raises :class:`QueueFull` at capacity and
        ``ValueError`` for requests the engine can never admit (the
        check runs HERE so a doomed request fails at submit time, not
        inside the serving loop)."""
        prompt = np.asarray(request.prompt, np.int32).reshape(-1)
        self.engine.validate_request(
            prompt.shape[0], request.max_new_tokens,
            request.temperature, request.top_k, request.top_p)
        request.prompt = prompt
        with self._lock:
            if len(self._queue) >= self.queue_capacity:
                raise QueueFull(
                    f"request queue at capacity "
                    f"({self.queue_capacity}); retry after a drain")
            request.uid = next(self._uid)
            self._queue.append(request)
        return request

    @property
    def queue_depth(self) -> int:
        with self._lock:
            return len(self._queue)

    @property
    def active_count(self) -> int:
        return sum(r is not None for r in self._slots)

    @property
    def occupancy(self) -> float:
        return self.active_count / self.engine.max_slots

    def has_work(self) -> bool:
        return self.active_count > 0 or self.queue_depth > 0

    # ------------------------------------------------------------- steps
    def _admit_from_queue(self) -> int:
        """Fill free slots FIFO; returns the number admitted."""
        admitted = 0
        for slot, occupant in enumerate(self._slots):
            if occupant is not None:
                continue
            with self._lock:
                if not self._queue:
                    break
                req = self._queue.popleft()
            self.engine.admit(
                slot, req.prompt,
                max_new_tokens=req.max_new_tokens,
                temperature=req.temperature,
                top_k=req.top_k or 0,
                top_p=req.top_p,
                eos_id=req.eos_id,
                seed=req.seed)
            self._slots[slot] = req
            admitted += 1
        return admitted

    def run_step(self) -> List[StepEvent]:
        """One step boundary: admit → decode → route/evict.

        Returns the tokens produced this step (empty when idle).  Call
        from the engine-owning thread only.
        """
        self._admit_from_queue()
        if self.active_count == 0:
            return []
        tokens, finished = self.engine.step()
        events: List[StepEvent] = []
        for slot, req in enumerate(self._slots):
            if req is None:
                continue
            tok = int(tokens[slot])
            fin = bool(finished[slot])
            req.tokens.append(tok)
            events.append(StepEvent(req, tok, fin))
            if fin:
                self.engine.release(slot)
                self._slots[slot] = None
        return events

    def drain(self) -> List[StepEvent]:
        """Run steps until queue and slots are empty; returns every
        event in emission order (synchronous convenience for tests and
        batch scripts — the threaded server streams instead)."""
        events: List[StepEvent] = []
        while self.has_work():
            events.extend(self.run_step())
        return events

    def cancel_queued(self) -> List[Request]:
        """Drop every not-yet-admitted request (server shutdown path)."""
        with self._lock:
            dropped = list(self._queue)
            self._queue.clear()
        return dropped
