"""Multi-replica serving fleet: health-gated router, graceful drain,
and replica-kill survival.

One :class:`~apex_tpu.serving.api.InferenceServer` is one host; heavy
traffic needs N replicas that individually fail, drain, and scale
without client-visible loss.  :class:`FleetRouter` is the front door:

- **Routing** — ``submit()`` goes to the least-loaded *routable*
  replica, ranked by the paged engine's ``blocks_in_use /
  blocks_total`` occupancy gauge (slot occupancy for dense replicas),
  queue depth breaking ties.  A failed routing attempt (full queue,
  closed replica, injected ``fleet.route`` fault) retries with capped,
  deterministically-jittered backoff onto the next-best replica before
  surfacing :class:`~apex_tpu.serving.api.RequestFailed`.
- **Health gating** — a supervisor thread probes every replica's
  ``health()`` on an interval, feeding a per-replica
  :class:`CircuitBreaker`: ``healthy`` → ``suspect`` after K
  consecutive probe failures or a step-latency p99 SLO breach →
  ``ejected`` (unroutable) → after a cooldown, ``probation`` (routable
  again, on trial) → ``healthy`` after consecutive good probes — or
  straight back to ``ejected`` on any probation failure.
- **Tenant migration** — a killed or dead replica's in-flight
  requests are requeued onto survivors via the PR-4/5 streamed-prefix
  machinery (``prompt ++ already-streamed tokens``, remaining budget,
  remaining deadline), so generation resumes elsewhere with greedy
  output token-identical to an uninterrupted run and zero
  client-visible loss (the client's :class:`FleetHandle` just keeps
  streaming).
- **Graceful drain** — :meth:`FleetRouter.drain` stops admitting to a
  replica, migrates every queued/active tenant, waits until the
  replica is empty (its paged pool back to ``blocks_in_use == 0``),
  then shuts it down and detaches it.
- **Scaling** — :meth:`FleetRouter.scale_up` builds a fresh replica
  from the factory; :meth:`FleetRouter.scale_down` routes through
  drain so nothing is lost.  With an :class:`AutoscaleConfig`, the
  supervisor drives both from aggregate queue depth and fleet TTFT
  p99 (:func:`scale_decision`).

Three deterministic fault sites plug into the
:class:`~apex_tpu.resilience.faults.FaultPlan` registry —
``fleet.route`` (per routing attempt), ``fleet.probe`` (per health
probe), and ``replica.kill`` (per supervisor tick; ANY raising kind
fired there SIGKILL-equivalently kills the replica) — so chaos runs
replay exactly; see the site table in ``apex_tpu/resilience/faults.py``.

Per-replica metrics aggregate into one fleet view through
:func:`apex_tpu.utils.metrics.namespaced_sink` /
:meth:`~apex_tpu.utils.metrics.MetricsWriter.merge` (no step-tag
collisions).  ``docs/fleet.md`` is the narrative guide; the chaos
acceptance soaks live in ``tests/test_chaos.py``.

Usage::

    factory = lambda: InferenceServer(model, params, max_slots=16,
                                      kv_cache="paged")
    router = FleetRouter(factory, replicas=3)
    with router:
        h = router.submit(prompt_tokens, max_new_tokens=256)
        for tok in h.stream():
            ...                     # survives a replica dying mid-way
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
import time
import zlib
from collections import deque
from typing import (
    Any, Callable, Deque, Dict, List, Mapping, Optional, Sequence,
)

import numpy as np

from apex_tpu.resilience import faults
from apex_tpu.serving.api import (
    RequestFailed,
    RequestHandle,
    ServerClosed,
)
from apex_tpu.serving.scheduler import QueueFull
from apex_tpu.utils.metrics import (
    MetricsWriter,
    counters,
    namespaced_sink,
    percentile_summary,
)

__all__ = [
    "FleetRouter",
    "FleetHandle",
    "CircuitBreaker",
    "AutoscaleConfig",
    "load_score",
    "select_replica",
    "route_backoff",
    "scale_decision",
    "HEALTHY",
    "SUSPECT",
    "EJECTED",
    "PROBATION",
]

#: every exception class the fault registry can raise — the fleet
#: sites treat ANY raising kind as the site's failure signal
#: (TransientError and Preempted are deliberately not FaultError
#: subclasses; see resilience.faults)
_INJECTED = (faults.FaultError, faults.TransientError, faults.Preempted)

#: circuit-breaker states (module constants so tests and dashboards
#: can name them without importing the class internals)
HEALTHY = "healthy"
SUSPECT = "suspect"
EJECTED = "ejected"
PROBATION = "probation"


class CircuitBreaker:
    """Per-replica health state machine (the router's gate).

    ::

        healthy --[suspect_after consecutive probe failures,
                   or one step-latency p99 breach]--> suspect
        suspect --[eject_after more consecutive failures]--> ejected
        suspect --[probation_probes consecutive successes]--> healthy
        ejected --[cooldown_s elapsed, via tick()]--> probation
        probation --[probation_probes consecutive successes]--> healthy
        probation --[any failure]--> ejected   (fresh cooldown)

    ``ejected`` is the only unroutable state (:attr:`routable`);
    ``suspect`` and ``probation`` still take traffic — the breaker
    sheds a replica only after repeated evidence, and re-admits it on
    trial rather than all at once.  Time is always passed in
    (``now``), so transitions are a pure function of the event
    sequence — unit-testable without clocks and replayable in chaos
    runs.  Thread-safe: the supervisor records probes while client
    dispatch threads record submit failures.  Every ejection counts
    on ``fleet.ejected``.
    """

    def __init__(self, *, suspect_after: int = 3, eject_after: int = 2,
                 cooldown_s: float = 2.0, probation_probes: int = 2):
        if suspect_after < 1 or eject_after < 1 or probation_probes < 1:
            raise ValueError(
                "suspect_after, eject_after and probation_probes must "
                "all be >= 1")
        if cooldown_s <= 0:
            raise ValueError(f"cooldown_s must be > 0, got {cooldown_s}")
        self.suspect_after = int(suspect_after)
        self.eject_after = int(eject_after)
        self.cooldown_s = float(cooldown_s)
        self.probation_probes = int(probation_probes)
        # transitions happen under _mutex; the router's gate reads the
        # state string unlocked (one atomic load — at worst a probe
        # routes to a replica ejected this instant, which the retry
        # path absorbs)
        # graftlint: unguarded(writes under _mutex; unlocked readers take one atomic str load, staleness absorbed by routing retries)
        self.state = HEALTHY
        # RLock: on_latency_breach re-enters on_failure
        self._mutex = threading.RLock()
        self._fails = 0  # graftlint: guarded-by(_mutex)
        self._oks = 0  # graftlint: guarded-by(_mutex)
        self._ejected_at: Optional[float] = None  # graftlint: guarded-by(_mutex)

    @property
    def routable(self) -> bool:
        """Whether the router may send traffic here (not ejected)."""
        return self.state != EJECTED

    def on_success(self, now: float = 0.0) -> str:
        """Record a good probe; returns the (possibly new) state."""
        del now
        with self._mutex:
            self._fails = 0
            if self.state in (SUSPECT, PROBATION):
                self._oks += 1
                if self._oks >= self.probation_probes:
                    self.state = HEALTHY
                    self._oks = 0
            return self.state

    def on_failure(self, now: float = 0.0) -> str:
        """Record a failed probe; returns the (possibly new) state."""
        with self._mutex:
            self._oks = 0
            if self.state == HEALTHY:
                self._fails += 1
                if self._fails >= self.suspect_after:
                    self.state = SUSPECT
                    self._fails = 0
            elif self.state == SUSPECT:
                self._fails += 1
                if self._fails >= self.eject_after:
                    self._eject(now)
            elif self.state == PROBATION:
                self._eject(now)
            return self.state

    def on_latency_breach(self, now: float = 0.0) -> str:
        """A step-latency p99 SLO breach: a healthy replica turns
        suspect immediately (no K-failure grace — latency is measured
        over a whole percentile window, not one probe); a suspect or
        probation replica counts it like a probe failure."""
        with self._mutex:
            if self.state == HEALTHY:
                self._oks = 0
                self._fails = 0
                self.state = SUSPECT
                return self.state
            return self.on_failure(now)

    # graftlint: requires-lock(_mutex)
    def _eject(self, now: float) -> None:
        # callers hold self._mutex
        self.state = EJECTED
        self._fails = 0
        self._oks = 0
        self._ejected_at = now
        counters.inc("fleet.ejected")

    def tick(self, now: float) -> str:
        """Move an ejected replica into probation once ``cooldown_s``
        has elapsed; call once per supervisor tick."""
        with self._mutex:
            if self.state == EJECTED and self._ejected_at is not None \
                    and now - self._ejected_at >= self.cooldown_s:
                self.state = PROBATION
                self._oks = 0
            return self.state


# --------------------------------------------------------------------- #
# pure routing / scaling math (unit-tested without servers)
# --------------------------------------------------------------------- #
def load_score(health: Mapping[str, Any]) -> float:
    """Least-loaded routing key for one replica ``health()`` dict: the
    paged pool's ``blocks_in_use / blocks_total`` occupancy when the
    gauge is present, else the dense slot ``occupancy`` — both in
    [0, 1], comparable across layouts.  Queue depth breaks ties
    upstream (:func:`select_replica`)."""
    total = health.get("blocks_total") or 0
    if total:
        return float(health.get("blocks_in_use", 0)) / float(total)
    return float(health.get("occupancy", 0.0))


def select_replica(
        healths: Sequence[Optional[Mapping[str, Any]]],
        affinity: Optional[Sequence[int]] = None) -> int:
    """Index of the least-loaded ready replica, or -1 when none is.

    ``healths[i]`` is replica i's ``health()`` dict, or ``None`` for a
    replica the caller already excluded (ejected, draining, dead).
    Ranking: :func:`load_score` ascending, then **prefix affinity**
    descending (``affinity[i]`` = trie-resident prefix pages of the
    request on replica i — a hit replica serves the request without
    recomputing or re-storing the shared prompt's KV), then
    ``queue_depth``, then index (stable under ties).  Affinity is a
    TIE-BREAK below load: it concentrates a hot prompt's tenants
    where its pages live, but never overrides least-loaded placement
    (no hot-prompt replica meltdown); with no ``affinity`` the
    pre-ISSUE-7 ordering is unchanged."""
    best = -1
    best_key = None
    for i, h in enumerate(healths):
        if not h or not h.get("ready"):
            continue
        hit = 0 if affinity is None else int(affinity[i])
        key = (load_score(h), -hit, int(h.get("queue_depth", 0)), i)
        if best_key is None or key < best_key:
            best, best_key = i, key
    return best


def route_backoff(attempt: int, uid: int = 0, *, base: float = 0.01,
                  cap: float = 0.25) -> float:
    """Capped exponential backoff with deterministic jitter.

    ``attempt`` counts retries (1 = first retry).  The raw delay
    ``base * 2**(attempt-1)`` is capped at ``cap``, then jittered into
    ``[raw/2, raw]`` by a hash of ``(uid, attempt)`` — the same
    crc32-into-[0,1) trick the fault registry uses, so a chaos run's
    retry timing replays exactly (no live RNG).  The cap holds after
    jitter: the returned delay never exceeds ``cap``."""
    raw = min(float(cap), float(base) * (2.0 ** max(0, attempt - 1)))
    u = zlib.crc32(f"{uid}:{attempt}".encode()) / 2.0 ** 32
    return raw * (0.5 + 0.5 * u)


@dataclasses.dataclass(frozen=True)
class AutoscaleConfig:
    """Queue-depth + TTFT-p99 scale thresholds (the roadmap's scale
    hooks).  ``scale_up_queue_depth`` — aggregate queued requests
    beyond which the fleet adds a replica; ``ttft_slo_p99_s`` — fleet
    TTFT p99 SLO whose breach also scales up (``None`` disables the
    latency trigger); ``scale_down_queue_depth`` — aggregate depth at
    or below which an idle fleet sheds a replica (through drain, so
    scale-down is loss-free); ``min_replicas``/``max_replicas`` bound
    the fleet; ``cooldown_ticks`` suppresses decisions for that many
    supervisor ticks after any scale action (anti-flap)."""

    scale_up_queue_depth: int = 8
    scale_down_queue_depth: int = 0
    ttft_slo_p99_s: Optional[float] = None
    min_replicas: int = 1
    max_replicas: int = 8
    cooldown_ticks: int = 10


def scale_decision(queue_depth: int, ttft_p99_s: Optional[float],
                   n_replicas: int,
                   cfg: AutoscaleConfig) -> Optional[str]:
    """Pure scale decision: ``"up"``, ``"down"``, or ``None``.

    Scale up when below ``min_replicas``, or when hot (aggregate
    ``queue_depth`` above the up-threshold, or TTFT p99 over its SLO)
    and below ``max_replicas``.  Scale down only when NOT hot, at or
    below the down-threshold, and above ``min_replicas``."""
    if n_replicas < cfg.min_replicas:
        return "up"
    hot = queue_depth > cfg.scale_up_queue_depth or (
        cfg.ttft_slo_p99_s is not None and ttft_p99_s is not None
        and ttft_p99_s > cfg.ttft_slo_p99_s)
    if hot:
        return "up" if n_replicas < cfg.max_replicas else None
    if queue_depth <= cfg.scale_down_queue_depth \
            and n_replicas > cfg.min_replicas:
        return "down"
    return None


# --------------------------------------------------------------------- #
# fleet request bookkeeping
# --------------------------------------------------------------------- #
@dataclasses.dataclass
class _FleetRequest:
    """Router-side record of one request: everything migration needs
    to resume it elsewhere (original prompt, streamed tokens, sampling
    params, remaining budget/deadline) plus where it currently runs."""

    uid: int
    prompt: np.ndarray
    budget: int
    temperature: float
    top_k: Optional[int]
    top_p: Optional[float]
    eos_id: Optional[int]
    seed: int
    deadline: Optional[float]
    accepted_at: float = 0.0
    tokens: List[int] = dataclasses.field(default_factory=list)
    handle: Optional["FleetHandle"] = None
    replica: int = -1
    migrations: int = 0


class FleetHandle(RequestHandle):
    """Client-side view of one *fleet* request — the same streaming
    API and error contract as :class:`~apex_tpu.serving.api.
    RequestHandle` (``TimeoutError`` retryable; ``RequestFailed`` /
    ``ServerClosed`` terminal), with migration invisible: if the
    replica serving this request dies or drains, the stream simply
    pauses while the router requeues it onto a survivor, then resumes
    — ``tokens_so_far``/``result`` return the union of tokens streamed
    across every replica the request visited, each exactly once."""


@dataclasses.dataclass
class _Replica:
    """Router-side record of one replica server."""

    index: int
    server: Any                      # InferenceServer (duck-typed)
    breaker: CircuitBreaker
    writer: Optional[MetricsWriter] = None
    draining: bool = False
    dead: bool = False
    #: fleet uid -> record, for every request currently on this replica
    active: Dict[int, _FleetRequest] = dataclasses.field(
        default_factory=dict)


class FleetRouter:
    """Health-gated front door over a pool of replica
    :class:`~apex_tpu.serving.api.InferenceServer`\\ s.

    ``factory`` builds one (unstarted) replica server; the router owns
    their lifecycle (``start``/``warmup`` on :meth:`start`, shutdown
    on :meth:`shutdown`, plus :meth:`drain`, :meth:`kill_replica`,
    :meth:`scale_up`/:meth:`scale_down` in between).  ``submit``
    mirrors the server's signature (minus backpressure knobs — the
    router retries across replicas instead of blocking on one queue)
    and returns a :class:`FleetHandle`.

    Failure semantics extend the single-server contract
    (``docs/resilience.md``): every accepted request still ends in
    exactly one of completed / ``RequestFailed`` / ``ServerClosed`` —
    but a replica dying (killed, crashed) or draining no longer fails
    its requests: they migrate to survivors and keep streaming, with
    greedy output token-identical to an uninterrupted run.
    ``RequestFailed`` now also covers routing exhaustion (no replica
    accepted after the retry budget) and failed migration (no
    survivor, expired deadline, unresumable continuation).

    The supervisor thread wakes every ``probe_interval`` seconds to
    probe health into each replica's :class:`CircuitBreaker` (with
    ``step_slo_ms`` as the latency-breach threshold, when set), check
    the ``replica.kill`` fault site, process pending migrations, drive
    autoscaling (when ``autoscale`` is set), and aggregate metrics.
    """

    def __init__(self, factory: Optional[Callable[[], Any]] = None, *,
                 replicas: int = 2,
                 servers: Optional[Sequence[Any]] = None,
                 probe_interval: float = 0.25,
                 breaker_factory: Optional[
                     Callable[[], CircuitBreaker]] = None,
                 step_slo_ms: Optional[float] = None,
                 route_retries: int = 3,
                 backoff_base: float = 0.01,
                 backoff_cap: float = 0.25,
                 autoscale: Optional[AutoscaleConfig] = None,
                 metrics: Optional[MetricsWriter] = None,
                 metrics_interval: int = 8):
        if servers is None and factory is None:
            raise ValueError("pass a replica factory or servers=[...]")
        if servers is None and replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        if route_retries < 0:
            raise ValueError(
                f"route_retries must be >= 0, got {route_retries}")
        self.factory = factory
        self.probe_interval = float(probe_interval)
        self.step_slo_ms = step_slo_ms
        self.route_retries = int(route_retries)
        self.backoff_base = float(backoff_base)
        self.backoff_cap = float(backoff_cap)
        self.autoscale = autoscale
        self.metrics = metrics
        self.metrics_interval = max(1, int(metrics_interval))
        self._breaker_factory = breaker_factory or CircuitBreaker
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        # append-only replica table (replicas are marked dead, never
        # removed): appends hold _lock; unlocked readers (monitors,
        # _live() on lock-free paths) index or iterate a list that
        # only grows, which CPython reads atomically — at worst a
        # probe misses a replica added this instant
        # graftlint: unguarded(append-only under _lock; unlocked iteration/indexing of a grow-only list is atomic per op)
        self._replicas: List[Optional[_Replica]] = []
        self._requests: Dict[int, _FleetRequest] = {}  # graftlint: guarded-by(_lock)
        self._migq: Deque[int] = deque()  # graftlint: guarded-by(_lock)
        self._pump_lock = threading.Lock()
        self._uid = itertools.count()
        self._route_steps = itertools.count()
        # TTFT reservoir: replica worker taps append (under _cv, which
        # IS _lock) while the supervisor/clients snapshot — unlocked,
        # list(deque)-during-append raises RuntimeError (the
        # pre-existing race graftlint's concurrency pass flagged)
        self._ttft: Deque[float] = deque(maxlen=4096)  # graftlint: guarded-by(_lock)
        self._submitted = 0  # graftlint: guarded-by(_lock)
        self._completed = 0  # graftlint: guarded-by(_lock)
        self._failed = 0  # graftlint: guarded-by(_lock)
        self._migrated = 0  # graftlint: guarded-by(_lock)
        self._tokens_total = 0  # graftlint: guarded-by(_lock)
        self._scale_cooldown = 0  # graftlint: guarded-by(_lock)
        self._running = False
        self._stopping = False
        self._stop_supervisor = False
        self._supervisor: Optional[threading.Thread] = None
        #: last exception a supervisor pass swallowed (the loop itself
        #: must outlive any single bad tick); surfaced in health()
        self.supervisor_error: Optional[BaseException] = None
        if servers is not None:
            for server in servers:
                self._add_replica(server)
        else:
            for _ in range(int(replicas)):
                self._add_replica(self.factory())

    # ---------------------------------------------------------- replicas
    def _add_replica(self, server: Any) -> _Replica:
        rep = _Replica(index=0, server=server,
                       breaker=self._breaker_factory())
        with self._lock:
            rep.index = len(self._replicas)
            self._replicas.append(rep)
        if self.metrics is not None \
                and getattr(server, "metrics", None) is None:
            # route the replica's self-drained emissions into the
            # fleet writer, namespaced — no step-tag collisions.  A
            # server the factory already wired its OWN writer+sink
            # keeps that pipeline untouched: its rows drain
            # server-side to the caller's sink and are deliberately
            # NOT fleet-aggregated (hand the router metrics-less
            # servers to aggregate them) — the fleet view still
            # carries the fleet/ summary rows either way
            rep.writer = MetricsWriter(sink=namespaced_sink(
                f"replica{rep.index}", self.metrics))
            server.metrics = rep.writer
        return rep

    def _live(self) -> List[_Replica]:
        """Replicas that can take traffic-lifecycle actions (not dead,
        not draining) — call with or without the lock held."""
        return [r for r in self._replicas
                if r is not None and not r.dead and not r.draining]

    @property
    def num_replicas(self) -> int:
        """Live (not dead, not draining) replica count."""
        with self._lock:
            return len(self._live())

    def replica(self, index: int) -> Any:
        """The replica server at ``index`` (introspection/tests)."""
        rep = self._replicas[index]
        if rep is None:
            raise ValueError(f"replica {index} was removed")
        return rep.server

    # --------------------------------------------------------- lifecycle
    def start(self, *, warmup: bool = True) -> "FleetRouter":
        """Start every replica (tracing its executables when
        ``warmup``) and the supervisor thread."""
        if self._running:
            raise RuntimeError("fleet already started")
        for rep in self._live():
            rep.server.start(warmup=warmup)
        self._running = True
        self._stopping = False
        self._stop_supervisor = False
        self._supervisor = threading.Thread(
            target=self._supervise, name="apex-tpu-fleet", daemon=True)
        self._supervisor.start()
        return self

    def shutdown(self, *, wait: bool = True,
                 timeout: Optional[float] = None) -> None:
        """Stop the fleet.  ``wait=True`` serves every in-flight
        request to a terminal outcome first (migrations included);
        ``wait=False`` cancels them (:class:`ServerClosed`)."""
        if wait:
            deadline = (None if timeout is None
                        else time.monotonic() + timeout)
            while True:
                self._pump_migrations()
                with self._cv:
                    if not self._requests:
                        break
                    if deadline is not None \
                            and time.monotonic() > deadline:
                        break
                    self._cv.wait(0.05)
        with self._cv:
            self._stopping = True
            self._stop_supervisor = True
            self._cv.notify_all()
        supervisor = self._supervisor
        if supervisor is not None:
            supervisor.join(timeout)
            self._supervisor = None
        for rep in list(self._replicas):
            if rep is not None and not rep.dead:
                rep.server.shutdown(wait=wait)
        # anything still tracked lost its replica without a migration
        # target: fail it explicitly (never silently lost)
        leftovers = []
        with self._cv:
            leftovers = list(self._requests.values())
            self._requests.clear()
            self._migq.clear()
            self._failed += len(leftovers)
        for rec in leftovers:
            rec.handle._fail(ServerClosed(
                "fleet shut down before the request finished"))
        if self.metrics is not None:
            self._emit_metrics()
        self._running = False

    def __enter__(self) -> "FleetRouter":
        return self.start()

    def __exit__(self, exc_type, exc, tb) -> None:
        self.shutdown(wait=exc_type is None)

    # ------------------------------------------------------------ intake
    def submit(self, prompt, *, max_new_tokens: int,
               temperature: float = 0.0, top_k: Optional[int] = None,
               top_p: Optional[float] = None,
               eos_id: Optional[int] = None, seed: int = 0,
               deadline: Optional[float] = None) -> FleetHandle:
        """Route one request to the least-loaded routable replica;
        returns its :class:`FleetHandle`.

        Raises :class:`~apex_tpu.serving.api.RequestFailed` when no
        replica accepts within the retry budget (each attempt backs
        off per :func:`route_backoff` and moves to the next-best
        replica), and :class:`ServerClosed` on a stopped fleet.
        ``deadline`` is fleet-scoped: migration forwards the
        *remaining* deadline to the new replica.
        """
        if not self._running or self._stopping:
            raise ServerClosed("fleet is not running")
        rec = _FleetRequest(
            uid=next(self._uid),
            prompt=np.asarray(prompt, np.int32).reshape(-1),
            budget=int(max_new_tokens),
            temperature=float(temperature),
            top_k=top_k, top_p=top_p, eos_id=eos_id, seed=int(seed),
            deadline=None if deadline is None else float(deadline),
            accepted_at=time.monotonic())
        rec.handle = FleetHandle(rec)
        with self._lock:
            self._requests[rec.uid] = rec
            self._submitted += 1
        try:
            self._dispatch(rec)
        except BaseException:
            with self._lock:
                self._requests.pop(rec.uid, None)
                self._submitted -= 1
            raise
        return rec.handle

    # ---------------------------------------------------------- routing
    def _select(self, excluded,
                prompt=None) -> Optional[_Replica]:
        """Least-loaded routable replica (health probed fresh), or
        ``None``.  ``prompt`` (the request's ``original ++ streamed``
        tokens) feeds the prefix-affinity tie-break: a replica whose
        trie already holds the prompt's prefix pages wins ties, so a
        hot system prompt's tenants converge where its KV lives — the
        routing hook PR 6 left open."""
        with self._lock:
            candidates = [r for r in self._live()
                          if r.breaker.routable
                          and r.index not in excluded]
            n = len(self._replicas)
        healths: List[Optional[Dict[str, Any]]] = [None] * n
        affinity = [0] * n
        for rep in candidates:
            try:
                healths[rep.index] = rep.server.health()
            except Exception:               # noqa: BLE001 — a replica
                healths[rep.index] = None   # too broken to probe is
                continue                    # simply not a candidate
            if prompt is not None:
                try:
                    affinity[rep.index] = int(getattr(
                        rep.server, "prefix_hit_blocks",
                        lambda _p: 0)(prompt))
                except Exception:           # noqa: BLE001 — affinity
                    affinity[rep.index] = 0  # is advisory, never fatal
        index = select_replica(healths, affinity)
        return None if index < 0 else self._replicas[index]

    def _dispatch(self, rec: _FleetRequest, *,
                  migration: bool = False) -> None:
        """Place ``rec`` on a replica — first admission and migration
        share this path (a migration's prompt is ``original ++
        streamed tokens`` with the remaining budget/deadline).  Raises
        :class:`RequestFailed` after the retry budget."""
        prompt = rec.prompt
        if rec.tokens:
            prompt = np.concatenate(
                [prompt, np.asarray(rec.tokens, np.int32)])
        budget = rec.budget - len(rec.tokens)
        last: Optional[BaseException] = None
        excluded: set = set()
        attempts = self.route_retries + 1
        for attempt in range(1, attempts + 1):
            if attempt > 1:
                time.sleep(route_backoff(
                    attempt - 1, rec.uid, base=self.backoff_base,
                    cap=self.backoff_cap))
            # recomputed per attempt: backoff slept above is charged
            # against the fleet-scoped deadline, never granted back
            deadline = None
            if rec.deadline is not None:
                remaining = rec.deadline - (time.monotonic()
                                            - rec.accepted_at)
                if migration and remaining <= 0:
                    raise RequestFailed(
                        f"request {rec.uid} deadline ({rec.deadline}s)"
                        f" expired before migration")
                deadline = max(remaining, 0.0)
            try:
                # one deterministic injection per routing attempt
                faults.inject("fleet.route",
                              step=next(self._route_steps))
            except _INJECTED as exc:
                last = exc
                counters.inc("fleet.route_fault")
                continue
            target = self._select(excluded, prompt)
            if target is None:
                # every replica excluded or unroutable — clear the
                # per-round exclusions (a replica may have recovered)
                # and back off
                excluded.clear()
                last = last or ServerClosed("no routable replica")
                continue
            # register BEFORE submitting: a fast worker can stream —
            # even finish — the request before submit() returns, and
            # the tap must find consistent bookkeeping
            with self._lock:
                rec.replica = target.index
                target.active[rec.uid] = rec
            try:
                target.server.submit(
                    prompt, max_new_tokens=budget,
                    temperature=rec.temperature, top_k=rec.top_k,
                    top_p=rec.top_p, eos_id=rec.eos_id, seed=rec.seed,
                    deadline=deadline, block=False,
                    tap=self._tap_for(rec, target.index))
            except QueueFull as exc:
                last = exc
                counters.inc("fleet.route_retry")
                excluded.add(target.index)
                with self._lock:
                    target.active.pop(rec.uid, None)
                continue
            except ServerClosed as exc:
                last = exc
                counters.inc("fleet.route_retry")
                excluded.add(target.index)
                target.breaker.on_failure(time.monotonic())
                with self._lock:
                    target.active.pop(rec.uid, None)
                continue
            except ValueError as exc:       # unresumable continuation
                with self._lock:
                    target.active.pop(rec.uid, None)
                failure = RequestFailed(
                    f"request {rec.uid} not routable: {exc}")
                failure.__cause__ = exc
                raise failure
            return
        counters.inc("fleet.route_failed")
        failure = RequestFailed(
            f"request {rec.uid}: no replica accepted after "
            f"{attempts} routing attempts")
        failure.__cause__ = last
        raise failure

    # --------------------------------------------------- stream plumbing
    def _tap_for(self, rec: _FleetRequest, replica_index: int):
        def tap(token: Optional[int], finished: bool,
                error: Optional[BaseException]) -> None:
            if error is not None:
                self._on_inner_error(rec, replica_index, error)
            else:
                self._on_inner_token(rec, replica_index, token,
                                     finished)
        return tap

    # graftlint: thread-entry(replica-worker)
    def _on_inner_token(self, rec: _FleetRequest, replica_index: int,
                        token: int, finished: bool) -> None:
        """A replica delivered one token (its worker thread): mirror
        it into the fleet handle and record it for migration."""
        first = not rec.tokens
        if first:           # clock read off the per-token hot path;
            # computed before taking _cv so lock-wait is not counted
            ttft = time.monotonic() - rec.accepted_at
        rec.tokens.append(int(token))
        rec.handle._deliver(int(token), bool(finished))
        with self._cv:
            if first:
                self._ttft.append(ttft)
            self._tokens_total += 1
            if finished:
                rep = self._replicas[replica_index]
                if rep is not None:
                    rep.active.pop(rec.uid, None)
                self._requests.pop(rec.uid, None)
                self._completed += 1
                self._cv.notify_all()

    # graftlint: thread-entry(replica-worker)
    def _on_inner_error(self, rec: _FleetRequest, replica_index: int,
                        error: BaseException) -> None:
        """A replica failed this request.  :class:`ServerClosed` (the
        replica died, was killed, or is draining) queues a migration —
        the fleet handle stays open and the stream resumes on a
        survivor; anything else (:class:`RequestFailed`: deadline,
        double transient fault) is terminal and forwarded."""
        migrate = isinstance(error, ServerClosed) and not self._stopping
        with self._cv:
            rep = self._replicas[replica_index]
            if rep is not None:
                rep.active.pop(rec.uid, None)
            if migrate:
                self._migq.append(rec.uid)
                self._cv.notify_all()
                return
            self._requests.pop(rec.uid, None)
            self._failed += 1
            self._cv.notify_all()
        rec.handle._fail(error)

    def _terminal(self, rec: _FleetRequest,
                  error: BaseException) -> None:
        with self._cv:
            self._requests.pop(rec.uid, None)
            self._failed += 1
            self._cv.notify_all()
        rec.handle._fail(error)

    def _pump_migrations(self) -> None:
        """Re-dispatch every queued migration (survivors continue each
        tenant from its streamed prefix).  Serialized; callable from
        the supervisor loop, :meth:`drain`'s wait loop, and
        :meth:`kill_replica` alike."""
        with self._pump_lock:
            while True:
                with self._lock:
                    if not self._migq:
                        return
                    uid = self._migq.popleft()
                    rec = self._requests.get(uid)
                if rec is None or rec.handle.done:
                    continue
                if self._stopping:
                    self._terminal(rec, ServerClosed(
                        "fleet shut down before the request finished"))
                    continue
                try:
                    self._dispatch(rec, migration=True)
                except RequestFailed as exc:
                    self._terminal(rec, exc)
                    continue
                rec.migrations += 1
                counters.inc("fleet.migrated")
                with self._cv:
                    self._migrated += 1
                    self._cv.notify_all()

    # ------------------------------------------------- drain / kill / scale
    def drain(self, index: int, *,
              timeout: Optional[float] = 120.0) -> Any:
        """Gracefully drain replica ``index`` and detach it.

        Stops admitting (router-side exclusion + the server's own
        ``begin_drain``), migrates every queued/in-flight tenant onto
        survivors via the streamed-prefix requeue, waits until the
        replica is empty, then shuts it down.  Loss-free: every active
        tenant finishes elsewhere or fails *explicitly*; the drained
        replica's paged pool is back to ``blocks_in_use == 0``.
        Returns the drained server (detached from the fleet).

        A ``TimeoutError`` leaves the replica draining but NOT wedged:
        ``drain(index)`` again resumes waiting on the same drain (it
        is idempotent up to the shutdown), or ``kill_replica(index)``
        abandons it.
        """
        with self._lock:
            rep = self._replicas[index]
            if rep is None or rep.dead:
                raise ValueError(f"replica {index} is not live")
            resuming = rep.draining
            rep.draining = True
        if not resuming:
            counters.inc("fleet.drain")
            rep.server.begin_drain()
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        while True:
            self._pump_migrations()
            with self._cv:
                pending = [uid for uid, rc in self._requests.items()
                           if rc.replica == index]
                if not rep.active and not pending:
                    break
                if deadline is not None \
                        and time.monotonic() > deadline:
                    raise TimeoutError(
                        f"drain of replica {index} did not complete "
                        f"within {timeout}s ({len(pending)} tenants "
                        f"pending); drain({index}) again to keep "
                        f"waiting, or kill_replica({index})")
                self._cv.wait(0.02)
        rep.server.shutdown(wait=True)
        with self._lock:
            rep.dead = True                  # detached from the fleet
        return rep.server

    def kill_replica(self, index: int) -> None:
        """SIGKILL-equivalent chaos drill on replica ``index``: the
        worker dies without draining or releasing engine state (see
        ``InferenceServer.kill``); every in-flight tenant migrates to
        survivors and resumes from its streamed prefix.  The
        ``replica.kill`` fault site routes here."""
        with self._lock:
            rep = self._replicas[index]
            if rep is None or rep.dead:
                return
            rep.dead = True
        counters.inc("fleet.replica_killed")
        rep.server.kill()
        # the dying worker's handle cancellations queued the
        # migrations — place them now rather than on the next tick
        self._pump_migrations()

    def scale_up(self, *, warmup: bool = True) -> Optional[int]:
        """Add one replica from the factory; returns its index (or
        ``None`` at the autoscale ``max_replicas`` ceiling)."""
        if self.factory is None:
            raise RuntimeError(
                "scale_up needs a replica factory (the router was "
                "built from a fixed server list)")
        if self.autoscale is not None \
                and self.num_replicas >= self.autoscale.max_replicas:
            return None
        server = self.factory()
        if self._running:
            # start (and warm) BEFORE joining the pool: the supervisor
            # probes every pooled replica, and a replica mid-warmup
            # would rack up "stopped" probe failures it never earned
            server.start(warmup=warmup)
        rep = self._add_replica(server)
        counters.inc("fleet.scale_up")
        return rep.index

    def scale_down(self, index: Optional[int] = None, *,
                   timeout: Optional[float] = 120.0) -> Optional[Any]:
        """Remove one replica through :meth:`drain` (loss-free).  With
        no ``index``, the replica with the fewest in-flight tenants
        goes (fewest migrations).  Returns the drained server, or
        ``None`` when the fleet is at its floor."""
        floor = (self.autoscale.min_replicas
                 if self.autoscale is not None else 1)
        with self._lock:
            live = self._live()
            if len(live) <= floor:
                return None
            if index is None:
                index = min(live,
                            key=lambda r: (len(r.active), r.index)
                            ).index
        counters.inc("fleet.scale_down")
        return self.drain(index, timeout=timeout)

    def maybe_scale(self, healths: Optional[
            Dict[int, Dict[str, Any]]] = None) -> Optional[str]:
        """One autoscale evaluation (the supervisor calls this every
        tick; tests may call it directly): aggregate queue depth +
        fleet TTFT p99 through :func:`scale_decision`, honoring the
        anti-flap cooldown.  ``healths`` reuses the tick's probe
        results (by replica index) instead of re-sweeping every
        server.  Returns the action taken."""
        cfg = self.autoscale
        if cfg is None:
            return None
        # finish an in-flight scale-down first: drain is resumable, so
        # the supervisor retries it in probe_interval-bounded slices
        # instead of blocking a whole tick or leaking a draining
        # zombie (draining replicas are invisible to _live(), so
        # nothing else would ever complete them)
        with self._lock:
            draining = [r for r in self._replicas
                        if r is not None and not r.dead and r.draining]
        if draining:
            try:
                self.drain(draining[0].index,
                           timeout=self.probe_interval)
            except TimeoutError:
                pass                       # resumed next tick
            return None
        with self._lock:
            if self._scale_cooldown > 0:
                self._scale_cooldown -= 1
                return None
        depth = sum(h.get("queue_depth", 0)
                    for h in self._healths(healths).values())
        ttft = self.latency_summary().get("ttft_p99_s")
        decision = scale_decision(depth, ttft, self.num_replicas, cfg)
        if decision == "up":
            if self.scale_up() is None:
                return None
        elif decision == "down":
            try:
                if self.scale_down(
                        timeout=self.probe_interval) is None:
                    return None
            except TimeoutError:
                pass       # the draining branch above finishes it
        if decision:
            with self._lock:
                self._scale_cooldown = cfg.cooldown_ticks
        return decision

    # --------------------------------------------------------- supervisor
    def _supervise(self) -> None:  # graftlint: thread-entry(fleet-supervisor)
        tick = 0
        next_tick = time.monotonic()
        while True:
            with self._cv:
                if self._stop_supervisor:
                    break
                wait = next_tick - time.monotonic()
                if wait > 0:
                    self._cv.wait(wait)
                if self._stop_supervisor:
                    break
            now = time.monotonic()
            run_tick = now >= next_tick
            try:
                # completions/errors notify _cv so migrations pump
                # promptly, but the probe/scale/metrics body keeps its
                # own cadence — tick-denominated knobs (breaker
                # streaks, autoscale cooldown, fault-site steps) must
                # count probe_interval beats, not request completions
                self._pump_migrations()
                if run_tick:
                    self._tick(now, tick)
            except Exception as exc:        # noqa: BLE001 — one bad
                # pass (a factory/warmup failure inside autoscale, a
                # drain timeout) must not kill the supervisor: probing
                # and migration pumping are what keep "never silently
                # lost, never hung" true for the whole fleet
                self.supervisor_error = exc
                counters.inc("fleet.supervisor_error")
            finally:
                # advance OUTSIDE the try: a persistently-raising tick
                # (factory that always OOMs, a broken metrics sink)
                # must still consume its beat, or the loop would spin
                # hot at wait<=0 re-firing fault sites at a frozen step
                if run_tick:
                    tick += 1
                    next_tick = now + self.probe_interval

    def _tick(self, now: float, tick: int) -> None:
        """One supervisor pass: ``replica.kill`` fault site, health
        probes through the breakers, dead-replica detection, pending
        migrations, autoscale, metrics.  ``tick`` is the fault-site
        step (shared by every replica probed this pass — pin specs
        with ``step``/``times``)."""
        with self._lock:
            replicas = [r for r in self._replicas
                        if r is not None and not r.dead]
        healths: Dict[int, Dict[str, Any]] = {}
        for rep in replicas:
            if rep.draining:
                continue
            try:
                # ANY raising kind at this site is a kill order
                faults.inject("replica.kill", step=tick)
            except _INJECTED:
                self.kill_replica(rep.index)
                continue
            ok, health = self._probe(rep, tick)
            if health is not None:
                healths[rep.index] = health
            if not ok:
                rep.breaker.on_failure(now)
            elif health is not None and health["status"] == "failed":
                # the worker died on its own — its cancel path already
                # queued the migrations; just mark the body
                with self._lock:
                    rep.dead = True
                counters.inc("fleet.replica_dead")
            else:
                breached = False
                # the latency breach is a HEALTHY→suspect signal only:
                # the p99 window is a trailing reservoir, and a
                # shed/probation replica serves no traffic to refresh
                # it — letting the stale percentile re-fire there
                # would eject a recovered replica forever on zero new
                # evidence (suspect→ejected stays probe-driven)
                if self.step_slo_ms is not None \
                        and rep.breaker.state == HEALTHY:
                    p99 = rep.server.latency_summary().get(
                        "step_ms_p99")
                    breached = p99 is not None and p99 > self.step_slo_ms
                if breached:
                    rep.breaker.on_latency_breach(now)
                else:
                    rep.breaker.on_success(now)
            rep.breaker.tick(now)
        self._pump_migrations()
        self.maybe_scale(healths)
        if self.metrics is not None \
                and tick % self.metrics_interval == 0:
            self._emit_metrics(healths)

    def _probe(self, rep: _Replica, tick: int):
        """One health probe: the ``fleet.probe`` fault site fires
        first (a raising kind counts as a failed probe — exactly how a
        flaky network or hung host looks to the breaker), then the
        replica's ``health()``."""
        try:
            faults.inject("fleet.probe", step=tick)
            health = rep.server.health()
        except _INJECTED:
            counters.inc("fleet.probe_fault")
            return False, None
        except Exception:                   # noqa: BLE001 — a probe
            return False, None              # must never kill the loop
        if health["status"] == "failed":
            return True, health             # dead, not unprobeable
        return bool(health.get("ready")), health

    # ---------------------------------------------------------- telemetry
    def _healths(self, cached: Optional[
            Dict[int, Dict[str, Any]]] = None
            ) -> Dict[int, Dict[str, Any]]:
        """``health()`` per live replica, preferring the tick's cached
        probe results so one supervisor pass sweeps each server once."""
        out: Dict[int, Dict[str, Any]] = {}
        for rep in self._live():
            health = None if cached is None else cached.get(rep.index)
            if health is None:
                try:
                    health = rep.server.health()
                except Exception:           # noqa: BLE001
                    continue
            out[rep.index] = health
        return out

    def _emit_metrics(self, healths: Optional[
            Dict[int, Dict[str, Any]]] = None) -> None:
        """Aggregate one fleet row (replica rows arrive continuously
        through their namespaced sinks) and drain the fleet writer."""
        writer = self.metrics
        if writer is None:
            return
        with self._lock:
            stats = {
                "replicas_live": len(self._live()),
                "in_flight": len(self._requests),
                "submitted": self._submitted,
                "completed": self._completed,
                "failed": self._failed,
                "migrated": self._migrated,
                "tokens_total": self._tokens_total,
            }
        sweep = self._healths(healths).values()
        stats["queue_depth"] = sum(
            int(h.get("queue_depth", 0)) for h in sweep)
        stats["replicas_ready"] = sum(
            bool(h.get("ready")) for h in sweep)
        # prefix-sharing / speculative-decoding merged view: summed
        # page gauges, fleet-mean accept rate (paged replicas only)
        stats["shared_blocks"] = sum(
            int(h.get("shared_blocks", 0)) for h in sweep)
        stats["cow_forks"] = sum(
            int(h.get("cow_forks", 0)) for h in sweep)
        rates = [float(h["spec_accept_rate"]) for h in sweep
                 if "spec_accept_rate" in h]
        if rates:
            stats["spec_accept_rate"] = sum(rates) / len(rates)
        # narrowest KV storage width in the fleet (8 = some replica
        # serves quantized pages); numeric for the metrics pipeline —
        # the dtype NAMES ride health()["kv_dtypes"]
        bits = [int(h["kv_bits"]) for h in sweep if "kv_bits" in h]
        if bits:
            stats["kv_bits_min"] = min(bits)
        # mesh view (ISSUE 13): a replica is no longer one chip — the
        # fleet's capacity is N replicas × M chips, and per-chip
        # throughput must divide by chips_total, not replicas_live
        chips = [int(h.get("chips_per_replica", 1)) for h in sweep]
        stats["chips_per_replica"] = max(chips, default=1)
        stats["chips_total"] = sum(chips)
        stats.update(self.latency_summary())
        writer(writer.advance_step(),
               {f"fleet/{k}": float(v) for k, v in stats.items()})
        writer.drain()

    def latency_summary(self) -> Dict[str, float]:
        """Fleet-level latency percentiles: TTFT over every request
        the router accepted (migration pauses included — the client's
        honest first-token wait), plus the worst per-replica decode
        step p99 (``step_ms_p99_max``)."""
        # snapshot under _lock: replica workers append concurrently,
        # and iterating a deque during an append raises RuntimeError
        with self._lock:
            ttft = list(self._ttft)
        out: Dict[str, float] = {}
        out.update(percentile_summary(
            ttft, "ttft_p50_s", "ttft_p99_s"))
        p99s = []
        for rep in self._live():
            try:
                p99 = rep.server.latency_summary().get("step_ms_p99")
            except Exception:               # noqa: BLE001
                continue
            if p99 is not None:
                p99s.append(p99)
        if p99s:
            out["step_ms_p99_max"] = float(max(p99s))
        return out

    def stats(self) -> Dict[str, int]:
        """Fleet scoreboard (the chaos-soak ledger): ``submitted ==
        completed + failed + in_flight`` at every instant — nothing is
        ever silently lost."""
        with self._lock:
            return {
                "submitted": self._submitted,
                "completed": self._completed,
                "failed": self._failed,
                "in_flight": len(self._requests),
                "migrated": self._migrated,
                "tokens_total": self._tokens_total,
                "replicas_live": len(self._live()),
            }

    def health(self) -> Dict[str, Any]:
        """Fleet readiness probe: ``ready`` when at least one replica
        is routable and ready; ``replicas`` carries each replica's
        breaker state, drain/dead flags, in-flight count, and its own
        ``health()`` dict (for live replicas)."""
        entries = []
        ready = 0
        with self._lock:
            replicas = [r for r in self._replicas if r is not None]
        for rep in replicas:
            entry: Dict[str, Any] = {
                "index": rep.index,
                "breaker": rep.breaker.state,
                "draining": rep.draining,
                "dead": rep.dead,
                "in_flight": len(rep.active),
            }
            if not rep.dead:
                try:
                    health = rep.server.health()
                except Exception:           # noqa: BLE001
                    health = None
                entry["health"] = health
                if health is not None and health.get("ready") \
                        and rep.breaker.routable and not rep.draining:
                    ready += 1
            entries.append(entry)
        sweep = [e.get("health") or {} for e in entries]
        rates = [float(h["spec_accept_rate"]) for h in sweep
                 if "spec_accept_rate" in h]
        out = {
            "status": "serving" if (self._running
                                    and not self._stopping)
            else "stopped",
            "ready": ready > 0 and self._running and not self._stopping,
            "replicas_ready": ready,
            "replicas": entries,
            # fleet-merged prefix-sharing / drafting gauges
            "shared_blocks": sum(
                int(h.get("shared_blocks", 0)) for h in sweep),
            "cow_forks": sum(
                int(h.get("cow_forks", 0)) for h in sweep),
            "spec_accept_rate": (sum(rates) / len(rates)
                                 if rates else 0.0),
            # distinct KV-pool storage dtypes across live replicas
            # (sorted; "none" = an unquantized paged pool) — a mixed
            # fleet mid-rollout legitimately reports several
            "kv_dtypes": sorted({
                str(h.get("kv_dtype") or "none") for h in sweep
                if "kv_bits" in h}),
            # mesh view (ISSUE 13): widest replica + total chips the
            # fleet spans (N replicas × M chips — health gauges stay
            # per-replica, so routing/breakers never changed), plus
            # the distinct per-replica mesh shapes (a mixed fleet
            # mid-resize legitimately reports several)
            "chips_per_replica": max(
                (int(h.get("chips_per_replica", 1)) for h in sweep),
                default=1),
            "chips_total": sum(
                int(h.get("chips_per_replica", 1)) for h in sweep),
            "mesh_shapes": sorted({
                str(h["mesh_shape"]) for h in sweep
                if h.get("mesh_shape")}),
            "supervisor_error": (None if self.supervisor_error is None
                                 else repr(self.supervisor_error)),
        }
        out.update(self.stats())
        return out
