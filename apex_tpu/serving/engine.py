"""Continuous-batching decode engine over the slotted KV-cache pool.

One model, ``max_slots`` concurrent tenants, four compiled
executables for the engine's whole lifetime:

- ``decode_step``  — ONE trace: vmap over slots of the model's
  ``decode=True`` single-token path, followed by branchless per-slot
  sampling whose parameters (temperature / top_k / top_p / eos /
  budget) are device arrays in
  :class:`~apex_tpu.serving.cache.SlotState` — mixed sampling configs
  (nucleus sampling included) share the executable.
- ``prefill``      — one trace PER PROMPT BUCKET: the prompt, right-
  padded to its bucket length, runs through the shared chunked-prefill
  path (``apex_tpu.models.generate.prefill_tokens``) into a fresh
  per-slot cache, whose cursors are then rewound to ``true_len - 1``
  so the first decode step re-feeds the last real prompt token (pad
  K/V beyond the cursor is masked, then overwritten — the padded
  prefill computes exactly the unpadded function).
- ``admit``        — ONE trace: scatter the prefilled slot cache +
  tenant params into the pool at a traced slot index.
- ``release``      — ONE trace: zero the slot row, clear the active bit.

Every executable is wrapped in
:func:`apex_tpu.utils.tracecheck.retrace_guard` with exactly that
budget, so a shape or signature leak raises ``RetraceError`` instead of
silently recompiling per request — the engine *enforces* its own
zero-retrace steady state rather than merely promising it.

Greedy decoding through the engine is token-identical to
``generate()``: same prefill path, same fp32 argmax; the refeed step
recomputes the last prompt position's K/V bit-compatibly up to
blocked-vs-einsum accumulation order (≈1e-7 — far below argmax
resolution on real logits).

The step boundary is the only device→host sync: ``step()`` returns the
per-slot tokens and finished flags as numpy so the scheduler can evict
and refill.  Inactive slots still compute (static shapes — no dynamic
batch); their outputs are ignored on the host and their slot rows are
fully rebuilt at the next admission.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from apex_tpu.models.generate import (
    apply_decode,
    cache_shapes,
    prefill_tokens,
)
from apex_tpu.serving import cache as slot_cache
from apex_tpu.utils import tracecheck

__all__ = ["Engine", "PagedEngine", "StepOutput", "sample_dynamic",
           "DEFAULT_BUCKETS"]

DEFAULT_BUCKETS: Tuple[int, ...] = (32, 128, 512)


class StepOutput(NamedTuple):
    """One engine step's host-visible result.

    ``tokens``/``finished`` are length-``max_slots`` numpy arrays as in
    the dense engine; ``emitted[i]`` marks slots whose token is REAL
    this step (a mid-prefill tenant computes but emits nothing);
    ``preempted`` lists slots the engine evicted for block exhaustion
    before the step ran — their tenants' blocks and slot state are
    already released, and the scheduler requeues them to continue from
    their streamed prefix.
    """

    tokens: np.ndarray
    finished: np.ndarray
    emitted: np.ndarray
    preempted: Tuple[int, ...]


def _check_sampling(vocab_size: int, top_k, top_p) -> None:
    """Shared sampling-parameter validation (dense + paged engines)."""
    if top_k is not None and top_k != 0 \
            and not 1 <= top_k <= vocab_size:
        raise ValueError(
            f"top_k must be in [1, vocab_size={vocab_size}] "
            f"(or 0/None to disable), got {top_k}")
    if top_p is not None and not 0.0 < top_p <= 1.0:
        raise ValueError(
            f"top_p must be in (0, 1] (or None to disable), "
            f"got {top_p}")


def sample_dynamic(logits, keys, temperature, top_k, top_p,
                   vocab_size: int):
    """Branchless per-row sampling with DEVICE-ARRAY parameters.

    ``logits`` (rows, vocab); ``keys`` (rows, 2) uint32; ``temperature``
    / ``top_k`` / ``top_p`` (rows,).  Per row: fp32 argmax when
    ``temperature <= 0`` else top-k- and/or nucleus-truncated
    categorical at ``logits/temperature`` (``top_k == 0`` and
    ``top_p <= 0`` / ``>= 1`` disable their filters — a disabled
    filter is an exact no-op, not an epsilon approximation).  The math
    mirrors ``generate``'s static
    :func:`~apex_tpu.models.generate.sample_logits` — kth-largest /
    nucleus threshold on the scaled logits, ``-1e30`` mask, top-k
    before top-p (the HF warper order) — but every parameter is
    traced, so one executable serves any mix.  The nucleus pass reuses
    the top-k sort (the post-mask order is the pre-mask order with the
    masked tail replaced), so mixed top-p traffic costs no second
    O(V·logV) sort.
    """
    logits = logits.astype(jnp.float32)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    safe_t = jnp.maximum(temperature, 1e-6)[:, None]
    scaled = logits / safe_t
    k = jnp.where(top_k > 0, top_k, vocab_size)          # (rows,)
    ordered = jnp.sort(scaled, axis=-1)                  # ascending
    kth = jnp.take_along_axis(
        ordered, (vocab_size - k)[:, None], axis=-1)     # k-th largest
    scaled = jnp.where(scaled < kth, -1e30, scaled)
    # nucleus filter over the top-k-masked distribution, sort reused:
    # descending masked order = reversed `ordered` with the SAME
    # `< kth` criterion applied that masked `scaled` — value-based,
    # not position-based, so k-th-boundary ties survive in both or
    # neither (keeps engine/generate parity in tie cases)
    p_on = (top_p > 0.0) & (top_p < 1.0)                 # (rows,)
    rev = ordered[:, ::-1]
    desc = jnp.where(rev < kth, -1e30, rev)
    probs = jax.nn.softmax(desc, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep = cum - probs < jnp.where(p_on, top_p, 1.0)[:, None]
    thresh = jnp.min(jnp.where(keep, desc, jnp.inf), axis=-1,
                     keepdims=True)
    scaled = jnp.where(p_on[:, None] & (scaled < thresh), -1e30,
                       scaled)
    sampled = jax.vmap(jax.random.categorical)(keys, scaled)
    sampled = sampled.astype(jnp.int32)
    return jnp.where(temperature > 0.0, sampled, greedy)


class Engine:
    """Multi-tenant KV-cached decode over one model.

    Host API (single-threaded — callers serialize; the
    ``apex_tpu.serving.api`` server owns one engine per worker thread):

    - ``admit(slot, prompt, *, max_new_tokens, ...)`` — prefill +
      install one request into a free slot.
    - ``step()`` — decode every slot one token; returns
      ``(tokens, finished)`` numpy arrays of length ``max_slots``
      (only slots the caller knows to be occupied carry meaning).
    - ``release(slot)`` — zero + free a slot.
    - ``warmup()`` — trace all executables (one dummy request per
      prompt bucket) so steady state is retrace-free from request one.

    ``prompt_buckets`` quantizes prompt lengths: a prompt compiles
    nothing new as long as its length fits an existing bucket, so the
    compile count is ``len(buckets) + 3`` for the process lifetime.
    """

    #: dense slab layout — :class:`PagedEngine` is the paged twin
    paged = False

    def __init__(self, model, params, *, max_slots: int = 4,
                 prompt_buckets: Sequence[int] = DEFAULT_BUCKETS,
                 prefill_chunk: int = 0):
        cfg = getattr(model, "cfg", None)
        if cfg is None or not hasattr(cfg, "max_seq_len"):
            raise ValueError(
                "Engine needs a model with a .cfg carrying max_seq_len "
                "and vocab_size (GPTModel / LlamaModel contract)")
        if not getattr(cfg, "causal", True):
            raise ValueError("Engine requires a causal model "
                             "(decode=True contract)")
        if getattr(cfg, "kv_cache", "dense") == "paged":
            raise ValueError(
                "this model is configured for the paged KV-cache "
                "(cfg.kv_cache='paged') — serve it through "
                "PagedEngine, or pass the dense twin (the engines "
                "build their own layout twin from cfg)")
        if max_slots < 1:
            raise ValueError(f"max_slots must be >= 1, got {max_slots}")
        if prefill_chunk < 0:
            raise ValueError(
                f"prefill_chunk must be >= 0, got {prefill_chunk}")
        self.model = model
        self.max_slots = int(max_slots)
        self.max_seq_len = int(cfg.max_seq_len)
        self.vocab_size = int(cfg.vocab_size)
        buckets = sorted({int(b) for b in prompt_buckets})
        if not buckets or buckets[0] < 1:
            raise ValueError(
                f"prompt_buckets must be positive, got {prompt_buckets}")
        if buckets[-1] >= self.max_seq_len:
            # == is useless too: a max_seq_len prompt has no cache room
            # left to generate even one token
            raise ValueError(
                f"largest prompt bucket ({buckets[-1]}) must be < "
                f"max_seq_len ({self.max_seq_len}) — the cache must "
                f"hold prompt + generated tokens")
        self.prompt_buckets = tuple(buckets)
        self._prefill_chunk = int(prefill_chunk)
        self._variables = dict(params)
        if "cache" in self._variables:
            raise ValueError(
                "params must not carry a 'cache' collection — the "
                "engine owns the cache pool")
        self._shapes = cache_shapes(model, 1)
        slot_cache.validate_cache_tree(self._shapes)
        self.cache = slot_cache.stacked_zeros(self._shapes, max_slots)
        self.state = slot_cache.init_slot_state(max_slots)
        self._build()

    # ------------------------------------------------------------- jits
    def _build(self) -> None:
        model = self.model
        shapes = self._shapes
        vocab = self.vocab_size
        prefill_chunk = self._prefill_chunk

        def decode_step(variables, pool, state):
            # one token for every slot: vmap of the b=1 decode path
            # over the slot axis — per-slot cache cursors make each
            # row attend at its own position (the scalar cache_index
            # of the plain batched path advances in lockstep and
            # cannot express ragged tenants)
            def one_slot(cache_i, tok_i):
                logits, cache_o = apply_decode(
                    model, variables, cache_i, tok_i[None, None])
                return logits[0, -1], cache_o

            logits, pool = jax.vmap(one_slot)(pool, state.tok)
            split = jax.vmap(jax.random.split)(state.rng)
            nxt = sample_dynamic(logits, split[:, 0],
                                 state.temperature, state.top_k,
                                 state.top_p, vocab)
            produced = state.produced + state.active.astype(jnp.int32)
            hit_budget = produced >= state.budget
            hit_eos = (state.eos_id >= 0) & (nxt == state.eos_id)
            finished = state.active & (hit_budget | hit_eos)
            state = state._replace(
                tok=jnp.where(state.active, nxt, state.tok),
                produced=produced,
                active=state.active & ~finished,
                rng=split[:, 1])
            return pool, state, nxt, finished

        def prefill(variables, prompt, true_len):
            # prompt: (1, bucket_len) right-padded; true_len: traced
            fresh = slot_cache.zeros_from_shapes(shapes)
            _last, filled = prefill_tokens(
                model, variables, fresh, prompt, prefill_chunk)
            return slot_cache.rewind_index_leaves(filled, true_len - 1)

        def admit(pool, state, slot, one, tok, budget, temperature,
                  top_k, top_p, eos_id, seed):
            pool = slot_cache.write_slot(pool, slot, one)
            state = slot_cache.admit_slot(
                state, slot, tok, budget, temperature, top_k, top_p,
                eos_id, seed)
            return pool, state

        def release(pool, state, slot):
            return (slot_cache.reset_slot(pool, slot),
                    slot_cache.release_slot(state, slot))

        # exact retrace budgets: ANY excess trace raises RetraceError —
        # the engine's zero-retrace steady state is enforced, not
        # aspirational.  The pool/state threads through with donation
        # (two live copies of max_slots × max_seq_len K/V would double
        # the engine's HBM footprint).
        self._step = tracecheck.retrace_guard(
            decode_step, max_traces=1, name="serving.decode_step",
            donate_argnums=(1, 2))
        self._prefill = tracecheck.retrace_guard(
            prefill, max_traces=len(self.prompt_buckets),
            name="serving.prefill")
        self._admit = tracecheck.retrace_guard(
            admit, max_traces=1, name="serving.admit",
            donate_argnums=(0, 1))
        self._release = tracecheck.retrace_guard(
            release, max_traces=1, name="serving.release",
            donate_argnums=(0, 1))

    # ------------------------------------------------------------- host
    def bucket_for(self, prompt_len: int) -> int:
        """Smallest configured bucket holding ``prompt_len`` tokens."""
        for b in self.prompt_buckets:
            if prompt_len <= b:
                return b
        raise ValueError(
            f"prompt of {prompt_len} tokens exceeds the largest "
            f"prompt bucket ({self.prompt_buckets[-1]}); configure "
            f"larger prompt_buckets")

    def validate_request(self, prompt_len: int, max_new_tokens: int,
                         temperature: float = 0.0,
                         top_k: Optional[int] = None,
                         top_p: Optional[float] = None) -> int:
        """Static admission checks; returns the prompt's bucket."""
        if prompt_len < 1:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        bucket = self.bucket_for(prompt_len)
        if prompt_len + max_new_tokens > self.max_seq_len:
            raise ValueError(
                f"prompt_len ({prompt_len}) + max_new_tokens "
                f"({max_new_tokens}) exceeds max_seq_len "
                f"({self.max_seq_len})")
        _check_sampling(self.vocab_size, top_k, top_p)
        del temperature      # any float is admissible (<=0 -> greedy)
        return bucket

    def can_admit(self, prompt_len: int, max_new_tokens: int) -> bool:
        """Dense pool: the slab reserves worst-case room per slot, so
        a free slot is always admissible (the scheduler gates on slot
        availability; the paged engine gates on free blocks here)."""
        del prompt_len, max_new_tokens
        return True

    def admit(self, slot: int, prompt, *, max_new_tokens: int,
              temperature: float = 0.0, top_k: Optional[int] = None,
              top_p: Optional[float] = None,
              eos_id: Optional[int] = None, seed: int = 0) -> None:
        """Prefill ``prompt`` (1-D int tokens) and install it in
        ``slot``.  The caller owns slot accounting (the scheduler's
        host-side table); admitting over an occupied slot silently
        replaces the tenant."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        bucket = self.validate_request(
            prompt.shape[0], max_new_tokens, temperature, top_k, top_p)
        if not 0 <= slot < self.max_slots:
            raise ValueError(
                f"slot must be in [0, {self.max_slots}), got {slot}")
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :prompt.shape[0]] = prompt
        one = self._prefill(self._variables, jnp.asarray(padded),
                            np.int32(prompt.shape[0]))
        self.cache, self.state = self._admit(
            self.cache, self.state, np.int32(slot), one,
            np.int32(prompt[-1]), np.int32(max_new_tokens),
            np.float32(temperature), np.int32(top_k or 0),
            np.float32(0.0 if top_p is None else top_p),
            np.int32(-1 if eos_id is None else eos_id),
            np.uint32(seed))

    def step(self) -> Tuple[np.ndarray, np.ndarray]:
        """Decode one token for every slot.

        Returns ``(tokens, finished)`` — numpy, length ``max_slots``.
        ``finished[i]`` latches when slot i produced its eos or spent
        its budget this step (the slot is already marked free on
        device; the caller should :meth:`release` it to zero the row).
        The single per-step host sync lives here.
        """
        self.cache, self.state, toks, finished = self._step(
            self._variables, self.cache, self.state)
        return np.asarray(toks), np.asarray(finished)

    def release(self, slot: int) -> None:
        """Zero and free ``slot``."""
        self.cache, self.state = self._release(
            self.cache, self.state, np.int32(slot))

    def warmup(self) -> None:
        """Trace every executable up front: one dummy tenant per
        prompt bucket through admit → step → release.  After this, a
        steady-state soak over any request mix triggers zero retraces
        (and the retrace guards would raise if it did)."""
        for bucket in self.prompt_buckets:
            self.admit(0, np.zeros((bucket,), np.int32),
                       max_new_tokens=1)
            self.step()
            self.release(0)

    @property
    def trace_counts(self) -> dict:
        """Observed traces per executable (diagnostics / tests)."""
        return {
            "decode_step": self._step.trace_count,
            "prefill": self._prefill.trace_count,
            "admit": self._admit.trace_count,
            "release": self._release.trace_count,
        }


# --------------------------------------------------------------------- #
# paged engine — token-granular serving datapath
# --------------------------------------------------------------------- #
@dataclasses.dataclass
class _Tenant:
    """Host-side record of one slot's tenant (the device never sees
    prompts or block lists — only the tables/cursors built from them)."""

    prompt: np.ndarray          # full prompt tokens
    fed: int = 0                # prompt tokens already fed (chunked)
    cursor: int = 0             # tokens written into the cache
    blocks: List[int] = dataclasses.field(default_factory=list)
    seq: int = 0                # admission order (LIFO preemption key)


class PagedEngine:
    """Continuous-batching decode over a PAGED KV-cache pool.

    The dense :class:`Engine` reserves a ``max_slots × max_seq_len``
    K/V slab and admits via bucket-padded whole-prompt prefill.  This
    engine instead:

    - stores K/V in fixed-size **pages** of a pool sized in TOKENS
      (``pool_tokens``), shared across tenants through per-slot block
      tables (:class:`~apex_tpu.serving.cache.BlockAllocator`) — HBM
      footprint and per-step attention bytes scale with live tokens,
      so the same budget holds several times the dense slot count;
    - runs **chunked prefill inside the decode step**: prompts are
      split into ``prefill_chunk``-token pieces that ride the regular
      step beside decoding tenants (ONE fused mixed prefill+decode
      executable), so a long prompt can never head-of-line-block
      co-tenants and per-step latency is bounded by the chunk;
    - the whole ragged batch is ONE model application — per-row
      cursors/block tables in the cache collection replace the dense
      engine's per-slot vmap, and attention goes through
      :func:`apex_tpu.ops.paged_attention`.

    Exactly FOUR executables for the process lifetime, each under an
    exact :func:`~apex_tpu.utils.tracecheck.retrace_guard` budget of 1:
    ``decode_step`` (width-1 step), ``prefill_step`` (the width-
    ``prefill_chunk`` mixed step — the dense engine's per-bucket
    prefills collapse to this one shape), ``admit`` (slot-state
    scatter; no cache writes — pages are overwritten before they become
    visible, so admission and release never touch the pool), and
    ``release``.

    Block exhaustion preempts the YOUNGEST tenant (its blocks are
    freed, its slot state cleared) and reports it in
    ``StepOutput.preempted``; the scheduler requeues it to continue
    from its streamed prefix (PR 4's fault-recovery machinery).

    ``block_size=0`` consults the
    :mod:`~apex_tpu.ops.autotune` table (op ``"paged_attention"``,
    keyed on head_dim/dtype) and falls back to 16.  ``pool_tokens``
    defaults to ``max_slots × max_seq_len`` — the dense slab's
    footprint; shrink it to trade capacity for memory (admission
    token-gates and preemption backstops the overcommit).
    """

    paged = True

    def __init__(self, model, params, *, max_slots: int = 4,
                 block_size: int = 0,
                 pool_tokens: Optional[int] = None,
                 prefill_chunk: int = 32,
                 admit_headroom: Optional[int] = None):
        cfg = getattr(model, "cfg", None)
        if cfg is None or not hasattr(cfg, "max_seq_len"):
            raise ValueError(
                "PagedEngine needs a model with a .cfg carrying "
                "max_seq_len and vocab_size (GPTModel / LlamaModel "
                "contract)")
        if not getattr(cfg, "causal", True):
            raise ValueError("PagedEngine requires a causal model "
                             "(decode=True contract)")
        if getattr(cfg, "sliding_window", None) is not None:
            raise ValueError(
                "PagedEngine does not support sliding-window models — "
                "the paged pool already bounds decode memory to live "
                "tokens; serve with sliding_window=None")
        if max_slots < 1:
            raise ValueError(f"max_slots must be >= 1, got {max_slots}")
        if prefill_chunk < 1:
            raise ValueError(
                f"prefill_chunk must be >= 1, got {prefill_chunk}")
        self.model = model
        self.max_slots = int(max_slots)
        self.max_seq_len = int(cfg.max_seq_len)
        self.vocab_size = int(cfg.vocab_size)
        self._chunk = int(prefill_chunk)
        if block_size == 0:
            from apex_tpu.ops import autotune
            block_size = autotune.cached_block_rows(
                "paged_attention", int(cfg.head_dim),
                str(jnp.dtype(cfg.dtype))) or 16
        if block_size < 1:
            raise ValueError(
                f"block_size must be >= 1, got {block_size}")
        self.block_size = int(block_size)
        if pool_tokens is None:
            pool_tokens = self.max_slots * self.max_seq_len
        # the pool bounds the largest ADMISSIBLE request
        # (validate_request rejects anything that could never fit
        # alone); the floor here only covers the warmup tenant
        min_tokens = min(self._chunk + 3, self.max_seq_len)
        if pool_tokens < min_tokens:
            raise ValueError(
                f"pool_tokens ({pool_tokens}) must cover at least the "
                f"warmup tenant ({min_tokens} tokens)")
        num_blocks = slot_cache.blocks_for(pool_tokens,
                                           self.block_size) + 1
        self._alloc = slot_cache.BlockAllocator(num_blocks,
                                                self.block_size)
        self._headroom = (2 * self.block_size if admit_headroom is None
                          else int(admit_headroom))
        self._variables = dict(params)
        if "cache" in self._variables:
            raise ValueError(
                "params must not carry a 'cache' collection — the "
                "engine owns the cache pool")
        # the paged twin: same parameters, paged cache layout — the
        # layout is part of the module hash, so its executables can
        # never collide with a dense model's in any jit cache
        self._paged_model = type(model)(cfg=dataclasses.replace(
            cfg, kv_cache="paged", kv_block_size=self.block_size,
            kv_pool_blocks=num_blocks))
        shapes = cache_shapes(self._paged_model, self.max_slots)
        self.cache = jax.tree.map(
            lambda s: jnp.zeros(s.shape, s.dtype), shapes)
        self.state = slot_cache.init_slot_state(self.max_slots)
        mb = slot_cache.blocks_for(self.max_seq_len, self.block_size)
        self._tables = np.zeros((self.max_slots, mb), np.int32)
        self._cursors = np.zeros((self.max_slots,), np.int32)
        self._tenants: List[Optional[_Tenant]] = [None] * self.max_slots
        self._admit_seq = 0
        self._build()

    # ------------------------------------------------------------- jits
    def _build(self) -> None:
        model = self._paged_model
        vocab = self.vocab_size

        def step_fn(variables, cache, state, tables, cursors, feed,
                    n_tokens, is_prefill, emit):
            # the host-authoritative block tables / cursors overwrite
            # their cache leaves (the model never advances them)
            cache = slot_cache.set_paged_leaves(cache, tables, cursors)
            # one ragged-batch application: prefilling rows feed their
            # chunk, decoding rows their last sampled token (+ pad)
            tok_ids = jnp.zeros_like(feed).at[:, 0].set(state.tok)
            ids = jnp.where(is_prefill[:, None], feed, tok_ids)
            logits, cache = apply_decode(model, variables, cache, ids)
            last = jnp.take_along_axis(
                logits, (n_tokens - 1)[:, None, None], axis=1)[:, 0]
            split = jax.vmap(jax.random.split)(state.rng)
            nxt = sample_dynamic(last, split[:, 0], state.temperature,
                                 state.top_k, state.top_p, vocab)
            # emission is gated on the host plan: a mid-prefill tenant
            # computes but emits nothing, and its rng does NOT advance
            # — the k-th produced token always uses the k-th split, so
            # sampled chains are invariant to chunking
            emit = emit & state.active
            produced = state.produced + emit.astype(jnp.int32)
            hit_budget = produced >= state.budget
            hit_eos = (state.eos_id >= 0) & (nxt == state.eos_id)
            finished = emit & (hit_budget | hit_eos)
            state = state._replace(
                tok=jnp.where(emit, nxt, state.tok),
                produced=produced,
                active=state.active & ~finished,
                rng=jnp.where(emit[:, None], split[:, 1], state.rng))
            return cache, state, nxt, finished

        def admit(state, slot, tok, budget, temperature, top_k, top_p,
                  eos_id, seed):
            return slot_cache.admit_slot(
                state, slot, tok, budget, temperature, top_k, top_p,
                eos_id, seed)

        def release(state, slot):
            return slot_cache.release_slot(state, slot)

        # exact budgets: decode/admit/release = 1 and the dense
        # engine's per-bucket prefills collapse to ONE mixed-step
        # shape — any excess trace raises RetraceError
        self._decode = tracecheck.retrace_guard(
            step_fn, max_traces=1, name="serving.decode_step",
            donate_argnums=(1, 2))
        self._prefill = tracecheck.retrace_guard(
            step_fn, max_traces=1, name="serving.prefill_step",
            donate_argnums=(1, 2))
        self._admit = tracecheck.retrace_guard(
            admit, max_traces=1, name="serving.admit",
            donate_argnums=(0,))
        self._release = tracecheck.retrace_guard(
            release, max_traces=1, name="serving.release",
            donate_argnums=(0,))

    # ------------------------------------------------------------- host
    def validate_request(self, prompt_len: int, max_new_tokens: int,
                         temperature: float = 0.0,
                         top_k: Optional[int] = None,
                         top_p: Optional[float] = None) -> None:
        """Static admission checks (no buckets: chunked prefill admits
        any prompt length that fits the cache and the pool)."""
        if prompt_len < 1:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if prompt_len + max_new_tokens > self.max_seq_len:
            raise ValueError(
                f"prompt_len ({prompt_len}) + max_new_tokens "
                f"({max_new_tokens}) exceeds max_seq_len "
                f"({self.max_seq_len})")
        need = slot_cache.blocks_for(prompt_len + max_new_tokens,
                                     self.block_size)
        if need > self._alloc.blocks_total:
            raise ValueError(
                f"request needs {need} pages "
                f"({prompt_len}+{max_new_tokens} tokens at "
                f"block_size={self.block_size}) but the whole pool "
                f"holds {self._alloc.blocks_total} — raise pool_tokens")
        _check_sampling(self.vocab_size, top_k, top_p)
        del temperature

    def can_admit(self, prompt_len: int, max_new_tokens: int) -> bool:
        """Token-budget admission gate: free pages must cover the
        prompt plus reserved decode headroom (preemption backstops the
        deliberate overcommit beyond the headroom)."""
        need = slot_cache.blocks_for(
            prompt_len + min(int(max_new_tokens), self._headroom),
            self.block_size)
        return self._alloc.blocks_free >= need

    def admit(self, slot: int, prompt, *, max_new_tokens: int,
              temperature: float = 0.0, top_k: Optional[int] = None,
              top_p: Optional[float] = None,
              eos_id: Optional[int] = None, seed: int = 0) -> None:
        """Install one request into a free slot.  NO prefill happens
        here — the prompt rides the next steps as chunks; no pages are
        allocated either (the step loop extends tables just ahead of
        the tokens it writes)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.validate_request(prompt.shape[0], max_new_tokens,
                              temperature, top_k, top_p)
        if not 0 <= slot < self.max_slots:
            raise ValueError(
                f"slot must be in [0, {self.max_slots}), got {slot}")
        if self._tenants[slot] is not None:
            raise ValueError(f"slot {slot} is occupied (paged "
                             "admission never silently replaces — the "
                             "tenant owns pool pages)")
        self._admit_seq += 1
        self._tenants[slot] = _Tenant(prompt=prompt,
                                      seq=self._admit_seq)
        self.state = self._admit(
            self.state, np.int32(slot), np.int32(prompt[-1]),
            np.int32(max_new_tokens), np.float32(temperature),
            np.int32(top_k or 0),
            np.float32(0.0 if top_p is None else top_p),
            np.int32(-1 if eos_id is None else eos_id),
            np.uint32(seed))

    def _youngest(self) -> int:
        live = [s for s, t in enumerate(self._tenants) if t is not None]
        return max(live, key=lambda s: self._tenants[s].seq)

    def _free_tenant(self, slot: int) -> None:
        """Return a tenant's pages and clear its host/device state.
        The pool itself is untouched: freed pages are garbage until
        their next owner overwrites them, and the position mask keeps
        garbage unreachable."""
        rec = self._tenants[slot]
        if rec is not None:
            self._alloc.free(rec.blocks)
            self._tables[slot] = 0
            self._cursors[slot] = 0
            self._tenants[slot] = None
        self.state = self._release(self.state, np.int32(slot))

    def _extend(self, slot: int, n: int,
                preempted: List[int]) -> None:
        """Grow ``slot``'s block table to cover its next ``n`` real
        tokens, preempting the youngest tenant on exhaustion.  A
        request is admission-validated to fit the whole pool alone, so
        the loop terminates: in the worst case everyone else (and
        finally the needy slot itself) is preempted."""
        rec = self._tenants[slot]
        while rec is not None:
            # capped at the table width: a finished-but-unreleased
            # tenant stepped past max_seq_len (possible in raw engine
            # drivers; the scheduler releases at the finish boundary)
            # wraps within its last page instead of growing the table
            need = min(slot_cache.blocks_for(rec.cursor + n,
                                             self.block_size),
                       self._tables.shape[1]) - len(rec.blocks)
            if need <= 0:
                return
            try:
                got = self._alloc.alloc(need)
            except slot_cache.BlockExhausted:
                victim = self._youngest()
                self._free_tenant(victim)
                preempted.append(victim)
                if victim == slot:
                    return
                continue
            start = len(rec.blocks)
            self._tables[slot, start:start + len(got)] = got
            rec.blocks.extend(got)

    def step(self) -> StepOutput:
        """One fused mixed prefill+decode step over every slot.

        Prefilling tenants consume their next prompt chunk (emitting a
        token only on the final chunk — that token IS the first
        generated one, sampled straight from the prefill logits);
        decoding tenants advance one token.  Inactive slots compute
        garbage into the null page.  The single per-step host sync
        lives here.
        """
        w = 1
        for rec in self._tenants:
            if rec is not None and rec.fed < rec.prompt.size:
                w = self._chunk
                break
        any_prefill = w == self._chunk
        feed = np.zeros((self.max_slots, w), np.int32)
        n_tokens = np.ones((self.max_slots,), np.int32)
        is_prefill = np.zeros((self.max_slots,), bool)
        emit = np.zeros((self.max_slots,), bool)
        preempted: List[int] = []
        for slot in range(self.max_slots):
            rec = self._tenants[slot]
            if rec is None:
                continue
            if rec.fed < rec.prompt.size:
                n = min(w, rec.prompt.size - rec.fed)
                feed[slot, :n] = rec.prompt[rec.fed:rec.fed + n]
                n_tokens[slot] = n
                is_prefill[slot] = True
                emit[slot] = rec.fed + n >= rec.prompt.size
            else:
                emit[slot] = True
            self._extend(slot, int(n_tokens[slot]), preempted)
        for slot in preempted:
            feed[slot] = 0
            n_tokens[slot] = 1
            is_prefill[slot] = False
            emit[slot] = False
        runner = self._prefill if any_prefill else self._decode
        self.cache, self.state, toks, finished = runner(
            self._variables, self.cache, self.state, self._tables,
            self._cursors, feed, n_tokens, is_prefill, emit)
        for slot in range(self.max_slots):
            rec = self._tenants[slot]
            if rec is None:
                continue
            n = int(n_tokens[slot])
            if is_prefill[slot]:
                rec.fed += n
            rec.cursor += n
            self._cursors[slot] = rec.cursor
        return StepOutput(np.asarray(toks), np.asarray(finished),
                          emit, tuple(preempted))

    def release(self, slot: int) -> None:
        """Free ``slot``: pages back to the pool, state cleared."""
        self._free_tenant(slot)

    def warmup(self) -> None:
        """Trace all four executables: one dummy tenant whose prompt
        spans a full chunk plus a remainder (mixed prefill step), then
        one pure decode step.  Steady state over ANY request mix is
        retrace-free afterwards — and guarded.

        The prompt clamps to ``max_seq_len - 2`` for small-context
        models (chunk width larger than the context is legal: real
        chunks are capped by the prompt; the executable widths traced
        are the same either way)."""
        plen = min(self._chunk + 1, self.max_seq_len - 2)
        self.admit(0, np.zeros((plen,), np.int32), max_new_tokens=2)
        while self._tenants[0] is not None:
            out = self.step()
            if bool(out.finished[0]):
                break
        self.release(0)

    # ------------------------------------------------------------ gauges
    @property
    def blocks_total(self) -> int:
        return self._alloc.blocks_total

    @property
    def blocks_free(self) -> int:
        return self._alloc.blocks_free

    @property
    def blocks_in_use(self) -> int:
        return self._alloc.blocks_in_use

    @property
    def pool_tokens(self) -> int:
        return self._alloc.tokens_total

    @property
    def live_tokens(self) -> int:
        """Tokens currently written for live tenants (host-side view)
        — a finer utilization numerator than whole pages; surfaced in
        ``InferenceServer.health()``/metrics so a fleet router can see
        real load, not just page-granular occupancy."""
        return int(sum(t.cursor for t in self._tenants
                       if t is not None))

    @property
    def trace_counts(self) -> dict:
        """Observed traces per executable (diagnostics / tests)."""
        return {
            "decode_step": self._decode.trace_count,
            "prefill_step": self._prefill.trace_count,
            "admit": self._admit.trace_count,
            "release": self._release.trace_count,
        }
