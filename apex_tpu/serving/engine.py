"""Continuous-batching decode engine over the slotted KV-cache pool.

One model, ``max_slots`` concurrent tenants, four compiled
executables for the engine's whole lifetime:

- ``decode_step``  — ONE trace: vmap over slots of the model's
  ``decode=True`` single-token path, followed by branchless per-slot
  sampling whose parameters (temperature / top_k / top_p / eos /
  budget) are device arrays in
  :class:`~apex_tpu.serving.cache.SlotState` — mixed sampling configs
  (nucleus sampling included) share the executable.
- ``prefill``      — one trace PER PROMPT BUCKET: the prompt, right-
  padded to its bucket length, runs through the shared chunked-prefill
  path (``apex_tpu.models.generate.prefill_tokens``) into a fresh
  per-slot cache, whose cursors are then rewound to ``true_len - 1``
  so the first decode step re-feeds the last real prompt token (pad
  K/V beyond the cursor is masked, then overwritten — the padded
  prefill computes exactly the unpadded function).
- ``admit``        — ONE trace: scatter the prefilled slot cache +
  tenant params into the pool at a traced slot index.
- ``release``      — ONE trace: zero the slot row, clear the active bit.

Every executable is wrapped in
:func:`apex_tpu.utils.tracecheck.retrace_guard` with exactly that
budget, so a shape or signature leak raises ``RetraceError`` instead of
silently recompiling per request — the engine *enforces* its own
zero-retrace steady state rather than merely promising it.

Greedy decoding through the engine is token-identical to
``generate()``: same prefill path, same fp32 argmax; the refeed step
recomputes the last prompt position's K/V bit-compatibly up to
blocked-vs-einsum accumulation order (≈1e-7 — far below argmax
resolution on real logits).

The step boundary is the only device→host sync: ``step()`` returns the
per-slot tokens and finished flags as numpy so the scheduler can evict
and refill.  Inactive slots still compute (static shapes — no dynamic
batch); their outputs are ignored on the host and their slot rows are
fully rebuilt at the next admission.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from apex_tpu.models.generate import (
    apply_decode,
    cache_shapes,
    prefill_tokens,
)
from apex_tpu.serving import cache as slot_cache
from apex_tpu.utils import tracecheck

__all__ = ["Engine", "sample_dynamic", "DEFAULT_BUCKETS"]

DEFAULT_BUCKETS: Tuple[int, ...] = (32, 128, 512)


def sample_dynamic(logits, keys, temperature, top_k, top_p,
                   vocab_size: int):
    """Branchless per-row sampling with DEVICE-ARRAY parameters.

    ``logits`` (rows, vocab); ``keys`` (rows, 2) uint32; ``temperature``
    / ``top_k`` / ``top_p`` (rows,).  Per row: fp32 argmax when
    ``temperature <= 0`` else top-k- and/or nucleus-truncated
    categorical at ``logits/temperature`` (``top_k == 0`` and
    ``top_p <= 0`` / ``>= 1`` disable their filters — a disabled
    filter is an exact no-op, not an epsilon approximation).  The math
    mirrors ``generate``'s static
    :func:`~apex_tpu.models.generate.sample_logits` — kth-largest /
    nucleus threshold on the scaled logits, ``-1e30`` mask, top-k
    before top-p (the HF warper order) — but every parameter is
    traced, so one executable serves any mix.  The nucleus pass reuses
    the top-k sort (the post-mask order is the pre-mask order with the
    masked tail replaced), so mixed top-p traffic costs no second
    O(V·logV) sort.
    """
    logits = logits.astype(jnp.float32)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    safe_t = jnp.maximum(temperature, 1e-6)[:, None]
    scaled = logits / safe_t
    k = jnp.where(top_k > 0, top_k, vocab_size)          # (rows,)
    ordered = jnp.sort(scaled, axis=-1)                  # ascending
    kth = jnp.take_along_axis(
        ordered, (vocab_size - k)[:, None], axis=-1)     # k-th largest
    scaled = jnp.where(scaled < kth, -1e30, scaled)
    # nucleus filter over the top-k-masked distribution, sort reused:
    # descending masked order = reversed `ordered` with the SAME
    # `< kth` criterion applied that masked `scaled` — value-based,
    # not position-based, so k-th-boundary ties survive in both or
    # neither (keeps engine/generate parity in tie cases)
    p_on = (top_p > 0.0) & (top_p < 1.0)                 # (rows,)
    rev = ordered[:, ::-1]
    desc = jnp.where(rev < kth, -1e30, rev)
    probs = jax.nn.softmax(desc, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep = cum - probs < jnp.where(p_on, top_p, 1.0)[:, None]
    thresh = jnp.min(jnp.where(keep, desc, jnp.inf), axis=-1,
                     keepdims=True)
    scaled = jnp.where(p_on[:, None] & (scaled < thresh), -1e30,
                       scaled)
    sampled = jax.vmap(jax.random.categorical)(keys, scaled)
    sampled = sampled.astype(jnp.int32)
    return jnp.where(temperature > 0.0, sampled, greedy)


class Engine:
    """Multi-tenant KV-cached decode over one model.

    Host API (single-threaded — callers serialize; the
    ``apex_tpu.serving.api`` server owns one engine per worker thread):

    - ``admit(slot, prompt, *, max_new_tokens, ...)`` — prefill +
      install one request into a free slot.
    - ``step()`` — decode every slot one token; returns
      ``(tokens, finished)`` numpy arrays of length ``max_slots``
      (only slots the caller knows to be occupied carry meaning).
    - ``release(slot)`` — zero + free a slot.
    - ``warmup()`` — trace all executables (one dummy request per
      prompt bucket) so steady state is retrace-free from request one.

    ``prompt_buckets`` quantizes prompt lengths: a prompt compiles
    nothing new as long as its length fits an existing bucket, so the
    compile count is ``len(buckets) + 3`` for the process lifetime.
    """

    def __init__(self, model, params, *, max_slots: int = 4,
                 prompt_buckets: Sequence[int] = DEFAULT_BUCKETS,
                 prefill_chunk: int = 0):
        cfg = getattr(model, "cfg", None)
        if cfg is None or not hasattr(cfg, "max_seq_len"):
            raise ValueError(
                "Engine needs a model with a .cfg carrying max_seq_len "
                "and vocab_size (GPTModel / LlamaModel contract)")
        if not getattr(cfg, "causal", True):
            raise ValueError("Engine requires a causal model "
                             "(decode=True contract)")
        if max_slots < 1:
            raise ValueError(f"max_slots must be >= 1, got {max_slots}")
        if prefill_chunk < 0:
            raise ValueError(
                f"prefill_chunk must be >= 0, got {prefill_chunk}")
        self.model = model
        self.max_slots = int(max_slots)
        self.max_seq_len = int(cfg.max_seq_len)
        self.vocab_size = int(cfg.vocab_size)
        buckets = sorted({int(b) for b in prompt_buckets})
        if not buckets or buckets[0] < 1:
            raise ValueError(
                f"prompt_buckets must be positive, got {prompt_buckets}")
        if buckets[-1] >= self.max_seq_len:
            # == is useless too: a max_seq_len prompt has no cache room
            # left to generate even one token
            raise ValueError(
                f"largest prompt bucket ({buckets[-1]}) must be < "
                f"max_seq_len ({self.max_seq_len}) — the cache must "
                f"hold prompt + generated tokens")
        self.prompt_buckets = tuple(buckets)
        self._prefill_chunk = int(prefill_chunk)
        self._variables = dict(params)
        if "cache" in self._variables:
            raise ValueError(
                "params must not carry a 'cache' collection — the "
                "engine owns the cache pool")
        self._shapes = cache_shapes(model, 1)
        slot_cache.validate_cache_tree(self._shapes)
        self.cache = slot_cache.stacked_zeros(self._shapes, max_slots)
        self.state = slot_cache.init_slot_state(max_slots)
        self._build()

    # ------------------------------------------------------------- jits
    def _build(self) -> None:
        model = self.model
        shapes = self._shapes
        vocab = self.vocab_size
        prefill_chunk = self._prefill_chunk

        def decode_step(variables, pool, state):
            # one token for every slot: vmap of the b=1 decode path
            # over the slot axis — per-slot cache cursors make each
            # row attend at its own position (the scalar cache_index
            # of the plain batched path advances in lockstep and
            # cannot express ragged tenants)
            def one_slot(cache_i, tok_i):
                logits, cache_o = apply_decode(
                    model, variables, cache_i, tok_i[None, None])
                return logits[0, -1], cache_o

            logits, pool = jax.vmap(one_slot)(pool, state.tok)
            split = jax.vmap(jax.random.split)(state.rng)
            nxt = sample_dynamic(logits, split[:, 0],
                                 state.temperature, state.top_k,
                                 state.top_p, vocab)
            produced = state.produced + state.active.astype(jnp.int32)
            hit_budget = produced >= state.budget
            hit_eos = (state.eos_id >= 0) & (nxt == state.eos_id)
            finished = state.active & (hit_budget | hit_eos)
            state = state._replace(
                tok=jnp.where(state.active, nxt, state.tok),
                produced=produced,
                active=state.active & ~finished,
                rng=split[:, 1])
            return pool, state, nxt, finished

        def prefill(variables, prompt, true_len):
            # prompt: (1, bucket_len) right-padded; true_len: traced
            fresh = slot_cache.zeros_from_shapes(shapes)
            _last, filled = prefill_tokens(
                model, variables, fresh, prompt, prefill_chunk)
            return slot_cache.rewind_index_leaves(filled, true_len - 1)

        def admit(pool, state, slot, one, tok, budget, temperature,
                  top_k, top_p, eos_id, seed):
            pool = slot_cache.write_slot(pool, slot, one)
            state = slot_cache.admit_slot(
                state, slot, tok, budget, temperature, top_k, top_p,
                eos_id, seed)
            return pool, state

        def release(pool, state, slot):
            return (slot_cache.reset_slot(pool, slot),
                    slot_cache.release_slot(state, slot))

        # exact retrace budgets: ANY excess trace raises RetraceError —
        # the engine's zero-retrace steady state is enforced, not
        # aspirational.  The pool/state threads through with donation
        # (two live copies of max_slots × max_seq_len K/V would double
        # the engine's HBM footprint).
        self._step = tracecheck.retrace_guard(
            decode_step, max_traces=1, name="serving.decode_step",
            donate_argnums=(1, 2))
        self._prefill = tracecheck.retrace_guard(
            prefill, max_traces=len(self.prompt_buckets),
            name="serving.prefill")
        self._admit = tracecheck.retrace_guard(
            admit, max_traces=1, name="serving.admit",
            donate_argnums=(0, 1))
        self._release = tracecheck.retrace_guard(
            release, max_traces=1, name="serving.release",
            donate_argnums=(0, 1))

    # ------------------------------------------------------------- host
    def bucket_for(self, prompt_len: int) -> int:
        """Smallest configured bucket holding ``prompt_len`` tokens."""
        for b in self.prompt_buckets:
            if prompt_len <= b:
                return b
        raise ValueError(
            f"prompt of {prompt_len} tokens exceeds the largest "
            f"prompt bucket ({self.prompt_buckets[-1]}); configure "
            f"larger prompt_buckets")

    def validate_request(self, prompt_len: int, max_new_tokens: int,
                         temperature: float = 0.0,
                         top_k: Optional[int] = None,
                         top_p: Optional[float] = None) -> int:
        """Static admission checks; returns the prompt's bucket."""
        if prompt_len < 1:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        bucket = self.bucket_for(prompt_len)
        if prompt_len + max_new_tokens > self.max_seq_len:
            raise ValueError(
                f"prompt_len ({prompt_len}) + max_new_tokens "
                f"({max_new_tokens}) exceeds max_seq_len "
                f"({self.max_seq_len})")
        if top_k is not None and top_k != 0 \
                and not 1 <= top_k <= self.vocab_size:
            raise ValueError(
                f"top_k must be in [1, vocab_size={self.vocab_size}] "
                f"(or 0/None to disable), got {top_k}")
        if top_p is not None and not 0.0 < top_p <= 1.0:
            raise ValueError(
                f"top_p must be in (0, 1] (or None to disable), "
                f"got {top_p}")
        del temperature      # any float is admissible (<=0 -> greedy)
        return bucket

    def admit(self, slot: int, prompt, *, max_new_tokens: int,
              temperature: float = 0.0, top_k: Optional[int] = None,
              top_p: Optional[float] = None,
              eos_id: Optional[int] = None, seed: int = 0) -> None:
        """Prefill ``prompt`` (1-D int tokens) and install it in
        ``slot``.  The caller owns slot accounting (the scheduler's
        host-side table); admitting over an occupied slot silently
        replaces the tenant."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        bucket = self.validate_request(
            prompt.shape[0], max_new_tokens, temperature, top_k, top_p)
        if not 0 <= slot < self.max_slots:
            raise ValueError(
                f"slot must be in [0, {self.max_slots}), got {slot}")
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :prompt.shape[0]] = prompt
        one = self._prefill(self._variables, jnp.asarray(padded),
                            np.int32(prompt.shape[0]))
        self.cache, self.state = self._admit(
            self.cache, self.state, np.int32(slot), one,
            np.int32(prompt[-1]), np.int32(max_new_tokens),
            np.float32(temperature), np.int32(top_k or 0),
            np.float32(0.0 if top_p is None else top_p),
            np.int32(-1 if eos_id is None else eos_id),
            np.uint32(seed))

    def step(self) -> Tuple[np.ndarray, np.ndarray]:
        """Decode one token for every slot.

        Returns ``(tokens, finished)`` — numpy, length ``max_slots``.
        ``finished[i]`` latches when slot i produced its eos or spent
        its budget this step (the slot is already marked free on
        device; the caller should :meth:`release` it to zero the row).
        The single per-step host sync lives here.
        """
        self.cache, self.state, toks, finished = self._step(
            self._variables, self.cache, self.state)
        return np.asarray(toks), np.asarray(finished)

    def release(self, slot: int) -> None:
        """Zero and free ``slot``."""
        self.cache, self.state = self._release(
            self.cache, self.state, np.int32(slot))

    def warmup(self) -> None:
        """Trace every executable up front: one dummy tenant per
        prompt bucket through admit → step → release.  After this, a
        steady-state soak over any request mix triggers zero retraces
        (and the retrace guards would raise if it did)."""
        for bucket in self.prompt_buckets:
            self.admit(0, np.zeros((bucket,), np.int32),
                       max_new_tokens=1)
            self.step()
            self.release(0)

    @property
    def trace_counts(self) -> dict:
        """Observed traces per executable (diagnostics / tests)."""
        return {
            "decode_step": self._step.trace_count,
            "prefill": self._prefill.trace_count,
            "admit": self._admit.trace_count,
            "release": self._release.trace_count,
        }
