"""Continuous-batching decode engine over the slotted KV-cache pool.

One model, ``max_slots`` concurrent tenants, four compiled
executables for the engine's whole lifetime:

- ``decode_step``  — ONE trace: vmap over slots of the model's
  ``decode=True`` single-token path, followed by branchless per-slot
  sampling whose parameters (temperature / top_k / top_p / eos /
  budget) are device arrays in
  :class:`~apex_tpu.serving.cache.SlotState` — mixed sampling configs
  (nucleus sampling included) share the executable.  The sampling
  tail is the FUSED epilogue of :mod:`apex_tpu.ops.fused_sampling`
  (ISSUE 14): one Pallas pass over the ``(slots, vocab)`` logits on
  TPU, the sort-based reference elsewhere — token-identical either
  way, and the reference now ``lax.cond``-skips its sort when no
  admitted row enables top-k/top-p.
- ``prefill``      — one trace PER PROMPT BUCKET: the prompt, right-
  padded to its bucket length, runs through the shared chunked-prefill
  path (``apex_tpu.models.generate.prefill_tokens``) into a fresh
  per-slot cache, whose cursors are then rewound to ``true_len - 1``
  so the first decode step re-feeds the last real prompt token (pad
  K/V beyond the cursor is masked, then overwritten — the padded
  prefill computes exactly the unpadded function).
- ``admit``        — ONE trace: scatter the prefilled slot cache +
  tenant params into the pool at a traced slot index.
- ``release``      — ONE trace: zero the slot row, clear the active bit.

Every executable is wrapped in
:func:`apex_tpu.utils.tracecheck.retrace_guard` with exactly that
budget, so a shape or signature leak raises ``RetraceError`` instead of
silently recompiling per request — the engine *enforces* its own
zero-retrace steady state rather than merely promising it.

Greedy decoding through the engine is token-identical to
``generate()``: same prefill path, same fp32 argmax; the refeed step
recomputes the last prompt position's K/V bit-compatibly up to
blocked-vs-einsum accumulation order (≈1e-7 — far below argmax
resolution on real logits).

The step boundary is the only device→host sync: ``step()`` returns the
per-slot tokens and finished flags as numpy so the scheduler can evict
and refill.  Inactive slots still compute (static shapes — no dynamic
batch); their outputs are ignored on the host and their slot rows are
fully rebuilt at the next admission.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from apex_tpu.core.mesh import TENSOR_AXIS
from apex_tpu.models.generate import (
    apply_decode,
    cache_shapes,
    prefill_tokens,
)
from apex_tpu.ops.fused_sampling import fused_sample, \
    fused_sample_reference
from apex_tpu.ops.paged_attention import tp_head_shards
from apex_tpu.serving import cache as slot_cache
from apex_tpu.utils import tracecheck
from apex_tpu.utils.metrics import counters

__all__ = ["Engine", "PagedEngine", "StepOutput", "sample_dynamic",
           "prompt_lookup_draft", "DEFAULT_BUCKETS", "tp_mesh"]


def tp_mesh(tp: int, devices=None):
    """A one-replica tensor-parallel serving mesh: ``tp`` chips on the
    ``tensor`` axis (every other axis 1).

    ``devices`` defaults to the first ``tp`` of ``jax.devices()``; a
    fleet packing N replicas × M chips onto one host passes each
    replica its own device slice (``jax.devices()[i*M:(i+1)*M]``).
    Never touches the library-global mesh (``set_current=False``) —
    replicas own disjoint meshes, and serving must not hijack the
    training topology."""
    from apex_tpu.core.mesh import initialize_mesh

    tp = int(tp)
    if tp < 1:
        raise ValueError(f"tp must be >= 1, got {tp}")
    devices = list(jax.devices() if devices is None else devices)
    if len(devices) < tp:
        raise ValueError(
            f"tp={tp} needs {tp} devices, only {len(devices)} "
            f"available")
    return initialize_mesh(tensor_model_parallel_size=tp,
                           devices=devices[:tp], set_current=False)


def _shard_params_for_tp(variables, mesh):
    """Place one replica's weights on its mesh: flax ``Partitioned``
    boxes shard per their annotations (the GSPMD tensor-parallel
    layers mark qkv/out/mlp kernels over the ``tensor`` axis — this is
    where a model too big for one chip actually fits), axes absent
    from the mesh are dropped, a dim the axis size doesn't divide
    falls back to replicated, and plain (unboxed) leaves replicate."""
    from flax.core import meta

    repl = jax.sharding.NamedSharding(mesh,
                                      jax.sharding.PartitionSpec())
    axes = set(mesh.axis_names)

    def place(x):
        if isinstance(x, meta.Partitioned):
            names = tuple(n if n in axes else None for n in x.names)
            sh = jax.sharding.NamedSharding(
                mesh, jax.sharding.PartitionSpec(*names))
            try:
                return x.replace_boxed(jax.device_put(x.unbox(), sh))
            except ValueError:
                return x.replace_boxed(jax.device_put(x.unbox(),
                                                      repl))
        return jax.device_put(x, repl)

    return jax.tree.map(place, variables,
                        is_leaf=lambda x: isinstance(x,
                                                     meta.Partitioned))


def _pin_replicated(tree, mesh):
    """In-trace: constrain every leaf of ``tree`` replicated over
    ``mesh`` — the SlotState / sampling outputs' fixed point (see
    ``serving.cache.constrain_paged_cache`` for why out-shardings
    must be pinned under retrace budgets of 1)."""
    repl = jax.sharding.NamedSharding(mesh,
                                      jax.sharding.PartitionSpec())
    return jax.tree.map(
        lambda x: jax.lax.with_sharding_constraint(x, repl), tree)


DEFAULT_BUCKETS: Tuple[int, ...] = (32, 128, 512)


class StepOutput(NamedTuple):
    """One engine step's host-visible result.

    ``tokens`` is ``(max_slots, width)`` — a speculative verify step
    can emit several tokens per slot per step; ``counts[i]`` says how
    many of row i's tokens are REAL this step (``tokens[i, :counts[i]]``,
    in emission order; 0 for a mid-prefill tenant, which computes but
    emits nothing).  ``finished[i]`` latches on row i's LAST emitted
    token; ``emitted`` is the legacy ``counts > 0`` mask.
    ``preempted`` lists slots the engine evicted for block exhaustion
    before the step ran — their tenants' blocks and slot state are
    already released, and the scheduler requeues them to continue from
    their streamed prefix.
    """

    tokens: np.ndarray
    finished: np.ndarray
    emitted: np.ndarray
    preempted: Tuple[int, ...]
    counts: np.ndarray


def prompt_lookup_draft(context: np.ndarray, k: int,
                        max_ngram: int = 3) -> np.ndarray:
    """Propose up to ``k`` draft tokens by PROMPT LOOKUP (n-gram
    continuation) — the model-free drafter of the speculative-decoding
    tentpole.

    Finds the most recent earlier occurrence of the context's trailing
    n-gram (longest ``n <= max_ngram`` first) and proposes the tokens
    that followed it.  Pure host-side numpy over ``prompt ++ streamed
    tokens``; returns an empty array when nothing matches — the row
    then rides the step as a plain one-token decode.  Summarization /
    code-editing / few-shot traffic repeats long prompt spans, which
    is exactly when lookup drafts hit ("LLM Inference Acceleration via
    Efficient Operation Fusion", PAPERS.md reports the same
    no-second-model recipe).
    """
    context = np.asarray(context, np.int32).reshape(-1)
    n_ctx = int(context.size)
    if k < 1 or n_ctx < 2:
        return np.empty((0,), np.int32)
    for n in range(min(int(max_ngram), n_ctx - 1), 0, -1):
        pattern = context[n_ctx - n:]
        windows = np.lib.stride_tricks.sliding_window_view(
            context[:n_ctx - 1], n)
        hits = np.nonzero((windows == pattern).all(axis=1))[0]
        if hits.size:
            start = int(hits[-1]) + n
            drafts = context[start:start + int(k)]
            if drafts.size:
                return drafts.astype(np.int32)
    return np.empty((0,), np.int32)


def _check_sampling(vocab_size: int, top_k, top_p) -> None:
    """Shared sampling-parameter validation (dense + paged engines)."""
    if top_k is not None and top_k != 0 \
            and not 1 <= top_k <= vocab_size:
        raise ValueError(
            f"top_k must be in [1, vocab_size={vocab_size}] "
            f"(or 0/None to disable), got {top_k}")
    if top_p is not None and not 0.0 < top_p <= 1.0:
        raise ValueError(
            f"top_p must be in (0, 1] (or None to disable), "
            f"got {top_p}")


def sample_dynamic(logits, keys, temperature, top_k, top_p,
                   vocab_size: int):
    """Branchless per-row sampling with DEVICE-ARRAY parameters.

    The engines' historical sampling tail, now living in
    :func:`apex_tpu.ops.fused_sampling.fused_sample_reference` as the
    golden semantics (and the non-Pallas dispatch target) of the fused
    one-pass sampling kernel — this name stays as the reference entry
    point and delegates verbatim.  Semantics: per row fp32 argmax when
    ``temperature <= 0``, else top-k- and/or nucleus-truncated
    categorical at ``logits/temperature``, mirroring ``generate``'s
    static :func:`~apex_tpu.models.generate.sample_logits` with traced
    parameters; an all-greedy / plain-temperature step now
    ``lax.cond``-skips the whole sort + softmax + cumsum tail at
    runtime (bitwise-equivalent on that predicate — see the ops
    module).  The engines themselves call
    :func:`~apex_tpu.ops.fused_sampling.fused_sample`, which resolves
    to the one-pass Pallas kernel on TPU and to exactly this
    composition elsewhere.
    """
    return fused_sample_reference(logits, keys, temperature, top_k,
                                  top_p, vocab_size)


def _active_sampling_params(state):
    """``(temperature, top_k, top_p)`` with RELEASED slots' filter
    params neutralized.

    ``release_slot`` only clears the active bit — a finished top-k /
    top-p tenant would otherwise leave its stale filter params in the
    slot row forever, and the fused epilogue's runtime sort
    short-circuit (skip the sort + cumsum tail when NO row enables a
    filter) would never fire again for the engine's lifetime.  Masking
    by ``active`` only changes rows whose tokens the emission gates
    already discard, so emitted chains are bit-identical either way —
    but the short-circuit predicate sees the true live traffic.
    """
    return (state.temperature,
            jnp.where(state.active, state.top_k, 0),
            jnp.where(state.active, state.top_p, 0.0))


class Engine:
    """Multi-tenant KV-cached decode over one model.

    Host API (single-threaded — callers serialize; the
    ``apex_tpu.serving.api`` server owns one engine per worker thread):

    - ``admit(slot, prompt, *, max_new_tokens, ...)`` — prefill +
      install one request into a free slot.
    - ``step()`` — decode every slot one token; returns
      ``(tokens, finished)`` numpy arrays of length ``max_slots``
      (only slots the caller knows to be occupied carry meaning).
    - ``release(slot)`` — zero + free a slot.
    - ``warmup()`` — trace all executables (one dummy request per
      prompt bucket) so steady state is retrace-free from request one.

    ``prompt_buckets`` quantizes prompt lengths: a prompt compiles
    nothing new as long as its length fits an existing bucket, so the
    compile count is ``len(buckets) + 3`` for the process lifetime.
    """

    #: dense slab layout — :class:`PagedEngine` is the paged twin
    paged = False

    def __init__(self, model, params, *, max_slots: int = 4,
                 prompt_buckets: Sequence[int] = DEFAULT_BUCKETS,
                 prefill_chunk: int = 0):
        cfg = getattr(model, "cfg", None)
        if cfg is None or not hasattr(cfg, "max_seq_len"):
            raise ValueError(
                "Engine needs a model with a .cfg carrying max_seq_len "
                "and vocab_size (GPTModel / LlamaModel contract)")
        if not getattr(cfg, "causal", True):
            raise ValueError("Engine requires a causal model "
                             "(decode=True contract)")
        if getattr(cfg, "kv_cache", "dense") == "paged":
            raise ValueError(
                "this model is configured for the paged KV-cache "
                "(cfg.kv_cache='paged') — serve it through "
                "PagedEngine, or pass the dense twin (the engines "
                "build their own layout twin from cfg)")
        if max_slots < 1:
            raise ValueError(f"max_slots must be >= 1, got {max_slots}")
        if prefill_chunk < 0:
            raise ValueError(
                f"prefill_chunk must be >= 0, got {prefill_chunk}")
        self.model = model
        self.max_slots = int(max_slots)
        self.max_seq_len = int(cfg.max_seq_len)
        self.vocab_size = int(cfg.vocab_size)
        buckets = sorted({int(b) for b in prompt_buckets})
        if not buckets or buckets[0] < 1:
            raise ValueError(
                f"prompt_buckets must be positive, got {prompt_buckets}")
        if buckets[-1] >= self.max_seq_len:
            # == is useless too: a max_seq_len prompt has no cache room
            # left to generate even one token
            raise ValueError(
                f"largest prompt bucket ({buckets[-1]}) must be < "
                f"max_seq_len ({self.max_seq_len}) — the cache must "
                f"hold prompt + generated tokens")
        self.prompt_buckets = tuple(buckets)
        self._prefill_chunk = int(prefill_chunk)
        self._variables = dict(params)
        if "cache" in self._variables:
            raise ValueError(
                "params must not carry a 'cache' collection — the "
                "engine owns the cache pool")
        self._shapes = cache_shapes(model, 1)
        slot_cache.validate_cache_tree(self._shapes)
        self.cache = slot_cache.stacked_zeros(self._shapes, max_slots)
        self.state = slot_cache.init_slot_state(max_slots)
        self._build()

    # ------------------------------------------------------------- jits
    def _build(self) -> None:
        model = self.model
        shapes = self._shapes
        vocab = self.vocab_size
        prefill_chunk = self._prefill_chunk

        def decode_step(variables, pool, state):
            # one token for every slot: vmap of the b=1 decode path
            # over the slot axis — per-slot cache cursors make each
            # row attend at its own position (the scalar cache_index
            # of the plain batched path advances in lockstep and
            # cannot express ragged tenants)
            def one_slot(cache_i, tok_i):
                logits, cache_o = apply_decode(
                    model, variables, cache_i, tok_i[None, None])
                return logits[0, -1], cache_o

            logits, pool = jax.vmap(one_slot)(pool, state.tok)
            split = jax.vmap(jax.random.split)(state.rng)
            # the fused decode epilogue: one-pass Pallas sampling on
            # TPU, the sample_dynamic reference elsewhere — tokens
            # identical either way (ops/fused_sampling parity
            # contract); released slots' stale filter params are
            # masked so the sort short-circuit tracks live traffic
            temp, top_k, top_p = _active_sampling_params(state)
            nxt = fused_sample(logits, split[:, 0], temp, top_k,
                               top_p, vocab_size=vocab)
            produced = state.produced + state.active.astype(jnp.int32)
            hit_budget = produced >= state.budget
            hit_eos = (state.eos_id >= 0) & (nxt == state.eos_id)
            finished = state.active & (hit_budget | hit_eos)
            state = state._replace(
                tok=jnp.where(state.active, nxt, state.tok),
                produced=produced,
                active=state.active & ~finished,
                rng=split[:, 1])
            return pool, state, nxt, finished

        def prefill(variables, prompt, true_len):
            # prompt: (1, bucket_len) right-padded; true_len: traced
            fresh = slot_cache.zeros_from_shapes(shapes)
            _last, filled = prefill_tokens(
                model, variables, fresh, prompt, prefill_chunk)
            return slot_cache.rewind_index_leaves(filled, true_len - 1)

        def admit(pool, state, slot, one, tok, budget, temperature,
                  top_k, top_p, eos_id, seed):
            pool = slot_cache.write_slot(pool, slot, one)
            state = slot_cache.admit_slot(
                state, slot, tok, budget, temperature, top_k, top_p,
                eos_id, seed)
            return pool, state

        def release(pool, state, slot):
            return (slot_cache.reset_slot(pool, slot),
                    slot_cache.release_slot(state, slot))

        # exact retrace budgets: ANY excess trace raises RetraceError —
        # the engine's zero-retrace steady state is enforced, not
        # aspirational.  The pool/state threads through with donation
        # (two live copies of max_slots × max_seq_len K/V would double
        # the engine's HBM footprint).
        self._step = tracecheck.retrace_guard(
            decode_step, max_traces=1, name="serving.decode_step",
            donate_argnums=(1, 2))
        self._prefill = tracecheck.retrace_guard(
            prefill, max_traces=len(self.prompt_buckets),
            name="serving.prefill")
        self._admit = tracecheck.retrace_guard(
            admit, max_traces=1, name="serving.admit",
            donate_argnums=(0, 1))
        self._release = tracecheck.retrace_guard(
            release, max_traces=1, name="serving.release",
            donate_argnums=(0, 1))

    # ------------------------------------------------------------- host
    def bucket_for(self, prompt_len: int) -> int:
        """Smallest configured bucket holding ``prompt_len`` tokens."""
        for b in self.prompt_buckets:
            if prompt_len <= b:
                return b
        raise ValueError(
            f"prompt of {prompt_len} tokens exceeds the largest "
            f"prompt bucket ({self.prompt_buckets[-1]}); configure "
            f"larger prompt_buckets")

    def validate_request(self, prompt_len: int, max_new_tokens: int,
                         temperature: float = 0.0,
                         top_k: Optional[int] = None,
                         top_p: Optional[float] = None) -> int:
        """Static admission checks; returns the prompt's bucket."""
        if prompt_len < 1:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        bucket = self.bucket_for(prompt_len)
        if prompt_len + max_new_tokens > self.max_seq_len:
            raise ValueError(
                f"prompt_len ({prompt_len}) + max_new_tokens "
                f"({max_new_tokens}) exceeds max_seq_len "
                f"({self.max_seq_len})")
        _check_sampling(self.vocab_size, top_k, top_p)
        del temperature      # any float is admissible (<=0 -> greedy)
        return bucket

    def can_admit(self, prompt_len: int, max_new_tokens: int,
                  prompt=None) -> bool:
        """Dense pool: the slab reserves worst-case room per slot, so
        a free slot is always admissible (the scheduler gates on slot
        availability; the paged engine gates on free blocks — shared-
        prefix-discounted — here)."""
        del prompt_len, max_new_tokens, prompt
        return True

    def admit(self, slot: int, prompt, *, max_new_tokens: int,
              temperature: float = 0.0, top_k: Optional[int] = None,
              top_p: Optional[float] = None,
              eos_id: Optional[int] = None, seed: int = 0) -> None:
        """Prefill ``prompt`` (1-D int tokens) and install it in
        ``slot``.  The caller owns slot accounting (the scheduler's
        host-side table); admitting over an occupied slot silently
        replaces the tenant."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        bucket = self.validate_request(
            prompt.shape[0], max_new_tokens, temperature, top_k, top_p)
        if not 0 <= slot < self.max_slots:
            raise ValueError(
                f"slot must be in [0, {self.max_slots}), got {slot}")
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :prompt.shape[0]] = prompt
        one = self._prefill(self._variables, jnp.asarray(padded),
                            np.int32(prompt.shape[0]))
        self.cache, self.state = self._admit(
            self.cache, self.state, np.int32(slot), one,
            np.int32(prompt[-1]), np.int32(max_new_tokens),
            np.float32(temperature), np.int32(top_k or 0),
            np.float32(0.0 if top_p is None else top_p),
            np.int32(-1 if eos_id is None else eos_id),
            np.uint32(seed))

    def step(self) -> Tuple[np.ndarray, np.ndarray]:  # graftlint: hot-step
        """Decode one token for every slot.

        Returns ``(tokens, finished)`` — numpy, length ``max_slots``.
        ``finished[i]`` latches when slot i produced its eos or spent
        its budget this step (the slot is already marked free on
        device; the caller should :meth:`release` it to zero the row).
        The single per-step host sync lives here.
        """
        self.cache, self.state, toks, finished = self._step(
            self._variables, self.cache, self.state)
        # graftlint: unsharded(the engine's single per-step host sync — the scheduler needs the sampled tokens to route)
        return np.asarray(toks), np.asarray(finished)

    def release(self, slot: int) -> None:
        """Zero and free ``slot``."""
        self.cache, self.state = self._release(
            self.cache, self.state, np.int32(slot))

    def warmup(self) -> None:
        """Trace every executable up front: one dummy tenant per
        prompt bucket through admit → step → release.  After this, a
        steady-state soak over any request mix triggers zero retraces
        (and the retrace guards would raise if it did)."""
        for bucket in self.prompt_buckets:
            self.admit(0, np.zeros((bucket,), np.int32),
                       max_new_tokens=1)
            self.step()
            self.release(0)

    @property
    def trace_counts(self) -> dict:
        """Observed traces per executable (diagnostics / tests)."""
        return {
            "decode_step": self._step.trace_count,
            "prefill": self._prefill.trace_count,
            "admit": self._admit.trace_count,
            "release": self._release.trace_count,
        }


# --------------------------------------------------------------------- #
# paged engine — token-granular serving datapath
# --------------------------------------------------------------------- #
@dataclasses.dataclass
class _Tenant:
    """Host-side record of one slot's tenant (the device never sees
    prompts or block lists — only the tables/cursors built from them)."""

    prompt: np.ndarray          # full prompt tokens
    fed: int = 0                # prompt tokens already fed (chunked)
    cursor: int = 0             # tokens written into the cache
    blocks: List[int] = dataclasses.field(default_factory=list)
    seq: int = 0                # admission order (LIFO preemption key)
    budget: int = 0             # max_new_tokens (host mirror)
    emitted: int = 0            # tokens emitted so far (host mirror)
    gen: List[int] = dataclasses.field(default_factory=list)
    #: chain digests of the prompt's full blocks (prefix sharing)
    digests: List[bytes] = dataclasses.field(default_factory=list)
    registered: int = 0         # prompt blocks offered to the trie


class PagedEngine:
    """Continuous-batching decode over a PAGED KV-cache pool.

    The dense :class:`Engine` reserves a ``max_slots × max_seq_len``
    K/V slab and admits via bucket-padded whole-prompt prefill.  This
    engine instead:

    - stores K/V in fixed-size **pages** of a pool sized in TOKENS
      (``pool_tokens``), shared across tenants through per-slot block
      tables (:class:`~apex_tpu.serving.cache.BlockAllocator`) — HBM
      footprint and per-step attention bytes scale with live tokens,
      so the same budget holds several times the dense slot count;
    - runs **chunked prefill inside the decode step**: prompts are
      split into ``prefill_chunk``-token pieces that ride the regular
      step beside decoding tenants (ONE fused mixed prefill+decode
      executable), so a long prompt can never head-of-line-block
      co-tenants and per-step latency is bounded by the chunk;
    - the whole ragged batch is ONE model application — per-row
      cursors/block tables in the cache collection replace the dense
      engine's per-slot vmap, and attention goes through
      :func:`apex_tpu.ops.paged_attention`.

    Exactly FOUR executables for the process lifetime — FIVE with
    speculative decoding on — each under an exact
    :func:`~apex_tpu.utils.tracecheck.retrace_guard` budget of 1:
    ``decode_step`` (width-1 step), ``prefill_step`` (the width-
    ``prefill_chunk`` mixed step — the dense engine's per-bucket
    prefills collapse to this one shape), the optional ``spec_step``
    (the width-``1 + spec_tokens`` draft/verify step below), ``admit``
    (slot-state scatter; no cache writes — pages are overwritten
    before they become visible, so admission and release never touch
    the pool), and ``release``.

    Block exhaustion preempts the YOUNGEST tenant (its blocks are
    freed, its slot state cleared) and reports it in
    ``StepOutput.preempted``; the scheduler requeues it to continue
    from its streamed prefix (PR 4's fault-recovery machinery).

    **Prefix sharing (``share_prefixes=True``)**: admission hashes the
    prompt block-by-block (:func:`~apex_tpu.serving.cache.
    chain_digests`) against a :class:`~apex_tpu.serving.cache.
    PrefixTrie` of live read-only prompt pages.  Hits are mapped
    refcounted (:meth:`BlockAllocator.incref`) instead of recomputed:
    the tenant's ``fed``/``cursor`` start past the shared prefix, so a
    hot system prompt costs the pool — and the prefill compute — once
    per replica instead of once per tenant.  Only FULL prompt blocks
    are shared and a tenant always re-feeds at least its final prompt
    token (the logits source); when the trie covers the whole prompt,
    the last matched block is **copy-on-write forked**: the tenant
    takes a private page and re-derives the block's KV by re-feeding
    its tokens through the ordinary prefill step (copy-by-recompute —
    bitwise identical, no extra executable), counted on ``cow_forks``.
    Eviction/preemption *decrement* refcounts; a page returns to the
    pool — and drops out of the trie — only when its last tenant
    leaves, so ``blocks_in_use`` stays exact and drains to 0.

    **Speculative decoding (``spec_tokens=K > 0``)**: a host-side
    prompt-lookup drafter (:func:`prompt_lookup_draft` — no second
    model) proposes up to K tokens per decoding row from the tenant's
    own ``prompt ++ streamed`` context; the ``spec_step`` feeds
    ``[current, d_1..d_k]`` through ONE model application (the
    chunked-prefill machinery already handles multi-token rows at
    arbitrary positions), samples at every position with sequentially
    split per-row keys, accepts the longest draft prefix matching the
    sampled chain plus one bonus token, and rolls the host cursor back
    over rejected tails (their pool writes are position-masked garbage
    the next step overwrites).  The rng advance is emission-gated *per
    emitted token* — the k-th produced token always consumes the k-th
    split — so greedy AND sampled chains are token-identical to
    ``generate()`` regardless of the acceptance pattern.

    **Quantized KV pages (``kv_dtype="int8"`` / ``"fp8"``)**: the pool
    stores 1-byte codes with per-(kv_head, page) fp32 amax scales
    riding the cache beside the block table
    (``TransformerConfig.kv_dtype`` — quantize-on-write in the model's
    paged scatter, in-register dequant in the Pallas kernel).  The
    allocator, refcounts, CoW forks, preemption and the trie are
    untouched — a shared or forked page carries its scale with it —
    and ``pool_tokens`` keeps counting TOKENS, which are now ~2×
    (bf16) / ~4× (fp32) cheaper: the default pool converts the dense
    slab's byte budget into quantized token capacity, and the
    shared-aware admission gate therefore admits the reclaimed HBM as
    occupancy.  ``kv_dtype="auto"`` adopts the (block_size, kv_dtype)
    pair a joint :func:`~apex_tpu.ops.autotune.tune_paged_attention`
    sweep measured best (unquantized when nothing is cached).

    Numerics contract under quantization: greedy chains agree with
    ``generate()`` within the quantized accuracy band (≥95% token
    agreement on trained models — tests), NOT bitwise; chains remain
    deterministic per (tokens, knobs) and co-tenant-independent.  One
    spec-decoding nuance: write-then-attend puts draft K/V in the pool
    before acceptance is known, so a REJECTED draft's amax legitimately
    stays in its page's monotone running scale — spec-on and spec-off
    quantized chains therefore agree within the band, not bitwise
    (same bounded drift class as rescale-on-append; the rolled-back
    CODES are overwritten next step as usual).

    **Tensor-parallel replica (``mesh=``, ISSUE 13)**: one engine can
    span M chips — the first change that serves a model too big for
    one.  Pass a :func:`tp_mesh` (or an int M) and the whole paged
    datapath shards: weights per their GSPMD annotations
    (ColumnParallel/RowParallel — XLA inserts the per-layer
    all-reduces), the K/V pool (and its quant-scale leaves) on the
    ``kv_heads`` axis through :func:`~apex_tpu.ops.paged_attention`'s
    shard_map path, while block tables, cursors and ``SlotState``
    stay REPLICATED — so the allocator, refcounts, CoW forking,
    preemption, the prefix trie, drafting and the scheduler above are
    byte-for-byte the single-chip host logic.  Prefix sharing,
    speculative decoding and quantized pages therefore ride the
    sharded pool unchanged, at the same 5×1 trace budget (step
    outputs pin their shardings to the committed placement, so the
    signatures reach a fixed point).  ``kv_heads % M != 0`` raises a
    loud ``ValueError`` here, at construction.

    ``block_size=0`` consults the
    :mod:`~apex_tpu.ops.autotune` table (op ``"paged_attention"``,
    keyed on head_dim + the pool's STORAGE dtype + the PER-SHARD
    kv_heads count — a TP engine must not adopt a block size swept at
    full head count) and falls back to 16.
    ``pool_tokens`` defaults to ``max_slots × max_seq_len`` —
    the dense slab's footprint (converted into quantized tokens at
    equal bytes when ``kv_dtype`` is set); shrink it to trade capacity
    for memory (admission token-gates and preemption backstops the
    overcommit).
    """

    paged = True

    def __init__(self, model, params, *, max_slots: int = 4,
                 block_size: int = 0,
                 pool_tokens: Optional[int] = None,
                 prefill_chunk: int = 32,
                 admit_headroom: Optional[int] = None,
                 share_prefixes: bool = False,
                 spec_tokens: int = 0,
                 spec_ngram: int = 3,
                 kv_dtype: Optional[str] = None,
                 mesh=None):
        cfg = getattr(model, "cfg", None)
        if cfg is None or not hasattr(cfg, "max_seq_len"):
            raise ValueError(
                "PagedEngine needs a model with a .cfg carrying "
                "max_seq_len and vocab_size (GPTModel / LlamaModel "
                "contract)")
        if not getattr(cfg, "causal", True):
            raise ValueError("PagedEngine requires a causal model "
                             "(decode=True contract)")
        if getattr(cfg, "sliding_window", None) is not None:
            raise ValueError(
                "PagedEngine does not support sliding-window models — "
                "the paged pool already bounds decode memory to live "
                "tokens; serve with sliding_window=None")
        if max_slots < 1:
            raise ValueError(f"max_slots must be >= 1, got {max_slots}")
        if prefill_chunk < 1:
            raise ValueError(
                f"prefill_chunk must be >= 1, got {prefill_chunk}")
        if spec_tokens < 0:
            raise ValueError(
                f"spec_tokens must be >= 0, got {spec_tokens}")
        if spec_ngram < 1:
            raise ValueError(
                f"spec_ngram must be >= 1, got {spec_ngram}")
        # tensor-parallel replica (ISSUE 13): an int builds a
        # tp-wide mesh over the first tp devices; a Mesh is used as
        # given (the fleet hands each replica its own device slice).
        # A mesh whose tensor axis is 1 is the single-chip engine.
        if isinstance(mesh, int):
            mesh = tp_mesh(mesh) if mesh > 1 else None
        if mesh is not None and TENSOR_AXIS not in mesh.axis_names:
            # loud, like every other TP config mistake: silently
            # serving single-chip on a mesh with no tensor axis would
            # let the user believe they are tensor-parallel
            raise ValueError(
                f"mesh has no {TENSOR_AXIS!r} axis (axes: "
                f"{tuple(mesh.axis_names)}) — build the serving mesh "
                f"with serving.tp_mesh(tp, devices), or pass an int")
        tp = (1 if mesh is None
              else int(dict(mesh.shape).get(TENSOR_AXIS, 1)))
        if tp <= 1:
            mesh, tp = None, 1
        else:
            # the loud config-time gate: kv_heads % tp == 0 (the GQA
            # group→shard mapping), instead of a shape error deep
            # inside shard_map
            tp_head_shards(cfg.num_heads, cfg.kv_heads, tp)
        self.mesh = mesh
        self.tp = tp
        self.model = model
        self.max_slots = int(max_slots)
        self.max_seq_len = int(cfg.max_seq_len)
        self.vocab_size = int(cfg.vocab_size)
        self._chunk = int(prefill_chunk)
        self.share_prefixes = bool(share_prefixes)
        self.spec_tokens = int(spec_tokens)
        self.spec_ngram = int(spec_ngram)
        #: the drafter — swapped for a forced-draft stub during warmup
        #: so the spec executable is traced even when the dummy context
        #: has no n-gram hit
        self._drafter = prompt_lookup_draft
        from apex_tpu.ops import autotune
        from apex_tpu.ops.paged_attention import (
            kv_quant_spec, kv_store_bytes_per_token)
        # autotune entries are keyed on the PER-SHARD kv_heads count:
        # a TP engine's decode step gathers kv_heads/tp heads' pages
        # per chip, so it must never adopt a block size swept at full
        # head count (and vice versa)
        shard_kv_heads = int(cfg.kv_heads) // self.tp
        if kv_dtype == "auto":
            # adopt the (block_size, kv_dtype) pair a joint
            # tune_paged_attention sweep measured best — only together
            # with block_size=0 (an explicit block size means the
            # caller is overriding the tuner, so we don't silently
            # flip their numerics either)
            pair = (autotune.cached_paged_pair(
                int(cfg.head_dim), str(jnp.dtype(cfg.dtype)),
                kv_heads=shard_kv_heads)
                if block_size == 0 else None)
            kv_dtype = pair[1] if pair else None
            if pair and block_size == 0:
                block_size = pair[0]
        store_dt, _qmax = kv_quant_spec(kv_dtype)   # validates name
        self.kv_dtype = kv_dtype
        #: pool storage bits per K/V element (metrics/health gauge)
        self.kv_bits = 8 * (jnp.dtype(cfg.dtype).itemsize
                            if store_dt is None
                            else jnp.dtype(store_dt).itemsize)
        if block_size == 0:
            # per-dtype lookup: a quantized pool's measured best block
            # size is cached under its STORAGE dtype
            key_dt = (str(jnp.dtype(cfg.dtype)) if store_dt is None
                      else str(jnp.dtype(store_dt)))
            block_size = autotune.cached_block_rows(
                "paged_attention", int(cfg.head_dim), key_dt,
                kv_heads=shard_kv_heads) or 16
        if block_size < 1:
            raise ValueError(
                f"block_size must be >= 1, got {block_size}")
        self.block_size = int(block_size)
        if pool_tokens is None:
            pool_tokens = self.max_slots * self.max_seq_len
            if store_dt is not None:
                # equal-HBM default: the dense-slab byte budget
                # (max_slots × max_seq_len tokens at the compute
                # dtype) buys ~itemsize× the QUANTIZED tokens, scale
                # overhead included — the reclaimed HBM becomes
                # admitted occupancy instead of idle savings (same
                # formula the bench traffic model counts with)
                unq = kv_store_bytes_per_token(
                    cfg.head_dim, self.block_size, dtype=cfg.dtype)
                qnt = kv_store_bytes_per_token(
                    cfg.head_dim, self.block_size, kv_dtype)
                pool_tokens = int(pool_tokens * unq / qnt)
        # the pool bounds the largest ADMISSIBLE request
        # (validate_request rejects anything that could never fit
        # alone); the floor here only covers the warmup tenants — the
        # drafted warmup pass admits chunk+1 prompt tokens with a
        # 2 + spec_tokens budget, so the floor grows with K
        min_tokens = min(self._chunk + 3 + self.spec_tokens,
                         self.max_seq_len)
        if pool_tokens < min_tokens:
            raise ValueError(
                f"pool_tokens ({pool_tokens}) must cover at least the "
                f"warmup tenant ({min_tokens} tokens)")
        num_blocks = slot_cache.blocks_for(pool_tokens,
                                           self.block_size) + 1
        self._alloc = slot_cache.BlockAllocator(num_blocks,
                                                self.block_size)
        self._trie = slot_cache.PrefixTrie()
        #: lifetime counters (gauges ride health()/metrics)
        self.cow_forks = 0
        self.spec_proposed = 0
        self.spec_accepted = 0
        self._headroom = (2 * self.block_size if admit_headroom is None
                          else int(admit_headroom))
        self._variables = dict(params)
        if "cache" in self._variables:
            raise ValueError(
                "params must not carry a 'cache' collection — the "
                "engine owns the cache pool")
        # the paged twin: same parameters, paged cache layout — the
        # layout is part of the module hash, so its executables can
        # never collide with a dense model's in any jit cache
        self._paged_model = type(model)(cfg=dataclasses.replace(
            cfg, kv_cache="paged", kv_block_size=self.block_size,
            kv_pool_blocks=num_blocks, kv_dtype=self.kv_dtype,
            kv_mesh=self.mesh,
            kv_shard_axis=(TENSOR_AXIS if self.mesh is not None
                           else None)))
        shapes = cache_shapes(self._paged_model, self.max_slots)
        self.cache = jax.tree.map(
            lambda s: jnp.zeros(s.shape, s.dtype), shapes)
        self.state = slot_cache.init_slot_state(self.max_slots)
        if self.mesh is not None:
            # commit the replica onto its mesh: weights per their
            # GSPMD annotations, the pool sharded on kv_heads, block
            # tables / cursors / slot state replicated.  The step
            # functions pin their outputs to the SAME placement, so
            # shardings reach a fixed point and the retrace budgets
            # of 1 hold exactly as on one chip.
            self._variables = _shard_params_for_tp(self._variables,
                                                   self.mesh)
            self.cache = slot_cache.shard_paged_cache(
                self.cache, self.mesh, TENSOR_AXIS)
            self.state = jax.device_put(
                self.state, jax.sharding.NamedSharding(
                    self.mesh, jax.sharding.PartitionSpec()))
        mb = slot_cache.blocks_for(self.max_seq_len, self.block_size)
        self._tables = np.zeros((self.max_slots, mb), np.int32)
        self._cursors = np.zeros((self.max_slots,), np.int32)
        self._tenants: List[Optional[_Tenant]] = [None] * self.max_slots
        self._admit_seq = 0
        self._build()

    # ------------------------------------------------------------- jits
    def _build(self) -> None:
        model = self._paged_model
        vocab = self.vocab_size
        mesh = self.mesh

        def pin_out(cache, state):
            # TP fixed point: outputs land exactly where the inputs
            # were committed (pool on kv_heads, everything else
            # replicated), so feeding them back never changes the jit
            # signature — the retrace budgets of 1 stay exact
            if mesh is None:
                return cache, state
            return (slot_cache.constrain_paged_cache(
                        cache, mesh, TENSOR_AXIS),
                    _pin_replicated(state, mesh))

        def step_fn(variables, cache, state, tables, cursors, feed,
                    n_tokens, is_prefill, emit):
            # the host-authoritative block tables / cursors overwrite
            # their cache leaves (the model never advances them);
            # n_tokens doubles as the quantized pool's chunk_lens so
            # pad lanes can't pollute page scales
            cache = slot_cache.set_paged_leaves(cache, tables, cursors,
                                                n_tokens)
            # one ragged-batch application: prefilling rows feed their
            # chunk, decoding rows their last sampled token (+ pad)
            tok_ids = jnp.zeros_like(feed).at[:, 0].set(state.tok)
            ids = jnp.where(is_prefill[:, None], feed, tok_ids)
            logits, cache = apply_decode(model, variables, cache, ids)
            last = jnp.take_along_axis(
                logits, (n_tokens - 1)[:, None, None], axis=1)[:, 0]
            split = jax.vmap(jax.random.split)(state.rng)
            # fused decode epilogue (see ops/fused_sampling): the
            # Pallas kernel reads the (slots, vocab) logits once on
            # TPU; the XLA reference is the historical sample_dynamic.
            # Released slots' stale filter params are masked so the
            # sort short-circuit tracks live traffic.
            temp, top_k, top_p = _active_sampling_params(state)
            nxt = fused_sample(last, split[:, 0], temp, top_k, top_p,
                               vocab_size=vocab)
            # emission is gated on the host plan: a mid-prefill tenant
            # computes but emits nothing, and its rng does NOT advance
            # — the k-th produced token always uses the k-th split, so
            # sampled chains are invariant to chunking
            emit = emit & state.active
            produced = state.produced + emit.astype(jnp.int32)
            hit_budget = produced >= state.budget
            hit_eos = (state.eos_id >= 0) & (nxt == state.eos_id)
            finished = emit & (hit_budget | hit_eos)
            state = state._replace(
                tok=jnp.where(emit, nxt, state.tok),
                produced=produced,
                active=state.active & ~finished,
                rng=jnp.where(emit[:, None], split[:, 1], state.rng))
            cache, state = pin_out(cache, state)
            return cache, state, nxt, finished

        spec_w = 1 + self.spec_tokens

        def spec_step_fn(variables, cache, state, tables, cursors,
                         feed, n_tokens, emit):
            # the draft/verify step: every active row decodes — feed
            # row i is [current_tok, d_1..d_k, pad] with n_tokens[i] =
            # 1 + k real tokens.  ONE model application scores all
            # positions; write-then-attend puts the drafts' K/V in the
            # pool first, and the absolute-position mask gives each
            # draft exactly its sequential context.
            cache = slot_cache.set_paged_leaves(cache, tables, cursors,
                                                n_tokens)
            ids = feed.at[:, 0].set(state.tok)
            logits, cache = apply_decode(model, variables, cache, ids)
            # sequential rng chain: position j samples with the j-th
            # split of the row's key — identical keys to j one-token
            # steps, which is what makes sampled chains
            # acceptance-invariant
            chain = state.rng
            keys, chains = [], [chain]
            for _ in range(spec_w):
                split = jax.vmap(jax.random.split)(chain)
                keys.append(split[:, 0])
                chain = split[:, 1]
                chains.append(chain)
            # ONE width-axis fused-epilogue call scores all 1+K
            # positions (the old path paid spec_w separate sorted
            # sampling tails in this executable); per-position keys
            # ride the width axis, per-slot params broadcast —
            # released slots masked, as in the plain step
            temp, top_k, top_p = _active_sampling_params(state)
            sampled = fused_sample(
                logits[:, :spec_w], jnp.stack(keys, axis=1),
                temp, top_k, top_p,
                vocab_size=vocab)                     # (slots, w)
            idx = jnp.arange(spec_w, dtype=jnp.int32)
            # draft j+1 accepted iff it equals the token the model
            # would have sampled at its position — the longest
            # accepted prefix reproduces the sequential chain exactly
            match = (sampled[:, :-1] == feed[:, 1:]) \
                & (idx[None, 1:] < n_tokens[:, None])
            accept = jnp.sum(jnp.cumprod(
                match.astype(jnp.int32), axis=1), axis=1)
            n_emit = jnp.minimum(accept + 1, n_tokens)
            eos_hit = (state.eos_id[:, None] >= 0) \
                & (sampled == state.eos_id[:, None])
            eos_pos = jnp.min(jnp.where(eos_hit, idx[None, :], spec_w),
                              axis=1)
            n_emit = jnp.minimum(n_emit, eos_pos + 1)
            remaining = jnp.maximum(state.budget - state.produced, 0)
            n_emit = jnp.minimum(n_emit, remaining)
            n_emit = jnp.where(emit & state.active, n_emit, 0)
            produced = state.produced + n_emit
            hit_budget = produced >= state.budget
            hit_eos = eos_pos < n_emit
            finished = (n_emit > 0) & (hit_budget | hit_eos)
            last = jnp.take_along_axis(
                sampled, jnp.maximum(n_emit - 1, 0)[:, None],
                axis=1)[:, 0]
            # rng advance is emission-gated per TOKEN: exactly n_emit
            # splits are consumed, like n_emit one-token steps
            new_rng = jnp.take_along_axis(
                jnp.stack(chains, axis=1), n_emit[:, None, None],
                axis=1)[:, 0]
            state = state._replace(
                tok=jnp.where(n_emit > 0, last, state.tok),
                produced=produced,
                active=state.active & ~finished,
                rng=new_rng)
            cache, state = pin_out(cache, state)
            return cache, state, sampled, n_emit, finished

        def admit(state, slot, tok, budget, temperature, top_k, top_p,
                  eos_id, seed):
            state = slot_cache.admit_slot(
                state, slot, tok, budget, temperature, top_k, top_p,
                eos_id, seed)
            return (state if mesh is None
                    else _pin_replicated(state, mesh))

        def release(state, slot):
            state = slot_cache.release_slot(state, slot)
            return (state if mesh is None
                    else _pin_replicated(state, mesh))

        # exact budgets: decode/spec/admit/release = 1 and the dense
        # engine's per-bucket prefills collapse to ONE mixed-step
        # shape — any excess trace raises RetraceError
        self._decode = tracecheck.retrace_guard(
            step_fn, max_traces=1, name="serving.decode_step",
            donate_argnums=(1, 2))
        self._prefill = tracecheck.retrace_guard(
            step_fn, max_traces=1, name="serving.prefill_step",
            donate_argnums=(1, 2))
        self._spec = tracecheck.retrace_guard(
            spec_step_fn, max_traces=1, name="serving.spec_step",
            donate_argnums=(1, 2))
        self._admit = tracecheck.retrace_guard(
            admit, max_traces=1, name="serving.admit",
            donate_argnums=(0,))
        self._release = tracecheck.retrace_guard(
            release, max_traces=1, name="serving.release",
            donate_argnums=(0,))

    # ------------------------------------------------------------- host
    def validate_request(self, prompt_len: int, max_new_tokens: int,
                         temperature: float = 0.0,
                         top_k: Optional[int] = None,
                         top_p: Optional[float] = None) -> None:
        """Static admission checks (no buckets: chunked prefill admits
        any prompt length that fits the cache and the pool)."""
        if prompt_len < 1:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if prompt_len + max_new_tokens > self.max_seq_len:
            raise ValueError(
                f"prompt_len ({prompt_len}) + max_new_tokens "
                f"({max_new_tokens}) exceeds max_seq_len "
                f"({self.max_seq_len})")
        need = slot_cache.blocks_for(prompt_len + max_new_tokens,
                                     self.block_size)
        if need > self._alloc.blocks_total:
            raise ValueError(
                f"request needs {need} pages "
                f"({prompt_len}+{max_new_tokens} tokens at "
                f"block_size={self.block_size}) but the whole pool "
                f"holds {self._alloc.blocks_total} — raise pool_tokens")
        _check_sampling(self.vocab_size, top_k, top_p)
        del temperature

    def _sharable_blocks(self, prompt: np.ndarray,
                         digests: Optional[List[bytes]] = None) -> int:
        """Trie-matched prompt blocks this prompt could map, CAPPED so
        at least the final prompt token is always re-fed (the logits
        source): a whole-prompt hit drops its last block — that block
        is re-derived into a private page (the copy-on-write fork)."""
        if not self.share_prefixes:
            return 0
        if digests is None:
            digests = slot_cache.chain_digests(prompt, self.block_size)
        matched = len(self._trie.match(digests))
        return min(matched,
                   (int(prompt.size) - 1) // self.block_size)

    def prefix_hit_blocks(self, prompt) -> int:
        """Pages of ``prompt``'s prefix already resident in this
        engine's trie (0 with sharing off) — the fleet router's
        prefix-affinity routing key, and the admission discount."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        return self._sharable_blocks(prompt)

    def can_admit(self, prompt_len: int, max_new_tokens: int,
                  prompt=None) -> bool:
        """Token-budget admission gate: free pages must cover the
        prompt plus reserved decode headroom (preemption backstops the
        deliberate overcommit beyond the headroom).  SHARED-aware when
        the caller passes the prompt tokens: trie-resident prefix
        pages cost nothing new, so reclaimed pool capacity converts
        directly into admitted occupancy."""
        shared = 0
        if prompt is not None and self.share_prefixes:
            shared = self.prefix_hit_blocks(prompt)
        need = slot_cache.blocks_for(
            prompt_len + min(int(max_new_tokens), self._headroom),
            self.block_size) - shared
        return self._alloc.blocks_free >= need

    def admit(self, slot: int, prompt, *, max_new_tokens: int,
              temperature: float = 0.0, top_k: Optional[int] = None,
              top_p: Optional[float] = None,
              eos_id: Optional[int] = None, seed: int = 0) -> None:
        """Install one request into a free slot.  NO prefill happens
        here — the prompt rides the next steps as chunks; no pages are
        allocated either (the step loop extends tables just ahead of
        the tokens it writes).  With ``share_prefixes``, trie-resident
        prompt-prefix pages ARE mapped here (refcounted, read-only):
        ``fed``/``cursor`` start past them, so their KV is neither
        recomputed nor re-stored."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.validate_request(prompt.shape[0], max_new_tokens,
                              temperature, top_k, top_p)
        if not 0 <= slot < self.max_slots:
            raise ValueError(
                f"slot must be in [0, {self.max_slots}), got {slot}")
        if self._tenants[slot] is not None:
            raise ValueError(f"slot {slot} is occupied (paged "
                             "admission never silently replaces — the "
                             "tenant owns pool pages)")
        self._admit_seq += 1
        rec = _Tenant(prompt=prompt, seq=self._admit_seq,
                      budget=int(max_new_tokens))
        if self.share_prefixes:
            rec.digests = slot_cache.chain_digests(prompt,
                                                   self.block_size)
            matched = self._trie.match(rec.digests)
            # same cap as _sharable_blocks, without a second trie walk
            n_share = min(len(matched),
                          (int(prompt.size) - 1) // self.block_size)
            if len(matched) > n_share:
                # whole-prompt hit: the dropped tail block will be
                # re-derived into a private page (CoW fork by
                # recompute — see the class docstring)
                self.cow_forks += 1
                counters.inc("serving.cow_fork")
            for page in matched[:n_share]:
                self._alloc.incref(page)
            rec.blocks = list(matched[:n_share])
            self._tables[slot, :n_share] = rec.blocks
            rec.fed = rec.cursor = n_share * self.block_size
            rec.registered = n_share
            self._cursors[slot] = rec.cursor
        self._tenants[slot] = rec
        self.state = self._admit(
            self.state, np.int32(slot), np.int32(prompt[-1]),
            np.int32(max_new_tokens), np.float32(temperature),
            np.int32(top_k or 0),
            np.float32(0.0 if top_p is None else top_p),
            np.int32(-1 if eos_id is None else eos_id),
            np.uint32(seed))

    def _youngest(self) -> int:
        live = [s for s, t in enumerate(self._tenants) if t is not None]
        return max(live, key=lambda s: self._tenants[s].seq)

    def _free_tenant(self, slot: int) -> None:
        """Return a tenant's pages and clear its host/device state.
        The pool itself is untouched: freed pages are garbage until
        their next owner overwrites them, and the position mask keeps
        garbage unreachable."""
        rec = self._tenants[slot]
        if rec is not None:
            # refcounted free: shared prefix pages survive until their
            # LAST tenant leaves; pages that actually returned to the
            # pool drop out of the trie (it only indexes live KV)
            for page in self._alloc.free(rec.blocks):
                self._trie.forget(page)
            self._tables[slot] = 0
            self._cursors[slot] = 0
            self._tenants[slot] = None
        self.state = self._release(self.state, np.int32(slot))

    def _read_only(self, page: int) -> bool:
        """A page no tenant may write: mapped by >1 tenant, or indexed
        by the trie (a future tenant may map it any time)."""
        return (self._alloc.refcount(page) > 1
                or self._trie.holds_block(page))

    def _extend(self, slot: int, n: int,
                preempted: List[int]) -> None:
        """Grow ``slot``'s block table to cover its next ``n`` real
        tokens, preempting the youngest tenant on exhaustion.  A
        request is admission-validated to fit the whole pool alone, so
        the loop terminates: in the worst case everyone else (and
        finally the needy slot itself) is preempted.

        Copy-on-write guard: the write range must never touch a
        READ-ONLY page.  By construction it cannot land mid-block in
        one (admission always leaves shared prefixes at a block
        boundary and re-derives a whole-prompt hit's tail block), so
        the only live case is an exact-boundary fork — swap in a fresh
        private page with nothing to copy — and exhaustion there
        preempts through the same loop as a plain extension."""
        rec = self._tenants[slot]
        while rec is not None and rec.cursor % self.block_size == 0:
            wb = rec.cursor // self.block_size
            if wb >= len(rec.blocks) \
                    or not self._read_only(rec.blocks[wb]):
                break
            try:
                got = self._alloc.alloc(1)
            except slot_cache.BlockExhausted:
                victim = self._youngest()
                self._free_tenant(victim)
                preempted.append(victim)
                if victim == slot:
                    return
                continue
            for page in self._alloc.free([rec.blocks[wb]]):
                self._trie.forget(page)
            rec.blocks[wb] = got[0]
            self._tables[slot, wb] = got[0]
            self.cow_forks += 1
            counters.inc("serving.cow_fork")
            break
        while rec is not None:
            # capped at the table width: a finished-but-unreleased
            # tenant stepped past max_seq_len (possible in raw engine
            # drivers; the scheduler releases at the finish boundary)
            # wraps within its last page instead of growing the table
            need = min(slot_cache.blocks_for(rec.cursor + n,
                                             self.block_size),
                       self._tables.shape[1]) - len(rec.blocks)
            if need <= 0:
                return
            try:
                got = self._alloc.alloc(need)
            except slot_cache.BlockExhausted:
                victim = self._youngest()
                self._free_tenant(victim)
                preempted.append(victim)
                if victim == slot:
                    return
                continue
            start = len(rec.blocks)
            self._tables[slot, start:start + len(got)] = got
            rec.blocks.extend(got)

    def _register_blocks(self, rec: _Tenant) -> None:
        """Offer a prefilling tenant's newly COMPLETED full prompt
        blocks to the trie: from the moment a block's last prompt
        token is fed (and therefore written), its page is finalized
        read-only KV any same-prefix admission may map."""
        full = min(int(rec.fed), int(rec.prompt.size)) \
            // self.block_size
        limit = min(full, len(rec.digests))
        while rec.registered < limit:
            i = rec.registered
            self._trie.register(rec.digests[i], rec.blocks[i])
            rec.registered += 1

    def _plan_drafts(self) -> List[Optional[np.ndarray]]:
        """Host-side draft proposal for every decoding row: up to
        ``spec_tokens`` prompt-lookup tokens, capped by the remaining
        budget (an accepted run emits ``drafts + 1`` tokens) and the
        cache envelope (every fed token is written at
        ``cursor + offset``)."""
        drafts: List[Optional[np.ndarray]] = [None] * self.max_slots
        for slot, rec in enumerate(self._tenants):
            if rec is None:
                continue
            cap = min(self.spec_tokens,
                      rec.budget - rec.emitted - 1,
                      self.max_seq_len - rec.cursor - 1)
            if cap < 1:
                continue
            context = rec.prompt
            if rec.gen:
                context = np.concatenate(
                    [context, np.asarray(rec.gen, np.int32)])
            proposal = self._drafter(context, cap, self.spec_ngram)
            if proposal.size:
                drafts[slot] = proposal[:cap]
        return drafts

    def step(self) -> StepOutput:  # graftlint: hot-step
        """One fused mixed prefill+decode step over every slot.

        Prefilling tenants consume their next prompt chunk (emitting a
        token only on the final chunk — that token IS the first
        generated one, sampled straight from the prefill logits);
        decoding tenants advance one token — or, in a drafted step
        (``spec_tokens > 0``, no prefill pending, at least one lookup
        hit), verify their draft run and emit the accepted prefix plus
        one bonus token.  Inactive slots compute garbage into the null
        page.  The single per-step host sync lives here.
        """
        any_prefill = any(rec is not None
                          and rec.fed < rec.prompt.size
                          for rec in self._tenants)
        drafts: List[Optional[np.ndarray]] = [None] * self.max_slots
        if not any_prefill and self.spec_tokens > 0:
            drafts = self._plan_drafts()
        any_spec = any(d is not None for d in drafts)
        w = (self._chunk if any_prefill
             else 1 + self.spec_tokens if any_spec else 1)
        feed = np.zeros((self.max_slots, w), np.int32)
        n_tokens = np.ones((self.max_slots,), np.int32)
        is_prefill = np.zeros((self.max_slots,), bool)
        emit = np.zeros((self.max_slots,), bool)
        preempted: List[int] = []
        for slot in range(self.max_slots):
            rec = self._tenants[slot]
            if rec is None:
                continue
            if rec.fed < rec.prompt.size:
                n = min(w, rec.prompt.size - rec.fed)
                feed[slot, :n] = rec.prompt[rec.fed:rec.fed + n]
                n_tokens[slot] = n
                is_prefill[slot] = True
                emit[slot] = rec.fed + n >= rec.prompt.size
            else:
                emit[slot] = True
                if drafts[slot] is not None:
                    d = drafts[slot]
                    feed[slot, 1:1 + d.size] = d
                    n_tokens[slot] = 1 + d.size
            self._extend(slot, int(n_tokens[slot]), preempted)
        for slot in preempted:
            feed[slot] = 0
            n_tokens[slot] = 1
            is_prefill[slot] = False
            emit[slot] = False
            drafts[slot] = None
        if any_spec:
            self.cache, self.state, sampled, n_emit, finished = \
                self._spec(self._variables, self.cache, self.state,
                           self._tables, self._cursors, feed,
                           n_tokens, emit)
            # graftlint: unsharded(the paged engine's single per-step host sync — verified drafts steer host-side cursors)
            tokens = np.asarray(sampled)
            # graftlint: unsharded(same fetch — accepted-prefix lengths roll the cursors back over rejected tails)
            counts = np.asarray(n_emit)
        else:
            runner = self._prefill if any_prefill else self._decode
            self.cache, self.state, toks, finished = runner(
                self._variables, self.cache, self.state, self._tables,
                self._cursors, feed, n_tokens, is_prefill, emit)
            # graftlint: unsharded(the paged engine's single per-step host sync — emitted tokens feed the host tenant table)
            tokens = np.asarray(toks)[:, None]
            counts = emit.astype(np.int32)
        for slot in range(self.max_slots):
            rec = self._tenants[slot]
            if rec is None:
                continue
            if any_spec:
                # keep only the verified prefix: the cursor rolls back
                # over rejected draft tails, whose pool writes are
                # position-masked garbage the next step overwrites
                kept = int(counts[slot])
                rec.cursor += kept
                proposed = int(n_tokens[slot]) - 1
                if proposed > 0:
                    self.spec_proposed += proposed
                    self.spec_accepted += max(kept - 1, 0)
            else:
                n = int(n_tokens[slot])
                if is_prefill[slot]:
                    rec.fed += n
                    if self.share_prefixes:
                        self._register_blocks(rec)
                rec.cursor += n
            # host mirrors of the emission (the drafter's context and
            # budget cap)
            kept = int(counts[slot])
            if kept:
                rec.emitted += kept
                rec.gen.extend(int(t) for t in tokens[slot, :kept])
            self._cursors[slot] = rec.cursor
        # graftlint: unsharded(finished flags ride the same per-step fetch; the caller releases finished slots)
        return StepOutput(tokens, np.asarray(finished),
                          counts > 0, tuple(preempted), counts)

    def release(self, slot: int) -> None:
        """Free ``slot``: pages back to the pool (refcount-decremented
        — shared prefix pages survive their co-tenants), state
        cleared."""
        self._free_tenant(slot)

    def warmup(self) -> None:
        """Trace every executable: one dummy tenant whose prompt spans
        a full chunk plus a remainder (mixed prefill step) and then
        decodes (width-1 step); with ``spec_tokens`` on, a second
        tenant runs under a forced-draft stub so the drafted step is
        traced even though the dummy context has no n-gram hit.
        Steady state over ANY request mix is retrace-free afterwards —
        and guarded.

        Prompts clamp for small-context models (chunk width larger
        than the context is legal: real chunks are capped by the
        prompt; the executable widths traced are the same either
        way)."""
        drafter = self._drafter

        def run_one(plen: int, budget: int) -> None:
            self.admit(0, np.zeros((plen,), np.int32),
                       max_new_tokens=budget)
            while self._tenants[0] is not None:
                out = self.step()
                if bool(out.finished[0]):
                    break
            self.release(0)

        try:
            # pass 1: prefill + plain decode (drafts suppressed so the
            # width-1 executable is the one traced)
            self._drafter = lambda context, k, ngram: np.empty(
                (0,), np.int32)
            run_one(max(1, min(self._chunk + 1, self.max_seq_len - 2)),
                    2)
            if self.spec_tokens:
                # pass 2: forced drafts so the spec executable traces
                self._drafter = lambda context, k, ngram: np.zeros(
                    (k,), np.int32)
                run_one(
                    max(1, min(self._chunk + 1,
                               self.max_seq_len - 2 - self.spec_tokens)),
                    2 + self.spec_tokens)
        finally:
            self._drafter = drafter

    # ------------------------------------------------------------ gauges
    @property
    def chips_per_replica(self) -> int:
        """Chips this ONE replica spans (the tensor-parallel degree;
        1 = the single-chip engine) — per-chip throughput in the
        Gemma-paper serving protocol divides by this."""
        return self.tp

    @property
    def mesh_shape(self) -> Optional[dict]:
        """``{axis: size}`` of the replica's mesh, or ``None`` on a
        single chip (health()/fleet merged-view field)."""
        if self.mesh is None:
            return None
        return {str(k): int(v) for k, v in dict(self.mesh.shape).items()
                if int(v) > 1}

    @property
    def blocks_total(self) -> int:
        return self._alloc.blocks_total

    @property
    def blocks_free(self) -> int:
        return self._alloc.blocks_free

    @property
    def blocks_in_use(self) -> int:
        return self._alloc.blocks_in_use

    @property
    def pool_tokens(self) -> int:
        return self._alloc.tokens_total

    @property
    def live_tokens(self) -> int:
        """Tokens currently written for live tenants (host-side view)
        — a finer utilization numerator than whole pages; surfaced in
        ``InferenceServer.health()``/metrics so a fleet router can see
        real load, not just page-granular occupancy."""
        return int(sum(t.cursor for t in self._tenants
                       if t is not None))

    @property
    def shared_blocks(self) -> int:
        """Physical pages currently mapped by more than one tenant."""
        return self._alloc.shared_blocks

    @property
    def blocks_saved(self) -> int:
        """Pool pages prefix sharing reclaims right now (Σ ref-1)."""
        return self._alloc.blocks_saved

    @property
    def trie_blocks(self) -> int:
        """Live pages indexed by the prefix trie (sharable)."""
        return len(self._trie)

    @property
    def spec_accept_rate(self) -> float:
        """Fraction of proposed draft tokens the verify step accepted
        (lifetime; 0.0 before any drafted step)."""
        if not self.spec_proposed:
            return 0.0
        return self.spec_accepted / self.spec_proposed

    @property
    def trace_counts(self) -> dict:
        """Observed traces per executable (diagnostics / tests).  The
        ``spec_step`` entry appears only when speculative decoding is
        configured — the documented budget is 4 executables, + 1 with
        drafting on."""
        out = {
            "decode_step": self._decode.trace_count,
            "prefill_step": self._prefill.trace_count,
            "admit": self._admit.trace_count,
            "release": self._release.trace_count,
        }
        if self.spec_tokens:
            out["spec_step"] = self._spec.trace_count
        return out
