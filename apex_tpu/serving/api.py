"""Threaded front-end: submit → handle, streaming tokens, metrics.

:class:`InferenceServer` owns one worker thread that runs the
engine/scheduler loop (JAX dispatch stays single-threaded); client
threads talk to it only through the bounded queue and per-request
:class:`RequestHandle` streams.  Throughput / occupancy / queue-depth
metrics flow through :class:`apex_tpu.utils.metrics.MetricsWriter`
every ``metrics_interval`` steps, tagged with the server's step counter
and drained in order.

Usage::

    server = InferenceServer(model, params, max_slots=4)
    with server:                       # starts (and warms up) the loop
        h = server.submit([1, 2, 3], max_new_tokens=16)
        for tok in h.stream():         # tokens as they decode
            ...
        full = h.result()              # or block for the final list
"""

from __future__ import annotations

import queue as queue_mod
import threading
import time
from typing import List, Optional, Sequence

import numpy as np

from apex_tpu.serving.engine import DEFAULT_BUCKETS, Engine
from apex_tpu.serving.scheduler import QueueFull, Request, Scheduler
from apex_tpu.utils.metrics import MetricsWriter

__all__ = ["InferenceServer", "RequestHandle", "ServerClosed"]

_SENTINEL = object()


class ServerClosed(RuntimeError):
    """Submit after shutdown, or a request cancelled by shutdown."""


class RequestHandle:
    """Client-side view of one in-flight request."""

    def __init__(self, request: Request):
        self._request = request
        self._stream: "queue_mod.Queue" = queue_mod.Queue()
        self._done = threading.Event()
        self._cancelled = False

    # ------------------------------------------------------- server side
    def _deliver(self, token: int, finished: bool) -> None:
        self._stream.put(int(token))
        if finished:
            self._stream.put(_SENTINEL)
            self._done.set()

    def _cancel(self) -> None:
        self._cancelled = True
        self._stream.put(_SENTINEL)
        self._done.set()

    # ------------------------------------------------------- client side
    def stream(self, timeout: Optional[float] = None):
        """Yield tokens as they are produced; ends at eos/budget.
        Raises :class:`ServerClosed` if the server shut down first,
        ``TimeoutError`` if no token arrives within ``timeout``."""
        while True:
            try:
                item = self._stream.get(timeout=timeout)
            except queue_mod.Empty:
                raise TimeoutError(
                    f"no token within {timeout}s") from None
            if item is _SENTINEL:
                if self._cancelled:
                    raise ServerClosed(
                        "server shut down before the request finished")
                return
            yield item

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Block until finished; returns every produced token."""
        if not self._done.wait(timeout):
            raise TimeoutError("request still decoding")
        if self._cancelled:
            raise ServerClosed(
                "server shut down before the request finished")
        return list(self._request.tokens)

    @property
    def done(self) -> bool:
        return self._done.is_set()

    @property
    def tokens_so_far(self) -> List[int]:
        return list(self._request.tokens)


class InferenceServer:
    """Continuous-batching inference server over one model.

    ``submit`` blocks (bounded backpressure) while the queue is full —
    pass ``block=False`` to get :class:`QueueFull` immediately.
    ``shutdown(wait=True)`` serves everything already accepted, then
    stops; ``wait=False`` cancels queued AND in-flight requests (their
    handles raise :class:`ServerClosed`).
    """

    def __init__(self, model, params, *, max_slots: int = 4,
                 prompt_buckets: Sequence[int] = DEFAULT_BUCKETS,
                 prefill_chunk: int = 0, queue_capacity: int = 64,
                 metrics: Optional[MetricsWriter] = None,
                 metrics_interval: int = 32):
        self.engine = Engine(
            model, params, max_slots=max_slots,
            prompt_buckets=prompt_buckets, prefill_chunk=prefill_chunk)
        self.scheduler = Scheduler(self.engine,
                                   queue_capacity=queue_capacity)
        self.metrics = metrics
        self.metrics_interval = max(1, int(metrics_interval))
        self._handles: dict = {}          # uid -> RequestHandle
        self._wakeup = threading.Condition()
        self._stop = False
        self._drain_on_stop = True
        self._thread: Optional[threading.Thread] = None
        self._steps = 0
        self._tokens_emitted = 0
        self._window_tokens = 0
        self._window_t0: Optional[float] = None
        self._last_emit_step = -1
        #: the exception that killed the worker loop, if any — clients
        #: see ServerClosed; the root cause lives here for post-mortems
        self.error: Optional[BaseException] = None

    # ---------------------------------------------------------- lifecycle
    def start(self, *, warmup: bool = True) -> "InferenceServer":
        if self._thread is not None:
            raise RuntimeError("server already started")
        if warmup:
            self.engine.warmup()
        self._thread = threading.Thread(
            target=self._serve, name="apex-tpu-serving", daemon=True)
        self._thread.start()
        return self

    def shutdown(self, *, wait: bool = True,
                 timeout: Optional[float] = None) -> None:
        if self._thread is None:
            return
        with self._wakeup:
            self._stop = True
            self._drain_on_stop = wait
            self._wakeup.notify_all()
        self._thread.join(timeout)
        self._thread = None

    def __enter__(self) -> "InferenceServer":
        return self.start()

    def __exit__(self, exc_type, exc, tb) -> None:
        # propagate client-side errors without hanging on a full drain
        self.shutdown(wait=exc_type is None)

    # ------------------------------------------------------------- intake
    def submit(self, prompt, *, max_new_tokens: int,
               temperature: float = 0.0, top_k: Optional[int] = None,
               top_p: Optional[float] = None,
               eos_id: Optional[int] = None, seed: int = 0,
               block: bool = True,
               timeout: Optional[float] = None) -> RequestHandle:
        """Enqueue one request; returns its :class:`RequestHandle`."""
        request = Request(
            prompt=np.asarray(prompt, np.int32).reshape(-1),
            max_new_tokens=int(max_new_tokens),
            temperature=float(temperature),
            top_k=top_k, top_p=top_p, eos_id=eos_id, seed=int(seed))
        # the handle must be reachable by the worker BEFORE the request
        # enters the queue: run_step doesn't take _wakeup, so a fast
        # worker can admit — even finish — a one-token request between
        # the enqueue and any later registration, and its events would
        # be dropped.  Keyed by object identity (stable pre-enqueue;
        # uid is only assigned inside scheduler.submit).
        handle = RequestHandle(request)
        self._handles[id(request)] = handle
        deadline = None if timeout is None else time.monotonic() + timeout
        try:
            while True:
                with self._wakeup:
                    if self._stop or self._thread is None:
                        raise ServerClosed("server is not running")
                    try:
                        self.scheduler.submit(request)
                        self._wakeup.notify_all()
                        return handle
                    except QueueFull:
                        if not block:
                            raise
                        remaining = None if deadline is None \
                            else deadline - time.monotonic()
                        if remaining is not None and remaining <= 0:
                            raise
                        # woken by the worker after each admission wave
                        self._wakeup.wait(
                            0.05 if remaining is None
                            else min(0.05, remaining))
        except BaseException:
            self._handles.pop(id(request), None)
            raise

    # ------------------------------------------------------------- worker
    def _serve(self) -> None:
        try:
            while True:
                with self._wakeup:
                    while (not self.scheduler.has_work()
                           and not self._stop):
                        self._wakeup.wait(0.1)
                    if self._stop and (not self._drain_on_stop
                                       or not self.scheduler.has_work()):
                        break
                events = self.scheduler.run_step()
                self._steps += 1
                now = time.monotonic()
                if self._window_t0 is None:
                    self._window_t0 = now
                for ev in events:
                    self._tokens_emitted += 1
                    self._window_tokens += 1
                    handle = self._handles.get(id(ev.request))
                    if handle is not None:
                        handle._deliver(ev.token, ev.finished)
                        if ev.finished:
                            self._handles.pop(id(ev.request), None)
                with self._wakeup:
                    self._wakeup.notify_all()   # queue space freed
                if self.metrics is not None \
                        and self._steps % self.metrics_interval == 0:
                    self._emit_metrics(now)
        except BaseException as exc:    # noqa: BLE001 — any engine
            # failure (RetraceError, OOM, ...) must not strand clients:
            # record it, flip _stop so submit()/blocking waiters see a
            # closed server, and fall through to the cancel path below
            self.error = exc
            with self._wakeup:
                self._stop = True
                self._wakeup.notify_all()
        finally:
            # cancel every leftover queued/in-flight handle (normal
            # wait=False shutdown reaches here too; after a full drain
            # there is simply nothing left to cancel)
            for req in self.scheduler.cancel_queued():
                handle = self._handles.pop(id(req), None)
                if handle is not None:
                    handle._cancel()
            for slot, req in enumerate(self.scheduler._slots):
                if req is None:
                    continue
                if self.error is None:
                    self.engine.release(slot)
                self.scheduler._slots[slot] = None
                handle = self._handles.pop(id(req), None)
                if handle is not None:
                    handle._cancel()
            if self.metrics is not None \
                    and self._steps != self._last_emit_step:
                self._emit_metrics(time.monotonic())

    def _emit_metrics(self, now: float) -> None:
        dt = max(now - (self._window_t0 or now), 1e-9)
        self.metrics(self._steps, {
            "tokens_per_sec": self._window_tokens / dt,
            "occupancy": self.scheduler.occupancy,
            "queue_depth": self.scheduler.queue_depth,
            "tokens_total": self._tokens_emitted,
        })
        self.metrics.drain()
        self._last_emit_step = self._steps
        self._window_tokens = 0
        self._window_t0 = now

    # ---------------------------------------------------------- telemetry
    @property
    def steps(self) -> int:
        return self._steps

    @property
    def tokens_emitted(self) -> int:
        return self._tokens_emitted
