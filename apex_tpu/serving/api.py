"""Threaded front-end: submit → handle, streaming tokens, metrics.

:class:`InferenceServer` owns one worker thread that runs the
engine/scheduler loop (JAX dispatch stays single-threaded); client
threads talk to it only through the bounded queue and per-request
:class:`RequestHandle` streams.  Throughput / occupancy / queue-depth
metrics flow through :class:`apex_tpu.utils.metrics.MetricsWriter`
every ``metrics_interval`` steps, tagged with the server's step counter
and drained in order.

Usage::

    server = InferenceServer(model, params, max_slots=4)
    with server:                       # starts (and warms up) the loop
        h = server.submit([1, 2, 3], max_new_tokens=16)
        for tok in h.stream():         # tokens as they decode
            ...
        full = h.result()              # or block for the final list
"""

from __future__ import annotations

import queue as queue_mod
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from apex_tpu.core.mesh import TENSOR_AXIS
from apex_tpu.resilience import faults
from apex_tpu.serving.engine import DEFAULT_BUCKETS, Engine, PagedEngine
from apex_tpu.serving.scheduler import QueueFull, Request, Scheduler
from apex_tpu.utils.metrics import (
    MetricsWriter,
    counters,
    percentile_summary,
)

__all__ = ["InferenceServer", "RequestHandle", "ServerClosed",
           "RequestFailed", "ReplicaDraining"]

_SENTINEL = object()

#: server-side observer a fleet router attaches to a handle:
#: ``tap(token, finished, error)`` — token events carry ``(tok, fin,
#: None)``, the terminal failure carries ``(None, True, exc)``.
Tap = Callable[[Optional[int], bool, Optional[BaseException]], None]


class ServerClosed(RuntimeError):
    """TERMINAL: the server shut down (or its worker died) before the
    request finished — the request will never produce more tokens.
    Also raised by ``submit`` on a stopped server."""


class ReplicaDraining(ServerClosed):
    """TERMINAL *for this replica only*: the server is gracefully
    draining (:meth:`InferenceServer.begin_drain`) and evicted the
    request — its engine slot is released, its streamed prefix is
    intact — so a fleet router can migrate it (``prompt ++ streamed
    tokens``, remaining budget) onto a survivor.  Plain clients
    without a router on top should treat it exactly as
    :class:`ServerClosed`."""


class RequestFailed(RuntimeError):
    """TERMINAL: this one request failed — deadline expired, repeated
    step faults, or an unresumable continuation — while the server
    itself keeps serving.  ``__cause__`` carries the root failure when
    there is one."""


class RequestHandle:
    """Client-side view of one in-flight request.

    Error contract (see ``docs/resilience.md``): :meth:`stream` and
    :meth:`result` raise exactly one of

    - ``TimeoutError`` — RETRYABLE: *no token yet* within ``timeout``.
      The request is still live; call again with the same handle.
    - :class:`RequestFailed` — TERMINAL: this request failed (deadline,
      repeated faults); the server is still serving others.
    - :class:`ServerClosed` — TERMINAL: the server stopped first.

    The terminal error is recorded on the handle *before* clients are
    woken, so a shutdown can never surface as a bare timeout: a reader
    either times out (and may retry) or observes the real terminal
    state — never a timeout that silently means "cancelled".
    """

    def __init__(self, request: Request, tap: Optional[Tap] = None):
        self._request = request
        self._stream: "queue_mod.Queue" = queue_mod.Queue()
        self._done = threading.Event()
        self._error: Optional[BaseException] = None
        # server-side observer (fleet plumbing): installed at
        # construction so no event can slip past it — a fast worker
        # may deliver before submit() even returns
        self._tap = tap

    # ------------------------------------------------------- server side
    def _deliver(self, token: int, finished: bool) -> None:
        self._stream.put(int(token))
        if finished:
            self._stream.put(_SENTINEL)
            self._done.set()
        if self._tap is not None:
            self._tap(int(token), bool(finished), None)

    def _fail(self, error: BaseException) -> None:
        """Terminal failure: record the cause, then wake clients."""
        self._error = error
        self._stream.put(_SENTINEL)
        self._done.set()
        if self._tap is not None:
            self._tap(None, True, error)

    def _cancel(self) -> None:
        self._fail(ServerClosed(
            "server shut down before the request finished"))

    # ------------------------------------------------------- client side
    def stream(self, timeout: Optional[float] = None):
        """Yield tokens as they are produced; ends at eos/budget.

        ``TimeoutError`` means *no token yet* — retryable, resume with
        another ``stream()``/``result()`` call; :class:`RequestFailed`
        and :class:`ServerClosed` are terminal (class docstring has the
        full contract).
        """
        while True:
            try:
                item = self._stream.get(timeout=timeout)
            except queue_mod.Empty:
                raise TimeoutError(
                    f"no token within {timeout}s (request still "
                    f"live — retryable)") from None
            if item is _SENTINEL:
                if self._error is not None:
                    raise self._error
                return
            yield item

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Block until finished; returns every produced token.  Same
        error contract as :meth:`stream`: ``TimeoutError`` is
        retryable ("still decoding"), :class:`RequestFailed` /
        :class:`ServerClosed` are terminal."""
        if not self._done.wait(timeout):
            raise TimeoutError(
                "request still decoding (retryable)")
        if self._error is not None:
            raise self._error
        return list(self._request.tokens)

    @property
    def done(self) -> bool:
        return self._done.is_set()

    @property
    def error(self) -> Optional[BaseException]:
        """The terminal error, or ``None`` (also ``None`` while live)."""
        return self._error

    @property
    def tokens_so_far(self) -> List[int]:
        return list(self._request.tokens)


class InferenceServer:
    """Continuous-batching inference server over one model.

    ``submit`` blocks (bounded backpressure) while the queue is full —
    pass ``block=False`` to get :class:`QueueFull` immediately.
    ``shutdown(wait=True)`` serves everything already accepted, then
    stops; ``wait=False`` cancels queued AND in-flight requests (their
    handles raise :class:`ServerClosed`).

    Failure semantics (docs/resilience.md): a retryable
    :class:`~apex_tpu.resilience.faults.TransientError` during a step
    poisons only the slots it names (all active slots when it names
    none) — those tenants are evicted and requeued ONCE, continuing
    from their already-streamed prefix; a second fault (or an
    unresumable continuation) fails just that request with
    :class:`RequestFailed`.  Per-request deadlines are enforced both in
    the queue and mid-decode.  Every accepted request therefore ends in
    exactly one of: tokens delivered to completion, ``RequestFailed``,
    or ``ServerClosed`` — never silently lost, never hung.  Anything
    non-transient still kills the worker and cancels all clients (the
    engine's device state cannot be trusted after an arbitrary
    failure).
    """

    def __init__(self, model, params, *, max_slots: int = 4,
                 prompt_buckets: Optional[Sequence[int]] = None,
                 prefill_chunk: int = 0, queue_capacity: int = 64,
                 metrics: Optional[MetricsWriter] = None,
                 metrics_interval: int = 32,
                 kv_cache: str = "dense", block_size: int = 0,
                 pool_tokens: Optional[int] = None,
                 admit_headroom: Optional[int] = None,
                 share_prefixes: bool = False,
                 spec_tokens: int = 0, spec_ngram: int = 3,
                 kv_dtype: Optional[str] = None,
                 tp: int = 0, mesh: Optional[Any] = None):
        if kv_cache == "paged":
            if prompt_buckets is not None:
                raise ValueError(
                    "prompt_buckets only applies to kv_cache='dense' "
                    "— chunked prefill admits any prompt length; "
                    "tune prefill_chunk (step width) and pool_tokens "
                    "instead")
            if tp and mesh is not None:
                # mesh may be a Mesh or an int (the engine accepts
                # both); either way its tensor width must agree with
                # an explicit tp
                mesh_tp = (mesh if isinstance(mesh, int)
                           else dict(mesh.shape).get(TENSOR_AXIS, 1))
                if mesh_tp != tp:
                    raise ValueError(
                        f"tp={tp} disagrees with mesh "
                        f"({TENSOR_AXIS} axis {mesh_tp}) — pass one "
                        f"or make them match")
            # chunked prefill needs a chunk width; 0 (the dense
            # single-call convention) maps to the engine default
            self.engine: Any = PagedEngine(
                model, params, max_slots=max_slots,
                block_size=block_size, pool_tokens=pool_tokens,
                prefill_chunk=prefill_chunk or 32,
                admit_headroom=admit_headroom,
                share_prefixes=share_prefixes,
                spec_tokens=spec_tokens, spec_ngram=spec_ngram,
                kv_dtype=kv_dtype,
                mesh=(mesh if mesh is not None
                      else (tp if tp and tp > 1 else None)))
        elif kv_cache == "dense":
            if share_prefixes or spec_tokens:
                raise ValueError(
                    "share_prefixes / spec_tokens require "
                    "kv_cache='paged' — the dense slab has no page "
                    "pool to share and no mixed multi-token step to "
                    "verify drafts in")
            if (tp and tp > 1) or mesh is not None:
                raise ValueError(
                    "tp / mesh require kv_cache='paged' — "
                    "tensor-parallel serving shards the paged pool "
                    "on its kv_heads axis (and the matmuls over the "
                    "GSPMD layers); the dense slab engine is "
                    "single-chip")
            if kv_dtype is not None:
                raise ValueError(
                    "kv_dtype requires kv_cache='paged' — quantized "
                    "KV pages live in the paged pool (per-page "
                    "scales beside the block table); the dense slab "
                    "stores K/V in the model's compute dtype")
            self.engine = Engine(
                model, params, max_slots=max_slots,
                prompt_buckets=(DEFAULT_BUCKETS if prompt_buckets
                                is None else prompt_buckets),
                prefill_chunk=prefill_chunk)
        else:
            raise ValueError(
                f"kv_cache={kv_cache!r} not in ('dense', 'paged')")
        self.scheduler = Scheduler(self.engine,
                                   queue_capacity=queue_capacity)
        self.metrics = metrics
        self.metrics_interval = max(1, int(metrics_interval))
        # identity-keyed handle registry: client threads setitem/pop,
        # the worker get/pops — every touch is one GIL-atomic dict op,
        # it is never iterated, and keys are unique per request
        # graftlint: unguarded(single atomic dict ops per touch, identity keys, never iterated)
        self._handles: dict = {}          # uid -> RequestHandle
        self._wakeup = threading.Condition()
        self._stop = False  # graftlint: guarded-by(_wakeup)
        self._drain_on_stop = True
        self._draining = False
        self._drain_evicted = 0
        self._started_at: Optional[float] = None
        self._thread: Optional[threading.Thread] = None
        self._steps = 0
        self._step_attempts = 0
        self._tokens_emitted = 0
        self._window_tokens = 0
        self._window_t0: Optional[float] = None
        self._last_emit_step = -1
        self._requeues = 0
        self._failed_requests = 0
        self._deadline_expired = 0
        # latency telemetry: time-to-first-token per request and
        # per-step decode wall time, bounded reservoirs (p50/p99 ride
        # every metrics emission and the soak summary).  The worker
        # appends while any thread (fleet supervisor SLO probes,
        # clients) snapshots — iterating a deque during an append
        # raises RuntimeError, so both sides hold _lat_lock (the
        # pre-existing race graftlint's concurrency pass flagged)
        self._lat_lock = threading.Lock()
        self._ttft: deque = deque(maxlen=2048)  # graftlint: guarded-by(_lat_lock)
        self._step_times: deque = deque(maxlen=4096)  # graftlint: guarded-by(_lat_lock)
        #: the exception that killed the worker loop, if any — clients
        #: see ServerClosed; the root cause lives here for post-mortems.
        #: Published under _wakeup together with the _stop flip, so a
        #: reader that saw _stop also sees the cause
        self.error: Optional[BaseException] = None  # graftlint: guarded-by(_wakeup)

    # ---------------------------------------------------------- lifecycle
    def start(self, *, warmup: bool = True) -> "InferenceServer":
        if self._thread is not None:
            raise RuntimeError("server already started")
        if warmup:
            self.engine.warmup()
        self._started_at = time.monotonic()
        self._thread = threading.Thread(
            target=self._serve, name="apex-tpu-serving", daemon=True)
        self._thread.start()
        return self

    def shutdown(self, *, wait: bool = True,
                 timeout: Optional[float] = None) -> None:
        if self._thread is None:
            return
        with self._wakeup:
            self._stop = True
            self._drain_on_stop = wait
            self._wakeup.notify_all()
        self._thread.join(timeout)
        self._thread = None

    def begin_drain(self) -> None:
        """Graceful drain, phase 1: stop admitting and evict every
        queued/in-flight request at the next step boundary, failing
        each handle with :class:`ReplicaDraining` so a fleet router
        can migrate it (``prompt ++ streamed tokens`` onto a
        survivor).  The engine releases every slot through the normal
        compiled ``release`` — a paged pool returns to
        ``blocks_in_use == 0`` — and the worker then idles until
        :meth:`shutdown`.  Without a router on top, clients simply
        observe :class:`ServerClosed` (its base class)."""
        with self._wakeup:
            self._draining = True
            self._wakeup.notify_all()

    @property
    def draining(self) -> bool:
        """True after :meth:`begin_drain` — also in :meth:`health`."""
        return self._draining

    def kill(self, error: Optional[BaseException] = None) -> None:
        """SIGKILL-equivalent death for chaos drills (the
        ``replica.kill`` fault site routes here): the worker stops
        WITHOUT draining and WITHOUT releasing engine state — a real
        SIGKILL takes the host's device memory with it — so a paged
        pool's accounting is abandoned mid-flight (``blocks_in_use``
        stays nonzero; the replica is dead, not reusable).  Every
        queued and in-flight handle fails with :class:`ServerClosed`;
        a :class:`~apex_tpu.serving.fleet.FleetRouter` migrates them
        onto survivors.  Idempotent; a no-op on a server with no live
        worker (never started, or already shut down cleanly) — there
        is nothing to kill, and fabricating an ``error`` there would
        make ``health()`` report a failure that never happened."""
        with self._wakeup:
            thread = self._thread
            if thread is None:
                return
            if self.error is None:
                self.error = error if error is not None \
                    else RuntimeError("replica killed (chaos drill)")
            self._stop = True
            self._drain_on_stop = False
            self._wakeup.notify_all()
        thread.join()
        self._thread = None

    def __enter__(self) -> "InferenceServer":
        return self.start()

    def __exit__(self, exc_type, exc, tb) -> None:
        # propagate client-side errors without hanging on a full drain
        self.shutdown(wait=exc_type is None)

    # ------------------------------------------------------------- intake
    def submit(self, prompt, *, max_new_tokens: int,
               temperature: float = 0.0, top_k: Optional[int] = None,
               top_p: Optional[float] = None,
               eos_id: Optional[int] = None, seed: int = 0,
               deadline: Optional[float] = None,
               block: bool = True,
               timeout: Optional[float] = None,
               tap: Optional[Tap] = None) -> RequestHandle:
        """Enqueue one request; returns its :class:`RequestHandle`.

        ``deadline`` (seconds from acceptance) bounds the request's
        total latency: once expired — whether still queued or
        mid-decode — it fails with :class:`RequestFailed` and its slot
        is freed.  ``timeout`` bounds only this *submission* under
        backpressure (distinct from the deadline).  ``tap`` is fleet
        plumbing: a server-side observer of the handle's events (see
        :data:`Tap`), installed before the request can produce any —
        :class:`~apex_tpu.serving.fleet.FleetRouter` uses it to mirror
        streams and catch migration signals.
        """
        request = Request(
            prompt=np.asarray(prompt, np.int32).reshape(-1),
            max_new_tokens=int(max_new_tokens),
            temperature=float(temperature),
            top_k=top_k, top_p=top_p, eos_id=eos_id, seed=int(seed),
            deadline=None if deadline is None else float(deadline))
        # the handle must be reachable by the worker BEFORE the request
        # enters the queue: run_step doesn't take _wakeup, so a fast
        # worker can admit — even finish — a one-token request between
        # the enqueue and any later registration, and its events would
        # be dropped.  Keyed by object identity (stable pre-enqueue;
        # uid is only assigned inside scheduler.submit).
        handle = RequestHandle(request, tap=tap)
        self._handles[id(request)] = handle
        # distinct from the per-request `deadline`: this bounds only
        # the backpressure wait of THIS submit call
        submit_deadline = None if timeout is None \
            else time.monotonic() + timeout
        try:
            while True:
                with self._wakeup:
                    if self._stop or self._thread is None:
                        raise ServerClosed("server is not running")
                    if self._draining:
                        raise ServerClosed(
                            "server is draining (not admitting)")
                    try:
                        self.scheduler.submit(request)
                        self._wakeup.notify_all()
                        return handle
                    except QueueFull:
                        if not block:
                            raise
                        remaining = None if submit_deadline is None \
                            else submit_deadline - time.monotonic()
                        if remaining is not None and remaining <= 0:
                            raise
                        # woken by the worker after each admission wave
                        self._wakeup.wait(
                            0.05 if remaining is None
                            else min(0.05, remaining))
        except BaseException:
            self._handles.pop(id(request), None)
            raise

    # ------------------------------------------------------------- worker
    def _serve(self) -> None:  # graftlint: thread-entry(serving-worker)
        try:
            while True:
                with self._wakeup:
                    while (not self.scheduler.has_work()
                           and not self._stop):
                        self._wakeup.wait(0.1)
                    if self._stop and (not self._drain_on_stop
                                       or not self.scheduler.has_work()):
                        break
                if self._draining:
                    self._drain_out()
                    continue            # idle until shutdown()
                self._expire_deadlines()
                if not self.scheduler.has_work():
                    continue                # everything just expired
                try:
                    # injected against the ATTEMPT counter, not
                    # self._steps: a faulted attempt doesn't advance
                    # the step count, and a step-pinned fault keyed on
                    # it would re-fire forever and starve recovery
                    attempt = self._step_attempts
                    self._step_attempts += 1
                    faults.inject("serving.step", step=attempt)
                    t_step0 = time.monotonic()
                    events = self.scheduler.run_step()
                    with self._lat_lock:
                        self._step_times.append(
                            time.monotonic() - t_step0)
                except faults.TransientError as exc:
                    # a retryable step fault: the raiser guarantees
                    # engine state is intact (host-side failure, raised
                    # before dispatch), so recovery is slot-local —
                    # evict the poisoned tenants, requeue each once
                    self._recover_step(exc)
                    with self._wakeup:
                        self._wakeup.notify_all()
                    continue
                for req, exc in self.scheduler.take_admit_failures():
                    failure = RequestFailed(
                        f"admission failed twice for request "
                        f"{req.uid}: {exc}")
                    failure.__cause__ = exc
                    self._fail_request(req, failure)
                self._steps += 1
                now = time.monotonic()
                if self._window_t0 is None:
                    self._window_t0 = now
                for ev in events:
                    self._tokens_emitted += 1
                    self._window_tokens += 1
                    if len(ev.request.tokens) == 1:
                        # first token of this request (requeued
                        # continuations keep their prefix, so this
                        # fires exactly once per request)
                        with self._lat_lock:
                            self._ttft.append(
                                now - ev.request.accepted_at)
                    handle = self._handles.get(id(ev.request))
                    if handle is not None:
                        handle._deliver(ev.token, ev.finished)
                        if ev.finished:
                            self._handles.pop(id(ev.request), None)
                with self._wakeup:
                    self._wakeup.notify_all()   # queue space freed
                if self.metrics is not None \
                        and self._steps % self.metrics_interval == 0:
                    self._emit_metrics(now)
        except BaseException as exc:    # noqa: BLE001 — any engine
            # failure (RetraceError, OOM, ...) must not strand clients:
            # record it, flip _stop so submit()/blocking waiters see a
            # closed server, and fall through to the cancel path below.
            # Both published under _wakeup: a reader that observed the
            # stop flag must also observe its cause
            with self._wakeup:
                self.error = exc
                self._stop = True
                self._wakeup.notify_all()
        finally:
            with self._wakeup:
                error = self.error
            # cancel every leftover queued/in-flight handle (normal
            # wait=False shutdown reaches here too; after a full drain
            # there is simply nothing left to cancel)
            for req in self.scheduler.cancel_queued():
                handle = self._handles.pop(id(req), None)
                if handle is not None:
                    handle._cancel()
            for slot, req in enumerate(self.scheduler._slots):
                if req is None:
                    continue
                if error is None:
                    self.engine.release(slot)
                self.scheduler._slots[slot] = None
                handle = self._handles.pop(id(req), None)
                if handle is not None:
                    handle._cancel()
            if self.metrics is not None \
                    and self._steps != self._last_emit_step:
                self._emit_metrics(time.monotonic())

    def _drain_out(self) -> None:
        """Evict everything for :meth:`begin_drain` (worker thread):
        queued requests are cancelled, active tenants evicted with
        their engine slots released (pages go home), and every handle
        fails with :class:`ReplicaDraining` — the router-visible
        migrate signal.  Not counted as request failures: drain is
        scheduling, not loss."""
        dropped = self.scheduler.cancel_queued()
        dropped += self.scheduler.evict_all()
        for req in dropped:
            self._drain_evicted += 1
            counters.inc("serving.drain_evict")
            handle = self._handles.pop(id(req), None)
            if handle is not None:
                handle._fail(ReplicaDraining(
                    f"request {req.uid} evicted by graceful drain "
                    f"after {len(req.tokens)} streamed tokens"))
        if dropped:
            with self._wakeup:
                self._wakeup.notify_all()

    # ----------------------------------------------------- fault recovery
    def _fail_request(self, req: Request,
                      failure: RequestFailed) -> None:
        """Route a terminal per-request failure to its handle."""
        self._failed_requests += 1
        counters.inc("serving.request_failed")
        handle = self._handles.pop(id(req), None)
        if handle is not None:
            handle._fail(failure)

    def _recover_step(self, exc: "faults.TransientError") -> None:
        """Evict the poisoned slots; requeue each tenant once.

        ``exc.slots`` names the poisoned slots when attribution exists;
        with none, every active slot is suspect (the fault fired before
        any of them stepped).  A tenant already requeued once — or one
        whose continuation no longer fits a bucket — fails terminally
        with :class:`RequestFailed`; the server itself keeps serving.
        """
        counters.inc("serving.step_fault")
        poisoned = getattr(exc, "slots", None)
        for slot, req in enumerate(list(self.scheduler._slots)):
            if req is None:
                continue
            if poisoned is not None and slot not in poisoned:
                continue
            self.scheduler.evict(slot)
            cause: BaseException = exc
            if req.retries < 1:
                req.retries += 1
                try:
                    self.scheduler.requeue(req)
                    self._requeues += 1
                    counters.inc("serving.requeue")
                    continue
                except ValueError as ve:    # unresumable continuation
                    cause = ve
            failure = RequestFailed(
                f"request {req.uid} evicted by a step fault and not "
                f"requeueable (retries={req.retries}): {cause}")
            failure.__cause__ = cause
            self._fail_request(req, failure)

    def _expire_deadlines(self) -> None:
        """Fail queued AND in-flight requests past their deadline."""
        now = time.monotonic()
        for req in self.scheduler.expire_queued(now):
            self._deadline_expired += 1
            counters.inc("serving.deadline_expired")
            self._fail_request(req, RequestFailed(
                f"request {req.uid} deadline ({req.deadline}s) "
                f"expired in queue"))
        for slot, req in enumerate(list(self.scheduler._slots)):
            if req is None or req.deadline is None:
                continue
            if now - req.accepted_at > req.deadline:
                self.scheduler.evict(slot)
                self._deadline_expired += 1
                counters.inc("serving.deadline_expired")
                self._fail_request(req, RequestFailed(
                    f"request {req.uid} deadline ({req.deadline}s) "
                    f"expired after {len(req.tokens)} tokens"))

    def latency_summary(self) -> Dict[str, float]:
        """p50/p99 of time-to-first-token and per-step decode latency
        over the bounded reservoirs (seconds / milliseconds) — the
        soak-summary numbers; also folded into every metrics
        emission."""
        # snapshot under _lat_lock: the worker thread appends
        # concurrently, and iterating a deque during an append raises
        # RuntimeError — list(deque) iterates too, so the snapshot
        # itself must exclude the appender, not just downstream use
        with self._lat_lock:
            ttft = list(self._ttft)
            step_times = list(self._step_times)
        out: Dict[str, float] = {}
        out.update(percentile_summary(
            ttft, "ttft_p50_s", "ttft_p99_s"))
        out.update(percentile_summary(
            step_times, "step_ms_p50", "step_ms_p99", scale=1e3))
        return out

    def _emit_metrics(self, now: float) -> None:
        dt = max(now - (self._window_t0 or now), 1e-9)
        chips = int(getattr(self.engine, "chips_per_replica", 1))
        payload = {
            "tokens_per_sec": self._window_tokens / dt,
            # the Gemma-paper serving protocol reports throughput PER
            # CHIP — a tensor-parallel replica (chips > 1) divides by
            # its mesh width so 1×M and M×1 deployments compare at
            # equal chip count
            "tokens_per_sec_per_chip": self._window_tokens / dt / chips,
            "chips_per_replica": chips,
            "occupancy": self.scheduler.occupancy,
            "queue_depth": self.scheduler.queue_depth,
            "tokens_total": self._tokens_emitted,
            "requeues": self._requeues,
            "failed_requests": self._failed_requests,
            "deadline_expired": self._deadline_expired,
            "preempts": self.scheduler.preempts,
        }
        payload.update(self.latency_summary())
        blocks_total = getattr(self.engine, "blocks_total", None)
        if blocks_total:
            # pool occupancy gauge (paged engine): the overcommit dial
            payload["blocks_in_use"] = self.engine.blocks_in_use
            payload["blocks_total"] = blocks_total
            payload["live_tokens"] = self.engine.live_tokens
            # prefix-sharing gauges (0 when off); the accept rate only
            # when drafting is configured — a fleet-mean over
            # spec-disabled replicas' hardwired 0.0 would dilute it
            payload["shared_blocks"] = self.engine.shared_blocks
            payload["cow_forks"] = self.engine.cow_forks
            # pool storage width (8 = quantized int8/fp8 pages) —
            # numeric so any sink can plot/aggregate it; the dtype
            # NAME rides health()
            payload["kv_bits"] = self.engine.kv_bits
            if getattr(self.engine, "spec_tokens", 0):
                payload["spec_accept_rate"] = \
                    self.engine.spec_accept_rate
        self.metrics(self._steps, payload)
        self.metrics.drain()
        self._last_emit_step = self._steps
        self._window_tokens = 0
        self._window_t0 = now

    # ------------------------------------------------------------- health
    def health(self) -> Dict[str, Any]:
        """Readiness/liveness probe (cheap; any thread).

        ``status`` is ``"serving"`` (worker alive, accepting),
        ``"stopped"`` (never started, shut down, or stopping), or
        ``"failed"`` (worker died — root cause in ``error``);
        ``ready`` is the single boolean a load balancer should gate on
        — a *draining* replica stays ``status="serving"`` but reports
        ``ready=False`` (and ``draining=True``) so routers stop
        admitting without treating it as a failure.  ``uptime_s`` is
        seconds since :meth:`start`.  Counter fields make the probe
        double as the chaos-soak scoreboard: accepted == completed +
        failed when nothing is lost.  The full field table lives in
        ``docs/serving.md``.
        """
        now = time.monotonic()
        with self._wakeup:
            alive = self._thread is not None and self._thread.is_alive()
            stopping = self._stop
            draining = self._draining
            error = self.error
        if error is not None:
            status = "failed"
        elif not alive or stopping:
            status = "stopped"
        else:
            status = "serving"
        out = {
            "status": status,
            "ready": status == "serving" and not draining,
            "draining": draining,
            "uptime_s": (0.0 if self._started_at is None
                         else now - self._started_at),
            "steps": self._steps,
            "queue_depth": self.scheduler.queue_depth,
            "occupancy": self.scheduler.occupancy,
            "tokens_emitted": self._tokens_emitted,
            "requeues": self._requeues,
            "failed_requests": self._failed_requests,
            "deadline_expired": self._deadline_expired,
            "drain_evicted": self._drain_evicted,
            "preempts": self.scheduler.preempts,
            "error": None if error is None else repr(error),
            # chips this ONE replica spans (tensor-parallel paged
            # engine; 1 everywhere else) — the fleet's capacity math
            # and the per-chip throughput protocol both read it
            "chips_per_replica": int(
                getattr(self.engine, "chips_per_replica", 1)),
        }
        mesh_shape = getattr(self.engine, "mesh_shape", None)
        if mesh_shape:
            out["mesh_shape"] = mesh_shape
        blocks_total = getattr(self.engine, "blocks_total", None)
        if blocks_total:
            out["blocks_in_use"] = self.engine.blocks_in_use
            out["blocks_total"] = blocks_total
            out["live_tokens"] = self.engine.live_tokens
            out["shared_blocks"] = self.engine.shared_blocks
            out["cow_forks"] = self.engine.cow_forks
            out["kv_dtype"] = self.engine.kv_dtype
            out["kv_bits"] = self.engine.kv_bits
            if getattr(self.engine, "spec_tokens", 0):
                out["spec_accept_rate"] = self.engine.spec_accept_rate
        return out

    def prefix_hit_blocks(self, prompt) -> int:
        """Pages of ``prompt``'s prefix already resident in this
        server's trie (0 for dense engines or with sharing off) — the
        fleet router's prefix-affinity key."""
        fn = getattr(self.engine, "prefix_hit_blocks", None)
        return 0 if fn is None else int(fn(prompt))

    # ---------------------------------------------------------- telemetry
    @property
    def steps(self) -> int:
        return self._steps

    @property
    def tokens_emitted(self) -> int:
        return self._tokens_emitted
