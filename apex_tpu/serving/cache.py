"""Slotted KV-cache pool — the static-shape substrate of the engine.

Continuous batching needs per-sequence cache state (each tenant sits at
its own decode position), but TPU-friendly programs need *one* set of
shapes for the process lifetime.  The resolution: the model's per-slot
decode cache (the ``init_cache`` pytree at batch=1) is stacked along a
new leading **slot** axis into a ``(max_slots, ...)`` pool, and every
mutation is a functional scatter at a *traced* slot index — admission
overwrites one slot row, eviction zeroes it, decode advances all rows
together.  Shapes never change: one compiled executable serves any mix
of tenants.

Per-slot scalar bookkeeping (active mask, next token, produced count,
token budget, sampling params, rng key) lives in :class:`SlotState` —
plain ``(max_slots,)`` device arrays carried through the jitted step,
NOT static jit arguments, so heterogeneous sampling configs share one
executable (the ISSUE 2 tentpole contract).

Only the **dense** cache layout is supported: the rolling ring-buffer
cache of sliding-window models keys visibility off per-slot positions,
which the engine's rewind-on-admit trick (see
:func:`rewind_index_leaves`) cannot restate; :func:`validate_cache_tree`
rejects it loudly.
"""

from __future__ import annotations

import hashlib
from typing import Any, Dict, List, NamedTuple

import jax
import jax.numpy as jnp

import numpy as np

__all__ = [
    "SlotState",
    "init_slot_state",
    "validate_cache_tree",
    "stacked_zeros",
    "zeros_from_shapes",
    "write_slot",
    "reset_slot",
    "rewind_index_leaves",
    "BlockAllocator",
    "BlockExhausted",
    "blocks_for",
    "set_paged_leaves",
    "PrefixTrie",
    "chain_digests",
]

# cache leaves that hold *positions* rather than keys/values: the
# per-layer attention write cursor and (learned-position models) the
# model-level position cursor.  rewind_index_leaves targets these.
_INDEX_LEAF_NAMES = ("cache_index", "position_index")

# ring-buffer-only leaf: its presence marks a sliding-window cache
_RING_LEAF = "slot_positions"


def _leaf_name(path) -> str:
    """Last key of a tree path (DictKey / GetAttrKey / SequenceKey)."""
    last = path[-1]
    for attr in ("key", "name", "idx"):
        val = getattr(last, attr, None)
        if val is not None:
            return str(val)
    return str(last)


def validate_cache_tree(shapes: Any) -> None:
    """Reject cache structures the slot pool cannot manage.

    ``shapes``: the per-slot cache as ShapeDtypeStructs (from
    ``apex_tpu.models.generate.cache_shapes(model, 1)``).  Raises
    ``ValueError`` for ring-buffer (sliding-window) caches.
    """
    leaves = jax.tree_util.tree_flatten_with_path(shapes)[0]
    for path, _leaf in leaves:
        if _leaf_name(path) == _RING_LEAF:
            raise ValueError(
                "the serving engine requires the dense KV-cache layout; "
                "this model uses the sliding-window ring-buffer cache "
                f"(found a {_RING_LEAF!r} leaf).  Serve sliding-window "
                "models with sliding_window=None (or >= max_seq_len) — "
                "the dense cache computes the same function whenever "
                "sequences stay within the window")


def stacked_zeros(shapes: Any, max_slots: int) -> Any:
    """All-zero slot pool: each per-slot leaf gains a leading
    ``(max_slots,)`` axis.  Zeros ARE the initialized cache (the
    ``init_cache`` zeros-from-shape invariant)."""
    return jax.tree.map(
        lambda s: jnp.zeros((max_slots,) + tuple(s.shape), s.dtype),
        shapes)


def zeros_from_shapes(shapes: Any) -> Any:
    """One slot's fresh zero cache (used inside the jitted prefill)."""
    return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), shapes)


def write_slot(pool: Any, slot, one: Any) -> Any:
    """Scatter a per-slot cache into row ``slot`` of the pool
    (traceable; ``slot`` is a traced scalar, so admission into any slot
    replays one compiled executable)."""
    return jax.tree.map(lambda big, small: big.at[slot].set(small),
                        pool, one)


def reset_slot(pool: Any, slot) -> Any:
    """Zero row ``slot`` (eviction hygiene: stale K/V never outlives
    its tenant, even though admission fully overwrites the row)."""
    return jax.tree.map(
        lambda big: big.at[slot].set(jnp.zeros_like(big[slot])), pool)


def rewind_index_leaves(cache: Any, position) -> Any:
    """Set every index leaf (``cache_index`` / ``position_index``) to
    ``position``, leaving K/V leaves untouched.

    The admission trick: a prompt right-padded to its bucket prefills
    positions ``[0, bucket)``; rewinding the cursors to
    ``true_len - 1`` makes the next decode step re-feed the last real
    prompt token at its true position.  Pad K/V beyond the cursor is
    invisible — cache attention masks positions ``> index``, and every
    later token overwrites its slot before attending — so the padded
    prefill computes exactly the unpadded function.
    """
    pos = jnp.asarray(position, jnp.int32)

    def fix(path, leaf):
        if _leaf_name(path) in _INDEX_LEAF_NAMES:
            return jnp.full(leaf.shape, pos, leaf.dtype)
        return leaf

    return jax.tree_util.tree_map_with_path(fix, cache)


class SlotState(NamedTuple):
    """Per-slot device state — ``(max_slots,)`` arrays, one pytree.

    Sampling params ride here as DEVICE ARRAYS (not static jit args):
    a slot decoding greedily and a slot sampling at ``temperature=1.2,
    top_k=40, top_p=0.9`` run in the same compiled step.  Conventions:
    ``top_k == 0`` disables truncation, ``top_p <= 0`` (or ``>= 1``)
    disables the nucleus filter, ``eos_id == -1`` disables eos
    stopping, and ``rng`` is a per-slot PRNG key so a request's sampled
    tokens are a function of its own seed, independent of co-tenants.
    """

    active: jax.Array        # bool  — slot occupied
    tok: jax.Array           # int32 — next token to feed
    produced: jax.Array      # int32 — tokens produced so far
    budget: jax.Array        # int32 — max_new_tokens for the tenant
    temperature: jax.Array   # float32
    top_k: jax.Array         # int32 — 0 = disabled
    top_p: jax.Array         # float32 — <= 0 or >= 1 = disabled
    eos_id: jax.Array        # int32 — -1 = disabled
    rng: jax.Array           # uint32 (max_slots, 2) — per-slot key


def init_slot_state(max_slots: int) -> SlotState:
    """All-free slot state (inactive slots decode garbage that is
    ignored on the host and overwritten at admission)."""
    z = lambda dt: jnp.zeros((max_slots,), dt)   # noqa: E731
    return SlotState(
        active=z(bool),
        tok=z(jnp.int32),
        produced=z(jnp.int32),
        budget=jnp.ones((max_slots,), jnp.int32),
        temperature=z(jnp.float32),
        top_k=z(jnp.int32),
        top_p=z(jnp.float32),
        eos_id=jnp.full((max_slots,), -1, jnp.int32),
        rng=jnp.zeros((max_slots, 2), jnp.uint32),
    )


def admit_slot(state: SlotState, slot, tok, budget, temperature,
               top_k, top_p, eos_id, seed) -> SlotState:
    """Functional admission of one tenant into ``slot`` (traceable).

    ``seed`` derives the slot's private PRNG key inside the trace, so
    admission stays a single compiled executable for any seed.
    """
    key = jax.random.PRNGKey(seed)
    if key.dtype != jnp.uint32:      # typed-key jax: store the raw bits
        key = jax.random.key_data(key)
    return state._replace(
        active=state.active.at[slot].set(True),
        tok=state.tok.at[slot].set(tok),
        produced=state.produced.at[slot].set(0),
        budget=state.budget.at[slot].set(budget),
        temperature=state.temperature.at[slot].set(temperature),
        top_k=state.top_k.at[slot].set(top_k),
        top_p=state.top_p.at[slot].set(top_p),
        eos_id=state.eos_id.at[slot].set(eos_id),
        rng=state.rng.at[slot].set(key.astype(jnp.uint32)),
    )


def release_slot(state: SlotState, slot) -> SlotState:
    """Mark ``slot`` free (traceable)."""
    return state._replace(active=state.active.at[slot].set(False))


__all__ += ["admit_slot", "release_slot"]


# --------------------------------------------------------------------- #
# paged KV-cache: host-side block pool + device-leaf plumbing
# --------------------------------------------------------------------- #
# leaves of the PAGED cache tree the engine overwrites every step from
# its host allocator (block_tables/cursors per layer; position_index at
# the model level for learned-position models)
_TABLE_LEAF = "block_tables"
_CURSOR_LEAVES = ("cursors", "position_index")
_CHUNK_LENS_LEAF = "chunk_lens"


class BlockExhausted(RuntimeError):
    """The paged KV pool has no free blocks left.

    Raised by :meth:`BlockAllocator.alloc`; the engine's step loop
    catches it and preempts a tenant (whose requeue continues from its
    streamed prefix) instead of failing the step.
    """


def blocks_for(tokens: int, block_size: int) -> int:
    """Pages needed to hold ``tokens`` cache positions."""
    return -(-int(tokens) // int(block_size))


class BlockAllocator:
    """Host-side refcounted free list over the physical page pool.

    The pool is sized in TOKENS (``num_blocks × block_size``), shared
    by every tenant — the paged tentpole's replacement for the dense
    ``max_slots × max_seq_len`` reservation.  Physical block 0 is the
    reserved **null page**: unallocated block-table entries point at
    it, pad-token writes land in it, and the position mask keeps its
    contents unreachable — so it is never handed out.

    The allocator counts PAGES and is storage-dtype-agnostic: under a
    quantized pool (``kv_dtype="int8"``/``"fp8"``, ISSUE 8) the same
    page index addresses 1-byte K/V codes plus one fp32 amax scale per
    (kv_head, page) riding the cache beside the block table — a page's
    scale travels with it through sharing, CoW forks, preemption and
    reuse (the write path resets it at the page's first write), so
    nothing below this line changes; only how many tokens the same HBM
    buys does.

    Pages carry a **refcount** (the prefix-sharing substrate, ISSUE 7):
    :meth:`alloc` hands out pages at refcount 1, :meth:`incref` lets a
    second tenant reference the same physical page (a shared read-only
    prompt-prefix block), and :meth:`free` *decrements* — a page
    returns to the free list only when its last reference drops, so a
    hot system prompt's KV is charged to the pool once no matter how
    many tenants map it.  ``blocks_in_use`` stays EXACT under sharing:
    it counts physical pages, never logical references.

    Not thread-safe: the engine-owning thread is the only caller (the
    same single-writer discipline as the engine itself).
    """

    def __init__(self, num_blocks: int, block_size: int):
        if block_size < 1:
            raise ValueError(
                f"block_size must be >= 1, got {block_size}")
        if num_blocks < 2:
            raise ValueError(
                "num_blocks must be >= 2 (block 0 is the reserved "
                f"null page), got {num_blocks}")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        # LIFO free stack: blocks freed together are reused together
        # (keeps a tenant's pages warm in any downstream cache level)
        self._free: List[int] = list(range(num_blocks - 1, 0, -1))
        #: live refcounts — only allocated pages have an entry
        self._refs: Dict[int, int] = {}

    @property
    def blocks_total(self) -> int:
        """Allocatable pages (the null page is not allocatable)."""
        return self.num_blocks - 1

    @property
    def blocks_free(self) -> int:
        return len(self._free)

    @property
    def blocks_in_use(self) -> int:
        return self.blocks_total - len(self._free)

    @property
    def tokens_total(self) -> int:
        return self.blocks_total * self.block_size

    @property
    def tokens_free(self) -> int:
        return len(self._free) * self.block_size

    @property
    def shared_blocks(self) -> int:
        """Physical pages currently mapped by MORE than one reference
        — the prefix-sharing win gauge (:attr:`blocks_saved` counts
        the pool pages that sharing reclaims).  Snapshots the refcount
        dict first: health probes read this from other threads while
        the engine thread allocates/frees, and iterating a mutating
        dict raises."""
        return sum(1 for r in list(self._refs.values()) if r > 1)

    @property
    def blocks_saved(self) -> int:
        """Pool pages sharing reclaimed: ``Σ (refcount - 1)`` — the
        pages an unshared pool would additionally burn right now
        (snapshot semantics, as :attr:`shared_blocks`)."""
        return sum(r - 1 for r in list(self._refs.values()) if r > 1)

    def refcount(self, block: int) -> int:
        """Live references to ``block`` (0 = free)."""
        return self._refs.get(int(block), 0)

    def alloc(self, n: int) -> List[int]:
        """Take ``n`` pages (each at refcount 1); raises
        :class:`BlockExhausted` (taking none) when fewer than ``n``
        are free — allocation is atomic so a failed extension never
        leaks partial pages."""
        if n < 0:
            raise ValueError(f"n must be >= 0, got {n}")
        if n > len(self._free):
            raise BlockExhausted(
                f"need {n} blocks, {len(self._free)} free "
                f"(pool: {self.blocks_total} × {self.block_size} tok)")
        taken = self._free[-n:] if n else []
        del self._free[len(self._free) - n:]
        for blk in taken:
            self._refs[blk] = 1
        return taken

    def incref(self, block: int) -> int:
        """Add one reference to a LIVE page (prefix sharing: a new
        tenant maps an existing read-only prompt block).  Returns the
        new refcount; raises on a free/out-of-range page — sharing
        dead KV is a caller bug."""
        blk = int(block)
        if blk not in self._refs:
            raise ValueError(
                f"incref of block {blk} which is not allocated")
        self._refs[blk] += 1
        return self._refs[blk]

    def free(self, blocks) -> List[int]:
        """Drop one reference per page; pages whose LAST reference
        dropped return to the pool and are listed in the return value
        (the engine forgets them from its prefix trie).  Decrementing
        a free page — the old double-free — still raises."""
        freed: List[int] = []
        for blk in blocks:
            blk = int(blk)
            if not 1 <= blk < self.num_blocks:
                raise ValueError(
                    f"block {blk} outside the allocatable range "
                    f"[1, {self.num_blocks})")
            refs = self._refs.get(blk)
            if refs is None:
                raise ValueError(f"double free of block {blk}")
            if refs > 1:
                self._refs[blk] = refs - 1
            else:
                del self._refs[blk]
                self._free.append(blk)
                freed.append(blk)
        return freed


# --------------------------------------------------------------------- #
# prefix trie: block-granular prompt-prefix index over live pages
# --------------------------------------------------------------------- #
def chain_digests(tokens: np.ndarray, block_size: int) -> List[bytes]:
    """Chained content digests of every FULL ``block_size`` block of
    ``tokens``: ``digest_i = sha256(digest_{i-1} || block_i_tokens)``.

    The chaining makes each digest identify the whole prefix up to and
    including its block — two prompts share block ``i`` iff they agree
    on every token of blocks ``0..i`` — so a flat dict over digests IS
    a trie walk.  Content-addressed (sha256 over the raw int32 bytes):
    collisions are cryptographically negligible, so digest equality is
    treated as prefix equality.
    """
    tokens = np.ascontiguousarray(tokens, np.int32)
    out: List[bytes] = []
    digest = b"apex-tpu-prefix-v1"
    for i in range(tokens.size // int(block_size)):
        h = hashlib.sha256(digest)
        h.update(tokens[i * block_size:(i + 1) * block_size].tobytes())
        digest = h.digest()
        out.append(digest)
    return out


class PrefixTrie:
    """Digest → physical page index of LIVE read-only prompt blocks.

    The admission-time half of copy-on-write prefix sharing
    (:class:`~apex_tpu.serving.engine.PagedEngine`): a tenant that
    finishes prefilling a full prompt block :meth:`register`\\ s its
    page under the block's chain digest; a later admission
    :meth:`match`\\ es its own prompt's digests against the trie and
    maps the hit pages instead of recomputing (and re-storing) their
    KV.  Entries are removed by :meth:`forget` when the underlying
    page's last reference drops — the trie only ever points at live
    pool pages, so a hit can always be increfed.
    """

    def __init__(self):
        self._by_digest: Dict[bytes, int] = {}
        self._by_block: Dict[int, bytes] = {}

    def __len__(self) -> int:
        return len(self._by_digest)

    def register(self, digest: bytes, block: int) -> bool:
        """Index ``block`` under ``digest``; first writer wins (a
        concurrent tenant prefilling the same prompt keeps its private
        duplicate unregistered).  Returns whether the entry was
        added."""
        if digest in self._by_digest:
            return False
        block = int(block)
        if block in self._by_block:
            # one physical page per digest AND per block: re-keying a
            # live page would leave a stale digest→block entry behind
            return False
        self._by_digest[digest] = block
        self._by_block[block] = digest
        return True

    def forget(self, block: int) -> None:
        """Drop the entry for a page returning to the free list (a
        no-op for unregistered pages)."""
        digest = self._by_block.pop(int(block), None)
        if digest is not None:
            del self._by_digest[digest]

    def holds_block(self, block: int) -> bool:
        """Whether ``block`` is indexed (and therefore read-only for
        its current owner)."""
        return int(block) in self._by_block

    def match(self, digests: List[bytes]) -> List[int]:
        """Longest-prefix hit: the physical pages for the leading run
        of ``digests`` present in the trie (chain digests make any
        hit's whole prefix a hit too)."""
        pages: List[int] = []
        for digest in digests:
            block = self._by_digest.get(digest)
            if block is None:
                break
            pages.append(block)
        return pages


def _tp_spec_for_leaf(name: str, ndim: int, axis: str):
    """PartitionSpec of one paged-cache leaf under tensor-parallel
    serving: the K/V pool leaves shard their ``kv_heads`` dim (at
    ``ndim - 4`` — the scanned layer stack prepends a layer axis, the
    unrolled form doesn't), the per-(kv_head, page) quant-scale leaves
    shard the same dim at ``ndim - 2``, and EVERYTHING else — block
    tables, cursors, chunk_lens, position_index, the SlotState twin —
    is replicated, which is what keeps the engine's host-side
    allocator / refcount / trie logic mesh-oblivious."""
    import jax.sharding as shd

    if name in ("paged_key", "paged_value"):
        dim = ndim - 4
    elif name in ("key_scales", "value_scales"):
        dim = ndim - 2
    else:
        return shd.PartitionSpec()
    spec = [None] * ndim
    spec[dim] = axis
    return shd.PartitionSpec(*spec)


def paged_pool_shardings(cache: Any, mesh, axis: str) -> Any:
    """``NamedSharding`` tree matching ``cache``: pool/scale leaves
    sharded on kv_heads over ``axis``, the rest replicated (see
    :func:`_tp_spec_for_leaf`)."""
    import jax.sharding as shd

    def f(path, leaf):
        return shd.NamedSharding(
            mesh, _tp_spec_for_leaf(_leaf_name(path),
                                    jnp.ndim(leaf), axis))

    return jax.tree_util.tree_map_with_path(f, cache)


def shard_paged_cache(cache: Any, mesh, axis: str) -> Any:
    """Place a paged cache tree on the replica's mesh (host-side
    ``device_put`` at engine construction)."""
    return jax.device_put(cache, paged_pool_shardings(cache, mesh,
                                                      axis))


def constrain_paged_cache(cache: Any, mesh, axis: str) -> Any:
    """The in-trace twin of :func:`shard_paged_cache`:
    ``with_sharding_constraint`` every leaf to the same placement, so
    the jitted step's OUTPUT cache lands exactly where its input was
    committed — shardings reach a fixed point and the retrace guards
    (budget 1) never see a second signature."""
    return jax.tree.map(jax.lax.with_sharding_constraint, cache,
                        paged_pool_shardings(cache, mesh, axis))


__all__ += ["paged_pool_shardings", "shard_paged_cache",
            "constrain_paged_cache"]


def set_paged_leaves(cache: Any, tables, cursors,
                     chunk_lens=None) -> Any:
    """Overwrite the paged cache tree's ``block_tables`` and cursor
    leaves (``cursors`` / ``position_index``) with the engine's
    host-authoritative values, broadcast to each leaf's shape (the
    scanned layer stack adds a leading layer axis — every layer shares
    one logical→physical mapping because the per-layer pools are
    parallel).  ``chunk_lens`` (per-row REAL lane counts for the
    coming mixed step) overwrites the quantized pool's ``chunk_lens``
    leaf the same way — the write path routes lanes past it to the
    null page so pad-lane amax never reaches a live page scale; pass
    ``None`` to leave the leaf untouched (non-engine callers keep the
    model's every-lane-real default, and unquantized pools have no
    such leaf).  K/V pool leaves — and, under a quantized pool, the
    ``key_scales``/``value_scales`` per-page amax leaves that ride
    beside them — pass through untouched: the model's write path owns
    them.
    """
    tables = jnp.asarray(tables, jnp.int32)
    cursors = jnp.asarray(cursors, jnp.int32)
    if chunk_lens is not None:
        chunk_lens = jnp.asarray(chunk_lens, jnp.int32)

    def fix(path, leaf):
        name = _leaf_name(path)
        if name == _TABLE_LEAF:
            return jnp.broadcast_to(tables, leaf.shape).astype(leaf.dtype)
        if name in _CURSOR_LEAVES:
            return jnp.broadcast_to(cursors, leaf.shape).astype(leaf.dtype)
        if name == _CHUNK_LENS_LEAF and chunk_lens is not None:
            return jnp.broadcast_to(chunk_lens,
                                    leaf.shape).astype(leaf.dtype)
        return leaf

    return jax.tree_util.tree_map_with_path(fix, cache)
