"""Tensor-parallel layers (Megatron-style) — GSPMD modules + shard_map fns.

Reference: ``apex/transformer/tensor_parallel/layers.py`` —
``ColumnParallelLinear`` (shard out-features; optional gather),
``RowParallelLinear`` (shard in-features; all-reduce output),
``VocabParallelEmbedding`` (shard vocab; masked lookup + all-reduce),
with ``sequence_parallel_enabled`` converting the TP all-reduces into
all-gather/reduce-scatter pairs and ``gradient_accumulation_fusion``
fusing the wgrad GEMM.

TPU translation — the central design pivot (SURVEY.md §2.6): topology is
declarative.  Two equivalent forms are provided:

1. **flax modules** (primary): weights carry ``nn.with_partitioning``
   metadata over the ``tensor`` mesh axis; activations get
   ``with_sharding_constraint`` hints.  Under ``jit`` over a mesh, XLA
   inserts exactly the collectives the reference hand-codes (all-gather
   on entry / reduce-scatter on exit under SP), overlapped by the
   compiler's latency-hiding scheduler — the analogue of the
   reference's async grad all-reduce overlap.  ``gradient_
   accumulation_fusion`` needs no port: XLA accumulates wgrads in fp32
   via ``preferred_element_type`` and fuses the accumulate.
2. **shard_map functions**: explicit per-shard math built on
   :mod:`apex_tpu.transformer.mappings` for schedule-controlled code
   (pipeline stages, custom overlap), mirroring how the reference's
   layers call ``copy_to/reduce_from`` internally.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax
import flax.linen as nn

from apex_tpu.core import mesh as mesh_lib
from apex_tpu.core.mesh import TENSOR_AXIS
from apex_tpu.transformer import mappings

__all__ = [
    "ColumnParallelLinear",
    "RowParallelLinear",
    "VocabParallelEmbedding",
    "column_parallel_linear",
    "row_parallel_linear",
    "vocab_parallel_embedding",
    "maybe_constrain",
]


def maybe_constrain(x, *spec):
    """``with_sharding_constraint`` if a mesh is initialized, else noop.

    Lets the same module run on a laptop (no mesh) and a pod slice.
    Axes not present in the ambient mesh — or manual (shard_map'ed,
    e.g. ``pipe`` inside the pipeline schedule) — are dropped from the
    spec, so TP/SP constraints compose with any surrounding topology.
    """
    # the ambient-mesh accessors arrived in newer jax; on versions
    # without them (no jax.set_mesh either) the library-global mesh
    # below is the only ambient-mesh channel, so falling through IS the
    # whole old-jax semantics, not a degraded mode
    get_abstract_mesh = getattr(jax.sharding, "get_abstract_mesh", None)
    abstract = None if get_abstract_mesh is None else get_abstract_mesh()
    # the abstract-mesh form of the constraint is only legal under a
    # trace; eagerly (e.g. model.init under jax.set_mesh) fall through
    # to the concrete-mesh NamedSharding path below
    if (abstract is not None and not abstract.empty
            and isinstance(x, jax.core.Tracer)):
        # inside jax.set_mesh / shard_map: resolve against the ambient
        # abstract mesh, keeping only its Auto (GSPMD-managed) axes
        auto = {n for n, t in zip(abstract.axis_names,
                                  abstract.axis_types)
                if t == jax.sharding.AxisType.Auto}
        spec = tuple(s if s in auto else None for s in spec)
        if all(s is None for s in spec):
            return x
        return lax.with_sharding_constraint(
            x, jax.sharding.PartitionSpec(*spec))
    # eager: prefer the ambient jax.set_mesh mesh (concrete form), then
    # the library-global one.  Under a trace with no ambient abstract
    # mesh (plain jit), jax.sharding.get_mesh() raises — skip straight
    # to the library-global mesh, whose concrete NamedSharding is legal
    # inside jit.
    try:
        get_ambient_mesh = getattr(jax.sharding, "get_mesh", None)
        mesh = None if get_ambient_mesh is None else get_ambient_mesh()
        if mesh is not None and mesh.empty:
            mesh = None
    except ValueError:
        mesh = None
    if mesh is None:
        try:
            mesh = mesh_lib.get_mesh()
        except RuntimeError:
            return x
    if mesh.size == 1:
        return x
    # drop axes absent from this mesh (e.g. a user mesh with foreign
    # axis names) so the constraint degrades instead of erroring
    names = set(mesh.axis_names)
    spec = tuple(s if s in names else None for s in spec)
    if all(s is None for s in spec):
        return x
    sharding = jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec(*spec))
    return lax.with_sharding_constraint(x, sharding)


# --------------------------------------------------------------------- #
# flax modules (GSPMD form)
# --------------------------------------------------------------------- #
class ColumnParallelLinear(nn.Module):
    """Linear with output features sharded over the ``tensor`` axis.

    ``gather_output=True`` replicates the output (reference default);
    ``False`` leaves it feature-sharded for a following RowParallel.
    ``sequence_parallel`` marks the input as sequence-sharded: XLA then
    materializes the all-gather on entry (reference:
    ``sequence_parallel_enabled``).
    """

    features: int
    use_bias: bool = True
    gather_output: bool = False
    sequence_parallel: bool = False
    axis: str = TENSOR_AXIS
    dtype: Optional[jnp.dtype] = None
    param_dtype: jnp.dtype = jnp.float32
    kernel_init: Callable = nn.initializers.lecun_normal()
    bias_init: Callable = nn.initializers.zeros_init()

    @nn.compact
    def __call__(self, x):
        dtype = self.dtype or x.dtype
        kernel = self.param(
            "kernel",
            nn.with_partitioning(self.kernel_init, (None, self.axis)),
            (x.shape[-1], self.features), self.param_dtype)
        if self.sequence_parallel:
            # input arrives sequence-sharded over the tensor axis;
            # the matmul needs it whole: constrain to gathered form.
            x = maybe_constrain(x, "data")
        y = jax.lax.dot_general(
            x.astype(dtype), kernel.astype(dtype),
            (((x.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        if self.use_bias:
            bias = self.param(
                "bias", nn.with_partitioning(self.bias_init, (self.axis,)),
                (self.features,), self.param_dtype)
            y = y + bias.astype(jnp.float32)
        y = y.astype(dtype)
        if self.gather_output:
            y = maybe_constrain(y, "data")
        else:
            y = maybe_constrain(y, "data", *([None] * (x.ndim - 2)),
                                self.axis)
        return y


class RowParallelLinear(nn.Module):
    """Linear with input features sharded over the ``tensor`` axis.

    Output is the all-reduced full tensor (reference semantics); under
    ``sequence_parallel`` the reduce becomes a reduce-scatter along the
    sequence dim (XLA chooses it from the output constraint).
    """

    features: int
    use_bias: bool = True
    sequence_parallel: bool = False
    input_is_parallel: bool = True
    axis: str = TENSOR_AXIS
    dtype: Optional[jnp.dtype] = None
    param_dtype: jnp.dtype = jnp.float32
    kernel_init: Callable = nn.initializers.lecun_normal()
    bias_init: Callable = nn.initializers.zeros_init()

    @nn.compact
    def __call__(self, x):
        dtype = self.dtype or x.dtype
        kernel = self.param(
            "kernel",
            nn.with_partitioning(self.kernel_init, (self.axis, None)),
            (x.shape[-1], self.features), self.param_dtype)
        if self.input_is_parallel:
            x = maybe_constrain(x, "data", *([None] * (x.ndim - 2)),
                                self.axis)
        y = jax.lax.dot_general(
            x.astype(dtype), kernel.astype(dtype),
            (((x.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        if self.use_bias:
            # bias replicated; added after the (implicit) reduce
            bias = self.param("bias", self.bias_init, (self.features,),
                              self.param_dtype)
            y = y + bias.astype(jnp.float32)
        y = y.astype(dtype)
        if self.sequence_parallel:
            # sequence-sharded output → XLA lowers psum to reduce-scatter
            y = maybe_constrain(y, "data", self.axis)
        else:
            y = maybe_constrain(y, "data")
        return y


class VocabParallelEmbedding(nn.Module):
    """Embedding with the vocab dim sharded over the ``tensor`` axis.

    GSPMD form: the table is partitioned ``(tensor, None)``; the lookup
    compiles to the same masked-gather + all-reduce the reference codes
    by hand.
    """

    num_embeddings: int
    features: int
    axis: str = TENSOR_AXIS
    dtype: Optional[jnp.dtype] = None
    param_dtype: jnp.dtype = jnp.float32
    embedding_init: Callable = nn.initializers.normal(stddev=0.02)

    def setup(self):
        self.embedding = self.param(
            "embedding",
            nn.with_partitioning(self.embedding_init, (self.axis, None)),
            (self.num_embeddings, self.features), self.param_dtype)

    def __call__(self, ids):
        dtype = self.dtype or self.param_dtype
        y = jnp.take(jnp.asarray(self.embedding).astype(dtype), ids,
                     axis=0)
        return maybe_constrain(y, "data")

    def attend(self, x):
        """Logits against the (sharded) table — output-embedding tying
        (vocab-sharded logits out, like the reference's parallel LM head).
        """
        table = jnp.asarray(self.embedding)
        y = jax.lax.dot_general(
            x, table.astype(x.dtype),
            (((x.ndim - 1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32).astype(x.dtype)
        return maybe_constrain(
            y, "data", *([None] * (x.ndim - 2)), self.axis)


# --------------------------------------------------------------------- #
# shard_map functions (explicit form)
# --------------------------------------------------------------------- #
def column_parallel_linear(x, kernel_shard, bias_shard=None, *,
                           sequence_parallel: bool = False,
                           seq_dim: int = 1,
                           axis: str = TENSOR_AXIS):
    """Per-shard column-parallel linear (inside ``shard_map``).

    ``kernel_shard``: (in, out/tp).  Input: replicated, or
    sequence-sharded when ``sequence_parallel``.
    """
    if sequence_parallel:
        x = mappings.gather_from_sequence_parallel_region(
            x, axis, seq_dim)
    else:
        x = mappings.copy_to_tensor_parallel_region(x, axis)
    y = jax.lax.dot_general(
        x, kernel_shard, (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(x.dtype)
    if bias_shard is not None:
        y = y + bias_shard.astype(y.dtype)
    return y


def row_parallel_linear(x, kernel_shard, bias=None, *,
                        sequence_parallel: bool = False,
                        seq_dim: int = 1,
                        axis: str = TENSOR_AXIS):
    """Per-shard row-parallel linear (inside ``shard_map``).

    ``kernel_shard``: (in/tp, out); ``x``: feature-sharded.  Output:
    full (all-reduce) or sequence-sharded (reduce-scatter) under SP.
    """
    y = jax.lax.dot_general(
        x, kernel_shard, (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(x.dtype)
    if sequence_parallel:
        y = mappings.reduce_scatter_to_sequence_parallel_region(
            y, axis, seq_dim)
    else:
        y = mappings.reduce_from_tensor_parallel_region(y, axis)
    if bias is not None:
        y = y + bias.astype(y.dtype)
    return y


def vocab_parallel_embedding(ids, table_shard, *, axis: str = TENSOR_AXIS):
    """Per-shard vocab-parallel lookup (inside ``shard_map``).

    ``table_shard``: (vocab/tp, features).  Masked local lookup +
    all-reduce, exactly the reference's algorithm.
    """
    per = table_shard.shape[0]
    start = lax.axis_index(axis) * per
    in_range = (ids >= start) & (ids < start + per)
    local_ids = jnp.clip(ids - start, 0, per - 1)
    y = jnp.take(table_shard, local_ids, axis=0)
    y = jnp.where(in_range[..., None], y, 0)
    return mappings.reduce_from_tensor_parallel_region(y, axis)
