"""Logging helper (``apex/transformer/log_util.py`` parity)."""

from __future__ import annotations

import logging
import os

__all__ = ["get_transformer_logger", "set_logging_level"]

_PREFIX = "apex_tpu.transformer"


def get_transformer_logger(name: str) -> logging.Logger:
    """Namespaced logger; level from APEX_TPU_LOG_LEVEL if set."""
    logger = logging.getLogger(f"{_PREFIX}.{name}")
    env = os.environ.get("APEX_TPU_LOG_LEVEL")
    if env and logger.level == logging.NOTSET:
        logger.setLevel(env.upper())
    return logger


def set_logging_level(verbosity) -> None:
    """Set the package-wide transformer log level."""
    logging.getLogger(_PREFIX).setLevel(verbosity)
