"""Tokenized-batch distribution within the model-parallel group.

Reference: ``apex/transformer/tensor_parallel/data.py`` —
``broadcast_data(keys, data, datatype)``: rank 0 of each tensor-parallel
group packs the batch dict into one flat int64 buffer and NCCL-broadcasts
it so every TP rank sees identical data.

TPU design: under GSPMD there is nothing to broadcast — a batch placed
with a sharding that does NOT mention the ``tensor``/``pipe`` axes is by
definition replicated across them, and the runtime moves bytes at most
once per device.  ``broadcast_data`` therefore (a) validates the batch
like the reference (same keys, int dtype) and (b) applies the
replicated-over-model-axes sharding; inside a traced region it reduces
to ``with_sharding_constraint``.
"""

from __future__ import annotations

from typing import Any, Dict, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu.core.mesh import DATA_AXIS, get_mesh

__all__ = ["broadcast_data", "model_replicated_sharding"]


def model_replicated_sharding(mesh=None, *, batch_axes=(DATA_AXIS,)):
    """Sharding for a batch: split over data axes, replicated over
    tensor/pipe/context (the TP-group "broadcast" as a layout fact)."""
    mesh = mesh or get_mesh()
    return NamedSharding(mesh, P(tuple(batch_axes)))


def broadcast_data(keys: Sequence[str], data: Dict[str, Any], datatype,
                   *, mesh=None) -> Dict[str, jnp.ndarray]:
    """Validate + place a batch dict replicated across model-parallel axes.

    Parity with the reference's contract: every key in ``keys`` must be
    present with dtype ``datatype``; returns arrays the whole TP group
    observes identically.  Outside jit this is a ``device_put``; inside,
    a sharding constraint.
    """
    out = {}
    sharding = model_replicated_sharding(mesh)
    for k in keys:
        if k not in data:
            raise KeyError(f"broadcast_data: missing key {k!r}")
        arr = jnp.asarray(data[k])
        if arr.dtype != jnp.dtype(datatype):
            raise TypeError(
                f"broadcast_data: key {k!r} has dtype {arr.dtype}, "
                f"expected {jnp.dtype(datatype)}")
        if isinstance(arr, jax.core.Tracer):
            out[k] = jax.lax.with_sharding_constraint(arr, sharding)
        else:
            out[k] = jax.device_put(arr, sharding)
    return out
