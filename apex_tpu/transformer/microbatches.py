"""Microbatch calculator (global-batch bookkeeping, incl. rampup).

Reference: ``apex/transformer/microbatches.py`` +
``apex/transformer/pipeline_parallel/utils.py`` —
``setup_microbatch_calculator(rank, rampup_batch_size,
global_batch_size, micro_batch_size, data_parallel_size)``,
``get_num_microbatches()``, ``get_current_global_batch_size()``,
``update_num_microbatches(consumed_samples)``.

Plain python config math (host-side; never traced), reused verbatim in
spirit: num_microbatches = global_batch // (micro_batch * dp_size), with
an optional linear batch-size rampup schedule.
"""

from __future__ import annotations

from typing import List, Optional, Union

__all__ = [
    "build_num_microbatches_calculator",
    "setup_microbatch_calculator",
    "get_num_microbatches",
    "get_current_global_batch_size",
    "update_num_microbatches",
    "ConstantNumMicroBatches",
    "RampupBatchsizeNumMicroBatches",
    "destroy_microbatch_calculator",
]

_CALCULATOR = None


class ConstantNumMicroBatches:
    """Fixed global batch size."""

    def __init__(self, global_batch_size: int, micro_batch_size: int,
                 data_parallel_size: int):
        per_step = micro_batch_size * data_parallel_size
        if global_batch_size % per_step:
            raise ValueError(
                f"global_batch_size ({global_batch_size}) must be "
                f"divisible by micro_batch_size * data_parallel_size "
                f"({micro_batch_size} * {data_parallel_size})")
        self.num_micro_batches = global_batch_size // per_step
        self.current_global_batch_size = global_batch_size
        self.micro_batch_size = micro_batch_size

    def get(self) -> int:
        return self.num_micro_batches

    def get_current_global_batch_size(self) -> int:
        return self.current_global_batch_size

    def update(self, consumed_samples: int,
               consistency_check: bool = True) -> None:
        pass


class RampupBatchsizeNumMicroBatches(ConstantNumMicroBatches):
    """Linear global-batch rampup: start → global over ramp samples.

    Reference semantics: batch size increments in steps of
    ``increment``; each size holds for an equal share of
    ``ramup_samples`` consumed samples.
    """

    def __init__(self, start_batch_size: int, batch_size_increment: int,
                 ramup_samples: int, global_batch_size: int,
                 micro_batch_size: int, data_parallel_size: int):
        super().__init__(global_batch_size, micro_batch_size,
                         data_parallel_size)
        self.micro_batch_times_data_parallel_size = (
            micro_batch_size * data_parallel_size)
        diff = global_batch_size - start_batch_size
        if diff < 0 or (batch_size_increment <= 0 and diff > 0) \
                or (batch_size_increment > 0
                    and diff % batch_size_increment):
            raise ValueError(
                f"cannot ramp {start_batch_size} -> {global_batch_size} "
                f"in increments of {batch_size_increment}")
        if start_batch_size % self.micro_batch_times_data_parallel_size:
            raise ValueError("start batch size must be divisible by "
                             "micro_batch_size * data_parallel_size")
        self.start_batch_size = start_batch_size
        self.batch_size_increment = batch_size_increment
        self.ramup_samples = ramup_samples
        self.global_batch_size = global_batch_size
        num_increments = diff // batch_size_increment if \
            batch_size_increment else 0
        self.rampup_samples_per_increment = (
            ramup_samples / num_increments if num_increments else 0)
        self.update(0, False)

    def update(self, consumed_samples: int,
               consistency_check: bool = True) -> None:
        if (self.rampup_samples_per_increment == 0
                or consumed_samples > self.ramup_samples):
            gbs = self.global_batch_size
        else:
            steps = int(consumed_samples /
                        self.rampup_samples_per_increment)
            gbs = (self.start_batch_size
                   + steps * self.batch_size_increment)
            gbs = min(gbs, self.global_batch_size)
        if consistency_check and \
                gbs % self.micro_batch_times_data_parallel_size:
            raise ValueError(
                f"ramped batch size {gbs} not divisible by "
                f"micro*dp {self.micro_batch_times_data_parallel_size}")
        self.current_global_batch_size = gbs
        self.num_micro_batches = (
            gbs // self.micro_batch_times_data_parallel_size)


def build_num_microbatches_calculator(
    rampup_batch_size: Optional[Union[List[int], tuple]],
    global_batch_size: int,
    micro_batch_size: int,
    data_parallel_size: int,
):
    """Constant or rampup calculator from the reference's
    ``rampup_batch_size = [start, increment, ramp_samples]`` spec."""
    if rampup_batch_size is None:
        return ConstantNumMicroBatches(
            global_batch_size, micro_batch_size, data_parallel_size)
    if len(rampup_batch_size) != 3:
        raise ValueError(
            "rampup_batch_size = [start, increment, ramp_samples]")
    start, inc, samples = (int(v) for v in rampup_batch_size)
    return RampupBatchsizeNumMicroBatches(
        start, inc, samples, global_batch_size, micro_batch_size,
        data_parallel_size)


def setup_microbatch_calculator(
    rank: int = 0,
    rampup_batch_size: Optional[list] = None,
    global_batch_size: int = 1,
    micro_batch_size: int = 1,
    data_parallel_size: int = 1,
) -> None:
    """Install the global calculator (reference-compatible signature;
    ``rank`` only gated logging upstream)."""
    global _CALCULATOR
    _CALCULATOR = build_num_microbatches_calculator(
        rampup_batch_size, global_batch_size, micro_batch_size,
        data_parallel_size)


def _get():
    if _CALCULATOR is None:
        raise RuntimeError("call setup_microbatch_calculator(...) first")
    return _CALCULATOR


def get_num_microbatches() -> int:
    """Current number of microbatches from the global calculator
    (reference: ``apex.transformer.pipeline_parallel.utils``)."""
    return _get().get()


def get_current_global_batch_size() -> int:
    """Current global batch size (rampup-aware), reference name."""
    return _get().get_current_global_batch_size()


def update_num_microbatches(consumed_samples: int,
                            consistency_check: bool = True) -> None:
    """Advance the rampup schedule to ``consumed_samples`` (reference
    name; no-op for the constant calculator)."""
    _get().update(consumed_samples, consistency_check)


def destroy_microbatch_calculator() -> None:
    """Reset the global calculator (test isolation, reference name)."""
    global _CALCULATOR
    _CALCULATOR = None
