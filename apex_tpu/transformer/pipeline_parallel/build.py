"""``build_model`` — stack a homogeneous layer into pipeline stages.

Reference: ``apex/transformer/pipeline_parallel/utils.py::build_model``
(SURVEY.md §2.6 schedules row) — the reference builds a list of model
chunks, one per (virtual) pipeline stage, so users never hand-slice
their model.  The TPU analogue stacks *parameters* instead of modules:
the schedules (:mod:`.schedules`) expect a ``(pp, ...)`` (or
``(V, pp, ...)`` interleaved) leading stack on every parameter leaf plus
a matching :class:`~jax.sharding.PartitionSpec` tree, which every caller
previously assembled by hand with ``jax.vmap`` + ``jax.tree.map``.

:func:`build_model` does that assembly once: init every layer, reshape
the stacked leaves into the schedule's stage layout (interleaved chunk
``c`` on rank ``r`` implements global stage ``c*pp + r``, matching
``spmd_pipeline_1f1b_interleaved``), derive the spec tree from the
layer's own flax partitioning metadata (so TP-sharded weights stay
TP-sharded inside each stage), and return a ``stage_fn`` that scans the
per-stage layers — compile-friendly, no Python loop per layer.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from apex_tpu.core.mesh import PIPE_AXIS

__all__ = ["build_model"]


def build_model(
    layer_module,
    num_layers: int,
    pipeline_model_parallel_size: int,
    virtual_pipeline_model_parallel_size: Optional[int] = None,
    *,
    rng,
    sample_input,
    axis: str = PIPE_AXIS,
    layer_remat: bool = False,
) -> Tuple[Callable, Any, Any]:
    """Build ``(stage_fn, stacked_params, params_spec)`` for the
    pipeline schedules.

    ``layer_module`` is one flax layer (e.g.
    :class:`~apex_tpu.models.ParallelTransformerLayer`) applied
    ``num_layers`` times; ``sample_input`` is one microbatch activation
    ``(mb, seq, hidden)`` used for shape inference.  ``num_layers`` must
    divide evenly into ``pp * V`` stages; each stage applies
    ``num_layers // (pp * V)`` layers via ``lax.scan``.

    Returns:
      - ``stage_fn(stage_params, x) -> y`` — one pipeline stage, for
        :func:`.schedules.forward_backward_pipelining_without_interleaving`
        (or the interleaved driver when ``V > 1``),
      - ``stacked_params`` — unboxed pytree whose leaves lead with
        ``(pp, layers_per_stage, ...)`` (``(V, pp, layers_per_stage,
        ...)`` interleaved), independently initialized per layer from
        ``rng``,
      - ``params_spec`` — matching ``PartitionSpec`` tree: ``axis`` over
        the stage dim, the layer's own partitioning (tensor axes) on the
        parameter dims — use it to ``device_put`` the stacked params so
        TP weights land sharded.  Do NOT pass it to the schedule
        drivers: their ``params_spec`` argument is a ``shard_map``
        in_spec restricted to the manual pipe axis, and their defaults
        (``P(axis)`` / ``P(None, axis)``) already match this layout —
        the tensor-axis sharding rides along via GSPMD.

    ``layer_remat=True`` wraps each layer application in
    ``jax.checkpoint``: differentiating a stage then holds ONE layer's
    residuals at a time instead of all ``layers_per_stage`` — the
    deep-stage analogue of the 1F1B schedule's stage-input
    remat-by-construction (its backward unit recomputes the stage
    interior, which without this flag materializes every layer's
    residuals at once).
    """
    import flax.linen as nn

    pp = pipeline_model_parallel_size
    v = virtual_pipeline_model_parallel_size or 1
    n_stages = pp * v
    if num_layers % n_stages != 0:
        raise ValueError(
            f"num_layers={num_layers} must be divisible by "
            f"pp*V={pp}*{v}={n_stages}")
    per_stage = num_layers // n_stages

    def layer_init(key):
        return layer_module.init(key, sample_input)

    keys = jax.random.split(rng, num_layers)
    stacked = jax.vmap(layer_init)(keys)          # (num_layers, ...)
    # one layer's spec from its own flax partitioning metadata, before
    # unboxing (vmap leaves the Partitioned names un-lifted, so the
    # layer-level eval_shape is the reliable source)
    layer_spec = nn.get_partition_spec(
        jax.eval_shape(layer_init, jax.random.PRNGKey(0)))
    stacked = nn.meta.unbox(stacked)

    if v > 1:
        # (V, pp, per_stage, ...): chunk c on rank r = stage c*pp + r,
        # covering layers [(c*pp + r) * per_stage, ...) — row-major
        # reshape gives exactly that ordering
        stacked = jax.tree.map(
            lambda a: a.reshape(v, pp, per_stage, *a.shape[1:]), stacked)
        prefix = (None, axis, None)
    else:
        stacked = jax.tree.map(
            lambda a: a.reshape(pp, per_stage, *a.shape[1:]), stacked)
        prefix = (axis, None)

    params_spec = jax.tree.map(
        lambda s: P(*prefix, *s), layer_spec,
        is_leaf=lambda x: isinstance(x, P))

    def stage_fn(stage_params, x):
        apply = lambda lp, h: layer_module.apply(lp, h)
        if layer_remat:
            apply = jax.checkpoint(
                apply, policy=jax.checkpoint_policies.nothing_saveable)

        def body(h, layer_params):
            return apply(layer_params, h), None

        y, _ = lax.scan(body, x, stage_params)
        return y

    return stage_fn, stacked, params_spec
