"""Stage-to-stage communication over the ``pipe`` mesh axis.

Reference: ``apex/transformer/pipeline_parallel/p2p_communication.py`` —
batched NCCL isend/irecv (``torch.distributed.P2POp``) with a
shape/dtype handshake and fused ``send_forward_recv_backward`` ops.

TPU translation: a pipeline "send to next stage" is one
``lax.ppermute`` over the ``pipe`` axis — a neighbor exchange on ICI.
Shapes are static under jit, so the reference's handshake disappears;
"batched p2p" disappears because a single ppermute moves any pytree.
These helpers are usable only inside ``shard_map`` with the ``pipe``
axis bound; the scheduler (:mod:`.schedules`) composes them.

Semantics note: ppermute is a *collective* permutation — "send forward"
necessarily also "receives" from the previous stage (the first stage
receives the last stage's tensor, which schedules mask out), which is
exactly how the reference fuses ``send_forward_recv_forward``.
"""

from __future__ import annotations

from typing import Any

import jax
from jax import lax

from apex_tpu.core.mesh import PIPE_AXIS

__all__ = [
    "send_forward_recv_forward",
    "send_backward_recv_backward",
    "send_forward",
    "recv_forward",
    "send_backward",
    "recv_backward",
]


def _shift(tree: Any, axis: str, offset: int) -> Any:
    n = lax.axis_size(axis)
    perm = [(i, (i + offset) % n) for i in range(n)]
    return jax.tree.map(lambda x: lax.ppermute(x, axis, perm), tree)


def send_forward_recv_forward(tree: Any, *, axis: str = PIPE_AXIS) -> Any:
    """Rotate activations one stage forward (rank r → r+1, wrapping).

    The returned value on rank r is rank r-1's input; rank 0 receives
    rank pp-1's (masked out by the schedule)."""
    return _shift(tree, axis, +1)


def send_backward_recv_backward(tree: Any, *, axis: str = PIPE_AXIS) -> Any:
    """Rotate gradients one stage backward (rank r → r-1, wrapping).

    This is the transpose of :func:`send_forward_recv_forward`, which is
    why autodiff through the forward schedule yields exactly the
    reference's backward communication pattern."""
    return _shift(tree, axis, -1)


# Aliases matching the reference's unfused names: on TPU there is no
# distinction — the collective IS the fused send+recv.
send_forward = send_forward_recv_forward
recv_forward = send_forward_recv_forward
send_backward = send_backward_recv_backward
recv_backward = send_backward_recv_backward
