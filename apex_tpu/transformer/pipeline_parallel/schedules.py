"""Pipeline-parallel schedules — the microbatch engine.

Reference: ``apex/transformer/pipeline_parallel/schedules/`` —
``forward_backward_no_pipelining``, ``_pipelining_without_interleaving``
(1F1B: warmup fwds, steady one-fwd-one-bwd, cooldown bwds),
``_pipelining_with_interleaving`` (virtual pipeline), dispatched by
``get_forward_backward_func()`` (SURVEY.md §3.5).

TPU design — *the schedule is a program, not an event loop*.  Two
complementary mechanisms:

- :func:`spmd_pipeline_1f1b` / :func:`spmd_pipeline_1f1b_interleaved`
  (used by the reference-named drivers) hand-write the
  one-forward-one-backward tick table as a single ``lax.scan`` inside
  ``shard_map`` over ``pipe``: each tick runs one forward unit and one
  backward unit (``jax.vjp`` recompute + transpose), activations ride
  a forward ``ppermute`` ring, cotangents a reverse ring, and live
  activations are bounded by a ``2*pp``(·V)-slot stash of stage
  *inputs* — O(pp·V), flat in M, exactly the memory shape that is
  1F1B's reason to exist.  Dead warmup/cooldown units are skipped with
  ``lax.cond``, not computed-and-masked; the non-interleaved form also
  streams cyclically-sharded microbatches to rank 0 through a feed
  ring, so input memory is O(M/pp) per rank.
- :func:`spmd_pipeline` / :func:`spmd_pipeline_interleaved` are
  *autodiff-able forward* pipelines (scan + ppermute): JAX transposes
  them into the reverse pipeline, so they compose with outer
  ``value_and_grad`` (e.g. a model with embedding/head outside the
  pipelined region).  Convenient, but the transposed scan stashes all
  ``M + pp - 1`` tick outputs — O(M) activation memory; prefer the
  1F1B drivers for large M.

The pipeline spans the homogeneous transformer stack (stage params are
stacked along a leading ``pp`` axis and split by ``shard_map``);
embedding/head run outside the pipelined region, as in Megatron's
``build_model`` stage-embedding special-casing.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.core.mesh import PIPE_AXIS
from apex_tpu.transformer.microbatches import get_num_microbatches
from apex_tpu.transformer.pipeline_parallel.p2p import (
    send_backward_recv_backward,
    send_forward_recv_forward,
)

__all__ = [
    "spmd_pipeline",
    "spmd_pipeline_1f1b",
    "spmd_pipeline_1f1b_interleaved",
    "spmd_pipeline_interleaved",
    "forward_backward_no_pipelining",
    "forward_backward_pipelining_without_interleaving",
    "forward_backward_pipelining_with_interleaving",
    "get_forward_backward_func",
]


# collective (and collective-inducing) primitives that make lax.cond
# dead-tick skipping unsafe — see _unit
_COLLECTIVE_PRIMS = frozenset({
    "psum", "psum2", "pmax", "pmin", "all_gather", "all_to_all",
    "ppermute", "pbroadcast", "psum_scatter", "reduce_scatter",
    "sharding_constraint", "collective_permute", "pgather",
})


def _contains_collectives(jaxpr) -> bool:
    """Recursively scan a jaxpr (and sub-jaxprs) for collectives."""
    def subs(v):
        if isinstance(v, jax.extend.core.ClosedJaxpr):
            yield v.jaxpr
        elif isinstance(v, jax.extend.core.Jaxpr):
            yield v
        elif isinstance(v, (tuple, list)):
            for item in v:
                yield from subs(item)

    for eqn in jaxpr.eqns:
        if eqn.primitive.name in _COLLECTIVE_PRIMS:
            return True
        for val in eqn.params.values():
            for sub in subs(val):
                if _contains_collectives(sub):
                    return True
    return False


def _traces_collectives(fn, *args) -> bool:
    """True if tracing ``fn(*args)`` — forward OR its vjp pullback —
    records any collective primitive (explicit ``lax.p*`` or a sharding
    constraint that GSPMD may lower to one).  The pullback is probed
    separately because a collective can appear only in the backward
    (e.g. a ``custom_vjp`` whose bwd rule psums, or a transpose that
    inserts ``psum_invariant``); a forward-only probe would classify
    such a stage collective-free, cond-skip it, and deadlock on
    rank-divergent backward units.  Unable-to-trace counts as True
    (the safe answer: computed-and-masked mode is always sound)."""
    try:
        jaxpr = jax.make_jaxpr(fn)(*args).jaxpr
    except Exception:
        return True
    if _contains_collectives(jaxpr):
        return True

    def _ct_like(x):
        if jnp.issubdtype(x.dtype, jnp.inexact):
            return jnp.zeros(x.shape, x.dtype)
        return np.zeros(x.shape, jax.dtypes.float0)

    def probe(*a):
        y, pullback = jax.vjp(fn, *a)
        return pullback(jax.tree.map(_ct_like, y))

    try:
        bwd_jaxpr = jax.make_jaxpr(probe)(*args).jaxpr
    except Exception:
        return True
    return _contains_collectives(bwd_jaxpr)


def _unit(skip, pred, live_fn, dead_fn, operands):
    """One schedule unit: ``lax.cond``-skipped or computed-and-masked.

    Dead warmup/cooldown units are cheapest skipped with ``lax.cond``
    — but 1F1B's predicates vary over the pipe rank, and a collective
    inside a branch only some ranks enter deadlocks the program: the
    non-entering ranks never send (TPU) / never join the rendezvous
    (CPU).  GSPMD freely places collectives inside the branch when the
    stage body is tensor/sequence-parallel (observed: the qkv-slice
    reshard of ``ParallelAttention`` under tp=2), so cond-skipping is
    only sound for collective-free stage bodies — the driver
    auto-detects via :func:`_traces_collectives` (``skip_dead_ticks``
    overrides).  The masked form computes every unit and selects
    results — dead units burn stage-compute during warmup/cooldown
    ticks (bounded by the bubble fraction) but every collective runs
    unconditionally on every rank.
    """
    if skip:
        return lax.cond(pred, live_fn, dead_fn, operands)
    live = live_fn(operands)
    dead = dead_fn(operands)
    return jax.tree.map(lambda a, b: jnp.where(pred, a, b), live, dead)


def _closure_aux_specs(loss_params, return_input_cotangents):
    """shard_map out_specs for the embedding/head-closure aux dict."""
    aux = {}
    if loss_params is not None:
        aux["loss_params_grads"] = jax.tree.map(
            lambda _: P(), loss_params)
    if return_input_cotangents:
        aux["input_cotangents"] = P()
    return aux


def _closure_aux_collect(extras, loss_params, return_input_cotangents,
                         axis):
    """Replicate the rank-local closure extras over ``axis``:
    loss-param grads fired on the last rank only (psum = the sum);
    input cotangents live on rank 0 (masked psum = broadcast)."""
    aux = {}
    if loss_params is not None:
        aux["loss_params_grads"] = jax.tree.map(
            lambda g: lax.psum(g, axis), extras["loss_params_grads"])
    if return_input_cotangents:
        cts = extras["input_cotangents"]
        aux["input_cotangents"] = lax.psum(
            jnp.where(lax.axis_index(axis) == 0, cts,
                      jnp.zeros_like(cts)), axis)
    return aux


def _after(first, x):
    """Return ``x`` ordered after ``first`` (``optimization_barrier``).

    One 1F1B tick contains several mutually data-independent collective
    groups: the GSPMD collectives inside the forward / loss / backward
    units (e.g. tensor-parallel all-reduces in the stage body) and the
    three ring ``ppermute``\\ s.  XLA's CPU thunk executor dispatches
    independent ops concurrently in a timing-dependent order, so two
    devices can enter two such collectives in opposite orders and
    deadlock the in-process rendezvous (observed with attention-sized
    stage bodies).  Chaining the groups with barriers imposes the same
    total order on every device.  On TPU each core executes thunks in
    program order anyway, so the barrier costs nothing; the serialized
    rings move one microbatch each — noise next to stage compute.
    """
    x, _ = lax.optimization_barrier((x, first))
    return x


# --------------------------------------------------------------------- #
# core: collective SPMD pipeline (inside shard_map)
# --------------------------------------------------------------------- #
def spmd_pipeline(
    stage_fn: Callable,
    stage_params: Any,
    microbatches: jnp.ndarray,
    *,
    axis: str = PIPE_AXIS,
    remat: bool = True,
):
    """Run ``microbatches`` through a ``pp``-stage pipeline.

    Must be called inside ``shard_map`` with ``axis`` bound.  Per rank:
    ``stage_params`` is this stage's chunk (leading ``pp`` axis split by
    the shard_map in_spec); ``microbatches`` is ``(M, mb, seq, hidden)``
    (replicated; only stage 0 reads it).  ``stage_fn(params, x) -> y``
    maps ``(mb, seq, hidden) -> (mb, seq, hidden)``.

    Returns ``(M, mb, seq, hidden)`` last-stage outputs, replicated over
    ``axis`` (masked ``psum`` broadcast).
    """
    pp = lax.axis_size(axis)
    rank = lax.axis_index(axis)
    num_micro = microbatches.shape[0]
    n_ticks = num_micro + pp - 1

    # shard_map's in_spec P(axis) splits the stacked stage axis but
    # keeps it as a size-1 leading dim — strip it so stage_fn sees the
    # per-stage parameter shapes
    for leaf in jax.tree.leaves(stage_params):
        if leaf.ndim and leaf.shape[0] != 1:
            raise ValueError(
                f"stage_params' leading (stacked-stage) axis must be "
                f"split over '{axis}' to local size 1, got local size "
                f"{leaf.shape[0]} for a {leaf.shape} leaf — pass "
                f"params_spec=P('{axis}', ...) on every leaf")
    # 0-d leaves are replicated scalars (no stacked axis to strip)
    stage_params = jax.tree.map(
        lambda a: a[0] if a.ndim else a, stage_params)

    body = stage_fn
    if remat:
        body = jax.checkpoint(
            stage_fn, policy=jax.checkpoint_policies.nothing_saveable)

    def tick(carry, t):
        recv = carry
        # stage 0 feeds microbatch t (clamped; dead ticks masked out by
        # the output slice), later stages consume the neighbor's hand-off
        mb = lax.dynamic_index_in_dim(
            microbatches, jnp.clip(t, 0, num_micro - 1), axis=0,
            keepdims=False)
        x = jnp.where(rank == 0, mb, recv)
        y = body(stage_params, x)
        # rotate: rank r's output becomes rank r+1's next input; the
        # wrap (last -> 0) carries garbage that stage 0 ignores
        nxt = send_forward_recv_forward(y, axis=axis)
        return nxt, y

    init = jnp.zeros_like(microbatches[0])
    # the carry is device-varying over the pipe axis from tick 1 on;
    # mark the (replicated) zeros accordingly for vma tracking
    init = lax.pcast(init, (axis,), to="varying")
    _, ys = lax.scan(tick, init, jnp.arange(n_ticks))
    # rank pp-1 emits microbatch m at tick m + pp - 1
    outs = ys[pp - 1:]
    # replicate the last stage's outputs over the pipe axis (masked
    # broadcast; transposes to "grads enter at the last stage")
    outs = lax.psum(
        jnp.where(rank == pp - 1, outs, jnp.zeros_like(outs)), axis)
    return outs


# --------------------------------------------------------------------- #
# true 1F1B: interleaved forward/backward, O(pp) live activations
# --------------------------------------------------------------------- #
def spmd_pipeline_1f1b(
    stage_fn: Callable,
    loss_fn: Callable,
    stage_params: Any,
    microbatches: jnp.ndarray,
    *,
    axis: str = PIPE_AXIS,
    microbatches_distributed: bool = False,
    skip_dead_ticks: Optional[bool] = None,
    loss_params: Any = None,
    return_input_cotangents: bool = False,
):
    """One-forward-one-backward pipeline, computing ``(loss, grads)``
    directly — the schedule IS the backward pass, not its autodiff
    transpose.

    Reference: ``fwd_bwd_pipelining_without_interleaving.py`` — the
    point of 1F1B is the *memory shape*: each microbatch's backward runs
    as soon as its loss exists, so live activations are bounded by
    O(pp) microbatches regardless of M (SURVEY.md §2.6 schedules row).
    A ``value_and_grad`` over a scanned forward cannot have that shape
    (the transposed scan replays stashed tick outputs, O(M)); so this
    function hand-writes the 1F1B tick table as a single SPMD
    ``lax.scan`` and differentiates *inside* each tick:

    - tick ``t``, rank ``r`` **forward-unit**: microbatch ``mf = t - r``
      (valid when ``0 <= mf < M``) — stage input from the forward
      ``ppermute`` ring (rank 0 injects fresh microbatches), stage
      output handed to ``r+1``; the stage *input* is stored in a
      ``2*pp``-slot circular stash (inputs only — the stage interior is
      recomputed in the backward unit, remat by construction).
    - rank ``pp-1`` computes ``loss_fn`` and its output-cotangent
      immediately after each forward (the "1B follows 1F" half).
    - tick ``t``, rank ``r`` **backward-unit**: microbatch
      ``mb = t - (2*pp - 1) + r`` — pops the stashed input,
      ``jax.vjp(stage_fn)`` recomputes the stage and pulls the incoming
      cotangent back; the input-cotangent rides the reverse
      ``ppermute`` ring to rank ``r-1``, the parameter-cotangent
      accumulates into the scan carry.
    - dead warmup/cooldown units are *skipped* (``lax.cond``) when the
      stage/loss bodies are collective-free, else computed-and-masked —
      a collective inside a branch only some pipe ranks enter would
      deadlock (see :func:`_unit`).  ``skip_dead_ticks`` overrides the
      auto-detection (``None``).

    Memory: carry = fwd/bwd ring activations + ``2*pp`` stash slots +
    grad accumulator — flat in M (asserted by
    ``tests/test_pipeline.py::test_memory_flat_in_microbatches``).
    Total ticks ``M + 2*pp - 1``; each runs one F and one B unit, so
    the bubble is ``(2*pp-1)/(M+2*pp-1)`` of the schedule — the
    steady-state is exactly Megatron's one-forward-one-backward.

    Must be called inside ``shard_map`` with ``axis`` bound; arguments
    as in :func:`spmd_pipeline` plus ``loss_fn(y, microbatch_index) ->
    scalar`` (mean over the microbatch; the returned loss is the mean
    over all M microbatches).  Returns ``(loss_local, grads_local)``:
    ``loss_local`` is the total on rank ``pp-1`` and 0 elsewhere (psum
    and divide by M outside or use the driver), ``grads_local`` matches
    this rank's stripped ``stage_params``.

    ``microbatches_distributed=True``: ``microbatches`` is the *local*
    cyclic shard ``(M/pp, mb, ...)`` — rank ``r`` holds global
    microbatches ``r::pp`` — instead of the full replicated ``(M, ...)``
    tensor, so per-rank input memory is O(M/pp) not O(M).  A feed ring
    streams each microbatch to rank 0 just in time: every ``pp`` ticks
    all ranks inject their next local microbatch into a one-slot feed
    buffer that shifts one hop toward rank 0 per tick — the item rank
    ``j`` injects at tick ``q*pp`` arrives at rank 0 exactly at tick
    ``q*pp + j``, which is when microbatch ``q*pp + j`` enters the
    pipeline.  One extra single-microbatch ``ppermute`` per tick,
    overlapped with the stage compute like the main rings.

    **Embedding/head closure** (Megatron's ``build_model``
    stage-embedding special-casing, SURVEY.md §2.6): a full train step
    also needs gradients for parameters living *outside* the pipelined
    stage stack.

    - ``loss_params``: when given, the loss signature becomes
      ``loss_fn(loss_params, y, microbatch_index)`` (e.g. the LM head
      weights + labels-side state) and a third return element carries
      ``d loss / d loss_params``, accumulated over the rank-``pp-1``
      loss units (zeros elsewhere; the driver psums over ``axis``).
    - ``return_input_cotangents=True``: additionally return the stack
      of rank-0 backward input-cotangents ``(M, mb, ...)`` — exactly
      ``d loss / d h`` for each pipeline-input microbatch ``h`` — so
      the caller can close the embedding backward
      (``d_embed = zeros.at[ids].add(cts)``).  This buffer is O(M) by
      necessity (the embedding backward needs every microbatch's
      cotangent); the O(pp) live-activation property of the schedule
      itself is unchanged.

    With either option the return is ``(loss_local, grads_local,
    extras)`` where ``extras`` holds ``"loss_params_grads"`` and/or
    ``"input_cotangents"`` (both rank-local; see the driver for the
    psum/replication).
    """
    pp = lax.axis_size(axis)
    rank = lax.axis_index(axis)
    if microbatches_distributed:
        local_n = microbatches.shape[0]
        num_micro = local_n * pp
    else:
        num_micro = microbatches.shape[0]
    n_ticks = num_micro + 2 * pp - 1
    n_slots = 2 * pp

    for leaf in jax.tree.leaves(stage_params):
        if leaf.ndim and leaf.shape[0] != 1:
            raise ValueError(
                f"stage_params' leading (stacked-stage) axis must be "
                f"split over '{axis}' to local size 1, got local size "
                f"{leaf.shape[0]} for a {leaf.shape} leaf — pass "
                f"params_spec=P('{axis}', ...) on every leaf")
    params_local = jax.tree.map(
        lambda a: a[0] if a.ndim else a, stage_params)

    mb_shape = microbatches[0]

    if skip_dead_ticks is None:
        # cond-skipping dead units is only sound for collective-free
        # stage/loss bodies (see _unit); detect and fall back to the
        # computed-and-masked form otherwise
        if loss_params is None:
            loss_probe = lambda y: loss_fn(y, jnp.int32(0))
        else:
            loss_probe = lambda y: loss_fn(loss_params, y, jnp.int32(0))
        skip_dead_ticks = not (
            _traces_collectives(stage_fn, params_local, mb_shape)
            or _traces_collectives(loss_probe, mb_shape))

    def varying(x):
        """Mark ``x`` device-varying over ``axis`` (no-op if already)."""
        try:
            return lax.pcast(x, (axis,), to="varying")
        except ValueError:
            return x

    # mark loss_params varying BEFORE the vjp: pulling a cotangent for
    # a pipe-INVARIANT input makes the transpose insert a psum over
    # `axis` inside the (rank-divergent) loss cond — a deadlock (see
    # _unit); varying is metadata-only and the driver psums the grads
    # explicitly afterwards
    if loss_params is not None:
        loss_params = jax.tree.map(varying, loss_params)

    def tick(carry, t):
        (fwd_x, bwd_ct, pending_ct, feed, stash, loss_acc, grad_acc,
         lp_grad_acc, ct_buf) = carry

        # ---- forward unit: microbatch mf = t - rank ----
        mf = t - rank
        valid_f = (mf >= 0) & (mf < num_micro)
        if microbatches_distributed:
            # feed-ring invariant: at the start of tick t, rank 0's
            # feed buffer holds microbatch t (see docstring)
            mb = feed
        else:
            mb = lax.dynamic_index_in_dim(
                microbatches, jnp.clip(mf, 0, num_micro - 1), axis=0,
                keepdims=False)
        x = jnp.where(rank == 0, mb, fwd_x)
        y = _unit(skip_dead_ticks, valid_f,
                  lambda a: varying(stage_fn(params_local, a)),
                  lambda a: varying(jnp.zeros_like(a)), x)
        # stash the stage INPUT (slot mf mod 2pp; live range < 2pp so
        # no collision); dead units must not overwrite a live slot
        slot = jnp.clip(mf, 0, num_micro - 1) % n_slots
        new_stash = lax.dynamic_update_index_in_dim(
            stash, x.astype(stash.dtype), slot, axis=0)
        stash = jnp.where(valid_f, new_stash, stash)

        # ---- loss + output-cotangent on the last rank ----
        def loss_and_ct(y):
            if loss_params is None:
                lval, pull = jax.vjp(lambda yy: loss_fn(yy, mf), y)
            else:
                lval, pull = jax.vjp(
                    lambda lp, yy: loss_fn(lp, yy, mf), loss_params, y)
            # compute 1/M in f32 first: a bf16 loss_fn would otherwise
            # round the seed (and the f32 zero in the false branch
            # requires an f32 loss either way)
            seed = varying((jnp.float32(1) / num_micro).astype(lval.dtype))
            if loss_params is None:
                (ct,) = pull(seed)
                glp = ()
            else:
                glp, ct = pull(seed)
            return (varying(lval.astype(jnp.float32)), varying(ct),
                    jax.tree.map(varying, glp))

        is_last = rank == pp - 1
        lval, new_pending, glp = _unit(
            skip_dead_ticks, valid_f & is_last, loss_and_ct,
            lambda y: (varying(jnp.zeros((), jnp.float32)),
                       varying(jnp.zeros_like(y)),
                       jax.tree.map(
                           lambda a: varying(jnp.zeros_like(a)),
                           () if loss_params is None else loss_params)),
            y)
        loss_acc = loss_acc + lval
        if loss_params is not None:
            lp_grad_acc = jax.tree.map(jnp.add, lp_grad_acc, glp)

        # ---- backward unit: microbatch mb_b = t - (2pp-1) + rank ----
        mb_b = t - (2 * pp - 1) + rank
        valid_b = (mb_b >= 0) & (mb_b < num_micro)
        x_saved = lax.dynamic_index_in_dim(
            stash, jnp.clip(mb_b, 0, num_micro - 1) % n_slots, axis=0,
            keepdims=False)
        # incoming cotangent: reverse ring from rank r+1; the last rank
        # feeds itself the loss cotangent it computed LAST tick (for
        # exactly the microbatch whose backward is due this tick)
        # (ordered after the forward+loss units — see _after)
        ct_in = _after((y, lval), jnp.where(is_last, pending_ct, bwd_ct))

        def run_bwd(operands):
            x_s, ct = operands
            _, pull = jax.vjp(stage_fn, params_local, x_s)
            gp, gx = pull(ct)
            return jax.tree.map(varying, (gp, gx))

        gp, gx = _unit(
            skip_dead_ticks, valid_b, run_bwd,
            lambda operands: jax.tree.map(varying, (
                jax.tree.map(jnp.zeros_like, params_local),
                jnp.zeros_like(operands[0]))),
            (x_saved, ct_in))
        grad_acc = jax.tree.map(jnp.add, grad_acc, gp)

        # ---- rings (barrier-chained into one device-uniform order) ----
        fwd_x = send_forward_recv_forward(_after(gx, y), axis=axis)
        bwd_ct = send_backward_recv_backward(_after(fwd_x, gx), axis=axis)
        if microbatches_distributed:
            # re-establish the feed invariant for tick t+1: inject the
            # next local microbatch every pp ticks, else shift the feed
            # one hop toward rank 0
            nxt_q = (t + 1) // pp
            local_next = lax.dynamic_index_in_dim(
                microbatches, jnp.clip(nxt_q, 0, local_n - 1), axis=0,
                keepdims=False)
            shifted = lax.ppermute(
                _after(bwd_ct, feed), axis,
                [(i, (i - 1) % pp) for i in range(pp)])
            feed = jnp.where((t + 1) % pp == 0, local_next, shifted)
        if return_input_cotangents:
            # rank 0's input-cotangent = dL/d(pipeline input) for
            # microbatch mb_b; store at its microbatch slot — an O(M)
            # carry buffer, not an O(n_ticks) scan stack (which would
            # add (2pp-1) microbatch-sized slots, replicated on every
            # rank, of zeros)
            upd = lax.dynamic_update_index_in_dim(
                ct_buf, gx.astype(ct_buf.dtype),
                jnp.clip(mb_b, 0, num_micro - 1), axis=0)
            ct_buf = jnp.where((rank == 0) & valid_b, upd, ct_buf)
        return (fwd_x, bwd_ct, new_pending, feed, stash, loss_acc,
                grad_acc, lp_grad_acc, ct_buf), None

    feed0 = (varying(microbatches[0]) if microbatches_distributed
             else varying(jnp.zeros((), mb_shape.dtype)))
    init = (
        varying(jnp.zeros_like(mb_shape)),                  # fwd ring
        varying(jnp.zeros_like(mb_shape)),                  # bwd ring
        varying(jnp.zeros_like(mb_shape)),                  # pending ct
        feed0,                                              # feed ring
        varying(jnp.zeros((n_slots,) + mb_shape.shape,
                          mb_shape.dtype)),                 # stash
        varying(jnp.zeros((), jnp.float32)),                # loss acc
        # grad acc: zeros_like(params) is already device-varying (the
        # params came in split over `axis`), so no pcast here
        jax.tree.map(jnp.zeros_like, params_local),          # grad acc
        # loss-params grad acc (replicated zeros -> mark varying: only
        # the last rank accumulates)
        jax.tree.map(lambda a: varying(jnp.zeros_like(a)),
                     () if loss_params is None else loss_params),
        varying(jnp.zeros(                                  # ct buffer
            ((num_micro,) if return_input_cotangents else (0,))
            + mb_shape.shape, mb_shape.dtype)),
    )
    carry, _ = lax.scan(tick, init, jnp.arange(n_ticks))
    loss_acc, grad_acc, lp_grad_acc, ct_buf = (
        carry[-4], carry[-3], carry[-2], carry[-1])
    if loss_params is None and not return_input_cotangents:
        return loss_acc, grad_acc
    extras = {}
    if loss_params is not None:
        extras["loss_params_grads"] = lp_grad_acc
    if return_input_cotangents:
        extras["input_cotangents"] = ct_buf
    return loss_acc, grad_acc, extras


# --------------------------------------------------------------------- #
# true 1F1B, interleaved (virtual pipeline) variant
# --------------------------------------------------------------------- #
def spmd_pipeline_1f1b_interleaved(
    stage_fn: Callable,
    loss_fn: Callable,
    stage_params: Any,
    microbatches: jnp.ndarray,
    *,
    axis: str = PIPE_AXIS,
    microbatches_distributed: bool = False,
    skip_dead_ticks: Optional[bool] = None,
    loss_params: Any = None,
    return_input_cotangents: bool = False,
):
    """Interleaved (virtual-pipeline) one-forward-one-backward schedule
    computing ``(loss, grads)`` with O(pp·V) live activations.

    Reference: ``fwd_bwd_pipelining_with_interleaving.py`` — V model
    chunks per rank (global stage ``c*pp + r``), each microbatch laps
    the ring V times, bubble ``(pp-1)/(V·M)``; 1F1B keeps at most
    O(pp·V) microbatch activations live regardless of M.

    Tick table (one ``lax.scan``): forward item ``if = t - rank`` with
    ``if = g·V·pp + c·pp + j`` (microbatch ``m = g·pp + j``, lap ``c``)
    — the circular enumeration of :func:`spmd_pipeline_interleaved`,
    whose ppermute wrap link is the lap hand-off.  Backward items run
    in the order ``ρ(i) = g·V·pp + (V-1-c)·pp + j`` (groups in arrival
    order, laps reversed) at tick ``τ(i, r) = V·pp + ρ(i) +
    (pp-1-r)``: within a lap the cotangent steps down the reverse ring
    one rank per tick, and the lap boundary lines up exactly —
    ``τ(i+pp, 0) = τ(i, pp-1) - 1``, so lap ``c``'s last-rank backward
    consumes the cotangent lap ``c+1``'s rank-0 backward sent through
    the reverse wrap link one tick earlier.  Setting V=1 recovers the
    plain 1F1B table (``τ = pp + m + pp-1-r``).

    The last rank computes each microbatch's loss cotangent right
    after its final-lap forward (tick ``V·pp + ρ(i) - 1``) and feeds
    itself one tick later, exactly like the non-interleaved schedule.
    Stage inputs live in a ``2·V·pp``-slot stash (an item's slot is
    freed after ``≤ 2·V·pp - 1`` ticks, its maximum fwd→bwd distance),
    so memory is flat in M.  Requires ``M % pp == 0`` (the reference's
    interleaved constraint, enforced by the driver).

    ``stage_params`` per rank: leading ``(V, 1, ...)`` axes — the
    ``(V, pp, ...)`` global stack split over ``axis`` on dim 1 — or
    0-d replicated scalars.  Returns ``(loss_local, grads_local)`` as
    in :func:`spmd_pipeline_1f1b`, with ``grads_local`` carrying the
    chunk axis ``(V, ...)``.

    ``microbatches_distributed=True``: ``microbatches`` is the local
    cyclic shard ``(M/pp, mb, ...)`` (rank ``r`` holds ``r::pp``) and a
    feed ring streams each to rank 0 just in time — rank 0 consumes
    lap-0 items at ticks ``t ≡ j (mod V·pp), j < pp``, so all ranks
    inject their next local microbatch every ``V·pp`` ticks, the feed
    shifts one hop toward rank 0 for the first ``pp`` ticks of each
    window and idles the rest.  Per-rank input memory O(M/pp).

    ``loss_params`` / ``return_input_cotangents``: embedding/head
    closure exactly as in :func:`spmd_pipeline_1f1b` — microbatch
    ``m``'s pipeline-input cotangent exits at rank 0's chunk-0
    backward and is stored into an O(M) carry buffer at slot ``m``.
    """
    pp = lax.axis_size(axis)
    rank = lax.axis_index(axis)
    if microbatches_distributed:
        local_n = microbatches.shape[0]
        num_micro = local_n * pp
    else:
        num_micro = microbatches.shape[0]
    if num_micro % pp:
        raise ValueError(
            f"interleaved schedule requires num_microbatches "
            f"({num_micro}) % pipeline size ({pp}) == 0 "
            f"(reference constraint)")

    for leaf in jax.tree.leaves(stage_params):
        if leaf.ndim == 1 or (leaf.ndim >= 2 and leaf.shape[1] != 1):
            raise ValueError(
                f"stage_params leaves must be (V, pp, ...) stacks with "
                f"dim 1 split over '{axis}' to local size 1, or 0-d "
                f"replicated scalars; got local shape {leaf.shape} — "
                f"pass params_spec=P(None, '{axis}', ...)")
    params_local = jax.tree.map(
        lambda a: a[:, 0] if a.ndim >= 2 else a, stage_params)
    stacked = [l for l in jax.tree.leaves(params_local) if l.ndim]
    if not stacked:
        raise ValueError("stage_params has no stacked (V, pp, ...) leaf")
    v = stacked[0].shape[0]

    n_items = num_micro * v
    # last backward: ρ = n_items-1 on rank 0 → t = v·pp + n_items-1 + pp-1
    n_ticks = v * pp + n_items + pp - 1
    n_slots = 2 * v * pp

    mb_shape = microbatches[0]

    if skip_dead_ticks is None:
        # see _unit: cond-skipping requires collective-free bodies
        chunk0 = jax.tree.map(
            lambda a: a[0] if a.ndim else a, params_local)
        if loss_params is None:
            loss_probe = lambda y: loss_fn(y, jnp.int32(0))
        else:
            loss_probe = lambda y: loss_fn(loss_params, y, jnp.int32(0))
        skip_dead_ticks = not (
            _traces_collectives(stage_fn, chunk0, mb_shape)
            or _traces_collectives(loss_probe, mb_shape))

    def varying(x):
        try:
            return lax.pcast(x, (axis,), to="varying")
        except ValueError:
            return x

    # see spmd_pipeline_1f1b: a pipe-invariant loss_params would make
    # the vjp transpose insert a psum inside the loss cond
    if loss_params is not None:
        loss_params = jax.tree.map(varying, loss_params)

    def chunk_params(c):
        return jax.tree.map(
            lambda a: lax.dynamic_index_in_dim(
                a, c, axis=0, keepdims=False) if a.ndim else a,
            params_local)

    def tick(carry, t):
        (fwd_x, bwd_ct, pending_ct, feed, stash, loss_acc,
         grad_acc, lp_grad_acc, ct_buf) = carry

        # ---- forward unit: item if = t - rank ----
        i_f = t - rank
        valid_f = (i_f >= 0) & (i_f < n_items)
        iv = jnp.clip(i_f, 0, n_items - 1)
        g_f = iv // (v * pp)
        rem = iv % (v * pp)
        c_f = rem // pp
        j_f = rem % pp
        m_f = g_f * pp + j_f
        if microbatches_distributed:
            # feed-ring invariant: when rank 0 runs a lap-0 item (tick
            # t ≡ j mod V·pp, j < pp), its feed buffer holds exactly
            # microbatch g·pp + j (see docstring)
            mb = feed
        else:
            mb = lax.dynamic_index_in_dim(microbatches, m_f, axis=0,
                                          keepdims=False)
        # rank 0 lap 0 injects fresh microbatches; every other (rank,
        # lap) consumes the fwd-ring hand-off (wrap link = lap hand-off)
        x = jnp.where((rank == 0) & (c_f == 0), mb, fwd_x)
        y = _unit(
            skip_dead_ticks, valid_f,
            lambda a: varying(stage_fn(chunk_params(c_f), a)),
            lambda a: varying(jnp.zeros_like(a)), x)
        slot_f = iv % n_slots
        new_stash = lax.dynamic_update_index_in_dim(
            stash, x.astype(stash.dtype), slot_f, axis=0)
        stash = jnp.where(valid_f, new_stash, stash)

        # ---- loss + output-cotangent on the last rank, last lap ----
        def loss_and_ct(y):
            if loss_params is None:
                lval, pull = jax.vjp(lambda yy: loss_fn(yy, m_f), y)
            else:
                lval, pull = jax.vjp(
                    lambda lp, yy: loss_fn(lp, yy, m_f), loss_params, y)
            seed = varying(
                (jnp.float32(1) / num_micro).astype(lval.dtype))
            if loss_params is None:
                (ct,) = pull(seed)
                glp = ()
            else:
                glp, ct = pull(seed)
            return (varying(lval.astype(jnp.float32)), varying(ct),
                    jax.tree.map(varying, glp))

        is_last = rank == pp - 1
        fire_loss = valid_f & is_last & (c_f == v - 1)
        lval, maybe_pending, glp = _unit(
            skip_dead_ticks, fire_loss, loss_and_ct,
            lambda y: (varying(jnp.zeros((), jnp.float32)),
                       varying(jnp.zeros_like(y)),
                       jax.tree.map(
                           lambda a: varying(jnp.zeros_like(a)),
                           () if loss_params is None else loss_params)),
            y)
        # only overwrite the pending slot when a loss actually fired —
        # it is consumed exactly one tick later, before the next fire
        new_pending = jnp.where(fire_loss, maybe_pending, pending_ct)
        loss_acc = loss_acc + lval
        if loss_params is not None:
            lp_grad_acc = jax.tree.map(jnp.add, lp_grad_acc, glp)

        # ---- backward unit: ρ = t - v·pp - (pp-1-rank) ----
        rho = t - v * pp - (pp - 1 - rank)
        valid_b = (rho >= 0) & (rho < n_items)
        rv = jnp.clip(rho, 0, n_items - 1)
        g_b = rv // (v * pp)
        remb = rv % (v * pp)
        c_b = (v - 1) - remb // pp          # laps reversed in backward
        j_b = remb % pp
        i_b = g_b * v * pp + c_b * pp + j_b
        x_saved = lax.dynamic_index_in_dim(
            stash, i_b % n_slots, axis=0, keepdims=False)
        # cotangent source: last rank on the final lap feeds itself the
        # pending loss cotangent (computed last tick); everything else
        # reads the reverse ring (whose wrap link 0 -> pp-1 is the
        # backward lap hand-off)
        # (ordered after the forward+loss units — see _after)
        ct_in = _after((y, lval), jnp.where(
            is_last & (c_b == v - 1), pending_ct, bwd_ct))

        def run_bwd(operands):
            x_s, ct = operands
            cp = chunk_params(c_b)
            _, pull = jax.vjp(lambda p, xx: stage_fn(p, xx), cp, x_s)
            gp, gx = pull(ct)
            return jax.tree.map(varying, (gp, gx))

        gp, gx = _unit(
            skip_dead_ticks, valid_b, run_bwd,
            lambda operands: jax.tree.map(varying, (
                jax.tree.map(jnp.zeros_like, chunk_params(0)),
                jnp.zeros_like(operands[0]))),
            (x_saved, ct_in))
        # scatter-accumulate this chunk's parameter grads at index c_b
        grad_acc = jax.tree.map(
            lambda acc, g: lax.dynamic_update_index_in_dim(
                acc,
                lax.dynamic_index_in_dim(acc, c_b, 0, keepdims=False)
                + g, c_b, axis=0) if acc.ndim else acc + g,
            grad_acc, gp)

        # ---- rings (barrier-chained into one device-uniform order) ----
        fwd_x = send_forward_recv_forward(_after(gx, y), axis=axis)
        bwd_ct = send_backward_recv_backward(_after(fwd_x, gx), axis=axis)
        if microbatches_distributed:
            # re-establish the feed invariant for tick t+1: inject the
            # next local microbatch at each V·pp-tick window start,
            # shift one hop toward rank 0 during the window's first pp
            # ticks (the lap-0 consumption phase), idle the rest
            tn = t + 1
            win = tn % (v * pp)
            local_next = lax.dynamic_index_in_dim(
                microbatches,
                jnp.clip(tn // (v * pp), 0, local_n - 1),
                axis=0, keepdims=False)
            shifted = lax.ppermute(
                _after(bwd_ct, feed), axis,
                [(i, (i - 1) % pp) for i in range(pp)])
            feed = jnp.where(
                win == 0, local_next,
                jnp.where(win < pp, shifted, feed))
        if return_input_cotangents:
            # rank 0's chunk-0 backward carries dL/d(pipeline input);
            # store at its microbatch slot — an O(M) carry buffer, not
            # an O(n_ticks) = O(V·M) scan stack
            m_b = g_b * pp + j_b
            upd = lax.dynamic_update_index_in_dim(
                ct_buf, gx.astype(ct_buf.dtype),
                jnp.clip(m_b, 0, num_micro - 1), axis=0)
            ct_buf = jnp.where(
                (rank == 0) & (c_b == 0) & valid_b, upd, ct_buf)
        return (fwd_x, bwd_ct, new_pending, feed, stash, loss_acc,
                grad_acc, lp_grad_acc, ct_buf), None

    feed0 = (varying(microbatches[0]) if microbatches_distributed
             else varying(jnp.zeros((), mb_shape.dtype)))
    init = (
        varying(jnp.zeros_like(mb_shape)),                  # fwd ring
        varying(jnp.zeros_like(mb_shape)),                  # bwd ring
        varying(jnp.zeros_like(mb_shape)),                  # pending ct
        feed0,                                              # feed ring
        varying(jnp.zeros((n_slots,) + mb_shape.shape,
                          mb_shape.dtype)),                 # stash
        varying(jnp.zeros((), jnp.float32)),                # loss acc
        jax.tree.map(jnp.zeros_like, params_local),          # grad acc
        jax.tree.map(lambda a: varying(jnp.zeros_like(a)),
                     () if loss_params is None else loss_params),
        varying(jnp.zeros(                                  # ct buffer
            ((num_micro,) if return_input_cotangents else (0,))
            + mb_shape.shape, mb_shape.dtype)),
    )
    carry, _ = lax.scan(tick, init, jnp.arange(n_ticks))
    loss_acc, grad_acc, lp_grad_acc, ct_buf = (
        carry[-4], carry[-3], carry[-2], carry[-1])
    if loss_params is None and not return_input_cotangents:
        return loss_acc, grad_acc
    extras = {}
    if loss_params is not None:
        extras["loss_params_grads"] = lp_grad_acc
    if return_input_cotangents:
        extras["input_cotangents"] = ct_buf
    return loss_acc, grad_acc, extras


# --------------------------------------------------------------------- #
# interleaved (virtual pipeline) variant — the circular schedule
# --------------------------------------------------------------------- #
def spmd_pipeline_interleaved(
    stage_fn: Callable,
    stage_params: Any,
    microbatches: jnp.ndarray,
    *,
    axis: str = PIPE_AXIS,
    remat: bool = True,
):
    """Virtual-pipeline forward: each rank holds ``V`` model chunks.

    Reference: ``fwd_bwd_pipelining_with_interleaving.py`` — global
    stage ``s = c*pp + r`` lives on rank ``r`` as chunk ``c``, and a
    microbatch circles the ring ``V`` times; the bubble shrinks from
    ``(pp-1)/M`` to ``(pp-1)/(V·M)`` ticks.

    TPU form: one ``lax.scan`` over ``M·V + pp - 1`` ticks.  Item
    ``i = t - rank`` enumerates (group g, lap c, slot j) in the order
    ``i = g·V·pp + c·pp + j`` with microbatch ``m = g·pp + j`` — chosen
    so a microbatch leaving rank ``pp-1`` on lap ``c`` re-enters rank 0
    on lap ``c+1`` exactly one tick later: the wrap link of the same
    ``ppermute`` ring IS the lap hand-off, every rank is busy every
    valid tick, and no inter-lap buffering exists.  Requires
    ``M % pp == 0`` (the reference's interleaved constraint).  Backward
    is the transposed scan, as in :func:`spmd_pipeline`.

    ``stage_params`` per rank: leading axes ``(V, 1, ...)`` — a
    ``(V, pp, ...)`` global stack split over ``axis`` on dim 1.
    Returns ``(M, mb, seq, hidden)`` last-lap outputs, replicated.
    """
    pp = lax.axis_size(axis)
    rank = lax.axis_index(axis)
    num_micro = microbatches.shape[0]
    if num_micro % pp:
        raise ValueError(
            f"interleaved schedule requires num_microbatches "
            f"({num_micro}) % pipeline size ({pp}) == 0 "
            f"(reference constraint)")

    # strip the split pp dim (local size 1) from the (V, pp, ...) stack;
    # 0-d leaves are replicated scalars shared by every chunk (same
    # convention as spmd_pipeline), anything else must carry the stack
    for leaf in jax.tree.leaves(stage_params):
        if leaf.ndim == 1 or (leaf.ndim >= 2 and leaf.shape[1] != 1):
            raise ValueError(
                f"stage_params leaves must be (V, pp, ...) stacks with "
                f"dim 1 split over '{axis}' to local size 1, or 0-d "
                f"replicated scalars; got local shape {leaf.shape} — "
                f"pass params_spec=P(None, '{axis}', ...)")
    stage_params = jax.tree.map(
        lambda a: a[:, 0] if a.ndim >= 2 else a, stage_params)
    stacked = [l for l in jax.tree.leaves(stage_params) if l.ndim]
    if not stacked:
        raise ValueError("stage_params has no stacked (V, pp, ...) leaf")
    v = stacked[0].shape[0]

    body = stage_fn
    if remat:
        body = jax.checkpoint(
            stage_fn, policy=jax.checkpoint_policies.nothing_saveable)

    n_items = num_micro * v
    n_ticks = n_items + pp - 1

    def tick(carry, t):
        recv = carry
        i = t - rank                       # this rank's item index
        iv = jnp.clip(i, 0, n_items - 1)
        g = iv // (v * pp)
        rem = iv % (v * pp)
        c = rem // pp                      # lap / chunk index
        j = rem % pp
        m = g * pp + j                     # microbatch index
        mb = lax.dynamic_index_in_dim(microbatches, m, axis=0,
                                      keepdims=False)
        # rank 0 injects fresh microbatches on lap 0; all other
        # (rank, lap) combinations consume the ring hand-off
        x = jnp.where((rank == 0) & (c == 0), mb, recv)
        chunk_params = jax.tree.map(
            lambda a: lax.dynamic_index_in_dim(
                a, c, axis=0, keepdims=False) if a.ndim else a,
            stage_params)
        y = body(chunk_params, x)
        nxt = send_forward_recv_forward(y, axis=axis)
        return nxt, y

    init = jnp.zeros_like(microbatches[0])
    init = lax.pcast(init, (axis,), to="varying")
    _, ys = lax.scan(tick, init, jnp.arange(n_ticks))

    # final output of microbatch m = (g, j): item g·V·pp + (V-1)·pp + j
    # finishes on rank pp-1 at tick item + pp - 1
    ms = jnp.arange(num_micro)
    out_ticks = (ms // pp) * (v * pp) + (v - 1) * pp + (ms % pp) + pp - 1
    outs = jnp.take(ys, out_ticks, axis=0)
    outs = lax.psum(
        jnp.where(rank == pp - 1, outs, jnp.zeros_like(outs)), axis)
    return outs


# --------------------------------------------------------------------- #
# reference-named drivers
# --------------------------------------------------------------------- #
def forward_backward_no_pipelining(
    forward_step_func: Callable,
    batch: Any,
    model_params: Any,
    *,
    num_microbatches: Optional[int] = None,
):
    """Grad accumulation over microbatches, no pipeline (reference:
    ``fwd_bwd_no_pipelining.py``).

    ``forward_step_func(params, microbatch) -> scalar loss`` (mean over
    the microbatch).  ``batch`` is a pytree whose leaves have a leading
    ``(M * mb)`` dim.  Returns ``(mean_loss, grads)`` — one jit-fused
    accumulation loop (``lax.scan``), the analogue of the reference's
    ``no_sync``-until-last-microbatch.
    """
    m = num_microbatches or get_num_microbatches()
    mbs = jax.tree.map(
        lambda x: x.reshape(m, x.shape[0] // m, *x.shape[1:]), batch)

    grad_fn = jax.value_and_grad(forward_step_func)

    def step(acc, mb):
        loss, g = grad_fn(model_params, mb)
        acc_loss, acc_g = acc
        return (acc_loss + loss,
                jax.tree.map(jnp.add, acc_g, g)), None

    zero = (jnp.zeros((), jnp.float32),
            jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32),
                         model_params))
    (loss_sum, grad_sum), _ = lax.scan(step, zero, mbs)
    inv = 1.0 / m
    return loss_sum * inv, jax.tree.map(lambda g: g * inv, grad_sum)


def _pipelined_value_and_grad(
    pipeline_fn: Callable,
    default_pspec: Callable[[str], P],
    stage_fn: Callable,
    loss_fn: Callable,
    stage_params: Any,
    batch: jnp.ndarray,
    *,
    mesh: Mesh,
    num_microbatches: Optional[int],
    axis: str,
    remat: bool,
    params_spec: Optional[Any],
):
    """Shared driver for both pipeline schedules: shard_map over the
    pipe axis, vmap the loss over last-stage outputs, value_and_grad."""
    m = num_microbatches or get_num_microbatches()
    mbs = batch.reshape(m, batch.shape[0] // m, *batch.shape[1:])
    pspec = params_spec if params_spec is not None else default_pspec(axis)

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(pspec, P()), out_specs=P(),
        # only `pipe` goes manual: data/tensor axes inside the stage
        # remain GSPMD-managed, so TP layers compose with the pipeline.
        # check_vma must stay on — with it off, grad-of-partial-manual
        # shard_map fails out_specs validation on inferred residuals
        axis_names={axis})
    def pipelined_loss(params_local, mbs_local):
        outs = pipeline_fn(stage_fn, params_local, mbs_local,
                           axis=axis, remat=remat)
        losses = jax.vmap(loss_fn)(outs, jnp.arange(m))
        return jnp.mean(losses)

    return jax.value_and_grad(pipelined_loss)(stage_params, mbs)


def _distribute_microbatches(mbs, m, mesh, axis):
    """Cyclic microbatch sharding over the pipe ranks (rank r holds
    ``r::pp``) for the feed-ring drivers: returns ``(mbs, mb_spec,
    distributed)``; falls back to replicated when M %% pp != 0."""
    pp_size = mesh.shape[axis]
    if pp_size > 1 and m % pp_size == 0:
        mbs = jnp.swapaxes(
            mbs.reshape(m // pp_size, pp_size, *mbs.shape[1:]), 0, 1)
        return mbs, P(axis), True
    return mbs, P(), False


def forward_backward_pipelining_without_interleaving(
    stage_fn: Callable,
    loss_fn: Callable,
    stage_params: Any,
    batch: jnp.ndarray,
    *,
    mesh: Mesh,
    num_microbatches: Optional[int] = None,
    axis: str = PIPE_AXIS,
    remat: bool = True,
    params_spec: Optional[Any] = None,
    skip_dead_ticks: Optional[bool] = None,
    loss_params: Any = None,
    return_input_cotangents: bool = False,
    distribute_inputs: bool = True,
):
    """Pipelined forward+backward (reference: 1F1B,
    ``fwd_bwd_pipelining_without_interleaving.py``).

    ``stage_fn(stage_params, x) -> y`` is one pipeline stage (its params
    are ``stage_params`` with the leading ``pp`` axis removed);
    ``loss_fn(y, microbatch_index) -> scalar`` scores last-stage output.
    ``batch``: ``(M * mb, seq, hidden)``.  Returns ``(loss, grads)``
    with ``grads`` matching ``stage_params``.

    ``loss_params`` / ``return_input_cotangents`` close the
    embedding/head gradients over the pipelined region (see
    :func:`spmd_pipeline_1f1b`): with either set, returns ``(loss,
    grads, aux)`` where ``aux["loss_params_grads"]`` matches
    ``loss_params`` (already summed over ranks) and
    ``aux["input_cotangents"]`` is ``(M, mb, ...)`` — ``dL/dh`` per
    pipeline-input microbatch, replicated over ``axis``.

    ``distribute_inputs=False`` disables the O(M/pp) cyclic microbatch
    sharding (feed ring) and replicates the inputs over ``axis``
    instead — GSPMD then moves batch-sharded inputs with an all-gather
    rather than an all-to-all.  Use when M is small enough that input
    memory doesn't matter, or on backends whose all-to-all is fragile
    (XLA:CPU's in-process communicator).

    This drives :func:`spmd_pipeline_1f1b` — the explicit
    one-forward-one-backward tick table with O(pp) live activations —
    rather than autodiff over the forward scan (which would stash all
    ``M + pp - 1`` tick outputs).  ``remat`` is accepted for API
    stability but has no effect: 1F1B recomputes each stage interior
    from its stashed input by construction (``jax.vjp`` per backward
    unit), which is exactly ``remat=True`` semantics.
    """
    del remat  # remat-by-construction (see docstring)
    m = num_microbatches or get_num_microbatches()
    mbs = batch.reshape(m, batch.shape[0] // m, *batch.shape[1:])
    pspec = params_spec if params_spec is not None else P(axis)

    # shard the microbatch axis over the pipe ranks (cyclic) so
    # per-rank input memory is O(M/pp) — the feed ring inside
    # spmd_pipeline_1f1b streams them to rank 0
    if distribute_inputs:
        mbs, mb_spec, distributed = _distribute_microbatches(
            mbs, m, mesh, axis)
    else:
        mb_spec, distributed = P(), False

    has_aux = loss_params is not None or return_input_cotangents
    aux_specs = _closure_aux_specs(loss_params, return_input_cotangents)

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(pspec, mb_spec),
        out_specs=((P(), pspec, aux_specs) if has_aux
                   else (P(), pspec)),
        # only `pipe` goes manual: data/tensor axes inside the stage
        # remain GSPMD-managed, so TP layers compose with the pipeline
        axis_names={axis})
    def run(params_local, mbs_local):
        if distributed:
            mbs_local = mbs_local[0]     # strip the split pp dim
        out = spmd_pipeline_1f1b(
            stage_fn, loss_fn, params_local, mbs_local, axis=axis,
            microbatches_distributed=distributed,
            skip_dead_ticks=skip_dead_ticks,
            loss_params=loss_params,
            return_input_cotangents=return_input_cotangents)
        loss_local, grads_local = out[0], out[1]
        # loss_local is the per-microbatch sum on rank pp-1, 0 elsewhere
        loss = lax.psum(loss_local, axis) / m
        # restore the stripped stacked-stage axis for the out_spec
        # (judge by the LOCAL leaf: ndim>=1 means it carried the split
        # stage axis; 0-d leaves were replicated scalars whose grad is
        # the sum of every stage's contribution)
        grads = jax.tree.map(
            lambda g, a: g[None] if a.ndim else lax.psum(g, axis),
            grads_local, params_local)
        if not has_aux:
            return loss, grads
        return loss, grads, _closure_aux_collect(
            out[2], loss_params, return_input_cotangents, axis)

    return run(stage_params, mbs)


def forward_backward_pipelining_with_interleaving(
    stage_fn: Callable,
    loss_fn: Callable,
    stage_params: Any,
    batch: jnp.ndarray,
    *,
    mesh: Mesh,
    num_microbatches: Optional[int] = None,
    axis: str = PIPE_AXIS,
    remat: bool = True,
    params_spec: Optional[Any] = None,
    skip_dead_ticks: Optional[bool] = None,
    loss_params: Any = None,
    return_input_cotangents: bool = False,
):
    """Interleaved pipelined forward+backward (reference:
    ``fwd_bwd_pipelining_with_interleaving.py``).

    Like :func:`forward_backward_pipelining_without_interleaving`, but
    ``stage_params`` carries a leading ``(V, pp)`` double stack — chunk
    ``c`` on rank ``r`` implements global stage ``c*pp + r`` — so each
    microbatch makes ``V`` laps around the ring.  Requires
    ``num_microbatches % pp == 0``.

    Drives :func:`spmd_pipeline_1f1b_interleaved` — the explicit
    interleaved 1F1B tick table with O(pp·V) live activations —
    rather than autodiff over the circular forward scan (which would
    stash all ``M·V + pp - 1`` tick outputs).  ``remat`` is accepted
    for API stability but has no effect: each backward unit recomputes
    its stage interior from the stashed input by construction.

    ``loss_params`` / ``return_input_cotangents``: embedding/head
    closure with the same semantics and ``aux`` shape as
    :func:`forward_backward_pipelining_without_interleaving`.
    """
    del remat  # remat-by-construction (see docstring)
    m = num_microbatches or get_num_microbatches()
    mbs = batch.reshape(m, batch.shape[0] // m, *batch.shape[1:])
    pspec = params_spec if params_spec is not None else P(None, axis)

    # cyclic microbatch sharding + feed-ring streaming, as in the
    # non-interleaved driver: per-rank input memory O(M/pp)
    mbs, mb_spec, distributed = _distribute_microbatches(
        mbs, m, mesh, axis)

    has_aux = loss_params is not None or return_input_cotangents
    aux_specs = _closure_aux_specs(loss_params, return_input_cotangents)

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(pspec, mb_spec),
        out_specs=((P(), pspec, aux_specs) if has_aux
                   else (P(), pspec)),
        axis_names={axis})
    def run(params_local, mbs_local):
        if distributed:
            mbs_local = mbs_local[0]     # strip the split pp dim
        out = spmd_pipeline_1f1b_interleaved(
            stage_fn, loss_fn, params_local, mbs_local, axis=axis,
            microbatches_distributed=distributed,
            skip_dead_ticks=skip_dead_ticks,
            loss_params=loss_params,
            return_input_cotangents=return_input_cotangents)
        loss_local, grads_local = out[0], out[1]
        loss = lax.psum(loss_local, axis) / m
        # restore the stripped split-pp axis for the out_spec: local
        # grads are (V, ...); the spec expects (V, 1, ...).  0-d
        # replicated scalars psum every stage's contribution.
        grads = jax.tree.map(
            lambda g, a: g[:, None] if a.ndim else lax.psum(g, axis),
            grads_local, params_local)
        if not has_aux:
            return loss, grads
        return loss, grads, _closure_aux_collect(
            out[2], loss_params, return_input_cotangents, axis)

    return run(stage_params, mbs)


def get_forward_backward_func(
    pipeline_model_parallel_size: int = 1,
    virtual_pipeline_model_parallel_size: Optional[int] = None,
):
    """Reference dispatch (``schedules/common.py``): pick the schedule
    from the pipeline topology."""
    if pipeline_model_parallel_size > 1:
        if virtual_pipeline_model_parallel_size is not None \
                and virtual_pipeline_model_parallel_size > 1:
            return forward_backward_pipelining_with_interleaving
        return forward_backward_pipelining_without_interleaving
    return forward_backward_no_pipelining
