"""apex_tpu.transformer.pipeline_parallel — microbatch pipeline engine.

Reference: ``apex/transformer/pipeline_parallel/`` (schedules +
p2p_communication + utils).  See :mod:`.schedules` for the TPU design
(scan + ppermute inside shard_map; backward by transposition).
"""

from apex_tpu.transformer.pipeline_parallel.build import build_model
from apex_tpu.transformer.pipeline_parallel.schedules import (
    spmd_pipeline,
    spmd_pipeline_interleaved,
    forward_backward_no_pipelining,
    forward_backward_pipelining_without_interleaving,
    forward_backward_pipelining_with_interleaving,
    get_forward_backward_func,
)
from apex_tpu.transformer.pipeline_parallel import p2p

__all__ = [
    "build_model",
    "spmd_pipeline",
    "spmd_pipeline_interleaved",
    "forward_backward_no_pipelining",
    "forward_backward_pipelining_without_interleaving",
    "forward_backward_pipelining_with_interleaving",
    "get_forward_backward_func",
    "p2p",
]
