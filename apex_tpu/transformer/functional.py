"""apex_tpu.transformer.functional — reference-named fused functionals.

Reference: ``apex/transformer/functional/{fused_softmax,fused_rope}.py``
— the ``FusedScaleMaskSoftmax`` wrapper (picks the scaled / masked /
upper-triangular CUDA kernel by ``AttnMaskType`` and shape limits) and
``fused_apply_rotary_pos_emb*``.  Thin aliases over the Pallas ops,
kept so code written against the reference's import paths reads the
same; the shape-limit fallback logic dissolves (the Pallas dispatch in
:mod:`apex_tpu.ops` handles envelopes per call).
"""

from __future__ import annotations

from typing import Optional

from apex_tpu.ops.rope import fused_rope, rope_cos_sin
from apex_tpu.ops.softmax import fused_scale_mask_softmax
from apex_tpu.transformer.enums import AttnMaskType

__all__ = ["FusedScaleMaskSoftmax", "fused_apply_rotary_pos_emb",
           "fused_apply_rotary_pos_emb_cached"]


class FusedScaleMaskSoftmax:
    """Callable with the reference's constructor shape.

    ``attn_mask_type``: :class:`AttnMaskType` — ``causal`` applies the
    in-kernel upper-triangular mask (reference's
    ``scaled_upper_triang_masked_softmax``); ``padding`` expects an
    explicit boolean mask (True = masked) at call time.
    """

    def __init__(self, attn_mask_type: AttnMaskType = AttnMaskType.padding,
                 scale: Optional[float] = None,
                 scaled_masked_softmax_fusion: bool = True):
        self.attn_mask_type = attn_mask_type
        self.scale = 1.0 if scale is None else float(scale)
        # fusion flag kept for signature parity; the Pallas/XLA choice
        # is the ops-level dispatch ("auto")
        self.fusion = scaled_masked_softmax_fusion

    def __call__(self, x, mask=None):
        return fused_scale_mask_softmax(
            x, mask, scale=self.scale,
            causal=(self.attn_mask_type == AttnMaskType.causal),
            implementation=None if self.fusion else "xla")


def fused_apply_rotary_pos_emb(t, cos=None, sin=None, *, base=10000.0):
    """RoPE with on-the-fly tables (``fused_apply_rotary_pos_emb``).

    ``t``: (batch, seq, heads, dim).  ``cos``/``sin`` optional
    precomputed tables (see :func:`fused_apply_rotary_pos_emb_cached`).
    """
    if cos is None or sin is None:
        cos, sin = rope_cos_sin(t.shape[1], t.shape[-1], base=base)
    return fused_rope(t, cos, sin)


def fused_apply_rotary_pos_emb_cached(t, cos, sin):
    """RoPE with caller-cached cos/sin tables (reference's ``_cached``
    variant; identical math, tables reused across layers)."""
    return fused_rope(t, cos, sin)
