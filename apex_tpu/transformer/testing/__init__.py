"""apex_tpu.transformer.testing — shared distributed-test harness.

Reference: ``apex/transformer/testing/{commons,standalone_gpt,
standalone_bert}.py`` — the toy models + process-group bring-up the
reference's TP/PP test suite shares (SURVEY.md §2.6, §4).
"""

from apex_tpu.transformer.testing.commons import (
    set_random_seed,
    initialize_distributed,
    standalone_gpt,
    standalone_bert,
    random_token_batch,
)

__all__ = [
    "set_random_seed",
    "initialize_distributed",
    "standalone_gpt",
    "standalone_bert",
    "random_token_batch",
]
