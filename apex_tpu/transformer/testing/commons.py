"""Test-harness commons (``apex/transformer/testing/commons.py`` parity).

The reference's ``initialize_distributed`` spins up torch.distributed +
NCCL groups; here the analogue is building the named mesh (on virtual
CPU devices in CI).  ``standalone_gpt``/``standalone_bert`` return the
tiny models + initialized params the schedule/TP tests train.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.core import mesh as mesh_lib
from apex_tpu.models.bert import BertConfig, BertModel
from apex_tpu.models.gpt import GPTConfig, GPTModel

__all__ = ["set_random_seed", "initialize_distributed",
           "standalone_gpt", "standalone_bert", "random_token_batch"]


def set_random_seed(seed: int) -> jax.Array:
    """Seed numpy + return a JAX PRNG key.

    Parity: the reference seeds python/numpy/torch/CUDA and the
    model-parallel RNG tracker; JAX's functional keys replace the
    tracker (fold per mesh coordinate where needed —
    ``apex_tpu.transformer.random``).
    """
    np.random.seed(seed)
    return jax.random.PRNGKey(seed)


def initialize_distributed(tensor_model_parallel_size: int = 1,
                           pipeline_model_parallel_size: int = 1,
                           **kw):
    """Build the test mesh (``initialize_distributed`` +
    ``initialize_model_parallel`` rolled into one — topology is
    declarative on TPU)."""
    return mesh_lib.initialize_mesh(
        tensor_model_parallel_size=tensor_model_parallel_size,
        pipeline_model_parallel_size=pipeline_model_parallel_size,
        **kw)


def standalone_gpt(seed: int = 0, **cfg_kw) -> Tuple[GPTModel, dict]:
    """Tiny GPT + params (``standalone_gpt.py`` parity)."""
    cfg = GPTConfig.tiny(**cfg_kw)
    model = GPTModel(cfg)
    key = set_random_seed(seed)
    tokens = jnp.zeros((2, 8), jnp.int32)
    params = model.init(key, tokens)["params"]
    return model, params


def standalone_bert(seed: int = 0, **cfg_kw) -> Tuple[BertModel, dict]:
    """Tiny BERT + params (``standalone_bert.py`` parity)."""
    cfg = BertConfig.tiny(**cfg_kw)
    model = BertModel(cfg)
    key = set_random_seed(seed)
    tokens = jnp.zeros((2, 8), jnp.int32)
    params = model.init(key, tokens)["params"]
    return model, params


def random_token_batch(key: jax.Array, batch: int, seq: int,
                       vocab: int,
                       dtype=jnp.int32) -> Tuple[jax.Array, jax.Array]:
    """(input_ids, labels) for LM tests: labels = inputs shifted left."""
    ids = jax.random.randint(key, (batch, seq + 1), 0, vocab, dtype)
    return ids[:, :-1], ids[:, 1:]
