"""Mixture-of-Experts FFN with expert parallelism.

**Beyond-reference extension** (SURVEY.md §2.6 checklist: "EP / MoE:
ABSENT" in apex) — included because expert parallelism is a
first-class axis of modern TPU training, alongside the ring-attention
context parallelism.

Design (GShard-style dense dispatch, TPU-shaped):

- token-choice top-k gating with load-balancing auxiliary loss;
- capacity-bounded dispatch/combine as einsums against a one-hot
  dispatch mask — dense, static-shaped, MXU-friendly (no ragged
  scatter);
- the stacked expert weights ``(E, ...)`` carry a sharding spec over a
  mesh axis (``expert_axis``); under GSPMD the dispatch einsum lowers
  to the all-to-all that routes tokens to expert shards, exactly where
  a NCCL implementation hand-codes ``all_to_all``.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import flax.linen as nn

from apex_tpu.core.mesh import TENSOR_AXIS
from apex_tpu.ops.mlp import resolve_activation

__all__ = ["MoEConfig", "top_k_gating", "MoEMLP"]


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    num_experts: int = 8
    top_k: int = 2
    # per-group expert capacity = capacity_factor * S*k/E (group = batch
    # row; bounds dispatch memory linearly in the global token count)
    capacity_factor: float = 1.25
    hidden_size: int = 1024
    ffn_hidden_size: Optional[int] = None
    activation: str = "gelu"
    # gated-linear-unit experts (SwiGLU when activation="silu") — the
    # Mixtral expert shape: act(x·w1) * (x·wg) -> w2
    gated: bool = False
    # expert biases (b1/b2); False for the bias-free Llama/Mixtral
    # recipes (plumbed from TransformerConfig.add_bias_linear)
    use_bias: bool = True
    expert_axis: Optional[str] = TENSOR_AXIS
    aux_loss_weight: float = 1e-2
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @property
    def ffn_size(self) -> int:
        return self.ffn_hidden_size or 4 * self.hidden_size


def top_k_gating(logits: jax.Array, k: int, capacity: int
                 ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Token-choice top-k routing with capacity.

    ``logits``: (T, E).  Returns ``(dispatch, combine, aux_loss)``:
    ``dispatch`` (T, E, C) one-hot routing mask, ``combine`` (T, E, C)
    = dispatch * gate probability, ``aux_loss`` the Switch/GShard
    load-balancing term (mean_prob · mean_assignment · E).
    Tokens beyond an expert's capacity are dropped (standard GShard
    semantics); position within the expert buffer is assigned in token
    order via a cumulative count.
    """
    t, e = logits.shape
    if k > e:
        raise ValueError(
            f"top_k ({k}) cannot exceed num_experts ({e}) — later "
            f"routing rounds would silently double-route to expert 0")
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    dispatch = jnp.zeros((t, e, capacity), jnp.float32)
    combine = jnp.zeros((t, e, capacity), jnp.float32)
    # running per-expert fill count across the k routing rounds
    fill = jnp.zeros((e,), jnp.int32)
    masked = probs
    assign_frac = jnp.zeros((e,), jnp.float32)
    for _ in range(k):
        choice = jnp.argmax(masked, axis=-1)               # (T,)
        onehot = jax.nn.one_hot(choice, e, dtype=jnp.float32)
        assign_frac = assign_frac + jnp.mean(onehot, axis=0)
        # position of each token in its chosen expert's buffer
        pos = jnp.cumsum(onehot, axis=0) - 1.0 + fill[None, :]
        pos_tok = jnp.sum(pos * onehot, axis=-1).astype(jnp.int32)
        keep = pos_tok < capacity
        poh = jax.nn.one_hot(pos_tok, capacity, dtype=jnp.float32)
        d = (onehot * keep[:, None].astype(jnp.float32))[..., None] \
            * poh[:, None, :]
        gate = jnp.sum(probs * onehot, axis=-1)            # (T,)
        dispatch = dispatch + d
        combine = combine + d * gate[:, None, None]
        fill = fill + jnp.sum(
            onehot * keep[:, None], axis=0).astype(jnp.int32)
        masked = masked * (1.0 - onehot)                   # next round
    # load-balance loss (Switch eq. 4): E * Σ_e mean_prob_e * frac_e
    aux = e * jnp.sum(jnp.mean(probs, axis=0) * assign_frac / k)
    if k > 1:
        # renormalize combine weights over the k selected experts
        denom = jnp.sum(combine, axis=(1, 2), keepdims=True)
        combine = combine / jnp.maximum(denom, 1e-9)
    # k == 1 keeps the raw gate probability as the output scale
    # (Switch semantics) — renormalizing would make it identically 1
    # and cut the router off from the task-loss gradient.
    return dispatch, combine, aux


class MoEMLP(nn.Module):
    """MoE FFN block: gate → dispatch → stacked expert MLPs → combine.

    Drop-in for a dense ``ParallelMLP``; returns ``(y, aux_loss)``.
    Expert weights are stacked ``(E, ...)`` and sharded over
    ``cfg.expert_axis`` — GSPMD inserts the token all-to-all.

    Tokens are routed **per group** (group = batch row, GShard-style):
    per-expert capacity is ``cf·S·k/E`` *per group*, so dispatch/combine
    masks are ``(B, S, E, C)`` — linear in the global token count
    instead of the quadratic blowup of a single flat token pool.
    """

    cfg: MoEConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        b, s, h = x.shape
        e = cfg.num_experts
        # ceil, not floor: the documented contract is "at least
        # cf·S·k/E slots"; truncation would drop tokens at nearly
        # double the configured rate at small S
        capacity = max(1, math.ceil(
            cfg.capacity_factor * s * cfg.top_k / e))

        gate_w = self.param("gate", nn.initializers.normal(0.02),
                            (h, e), cfg.param_dtype)
        logits = jnp.einsum("gsh,he->gse", x.astype(jnp.float32),
                            gate_w.astype(jnp.float32))
        dispatch, combine, aux = jax.vmap(
            lambda lg: top_k_gating(lg, cfg.top_k, capacity))(logits)
        aux = jnp.mean(aux)

        part = nn.with_partitioning if cfg.expert_axis else (
            lambda init, spec: init)
        w1 = self.param(
            "w1", part(nn.initializers.he_normal(),
                       (cfg.expert_axis, None, None)),
            (e, h, cfg.ffn_size), cfg.param_dtype)
        w2 = self.param(
            "w2", part(nn.initializers.he_normal(),
                       (cfg.expert_axis, None, None)),
            (e, cfg.ffn_size, h), cfg.param_dtype)
        if cfg.use_bias:
            b1 = self.param(
                "b1", part(nn.initializers.zeros_init(),
                           (cfg.expert_axis, None)),
                (e, cfg.ffn_size), cfg.param_dtype)
            b2 = self.param(
                "b2", part(nn.initializers.zeros_init(),
                           (cfg.expert_axis, None)),
                (e, h), cfg.param_dtype)

        # dispatch: (G,S,E,C) x (G,S,H) -> (G,E,C,H); GSPMD turns the
        # E-sharded contraction into the token all-to-all
        xin = jnp.einsum("gsec,gsh->gech", dispatch.astype(cfg.dtype),
                         x.astype(cfg.dtype))
        act = resolve_activation(cfg.activation, gelu_approximate=True)
        pre = jnp.einsum(
            "gech,ehf->gecf", xin, w1.astype(cfg.dtype),
            preferred_element_type=jnp.float32)
        if cfg.use_bias:
            pre = pre + b1[None, :, None].astype(jnp.float32)
        hmid = act(pre)
        if cfg.gated:
            # SwiGLU-style experts (Mixtral): elementwise gate from a
            # third expert matrix, sharded identically over the
            # expert axis (no bias, as the Llama-family recipe)
            wg = self.param(
                "wg", part(nn.initializers.he_normal(),
                           (cfg.expert_axis, None, None)),
                (e, h, cfg.ffn_size), cfg.param_dtype)
            hmid = hmid * jnp.einsum(
                "gech,ehf->gecf", xin, wg.astype(cfg.dtype),
                preferred_element_type=jnp.float32)
        yout = jnp.einsum(
            "gecf,efh->gech", hmid.astype(cfg.dtype),
            w2.astype(cfg.dtype),
            preferred_element_type=jnp.float32)
        if cfg.use_bias:
            yout = yout + b2[None, :, None].astype(jnp.float32)
        y = jnp.einsum("gsec,gech->gsh", combine, yout)
        return y.astype(x.dtype), cfg.aux_loss_weight * aux
