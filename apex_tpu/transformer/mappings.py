"""Tensor-parallel autograd collectives (the Megatron "f"/"g" functions).

Reference: ``apex/transformer/tensor_parallel/mappings.py`` — the four
autograd-paired collectives over the TP process group, plus the
sequence-parallel pair:

==============================  ===========  ============
function                        forward      backward
==============================  ===========  ============
``copy_to_...``         ("f")   identity     all-reduce
``reduce_from_...``     ("g")   all-reduce   identity
``scatter_to_...``              slice chunk  all-gather
``gather_from_...``             all-gather   slice chunk
``reduce_scatter_to_sequence_parallel_...``  reduce-scatter  all-gather
``gather_from_sequence_parallel_...``        all-gather      reduce-scatter
==============================  ===========  ============

TPU translation: these are ``custom_vjp`` functions over named mesh
axes, usable inside ``shard_map``; the collectives are
``lax.psum`` / ``lax.all_gather`` / ``lax.psum_scatter`` riding ICI.
When layers are expressed with GSPMD sharding specs instead
(:mod:`apex_tpu.transformer.layers`), XLA inserts these same collectives
automatically and the duality is handled by transposition — these
explicit forms exist for schedule-controlled (``shard_map``) code, which
is exactly the role the reference's mappings play for Megatron.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.core.mesh import TENSOR_AXIS

__all__ = [
    "copy_to_tensor_parallel_region",
    "reduce_from_tensor_parallel_region",
    "scatter_to_tensor_parallel_region",
    "gather_from_tensor_parallel_region",
    "reduce_scatter_to_sequence_parallel_region",
    "gather_from_sequence_parallel_region",
    "scatter_to_sequence_parallel_region",
]


# --------------------------------------------------------------------- #
# f: identity fwd / all-reduce bwd
# --------------------------------------------------------------------- #
@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def copy_to_tensor_parallel_region(x, axis: str = TENSOR_AXIS):
    """Megatron ``f``: replicated input entering a TP-sharded block."""
    return x


def _copy_fwd(x, axis):
    return x, None


def _copy_bwd(axis, _, g):
    return (lax.psum(g, axis),)


copy_to_tensor_parallel_region.defvjp(_copy_fwd, _copy_bwd)


# --------------------------------------------------------------------- #
# g: all-reduce fwd / identity bwd
# --------------------------------------------------------------------- #
@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def reduce_from_tensor_parallel_region(x, axis: str = TENSOR_AXIS):
    """Megatron ``g``: partial sums leaving a TP-sharded block."""
    return lax.psum(x, axis)


def _reduce_fwd(x, axis):
    return lax.psum(x, axis), None


def _reduce_bwd(axis, _, g):
    return (g,)


reduce_from_tensor_parallel_region.defvjp(_reduce_fwd, _reduce_bwd)


# --------------------------------------------------------------------- #
# scatter / gather along the last (feature) dim
# --------------------------------------------------------------------- #
def _split_dim(x, axis_name, dim):
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    size = x.shape[dim] // n
    return lax.dynamic_slice_in_dim(x, idx * size, size, axis=dim)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def scatter_to_tensor_parallel_region(x, axis: str = TENSOR_AXIS,
                                      dim: int = -1):
    """Slice this rank's feature chunk (fwd) / all-gather (bwd)."""
    return _split_dim(x, axis, dim)


def _scatter_fwd(x, axis, dim):
    return _split_dim(x, axis, dim), None


def _scatter_bwd(axis, dim, _, g):
    return (lax.all_gather(g, axis, axis=dim, tiled=True),)


scatter_to_tensor_parallel_region.defvjp(_scatter_fwd, _scatter_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def gather_from_tensor_parallel_region(x, axis: str = TENSOR_AXIS,
                                       dim: int = -1):
    """All-gather feature chunks (fwd) / slice own chunk (bwd)."""
    return lax.all_gather(x, axis, axis=dim, tiled=True)


def _gather_fwd(x, axis, dim):
    return lax.all_gather(x, axis, axis=dim, tiled=True), None


def _gather_bwd(axis, dim, _, g):
    return (_split_dim(g, axis, dim),)


gather_from_tensor_parallel_region.defvjp(_gather_fwd, _gather_bwd)


# --------------------------------------------------------------------- #
# sequence-parallel pair (Korthikanti et al.; reference's SP mappings)
# --------------------------------------------------------------------- #
@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def reduce_scatter_to_sequence_parallel_region(x, axis: str = TENSOR_AXIS,
                                               dim: int = 0):
    """Reduce partial sums and scatter along sequence dim (fwd);
    all-gather (bwd).  Exit of a TP block under sequence parallelism."""
    return lax.psum_scatter(x, axis, scatter_dimension=dim, tiled=True)


def _rs_fwd(x, axis, dim):
    return lax.psum_scatter(x, axis, scatter_dimension=dim, tiled=True), None


def _rs_bwd(axis, dim, _, g):
    return (lax.all_gather(g, axis, axis=dim, tiled=True),)


reduce_scatter_to_sequence_parallel_region.defvjp(_rs_fwd, _rs_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def gather_from_sequence_parallel_region(x, axis: str = TENSOR_AXIS,
                                         dim: int = 0):
    """All-gather sequence shards (fwd); reduce-scatter (bwd).  Entry of
    a TP block under sequence parallelism."""
    return lax.all_gather(x, axis, axis=dim, tiled=True)


def _gs_fwd(x, axis, dim):
    return lax.all_gather(x, axis, axis=dim, tiled=True), None


def _gs_bwd(axis, dim, _, g):
    return (lax.psum_scatter(g, axis, scatter_dimension=dim, tiled=True),)


gather_from_sequence_parallel_region.defvjp(_gs_fwd, _gs_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def scatter_to_sequence_parallel_region(x, axis: str = TENSOR_AXIS,
                                        dim: int = 0):
    """Slice this rank's sequence chunk (fwd) / all-gather (bwd) —
    used on embeddings entering an SP region."""
    return _split_dim(x, axis, dim)


def _ss_fwd(x, axis, dim):
    return _split_dim(x, axis, dim), None


def _ss_bwd(axis, dim, _, g):
    return (lax.all_gather(g, axis, axis=dim, tiled=True),)


scatter_to_sequence_parallel_region.defvjp(_ss_fwd, _ss_bwd)
