"""apex_tpu.transformer — see package docstring in apex_tpu/__init__.py."""
