"""apex_tpu.transformer — Megatron-style model parallelism on a mesh.

TPU-native port of ``apex/transformer`` (SURVEY.md §2.6): tensor /
sequence parallelism over named mesh axes instead of NCCL process
groups; collectives via GSPMD sharding or explicit shard_map mappings.
(Pipeline-parallel schedules land in ``pipeline_parallel``.)
"""

from apex_tpu.transformer import data
from apex_tpu.transformer import functional
from apex_tpu.transformer import log_util
from apex_tpu.transformer import microbatches
from apex_tpu.transformer import moe
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer import pipeline_parallel
from apex_tpu.transformer import mappings
from apex_tpu.transformer import random
from apex_tpu.transformer.layers import (
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
    column_parallel_linear,
    row_parallel_linear,
    vocab_parallel_embedding,
)
from apex_tpu.transformer.cross_entropy import vocab_parallel_cross_entropy
from apex_tpu.transformer.data import broadcast_data
from apex_tpu.transformer.moe import MoEConfig, MoEMLP
from apex_tpu.transformer.microbatches import (
    setup_microbatch_calculator,
    get_num_microbatches,
)
from apex_tpu.transformer.utils import (
    divide,
    ensure_divisibility,
    split_tensor_along_last_dim,
)
from apex_tpu.transformer.enums import (
    LayerType,
    AttnType,
    AttnMaskType,
    ModelType,
)

__all__ = [
    "parallel_state", "mappings", "random", "data", "functional",
    "log_util",
    "microbatches", "moe", "pipeline_parallel", "broadcast_data",
    "MoEConfig", "MoEMLP",
    "setup_microbatch_calculator", "get_num_microbatches",
    "ColumnParallelLinear", "RowParallelLinear", "VocabParallelEmbedding",
    "column_parallel_linear", "row_parallel_linear",
    "vocab_parallel_embedding",
    "vocab_parallel_cross_entropy",
    "divide", "ensure_divisibility", "split_tensor_along_last_dim",
    "LayerType", "AttnType", "AttnMaskType", "ModelType",
]
