"""API-parity facade over the declarative mesh.

Reference: ``apex/transformer/parallel_state.py`` —
``initialize_model_parallel(tensor_model_parallel_size_,
pipeline_model_parallel_size_, virtual_pipeline_model_parallel_size_,
...)`` plus ~30 ``get_*`` accessors over NCCL process groups.

Here every "group" is a named mesh axis (SURVEY.md §2.6 "the central
design pivot"); the accessors below return axis names / sizes so code
written against the reference's API reads naturally.  Rank accessors are
only meaningful inside ``shard_map``/``pjit`` (they trace to
``lax.axis_index``), reflecting that on TPU "which rank am I" is a
per-device question inside the program, not a process-global.
"""

from __future__ import annotations

from typing import Optional

import jax

from apex_tpu.core import mesh as mesh_lib
from apex_tpu.core.mesh import (
    DATA_AXIS, FSDP_AXIS, PIPE_AXIS, TENSOR_AXIS, CONTEXT_AXIS,
)

__all__ = [
    "initialize_model_parallel",
    "model_parallel_is_initialized",
    "destroy_model_parallel",
    "get_tensor_model_parallel_world_size",
    "get_pipeline_model_parallel_world_size",
    "get_data_parallel_world_size",
    "get_context_parallel_world_size",
    "get_tensor_model_parallel_rank",
    "get_pipeline_model_parallel_rank",
    "get_data_parallel_rank",
    "get_tensor_model_parallel_axis",
    "get_pipeline_model_parallel_axis",
    "get_data_parallel_axis",
    "is_pipeline_first_stage",
    "is_pipeline_last_stage",
    "get_virtual_pipeline_model_parallel_world_size",
    "get_amax_reduction_axes",
]

_VIRTUAL_PIPE_SIZE: Optional[int] = None


def initialize_model_parallel(
    tensor_model_parallel_size_: int = 1,
    pipeline_model_parallel_size_: int = 1,
    virtual_pipeline_model_parallel_size_: Optional[int] = None,
    *,
    context_parallel_size_: int = 1,
    fsdp_size_: int = 1,
    **kwargs,
):
    """Build the global mesh (reference-compatible signature)."""
    global _VIRTUAL_PIPE_SIZE
    _VIRTUAL_PIPE_SIZE = virtual_pipeline_model_parallel_size_
    return mesh_lib.initialize_mesh(
        tensor_model_parallel_size=tensor_model_parallel_size_,
        pipeline_model_parallel_size=pipeline_model_parallel_size_,
        context_parallel_size=context_parallel_size_,
        fsdp_size=fsdp_size_,
        **kwargs,
    )


def model_parallel_is_initialized() -> bool:
    """True iff a global mesh exists (reference: the process-group
    initialization flag)."""
    try:
        mesh_lib.get_mesh()
        return True
    except RuntimeError:
        return False


def destroy_model_parallel() -> None:
    """Tear down the global mesh + virtual-pipeline state (reference
    name; test-isolation helper)."""
    global _VIRTUAL_PIPE_SIZE
    _VIRTUAL_PIPE_SIZE = None
    mesh_lib.destroy_mesh()


# ------------------------- world sizes ------------------------------- #
def get_tensor_model_parallel_world_size() -> int:
    """Size of the ``tensor`` mesh axis (reference: TP group size)."""
    return mesh_lib.mesh_axis_size(TENSOR_AXIS)


def get_pipeline_model_parallel_world_size() -> int:
    """Size of the ``pipe`` mesh axis (reference: PP group size)."""
    return mesh_lib.mesh_axis_size(PIPE_AXIS)


def get_data_parallel_world_size() -> int:
    """Combined ``data`` x ``fsdp`` axis size — the reference counts
    sharded-optimizer replicas in its data-parallel group."""
    return (mesh_lib.mesh_axis_size(DATA_AXIS)
            * mesh_lib.mesh_axis_size(FSDP_AXIS))


def get_context_parallel_world_size() -> int:
    """Size of the ``context`` (sequence/ring) axis — beyond-reference
    (apex has no CP); 1 unless context parallelism is configured."""
    return mesh_lib.mesh_axis_size(CONTEXT_AXIS)


def get_virtual_pipeline_model_parallel_world_size() -> Optional[int]:
    """V of the interleaved schedule (model chunks per rank), or None
    when not using virtual pipelining."""
    return _VIRTUAL_PIPE_SIZE


# ------------------------- ranks (in-program) ------------------------ #
def get_tensor_model_parallel_rank():
    """This device's coordinate on the ``tensor`` axis — traced
    (``lax.axis_index``): only meaningful inside shard_map/pjit."""
    return jax.lax.axis_index(TENSOR_AXIS)


def get_pipeline_model_parallel_rank():
    """This device's coordinate on the ``pipe`` axis (traced)."""
    return jax.lax.axis_index(PIPE_AXIS)


def get_data_parallel_rank():
    """This device's coordinate on the ``data`` axis (traced)."""
    return jax.lax.axis_index(DATA_AXIS)


def is_pipeline_first_stage():
    """Traced predicate: pipe coordinate == 0 (reference name)."""
    return jax.lax.axis_index(PIPE_AXIS) == 0


def is_pipeline_last_stage():
    """Traced predicate: pipe coordinate == pp - 1 (reference name)."""
    return (jax.lax.axis_index(PIPE_AXIS)
            == mesh_lib.mesh_axis_size(PIPE_AXIS) - 1)


def get_amax_reduction_axes():
    """Mesh axes over which FP8-style amax statistics reduce (reference:
    the amax-reduction process groups newer ``parallel_state`` versions
    build for FP8 training) — every model-parallel axis plus data, so a
    ``lax.pmax`` over these axes reproduces the reference's global amax
    all-reduce.  TPU v5 has no fp8 MXU path; this exists for API parity
    and for int8/quantized-compression amax plumbing
    (``apex_tpu.parallel.ddp`` int8 all-reduce)."""
    return (DATA_AXIS, FSDP_AXIS, TENSOR_AXIS, CONTEXT_AXIS)


# ------------------------- axis names -------------------------------- #
def get_tensor_model_parallel_axis() -> str:
    """The ``tensor`` axis name — what replaces "the TP group" in
    collectives and PartitionSpecs."""
    return TENSOR_AXIS


def get_pipeline_model_parallel_axis() -> str:
    """The ``pipe`` axis name."""
    return PIPE_AXIS


def get_data_parallel_axis() -> str:
    """The ``data`` axis name."""
    return DATA_AXIS
