"""Small shared helpers (reference: ``apex/transformer/utils.py``)."""

from __future__ import annotations

from typing import Sequence, Tuple

import jax.numpy as jnp

__all__ = ["divide", "ensure_divisibility", "split_tensor_along_last_dim"]


def ensure_divisibility(numerator: int, denominator: int) -> None:
    if numerator % denominator != 0:
        raise ValueError(
            f"{numerator} is not divisible by {denominator}")


def divide(numerator: int, denominator: int) -> int:
    ensure_divisibility(numerator, denominator)
    return numerator // denominator


def split_tensor_along_last_dim(x, num_partitions: int) -> Tuple:
    """Split the last dim into equal chunks (reference helper)."""
    last = divide(x.shape[-1], num_partitions)
    return tuple(
        x[..., i * last:(i + 1) * last] for i in range(num_partitions))
