"""Model-parallel RNG + activation checkpointing.

Reference: ``apex/transformer/tensor_parallel/random.py`` —
``CudaRNGStatesTracker`` / ``model_parallel_cuda_manual_seed`` maintain
separate CUDA RNG streams per tensor-parallel rank (so dropout differs
across TP ranks where it must, and matches where it must), and
``checkpoint`` re-runs the forward with the RNG state replayed.

TPU translation: JAX RNG is functional, so the entire stateful tracker
collapses to key derivation — fold the mesh coordinate into the key.
RNG replay under recomputation is free (same key → same bits), so
activation checkpointing is just :func:`jax.checkpoint` with a policy;
provided here with reference-shaped names.
"""

from __future__ import annotations

from typing import Optional

import jax
from jax import lax

from apex_tpu.core.mesh import TENSOR_AXIS, DATA_AXIS

__all__ = [
    "model_parallel_rng_key",
    "data_parallel_rng_key",
    "checkpoint",
    "CHECKPOINT_POLICIES",
]


def model_parallel_rng_key(key, axis: str = TENSOR_AXIS):
    """Per-TP-rank key (tracker's 'model-parallel-rng' stream).

    Inside ``shard_map``/``pjit``: distinct stream per tensor rank —
    use for dropout on TP-sharded activations.
    """
    return jax.random.fold_in(key, lax.axis_index(axis))


def data_parallel_rng_key(key, axis: str = DATA_AXIS):
    """Per-DP-rank key (distinct dropout per data shard)."""
    return jax.random.fold_in(key, lax.axis_index(axis))


#: Named remat policies ≙ Megatron's 'full'/'selective' recompute knobs.
CHECKPOINT_POLICIES = {
    "full": None,  # recompute everything (reference 'full' recompute)
    "dots": jax.checkpoint_policies.checkpoint_dots,
    "dots_no_batch": jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims,
    "nothing": jax.checkpoint_policies.everything_saveable,
}


def checkpoint(fn, *, policy: Optional[str] = "full",
               prevent_cse: bool = True):
    """Activation checkpointing (reference ``tensor_parallel.checkpoint``).

    Wrap a layer/block function; the backward recomputes activations
    (RNG replay is automatic — functional keys).  ``policy`` selects
    what XLA may keep (see :data:`CHECKPOINT_POLICIES`).
    """
    pol = CHECKPOINT_POLICIES[policy] if isinstance(policy, str) else policy
    if pol is None:
        return jax.checkpoint(fn, prevent_cse=prevent_cse)
    return jax.checkpoint(fn, policy=pol, prevent_cse=prevent_cse)
