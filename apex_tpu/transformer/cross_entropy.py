"""Vocab-parallel cross entropy.

Reference: ``apex/transformer/tensor_parallel/cross_entropy.py`` —
``vocab_parallel_cross_entropy(logits, target)``: with logits sharded
over the vocab dim across the TP group, computes CE without gathering
the full vocab: (1) all-reduce-max for stability, (2) masked local
target-logit lookup + all-reduce, (3) local exp-sum + all-reduce.

TPU form: the same three collectives as ``lax.pmax``/``psum`` inside
``shard_map``; gradients flow through JAX transposition (the reference
hand-writes the backward — softmax minus one-hot — which autodiff
produces here from the same forward, with the max term
stop-gradiented as usual).  Label smoothing matches
:mod:`apex_tpu.ops.xentropy` semantics.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.core.mesh import TENSOR_AXIS
from apex_tpu.transformer.mappings import reduce_from_tensor_parallel_region as _reduce_from

__all__ = ["vocab_parallel_cross_entropy"]


def vocab_parallel_cross_entropy(logits_shard, target, *,
                                 smoothing: float = 0.0,
                                 axis: str = TENSOR_AXIS):
    """Per-example CE from vocab-sharded logits (inside ``shard_map``).

    ``logits_shard``: (..., vocab/tp) this rank's vocab slice;
    ``target``: (...) global vocab ids.  Returns fp32 loss of
    ``target.shape``.
    """
    lf = logits_shard.astype(jnp.float32)
    per = lf.shape[-1]
    start = lax.axis_index(axis) * per

    # (1) global max for numerical stability (bwd: treated as constant;
    # stop_gradient BEFORE pmax — the collective has no JVP rule)
    local_max = lax.stop_gradient(jnp.max(lf, axis=-1))
    global_max = lax.pmax(local_max, axis)
    lf = lf - global_max[..., None]

    # (2) target logit: masked local pick + all-reduce
    in_range = (target >= start) & (target < start + per)
    local_ids = jnp.clip(target - start, 0, per - 1)
    picked = jnp.take_along_axis(lf, local_ids[..., None], axis=-1)[..., 0]
    picked = jnp.where(in_range, picked, 0.0)
    # all-reduce with identity backward (Megatron "g"): the loss is
    # replicated across TP ranks, so a raw psum would 4x-count the
    # cotangent — the custom-VJP mapping is load-bearing here.
    picked = _reduce_from(picked, axis)

    # (3) global log-sum-exp
    sum_exp = _reduce_from(jnp.sum(jnp.exp(lf), axis=-1), axis)
    lse = jnp.log(sum_exp)

    nll = lse - picked
    if smoothing > 0.0:
        vocab = per * lax.axis_size(axis)
        mean_logit = _reduce_from(jnp.sum(lf, axis=-1), axis) / vocab
        smooth = lse - mean_logit
        return (1.0 - smoothing) * nll + smoothing * smooth
    return nll
