"""apex_tpu.fp16_utils — the legacy manual-fp16 API, functional.

Reference: ``apex/fp16_utils/{fp16_optimizer,fp16util,loss_scaler}.py``
— the pre-amp workflow: convert the network to half by hand, keep fp32
master weights inside ``FP16_Optimizer``, scale the loss, copy model
grads to master grads, step on the masters, copy back.

TPU translation: the same five verbs as pure pytree functions, and
``FP16_Optimizer`` as a thin stateful-API-shaped facade whose ``init``/
``step`` are pure (state in, state out) so the whole step jits.  All of
it is subsumed by :mod:`apex_tpu.amp` (SURVEY.md §2.1 "legacy" row);
kept for API parity with code written against ``fp16_utils``.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from apex_tpu.core.loss_scale import (
    DynamicLossScale,
    LossScaleState,
    NoOpLossScale,
    StaticLossScale,
    all_finite,
)
from apex_tpu.core.precision import _default_bn_filter, tree_cast

__all__ = [
    "network_to_half", "BN_convert_float",
    "master_params_to_model_params", "model_grads_to_master_grads",
    "prep_param_lists", "FP16_Optimizer", "FP16OptimizerState",
    "LossScaler", "DynamicLossScaler",
]


def network_to_half(params: Any, *, half_dtype=jnp.float16) -> Any:
    """Cast floating leaves to half, keeping norm-layer leaves fp32.

    Parity: ``fp16util.network_to_half`` (whose BN2 wrapper keeps
    BatchNorm in fp32 — here the BN path filter does the same job).
    """
    return tree_cast(params, half_dtype, keep_fp32_filter=_default_bn_filter)


def BN_convert_float(params: Any) -> Any:
    """Cast norm-layer leaves back to fp32 (``fp16util.BN_convert_float``)."""

    def _cast(path, leaf):
        if _default_bn_filter(path, leaf) and jnp.issubdtype(
                jnp.asarray(leaf).dtype, jnp.floating):
            return jnp.asarray(leaf, jnp.float32)
        return leaf

    return jax.tree_util.tree_map_with_path(_cast, params)


def prep_param_lists(params: Any) -> Tuple[Any, Any]:
    """(model_params, fp32 master copies) — ``fp16util.prep_param_lists``."""
    masters = jax.tree.map(
        lambda p: jnp.asarray(p, jnp.float32)
        if jnp.issubdtype(jnp.asarray(p).dtype, jnp.floating) else p,
        params)
    return params, masters


def master_params_to_model_params(model_params: Any,
                                  master_params: Any) -> Any:
    """Round fp32 masters into the model params' dtypes."""
    return jax.tree.map(
        lambda p, m: m.astype(jnp.asarray(p).dtype), model_params,
        master_params)


def model_grads_to_master_grads(model_grads: Any) -> Any:
    """Upcast half model grads to fp32 master grads."""
    return jax.tree.map(
        lambda g: jnp.asarray(g, jnp.float32)
        if jnp.issubdtype(jnp.asarray(g).dtype, jnp.floating) else g,
        model_grads)


# --------------------------------------------------------------------- #
# legacy loss scalers (constructor-arg parity with fp16_utils)
# --------------------------------------------------------------------- #
def LossScaler(scale: float = 1.0) -> StaticLossScale:
    """Static scaler (``fp16_utils.LossScaler``)."""
    return StaticLossScale(scale=scale)


def DynamicLossScaler(init_scale: float = 2.0 ** 32,
                      scale_factor: float = 2.0,
                      scale_window: int = 1000) -> DynamicLossScale:
    """Dynamic scaler with the legacy module's defaults/arg names."""
    return DynamicLossScale(init_scale=init_scale,
                            growth_factor=scale_factor,
                            backoff_factor=1.0 / scale_factor,
                            growth_interval=scale_window)


class FP16OptimizerState(NamedTuple):
    master_params: Any
    opt_state: Any
    loss_scale_state: LossScaleState


class FP16_Optimizer:
    """Master-weight wrapper (``fp16_utils.FP16_Optimizer`` parity).

    Pure-functional shape: ``state = opt.init(model_params)``;
    ``new_state, model_params, finite = opt.step(state, model_params,
    model_grads)``.  The step unscales, checks finiteness, updates the
    fp32 masters (skipping on overflow like the reference), rounds them
    back into the model params, and adjusts the dynamic scale.
    """

    def __init__(self, tx: optax.GradientTransformation,
                 static_loss_scale: float = 1.0,
                 dynamic_loss_scale: bool = False,
                 dynamic_loss_args: Optional[dict] = None):
        self.tx = tx
        if dynamic_loss_scale:
            self.loss_scaler = DynamicLossScaler(
                **(dynamic_loss_args or {}))
        elif static_loss_scale == 1.0:
            self.loss_scaler = NoOpLossScale()
        else:
            self.loss_scaler = LossScaler(static_loss_scale)

    def init(self, model_params: Any) -> FP16OptimizerState:
        _, masters = prep_param_lists(model_params)
        return FP16OptimizerState(
            master_params=masters,
            opt_state=self.tx.init(masters),
            loss_scale_state=self.loss_scaler.init(),
        )

    def scale_loss(self, state: FP16OptimizerState, loss: Any) -> Any:
        """``optimizer.backward(loss)``'s scaling half, as a function."""
        return self.loss_scaler.scale(state.loss_scale_state, loss)

    def step(self, state: FP16OptimizerState, model_params: Any,
             model_grads: Any):
        ls, ls_state = self.loss_scaler, state.loss_scale_state
        grads = model_grads_to_master_grads(model_grads)
        grads = ls.unscale(ls_state, grads)
        finite = all_finite(grads)
        updates, new_opt_state = self.tx.update(
            grads, state.opt_state, state.master_params)
        new_masters = optax.apply_updates(state.master_params, updates)
        new_masters = ls.select_step(finite, new_masters,
                                     state.master_params)
        new_opt_state = ls.select_step(finite, new_opt_state,
                                       state.opt_state)
        new_state = FP16OptimizerState(
            master_params=new_masters,
            opt_state=new_opt_state,
            loss_scale_state=ls.adjust(ls_state, finite),
        )
        new_model = master_params_to_model_params(model_params,
                                                  new_masters)
        return new_state, new_model, finite

    # persistence parity (fp16_optimizer state_dict keeps scaler state)
    def state_dict(self, state: FP16OptimizerState) -> dict:
        return {
            "loss_scaler": state.loss_scale_state.state_dict(),
            "master_params": state.master_params,
            "opt_state": state.opt_state,
        }

    def load_state_dict(self, d: dict) -> FP16OptimizerState:
        return FP16OptimizerState(
            master_params=d["master_params"],
            opt_state=d["opt_state"],
            loss_scale_state=LossScaleState.from_state_dict(
                d["loss_scaler"]),
        )
