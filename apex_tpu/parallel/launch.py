"""Multi-host bootstrap — the reference's launcher row, TPU-native.

Reference: ``apex/parallel/multiproc.py`` (a tiny pre-``torchrun``
process-per-GPU launcher) plus the ``torch.distributed.launch``
conventions its examples assume (SURVEY.md §2.5).  On TPU there is no
process-per-chip launcher to port: each *host* runs one process that
owns all its local chips, and multi-host coordination is
``jax.distributed.initialize`` — on Cloud TPU it autodetects the
coordinator and process indices from the TPU metadata, so the common
case is a single zero-argument call.

:func:`init_distributed` wraps that with the reference-style
environment conventions (``MASTER_ADDR``/``MASTER_PORT``/``RANK``/
``WORLD_SIZE``, which ``apex.parallel.multiproc`` and
``torch.distributed.launch`` both set) so migrated launch scripts work
unchanged, and is a no-op on a single host.
"""

from __future__ import annotations

import os
from typing import Optional

__all__ = ["init_distributed", "is_distributed"]

_INITIALIZED = False


def is_distributed() -> bool:
    """True once :func:`init_distributed` has set up multi-host JAX."""
    return _INITIALIZED


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    **kwargs,
) -> bool:
    """Initialize multi-host JAX, reading reference-style env vars.

    Resolution order for each field: explicit argument →
    ``MASTER_ADDR:MASTER_PORT`` / ``WORLD_SIZE`` / ``RANK`` (the
    conventions the reference's launcher and ``torch.distributed``
    set) → autodetection by ``jax.distributed.initialize`` (Cloud TPU
    metadata).  Returns True if a multi-host runtime was started,
    False for the single-host no-op (``WORLD_SIZE`` absent or 1 and no
    explicit arguments).

    Call once, before any other JAX API touches the backend —
    the same "first thing in main()" contract as
    ``torch.distributed.init_process_group``.
    """
    global _INITIALIZED
    if _INITIALIZED:
        return True
    if coordinator_address is None:
        addr = os.environ.get("MASTER_ADDR")
        if addr:
            port = os.environ.get("MASTER_PORT", "8476")
            coordinator_address = f"{addr}:{port}"
    if num_processes is None and "WORLD_SIZE" in os.environ:
        num_processes = int(os.environ["WORLD_SIZE"])
    if process_id is None and "RANK" in os.environ:
        process_id = int(os.environ["RANK"])

    if coordinator_address is None and num_processes in (None, 1):
        # single host (no coordinator, world size absent or 1, e.g. a
        # migrated script that only sets RANK=0): plain local JAX
        return False

    import jax

    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            **kwargs,
        )
    except Exception as e:
        missing = [n for n, v in
                   (("MASTER_ADDR", coordinator_address),
                    ("WORLD_SIZE", num_processes),
                    ("RANK", process_id)) if v is None]
        if missing:
            # a partial launch env (some of coordinator/world/rank
            # unresolved, and jax's cluster autodetection couldn't fill
            # the gaps either) otherwise surfaces as an opaque
            # JAX-internal error; name the reference-style env vars
            # that would complete it — keeping the underlying error in
            # the message, since with autodetection in play the true
            # cause may be e.g. a connection failure instead
            raise ValueError(
                f"jax.distributed.initialize failed "
                f"({type(e).__name__}: {e}) with "
                f"{' and '.join(missing)} unresolved — if the "
                f"underlying error is about the missing field(s), set "
                f"the named env var(s) or pass coordinator_address/"
                f"num_processes/process_id explicitly; otherwise see "
                f"the chained error") from e
        raise
    _INITIALIZED = True
    return True
