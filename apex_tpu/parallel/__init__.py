"""apex_tpu.parallel — single-axis distributed building blocks.

TPU-native replacement for ``apex/parallel`` (SURVEY.md §2.5): data
parallelism and SyncBatchNorm ride ICI collectives inserted by GSPMD
instead of NCCL hooks; LARC lives in :mod:`apex_tpu.optim`.
"""

from apex_tpu.parallel.ddp import (
    DistributedDataParallel,
    replicate,
    shard_batch,
    all_reduce_mean_grads,
)
from apex_tpu.parallel.sync_batchnorm import (
    SyncBatchNorm,
    sync_batch_norm_stats,
    convert_syncbn_model,
)
from apex_tpu.parallel.distributed_optim import (
    ZeroConfig,
    ZeroOptState,
    all_gather_params,
    distributed_fused_adam,
    distributed_fused_lamb,
    reduce_scatter_mean_grads,
    zero_param_specs,
    zero_partition,
    zero_shardings,
    zero_state_specs,
    zero_unpartition,
)
from apex_tpu.parallel.pipeline import (
    bubble_fraction,
    live_microbatches,
    pipeline_state_shardings,
    pipeline_state_specs,
    run_1f1b,
    schedule_ticks,
    stage_local_zero,
    stage_shardings,
    stage_specs,
    stage_split,
    stage_unsplit,
    sync_grad_overflow,
    wrap_pipeline_step,
)
from apex_tpu.parallel.ring_attention import (
    ring_attention,
    ring_self_attention,
)
from apex_tpu.parallel.ulysses import (
    ulysses_attention,
    ulysses_self_attention,
)
from apex_tpu.parallel.launch import (
    init_distributed,
    is_distributed,
)
from apex_tpu.optim import LARC

__all__ = [
    "init_distributed",
    "is_distributed",
    "DistributedDataParallel", "replicate", "shard_batch",
    "all_reduce_mean_grads",
    "SyncBatchNorm", "sync_batch_norm_stats", "convert_syncbn_model",
    "ZeroConfig", "ZeroOptState",
    "distributed_fused_adam", "distributed_fused_lamb",
    "zero_partition", "zero_unpartition",
    "reduce_scatter_mean_grads", "all_gather_params",
    "zero_param_specs", "zero_shardings", "zero_state_specs",
    "bubble_fraction", "schedule_ticks", "live_microbatches",
    "stage_split", "stage_unsplit", "stage_specs", "stage_shardings",
    "stage_local_zero", "pipeline_state_specs",
    "pipeline_state_shardings", "sync_grad_overflow",
    "run_1f1b", "wrap_pipeline_step",
    "ring_attention", "ring_self_attention",
    "ulysses_attention", "ulysses_self_attention",
    "LARC",
]
