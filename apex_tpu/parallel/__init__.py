"""apex_tpu.parallel — see package docstring in apex_tpu/__init__.py."""
