"""Distributed ("ZeRO"-sharded) fused optimizers.

Reference: ``apex/contrib/optimizers/distributed_fused_adam.py`` /
``distributed_fused_lamb.py`` — optimizer state and master params
sharded across the DP group; gradients reduce-scattered into shards
during backward (bucketed, overlapped), updated shard-locally, params
all-gathered after the step (SURVEY.md §2.7).

TPU translation: the reduce-scatter/all-gather choreography IS the
GSPMD lowering of "optimizer state sharded over the ``fsdp`` axis" —
XLA inserts a reduce-scatter for the grads feeding sharded state, runs
the (already fused, :mod:`apex_tpu.optim`) update shard-locally, and
all-gathers params where the forward needs them, overlapping both with
compute.  So the distributed variants are *placement policies* over the
same transforms:

    tx = distributed_fused_adam(lr)            # == fused_adam
    shardings = zero_shardings(mesh, params)   # state/master specs
    train_step = jit(step, in_shardings=(shardings.state, ...))

``zero_shardings`` computes per-leaf PartitionSpecs that shard the
*largest* dim of each ≥1-D leaf over ``fsdp`` (ZeRO-1/2 equivalent);
scalars stay replicated.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from apex_tpu.core import mesh as mesh_lib
from apex_tpu.core.mesh import FSDP_AXIS
from apex_tpu.optim import fused_adam, fused_lamb

__all__ = [
    "distributed_fused_adam",
    "distributed_fused_lamb",
    "zero_param_specs",
    "zero_shardings",
]

# The transforms are identical — distribution is placement, not math.
distributed_fused_adam = fused_adam
distributed_fused_lamb = fused_lamb


def _leaf_spec(leaf, axis: str, axis_size: int) -> PartitionSpec:
    shape = jnp.shape(leaf)
    if not shape:
        return PartitionSpec()
    # shard the largest divisible dim; else replicate
    order = sorted(range(len(shape)), key=lambda i: -shape[i])
    for i in order:
        if shape[i] % axis_size == 0 and shape[i] >= axis_size:
            spec = [None] * len(shape)
            spec[i] = axis
            return PartitionSpec(*spec)
    return PartitionSpec()


def zero_param_specs(params: Any, *, axis: str = FSDP_AXIS,
                     mesh=None) -> Any:
    """Per-leaf PartitionSpecs sharding each tensor over ``fsdp``."""
    mesh = mesh or mesh_lib.get_mesh()
    n = mesh.shape.get(axis, 1)
    return jax.tree.map(lambda p: _leaf_spec(p, axis, n), params)


def zero_shardings(tree: Any, *, axis: str = FSDP_AXIS, mesh=None) -> Any:
    """Per-leaf NamedShardings for params/opt-state pytrees (apply with
    ``jax.device_put`` or as ``jit`` in/out shardings)."""
    mesh = mesh or mesh_lib.get_mesh()
    specs = zero_param_specs(tree, axis=axis, mesh=mesh)
    return jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                        is_leaf=lambda x: isinstance(x, PartitionSpec))
