"""ZeRO-1/2: optimizer state sharded across the data-parallel group.

Reference: ``apex/contrib/optimizers/distributed_fused_adam.py`` /
``distributed_fused_lamb.py`` — optimizer state and master params
sharded across the DP group; gradients reduce-scattered into shards
during backward (bucketed, overlapped), updated shard-locally, params
all-gathered after the step (SURVEY.md §2.7) — and "Automatic
Cross-Replica Sharding of Weight Update in Data-Parallel Training"
(PAPERS.md, arxiv 2004.13336), whose GSPMD formulation this module
implements directly:

1. **reduce-scatter** the gradients over the ZeRO axis — each device
   receives only the shard of the (mean) gradient it owns.  The wire
   lever composes with the PR-8 int8 quantized-collective machinery in
   :mod:`apex_tpu.parallel.ddp` (``reduce_dtype="int8"`` rides the
   same amax/scale discipline and ``all_to_all`` leg; a half dtype
   halves the wire bytes; ``None`` reduce-scatters exactly in fp32).
2. **shard-local update** — the existing fused optimizers
   (:mod:`apex_tpu.optim`) run unchanged on fp32 **master shards**
   carrying the machine-checked ``precision(master-fp32)`` contract:
   elementwise updates (Adam/SGD/Adagrad) are shard-exact by
   construction; LAMB takes a ``shard_axis`` so its per-tensor norms
   ``psum`` across shards (:func:`distributed_fused_lamb`).  LARC has
   no shard-aware variant yet — its per-leaf trust ratios would be
   silently shard-local; don't chain it into a ZeRO update.
3. **all-gather** the updated params in the *compute/storage* dtype
   (bf16 under O2 — half the gather bytes of fp32) for the next
   forward.

What each stage buys (per chip, n-way sharding, P params):

- **ZeRO-1** (``stage=1``): optimizer state (fp32 masters + both Adam
  moments, 12 B/param replicated) shrinks to ``12/n`` B/param; the
  gradient sync stays a full all-reduce and the full mean gradient is
  materialized before slicing.
- **ZeRO-2** (``stage=2``, default): same state sharding, but the
  gradients are reduce-scattered — the full unscaled fp32 gradient
  buffer never materializes; each device only ever holds its
  ``P/n``-element shard.  This is the ``temp``-HBM lever the bench
  roofline identifies (``_zero_bytes_on_wire`` in ``bench_configs``
  models both wire and resident bytes).

The choreography lives in
:meth:`apex_tpu.core.train_state.MixedPrecisionTrainState.apply_gradients`
(zero mode): pass ``zero=ZeroConfig(...)`` to ``amp.initialize`` /
``MixedPrecisionTrainState.create`` and run the train step inside
``jax.shard_map`` with :func:`zero_state_specs` as the state's
in/out specs.  Placement of the sharded state on the mesh — and the
restore target for :class:`~apex_tpu.resilience.ResilientCheckpointer`
— comes from :func:`zero_shardings`.  See ``docs/zero.md``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec

from apex_tpu.core import mesh as mesh_lib
from apex_tpu.core.mesh import FSDP_AXIS
from apex_tpu.optim import fused_adam, fused_lamb
from apex_tpu.parallel import ddp as _ddp

__all__ = [
    "ZeroConfig",
    "ZeroOptState",
    "distributed_fused_adam",
    "distributed_fused_lamb",
    "zero_partition",
    "zero_unpartition",
    "reduce_scatter_mean_grads",
    "all_gather_params",
    "zero_state_specs",
    "zero_param_specs",
    "zero_shardings",
]


# ------------------------------------------------------------- configuration

@dataclasses.dataclass(frozen=True)
class ZeroConfig:
    """Static description of a ZeRO-sharded optimizer layout.

    Stored as a non-pytree field on
    :class:`~apex_tpu.core.train_state.MixedPrecisionTrainState`, so it
    must stay hashable.

    ``axis`` — mesh axis the state shards over (and the grads
    reduce-scatter over); the canonical choice is ``"fsdp"``, but any
    data-parallel axis works (the simple example uses ``"data"``).
    ``stage`` — 1 (all-reduce grads, slice locally) or 2
    (reduce-scatter; the full gradient never materializes).
    ``reduce_dtype`` — wire dtype of the grad sync: ``None`` (exact,
    fp32), a half dtype, or ``"int8"`` (the EQuARX amax/scale
    discipline shared with :func:`~apex_tpu.parallel.ddp.
    all_reduce_mean_grads`).
    ``axis_size`` — number of shards; ``0`` resolves from the current
    :func:`~apex_tpu.core.mesh.get_mesh` at ``create`` time (pass it
    explicitly when training over a raw, unregistered mesh).
    """

    axis: str = FSDP_AXIS
    stage: int = 2
    reduce_dtype: Any = None
    axis_size: int = 0

    def resolved(self, mesh=None) -> "ZeroConfig":
        """Validate and fill ``axis_size`` from the mesh if unset."""
        if self.stage not in (1, 2):
            raise ValueError(f"ZeRO stage must be 1 or 2, got "
                             f"{self.stage!r}")
        # reuse ddp's normalization so an int dtype fails loudly here
        _ddp._normalize_allreduce_dtype(self.reduce_dtype)
        n = self.axis_size
        if not n:
            mesh = mesh or mesh_lib.get_mesh()
            n = mesh.shape.get(self.axis, 0)
            if not n:
                raise ValueError(
                    f"mesh has no axis {self.axis!r} — name a mesh "
                    f"axis or pass axis_size explicitly")
        if n < 1:
            raise ValueError(f"axis_size must be >= 1, got {n}")
        return dataclasses.replace(self, axis_size=int(n))


class ZeroOptState(NamedTuple):
    """The sharded ``opt_state`` of a zero-mode train state.

    ``master`` — fp32 master shards, one ``(n, m)`` leaf per param
    leaf (row ``i`` lives on shard ``i``; ``m = ceil(size / n)``,
    zero-padded).  ``inner`` — the wrapped optimizer's state over the
    master-shard tree (Adam moments etc. inherit the ``(n, m)``
    layout, so they shard with the masters).
    """

    master: Any
    inner: Any


# ---------------------------------------------------------- shard layout

def zero_partition(tree: Any, axis_size: int, *,
                   dtype: Any = jnp.float32) -> Any:
    """Stack every leaf into ``(axis_size, m)`` ZeRO shards.

    Each floating leaf is flattened, cast to ``dtype`` (fp32 — the
    master copy), zero-padded to a multiple of ``axis_size`` and
    reshaped so row ``i`` is shard ``i``'s slice (the
    ``ddp._pad_rows`` layout the reduce-scatter legs share).
    Non-floating leaves keep their dtype.  The tree structure is
    preserved, so pytree paths (and the policy's norm-layer filters)
    still apply.
    """
    n = int(axis_size)

    def part(p):
        x = jnp.ravel(jnp.asarray(p))
        if dtype is not None and jnp.issubdtype(x.dtype, jnp.floating):
            x = x.astype(dtype)
        return _ddp._pad_rows(x, n)

    return jax.tree.map(part, tree)


def zero_unpartition(shards: Any, like: Any) -> Any:
    """Inverse of :func:`zero_partition`: drop padding, restore shapes.

    ``like`` supplies the original shapes; dtypes stay the shards'
    (cast with the precision policy afterwards if needed).
    """
    def un(s, p):
        shape = jnp.shape(p)
        size = 1
        for d in shape:
            size *= d
        return s.reshape(-1)[:size].reshape(shape)

    return jax.tree.map(un, shards, like)


# ------------------------------------------------------------- collectives

def reduce_scatter_mean_grads(grads: Any, axis: str = FSDP_AXIS, *,
                              reduce_dtype: Any = None,
                              stage: int = 2,
                              average: bool = True) -> Any:
    """Reduce-scatter gradients into ``(1, m)`` fp32 shards (inside
    ``shard_map``) — the ZeRO gradient sync.

    Per leaf, the result is this device's row of the
    :func:`zero_partition` layout of the mean (or summed) gradient, in
    fp32 — ready to feed a shard-local fused-optimizer update against
    the matching master shard.

    ``stage=2`` (default) exchanges only shards: an ``all_to_all``
    hands every device the n contributions to its chunk, summed
    on-chip in fp32 — the full gradient never materializes.  With
    ``reduce_dtype="int8"`` the exchange is the 1-byte/element
    reduce-scatter leg of :func:`~apex_tpu.parallel.ddp.
    all_reduce_mean_grads`'s EQuARX path (same amax/scale discipline,
    shared implementation); non-finite grads poison the shard with NaN
    so dynamic-loss-scale overflow detection still fires.  A half
    ``reduce_dtype`` puts 2-byte elements on the wire and accumulates
    in fp32.

    ``stage=1`` all-reduces the full gradient (via
    :func:`~apex_tpu.parallel.ddp.all_reduce_mean_grads`, honoring the
    same ``reduce_dtype`` lever) and slices the local shard — more
    resident bytes (the full mean gradient exists on every device),
    kept for the ZeRO-1 memory/simplicity point of the design space.
    """
    dtype = _ddp._normalize_allreduce_dtype(reduce_dtype)
    n = lax.axis_size(axis)
    if stage not in (1, 2):
        raise ValueError(f"stage must be 1 or 2, got {stage!r}")

    if stage == 1:
        full = _ddp.all_reduce_mean_grads(
            grads, axis, allreduce_dtype=reduce_dtype, average=average)

        def slice_own(g):
            rows = _ddp._pad_rows(jnp.ravel(g).astype(jnp.float32), n)
            return lax.dynamic_slice_in_dim(
                rows, lax.axis_index(axis), 1, axis=0)

        return jax.tree.map(slice_own, full)

    def rs(g):
        if dtype == "int8":
            s, inv_scale, amax = _ddp._q8_reduce_scatter(g, axis, n)
            deq = s.astype(jnp.float32) * inv_scale
            if average:
                deq = deq / n
            # inf/nan grads must not be masked to zero: overflow
            # detection (DynamicLossScale) keys off non-finite grads
            deq = jnp.where(jnp.isfinite(amax), deq, jnp.nan)
            return deq.reshape(1, -1)
        wire = g if dtype is None else g.astype(dtype)
        mine = lax.all_to_all(_ddp._pad_rows(jnp.ravel(wire), n), axis,
                              split_axis=0, concat_axis=0, tiled=True)
        # accumulate the n contributions in fp32 regardless of the
        # wire dtype — a bf16 wire must not mean a bf16 running sum
        s = jnp.sum(mine.astype(jnp.float32), axis=0)
        if average:
            s = s / n
        return s.reshape(1, -1)

    return jax.tree.map(rs, grads)


def all_gather_params(shards: Any, like: Any,
                      axis: str = FSDP_AXIS) -> Any:
    """All-gather ``(1, m)`` shards back into full param leaves
    (inside ``shard_map``).

    The gather runs in the shards' dtype — cast to the compute/storage
    dtype *before* calling (bf16 under O2) so the wire carries 2-byte
    elements; only the resident master shard stays fp32.  ``like``
    supplies the original shapes.
    """
    def ag(s, p):
        full = lax.all_gather(s.reshape(-1), axis, tiled=True)
        shape = jnp.shape(p)
        size = 1
        for d in shape:
            size *= d
        return full[:size].reshape(shape)

    return jax.tree.map(ag, shards, like)


# ------------------------------------------------- placement (load-bearing)

def _is_zero_state(tree: Any) -> bool:
    from apex_tpu.core.train_state import MixedPrecisionTrainState
    return isinstance(tree, MixedPrecisionTrainState) \
        and getattr(tree, "zero", None) is not None


def zero_state_specs(state: Any) -> Any:
    """Per-leaf ``PartitionSpec`` tree for a zero-mode train state.

    Master/optimizer shards (the ``(n, m)`` leaves of
    :class:`ZeroOptState`) get ``P(axis)`` on their shard dim;
    everything else — params, step, loss-scale state, scalar counters
    — is replicated.  This is both the ``shard_map`` in/out spec for
    the train step and (via :func:`zero_shardings`) the committed
    placement / checkpoint-restore target.
    """
    if not _is_zero_state(state):
        raise ValueError("zero_state_specs expects a MixedPrecision"
                         "TrainState created with zero=ZeroConfig(...)")
    z = state.zero
    replicated = jax.tree.map(lambda _: PartitionSpec(), state)

    def shard_spec(leaf):
        # static shape metadata only — every ZeroOptState array leaf is
        # (axis_size, m) by construction; scalars (the step counter)
        # stay replicated
        if leaf.ndim >= 1 and leaf.shape[0] == z.axis_size:
            spec = [z.axis] + [None] * (leaf.ndim - 1)
            return PartitionSpec(*spec)
        return PartitionSpec()

    return replicated.replace(
        opt_state=jax.tree.map(shard_spec, state.opt_state))


def _leaf_spec(leaf, axis: str, axis_size: int) -> PartitionSpec:
    shape = jnp.shape(leaf)
    if not shape:
        return PartitionSpec()
    # shard the largest divisible dim; else replicate
    order = sorted(range(len(shape)), key=lambda i: -shape[i])
    for i in order:
        if shape[i] % axis_size == 0 and shape[i] >= axis_size:
            spec = [None] * len(shape)
            spec[i] = axis
            return PartitionSpec(*spec)
    return PartitionSpec()


def zero_param_specs(params: Any, *, axis: str = FSDP_AXIS,
                     mesh=None) -> Any:
    """Per-leaf PartitionSpecs sharding each tensor over ``fsdp``
    (generic largest-divisible-dim heuristic, for plain pytrees)."""
    mesh = mesh or mesh_lib.get_mesh()
    n = mesh.shape.get(axis, 1)
    return jax.tree.map(lambda p: _leaf_spec(p, axis, n), params)


def zero_shardings(tree: Any, *, axis: str = FSDP_AXIS,
                   mesh=None) -> Any:
    """Per-leaf ``NamedSharding``\\ s for ZeRO placement.

    Two modes:

    - a **zero-mode** :class:`~apex_tpu.core.train_state.
      MixedPrecisionTrainState` → the exact state placement
      (:func:`zero_state_specs` over the mesh): master/opt shards on
      their ZeRO axis, everything else replicated.  Apply with
      ``jax.device_put`` after ``create`` to commit the layout, and
      build the :class:`~apex_tpu.resilience.ResilientCheckpointer`
      restore target the same way — orbax restores arrays with the
      target's shardings, so a resumed run lands exactly where a fresh
      one does.
    - any other pytree → the generic largest-divisible-dim heuristic
      per leaf (the pre-ZeRO behavior, kept for raw param trees).
    """
    mesh = mesh or mesh_lib.get_mesh()
    if _is_zero_state(tree):
        specs = zero_state_specs(tree)
    else:
        specs = zero_param_specs(tree, axis=axis, mesh=mesh)
    return jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                        is_leaf=lambda x: isinstance(x, PartitionSpec))


# ------------------------------------------------- distributed optimizers

def distributed_fused_adam(*args: Any, **kwargs: Any):
    """:func:`~apex_tpu.optim.fused_adam` for ZeRO-sharded state.

    The Adam update is elementwise, so the shard-local update on
    ``(1, m)`` master shards is *exactly* the full update restricted
    to the shard — the transform itself needs no distribution
    awareness; the sharding is carried by the
    :class:`ZeroOptState` layout and the reduce-scatter/all-gather
    choreography in ``apply_gradients``.  (Reference:
    ``apex/contrib/optimizers/distributed_fused_adam.py``.)

    Note: ``moment_format="fp8_block_scaled"`` lays its quantization
    blocks over the *flattened full leaf* and is rejected at
    ``create`` time for zero states (the state is not shard-shaped);
    use ``moment_dtype`` for reduced-precision sharded moments.
    """
    return fused_adam(*args, **kwargs)


def distributed_fused_lamb(*args: Any, shard_axis: Optional[str],
                           **kwargs: Any):
    """:func:`~apex_tpu.optim.fused_lamb` for ZeRO-sharded state.

    LAMB is *not* elementwise: the global-norm grad clip and the
    per-tensor trust ratios need whole-tensor L2 norms, which a shard
    only sees ``1/n`` of.  ``shard_axis`` (keyword-REQUIRED: pass the
    :class:`ZeroConfig` axis you train over — a wrong default would
    either fail at trace time or silently compute shard-local trust
    ratios) makes every norm a ``psum`` across the shards, batched
    into one collective — the reference ``distributed_fused_lamb``'s
    allreduced-L2 stage — so the sharded update is exactly the full
    one.  (Padding rows are zero and contribute nothing to the
    norms.)  ``shard_axis=None`` is the plain :func:`fused_lamb` for
    GSPMD-placed, unsharded-update flows.
    """
    return fused_lamb(*args, shard_axis=shard_axis, **kwargs)
