"""Ulysses sequence parallelism — all-to-all context parallelism.

**Beyond-reference** (SURVEY.md §2.6 checklist, §5): the reference has
no context parallelism at all; this module is the second CP strategy
next to :mod:`apex_tpu.parallel.ring_attention`, trading the ring's
O(cp) neighbor exchanges for TWO all-to-alls around one full-sequence
attention (the DeepSpeed-Ulysses pattern):

- input arrives sequence-sharded ``(b, s/cp, h, d)`` over the
  ``context`` axis;
- ``all_to_all`` re-shards heads↔sequence: every device then holds the
  FULL sequence for ``h/cp`` of the heads;
- attention runs locally through the Pallas flash kernel — the banded
  sliding-window grid, in-kernel dropout, and the seq-aware block
  autotuning all apply unchanged (the ring path has its own jnp
  accumulation instead);
- the output all-to-alls back to sequence-sharded.

When to prefer which (both exact): Ulysses moves ``2·b·s/cp·h·d``
elements per device per call in two collectives and keeps the
attention itself a single dense kernel — best when ``h >= cp`` and the
per-device full-sequence KV fits HBM.  Ring attention streams KV in
``cp`` chunks with compute overlap and O(s/cp) KV memory — the choice
for extreme lengths.  GQA: kv heads split naturally when
``hk % cp == 0``; for ``cp % hk == 0`` the kv heads are repeated to
``cp`` before the exchange (the repeat is wire-cheap: kv is
``hk/h``-sized) — head-block alignment with the grouped q layout is
preserved in both cases.

Layout matches :func:`apex_tpu.ops.fused_attention`:
``(batch, seq_local, heads, head_dim)``.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.core.mesh import CONTEXT_AXIS
from apex_tpu.ops.attention import _derive_seed, fused_attention

__all__ = ["ulysses_attention", "ulysses_self_attention"]


def ulysses_attention(q, k, v, axis: str = CONTEXT_AXIS, *,
                      causal: bool = False,
                      scale: Optional[float] = None,
                      window: Optional[int] = None,
                      dropout_rate: float = 0.0,
                      dropout_rng=None,
                      implementation: Optional[str] = None):
    """Exact attention over a sequence sharded on mesh axis ``axis``.

    Must be called inside ``shard_map`` with ``axis`` manual;
    ``q``/``k``/``v`` are local sequence shards ``(b, s_local, h|hk,
    d)``; returns the local output shard ``(b, s_local, h, d)``.
    Semantics (incl. GQA and ``window``) match
    :func:`apex_tpu.ops.fused_attention` on the gathered sequence.
    Dropout is statistically equivalent but NOT bit-identical to the
    unsharded call: the seed is folded with ``lax.axis_index(axis)`` so
    head shards on different devices draw independent masks (without
    the fold, every shard's local lane indices coincide and global
    heads ``h/cp`` apart would share one mask).
    Requires ``h % cp == 0`` and ``hk % cp == 0 or cp % hk == 0``.
    """
    cp = lax.axis_size(axis)
    # dropout_rng=None with rate>0 passes through untouched so
    # fused_attention's "dropout needs an rng" guard still raises
    if dropout_rate > 0.0 and dropout_rng is not None:
        # mix the shard index into the normalized int32 seed (handles
        # keys AND integer seeds uniformly — _derive_seed is the same
        # normalization fused_attention itself applies)
        seed = _derive_seed(dropout_rng)[0].astype(jnp.uint32)
        mix = ((lax.axis_index(axis).astype(jnp.uint32)
                + jnp.uint32(1)) * jnp.uint32(0x9E3779B9))
        dropout_rng = (seed ^ mix).astype(jnp.int32)
    h, hk = q.shape[2], k.shape[2]
    if h % cp:
        raise ValueError(
            f"ulysses needs num_heads ({h}) divisible by the context "
            f"axis size ({cp}) — use ring_attention otherwise")
    if hk % cp and cp % hk:
        raise ValueError(
            f"ulysses GQA needs kv heads ({hk}) divisible by cp ({cp}) "
            f"or cp divisible by kv heads — got neither")
    if hk % cp:
        # fewer kv heads than devices: repeat groups so each device
        # receives exactly one kv head; the contiguous q head blocks
        # stay aligned with their group (verified in the test suite)
        k = jnp.repeat(k, cp // hk, axis=2)
        v = jnp.repeat(v, cp // hk, axis=2)

    def seq_to_heads(x):
        # (b, s/cp, hx, d) -> (b, s, hx/cp, d)
        return lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                              tiled=True)

    q, k, v = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    o = fused_attention(
        q, k, v, causal=causal, scale=scale, window=window,
        dropout_rate=dropout_rate, dropout_rng=dropout_rng,
        implementation=implementation)
    # (b, s, h/cp, d) -> (b, s/cp, h, d)
    return lax.all_to_all(o, axis, split_axis=1, concat_axis=2,
                          tiled=True)


def ulysses_self_attention(q, k, v, *, mesh: Mesh,
                           axis: str = CONTEXT_AXIS,
                           causal: bool = False,
                           scale: Optional[float] = None,
                           window: Optional[int] = None,
                           batch_spec: Optional[Tuple] = None,
                           implementation: Optional[str] = None):
    """Convenience wrapper: global (b, S, h, d) arrays in, shard_map'd
    Ulysses attention over ``axis`` inside.

    ``batch_spec`` optionally names a mesh axis for the batch dim (e.g.
    ``'data'``) so DP×CP compose; other dims are replicated.
    """
    bs = batch_spec
    spec = P(bs, axis, None, None)

    @functools.partial(
        jax.shard_map, mesh=mesh, in_specs=(spec, spec, spec),
        out_specs=spec, axis_names={axis} | ({bs} if bs else set()))
    def run(ql, kl, vl):
        return ulysses_attention(
            ql, kl, vl, axis, causal=causal, scale=scale,
            window=window, implementation=implementation)

    return run(q, k, v)
