"""Data parallelism over the mesh (ICI collectives instead of NCCL).

Reference: ``apex/parallel/distributed.py`` —
``DistributedDataParallel(model, message_size=…, delay_allreduce=…)``
registers backward hooks that flatten grads into buckets and launch
async NCCL all-reduces overlapped with the remaining backward
(SURVEY.md §3.3).

TPU translation: the entire mechanism dissolves into the compiler.
With parameters replicated over the ``data`` axis and the batch sharded
over it, XLA's SPMD partitioner inserts the gradient all-reduce and its
latency-hiding scheduler overlaps it with the backward — the exact
behavior apex implements with hooks, flatten buckets and side streams.
What remains for the library:

- :func:`shard_batch` / :func:`replicate` — the sharding declarations
  that *cause* DP (constructor-broadcast parity: replicate params once).
- :func:`all_reduce_mean_grads` — explicit per-shard form for
  ``shard_map`` training steps (``gradient_average=True`` semantics).
- :class:`DistributedDataParallel` — a thin callable wrapper with the
  reference's name for drop-in reading; it only applies shardings.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec

from apex_tpu.core import mesh as mesh_lib
from apex_tpu.core.mesh import DATA_AXIS, FSDP_AXIS

__all__ = [
    "replicate",
    "shard_batch",
    "all_reduce_mean_grads",
    "DistributedDataParallel",
]


def replicate(tree: Any, mesh=None) -> Any:
    """Place params replicated over every mesh axis (rank-0 broadcast
    parity: all DP ranks start identical)."""
    mesh = mesh or mesh_lib.get_mesh()
    sharding = NamedSharding(mesh, PartitionSpec())
    return jax.device_put(tree, sharding)


def shard_batch(batch: Any, mesh=None, *,
                axes: Sequence[str] = (DATA_AXIS, FSDP_AXIS)) -> Any:
    """Shard the leading (batch) dim of every leaf over the DP axes."""
    mesh = mesh or mesh_lib.get_mesh()
    axes = tuple(a for a in axes if mesh.shape.get(a, 1) > 1) or None
    sharding = NamedSharding(mesh, PartitionSpec(axes))
    return jax.tree.map(lambda x: jax.device_put(x, sharding), batch)


def _normalize_allreduce_dtype(allreduce_dtype: Any):
    """None | 'int8' | a floating dtype — anything else is an error
    (an int dtype reaching ``astype`` would silently zero gradients)."""
    if allreduce_dtype is None:
        return None
    if allreduce_dtype == "int8" or (
            _is_dtype_like(allreduce_dtype)
            and jnp.dtype(allreduce_dtype) == jnp.dtype(jnp.int8)):
        return "int8"
    if _is_dtype_like(allreduce_dtype) and jnp.issubdtype(
            jnp.dtype(allreduce_dtype), jnp.floating):
        return jnp.dtype(allreduce_dtype)
    raise ValueError(
        f"allreduce_dtype must be None, a floating dtype, or 'int8'; "
        f"got {allreduce_dtype!r}")


def _is_dtype_like(x) -> bool:
    try:
        jnp.dtype(x)
        return True
    except TypeError:
        return False


def all_reduce_mean_grads(grads: Any, axis: str = DATA_AXIS, *,
                          allreduce_dtype: Any = None,
                          average: bool = True) -> Any:
    """Explicit grad all-reduce inside ``shard_map``
    (``gradient_average=True``; one fused all-reduce like delayed
    single-bucket mode — bucketing itself is unnecessary under XLA).
    ``average=False`` sums (``gradient_average=False`` parity).

    ``allreduce_dtype`` — communication compression:

    - ``None``: reduce in the grads' dtype (default);
    - a half dtype (``jnp.bfloat16``/``jnp.float16``): cast before the
      all-reduce, upcast after — the reference DDP's fp16-allreduce
      option (halves ICI bytes);
    - ``"int8"``: EQuARX-style quantized all-reduce (beyond-reference):
      grads scaled by the *global* amax to int8, summed in int32 (no
      overflow for < 2^24 replicas), dequantized — ~4× fewer bytes on
      the wire at ~1/127 amax quantization error.  Non-finite grads
      come back NaN so dynamic-loss-scale overflow detection still
      fires (a plain pmean would likewise propagate them).
    """
    dtype = _normalize_allreduce_dtype(allreduce_dtype)
    reduce = lax.pmean if average else lax.psum
    if dtype is None:
        return jax.tree.map(lambda g: reduce(g, axis), grads)
    if dtype == "int8":
        n = lax.axis_size(axis)

        def q8(g):
            amax = lax.pmax(jnp.max(jnp.abs(g)).astype(jnp.float32),
                            axis)
            scale = jnp.where(amax > 0, 127.0 / amax, 0.0)
            q = jnp.clip(jnp.round(g.astype(jnp.float32) * scale),
                         -127, 127).astype(jnp.int32)
            s = lax.psum(q, axis)
            deq = s.astype(jnp.float32) * jnp.where(
                scale > 0, 1.0 / scale, 0.0)
            if average:
                deq = deq / n
            # inf/nan grads must not be masked to zero: overflow
            # detection (DynamicLossScale) keys off non-finite grads
            deq = jnp.where(jnp.isfinite(amax), deq, jnp.nan)
            return deq.astype(g.dtype)

        return jax.tree.map(q8, grads)

    def half(g):
        return reduce(g.astype(dtype), axis).astype(g.dtype)

    return jax.tree.map(half, grads)


class DistributedDataParallel:
    """Drop-in-named wrapper: shards data, replicates params, and lets
    GSPMD insert/overlap the gradient all-reduce.

    Usage::

        ddp = DistributedDataParallel(mesh)
        params = ddp.replicate(params)
        batch  = ddp.shard(batch)
        # any jitted train step now runs data-parallel; grads are
        # all-reduced by XLA exactly where apex's hooks would fire.
    """

    def __init__(self, mesh=None, *, gradient_average: bool = True,
                 allreduce_dtype: Any = None):
        self.mesh = mesh or mesh_lib.get_mesh()
        self.gradient_average = gradient_average
        self.allreduce_dtype = allreduce_dtype

    def replicate(self, params: Any) -> Any:
        return replicate(params, self.mesh)

    def shard(self, batch: Any) -> Any:
        return shard_batch(batch, self.mesh)

    def mean_grads(self, grads: Any, axis: str = DATA_AXIS) -> Any:
        return all_reduce_mean_grads(
            grads, axis, allreduce_dtype=self.allreduce_dtype,
            average=self.gradient_average)
